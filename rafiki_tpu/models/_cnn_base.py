"""Shared template body for the BatchNorm CNN families (ResNet / VGG /
DenseNet).

All three train with the same classic recipe — SGD-momentum + cosine
decay, no weight decay on biases/BN, bf16 compute with f32 params and
BN stats, DP over the trial's sub-mesh, donated train-step buffers,
epoch-boundary preemption checkpoints — and serve through the same
bucketed cached-jit forward. One implementation lives here; each family
contributes only its flax module (``_module``) and knob config.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from rafiki_tpu.constants import TaskType
from rafiki_tpu.data import batch_iterator, \
    load_image_classification_dataset
from rafiki_tpu.model import (BaseModel, TrainContext, bucketed_forward,
                              conform_images, same_tree_shapes, train_epoch)
from rafiki_tpu.parallel.sharding import (batch_sharding, make_mesh,
                                          replicated)


class BatchNormCNNTemplate(BaseModel):
    """Image-classification template over a flax module with
    ``batch_stats``. Subclasses implement ``get_knob_config`` and
    ``_module``; everything else — train/evaluate/predict/serving
    warmup/dump/load — is shared."""

    TASKS = (TaskType.IMAGE_CLASSIFICATION,)

    def __init__(self, **knobs: Any) -> None:
        super().__init__(**knobs)
        self._vars: Optional[Dict[str, Any]] = None
        self._n_classes: Optional[int] = None
        self._image_shape: Optional[Sequence[int]] = None
        self._fwd: Optional[Any] = None  # cached jitted forward

    # ---- family-specific ----
    def _module(self):
        raise NotImplementedError

    @classmethod
    def gang_epochs(cls, knobs: Dict[str, Any],
                    budget_scale: float) -> int:
        """Epoch count ``train()`` would spend (the gang/trial
        scheduler's per-proposal budget; mirrors the loop below)."""
        epochs = max(1, round(int(knobs["max_epochs"])
                              * float(budget_scale)))
        if knobs.get("quick_train"):
            epochs = min(epochs, 2)
        return epochs

    # ---- shared internals ----
    def _prep(self, images: np.ndarray) -> np.ndarray:
        x = images.astype(np.float32) / 255.0
        if x.ndim == 3:
            x = x[..., None]
        # BN at/near the stem absorbs input scale/shift, so no centering
        # is needed (unlike the ViT template); the stem's channel count
        # is fixed at train time, hence the conform
        return conform_images(x, self._image_shape)

    # ---- contract ----
    def train(self, dataset_path: str,
              ctx: Optional[TrainContext] = None) -> None:
        ctx = ctx or TrainContext()
        from rafiki_tpu.data.stream import (StreamingImageDataset,
                                            should_stream)

        # ImageNet-scale archives stream (constant host memory, worker-
        # thread decode + crop/flip augmentation — BASELINE config #2);
        # tuning-trial datasets keep the whole-array fast path
        stream = (StreamingImageDataset.is_streamable(dataset_path)
                  and should_stream(dataset_path))
        if stream:
            sds = StreamingImageDataset(dataset_path)
            self._n_classes = sds.n_classes
            self._image_shape = list(sds.image_shape)
            n_samples = sds.n
            x = np.zeros((1, *sds.image_shape), np.float32)  # shape probe
        else:
            ds = load_image_classification_dataset(dataset_path)
            self._n_classes = ds.n_classes
            self._image_shape = ds.image_shape
            x = self._prep(ds.images)
            y = ds.labels
            n_samples = len(x)

        module = self._module()
        devices = ctx.devices or jax.local_devices()
        mesh = make_mesh(devices)
        b_shard = batch_sharding(mesh)
        r_shard = replicated(mesh)

        n_data = len(devices)
        batch_size = int(self.knobs["batch_size"])
        batch_size = max(n_data, batch_size - batch_size % n_data)

        if self._vars is None:
            variables = module.init(jax.random.PRNGKey(0),
                                    jnp.zeros((1, *x.shape[1:])),
                                    train=False)
            variables = {"params": variables["params"],
                         "batch_stats": variables["batch_stats"]}
        else:
            variables = self._vars
        if ctx.shared_params is not None and self.knobs.get("share_params"):
            shared = ctx.shared_params.get("params")
            if shared is not None and same_tree_shapes(variables["params"],
                                                       shared):
                variables = {
                    "params": jax.tree_util.tree_map(jnp.asarray, shared),
                    "batch_stats": jax.tree_util.tree_map(
                        jnp.asarray,
                        ctx.shared_params.get("batch_stats",
                                              variables["batch_stats"])),
                }

        epochs = self.gang_epochs(self.knobs, ctx.budget_scale)
        steps_per_epoch = max(1, (n_samples + batch_size - 1) // batch_size)
        schedule = optax.cosine_decay_schedule(
            float(self.knobs["learning_rate"]), epochs * steps_per_epoch)

        def decay_mask(tree):
            # classic recipe: no decay on biases or BatchNorm scale/bias
            return jax.tree_util.tree_map_with_path(
                lambda kp, _: str(getattr(kp[-1], "key", "")) not in
                ("bias", "scale"), tree)

        tx = optax.chain(
            optax.add_decayed_weights(float(self.knobs["weight_decay"]),
                                      mask=decay_mask),
            optax.sgd(schedule, momentum=0.9, nesterov=True))

        params = jax.device_put(variables["params"], r_shard)
        batch_stats = jax.device_put(variables["batch_stats"], r_shard)
        opt_state = jax.device_put(tx.init(params), r_shard)

        # donate the param/stats/opt trees: in-place update, no per-step
        # copies riding HBM bandwidth
        @partial(jax.jit, donate_argnums=(0, 1, 2))
        def train_step(params, batch_stats, opt_state, xb, yb, mask):
            def loss_fn(p):
                logits, updates = module.apply(
                    {"params": p, "batch_stats": batch_stats}, xb,
                    train=True, mutable=["batch_stats"])
                losses = optax.softmax_cross_entropy_with_integer_labels(
                    logits.astype(jnp.float32), yb)
                loss = jnp.sum(losses * mask) / jnp.maximum(jnp.sum(mask),
                                                            1.0)
                return loss, updates["batch_stats"]

            (loss, new_stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), new_stats,
                    opt_state, loss)

        def step(state, b):
            params, batch_stats, opt_state = state
            params, batch_stats, opt_state, loss = train_step(
                params, batch_stats, opt_state, b["x"], b["y"], b["m"])
            return (params, batch_stats, opt_state), loss

        def epoch_batches(epoch: int):
            if stream:
                # decode/augment on worker threads, prep per batch —
                # host memory stays constant in dataset size
                for b in sds.iter_batches(batch_size, epoch=epoch,
                                          shuffle=True, seed=0,
                                          augment=True):
                    yield {"x": self._prep(b["x"]), "y": b["y"],
                           "m": b["mask"].astype(np.float32)}
            else:
                for b in batch_iterator({"x": x, "y": y}, batch_size,
                                        seed=epoch):
                    yield {"x": b["x"], "y": b["y"],
                           "m": b["mask"].astype(np.float32)}

        ctx.logger.define_plot("Loss over epochs", ["loss"], x_axis="epoch")
        # donation invalidates buffers that may alias self._vars (warm
        # start / re-train): drop the stale reference first
        self._vars = None
        with mesh:
            for epoch in range(epochs):
                state = (params, batch_stats, opt_state)
                (params, batch_stats, opt_state), mean_loss = train_epoch(
                    step, state, epoch_batches(epoch), sharding=b_shard)
                ctx.logger.log(epoch=epoch, loss=mean_loss)
                if ctx.checkpoint is not None:
                    # preemption safety: worker throttles + persists
                    self._vars = {"params": params,
                                  "batch_stats": batch_stats}
                    ctx.checkpoint(self.dump_parameters,
                                   frac_done=(epoch + 1) / epochs)
                if ctx.should_continue is not None and \
                        not ctx.should_continue(epoch, -mean_loss):
                    break
        self._vars = {"params": params, "batch_stats": batch_stats}
        self._fwd = None  # new params/arch → rebuild the cached jit

    def evaluate(self, dataset_path: str) -> float:
        ds = load_image_classification_dataset(dataset_path)
        probs = self._predict_probs(self._prep(ds.images))
        return float(np.mean(np.argmax(probs, -1) == ds.labels))

    def predict(self, queries: Sequence[Any]) -> List[Any]:
        x = self._prep(np.stack([np.asarray(q) for q in queries]))
        return [p.tolist() for p in self._predict_probs(x)]

    def warmup(self) -> None:
        """Compile the serving forward before traffic arrives."""
        if self._vars is None or self._image_shape is None:
            return
        self.predict([np.zeros(list(self._image_shape), np.uint8)])

    def _predict_probs(self, x: np.ndarray) -> np.ndarray:
        assert self._vars is not None, "model is not trained/loaded"
        if self._fwd is None:  # cache: jit memoizes by function identity
            module = self._module()

            @jax.jit
            def forward(variables, xb):
                logits = module.apply(variables, xb, train=False)
                return jax.nn.softmax(logits.astype(jnp.float32), -1)

            self._fwd = forward
        return bucketed_forward(self._fwd, self._vars, x, bucket=64)

    def dump_parameters(self) -> Dict[str, Any]:
        assert self._vars is not None, "model is not trained"
        return {
            "params": jax.tree_util.tree_map(np.asarray,
                                             self._vars["params"]),
            "batch_stats": jax.tree_util.tree_map(
                np.asarray, self._vars["batch_stats"]),
            "meta": {"n_classes": self._n_classes,
                     "image_shape": list(self._image_shape or [])},
        }

    def load_parameters(self, params: Dict[str, Any]) -> None:
        self._n_classes = int(params["meta"]["n_classes"])
        self._image_shape = list(params["meta"]["image_shape"])
        self._vars = {
            "params": jax.tree_util.tree_map(jnp.asarray, params["params"]),
            "batch_stats": jax.tree_util.tree_map(jnp.asarray,
                                                  params["batch_stats"]),
        }
        self._fwd = None
