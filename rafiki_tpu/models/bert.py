"""BERT-style text classification — BASELINE.md config #4.

Parity target: the reference's text templates (SURVEY.md §2 "Model zoo")
and benchmark config #4 ("BERT-base text-classification fine-tune under
the Advisor"). TPU-first design notes:

- The encoder's attention runs through the Pallas flash kernel with
  per-example ``kv_lens`` padding masks (``rafiki_tpu.ops.attention``) —
  pads never receive attention mass, matching real BERT semantics while
  keeping the batch a single static-shape MXU-friendly tensor.
- Tokenization is a deterministic hashed-vocabulary scheme (blake2b → id):
  this environment has zero egress, so there is no pretrained WordPiece
  vocab to download; hashing gives a stable open vocabulary with the same
  fixed-shape int32 batch interface a real tokenizer would produce.
- Sequences are bucketed to a knob-chosen max length; pre-LN blocks for
  optimization stability at AutoML-scale learning rates.
"""

from __future__ import annotations

import functools
import hashlib
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from rafiki_tpu.constants import TaskType
from rafiki_tpu.data import batch_iterator, \
    load_text_classification_dataset
from rafiki_tpu.model import (BaseModel, CategoricalKnob, FixedKnob,
                              FloatKnob, IntegerKnob, KnobConfig, PolicyKnob,
                              TrainContext, bucketed_forward,
                              same_tree_shapes, train_epoch)
from rafiki_tpu.ops.attention import flash_attention
from rafiki_tpu.parallel.sharding import (batch_sharding, make_mesh,
                                          replicated)

PAD_ID = 0
CLS_ID = 1
_RESERVED = 2  # ids below this are special tokens

_TOKEN_RE = re.compile(r"[a-z0-9]+")


class HashTokenizer:
    """Deterministic open-vocabulary tokenizer: lowercase word pieces →
    blake2b-hashed ids. Stable across processes (unlike Python ``hash``,
    which is salted per interpreter)."""

    def __init__(self, vocab_size: int = 1 << 15) -> None:
        if vocab_size <= _RESERVED:
            raise ValueError("vocab_size too small")
        self.vocab_size = vocab_size

    def token_id(self, token: str) -> int:
        h = hashlib.blake2b(token.encode("utf-8"), digest_size=8)
        return _RESERVED + int.from_bytes(h.digest(), "big") % (
            self.vocab_size - _RESERVED)

    def encode(self, text: str, max_len: int) -> Tuple[List[int], int]:
        """Returns (ids padded to ``max_len`` with a leading CLS, true
        length including CLS)."""
        ids = [CLS_ID]
        for tok in _TOKEN_RE.findall(text.lower()):
            if len(ids) >= max_len:
                break
            ids.append(self.token_id(tok))
        length = len(ids)
        ids = ids + [PAD_ID] * (max_len - length)
        return ids, length

    def encode_batch(self, texts: Sequence[str],
                     max_len: int) -> Tuple[np.ndarray, np.ndarray]:
        ids = np.zeros((len(texts), max_len), np.int32)
        lens = np.zeros((len(texts),), np.int32)
        for i, t in enumerate(texts):
            row, n = self.encode(t, max_len)
            ids[i] = row
            lens[i] = n
        return ids, lens


class _EncoderBlock(nn.Module):
    n_heads: int
    mlp_dim: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, lens: jnp.ndarray) -> jnp.ndarray:
        b, s, d = x.shape
        dh = d // self.n_heads
        y = nn.LayerNorm(dtype=self.dtype)(x)
        qkv = nn.Dense(3 * d, name="qkv", dtype=self.dtype)(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(b, s, self.n_heads, dh).transpose(0, 2, 1, 3)

        o = flash_attention(heads(q), heads(k), heads(v), kv_lens=lens)
        o = o.transpose(0, 2, 1, 3).reshape(b, s, d)
        x = x + nn.Dense(d, name="proj", dtype=self.dtype)(o)
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = nn.Dense(self.mlp_dim, dtype=self.dtype)(y)
        y = nn.gelu(y)
        return x + nn.Dense(d, dtype=self.dtype)(y)


class Bert(nn.Module):
    """Pre-LN transformer encoder over hashed token ids.

    BERT-base = hidden_dim=768, depth=12, n_heads=12, mlp_dim=3072.
    """

    vocab_size: int
    max_len: int
    hidden_dim: int = 768
    depth: int = 12
    n_heads: int = 12
    mlp_dim: int = 3072
    n_classes: int = 2
    dtype: Any = jnp.float32  # compute dtype; params stay f32

    @nn.compact
    def __call__(self, ids: jnp.ndarray, lens: jnp.ndarray) -> jnp.ndarray:
        x = nn.Embed(self.vocab_size, self.hidden_dim,
                     name="tok_embed", dtype=self.dtype)(ids)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, self.max_len, self.hidden_dim))
        x = x + pos[:, :ids.shape[1], :].astype(self.dtype)
        for i in range(self.depth):
            x = _EncoderBlock(self.n_heads, self.mlp_dim, self.dtype,
                              name=f"block_{i}")(x, lens)
        x = nn.LayerNorm(name="final_norm")(x.astype(jnp.float32))
        # CLS pooling (position 0 is always the CLS token)
        return nn.Dense(self.n_classes, name="head")(x[:, 0])


class BertClassifier(BaseModel):
    """Text classification: hashed tokens → pre-LN encoder → CLS head,
    AdamW with linear warmup + cosine decay, DP over the trial sub-mesh."""

    TASKS = (TaskType.TEXT_CLASSIFICATION,)

    @staticmethod
    def get_knob_config() -> KnobConfig:
        return {
            "max_epochs": FixedKnob(8),
            "vocab_size": FixedKnob(1 << 15),
            # all hidden_dim choices divide by all n_heads choices
            "hidden_dim": CategoricalKnob([96, 192, 384, 768],
                                          shape_relevant=True),
            "depth": IntegerKnob(2, 12, shape_relevant=True),
            "n_heads": CategoricalKnob([4, 8, 12], shape_relevant=True),
            "max_len": CategoricalKnob([32, 64, 128], shape_relevant=True),
            "learning_rate": FloatKnob(1e-5, 1e-2, is_exp=True),
            "weight_decay": FloatKnob(1e-5, 1e-1, is_exp=True),
            "warmup_frac": FloatKnob(0.0, 0.2),
            "batch_size": CategoricalKnob([16, 32, 64, 128],
                                          shape_relevant=True),
            "bf16": CategoricalKnob([True, False]),
            "quick_train": PolicyKnob("QUICK_TRAIN"),
            "share_params": PolicyKnob("SHARE_PARAMS"),
        }

    def __init__(self, **knobs: Any) -> None:
        super().__init__(**knobs)
        self._params: Optional[Any] = None
        self._n_classes: Optional[int] = None
        self._fwd: Optional[Any] = None
        self.tokenizer = HashTokenizer(int(self.knobs.get("vocab_size",
                                                          1 << 15)))

    # ---- internals ----
    def _module(self) -> Bert:
        k = self.knobs
        hd = int(k["hidden_dim"])
        heads = int(k["n_heads"])
        if hd % heads:
            raise ValueError(f"hidden_dim={hd} not divisible by "
                             f"n_heads={heads}")
        return Bert(vocab_size=self.tokenizer.vocab_size,
                    max_len=int(k["max_len"]), hidden_dim=hd,
                    depth=int(k["depth"]), n_heads=heads, mlp_dim=4 * hd,
                    n_classes=int(self._n_classes), dtype=self._dtype())

    def _dtype(self):
        return jnp.bfloat16 if self.knobs.get("bf16", True) else jnp.float32

    def _encode(self, texts: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
        return self.tokenizer.encode_batch(texts,
                                           int(self.knobs["max_len"]))

    # ---- contract ----
    def train(self, dataset_path: str,
              ctx: Optional[TrainContext] = None) -> None:
        ctx = ctx or TrainContext()
        ds = load_text_classification_dataset(dataset_path)
        self._n_classes = ds.n_classes
        ids, lens = self._encode(ds.texts)
        y = ds.labels

        module = self._module()
        devices = ctx.devices or jax.local_devices()
        mesh = make_mesh(devices)
        b_shard = batch_sharding(mesh)
        r_shard = replicated(mesh)

        n_data = len(devices)
        batch_size = int(self.knobs["batch_size"])
        batch_size = max(n_data, batch_size - batch_size % n_data)

        if self._params is None:
            params = module.init(
                jax.random.PRNGKey(0), jnp.zeros((1, ids.shape[1]),
                                                 jnp.int32),
                jnp.ones((1,), jnp.int32))["params"]
        else:
            params = self._params
        if ctx.shared_params is not None and self.knobs.get("share_params"):
            shared = ctx.shared_params.get("params")
            if shared is not None and same_tree_shapes(params, shared):
                params = jax.tree_util.tree_map(jnp.asarray, shared)

        epochs = max(1, round(int(self.knobs["max_epochs"])
                              * float(ctx.budget_scale)))
        if self.knobs.get("quick_train"):
            epochs = min(epochs, 2)
        steps_per_epoch = max(1, (len(ids) + batch_size - 1) // batch_size)
        total_steps = epochs * steps_per_epoch
        lr = float(self.knobs["learning_rate"])
        warmup = int(total_steps * float(self.knobs["warmup_frac"]))
        schedule = optax.warmup_cosine_decay_schedule(
            0.0, lr, max(warmup, 1), max(total_steps, 2))
        tx = optax.adamw(schedule,
                         weight_decay=float(self.knobs["weight_decay"]))

        params = jax.device_put(params, r_shard)
        opt_state = jax.device_put(tx.init(params), r_shard)

        # donate the param/opt trees: in-place update, no per-step copies
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def train_step(params, opt_state, ib, lb, yb, mask):
            def loss_fn(p):
                logits = module.apply({"params": p}, ib, lb)
                losses = optax.softmax_cross_entropy_with_integer_labels(
                    logits.astype(jnp.float32), yb)
                return jnp.sum(losses * mask) / jnp.maximum(jnp.sum(mask),
                                                            1.0)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        def step(state, b):
            params, opt_state = state
            params, opt_state, loss = train_step(
                params, opt_state, b["ids"], b["lens"], b["y"], b["m"])
            return (params, opt_state), loss

        ctx.logger.define_plot("Loss over epochs", ["loss"], x_axis="epoch")
        # donation invalidates buffers that may alias self._params (warm
        # start / re-train): drop the stale reference first
        self._params = None
        with mesh:
            for epoch in range(epochs):
                (params, opt_state), mean_loss = train_epoch(
                    step, (params, opt_state),
                    ({"ids": b["ids"], "lens": b["lens"], "y": b["y"],
                      "m": b["mask"].astype(np.float32)}
                     for b in batch_iterator(
                         {"ids": ids, "lens": lens, "y": y}, batch_size,
                         seed=epoch)),
                    sharding=b_shard)
                ctx.logger.log(epoch=epoch, loss=mean_loss)
                if ctx.checkpoint is not None:
                    # preemption safety: worker throttles + persists
                    self._params = params
                    ctx.checkpoint(self.dump_parameters,
                                   frac_done=(epoch + 1) / epochs)
                if ctx.should_continue is not None and \
                        not ctx.should_continue(epoch, -mean_loss):
                    break
        self._params = params
        self._fwd = None

    def evaluate(self, dataset_path: str) -> float:
        ds = load_text_classification_dataset(dataset_path)
        probs = self._predict_probs(ds.texts)
        return float(np.mean(np.argmax(probs, -1) == ds.labels))

    def predict(self, queries: Sequence[Any]) -> List[Any]:
        texts = [q if isinstance(q, str) else str(q) for q in queries]
        return [p.tolist() for p in self._predict_probs(texts)]

    def _predict_probs(self, texts: Sequence[str]) -> np.ndarray:
        assert self._params is not None, "model is not trained/loaded"
        ids, lens = self._encode(texts)
        if self._fwd is None:
            module = self._module()

            @jax.jit
            def forward(params, ib, lb):
                logits = module.apply({"params": params}, ib, lb)
                return jax.nn.softmax(logits.astype(jnp.float32), -1)

            self._fwd = forward
        return bucketed_forward(self._fwd, self._params, ids, lens,
                                bucket=64)

    def warmup(self) -> None:
        """Compile the serving forward before traffic arrives."""
        if self._params is None:
            return
        self.predict(["warmup"])

    def dump_parameters(self) -> Dict[str, Any]:
        assert self._params is not None, "model is not trained"
        return {
            "params": jax.tree_util.tree_map(np.asarray, self._params),
            "meta": {"n_classes": self._n_classes},
        }

    def load_parameters(self, params: Dict[str, Any]) -> None:
        self._n_classes = int(params["meta"]["n_classes"])
        self._params = jax.tree_util.tree_map(jnp.asarray, params["params"])
        self._fwd = None


if __name__ == "__main__":  # reference-style self-test block
    import tempfile

    from rafiki_tpu.utils.platform import apply_platform_env

    apply_platform_env()  # honor RAFIKI_JAX_PLATFORM=cpu for dev runs

    from rafiki_tpu.data import generate_text_classification_dataset
    from rafiki_tpu.model import test_model_class

    with tempfile.TemporaryDirectory() as d:
        train_p = f"{d}/train.jsonl"
        val_p = f"{d}/val.jsonl"
        generate_text_classification_dataset(train_p, 256, seed=0)
        generate_text_classification_dataset(val_p, 64, seed=1)
        preds = test_model_class(
            BertClassifier, TaskType.TEXT_CLASSIFICATION, train_p, val_p,
            queries=["tok1 tok2 tok3"],
            knobs={"max_epochs": 8, "vocab_size": 1 << 15, "hidden_dim": 96,
                   "depth": 2, "n_heads": 4, "max_len": 32,
                   "learning_rate": 1e-3, "weight_decay": 1e-4,
                   "warmup_frac": 0.1, "batch_size": 32, "bf16": False,
                   "quick_train": False, "share_params": False})
        print("prediction:", int(np.argmax(preds[0])))
