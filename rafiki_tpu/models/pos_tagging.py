"""POS-tagging templates: bigram HMM + BiLSTM (SURVEY.md §2 "Model zoo":
the reference ships a bigram HMM and a PyTorch BiLSTM for POS tagging).

- :class:`BigramHMM` — count-based emissions/transitions with add-k
  smoothing and a vectorized numpy Viterbi decode. Training is a single
  counting pass: the cheap, strong baseline the reference uses, and a
  fast advisor target (the knob space is just smoothing strengths).
- :class:`BiLSTMTagger` — flax ``nn.RNN`` over ``OptimizedLSTMCell`` in
  both directions, hash-vocab token embeddings (no downloaded vocab; same
  scheme as the BERT template), padded/bucketed batches with masked loss —
  the jit-compiled TPU counterpart of the reference's PyTorch BiLSTM.

Queries for both: a list of token lists → list of tag-name lists.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# NOTE: zoo templates use absolute imports — their module source is shipped
# to workers via serialize_model_class() and re-imported standalone.
from rafiki_tpu.constants import TaskType
from rafiki_tpu.data import CorpusDataset
from rafiki_tpu.model import (BaseModel, CategoricalKnob, FixedKnob,
                              FloatKnob, IntegerKnob, KnobConfig,
                              PolicyKnob, TrainContext, same_tree_shapes)

UNK = "<unk>"


# ---------------------------------------------------------------------------
# Bigram HMM
# ---------------------------------------------------------------------------

class BigramHMM(BaseModel):
    """Count-based bigram HMM tagger with add-k smoothing."""

    TASKS = (TaskType.POS_TAGGING,)

    @staticmethod
    def get_knob_config() -> KnobConfig:
        return {
            # smoothing strengths are the whole hyperparameter story for a
            # counting model; both matter on small corpora
            "emission_k": FloatKnob(1e-3, 1.0, is_exp=True),
            "transition_k": FloatKnob(1e-3, 1.0, is_exp=True),
            "min_word_count": IntegerKnob(1, 3),
        }

    def __init__(self, **knobs: Any) -> None:
        super().__init__(**knobs)
        self._vocab: Dict[str, int] = {}
        self._tags: List[str] = []
        self._log_emit: Optional[np.ndarray] = None   # [T, V]
        self._log_trans: Optional[np.ndarray] = None  # [T+1, T] (0 = start)

    # ---- contract ----
    def train(self, dataset_path: str,
              ctx: Optional[TrainContext] = None) -> None:
        ctx = ctx or TrainContext()
        ds = CorpusDataset.load(dataset_path)
        self._tags = list(ds.tag_names)
        tag_ix = {t: i for i, t in enumerate(self._tags)}

        counts: Dict[str, int] = {}
        for tokens, _ in ds.sentences:
            for w in tokens:
                counts[w] = counts.get(w, 0) + 1
        min_count = int(self.knobs.get("min_word_count", 1))
        self._vocab = {UNK: 0}
        for w, c in sorted(counts.items()):
            if c >= min_count:
                self._vocab[w] = len(self._vocab)

        T, V = len(self._tags), len(self._vocab)
        emit = np.zeros((T, V), np.float64)
        trans = np.zeros((T + 1, T), np.float64)  # row 0 = sentence start
        for tokens, tags in ds.sentences:
            prev = 0
            for w, tag in zip(tokens, tags):
                t = tag_ix[tag]
                emit[t, self._vocab.get(w, 0)] += 1
                trans[prev, t] += 1
                prev = t + 1
        ek = float(self.knobs.get("emission_k", 0.1))
        tk = float(self.knobs.get("transition_k", 0.1))
        self._log_emit = np.log(emit + ek) - np.log(
            (emit + ek).sum(axis=1, keepdims=True))
        self._log_trans = np.log(trans + tk) - np.log(
            (trans + tk).sum(axis=1, keepdims=True))
        ctx.logger.log(epoch=0, loss=0.0)  # single counting pass

    def _viterbi(self, tokens: Sequence[str]) -> List[str]:
        assert self._log_emit is not None and self._log_trans is not None
        T = len(self._tags)
        ids = [self._vocab.get(w, 0) for w in tokens]
        if not ids:
            return []
        # vectorized over tags: delta [T], psi [len, T]
        delta = self._log_trans[0] + self._log_emit[:, ids[0]]
        psi = np.zeros((len(ids), T), np.int64)
        for i in range(1, len(ids)):
            # scores[p, t] = delta[p] + trans[p+1, t]
            scores = delta[:, None] + self._log_trans[1:]
            psi[i] = np.argmax(scores, axis=0)
            delta = scores[psi[i], np.arange(T)] + self._log_emit[:, ids[i]]
        path = [int(np.argmax(delta))]
        for i in range(len(ids) - 1, 0, -1):
            path.append(int(psi[i][path[-1]]))
        return [self._tags[t] for t in reversed(path)]

    def evaluate(self, dataset_path: str) -> float:
        ds = CorpusDataset.load(dataset_path)
        correct = total = 0
        for tokens, tags in ds.sentences:
            pred = self._viterbi(tokens)
            correct += sum(p == t for p, t in zip(pred, tags))
            total += len(tags)
        return correct / max(total, 1)

    def predict(self, queries: Sequence[Any]) -> List[Any]:
        return [self._viterbi([str(w) for w in q]) for q in queries]

    def dump_parameters(self) -> Dict[str, Any]:
        assert self._log_emit is not None, "model is not trained"
        words = sorted(self._vocab, key=self._vocab.get)
        return {"log_emit": self._log_emit, "log_trans": self._log_trans,
                "meta": {"tags": self._tags, "words": words}}

    def load_parameters(self, params: Dict[str, Any]) -> None:
        self._log_emit = np.asarray(params["log_emit"])
        self._log_trans = np.asarray(params["log_trans"])
        self._tags = [str(t) for t in params["meta"]["tags"]]
        self._vocab = {str(w): i
                       for i, w in enumerate(params["meta"]["words"])}


# ---------------------------------------------------------------------------
# BiLSTM
# ---------------------------------------------------------------------------

def _hash_token(w: str, vocab_size: int) -> int:
    """Deterministic token→id (FNV-1a); id 0 is reserved for padding."""
    h = 2166136261
    for ch in w.encode("utf-8"):
        h = ((h ^ ch) * 16777619) & 0xFFFFFFFF
    return 1 + h % (vocab_size - 1)


class BiLSTMTagger(BaseModel):
    """Bidirectional LSTM tagger (flax ``nn.RNN``; masked CE loss)."""

    TASKS = (TaskType.POS_TAGGING,)

    @staticmethod
    def get_knob_config() -> KnobConfig:
        return {
            "max_epochs": FixedKnob(10),
            "vocab_size": CategoricalKnob([1024, 4096],
                                          shape_relevant=True),
            "embed_dim": CategoricalKnob([32, 64, 128],
                                         shape_relevant=True),
            "hidden_dim": CategoricalKnob([64, 128, 256],
                                          shape_relevant=True),
            "learning_rate": FloatKnob(1e-4, 3e-2, is_exp=True),
            "batch_size": CategoricalKnob([16, 32, 64],
                                          shape_relevant=True),
            "max_len": FixedKnob(32),
            "quick_train": PolicyKnob("QUICK_TRAIN"),
            "share_params": PolicyKnob("SHARE_PARAMS"),
        }

    def __init__(self, **knobs: Any) -> None:
        super().__init__(**knobs)
        self._params: Optional[Any] = None
        self._tags: List[str] = []
        self._fwd: Optional[Any] = None

    # ---- internals ----
    def _module(self):
        from flax import linen as nn

        import jax.numpy as jnp

        vocab = int(self.knobs["vocab_size"])
        embed = int(self.knobs["embed_dim"])
        hidden = int(self.knobs["hidden_dim"])
        n_tags = len(self._tags)

        class _BiLSTM(nn.Module):
            @nn.compact
            def __call__(self, ids: jnp.ndarray,
                         lens: jnp.ndarray) -> jnp.ndarray:
                x = nn.Embed(vocab, embed)(ids)                # [B,S,E]
                fwd = nn.RNN(nn.OptimizedLSTMCell(hidden))(
                    x, seq_lengths=lens)
                bwd = nn.RNN(nn.OptimizedLSTMCell(hidden), reverse=True,
                             keep_order=True)(x, seq_lengths=lens)
                h = jnp.concatenate([fwd, bwd], axis=-1)       # [B,S,2H]
                return nn.Dense(n_tags)(h)                     # [B,S,T]

        return _BiLSTM()

    def _encode(self, sents: Sequence[Sequence[str]]
                ) -> Tuple[np.ndarray, np.ndarray]:
        vocab = int(self.knobs["vocab_size"])
        max_len = int(self.knobs["max_len"])
        n = len(sents)
        ids = np.zeros((n, max_len), np.int32)
        lens = np.zeros((n,), np.int32)
        for i, toks in enumerate(sents):
            toks = list(toks)[:max_len]
            lens[i] = len(toks)
            for j, w in enumerate(toks):
                ids[i, j] = _hash_token(str(w), vocab)
        return ids, lens

    # ---- contract ----
    def train(self, dataset_path: str,
              ctx: Optional[TrainContext] = None) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        from rafiki_tpu.data import batch_iterator

        ctx = ctx or TrainContext()
        ds = CorpusDataset.load(dataset_path)
        self._tags = list(ds.tag_names)
        tag_ix = {t: i for i, t in enumerate(self._tags)}
        max_len = int(self.knobs["max_len"])

        ids, lens = self._encode([toks for toks, _ in ds.sentences])
        tags = np.zeros_like(ids)
        for i, (_, ts) in enumerate(ds.sentences):
            for j, t in enumerate(list(ts)[:max_len]):
                tags[i, j] = tag_ix[t]

        module = self._module()
        if self._params is None:
            params = module.init(jax.random.PRNGKey(0),
                                 jnp.asarray(ids[:1]),
                                 jnp.asarray(lens[:1]))["params"]
        else:
            params = self._params
        if ctx.shared_params is not None and self.knobs.get("share_params"):
            shared = ctx.shared_params.get("params")
            if shared is not None and same_tree_shapes(params, shared):
                params = jax.tree_util.tree_map(jnp.asarray, shared)

        tx = optax.adam(float(self.knobs["learning_rate"]))
        opt_state = tx.init(params)

        # donate the param/opt trees: in-place update, no per-step copies
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def train_step(params, opt_state, ib, lb, tb, mask):
            def loss_fn(p):
                logits = module.apply({"params": p}, ib, lb)
                tok_mask = (jnp.arange(ib.shape[1])[None, :]
                            < lb[:, None]).astype(jnp.float32)
                tok_mask = tok_mask * mask[:, None]
                losses = optax.softmax_cross_entropy_with_integer_labels(
                    logits, tb)
                return jnp.sum(losses * tok_mask) / jnp.maximum(
                    jnp.sum(tok_mask), 1.0)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        epochs = max(1, round(int(self.knobs["max_epochs"])
                              * float(ctx.budget_scale)))
        if self.knobs.get("quick_train"):
            epochs = min(epochs, 2)
        batch_size = int(self.knobs["batch_size"])
        ctx.logger.define_plot("Loss over epochs", ["loss"], x_axis="epoch")
        # donation invalidates buffers that may alias self._params (warm
        # start / re-train): drop the stale reference first
        self._params = None
        for epoch in range(epochs):
            losses = []
            for b in batch_iterator({"i": ids, "l": lens, "t": tags},
                                    batch_size, seed=epoch):
                params, opt_state, loss = train_step(
                    params, opt_state, b["i"], b["l"], b["t"],
                    b["mask"].astype(np.float32))
                losses.append(float(loss))
            mean_loss = float(np.mean(losses))
            ctx.logger.log(epoch=epoch, loss=mean_loss)
            if ctx.should_continue is not None and \
                    not ctx.should_continue(epoch, -mean_loss):
                break
        self._params = params
        self._fwd = None

    def _predict_tags(self, sents: Sequence[Sequence[str]]) -> List[List[str]]:
        import jax

        assert self._params is not None, "model is not trained/loaded"
        ids, lens = self._encode(sents)
        if self._fwd is None:
            module = self._module()

            @jax.jit
            def forward(params, ib, lb):
                return module.apply({"params": params}, ib, lb).argmax(-1)

            self._fwd = forward
        out: List[List[str]] = []
        bucket = 64
        for i in range(0, len(ids), bucket):
            ib, lb = ids[i:i + bucket], lens[i:i + bucket]
            pad = bucket - len(ib)
            if pad:
                ib = np.concatenate([ib, np.zeros((pad, ib.shape[1]),
                                                  ib.dtype)])
                lb = np.concatenate([lb, np.zeros((pad,), lb.dtype)])
            pred = np.asarray(self._fwd(self._params, ib, lb))
            for j in range(len(lb) - pad):
                out.append([self._tags[t] for t in pred[j, :lb[j]]])
        return out

    def evaluate(self, dataset_path: str) -> float:
        ds = CorpusDataset.load(dataset_path)
        max_len = int(self.knobs["max_len"])
        preds = self._predict_tags([toks for toks, _ in ds.sentences])
        correct = total = 0
        for pred, (_, tags) in zip(preds, ds.sentences):
            tags = list(tags)[:max_len]
            correct += sum(p == t for p, t in zip(pred, tags))
            total += len(tags)
        return correct / max(total, 1)

    def predict(self, queries: Sequence[Any]) -> List[Any]:
        return self._predict_tags([[str(w) for w in q] for q in queries])

    def dump_parameters(self) -> Dict[str, Any]:
        import jax

        assert self._params is not None, "model is not trained"
        return {"params": jax.tree_util.tree_map(np.asarray, self._params),
                "meta": {"tags": self._tags}}

    def load_parameters(self, params: Dict[str, Any]) -> None:
        import jax.numpy as jnp
        import jax

        self._tags = [str(t) for t in params["meta"]["tags"]]
        self._params = jax.tree_util.tree_map(jnp.asarray, params["params"])
        self._fwd = None


if __name__ == "__main__":  # reference-style self-test block
    import tempfile

    from rafiki_tpu.utils.platform import apply_platform_env

    apply_platform_env()

    from rafiki_tpu.data import generate_corpus_dataset
    from rafiki_tpu.model import test_model_class

    with tempfile.TemporaryDirectory() as d:
        train_p, val_p = f"{d}/train.jsonl", f"{d}/val.jsonl"
        generate_corpus_dataset(train_p, 400, seed=0)
        ds = generate_corpus_dataset(val_p, 100, seed=1)
        for cls in (BigramHMM, BiLSTMTagger):
            preds = test_model_class(
                cls, TaskType.POS_TAGGING, train_p, val_p,
                queries=[ds.sentences[0][0]])
            print(cls.__name__, "tags:", preds[0])
