"""VGG-style CNN family — the reference zoo's second CNN shape.

Parity target: SURVEY.md §2 "Model zoo" lists "TF VGG/DenseNet-style
CNNs" next to the feed-forward and ResNet families; this is the
TPU-native VGG: plain 3×3 conv stacks (+BatchNorm — the VGG-BN variant,
which actually trains without tricks) with stage-wise max-pool, a
global-average-pool head instead of VGG's 3 giant FC layers (GAP keeps
the net resolution-agnostic and drops ~90% of the parameters for free),
bf16 compute with f32 params/BN stats, data-parallel over the trial's
sub-mesh via NamedSharding. Convs are XLA's business — they lower
straight onto the MXU; no hand kernels needed here.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Sequence

import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from rafiki_tpu.constants import TaskType
from rafiki_tpu.model import (CategoricalKnob, FixedKnob, FloatKnob,
                              KnobConfig, PolicyKnob)
from rafiki_tpu.models._cnn_base import BatchNormCNNTemplate

#: convs per stage (each stage ends in 2x2 max-pool); channel width
#: doubles per stage from `width` up to 8x, VGG-style
VARIANTS: Dict[str, Sequence[int]] = {
    "vgg11": (1, 1, 2, 2, 2),
    "vgg13": (2, 2, 2, 2, 2),
    "vgg16": (2, 2, 3, 3, 3),
}


class VGG(nn.Module):
    """Conv stacks over (B, H, W, C); logits head on global avg pool."""

    stage_sizes: Sequence[int]
    width: int
    n_classes: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=self.dtype)
        x = x.astype(self.dtype)
        for stage, n_convs in enumerate(self.stage_sizes):
            ch = min(self.width * (2 ** stage), self.width * 8)
            for _ in range(n_convs):
                x = nn.Conv(ch, (3, 3), padding="SAME", use_bias=False,
                            dtype=self.dtype)(x)
                x = nn.relu(norm()(x))
            if min(x.shape[1], x.shape[2]) >= 2:  # never pool below 1px
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = jnp.mean(x, axis=(1, 2))  # GAP: resolution-agnostic head
        return nn.Dense(self.n_classes, dtype=jnp.float32,
                        name="head")(x.astype(jnp.float32))


class VGGClassifier(BatchNormCNNTemplate):
    """VGG template: image classification, DP over the trial sub-mesh,
    SGD-momentum with cosine decay (shared BatchNorm-CNN recipe —
    ``models/_cnn_base.py``)."""

    @staticmethod
    def get_knob_config() -> KnobConfig:
        return {
            "max_epochs": FixedKnob(5),
            "variant": CategoricalKnob(list(VARIANTS),
                                       shape_relevant=True),
            "width_mult": CategoricalKnob([0.25, 0.5, 1.0],
                                          shape_relevant=True),
            "learning_rate": FloatKnob(1e-3, 1.0, is_exp=True),
            "weight_decay": FloatKnob(1e-5, 1e-2, is_exp=True),
            "batch_size": CategoricalKnob([32, 64, 128, 256],
                                          shape_relevant=True),
            "bf16": CategoricalKnob([True, False]),
            "quick_train": PolicyKnob("QUICK_TRAIN"),
            "share_params": PolicyKnob("SHARE_PARAMS"),
        }

    def _module(self) -> VGG:
        assert self._n_classes is not None
        width = max(8, int(64 * float(self.knobs["width_mult"])))
        dtype = jnp.bfloat16 if self.knobs.get("bf16", True) else jnp.float32
        return VGG(stage_sizes=VARIANTS[str(self.knobs["variant"])],
                   width=width, n_classes=int(self._n_classes), dtype=dtype)


if __name__ == "__main__":  # reference-style self-test block
    import tempfile

    from rafiki_tpu.utils.platform import apply_platform_env

    apply_platform_env()  # honor RAFIKI_JAX_PLATFORM=cpu for dev runs

    from rafiki_tpu.data import generate_image_classification_dataset
    from rafiki_tpu.model import test_model_class

    with tempfile.TemporaryDirectory() as d:
        train_p = f"{d}/train.npz"
        val_p = f"{d}/val.npz"
        generate_image_classification_dataset(train_p, 256, seed=0)
        ds = generate_image_classification_dataset(val_p, 64, seed=1)
        preds = test_model_class(
            VGGClassifier, TaskType.IMAGE_CLASSIFICATION, train_p, val_p,
            queries=[ds.images[0]],
            knobs={"variant": "vgg11", "width_mult": 0.25,
                   "batch_size": 32, "max_epochs": 5, "learning_rate": 0.05,
                   "weight_decay": 1e-4, "bf16": False,
                   "quick_train": False, "share_params": False})
        print("prediction:", int(np.argmax(preds[0])))
