"""VGG-style CNN family — the reference zoo's second CNN shape.

Parity target: SURVEY.md §2 "Model zoo" lists "TF VGG/DenseNet-style
CNNs" next to the feed-forward and ResNet families; this is the
TPU-native VGG: plain 3×3 conv stacks (+BatchNorm — the VGG-BN variant,
which actually trains without tricks) with stage-wise max-pool, a
global-average-pool head instead of VGG's 3 giant FC layers (GAP keeps
the net resolution-agnostic and drops ~90% of the parameters for free),
bf16 compute with f32 params/BN stats, data-parallel over the trial's
sub-mesh via NamedSharding. Convs are XLA's business — they lower
straight onto the MXU; no hand kernels needed here.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from rafiki_tpu.constants import TaskType
from rafiki_tpu.data import batch_iterator, \
    load_image_classification_dataset
from rafiki_tpu.model import (BaseModel, CategoricalKnob, FixedKnob,
                              FloatKnob, KnobConfig, PolicyKnob,
                              TrainContext, bucketed_forward, conform_images,
                              same_tree_shapes, train_epoch)
from rafiki_tpu.parallel.sharding import (batch_sharding, make_mesh,
                                          replicated)

#: convs per stage (each stage ends in 2x2 max-pool); channel width
#: doubles per stage from `width` up to 8x, VGG-style
VARIANTS: Dict[str, Sequence[int]] = {
    "vgg11": (1, 1, 2, 2, 2),
    "vgg13": (2, 2, 2, 2, 2),
    "vgg16": (2, 2, 3, 3, 3),
}


class VGG(nn.Module):
    """Conv stacks over (B, H, W, C); logits head on global avg pool."""

    stage_sizes: Sequence[int]
    width: int
    n_classes: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=self.dtype)
        x = x.astype(self.dtype)
        for stage, n_convs in enumerate(self.stage_sizes):
            ch = min(self.width * (2 ** stage), self.width * 8)
            for _ in range(n_convs):
                x = nn.Conv(ch, (3, 3), padding="SAME", use_bias=False,
                            dtype=self.dtype)(x)
                x = nn.relu(norm()(x))
            if min(x.shape[1], x.shape[2]) >= 2:  # never pool below 1px
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = jnp.mean(x, axis=(1, 2))  # GAP: resolution-agnostic head
        return nn.Dense(self.n_classes, dtype=jnp.float32,
                        name="head")(x.astype(jnp.float32))


class VGGClassifier(BaseModel):
    """VGG template: image classification, DP over the trial sub-mesh,
    SGD-momentum with cosine decay (same classic recipe as ResNet)."""

    TASKS = (TaskType.IMAGE_CLASSIFICATION,)

    @staticmethod
    def get_knob_config() -> KnobConfig:
        return {
            "max_epochs": FixedKnob(5),
            "variant": CategoricalKnob(list(VARIANTS),
                                       shape_relevant=True),
            "width_mult": CategoricalKnob([0.25, 0.5, 1.0],
                                          shape_relevant=True),
            "learning_rate": FloatKnob(1e-3, 1.0, is_exp=True),
            "weight_decay": FloatKnob(1e-5, 1e-2, is_exp=True),
            "batch_size": CategoricalKnob([32, 64, 128, 256],
                                          shape_relevant=True),
            "bf16": CategoricalKnob([True, False]),
            "quick_train": PolicyKnob("QUICK_TRAIN"),
            "share_params": PolicyKnob("SHARE_PARAMS"),
        }

    def __init__(self, **knobs: Any) -> None:
        super().__init__(**knobs)
        self._vars: Optional[Dict[str, Any]] = None
        self._n_classes: Optional[int] = None
        self._image_shape: Optional[Sequence[int]] = None
        self._fwd: Optional[Any] = None  # cached jitted forward

    # ---- internals ----
    def _module(self) -> VGG:
        assert self._n_classes is not None
        width = max(8, int(64 * float(self.knobs["width_mult"])))
        dtype = jnp.bfloat16 if self.knobs.get("bf16", True) else jnp.float32
        return VGG(stage_sizes=VARIANTS[str(self.knobs["variant"])],
                   width=width, n_classes=int(self._n_classes), dtype=dtype)

    def _prep(self, images: np.ndarray) -> np.ndarray:
        x = images.astype(np.float32) / 255.0
        if x.ndim == 3:
            x = x[..., None]
        return conform_images(x, self._image_shape)

    # ---- contract ----
    def train(self, dataset_path: str,
              ctx: Optional[TrainContext] = None) -> None:
        ctx = ctx or TrainContext()
        ds = load_image_classification_dataset(dataset_path)
        self._n_classes = ds.n_classes
        self._image_shape = ds.image_shape
        x = self._prep(ds.images)
        y = ds.labels

        module = self._module()
        devices = ctx.devices or jax.local_devices()
        mesh = make_mesh(devices)
        b_shard = batch_sharding(mesh)
        r_shard = replicated(mesh)

        n_data = len(devices)
        batch_size = int(self.knobs["batch_size"])
        batch_size = max(n_data, batch_size - batch_size % n_data)

        if self._vars is None:
            variables = module.init(jax.random.PRNGKey(0),
                                    jnp.zeros((1, *x.shape[1:])),
                                    train=False)
            variables = {"params": variables["params"],
                         "batch_stats": variables["batch_stats"]}
        else:
            variables = self._vars
        if ctx.shared_params is not None and self.knobs.get("share_params"):
            shared = ctx.shared_params.get("params")
            if shared is not None and same_tree_shapes(variables["params"],
                                                       shared):
                variables = {
                    "params": jax.tree_util.tree_map(jnp.asarray, shared),
                    "batch_stats": jax.tree_util.tree_map(
                        jnp.asarray,
                        ctx.shared_params.get("batch_stats",
                                              variables["batch_stats"])),
                }

        epochs = max(1, round(int(self.knobs["max_epochs"])
                              * float(ctx.budget_scale)))
        if self.knobs.get("quick_train"):
            epochs = min(epochs, 2)
        steps_per_epoch = max(1, (len(x) + batch_size - 1) // batch_size)
        schedule = optax.cosine_decay_schedule(
            float(self.knobs["learning_rate"]), epochs * steps_per_epoch)

        def decay_mask(tree):
            return jax.tree_util.tree_map_with_path(
                lambda kp, _: str(getattr(kp[-1], "key", "")) not in
                ("bias", "scale"), tree)

        tx = optax.chain(
            optax.add_decayed_weights(float(self.knobs["weight_decay"]),
                                      mask=decay_mask),
            optax.sgd(schedule, momentum=0.9, nesterov=True))

        params = jax.device_put(variables["params"], r_shard)
        batch_stats = jax.device_put(variables["batch_stats"], r_shard)
        opt_state = jax.device_put(tx.init(params), r_shard)

        @partial(jax.jit, donate_argnums=(0, 1, 2))
        def train_step(params, batch_stats, opt_state, xb, yb, mask):
            def loss_fn(p):
                logits, updates = module.apply(
                    {"params": p, "batch_stats": batch_stats}, xb,
                    train=True, mutable=["batch_stats"])
                losses = optax.softmax_cross_entropy_with_integer_labels(
                    logits.astype(jnp.float32), yb)
                loss = jnp.sum(losses * mask) / jnp.maximum(jnp.sum(mask),
                                                            1.0)
                return loss, updates["batch_stats"]

            (loss, new_stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), new_stats,
                    opt_state, loss)

        def step(state, b):
            params, batch_stats, opt_state = state
            params, batch_stats, opt_state, loss = train_step(
                params, batch_stats, opt_state, b["x"], b["y"], b["m"])
            return (params, batch_stats, opt_state), loss

        ctx.logger.define_plot("Loss over epochs", ["loss"], x_axis="epoch")
        self._vars = None  # donation invalidates aliased buffers
        with mesh:
            for epoch in range(epochs):
                state = (params, batch_stats, opt_state)
                (params, batch_stats, opt_state), mean_loss = train_epoch(
                    step, state,
                    ({"x": b["x"], "y": b["y"],
                      "m": b["mask"].astype(np.float32)}
                     for b in batch_iterator({"x": x, "y": y}, batch_size,
                                             seed=epoch)),
                    sharding=b_shard)
                ctx.logger.log(epoch=epoch, loss=mean_loss)
                if ctx.checkpoint is not None:
                    # preemption safety: worker throttles + persists
                    self._vars = {"params": params,
                                  "batch_stats": batch_stats}
                    ctx.checkpoint(self.dump_parameters,
                                   frac_done=(epoch + 1) / epochs)
                if ctx.should_continue is not None and \
                        not ctx.should_continue(epoch, -mean_loss):
                    break
        self._vars = {"params": params, "batch_stats": batch_stats}
        self._fwd = None

    def evaluate(self, dataset_path: str) -> float:
        ds = load_image_classification_dataset(dataset_path)
        probs = self._predict_probs(self._prep(ds.images))
        return float(np.mean(np.argmax(probs, -1) == ds.labels))

    def predict(self, queries: Sequence[Any]) -> List[Any]:
        x = self._prep(np.stack([np.asarray(q) for q in queries]))
        return [p.tolist() for p in self._predict_probs(x)]

    def warmup(self) -> None:
        """Compile the serving forward before traffic arrives."""
        if self._vars is None or self._image_shape is None:
            return
        self.predict([np.zeros(list(self._image_shape), np.uint8)])

    def _predict_probs(self, x: np.ndarray) -> np.ndarray:
        assert self._vars is not None, "model is not trained/loaded"
        if self._fwd is None:
            module = self._module()

            @jax.jit
            def forward(variables, xb):
                logits = module.apply(variables, xb, train=False)
                return jax.nn.softmax(logits.astype(jnp.float32), -1)

            self._fwd = forward
        return bucketed_forward(self._fwd, self._vars, x, bucket=64)

    def dump_parameters(self) -> Dict[str, Any]:
        assert self._vars is not None, "model is not trained"
        return {
            "params": jax.tree_util.tree_map(np.asarray,
                                             self._vars["params"]),
            "batch_stats": jax.tree_util.tree_map(
                np.asarray, self._vars["batch_stats"]),
            "meta": {"n_classes": self._n_classes,
                     "image_shape": list(self._image_shape or [])},
        }

    def load_parameters(self, params: Dict[str, Any]) -> None:
        self._n_classes = int(params["meta"]["n_classes"])
        self._image_shape = list(params["meta"]["image_shape"])
        self._vars = {
            "params": jax.tree_util.tree_map(jnp.asarray, params["params"]),
            "batch_stats": jax.tree_util.tree_map(jnp.asarray,
                                                  params["batch_stats"]),
        }
        self._fwd = None


if __name__ == "__main__":  # reference-style self-test block
    import tempfile

    from rafiki_tpu.utils.platform import apply_platform_env

    apply_platform_env()  # honor RAFIKI_JAX_PLATFORM=cpu for dev runs

    from rafiki_tpu.data import generate_image_classification_dataset
    from rafiki_tpu.model import test_model_class

    with tempfile.TemporaryDirectory() as d:
        train_p = f"{d}/train.npz"
        val_p = f"{d}/val.npz"
        generate_image_classification_dataset(train_p, 256, seed=0)
        ds = generate_image_classification_dataset(val_p, 64, seed=1)
        preds = test_model_class(
            VGGClassifier, TaskType.IMAGE_CLASSIFICATION, train_p, val_p,
            queries=[ds.images[0]],
            knobs={"variant": "vgg11", "width_mult": 0.25,
                   "batch_size": 32, "max_epochs": 5, "learning_rate": 0.05,
                   "weight_decay": 1e-4, "bf16": False,
                   "quick_train": False, "share_params": False})
        print("prediction:", int(np.argmax(preds[0])))
