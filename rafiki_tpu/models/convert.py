"""Pretrained-weight ingestion: HF-style Llama safetensors → sharded params.

BASELINE.md config #5 names "Llama-3 8B LoRA fine-tune"; without a
checkpoint-import path the template could only ever train a Llama-shaped
module from random init (VERDICT r3 missing #3). This module maps
HuggingFace-convention checkpoint names/layouts onto this framework's
flax tree and materializes each weight DIRECTLY into its 2-D
(fsdp × tensor-parallel) sharding:

- Name map: ``model.layers.{i}.self_attn.q_proj.weight`` →
  ``block_{i}/attn/wq/kernel`` etc. HF ``nn.Linear`` stores (out, in);
  flax Dense kernels are (in, out), so projection matrices transpose on
  the way through. Embeddings ((vocab, dim) both sides) and RMSNorm
  scales pass straight. Rotary layout needs no permutation: both sides
  use the half-split rotate-half convention.
- Sharded load: with a mesh, each target leaf is built via
  ``jax.make_array_from_callback`` over its ``NamedSharding`` — the
  callback reads ONLY the requested shard's slice from the (mmap'd)
  safetensors file (``safe_open().get_slice()``), so no host ever
  materializes a full 8B tensor, let alone the full tree. fsdp specs
  from ``parallel/sharding.py`` decide the slicing.
- Leaves absent from the checkpoint (``lora_a``/``lora_b`` adapters —
  LoRA state is ours, not HF's) keep their initialized values.

``export_llama_safetensors`` writes the inverse mapping — round-trip
tested (export → sharded import → identical generation), and the
practical path for shipping fine-tuned weights back out.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..utils.pytree import flatten_paths as _flatten, set_path as _set_path

_BLOCK_RE = re.compile(r"^block_(\d+)$")


def hf_name_for(path: Tuple[str, ...]) -> Optional[Tuple[str, bool]]:
    """(HF tensor name, needs_transpose) for one of our param paths, or
    None for leaves that have no checkpoint counterpart (LoRA adapters).
    Raises on paths that look importable but match no rule — silent
    drops would load a half-initialized model."""
    if path[-1] in ("lora_a", "lora_b") or "moe" in path:
        # LoRA adapters and MoE routers/experts have no counterpart in
        # an HF dense-Llama checkpoint — they keep their init (and the
        # trainable mask trains them)
        return None
    joined = "/".join(path)
    if joined == "tok_embed/embedding":
        return "model.embed_tokens.weight", False
    if joined == "final_norm/scale":
        return "model.norm.weight", False
    if joined == "lm_head/kernel":
        return "lm_head.weight", True
    m = _BLOCK_RE.match(path[0])
    if m:
        i = int(m.group(1))
        rest = "/".join(path[1:])
        proj = {"attn/wq/kernel": "self_attn.q_proj",
                "attn/wk/kernel": "self_attn.k_proj",
                "attn/wv/kernel": "self_attn.v_proj",
                "attn/wo/kernel": "self_attn.o_proj",
                "gate/kernel": "mlp.gate_proj",
                "up/kernel": "mlp.up_proj",
                "down/kernel": "mlp.down_proj"}.get(rest)
        if proj:
            return f"model.layers.{i}.{proj}.weight", True
        norm = {"RMSNorm_0/scale": "input_layernorm",
                "RMSNorm_1/scale": "post_attention_layernorm"}.get(rest)
        if norm:
            return f"model.layers.{i}.{norm}.weight", False
    raise KeyError(f"no HF mapping for param path {joined!r}")


def _resolve_checkpoint(path: str) -> Dict[str, str]:
    """Tensor name → safetensors file for every layout HF ships:
    a single ``.safetensors`` file, a ``*.index.json`` (sharded
    multi-file checkpoints — how Llama-3 8B actually downloads), or a
    directory containing either."""
    import glob
    import json
    import os

    from safetensors import safe_open

    def from_index(idx_path: str) -> Dict[str, str]:
        with open(idx_path) as f:
            index = json.load(f)
        base = os.path.dirname(os.path.abspath(idx_path))
        return {name: os.path.join(base, fname)
                for name, fname in index["weight_map"].items()}

    def from_files(files) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for fp in files:
            with safe_open(fp, framework="np") as f:
                for name in f.keys():
                    out[name] = fp
        return out

    if os.path.isdir(path):
        idx = os.path.join(path, "model.safetensors.index.json")
        if os.path.exists(idx):
            return from_index(idx)
        files = sorted(glob.glob(os.path.join(path, "*.safetensors")))
        if not files:
            raise FileNotFoundError(
                f"{path}: no .safetensors or index.json found")
        return from_files(files)
    if path.endswith(".index.json"):
        return from_index(path)
    return from_files([path])


def read_hf_rope_config(path: str
                        ) -> Tuple[Optional[float], Optional[Dict]]:
    """``(rope_theta, rope_scaling)`` from the ``config.json`` next to
    an HF checkpoint (file, index, or directory); (None, None) when
    absent/unreadable. Llama-3 uses theta 500000 vs the Llama-1/2
    default 10000, and Llama-3.1+ additionally applies ``rope_scaling``
    — BOTH load cleanly and generate garbage when not honored, so
    callers cross-check the knob and warn on scaling they can't
    apply."""
    import json
    import os

    d = path if os.path.isdir(path) else os.path.dirname(
        os.path.abspath(path))
    cfg = os.path.join(d, "config.json")
    try:
        with open(cfg) as f:
            c = json.load(f)
        theta = c.get("rope_theta")
        scaling = c.get("rope_scaling")
        return (float(theta) if theta is not None else None,
                dict(scaling) if isinstance(scaling, dict) else None)
    except (OSError, ValueError, TypeError, json.JSONDecodeError):
        return None, None


def read_hf_rope_theta(path: str) -> Optional[float]:
    """Back-compat shim over :func:`read_hf_rope_config`."""
    return read_hf_rope_config(path)[0]


def import_llama_safetensors(path: str, params: Any, mesh=None,
                             tp_rules: Optional[Dict[str, int]] = None,
                             fsdp: bool = True,
                             min_size: int = 2 ** 16) -> Any:
    """Load an HF-convention Llama checkpoint into ``params``' structure.

    ``path``: a ``.safetensors`` file, a ``*.index.json``, or a
    checkpoint directory (sharded multi-file checkpoints supported —
    see :func:`_resolve_checkpoint`). ``params``: an initialized tree
    (shapes define what to read; leaves missing from the checkpoint
    keep their values). With ``mesh``, every imported leaf lands
    directly in its ``param_shardings`` placement via shard-sized file
    reads; without one, plain host arrays.
    """
    import contextlib

    import jax

    from safetensors import safe_open

    from rafiki_tpu.parallel.sharding import param_shardings

    name_to_file = _resolve_checkpoint(path)
    shardings = None
    if mesh is not None:
        shardings = _flatten(param_shardings(
            params, mesh, tp_rules=tp_rules, fsdp=fsdp,
            min_size=min_size))
    flat = _flatten(params)
    out = jax.tree_util.tree_map(lambda x: x, params)  # fresh structure

    with contextlib.ExitStack() as stack:
        handles: Dict[str, Any] = {}  # file → safe_open handle (mmap)

        def handle(fp: str):
            if fp not in handles:
                handles[fp] = stack.enter_context(
                    safe_open(fp, framework="np"))
            return handles[fp]

        for p, leaf in flat.items():
            mapped = hf_name_for(p)
            if mapped is None:
                continue
            name, transpose = mapped
            if name not in name_to_file:
                raise KeyError(
                    f"checkpoint {path!r} is missing {name!r} "
                    f"(for param {'/'.join(p)})")
            target_dtype = np.dtype(getattr(leaf, "dtype", np.float32))
            shape = tuple(leaf.shape)
            src = handle(name_to_file[name]).get_slice(name)
            src_shape = tuple(src.get_shape())
            want = tuple(reversed(shape)) if transpose else shape
            if src_shape != want:
                raise ValueError(
                    f"{name}: checkpoint shape {src_shape} != expected "
                    f"{want} for param {'/'.join(p)}")

            def read(idx, src=src, transpose=transpose,
                     dt=target_dtype):
                # idx: per-dim slices of the TARGET; a transposed weight
                # reads the mirrored source slice then transposes — only
                # the shard's bytes leave the (mmap'd) file
                if transpose:
                    block = src[idx[1], idx[0]]
                    return np.ascontiguousarray(
                        np.asarray(block).T).astype(dt, copy=False)
                return np.asarray(src[idx]).astype(dt, copy=False)

            if shardings is not None:
                arr = jax.make_array_from_callback(
                    shape, shardings[p], read)
            else:
                full = (slice(None),) * len(shape)
                arr = jax.numpy.asarray(read(full))
            _set_path(out, p, arr)
    return out


def export_llama_safetensors(params: Any, path: str) -> None:
    """Write ``params`` as an HF-convention Llama checkpoint (LoRA
    adapters are skipped — merge or ship them separately)."""
    from safetensors.numpy import save_file

    tensors: Dict[str, np.ndarray] = {}
    for p, leaf in _flatten(params).items():
        mapped = hf_name_for(p)
        if mapped is None:
            continue
        name, transpose = mapped
        arr = np.asarray(leaf)
        tensors[name] = np.ascontiguousarray(arr.T if transpose else arr)
    save_file(tensors, path)
