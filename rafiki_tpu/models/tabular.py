"""JaxTabularMLP — TPU-first tabular classifier.

The reference's tabular story is CPU sklearn/xgboost (SURVEY.md §2 "Model
zoo"); this template is its accelerator-native counterpart: a jit-compiled
flax MLP over standardized features, so tabular jobs ride the same TPU
sub-mesh scheduling as every other template. Feature standardization
(mean/std learned at train time) ships inside the parameter blob.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

# NOTE: zoo templates use absolute imports — their module source is shipped
# to workers via serialize_model_class() and re-imported standalone.
from rafiki_tpu.constants import TaskType
from rafiki_tpu.data import batch_iterator, load_tabular_dataset
from rafiki_tpu.model import (BaseModel, CategoricalKnob, FixedKnob,
                              FloatKnob, IntegerKnob, KnobConfig,
                              PolicyKnob, TrainContext, bucketed_forward,
                              same_tree_shapes)


class JaxTabularMLP(BaseModel):
    """Dense net over standardized tabular features."""

    TASKS = (TaskType.TABULAR_CLASSIFICATION,)

    @staticmethod
    def get_knob_config() -> KnobConfig:
        return {
            "max_epochs": FixedKnob(10),
            "hidden_layer_count": IntegerKnob(1, 4, shape_relevant=True),
            "hidden_layer_units": IntegerKnob(16, 256, is_exp=True,
                                              shape_relevant=True),
            "dropout": FloatKnob(0.0, 0.5),
            "learning_rate": FloatKnob(1e-4, 1e-1, is_exp=True),
            "batch_size": CategoricalKnob([64, 128, 256],
                                          shape_relevant=True),
            "quick_train": PolicyKnob("QUICK_TRAIN"),
            "share_params": PolicyKnob("SHARE_PARAMS"),
        }

    def __init__(self, **knobs: Any) -> None:
        super().__init__(**knobs)
        self._params: Optional[Any] = None
        self._n_classes: int = 0
        self._mean: Optional[np.ndarray] = None
        self._std: Optional[np.ndarray] = None
        self._fwd: Optional[Any] = None

    # ---- internals ----
    def _module(self):
        from flax import linen as nn

        layers = int(self.knobs["hidden_layer_count"])
        units = int(self.knobs["hidden_layer_units"])
        rate = float(self.knobs.get("dropout", 0.0))
        n_classes = self._n_classes

        class _Net(nn.Module):
            @nn.compact
            def __call__(self, x, train: bool = False):
                for _ in range(layers):
                    x = nn.relu(nn.Dense(units)(x))
                    x = nn.Dropout(rate, deterministic=not train)(x)
                return nn.Dense(n_classes)(x)

        return _Net()

    def _standardize(self, x: np.ndarray) -> np.ndarray:
        assert self._mean is not None and self._std is not None
        return ((x - self._mean) / self._std).astype(np.float32)

    # ---- contract ----
    def train(self, dataset_path: str,
              ctx: Optional[TrainContext] = None) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        ctx = ctx or TrainContext()
        ds = load_tabular_dataset(dataset_path)
        if ds.n_classes == 0:
            raise ValueError("JaxTabularMLP is a classifier; dataset is "
                             "regression (n_classes=0)")
        self._n_classes = int(ds.n_classes)
        self._mean = ds.features.mean(axis=0)
        self._std = ds.features.std(axis=0) + 1e-6
        x = self._standardize(ds.features)
        y = ds.labels

        module = self._module()
        if self._params is None:
            params = module.init(jax.random.PRNGKey(0),
                                 jnp.zeros((1, x.shape[1])))["params"]
        else:
            params = self._params
        if ctx.shared_params is not None and self.knobs.get("share_params"):
            shared = ctx.shared_params.get("params")
            if shared is not None and same_tree_shapes(params, shared):
                params = jax.tree_util.tree_map(jnp.asarray, shared)

        tx = optax.adam(float(self.knobs["learning_rate"]))
        opt_state = tx.init(params)

        # donate the param/opt trees: in-place update, no per-step copies
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def train_step(params, opt_state, rng, xb, yb, mask):
            def loss_fn(p):
                logits = module.apply({"params": p}, xb, train=True,
                                      rngs={"dropout": rng})
                losses = optax.softmax_cross_entropy_with_integer_labels(
                    logits, yb)
                return jnp.sum(losses * mask) / jnp.maximum(
                    jnp.sum(mask), 1.0)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        epochs = max(1, round(int(self.knobs["max_epochs"])
                              * float(ctx.budget_scale)))
        if self.knobs.get("quick_train"):
            epochs = min(epochs, 2)
        batch_size = int(self.knobs["batch_size"])
        rng = jax.random.PRNGKey(1)
        ctx.logger.define_plot("Loss over epochs", ["loss"], x_axis="epoch")
        # donation invalidates buffers that may alias self._params (warm
        # start / re-train): drop the stale reference first
        self._params = None
        for epoch in range(epochs):
            losses = []
            for b in batch_iterator({"x": x, "y": y}, batch_size,
                                    seed=epoch):
                rng, step_rng = jax.random.split(rng)
                params, opt_state, loss = train_step(
                    params, opt_state, step_rng, b["x"], b["y"],
                    b["mask"].astype(np.float32))
                losses.append(float(loss))
            mean_loss = float(np.mean(losses))
            ctx.logger.log(epoch=epoch, loss=mean_loss)
            if ctx.checkpoint is not None:
                # preemption safety: worker throttles + persists
                self._params = params
                ctx.checkpoint(self.dump_parameters,
                               frac_done=(epoch + 1) / epochs)
            if ctx.should_continue is not None and \
                    not ctx.should_continue(epoch, -mean_loss):
                break
        self._params = params
        self._fwd = None

    def _probs(self, x: np.ndarray) -> np.ndarray:
        import jax

        assert self._params is not None, "model is not trained/loaded"
        if self._fwd is None:
            module = self._module()

            @jax.jit
            def forward(params, xb):
                return jax.nn.softmax(
                    module.apply({"params": params}, xb), -1)

            self._fwd = forward
        return bucketed_forward(self._fwd, self._params, x, bucket=256)

    def evaluate(self, dataset_path: str) -> float:
        ds = load_tabular_dataset(dataset_path)
        probs = self._probs(self._standardize(ds.features))
        return float(np.mean(np.argmax(probs, -1) == ds.labels))

    def predict(self, queries: Sequence[Any]) -> List[Any]:
        x = np.asarray([np.asarray(q, np.float32).ravel()
                        for q in queries], np.float32)
        return [p.tolist() for p in self._probs(self._standardize(x))]

    def dump_parameters(self) -> Dict[str, Any]:
        import jax

        assert self._params is not None, "model is not trained"
        return {"params": jax.tree_util.tree_map(np.asarray, self._params),
                "mean": self._mean, "std": self._std,
                "meta": {"n_classes": self._n_classes}}

    def load_parameters(self, params: Dict[str, Any]) -> None:
        import jax
        import jax.numpy as jnp

        self._n_classes = int(params["meta"]["n_classes"])
        self._mean = np.asarray(params["mean"])
        self._std = np.asarray(params["std"])
        self._params = jax.tree_util.tree_map(jnp.asarray, params["params"])
        self._fwd = None


if __name__ == "__main__":  # reference-style self-test block
    import tempfile

    from rafiki_tpu.utils.platform import apply_platform_env

    apply_platform_env()

    from rafiki_tpu.data import generate_tabular_dataset
    from rafiki_tpu.model import test_model_class

    with tempfile.TemporaryDirectory() as d:
        train_p, val_p = f"{d}/train.npz", f"{d}/val.npz"
        generate_tabular_dataset(train_p, 1024, seed=0)
        ds = generate_tabular_dataset(val_p, 256, seed=1)
        preds = test_model_class(
            JaxTabularMLP, TaskType.TABULAR_CLASSIFICATION, train_p, val_p,
            queries=[ds.features[0]])
        print("probs:", [round(p, 3) for p in preds[0]])
