"""JaxTabularMLP — TPU-first tabular classifier.

The reference's tabular story is CPU sklearn/xgboost (SURVEY.md §2 "Model
zoo"); this template is its accelerator-native counterpart: a jit-compiled
flax MLP over standardized features, so tabular jobs ride the same TPU
sub-mesh scheduling as every other template. Feature standardization
(mean/std learned at train time) ships inside the parameter blob.

Knob application is *functional*: ``learning_rate`` AND ``dropout`` are
traceable — the train step takes them as traced scalar operands, with
dropout applied as explicit inverted-dropout masks (``bernoulli(keep)``
with a traced keep probability) instead of ``nn.Dropout`` (whose rate is
compile-time Python). The same functions back the sequential ``train()``
loop and the gang engine's vmapped lanes (``make_gang_spec``), so lanes
differing in lr/dropout share ONE compiled step.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

# NOTE: zoo templates use absolute imports — their module source is shipped
# to workers via serialize_model_class() and re-imported standalone.
from rafiki_tpu.constants import TaskType
from rafiki_tpu.data import batch_iterator, load_tabular_dataset
from rafiki_tpu.model import (BaseModel, CategoricalKnob, FixedKnob,
                              FloatKnob, GangSpec, IntegerKnob, Knobs,
                              KnobConfig, PolicyKnob, TrainContext,
                              bucketed_forward, same_tree_shapes)


class JaxTabularMLP(BaseModel):
    """Dense net over standardized tabular features."""

    TASKS = (TaskType.TABULAR_CLASSIFICATION,)

    @staticmethod
    def get_knob_config() -> KnobConfig:
        return {
            "max_epochs": FixedKnob(10),
            "hidden_layer_count": IntegerKnob(1, 4, shape_relevant=True),
            "hidden_layer_units": IntegerKnob(16, 256, is_exp=True,
                                              shape_relevant=True),
            "dropout": FloatKnob(0.0, 0.5, traceable=True),
            "learning_rate": FloatKnob(1e-4, 1e-1, is_exp=True,
                                       traceable=True),
            "batch_size": CategoricalKnob([64, 128, 256],
                                          shape_relevant=True),
            "quick_train": PolicyKnob("QUICK_TRAIN"),
            "share_params": PolicyKnob("SHARE_PARAMS"),
        }

    def __init__(self, **knobs: Any) -> None:
        super().__init__(**knobs)
        self._params: Optional[Any] = None
        self._n_classes: int = 0
        self._mean: Optional[np.ndarray] = None
        self._std: Optional[np.ndarray] = None
        self._fwd: Optional[Any] = None

    # ---- internals ----
    @staticmethod
    def _build_module(layers: int, units: int, n_classes: int):
        from flax import linen as nn

        class _Net(nn.Module):
            @nn.compact
            def __call__(self, x, drop_masks=None):
                for li in range(layers):
                    x = nn.relu(nn.Dense(units)(x))
                    if drop_masks is not None:  # None ⇒ deterministic
                        x = x * drop_masks[li]
                return nn.Dense(n_classes)(x)

        return _Net()

    def _module(self):
        return self._build_module(int(self.knobs["hidden_layer_count"]),
                                  int(self.knobs["hidden_layer_units"]),
                                  self._n_classes)

    @staticmethod
    def _lane_functions(module, layers: int, units: int, n_features: int,
                        batch_size: int):
        """``(init_lane, train_step)`` shared by the sequential loop and
        the gang engine's vmapped lanes. ``hp`` = traced
        ``{"dropout", "learning_rate"}``: dropout rides as explicit
        inverted-dropout masks (traced keep probability), lr as a
        post-``scale_by_adam`` multiplier — bit-identical to
        ``optax.adam(lr)``."""
        import jax
        import jax.numpy as jnp
        import optax

        tx = optax.scale_by_adam()

        def init_lane(rng: Any, hp: Dict[str, Any]) -> Dict[str, Any]:
            params = module.init(jax.random.PRNGKey(0),
                                 jnp.zeros((1, n_features)))["params"]
            # dropout rng stream lives IN the lane state so the compiled
            # step owns its own randomness (seed matches the historical
            # per-template PRNGKey(1) stream)
            return {"params": params, "opt": tx.init(params),
                    "rng": jax.random.PRNGKey(1)}

        def train_step(state: Dict[str, Any], hp: Dict[str, Any],
                       batch: Dict[str, Any]):
            rng, step_rng = jax.random.split(state["rng"])
            keep = 1.0 - hp["dropout"]  # knob domain [0, 0.5] ⇒ keep>0
            layer_rngs = jax.random.split(step_rng, max(layers, 1))
            drop_masks = [
                jax.random.bernoulli(layer_rngs[li], keep,
                                     (batch_size, units)) / keep
                for li in range(layers)]

            def loss_fn(p):
                logits = module.apply({"params": p}, batch["x"],
                                      drop_masks=drop_masks)
                losses = optax.softmax_cross_entropy_with_integer_labels(
                    logits, batch["y"])
                mask = batch["mask"].astype(jnp.float32)
                return jnp.sum(losses * mask) / jnp.maximum(
                    jnp.sum(mask), 1.0)

            loss, grads = jax.value_and_grad(loss_fn)(state["params"])
            updates, opt = tx.update(grads, state["opt"], state["params"])
            updates = jax.tree_util.tree_map(
                lambda u: -hp["learning_rate"] * u, updates)
            return {"params": optax.apply_updates(state["params"], updates),
                    "opt": opt, "rng": rng}, loss

        return init_lane, train_step

    @classmethod
    def gang_epochs(cls, knobs: Knobs, budget_scale: float) -> int:
        epochs = max(1, round(int(knobs["max_epochs"])
                              * float(budget_scale)))
        if knobs.get("quick_train"):
            epochs = min(epochs, 2)
        return epochs

    def _standardize(self, x: np.ndarray) -> np.ndarray:
        assert self._mean is not None and self._std is not None
        return ((x - self._mean) / self._std).astype(np.float32)

    # ---- contract ----
    def train(self, dataset_path: str,
              ctx: Optional[TrainContext] = None) -> None:
        import jax
        import jax.numpy as jnp

        ctx = ctx or TrainContext()
        ds = load_tabular_dataset(dataset_path)
        if ds.n_classes == 0:
            raise ValueError("JaxTabularMLP is a classifier; dataset is "
                             "regression (n_classes=0)")
        self._n_classes = int(ds.n_classes)
        self._mean = ds.features.mean(axis=0)
        self._std = ds.features.std(axis=0) + 1e-6
        x = self._standardize(ds.features)
        y = ds.labels

        module = self._module()
        batch_size = int(self.knobs["batch_size"])
        init_lane, train_step = self._lane_functions(
            module, int(self.knobs["hidden_layer_count"]),
            int(self.knobs["hidden_layer_units"]), x.shape[1], batch_size)
        hp = {"dropout": jnp.float32(float(self.knobs.get("dropout", 0.0))),
              "learning_rate":
              jnp.float32(float(self.knobs["learning_rate"]))}
        state = init_lane(jax.random.PRNGKey(0), hp)
        if self._params is not None:  # warm-started via load_parameters
            state = {**state, "params": self._params}
        if ctx.shared_params is not None and self.knobs.get("share_params"):
            shared = ctx.shared_params.get("params")
            if shared is not None and same_tree_shapes(state["params"],
                                                       shared):
                state = {**state,
                         "params": jax.tree_util.tree_map(jnp.asarray,
                                                          shared)}

        # donate the state tree: in-place update, no per-step copies
        step = jax.jit(train_step, donate_argnums=(0,))
        epochs = self.gang_epochs(self.knobs, ctx.budget_scale)
        ctx.logger.define_plot("Loss over epochs", ["loss"], x_axis="epoch")
        # donation invalidates buffers that may alias self._params (warm
        # start / re-train): drop the stale reference first
        self._params = None
        for epoch in range(epochs):
            losses = []
            for b in batch_iterator({"x": x, "y": y}, batch_size,
                                    seed=epoch):
                state, loss = step(state, hp, b)
                losses.append(float(loss))
            mean_loss = float(np.mean(losses))
            ctx.logger.log(epoch=epoch, loss=mean_loss)
            if ctx.checkpoint is not None:
                # preemption safety: worker throttles + persists
                self._params = state["params"]
                ctx.checkpoint(self.dump_parameters,
                               frac_done=(epoch + 1) / epochs)
            if ctx.should_continue is not None and \
                    not ctx.should_continue(epoch, -mean_loss):
                break
        self._params = state["params"]
        self._fwd = None

    @classmethod
    def make_gang_spec(cls, knobs: Knobs, train_dataset_path: str,
                       val_dataset_path: str) -> GangSpec:
        """Functional training recipe for the gang engine: lanes share
        this static bucket's architecture/batch shape and differ only in
        the traced ``dropout``/``learning_rate`` operands."""
        import jax.numpy as jnp

        ds = load_tabular_dataset(train_dataset_path)
        if ds.n_classes == 0:
            raise ValueError("JaxTabularMLP is a classifier; dataset is "
                             "regression (n_classes=0)")
        mean = ds.features.mean(axis=0)
        std = ds.features.std(axis=0) + 1e-6
        x = ((ds.features - mean) / std).astype(np.float32)
        y = ds.labels
        layers = int(knobs["hidden_layer_count"])
        units = int(knobs["hidden_layer_units"])
        batch_size = int(knobs["batch_size"])
        module = cls._build_module(layers, units, int(ds.n_classes))
        init_lane, train_step = cls._lane_functions(
            module, layers, units, x.shape[1], batch_size)
        vds = load_tabular_dataset(val_dataset_path)
        vx = ((vds.features - mean) / std).astype(np.float32)
        vy = vds.labels
        meta = {"n_classes": int(ds.n_classes)}

        def epoch_batches(epoch: int):
            return batch_iterator({"x": x, "y": y}, batch_size, seed=epoch)

        def eval_lane(state, hp, xb):
            return jnp.argmax(module.apply({"params": state["params"]},
                                           xb), -1)

        def eval_batches():
            return batch_iterator({"x": vx, "y": vy}, 256, shuffle=False)

        def export_blob(lane_state, hp):
            return {"params": jax.tree_util.tree_map(
                        np.asarray, lane_state["params"]),
                    "mean": np.asarray(mean), "std": np.asarray(std),
                    "meta": dict(meta)}

        def warm_lane(fresh, blob):
            shared = (blob or {}).get("params")
            if shared is None or not same_tree_shapes(fresh["params"],
                                                      shared):
                return fresh  # incompatible architecture → cold start
            return {**fresh, "params": jax.tree_util.tree_map(jnp.asarray,
                                                              shared)}

        import jax

        return GangSpec(hp_names=("dropout", "learning_rate"),
                        init_lane=init_lane, train_step=train_step,
                        epoch_batches=epoch_batches, eval_lane=eval_lane,
                        eval_batches=eval_batches, export_blob=export_blob,
                        warm_lane=warm_lane,
                        share_params_knob="share_params")

    def _probs(self, x: np.ndarray) -> np.ndarray:
        import jax

        assert self._params is not None, "model is not trained/loaded"
        if self._fwd is None:
            module = self._module()

            @jax.jit
            def forward(params, xb):
                return jax.nn.softmax(
                    module.apply({"params": params}, xb), -1)

            self._fwd = forward
        return bucketed_forward(self._fwd, self._params, x, bucket=256)

    def evaluate(self, dataset_path: str) -> float:
        ds = load_tabular_dataset(dataset_path)
        probs = self._probs(self._standardize(ds.features))
        return float(np.mean(np.argmax(probs, -1) == ds.labels))

    def predict(self, queries: Sequence[Any]) -> List[Any]:
        x = np.asarray([np.asarray(q, np.float32).ravel()
                        for q in queries], np.float32)
        return [p.tolist() for p in self._probs(self._standardize(x))]

    def dump_parameters(self) -> Dict[str, Any]:
        import jax

        assert self._params is not None, "model is not trained"
        return {"params": jax.tree_util.tree_map(np.asarray, self._params),
                "mean": self._mean, "std": self._std,
                "meta": {"n_classes": self._n_classes}}

    def load_parameters(self, params: Dict[str, Any]) -> None:
        import jax
        import jax.numpy as jnp

        self._n_classes = int(params["meta"]["n_classes"])
        self._mean = np.asarray(params["mean"])
        self._std = np.asarray(params["std"])
        self._params = jax.tree_util.tree_map(jnp.asarray, params["params"])
        self._fwd = None


if __name__ == "__main__":  # reference-style self-test block
    import tempfile

    from rafiki_tpu.utils.platform import apply_platform_env

    apply_platform_env()

    from rafiki_tpu.data import generate_tabular_dataset
    from rafiki_tpu.model import test_model_class

    with tempfile.TemporaryDirectory() as d:
        train_p, val_p = f"{d}/train.npz", f"{d}/val.npz"
        generate_tabular_dataset(train_p, 1024, seed=0)
        ds = generate_tabular_dataset(val_p, 256, seed=1)
        preds = test_model_class(
            JaxTabularMLP, TaskType.TABULAR_CLASSIFICATION, train_p, val_p,
            queries=[ds.features[0]])
        print("probs:", [round(p, 3) for p in preds[0]])
