"""Model zoo: task templates implementing the BaseModel contract.

Import cost matters here (workers import only the template they run), so
this module exposes lazy accessors instead of importing every template.
"""

from typing import Dict, Type

from ..model.base import BaseModel

_ZOO = {
    "JaxFeedForward": ("rafiki_tpu.models.mlp", "JaxFeedForward"),
    "ResNetClassifier": ("rafiki_tpu.models.resnet", "ResNetClassifier"),
    "VGGClassifier": ("rafiki_tpu.models.vgg", "VGGClassifier"),
    "DenseNetClassifier": ("rafiki_tpu.models.densenet",
                           "DenseNetClassifier"),
    "ViTBase16": ("rafiki_tpu.models.vit", "ViTBase16"),
    "BertClassifier": ("rafiki_tpu.models.bert", "BertClassifier"),
    "LlamaLoRA": ("rafiki_tpu.models.llama_lora", "LlamaLoRA"),
    "BigramHMM": ("rafiki_tpu.models.pos_tagging", "BigramHMM"),
    "BiLSTMTagger": ("rafiki_tpu.models.pos_tagging", "BiLSTMTagger"),
    "SklearnDecisionTree": ("rafiki_tpu.models.sklearn_models",
                            "SklearnDecisionTree"),
    "SklearnGBDT": ("rafiki_tpu.models.sklearn_models", "SklearnGBDT"),
    "SklearnSVM": ("rafiki_tpu.models.sklearn_models", "SklearnSVM"),
    "JaxTabularMLP": ("rafiki_tpu.models.tabular", "JaxTabularMLP"),
}


def get_model_template(name: str) -> Type[BaseModel]:
    import importlib

    if name not in _ZOO:
        raise KeyError(f"unknown template {name!r}; known: {sorted(_ZOO)}")
    mod_name, cls_name = _ZOO[name]
    try:
        mod = importlib.import_module(mod_name)
    except ModuleNotFoundError as e:
        raise KeyError(
            f"template {name!r} is not available in this build "
            f"({mod_name} missing)") from e
    return getattr(mod, cls_name)


def list_model_templates() -> Dict[str, str]:
    """Importable templates only (roadmap entries are silently skipped)."""
    import importlib.util

    out = {}
    for name, (mod, cls) in _ZOO.items():
        if importlib.util.find_spec(mod) is not None:
            out[name] = f"{mod}.{cls}"
    return out
