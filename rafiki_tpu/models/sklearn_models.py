"""Tabular-classification templates (SURVEY.md §2 "Model zoo": the
reference ships sklearn decision-tree / xgboost tabular templates).

:class:`SklearnDecisionTree` fits ``sklearn.tree.DecisionTreeClassifier``
but serializes the fitted tree as plain numpy arrays (children/feature/
threshold/leaf-distribution) instead of pickles — the ParamStore transport
is msgpack'd arrays, and unpickling foreign blobs on workers is exactly
the attack surface the model-transport design avoids. Prediction walks
the exported arrays directly (vectorized numpy), so a loaded model does
not even need sklearn present.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

# NOTE: zoo templates use absolute imports — their module source is shipped
# to workers via serialize_model_class() and re-imported standalone.
from rafiki_tpu.constants import TaskType
from rafiki_tpu.data import load_tabular_dataset
from rafiki_tpu.model import (BaseModel, CategoricalKnob, FloatKnob,
                              IntegerKnob, KnobConfig, TrainContext)


class SklearnDecisionTree(BaseModel):
    """Decision-tree classifier over tabular features."""

    TASKS = (TaskType.TABULAR_CLASSIFICATION,)

    @staticmethod
    def get_knob_config() -> KnobConfig:
        return {
            "max_depth": IntegerKnob(2, 16),
            "min_samples_split": IntegerKnob(2, 32, is_exp=True),
            "min_impurity_decrease": FloatKnob(1e-6, 1e-1, is_exp=True),
            "criterion": CategoricalKnob(["gini", "entropy"]),
        }

    def __init__(self, **knobs: Any) -> None:
        super().__init__(**knobs)
        # exported tree arrays (see dump_parameters)
        self._tree: Optional[Dict[str, np.ndarray]] = None
        self._n_classes: int = 0

    # ---- contract ----
    def train(self, dataset_path: str,
              ctx: Optional[TrainContext] = None) -> None:
        from sklearn.tree import DecisionTreeClassifier

        ctx = ctx or TrainContext()
        ds = load_tabular_dataset(dataset_path)
        if ds.n_classes == 0:
            raise ValueError("SklearnDecisionTree is a classifier; "
                             "dataset is regression (n_classes=0)")
        clf = DecisionTreeClassifier(
            max_depth=int(self.knobs["max_depth"]),
            min_samples_split=int(self.knobs["min_samples_split"]),
            min_impurity_decrease=float(
                self.knobs["min_impurity_decrease"]),
            criterion=str(self.knobs["criterion"]), random_state=0)
        clf.fit(ds.features, ds.labels)
        t = clf.tree_
        # leaf value → class distribution (normalized counts)
        dist = t.value[:, 0, :].astype(np.float64)
        dist = dist / np.maximum(dist.sum(axis=1, keepdims=True), 1e-12)
        self._tree = {
            "children_left": t.children_left.astype(np.int32),
            "children_right": t.children_right.astype(np.int32),
            "feature": t.feature.astype(np.int32),
            "threshold": t.threshold.astype(np.float32),
            "dist": dist.astype(np.float32),
        }
        self._n_classes = int(ds.n_classes)
        ctx.logger.log(epoch=0, loss=float(1.0 - clf.score(ds.features,
                                                           ds.labels)))

    def _probs(self, x: np.ndarray) -> np.ndarray:
        assert self._tree is not None, "model is not trained/loaded"
        t = self._tree
        node = np.zeros(len(x), np.int32)
        # vectorized traversal: all rows step one level per iteration;
        # leaves have children == -1 and simply stay put
        for _ in range(64):  # > max tree depth
            feat = t["feature"][node]
            leaf = feat < 0
            if leaf.all():
                break
            go_left = x[np.arange(len(x)), np.maximum(feat, 0)] \
                <= t["threshold"][node]
            nxt = np.where(go_left, t["children_left"][node],
                           t["children_right"][node])
            node = np.where(leaf, node, nxt).astype(np.int32)
        return t["dist"][node]

    def evaluate(self, dataset_path: str) -> float:
        ds = load_tabular_dataset(dataset_path)
        probs = self._probs(ds.features)
        return float(np.mean(np.argmax(probs, -1) == ds.labels))

    def predict(self, queries: Sequence[Any]) -> List[Any]:
        x = np.asarray([np.asarray(q, np.float32).ravel()
                        for q in queries], np.float32)
        return [p.tolist() for p in self._probs(x)]

    def dump_parameters(self) -> Dict[str, Any]:
        assert self._tree is not None, "model is not trained"
        return {**self._tree, "meta": {"n_classes": self._n_classes}}

    def load_parameters(self, params: Dict[str, Any]) -> None:
        self._n_classes = int(params["meta"]["n_classes"])
        self._tree = {k: np.asarray(params[k]) for k in
                      ("children_left", "children_right", "feature",
                       "threshold", "dist")}


if __name__ == "__main__":  # reference-style self-test block
    import tempfile

    from rafiki_tpu.data import generate_tabular_dataset
    from rafiki_tpu.model import test_model_class

    with tempfile.TemporaryDirectory() as d:
        train_p, val_p = f"{d}/train.npz", f"{d}/val.npz"
        generate_tabular_dataset(train_p, 1024, seed=0)
        ds = generate_tabular_dataset(val_p, 256, seed=1)
        preds = test_model_class(
            SklearnDecisionTree, TaskType.TABULAR_CLASSIFICATION,
            train_p, val_p, queries=[ds.features[0]])
        print("probs:", [round(p, 3) for p in preds[0]])
