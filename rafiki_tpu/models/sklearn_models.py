"""Tabular-classification templates (SURVEY.md §2 "Model zoo": the
reference ships sklearn decision-tree / xgboost tabular templates).

:class:`SklearnDecisionTree` fits ``sklearn.tree.DecisionTreeClassifier``
but serializes the fitted tree as plain numpy arrays (children/feature/
threshold/leaf-distribution) instead of pickles — the ParamStore transport
is msgpack'd arrays, and unpickling foreign blobs on workers is exactly
the attack surface the model-transport design avoids. Prediction walks
the exported arrays directly (vectorized numpy), so a loaded model does
not even need sklearn present.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

# NOTE: zoo templates use absolute imports — their module source is shipped
# to workers via serialize_model_class() and re-imported standalone.
from rafiki_tpu.constants import TaskType
from rafiki_tpu.data import load_tabular_dataset
from rafiki_tpu.model import (BaseModel, CategoricalKnob, FloatKnob,
                              IntegerKnob, KnobConfig, TrainContext)


class SklearnDecisionTree(BaseModel):
    """Decision-tree classifier over tabular features."""

    TASKS = (TaskType.TABULAR_CLASSIFICATION,)

    @staticmethod
    def get_knob_config() -> KnobConfig:
        return {
            "max_depth": IntegerKnob(2, 16),
            "min_samples_split": IntegerKnob(2, 32, is_exp=True),
            "min_impurity_decrease": FloatKnob(1e-6, 1e-1, is_exp=True),
            "criterion": CategoricalKnob(["gini", "entropy"]),
        }

    def __init__(self, **knobs: Any) -> None:
        super().__init__(**knobs)
        # exported tree arrays (see dump_parameters)
        self._tree: Optional[Dict[str, np.ndarray]] = None
        self._n_classes: int = 0

    # ---- contract ----
    def train(self, dataset_path: str,
              ctx: Optional[TrainContext] = None) -> None:
        from sklearn.tree import DecisionTreeClassifier

        ctx = ctx or TrainContext()
        ds = load_tabular_dataset(dataset_path)
        if ds.n_classes == 0:
            raise ValueError("SklearnDecisionTree is a classifier; "
                             "dataset is regression (n_classes=0)")
        clf = DecisionTreeClassifier(
            max_depth=int(self.knobs["max_depth"]),
            min_samples_split=int(self.knobs["min_samples_split"]),
            min_impurity_decrease=float(
                self.knobs["min_impurity_decrease"]),
            criterion=str(self.knobs["criterion"]), random_state=0)
        clf.fit(ds.features, ds.labels)
        t = clf.tree_
        # leaf value → class distribution (normalized counts)
        dist = t.value[:, 0, :].astype(np.float64)
        dist = dist / np.maximum(dist.sum(axis=1, keepdims=True), 1e-12)
        self._tree = {
            "children_left": t.children_left.astype(np.int32),
            "children_right": t.children_right.astype(np.int32),
            "feature": t.feature.astype(np.int32),
            "threshold": t.threshold.astype(np.float32),
            "dist": dist.astype(np.float32),
        }
        self._n_classes = int(ds.n_classes)
        ctx.logger.log(epoch=0, loss=float(1.0 - clf.score(ds.features,
                                                           ds.labels)))

    def _probs(self, x: np.ndarray) -> np.ndarray:
        assert self._tree is not None, "model is not trained/loaded"
        t = self._tree
        node = _walk_tree(x, t["children_left"], t["children_right"],
                          t["feature"], t["threshold"])
        return t["dist"][node]

    def evaluate(self, dataset_path: str) -> float:
        ds = load_tabular_dataset(dataset_path)
        probs = self._probs(ds.features)
        return float(np.mean(np.argmax(probs, -1) == ds.labels))

    def predict(self, queries: Sequence[Any]) -> List[Any]:
        x = np.asarray([np.asarray(q, np.float32).ravel()
                        for q in queries], np.float32)
        return [p.tolist() for p in self._probs(x)]

    def dump_parameters(self) -> Dict[str, Any]:
        assert self._tree is not None, "model is not trained"
        return {**self._tree, "meta": {"n_classes": self._n_classes}}

    def load_parameters(self, params: Dict[str, Any]) -> None:
        self._n_classes = int(params["meta"]["n_classes"])
        self._tree = {k: np.asarray(params[k]) for k in
                      ("children_left", "children_right", "feature",
                       "threshold", "dist")}


def _walk_tree(x: np.ndarray, left: np.ndarray, right: np.ndarray,
               feature: np.ndarray, threshold: np.ndarray,
               rows: Optional[np.ndarray] = None) -> np.ndarray:
    """Vectorized leaf lookup shared by the DT and GBDT templates.

    ``rows`` lets hot callers (GBDT sums hundreds of trees per batch)
    pass one precomputed ``np.arange(len(x))``.
    """
    node = np.zeros(len(x), np.int32)
    if rows is None:
        rows = np.arange(len(x))
    for _ in range(64):  # > max tree depth
        feat = feature[node]
        leaf = feat < 0
        if leaf.all():
            break
        go_left = x[rows, np.maximum(feat, 0)] <= threshold[node]
        nxt = np.where(go_left, left[node], right[node])
        node = np.where(leaf, node, nxt).astype(np.int32)
    return node


class SklearnGBDT(BaseModel):
    """Gradient-boosted decision trees over tabular features — the
    xgboost-equivalent template (SURVEY.md §2 "Model zoo": the reference
    ships an xgboost tabular template).

    Fits ``sklearn.ensemble.GradientBoostingClassifier`` but, like
    :class:`SklearnDecisionTree`, exports the fitted ensemble as plain
    numpy arrays (per-tree structure + leaf values + class priors)
    rather than pickles; prediction reimplements the staged-additive
    raw-score accumulation + softmax/sigmoid link over those arrays, so
    loaded models never unpickle foreign blobs and don't need sklearn.
    """

    TASKS = (TaskType.TABULAR_CLASSIFICATION,)

    @staticmethod
    def get_knob_config() -> KnobConfig:
        return {
            "n_estimators": IntegerKnob(10, 200, is_exp=True),
            "learning_rate_gb": FloatKnob(0.01, 0.5, is_exp=True),
            "max_depth": IntegerKnob(2, 6),
            "subsample": FloatKnob(0.5, 1.0),
        }

    def __init__(self, **knobs: Any) -> None:
        super().__init__(**knobs)
        self._blob: Optional[Dict[str, Any]] = None

    # ---- contract ----
    def train(self, dataset_path: str,
              ctx: Optional[TrainContext] = None) -> None:
        from sklearn.ensemble import GradientBoostingClassifier

        ctx = ctx or TrainContext()
        ds = load_tabular_dataset(dataset_path)
        if ds.n_classes == 0:
            raise ValueError("SklearnGBDT is a classifier; dataset is "
                             "regression (n_classes=0)")
        clf = GradientBoostingClassifier(
            n_estimators=int(self.knobs["n_estimators"]),
            learning_rate=float(self.knobs["learning_rate_gb"]),
            max_depth=int(self.knobs["max_depth"]),
            subsample=float(self.knobs["subsample"]), random_state=0)
        clf.fit(ds.features, ds.labels)
        # export: estimators_ is (n_stages, K) DecisionTreeRegressors
        # (K=1 binary); raw score_k(x) = prior_k + lr * Σ_s tree_sk(x)
        n_stages, k = clf.estimators_.shape
        trees = []
        for s in range(n_stages):
            for c in range(k):
                t = clf.estimators_[s, c].tree_
                trees.append({
                    "left": t.children_left.astype(np.int32),
                    "right": t.children_right.astype(np.int32),
                    "feature": t.feature.astype(np.int32),
                    "threshold": t.threshold.astype(np.float32),
                    "value": t.value[:, 0, 0].astype(np.float32),
                })
        # baseline raw scores from the PUBLIC init_ estimator (the
        # private _raw_predict_init has no API stability): sklearn's
        # default 'log-odds' init is log(p/(1-p)) for binary and
        # log(prior_k) for multiclass
        p0 = np.clip(clf.init_.predict_proba(ds.features[:1])[0],
                     1e-12, 1 - 1e-12)
        if k == 1:
            raw0 = np.asarray([np.log(p0[1] / (1.0 - p0[1]))])
        else:
            raw0 = np.log(p0)
        self._blob = {
            "trees": trees, "n_stages": n_stages, "k": k,
            "lr": float(clf.learning_rate),
            "prior": np.asarray(raw0, np.float32),
            "classes": clf.classes_.astype(np.int64),
            "n_classes": int(ds.n_classes),
        }
        ctx.logger.log(epoch=0, loss=float(1.0 - clf.score(ds.features,
                                                           ds.labels)))

    def _probs(self, x: np.ndarray) -> np.ndarray:
        assert self._blob is not None, "model is not trained/loaded"
        b = self._blob
        n_stages, k, lr = int(b["n_stages"]), int(b["k"]), float(b["lr"])
        raw = np.tile(np.asarray(b["prior"], np.float64), (len(x), 1))
        rows = np.arange(len(x))
        for s in range(n_stages):
            for c in range(k):
                t = b["trees"][s * k + c]
                node = _walk_tree(x, np.asarray(t["left"]),
                                  np.asarray(t["right"]),
                                  np.asarray(t["feature"]),
                                  np.asarray(t["threshold"]), rows=rows)
                raw[:, c] += lr * np.asarray(t["value"], np.float64)[node]
        if k == 1:  # binary: sigmoid link over the single raw column
            p1 = 1.0 / (1.0 + np.exp(-raw[:, 0]))
            local = np.stack([1.0 - p1, p1], axis=1)
        else:  # multiclass: softmax link
            raw -= raw.max(axis=1, keepdims=True)
            e = np.exp(raw)
            local = e / e.sum(axis=1, keepdims=True)
        # scatter back onto the full label space (classes_ ⊆ labels)
        probs = np.zeros((len(x), int(b["n_classes"])), np.float64)
        for i, cls in enumerate(np.asarray(b["classes"])):
            probs[:, int(cls)] = local[:, i]
        return probs

    def evaluate(self, dataset_path: str) -> float:
        ds = load_tabular_dataset(dataset_path)
        probs = self._probs(ds.features)
        return float(np.mean(np.argmax(probs, -1) == ds.labels))

    def predict(self, queries: Sequence[Any]) -> List[Any]:
        x = np.asarray([np.asarray(q, np.float32).ravel()
                        for q in queries], np.float32)
        return [p.tolist() for p in self._probs(x)]

    def dump_parameters(self) -> Dict[str, Any]:
        assert self._blob is not None, "model is not trained"
        return self._blob

    def load_parameters(self, params: Dict[str, Any]) -> None:
        self._blob = params


class SklearnSVM(BaseModel):
    """Kernel SVM over tabular features (SURVEY.md §2 "Model zoo": the
    reference zoo's sklearn SVM template).

    Fits ``sklearn.svm.SVC`` and exports support vectors, dual
    coefficients, and intercepts as arrays; prediction reimplements the
    one-vs-one decision functions (libsvm layout: pair (i, j) combines
    class-i SVs weighted by ``dual_coef_[j-1]`` and class-j SVs by
    ``dual_coef_[i]``) with pairwise voting — vote shares stand in for
    probabilities so the predictor's ensemble averaging still works.
    """

    TASKS = (TaskType.TABULAR_CLASSIFICATION,)

    @staticmethod
    def get_knob_config() -> KnobConfig:
        return {
            "C": FloatKnob(0.01, 100.0, is_exp=True),
            "kernel": CategoricalKnob(["linear", "rbf"]),
            "gamma_scale": FloatKnob(0.1, 10.0, is_exp=True),
        }

    def __init__(self, **knobs: Any) -> None:
        super().__init__(**knobs)
        self._blob: Optional[Dict[str, Any]] = None

    def train(self, dataset_path: str,
              ctx: Optional[TrainContext] = None) -> None:
        from sklearn.svm import SVC

        ctx = ctx or TrainContext()
        ds = load_tabular_dataset(dataset_path)
        if ds.n_classes == 0:
            raise ValueError("SklearnSVM is a classifier; dataset is "
                             "regression (n_classes=0)")
        mean = ds.features.mean(axis=0)
        std = ds.features.std(axis=0) + 1e-6
        x = (ds.features - mean) / std
        # gamma: 'scale' default times a tunable multiplier
        base_gamma = 1.0 / (x.shape[1] * max(x.var(), 1e-12))
        gamma = base_gamma * float(self.knobs["gamma_scale"])
        clf = SVC(C=float(self.knobs["C"]),
                  kernel=str(self.knobs["kernel"]), gamma=gamma,
                  random_state=0)
        clf.fit(x, ds.labels)
        self._blob = {
            "sv": clf.support_vectors_.astype(np.float32),
            "dual_coef": clf.dual_coef_.astype(np.float32),
            "intercept": clf.intercept_.astype(np.float32),
            "n_support": clf.n_support_.astype(np.int32),
            "classes": clf.classes_.astype(np.int64),
            "mean": mean.astype(np.float32), "std": std.astype(np.float32),
            "meta": {"kernel": str(self.knobs["kernel"]),
                     "gamma": float(gamma),
                     "n_classes": int(ds.n_classes)},
        }
        ctx.logger.log(epoch=0, loss=float(1.0 - clf.score(x, ds.labels)))

    def _kernel(self, x: np.ndarray, sv: np.ndarray) -> np.ndarray:
        if self._blob["meta"]["kernel"] == "linear":
            return x @ sv.T
        gamma = float(self._blob["meta"]["gamma"])
        d2 = (np.sum(x * x, 1)[:, None] + np.sum(sv * sv, 1)[None, :]
              - 2.0 * (x @ sv.T))
        return np.exp(-gamma * np.maximum(d2, 0.0))

    def _probs(self, x: np.ndarray) -> np.ndarray:
        assert self._blob is not None, "model is not trained/loaded"
        b = self._blob
        x = (x - np.asarray(b["mean"])) / np.asarray(b["std"])
        km = self._kernel(np.asarray(x, np.float64),
                          np.asarray(b["sv"], np.float64))
        n_support = np.asarray(b["n_support"])
        classes = np.asarray(b["classes"])
        dual = np.asarray(b["dual_coef"], np.float64)
        intercept = np.asarray(b["intercept"], np.float64)
        k = len(classes)
        starts = np.concatenate([[0], np.cumsum(n_support)])
        votes = np.zeros((len(x), k), np.float64)
        p = 0
        for i in range(k):
            for j in range(i + 1, k):
                si, ei = starts[i], starts[i + 1]
                sj, ej = starts[j], starts[j + 1]
                dec = (km[:, si:ei] @ dual[j - 1, si:ei]
                       + km[:, sj:ej] @ dual[i, sj:ej] + intercept[p])
                votes[:, i] += dec > 0
                votes[:, j] += dec <= 0
                p += 1
        if k == 1:  # degenerate single-class fit
            votes[:, 0] = 1.0
        share = votes / np.maximum(votes.sum(axis=1, keepdims=True), 1e-12)
        probs = np.zeros((len(x), int(b["meta"]["n_classes"])), np.float64)
        for i, cls in enumerate(classes):
            probs[:, int(cls)] = share[:, i]
        return probs

    def evaluate(self, dataset_path: str) -> float:
        ds = load_tabular_dataset(dataset_path)
        probs = self._probs(np.asarray(ds.features, np.float64))
        return float(np.mean(np.argmax(probs, -1) == ds.labels))

    def predict(self, queries: Sequence[Any]) -> List[Any]:
        x = np.asarray([np.asarray(q, np.float64).ravel()
                        for q in queries], np.float64)
        return [p.tolist() for p in self._probs(x)]

    def dump_parameters(self) -> Dict[str, Any]:
        assert self._blob is not None, "model is not trained"
        return self._blob

    def load_parameters(self, params: Dict[str, Any]) -> None:
        self._blob = params


if __name__ == "__main__":  # reference-style self-test block
    import tempfile

    from rafiki_tpu.data import generate_tabular_dataset
    from rafiki_tpu.model import test_model_class

    with tempfile.TemporaryDirectory() as d:
        train_p, val_p = f"{d}/train.npz", f"{d}/val.npz"
        generate_tabular_dataset(train_p, 1024, seed=0)
        ds = generate_tabular_dataset(val_p, 256, seed=1)
        for cls in (SklearnDecisionTree, SklearnGBDT, SklearnSVM):
            preds = test_model_class(
                cls, TaskType.TABULAR_CLASSIFICATION,
                train_p, val_p, queries=[ds.features[0]])
            print(cls.__name__, "probs:",
                  [round(p, 3) for p in preds[0]])
