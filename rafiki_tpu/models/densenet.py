"""DenseNet-style CNN family — dense connectivity via channel concat.

Parity target: SURVEY.md §2 "Model zoo" ("TF VGG/DenseNet-style CNNs").
DenseNet-BC shape: dense blocks where every layer consumes the concat of
ALL previous feature maps (growth rate k per layer), 1×1 bottlenecks
(4k) before each 3×3, and compression-0.5 transitions (1×1 conv +
2×2 avg-pool) between blocks. TPU notes: the concats are pure layout —
XLA fuses them into the conv input reads — and convs lower straight
onto the MXU; bf16 compute with f32 params/BN stats like the other
image families; global-average-pool head; DP over the trial sub-mesh.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Sequence

import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from rafiki_tpu.constants import TaskType
from rafiki_tpu.model import (CategoricalKnob, FixedKnob, FloatKnob,
                              KnobConfig, PolicyKnob)
from rafiki_tpu.models._cnn_base import BatchNormCNNTemplate

#: layers per dense block
VARIANTS: Dict[str, Sequence[int]] = {
    "densenet-s": (2, 4, 4),
    "densenet-m": (4, 8, 8),
}


class _DenseLayer(nn.Module):
    growth: int
    dtype: Any

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool) -> jnp.ndarray:
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=self.dtype)
        # BC bottleneck: BN-relu-1x1(4k) then BN-relu-3x3(k)
        y = nn.relu(norm()(x))
        y = nn.Conv(4 * self.growth, (1, 1), use_bias=False,
                    dtype=self.dtype)(y)
        y = nn.relu(norm()(y))
        y = nn.Conv(self.growth, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(y)
        return jnp.concatenate([x, y], axis=-1)  # dense connectivity


class DenseNet(nn.Module):
    """Dense blocks + compression transitions over (B, H, W, C)."""

    block_sizes: Sequence[int]
    growth: int
    n_classes: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = nn.Conv(2 * self.growth, (3, 3), padding="SAME",
                    use_bias=False, dtype=self.dtype, name="stem")(x)
        for b, n_layers in enumerate(self.block_sizes):
            for _ in range(n_layers):
                x = _DenseLayer(self.growth, self.dtype)(x, train)
            if b < len(self.block_sizes) - 1:
                # transition: BN-relu, 1x1 compression 0.5, 2x2 avg-pool
                x = nn.relu(norm()(x))
                x = nn.Conv(max(self.growth, x.shape[-1] // 2), (1, 1),
                            use_bias=False, dtype=self.dtype)(x)
                if min(x.shape[1], x.shape[2]) >= 2:
                    x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(norm()(x))
        x = jnp.mean(x, axis=(1, 2))  # GAP head
        return nn.Dense(self.n_classes, dtype=jnp.float32,
                        name="head")(x.astype(jnp.float32))


class DenseNetClassifier(BatchNormCNNTemplate):
    """DenseNet template: image classification, DP over the trial
    sub-mesh, SGD-momentum + cosine (shared BatchNorm-CNN recipe —
    ``models/_cnn_base.py``)."""

    @staticmethod
    def get_knob_config() -> KnobConfig:
        return {
            "max_epochs": FixedKnob(5),
            "variant": CategoricalKnob(list(VARIANTS),
                                       shape_relevant=True),
            "growth": CategoricalKnob([8, 12, 24], shape_relevant=True),
            "learning_rate": FloatKnob(1e-3, 1.0, is_exp=True),
            "weight_decay": FloatKnob(1e-5, 1e-2, is_exp=True),
            "batch_size": CategoricalKnob([32, 64, 128, 256],
                                          shape_relevant=True),
            "bf16": CategoricalKnob([True, False]),
            "quick_train": PolicyKnob("QUICK_TRAIN"),
            "share_params": PolicyKnob("SHARE_PARAMS"),
        }

    def _module(self) -> DenseNet:
        assert self._n_classes is not None
        dtype = jnp.bfloat16 if self.knobs.get("bf16", True) else jnp.float32
        return DenseNet(block_sizes=VARIANTS[str(self.knobs["variant"])],
                        growth=int(self.knobs["growth"]),
                        n_classes=int(self._n_classes), dtype=dtype)


if __name__ == "__main__":  # reference-style self-test block
    import tempfile

    from rafiki_tpu.utils.platform import apply_platform_env

    apply_platform_env()  # honor RAFIKI_JAX_PLATFORM=cpu for dev runs

    from rafiki_tpu.data import generate_image_classification_dataset
    from rafiki_tpu.model import test_model_class

    with tempfile.TemporaryDirectory() as d:
        train_p = f"{d}/train.npz"
        val_p = f"{d}/val.npz"
        generate_image_classification_dataset(train_p, 256, seed=0)
        ds = generate_image_classification_dataset(val_p, 64, seed=1)
        preds = test_model_class(
            DenseNetClassifier, TaskType.IMAGE_CLASSIFICATION, train_p,
            val_p, queries=[ds.images[0]],
            knobs={"variant": "densenet-s", "growth": 12,
                   "batch_size": 32, "max_epochs": 5, "learning_rate": 0.05,
                   "weight_decay": 1e-4, "bf16": False,
                   "quick_train": False, "share_params": False})
        print("prediction:", int(np.argmax(preds[0])))
