"""ViT — the north-star model family (BASELINE.md config #3).

The flax module runs its two hot ops through the Pallas kernels
(``rafiki_tpu.ops``): patch embedding as the fused MXU matmul and
attention as flash attention with online softmax. The ``ViTBase16`` template
wraps it in the model contract with data-parallel training over the
trial's TPU sub-mesh (gradients all-reduced by XLA via NamedSharding —
SURVEY.md §2.2 "data-parallel over ICI").
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from rafiki_tpu.constants import TaskType
from rafiki_tpu.data import batch_iterator, \
    load_image_classification_dataset
from rafiki_tpu.model import (BaseModel, CategoricalKnob, FixedKnob,
                              FloatKnob, IntegerKnob, KnobConfig, PolicyKnob,
                              TrainContext, bucketed_forward, conform_images,
                              same_tree_shapes, train_epoch)
from rafiki_tpu.ops.attention import flash_attention
from rafiki_tpu.ops.patch_embed import patch_embed
from rafiki_tpu.parallel.sharding import (batch_sharding, make_mesh,
                                          replicated)


class _Attention(nn.Module):
    n_heads: int
    dtype: Any = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        b, s, d = x.shape
        dh = d // self.n_heads
        qkv = nn.Dense(3 * d, dtype=self.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(b, s, self.n_heads, dh).transpose(0, 2, 1, 3)

        o = flash_attention(heads(q), heads(k), heads(v))
        o = o.transpose(0, 2, 1, 3).reshape(b, s, d)
        return nn.Dense(d, dtype=self.dtype, name="proj")(o)


class _Block(nn.Module):
    n_heads: int
    mlp_dim: int
    dtype: Any = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        # LayerNorms reduce in f32 (dtype=None) for stability; the matmuls
        # — where the MXU time is — run in ``dtype`` (bf16 on TPU: f32
        # matmuls lower to multi-pass bf16 on the MXU at ~1/3 the rate)
        x = x + _Attention(self.n_heads, self.dtype,
                           name="attn")(nn.LayerNorm()(x))
        y = nn.LayerNorm()(x)
        y = nn.Dense(self.mlp_dim, dtype=self.dtype)(y)
        y = nn.gelu(y)
        y = nn.Dense(x.shape[-1], dtype=self.dtype)(y)
        return x + y


class _PatchEmbed(nn.Module):
    """Pallas-fused patch projection as a flax layer."""

    patch_size: int
    hidden_dim: int
    dtype: Any = None

    @nn.compact
    def __call__(self, images: jnp.ndarray) -> jnp.ndarray:
        p = self.patch_size
        c = images.shape[-1]
        w = self.param("kernel", nn.initializers.lecun_normal(),
                       (p * p * c, self.hidden_dim))
        b = self.param("bias", nn.initializers.zeros, (self.hidden_dim,))
        if self.dtype is not None:
            images, w, b = (images.astype(self.dtype), w.astype(self.dtype),
                            b.astype(self.dtype))
        return patch_embed(images, w, b, p)


class ViT(nn.Module):
    """Vision Transformer over (B, H, W, C) images.

    ViT-B/16 = patch_size=16, hidden_dim=768, depth=12, n_heads=12,
    mlp_dim=3072.
    """

    patch_size: int = 16
    hidden_dim: int = 768
    depth: int = 12
    n_heads: int = 12
    mlp_dim: int = 3072
    n_classes: int = 1000
    # compute dtype for the matmul-heavy layers (params always f32).
    # None = promote (f32 compute); templates pass bf16 on TPU, where f32
    # matmuls cost ~3x on the MXU.
    dtype: Any = None
    # gradient checkpointing per transformer block: drop block-internal
    # activations in the forward and recompute them in the backward —
    # trades ~1/3 more FLOPs for O(depth) less activation HBM, buying
    # the larger train batches that raise MXU utilization. Identical
    # math (same params, same outputs, same grads).
    remat: bool = False

    @nn.compact
    def __call__(self, images: jnp.ndarray) -> jnp.ndarray:
        x = _PatchEmbed(self.patch_size, self.hidden_dim, self.dtype,
                        name="patch_embed")(images)
        b, n, d = x.shape
        cls = self.param("cls", nn.initializers.zeros, (1, 1, d))
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (b, 1, d)).astype(x.dtype), x], axis=1)
        pos = self.param("pos_embed",
                         nn.initializers.normal(0.02), (1, n + 1, d))
        x = x + pos.astype(x.dtype)
        block_cls = nn.remat(_Block) if self.remat else _Block
        for i in range(self.depth):
            x = block_cls(self.n_heads, self.mlp_dim, self.dtype,
                          name=f"block_{i}")(x)
        x = nn.LayerNorm(name="final_norm")(x)
        return nn.Dense(self.n_classes, name="head")(x[:, 0])


class ViTBase16(BaseModel):
    """ViT template: image classification with DP over the trial sub-mesh."""

    TASKS = (TaskType.IMAGE_CLASSIFICATION,)

    @staticmethod
    def get_knob_config() -> KnobConfig:
        return {
            "max_epochs": FixedKnob(5),
            "patch_size": CategoricalKnob([4, 7, 14, 16],
                                          shape_relevant=True),
            # every hidden_dim is divisible by every n_heads choice, so the
            # tuner's (hidden_dim, n_heads) point is exactly the model built
            # (no silent head-count remapping to pollute the search history)
            "hidden_dim": CategoricalKnob([96, 192, 384, 768],
                                          shape_relevant=True),
            "depth": IntegerKnob(2, 12, shape_relevant=True),
            "n_heads": CategoricalKnob([4, 8, 12], shape_relevant=True),
            "learning_rate": FloatKnob(1e-5, 1e-2, is_exp=True),
            "weight_decay": FloatKnob(1e-5, 1e-1, is_exp=True),
            "warmup_frac": FloatKnob(0.0, 0.3),
            "batch_size": CategoricalKnob([16, 32, 64, 128],
                                          shape_relevant=True),
            "bf16": CategoricalKnob([True, False]),
            # gradient checkpointing: bigger batches for ~1/3 extra
            # FLOPs — the knob the tuner flips when batch_size is HBM-
            # bound on TPU (identical math either way)
            "remat": FixedKnob(False),
            "quick_train": PolicyKnob("QUICK_TRAIN"),
            "share_params": PolicyKnob("SHARE_PARAMS"),
        }

    def __init__(self, **knobs: Any) -> None:
        super().__init__(**knobs)
        self._params: Optional[Any] = None
        self._n_classes: Optional[int] = None
        self._image_shape: Optional[Sequence[int]] = None
        self._fwd: Optional[Any] = None  # cached jitted forward
        #: input-normalization contract the ACTIVE params were trained
        #: under; fresh trains use v2, load_parameters adopts the
        #: checkpoint's version so old params keep serving correctly
        self._prep_version: int = 2

    # ---- internals ----
    def _module(self) -> ViT:
        k = self.knobs
        hd = int(k["hidden_dim"])
        heads = int(k["n_heads"])
        if hd % heads:
            raise ValueError(f"hidden_dim={hd} not divisible by "
                             f"n_heads={heads}")
        # compute dtype follows the bf16 knob: params stay f32, matmuls
        # run bf16 on the MXU (f32 would lower to ~3x-cost multi-pass)
        return ViT(patch_size=int(k["patch_size"]), hidden_dim=hd,
                   depth=int(k["depth"]), n_heads=heads,
                   mlp_dim=4 * hd, n_classes=int(self._n_classes),
                   dtype=self._dtype(),
                   remat=bool(k.get("remat", False)))

    def _prep(self, images: np.ndarray) -> np.ndarray:
        if self._prep_version == 1:
            # v1-checkpoint compatibility: params trained on [0, 1]
            # inputs must keep seeing [0, 1] at serving time
            x = images.astype(np.float32) / 255.0
        else:
            # center to [-1, 1]: with raw [0, 1] pixels the DC component
            # dominates every patch projection and a small ViT sits in a
            # uniform-logits plateau for its whole budget (measured:
            # chance accuracy at 15 epochs uncentered vs ~0.7 by epoch 8
            # centered)
            x = images.astype(np.float32) / 127.5 - 1.0
        if x.ndim == 3:
            x = x[..., None]
        # pos_embed is sized to the train-time patch count: conform queries
        # of other resolutions to the trained shape first
        x = conform_images(x, self._image_shape)
        p = int(self.knobs["patch_size"])
        # pad H/W up to patch multiples (e.g. 28x28 with p=16 → 32x32)
        ph = (-x.shape[1]) % p
        pw = (-x.shape[2]) % p
        if ph or pw:
            x = np.pad(x, ((0, 0), (0, ph), (0, pw), (0, 0)))
        return x

    def _dtype(self):
        return jnp.bfloat16 if self.knobs.get("bf16", True) else jnp.float32

    # ---- contract ----
    def train(self, dataset_path: str,
              ctx: Optional[TrainContext] = None) -> None:
        ctx = ctx or TrainContext()
        ds = load_image_classification_dataset(dataset_path)
        self._n_classes = ds.n_classes
        self._image_shape = ds.image_shape
        x = self._prep(ds.images)
        y = ds.labels

        module = self._module()
        devices = ctx.devices or jax.local_devices()
        mesh = make_mesh(devices)
        b_shard = batch_sharding(mesh)
        r_shard = replicated(mesh)

        batch_size = int(self.knobs["batch_size"])
        # static shapes: batch must divide the data axis
        n_data = len(devices)
        batch_size = max(n_data, batch_size - batch_size % n_data)
        dtype = self._dtype()

        if self._params is None:
            params = module.init(
                jax.random.PRNGKey(0),
                jnp.zeros((1, *x.shape[1:]), dtype))["params"]
        else:
            params = self._params
        if ctx.shared_params is not None and self.knobs.get("share_params") \
                and hasattr(ctx.shared_params, "get"):
            shared = ctx.shared_params.get("params")
            donor_prep = int(ctx.shared_params.get("meta", {})
                             .get("prep_version", 1))
            if shared is not None and donor_prep != self._prep_version:
                # input-contract mismatch: weights trained on v1 [0,1]
                # inputs warm-starting a v2 [-1,1] train would begin at
                # worse-than-random loss AND get re-stamped v2 on dump,
                # erasing the evidence — cold start is strictly better
                import logging

                logging.getLogger(__name__).warning(
                    "skipping warm start: donor checkpoint prep_version="
                    "%d != this train's %d (input normalization "
                    "contracts differ)", donor_prep, self._prep_version)
            elif shared is not None and same_tree_shapes(params, shared):
                params = jax.tree_util.tree_map(jnp.asarray, shared)

        epochs = max(1, round(int(self.knobs["max_epochs"])
                              * float(ctx.budget_scale)))
        if self.knobs.get("quick_train"):
            epochs = min(epochs, 2)

        # linear warmup + cosine decay (the standard ViT recipe): without
        # warmup, small ViTs sit in a uniform-logits plateau for most of a
        # short budget; with it they converge in a handful of epochs
        lr = float(self.knobs["learning_rate"])
        steps_per_epoch = max(1, (len(x) + batch_size - 1) // batch_size)
        total_steps = epochs * steps_per_epoch
        warmup = int(total_steps * float(self.knobs.get("warmup_frac", 0.1)))
        schedule = optax.warmup_cosine_decay_schedule(
            0.0, lr, max(warmup, 1), max(total_steps, 2))
        tx = optax.adamw(schedule,
                         weight_decay=float(self.knobs["weight_decay"]))
        params = jax.device_put(params, r_shard)
        opt_state = jax.device_put(tx.init(params), r_shard)

        # donate params/opt_state: the optimizer update writes in place
        # instead of copying the full trees every step (HBM traffic)
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def train_step(params, opt_state, xb, yb, mask):
            def loss_fn(p):
                logits = module.apply({"params": p}, xb.astype(dtype))
                losses = optax.softmax_cross_entropy_with_integer_labels(
                    logits.astype(jnp.float32), yb)
                return jnp.sum(losses * mask) / jnp.maximum(
                    jnp.sum(mask), 1.0)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        def step(state, b):
            params, opt_state = state
            params, opt_state, loss = train_step(params, opt_state,
                                                 b["x"], b["y"], b["m"])
            return (params, opt_state), loss

        ctx.logger.define_plot("Loss over epochs", ["loss"], x_axis="epoch")
        # donation below invalidates buffers that may alias self._params
        # (warm start / re-train): drop the stale reference so a failure
        # mid-train can't leave the model holding deleted arrays
        self._params = None
        with mesh:
            for epoch in range(epochs):
                (params, opt_state), mean_loss = train_epoch(
                    step, (params, opt_state),
                    ({"x": b["x"], "y": b["y"],
                      "m": b["mask"].astype(np.float32)}
                     for b in batch_iterator({"x": x, "y": y}, batch_size,
                                             seed=epoch)),
                    sharding=b_shard)
                ctx.logger.log(epoch=epoch, loss=mean_loss)
                if ctx.checkpoint is not None:
                    # preemption safety: worker throttles + persists
                    self._params = params
                    ctx.checkpoint(self.dump_parameters,
                                   frac_done=(epoch + 1) / epochs)
                if ctx.should_continue is not None and \
                        not ctx.should_continue(epoch, -mean_loss):
                    break
        self._params = params
        self._fwd = None  # new params/arch → rebuild the cached jit

    def evaluate(self, dataset_path: str) -> float:
        ds = load_image_classification_dataset(dataset_path)
        probs = self._predict_probs(self._prep(ds.images))
        return float(np.mean(np.argmax(probs, -1) == ds.labels))

    def predict(self, queries: Sequence[Any]) -> List[Any]:
        x = self._prep(np.stack([np.asarray(q) for q in queries]))
        return [p.tolist() for p in self._predict_probs(x)]

    def _predict_probs(self, x: np.ndarray) -> np.ndarray:
        assert self._params is not None, "model is not trained/loaded"
        if self._fwd is None:  # cache: jit memoizes by function identity
            module = self._module()
            dtype = self._dtype()

            @jax.jit
            def forward(params, xb):
                logits = module.apply({"params": params}, xb.astype(dtype))
                return jax.nn.softmax(logits.astype(jnp.float32), -1)

            self._fwd = forward
        return bucketed_forward(self._fwd, self._params, x, bucket=64)

    def warmup(self) -> None:
        """Compile the serving forward (one zero query through the same
        bucketed path predict() uses) before traffic arrives."""
        if self._params is None or self._image_shape is None:
            return
        shape = list(self._image_shape)
        self.predict([np.zeros(shape, np.uint8)])

    def dump_parameters(self) -> Dict[str, Any]:
        assert self._params is not None, "model is not trained"
        return {
            "params": jax.tree_util.tree_map(np.asarray, self._params),
            "meta": {"n_classes": self._n_classes,
                     "image_shape": list(self._image_shape or []),
                     # input normalization the params were trained under
                     # (1 = [0,1], 2 = centered [-1,1]); a re-dumped v1
                     # load stays v1 — the version follows the weights
                     "prep_version": self._prep_version},
        }

    def load_parameters(self, params: Dict[str, Any]) -> None:
        self._n_classes = int(params["meta"]["n_classes"])
        self._image_shape = list(params["meta"]["image_shape"])
        # honor the checkpoint's input contract: _prep applies the
        # normalization these weights were trained under, so v1
        # checkpoints serve at full quality instead of silently seeing
        # shifted inputs (ADVICE r3)
        self._prep_version = int(params["meta"].get("prep_version", 1))
        self._params = jax.tree_util.tree_map(jnp.asarray, params["params"])
        self._fwd = None


if __name__ == "__main__":  # reference-style self-test block
    import tempfile

    from rafiki_tpu.utils.platform import apply_platform_env

    apply_platform_env()  # honor RAFIKI_JAX_PLATFORM=cpu for dev runs

    from rafiki_tpu.data import generate_image_classification_dataset
    from rafiki_tpu.model import test_model_class

    with tempfile.TemporaryDirectory() as d:
        train_p = f"{d}/train.npz"
        val_p = f"{d}/val.npz"
        generate_image_classification_dataset(train_p, 256, seed=0)
        ds = generate_image_classification_dataset(val_p, 64, seed=1)
        preds = test_model_class(
            ViTBase16, TaskType.IMAGE_CLASSIFICATION, train_p, val_p,
            queries=[ds.images[0]],
            knobs={"patch_size": 4, "hidden_dim": 96, "depth": 2,
                   "n_heads": 4, "batch_size": 32, "max_epochs": 5,
                   "learning_rate": 1e-3, "weight_decay": 1e-4,
                   "warmup_frac": 0.1, "bf16": False,
                   "quick_train": False, "share_params": False})
        print("prediction:", int(np.argmax(preds[0])))
