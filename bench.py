"""Headline benchmark — prints ONE JSON line for the driver.

Metric: ViT-B/16 training throughput (samples/sec) on the available
accelerator. The reference published no numbers (BASELINE.md:
``"published": {}``), so ``vs_baseline`` compares against the last
locally recorded run in ``.bench_history.json`` (ratio >1 = faster),
else 1.0.

Architecture (BENCH r01 was rc=1, r02 rc=124 — both driver-window
failures): a PARENT process that never imports jax owns the deadline;
ALL accelerator work runs in a CHILD that appends a JSON record per
completed stage to a scratch file. A hung backend init or compile can
block Python signal delivery inside a C call, so in-process alarms are
not a defense — the parent's ``subprocess`` timeout is. Whatever the
child managed before the deadline is what gets emitted, always as one
parseable line, always rc=0.

Stages (child, accelerator): backend probe → ViT-B/16 bs=32 step timing
→ varlen Pallas kernel check (interpret=False fwd+bwd — the full-batch
kernels are already proven by the ViT stage itself, which runs Mosaic
flash attention + patch embed) → ViT-B/16 bs=128 → bs=256. Later
stages are skipped when the child's budget runs low (the headroom
floor scales with batch size) and a failing batch (e.g. OOM at 256 on
a smaller core) records an error stage without killing the sweep; the
best completed throughput wins. ``tpu_kernels_ok`` in the emitted line = ViT-on-TPU ran AND the
varlen check passed (VERDICT.md round-2 item #5).

Serving-side metrics (predictor req/s + p50, advisor trials/hour —
SURVEY.md §6) live in ``bench_extra.py``.

Deadline: ``RAFIKI_BENCH_DEADLINE`` seconds (default 280 — r02's driver
window outlived the old probe's 315s budget, so the window is assumed
≥300s; the parent emits and exits rc=0 well before that).
"""

from __future__ import annotations

import json
import os
import sys
import time

from _bench_common import (collect_errors, record as _record,
                           run_with_cpu_fallback)

DEADLINE = float(os.environ.get("RAFIKI_BENCH_DEADLINE", "280"))
METRIC = "vit_b16_train_throughput"


def _child(out_path: str, budget: float) -> None:
    """Run stages, appending a record per completed stage. May hang or
    die at any point — the parent only trusts what reached the file."""
    t_start = time.monotonic()

    def left() -> float:
        return budget - (time.monotonic() - t_start)

    from rafiki_tpu.utils.platform import apply_platform_env

    apply_platform_env()  # parent sets RAFIKI_JAX_PLATFORM=cpu on fallback

    import jax
    import jax.numpy as jnp

    backend = jax.default_backend()
    x = jnp.ones((256, 256), jnp.bfloat16)
    (x @ x).block_until_ready()
    _record(out_path, {"stage": "probe", "backend": backend})

    on_accel = backend not in ("cpu",)

    import optax

    from rafiki_tpu.models.vit import ViT

    if on_accel:
        # bf16 compute (params f32): f32 matmuls lower to multi-pass bf16
        # on the MXU at ~3x the cost — never benchmark the promoted path
        module = ViT(patch_size=16, hidden_dim=768, depth=12, n_heads=12,
                     mlp_dim=3072, n_classes=1000, dtype=jnp.bfloat16)
        # 256 rides only when budget remains (the per-stage gate below):
        # bf16 halved activation memory, so the throughput knee may sit
        # past 128 — the sweep's bs=64 rows already showed bf16+XLA
        # leading, and larger batches amortize dispatch further
        img, batches, metric = 224, (32, 128, 256), METRIC
    else:  # fallback: prove the path end-to-end in seconds. A toy model
        # under its OWN metric name — never comparable to B/16 history.
        module = ViT(patch_size=8, hidden_dim=96, depth=2, n_heads=4,
                     mlp_dim=384, n_classes=10)
        img, batches, metric = 64, (8,), "vit_s64_cpu_train_throughput"

    tx = optax.adam(1e-3)
    params0 = module.init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, img, img, 3), jnp.bfloat16))["params"]

    import functools

    # donate params/opt_state: no copy of the 86M-param trees per step
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, xb, yb):
        def loss_fn(p):
            logits = module.apply({"params": p}, xb)
            return jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(
                    logits.astype(jnp.float32), yb))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    def time_batch(bs: int) -> float:
        xb = jnp.zeros((bs, img, img, 3), jnp.bfloat16)
        yb = jnp.zeros((bs,), jnp.int32)
        params = jax.tree_util.tree_map(jnp.copy, params0)
        opt_state = tx.init(params)
        params, opt_state, loss = step(params, opt_state, xb, yb)
        float(loss)  # sync: drains remote-execution backends too
        iters = 20 if on_accel else 3
        t0 = time.perf_counter()
        for _ in range(iters):
            params, opt_state, loss = step(params, opt_state, xb, yb)
        float(loss)
        return bs * iters / (time.perf_counter() - t0)

    # stage: bs=32 first — the known-good compile, guarantees a number
    v = time_batch(batches[0])
    _record(out_path, {"stage": f"vit{batches[0]}", "value": v,
                       "batch": batches[0], "metric": metric})

    # stage: varlen Pallas kernels with real Mosaic lowering (TPU only).
    # The ViT stage above already ran the full-batch flash-attention
    # fwd+bwd and the patch-embed kernel on silicon; this covers the
    # scalar-prefetch varlen path.
    if backend == "tpu" and left() > 20:
        try:
            from rafiki_tpu.ops.attention import flash_attention

            q = jax.random.normal(jax.random.PRNGKey(1), (2, 2, 200, 64),
                                  jnp.bfloat16)
            lens = jnp.asarray([200, 77], jnp.int32)

            def loss_fn(q):
                o = flash_attention(q, q, q, kv_lens=lens, causal=True,
                                    interpret=False)
                return jnp.sum(o.astype(jnp.float32) ** 2)

            val, g = jax.jit(jax.value_and_grad(loss_fn))(q)
            ok = bool(jnp.isfinite(val)) and bool(
                jnp.all(jnp.isfinite(g.astype(jnp.float32))))
            _record(out_path, {"stage": "kernels", "tpu_kernels_ok": ok})
        except Exception as e:  # noqa: BLE001 — report, don't die
            _record(out_path, {"stage": "kernels", "tpu_kernels_ok": False,
                               "error": repr(e)[:200]})

    # stage: bigger batches while budget remains (compile ~30-60s each;
    # the headroom floor scales with batch — step time grows ~linearly)
    for bs in batches[1:]:
        if left() < 60 + bs // 8:
            break
        try:
            v = time_batch(bs)
        except Exception as e:  # noqa: BLE001 — e.g. OOM at the
            # largest batch on a smaller core: keep the failure visible
            # and keep sweeping/finishing instead of dying mid-stage
            _record(out_path, {"stage": f"vit{bs}_error",
                               "error": repr(e)[:200]})
            continue
        _record(out_path, {"stage": f"vit{bs}", "value": v, "batch": bs,
                           "metric": metric})

    _record(out_path, {"stage": "done"})


# ---------------------------------------------------------------- parent

def _emit(metric: str, value: float, batch: int, backend: str, kernels_ok,
          stages) -> None:
    hist_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".bench_history.json")
    vs = 1.0
    hist = {}
    try:
        with open(hist_path) as f:
            loaded = json.load(f)
        if isinstance(loaded, dict):
            hist = loaded
        prev = hist.get(metric)
        if isinstance(prev, (int, float)) and prev > 0:
            vs = value / prev
    except (OSError, ValueError):
        pass
    if backend == "tpu" and value > 0:
        hist[metric] = value
        try:
            with open(hist_path, "w") as f:
                json.dump(hist, f)
        except OSError:
            pass
    print(json.dumps({
        "metric": metric, "value": round(value, 2), "unit": "samples/sec",
        "vs_baseline": round(vs, 3), "backend": backend, "batch": batch,
        "tpu_kernels_ok": kernels_ok, "stages": stages,
    }))


def main() -> None:
    t0 = time.monotonic()
    out_path = os.path.abspath(f".bench_stages_{os.getpid()}.jsonl")

    def _no_throughput(records: list) -> bool:
        # rerun on CPU unless the accel child produced an actual number:
        # a hang can strike AFTER the probe (e.g. mid-compile — the r02
        # class), and a probe alone is not a benchmark
        return not any(r.get("stage", "").startswith("vit")
                       and "value" in r for r in records)

    # reserve ~70s upfront for the CPU-fallback child: if the accelerator
    # child hangs it consumes its whole budget and the fallback still has
    # to produce a labeled number before the deadline
    records, fallback_used = run_with_cpu_fallback(
        __file__, out_path, DEADLINE, time.monotonic, t0,
        fallback_reserve=70.0, need_rerun=_no_throughput)

    backend = next((r["backend"] for r in records
                    if r.get("stage") == "probe"), "none")
    kernels_ok = next((r["tpu_kernels_ok"] for r in records
                       if r.get("stage") == "kernels"), None)
    vits = [r for r in records if r.get("stage", "").startswith("vit")
            and "value" in r]
    stages = [r.get("stage") for r in records]
    if vits:
        best = max(vits, key=lambda r: r["value"])
        label = "cpu-fallback" if fallback_used else backend
        _emit(best.get("metric", METRIC), best["value"],
              best.get("batch", 0), label, kernels_ok, stages)
    else:
        print(json.dumps({
            "metric": "bench_error", "value": 0.0, "unit": "samples/sec",
            "vs_baseline": 0.0, "backend": backend,
            "tpu_kernels_ok": kernels_ok, "stages": stages,
            "errors": collect_errors(records),
        }))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        try:
            _child(sys.argv[2], float(sys.argv[3]))
        except Exception as e:  # noqa: BLE001
            _record(sys.argv[2], {"stage": "child_error",
                                  "error": repr(e)[:300]})
            sys.exit(1)
        sys.exit(0)
    try:
        main()
    except Exception as e:  # noqa: BLE001 — a parseable failure record
        # beats rc!=0 with no metric (the r01 failure class)
        print(json.dumps({"metric": "bench_error", "value": 0.0,
                          "unit": "samples/sec", "vs_baseline": 0.0,
                          "backend": "none", "error": repr(e)[:300]}))
        sys.exit(0)
