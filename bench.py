"""Headline benchmark — prints ONE JSON line for the driver.

Metric: flagship-model training throughput (samples/sec) on the available
accelerator (one TPU chip under the driver; CPU locally). The reference
published no numbers (BASELINE.md: ``"published": {}``), so
``vs_baseline`` compares against the last locally recorded run in
``.bench_history.json`` when present (ratio >1 = faster), else 1.0.

Hardening (round-1 BENCH was rc=1): backend initialization is probed with
retry + backoff; if the accelerator never comes up the bench reruns itself
pinned to CPU and labels the result ``backend:cpu-fallback``. Any
unexpected error still emits a parseable JSON line and exits 0.

Extra metrics (predictor req/s, p50, advisor trials/hour — SURVEY.md §6)
live in ``bench_extra.py`` so this stays one line.
"""

from __future__ import annotations

import json
import os
import sys
import time

_CPU_FALLBACK_ENV = "RAFIKI_BENCH_CPU_FALLBACK"

# One matmul on the default backend; proves init AND execution both work.
_PROBE_SRC = ("import jax, jax.numpy as jnp; b = jax.default_backend(); "
              "x = jnp.ones((256, 256), jnp.bfloat16); "
              "(x @ x).block_until_ready(); print(b)")


def _probe_backend(tries: int = 2, probe_timeout: float = 150.0) -> str:
    """Return the working backend name, probing in a SUBPROCESS.

    The accelerator failure mode observed in this image is a *hang* during
    backend init (the axon TPU tunnel blocks forever), not an exception —
    an in-process try/except never returns (round-1 BENCH_r01 rc=1 /
    MULTICHIP rc=124 family). So the probe runs in a child with a hard
    timeout; only after it proves the backend alive does the parent
    initialize jax itself. On failure → labeled CPU fallback.
    """
    import subprocess

    if os.environ.get(_CPU_FALLBACK_ENV):
        return "cpu"
    last = ""
    for attempt in range(tries):
        try:
            out = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC], timeout=probe_timeout,
                capture_output=True, text=True)
            if out.returncode == 0 and out.stdout.strip():
                return out.stdout.strip().splitlines()[-1]
            last = (out.stderr or "")[-200:]
        except subprocess.TimeoutExpired:
            last = f"probe hang >{probe_timeout}s"
        time.sleep(5.0 * (attempt + 1))
    print(f"bench: accelerator probe failed ({last}); CPU fallback",
          file=sys.stderr)
    os.environ[_CPU_FALLBACK_ENV] = "1"
    return "cpu"


def _bench_train_throughput(backend: str):
    import jax
    import jax.numpy as jnp
    import optax

    on_accel = backend not in ("cpu",)
    try:
        from rafiki_tpu.models.vit import ViT

        module = ViT(patch_size=16, hidden_dim=768, depth=12, n_heads=12,
                     mlp_dim=3072, n_classes=1000)
        # bs=128 to saturate the chip (round-1 bs=32 left the MXU idle);
        # tiny on CPU so the fallback path still finishes.
        batch = 128 if on_accel else 4
        x = jnp.zeros((batch, 224, 224, 3), jnp.bfloat16)
        name = "vit_b16_train_throughput"
    except ImportError:
        from rafiki_tpu.models.mlp import _MLP

        module = _MLP(hidden_layer_count=3, hidden_layer_units=256,
                      n_classes=10)
        batch = 512
        x = jnp.zeros((batch, 28, 28, 1), jnp.float32)
        name = "mlp_train_throughput"

    y = jnp.zeros((batch,), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), x)["params"]
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, xb, yb):
        def loss_fn(p):
            logits = module.apply({"params": p}, xb)
            return jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(logits, yb))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # warmup / compile; float() forces a device sync (block_until_ready
    # alone does not drain remote-execution backends)
    params, opt_state, loss = step(params, opt_state, x, y)
    float(loss)

    iters = 20 if on_accel else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss = step(params, opt_state, x, y)
    float(loss)
    dt = time.perf_counter() - t0
    return name, batch * iters / dt


def _emit(name: str, value: float, backend: str) -> None:
    hist_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".bench_history.json")
    vs = 1.0
    try:
        with open(hist_path) as f:
            hist = json.load(f)
        prev = hist.get(name)
        if prev:
            vs = value / prev
    except (OSError, ValueError):
        hist = {}
    if backend != "cpu-fallback":  # fallback runs don't become the baseline
        hist[name] = value
        try:
            with open(hist_path, "w") as f:
                json.dump(hist, f)
        except OSError:
            pass
    print(json.dumps({"metric": name, "value": round(value, 2),
                      "unit": "samples/sec", "vs_baseline": round(vs, 3),
                      "backend": backend}))


def main() -> None:
    backend = _probe_backend()
    fallback = bool(os.environ.get(_CPU_FALLBACK_ENV))
    label = "cpu-fallback" if fallback else backend
    if fallback:
        # Pin BEFORE the first in-process jax backend init (sitecustomize
        # bakes the env default, so use jax.config too).
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    try:
        name, value = _bench_train_throughput(backend)
        _emit(name, value, label)
    except Exception as e:
        # Never hand the driver a traceback: a parseable failure record
        # beats rc=1 with no metric.
        print(json.dumps({"metric": "bench_error", "value": 0.0,
                          "unit": "samples/sec", "vs_baseline": 0.0,
                          "backend": label, "error": repr(e)[:300]}))


if __name__ == "__main__":
    main()
