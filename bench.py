"""Headline benchmark — prints ONE JSON line for the driver.

Metric: flagship-model training throughput (samples/sec) on the available
accelerator (one TPU chip under the driver; CPU locally). The reference
published no numbers (BASELINE.md: ``"published": {}``), so
``vs_baseline`` compares against the last locally recorded run in
``.bench_history.json`` when present (ratio >1 = faster), else 1.0.
"""

from __future__ import annotations

import json
import os
import time


def _bench_train_throughput():
    import jax
    import jax.numpy as jnp
    import optax

    try:
        from rafiki_tpu.models.vit import ViT

        module = ViT(patch_size=16, hidden_dim=768, depth=12, n_heads=12,
                     mlp_dim=3072, n_classes=1000)
        batch = 32 if jax.default_backend() != "cpu" else 4
        x = jnp.zeros((batch, 224, 224, 3), jnp.bfloat16)
        name = "vit_b16_train_throughput"
    except ImportError:
        from rafiki_tpu.models.mlp import _MLP

        module = _MLP(hidden_layer_count=3, hidden_layer_units=256,
                      n_classes=10)
        batch = 512
        x = jnp.zeros((batch, 28, 28, 1), jnp.float32)
        name = "mlp_train_throughput"

    y = jnp.zeros((batch,), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), x)["params"]
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, xb, yb):
        def loss_fn(p):
            logits = module.apply({"params": p}, xb)
            return jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(logits, yb))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # warmup / compile; float() forces a device sync (block_until_ready
    # alone does not drain remote-execution backends)
    params, opt_state, loss = step(params, opt_state, x, y)
    float(loss)

    iters = 20 if jax.default_backend() != "cpu" else 5
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss = step(params, opt_state, x, y)
    float(loss)
    dt = time.perf_counter() - t0
    return name, batch * iters / dt


def main() -> None:
    name, value = _bench_train_throughput()
    hist_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".bench_history.json")
    vs = 1.0
    try:
        with open(hist_path) as f:
            hist = json.load(f)
        prev = hist.get(name)
        if prev:
            vs = value / prev
    except (OSError, ValueError):
        hist = {}
    hist[name] = value
    try:
        with open(hist_path, "w") as f:
            json.dump(hist, f)
    except OSError:
        pass
    print(json.dumps({"metric": name, "value": round(value, 2),
                      "unit": "samples/sec", "vs_baseline": round(vs, 3)}))


if __name__ == "__main__":
    main()
