"""Shared harness for the deadline-bounded benches (bench.py,
bench_extra.py).

The pattern both use: a parent that never imports jax owns the clock;
accelerator work runs in a child that appends one JSON record per
completed stage to a scratch file (fsynced, parsed per-line so a
mid-write kill can't discard finished stages); if the accelerator child
produced no useful records, a CPU-pinned rerun spends the remaining
budget so the driver always gets a labeled number.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Callable, List, Optional


def record(out_path: str, rec: dict) -> None:
    """Append one stage record; fsync so the parent sees it even if the
    child is killed right after."""
    with open(out_path, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())


def read_records(out_path: str) -> List[dict]:
    """Per-line parse: a partial trailing line (child killed mid-write)
    must not discard completed, fsynced records before it."""
    records: List[dict] = []
    try:
        with open(out_path) as f:
            for line in f:
                try:
                    records.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return records


def run_child(script: str, out_path: str, budget: float,
              env: dict, extra_args: Optional[List[str]] = None,
              kill_on_timeout: bool = True) -> "subprocess.Popen":
    """Run ``script --child out_path <child_budget> [extra]`` with a hard
    wall-clock timeout; the child's own soft budget is a bit shorter so
    it can skip late stages instead of being killed mid-stage.

    ``kill_on_timeout=False`` ABANDONS an overdue child instead of
    killing it: a child blocked claiming the TPU tunnel must never be
    SIGKILLed — a killed claimant leaves a stale server-side lease that
    can poison the tunnel for the NEXT claimant (observed: ~25-min
    blocked claims ending UNAVAILABLE for the rest of a session). The
    orphan exits on its own when its claim resolves or fails; its stage
    file is disposable. Returns the child's Popen either way — callers
    of the abandon path poll() it later to reap."""
    args = [sys.executable, os.path.abspath(script), "--child", out_path,
            str(max(10.0, budget - 15.0))] + list(extra_args or ())
    proc = subprocess.Popen(args, env=env, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    try:
        proc.wait(timeout=budget)
    except subprocess.TimeoutExpired:
        if kill_on_timeout:
            proc.kill()
            proc.wait()
        else:
            # deprioritize the orphan: if its claim later resolves it
            # would otherwise run the full accel bench concurrently with
            # the CPU fallback on this 1-core box and depress the
            # fallback's measured throughput. (A skewed-low fallback is
            # still preferred over SIGKILLing a claimant.)
            try:
                os.setpriority(os.PRIO_PROCESS, proc.pid, 19)
            except (OSError, AttributeError):
                pass
    return proc  # caller may poll() to reap an abandoned child


def _sweep_stale_stage_files(out_path: str) -> None:
    """Abandoned accelerator children may recreate their stage files
    after the parent exits; collect day-old leftovers of the same
    naming scheme so they never accumulate."""
    import glob
    import time

    base = os.path.dirname(os.path.abspath(out_path)) or "."
    prefix = os.path.basename(out_path).split("_stages_")[0]
    for f in glob.glob(os.path.join(base, f"{prefix}_stages_*.jsonl*")):
        try:
            if time.time() - os.path.getmtime(f) > 86400:
                os.unlink(f)
        except OSError:
            pass


def run_with_cpu_fallback(script: str, out_path: str, deadline: float,
                          now: Callable[[], float], t0: float,
                          fallback_reserve: float,
                          need_rerun: Callable[[List[dict]], bool],
                          extra_args: Optional[List[str]] = None,
                          ) -> tuple:
    """Accelerator child first, CPU-pinned rerun if it produced nothing
    useful. Returns (records, fallback_used). The accelerator child is
    abandoned (not killed) on timeout — see :func:`run_child` — so the
    CPU rerun writes to its own file; records merge from both."""
    _sweep_stale_stage_files(out_path)
    cpu_path = out_path + ".cpu"
    for p in (out_path, cpu_path):
        try:
            os.unlink(p)
        except OSError:
            pass
    accel = run_child(script, out_path,
                      max(30.0, deadline - fallback_reserve),
                      dict(os.environ), extra_args, kill_on_timeout=False)
    records = read_records(out_path)
    fallback_used = False
    if need_rerun(records):
        left = deadline - (now() - t0) - 5.0
        if left > 20:
            fallback_used = True
            env = dict(os.environ)
            env["RAFIKI_JAX_PLATFORM"] = "cpu"
            run_child(script, cpu_path, left, env, extra_args)
            records = records + read_records(cpu_path)
    for p in (out_path, cpu_path):
        try:
            os.unlink(p)
        except OSError:
            pass
    accel.poll()  # reap if the abandoned child exited meanwhile
    return records, fallback_used


def collect_errors(records: List[dict], limit: int = 3) -> List[str]:
    """Error strings the child fsynced (child_error / *_error stages)."""
    return [str(r.get("error", r.get("stage")))[:200] for r in records
            if "error" in r or str(r.get("stage", "")).endswith("_error")
            ][:limit]
