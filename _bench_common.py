"""Shared harness for the deadline-bounded benches (bench.py,
bench_extra.py).

The pattern both use: a parent that never imports jax owns the clock;
accelerator work runs in a child that appends one JSON record per
completed stage to a scratch file (fsynced, parsed per-line so a
mid-write kill can't discard finished stages); if the accelerator child
produced no useful records, a CPU-pinned rerun spends the remaining
budget so the driver always gets a labeled number.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Callable, List, Optional


def record(out_path: str, rec: dict) -> None:
    """Append one stage record; fsync so the parent sees it even if the
    child is killed right after."""
    with open(out_path, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())


def read_records(out_path: str) -> List[dict]:
    """Per-line parse: a partial trailing line (child killed mid-write)
    must not discard completed, fsynced records before it."""
    records: List[dict] = []
    try:
        with open(out_path) as f:
            for line in f:
                try:
                    records.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return records


def run_child(script: str, out_path: str, budget: float,
              env: dict, extra_args: Optional[List[str]] = None) -> None:
    """Run ``script --child out_path <child_budget> [extra]`` with a hard
    wall-clock timeout; the child's own soft budget is a bit shorter so
    it can skip late stages instead of being killed mid-stage."""
    args = [sys.executable, os.path.abspath(script), "--child", out_path,
            str(max(10.0, budget - 15.0))] + list(extra_args or ())
    try:
        subprocess.run(args, timeout=budget, env=env,
                       stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    except subprocess.TimeoutExpired:
        pass


def run_with_cpu_fallback(script: str, out_path: str, deadline: float,
                          now: Callable[[], float], t0: float,
                          fallback_reserve: float,
                          need_rerun: Callable[[List[dict]], bool],
                          extra_args: Optional[List[str]] = None,
                          ) -> tuple:
    """Accelerator child first, CPU-pinned rerun if it produced nothing
    useful. Returns (records, fallback_used)."""
    try:
        os.unlink(out_path)
    except OSError:
        pass
    run_child(script, out_path, max(30.0, deadline - fallback_reserve),
              dict(os.environ), extra_args)
    records = read_records(out_path)
    fallback_used = False
    if need_rerun(records):
        left = deadline - (now() - t0) - 5.0
        if left > 20:
            fallback_used = True
            env = dict(os.environ)
            env["RAFIKI_JAX_PLATFORM"] = "cpu"
            run_child(script, out_path, left, env, extra_args)
            records = read_records(out_path)
    try:
        os.unlink(out_path)
    except OSError:
        pass
    return records, fallback_used


def collect_errors(records: List[dict], limit: int = 3) -> List[str]:
    """Error strings the child fsynced (child_error / *_error stages)."""
    return [str(r.get("error", r.get("stage")))[:200] for r in records
            if "error" in r or str(r.get("stage", "")).endswith("_error")
            ][:limit]
