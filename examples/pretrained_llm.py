"""Pretrained-base LLM fine-tune with a real tokenizer (config #5).

The full round-trip a user with an HF-style Llama checkpoint follows:

1. train a byte-level BPE tokenizer on a local corpus and save the
   artifact;
2. point the ``LlamaLoRA`` template at the checkpoint
   (``pretrained_path`` — single ``.safetensors``, a sharded
   ``model-*-of-*.safetensors`` + index directory, or the index file)
   and the tokenizer (``tokenizer_path``); each base weight streams
   from the (mmap'd) file straight into its 2-D fsdp x tensor-parallel
   sharding — no host ever holds the full tree;
3. LoRA-fine-tune (base frozen, adapters/norms/head train) and
   generate with EXACT detokenization (the merge table travels inside
   dumped parameters, so serving hosts need no artifact file).

Zero egress here, so the "pretrained" checkpoint is synthesized by
exporting a freshly initialized base with
``export_llama_safetensors`` — byte-for-byte the layout conversion a
real HF download takes.

    RAFIKI_JAX_PLATFORM=cpu python examples/pretrained_llm.py
"""

from __future__ import annotations

import json
import tempfile

from rafiki_tpu.utils.platform import apply_platform_env

apply_platform_env()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from rafiki_tpu.data import (ByteBPETokenizer,  # noqa: E402
                             generate_text_classification_dataset)
from rafiki_tpu.models.convert import \
    export_llama_safetensors  # noqa: E402
from rafiki_tpu.models.llama_lora import LlamaLoRA  # noqa: E402

KNOBS = {"max_epochs": 2, "vocab_size": 0,  # vocab follows the artifact
         "hidden_dim": 64, "depth": 2, "n_heads": 4, "kv_ratio": 2,
         "lora_rank": 4, "max_len": 32, "model_parallel": 1,
         "learning_rate": 1e-2, "batch_size": 8, "bf16": False,
         "remat": False, "moe_experts": 0, "quick_train": True,
         "share_params": False}


def main() -> None:
    with tempfile.TemporaryDirectory() as d:
        corpus = f"{d}/corpus.jsonl"
        generate_text_classification_dataset(corpus, 64, seed=0)

        # 1) tokenizer: train byte-BPE on the corpus text, save artifact
        texts = [rec["text"] for line in open(corpus) if line.strip()
                 for rec in [json.loads(line)] if "text" in rec]
        tok = ByteBPETokenizer.train(texts, vocab_size=300)
        tok_path = f"{d}/bpe.json"
        tok.save(tok_path)
        sample = texts[0][:40]
        assert tok.decode(tok.encode_ids(sample)) == sample  # lossless
        print(f"tokenizer: vocab={tok.vocab_size}, artifact={tok_path}")

        # 2) the "pretrained" base (stand-in for an HF download)
        base = LlamaLoRA(**KNOBS, tokenizer_path=tok_path,
                         pretrained_path="")
        module = base._module()
        params = module.init(jax.random.PRNGKey(7),
                             jnp.zeros((1, 8), jnp.int32))["params"]
        ckpt = f"{d}/base.safetensors"
        export_llama_safetensors(params, ckpt)
        print(f"checkpoint: {ckpt}")

        # 3) fine-tune over the imported base + serve
        model = LlamaLoRA(**KNOBS, tokenizer_path=tok_path,
                          pretrained_path=ckpt)
        model.train(corpus)
        score = model.evaluate(corpus)
        out = model.predict([sample])
        print(f"fine-tuned: inverse-perplexity={score:.4f}")
        print(f"prompt:     {sample!r}")
        print(f"generated:  {out[0]!r}")


if __name__ == "__main__":
    main()
