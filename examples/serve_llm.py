"""LLM fine-tune + continuous-batch serving (BASELINE.md config #5).

Trains a small LlamaLoRA under the advisor, deploys it, and sends
overlapping generation requests — the inference worker serves them
through the slot-based continuous-batching decode loop.

    rafiki-tpu stack start --workdir ./rafiki_stack
    RAFIKI_JAX_PLATFORM=cpu python examples/serve_llm.py \
        --admin http://127.0.0.1:3000
"""

from __future__ import annotations

import argparse
import tempfile
import threading

from rafiki_tpu.utils.platform import apply_platform_env

apply_platform_env()

from rafiki_tpu.client import Client  # noqa: E402
from rafiki_tpu.data import \
    generate_text_classification_dataset  # noqa: E402
from rafiki_tpu.models.llama_lora import LlamaLoRA  # noqa: E402

#: tiny in-domain pins so the demo fits a laptop; drop for a real run
SMALL = {"hidden_dim": 64, "depth": 2, "n_heads": 4, "kv_ratio": 2,
         "lora_rank": 4, "max_len": 32, "model_parallel": 1,
         "learning_rate": 1e-2, "batch_size": 8, "bf16": False,
         "quick_train": True, "share_params": False}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--admin", default="http://127.0.0.1:3000")
    args = ap.parse_args()

    client = Client(args.admin)
    client.login("superadmin@rafiki", "rafiki")

    with tempfile.TemporaryDirectory() as d:
        tr, va = f"{d}/train.jsonl", f"{d}/val.jsonl"
        generate_text_classification_dataset(tr, 128, seed=0)
        generate_text_classification_dataset(va, 32, seed=1)

        model = client.create_model("demo-llama", "LANGUAGE_MODELING",
                                    LlamaLoRA)
        job = client.create_train_job(
            app="llm-demo", task="LANGUAGE_MODELING",
            train_dataset_id=tr, val_dataset_id=va,
            budget={"TRIAL_COUNT": 2},
            model_ids=[model["id"]],
            train_args={"advisor": "random", "knob_overrides": SMALL})
        job = client.wait_until_train_job_finished(job["id"], timeout=900)
        print("train job:", job["status"])

        # deploy the best trial WITH speculative decoding: the other
        # completed trial serves as the draft MODEL (swap in a smaller
        # parameterization for a real speedup; prompt-lookup drafting
        # needs only SPECULATE_K). MAX_NEW_TOKENS caps generations.
        trials = [t for t in client.get_trials_of_train_job(job["id"])
                  if t["status"] == "COMPLETED"]
        best_list = client.get_best_trials_of_train_job(job["id"])
        if not best_list:
            raise SystemExit(
                f"no deployable trial (trials: "
                f"{[t['status'] for t in trials] or 'none completed'})")
        deploy_budget = {"SPECULATE_K": 4, "MAX_NEW_TOKENS": 8}
        others = [t["id"] for t in trials
                  if t["id"] != best_list[0]["id"]]
        if others:
            deploy_budget["DRAFT_TRIAL_ID"] = others[0]
        ijob = client.create_inference_job(job["id"], max_workers=1,
                                           budget=deploy_budget)
        url = ijob["predictor_url"]
        print("predictor:", url)

        # overlapping clients: requests admitted into free KV slots
        # mid-flight share one decode loop on the worker
        def ask(prompt: str) -> None:
            out = client.predict(url, [prompt], timeout=180)
            print(f"  {prompt!r} -> {out[0]!r}")

        threads = [threading.Thread(target=ask, args=(p,))
                   for p in ("tok1 tok2 tok3", "tok4 tok5",
                             "tok6 tok7 tok8 tok9")]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        # live serving health: req/s, latency percentiles, and the
        # engine's speculation counters (acceptance shows up here).
        # Counters publish every ~50 worker-loop iterations — keep a
        # little traffic flowing until a fresh snapshot lands.
        w = {}
        for i in range(40):
            client.predict(url, [f"tok{i % 5 + 1} tok2"], timeout=60)
            health = client.get_inference_job_health(ijob["id"])
            w = next(iter(health.get("workers", {}).values()), {})
            if w.get("engine_spec_calls", 0):
                break
        print("speculative calls:",
              w.get("engine_spec_draft_model_calls")
              or w.get("engine_spec_calls", 0),
              "accepted:", w.get("engine_spec_accepted", 0),
              "drafted:", w.get("engine_spec_drafted", 0))

        # seeded sampling: reproducible under any serving load
        samp = {"temperature": 0.8, "top_k": 40, "seed": 1234}
        a = client.predict(url, ["tok1 tok2"], timeout=180,
                           sampling=samp)
        b = client.predict(url, ["tok1 tok2"], timeout=180,
                           sampling=samp)
        print("seeded sampling reproducible:", a == b)

        # token streaming: SSE deltas as the decode loop produces them
        print("streaming:", end="", flush=True)
        for ev in client.predict_stream(url, ["tok1 tok2 tok3"],
                                        timeout=180):
            if "delta" in ev:
                print(" +", "".join(ev["delta"].values()),
                      end="", flush=True)
            elif ev.get("done") and ev.get("error"):
                print(f"\nstream failed: {ev['error']} "
                      f"(partial: {ev.get('partial')})")
            elif ev.get("done"):
                print("\nfinal:", (ev.get("predictions") or [""])[0])

        client.stop_inference_job(ijob["id"])


if __name__ == "__main__":
    main()
