"""Multi-tenant LLM serving: N LoRA fine-tunes, one base, one engine.

The round-4 serving features end to end, library-level (no stack):

1. train TWO ``adapters_only`` LoRA fine-tunes of one base — only
   ``lora_a``/``lora_b`` move, so the trials share every other leaf;
2. stack them into ONE continuous-batching engine
   (``make_multi_adapter_engine``) — the base matmul runs once per
   fused step for the whole mixed-tenant batch, each request selecting
   its fine-tune by ``adapter_id``;
3. give each tenant its own system-prompt KV snapshot
   (``register_prefix(..., adapter_id=i)``) so shared prefixes skip
   prefill per tenant;
4. stream tokens as they decode (``poll_partial``).

Against the full stack the same features ride the REST API: deploy
with ``client.create_inference_job(job_id, budget={"MULTI_ADAPTER": 1})``,
route with ``client.predict(url, qs, sampling={"adapter_id": i})``, and
stream with ``client.predict_stream(url, qs)``.

    RAFIKI_JAX_PLATFORM=cpu python examples/multi_tenant_serving.py
"""

from __future__ import annotations

import tempfile

from rafiki_tpu.utils.platform import apply_platform_env

apply_platform_env()

from rafiki_tpu.data import \
    generate_text_classification_dataset  # noqa: E402
from rafiki_tpu.models.llama_lora import LlamaLoRA  # noqa: E402

KNOBS = {"max_epochs": 2, "vocab_size": 1 << 10, "hidden_dim": 64,
         "depth": 2, "n_heads": 4, "kv_ratio": 2, "lora_rank": 4,
         "max_len": 32, "model_parallel": 1, "learning_rate": 1e-2,
         "batch_size": 8, "bf16": False, "quick_train": True,
         "share_params": False, "adapters_only": True}


def main() -> None:
    with tempfile.TemporaryDirectory() as d:
        tenants = []
        for seed in (0, 1):  # two "tenants" fine-tune on their own data
            tr = f"{d}/tenant{seed}.jsonl"
            generate_text_classification_dataset(tr, 64, seed=seed)
            m = LlamaLoRA(**KNOBS)
            m.train(tr)
            tenants.append(m)

    base = tenants[0]
    engine = base.make_multi_adapter_engine(
        [m._params for m in tenants], max_slots=4, max_new_tokens=8)
    print(f"one engine, {engine.engine.n_adapters} tenants, "
          "one base model's HBM")

    # per-tenant system prompts: each adapter gets its own KV snapshot
    for aid in range(2):
        n = engine.register_prefix("tok1 tok2 tok3", adapter_id=aid)
        print(f"tenant {aid}: prefix KV cached ({n} tokens)")

    # mixed-tenant traffic decodes in the SAME fused steps, streaming
    prompt = "tok1 tok2 tok3 tok4"
    engine.submit("tenant-0", prompt, adapter_id=0)
    engine.submit("tenant-1", prompt, adapter_id=1)
    finals = {}
    while engine.busy:
        engine.step()
        for rid, delta in engine.poll_partial():
            print(f"  {rid} += {delta!r}")
        for rid, text in engine.poll():
            finals[rid] = text
    for rid in sorted(finals):
        print(f"{rid}: {finals[rid]!r}")
    assert finals["tenant-0"] != finals["tenant-1"]
    stats = engine.stats
    print(f"prefix hits: {stats['prefix_hits']}, "
          f"concurrent: {stats['max_concurrent']}")


if __name__ == "__main__":
    main()
