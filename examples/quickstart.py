"""Quickstart: the full train → tune → deploy → predict loop via the SDK.

Mirrors the reference's examples/ quickstart scripts (SURVEY.md §4: the
quickstart doubles as the integration flow). Run a stack first:

    rafiki-tpu stack start --workdir ./rafiki_stack
    RAFIKI_JAX_PLATFORM=cpu python examples/quickstart.py \
        --admin http://127.0.0.1:3000

On a CPU-only host keep RAFIKI_JAX_PLATFORM=cpu; on a TPU VM drop it.
"""

from __future__ import annotations

import argparse
import tempfile

from rafiki_tpu.utils.platform import apply_platform_env

apply_platform_env()

import numpy as np  # noqa: E402

from rafiki_tpu.client import Client  # noqa: E402
from rafiki_tpu.data import \
    generate_image_classification_dataset  # noqa: E402
from rafiki_tpu.models.mlp import JaxFeedForward  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--admin", default="http://127.0.0.1:3000")
    ap.add_argument("--trials", type=int, default=3)
    args = ap.parse_args()

    client = Client(args.admin)
    client.login("superadmin@rafiki", "rafiki")

    with tempfile.TemporaryDirectory() as d:
        train_p, val_p = f"{d}/train.npz", f"{d}/val.npz"
        generate_image_classification_dataset(train_p, 1024, seed=0)
        val = generate_image_classification_dataset(val_p, 256, seed=1)

        model = client.create_model("quickstart-mlp",
                                    "IMAGE_CLASSIFICATION", JaxFeedForward)
        job = client.create_train_job(
            app="quickstart", task="IMAGE_CLASSIFICATION",
            train_dataset_id=train_p, val_dataset_id=val_p,
            budget={"TRIAL_COUNT": args.trials},
            model_ids=[model["id"]])
        print("train job:", job["id"], job["status"])

        job = client.wait_until_train_job_finished(job["id"], timeout=900)
        best = client.get_best_trials_of_train_job(job["id"])
        print("best trial score:", best[0]["score"])

        ijob = client.create_inference_job(job["id"], max_workers=2)
        print("predictor:", ijob["predictor_url"])
        preds = client.predict(ijob["predictor_url"],
                               [val.images[i] for i in range(8)],
                               timeout=120)
        acc = np.mean([int(np.argmax(p)) == val.labels[i]
                       for i, p in enumerate(preds)])
        print(f"deployed ensemble accuracy on 8 queries: {acc:.2f}")
        client.stop_inference_job(ijob["id"])


if __name__ == "__main__":
    main()
