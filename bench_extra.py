"""Serving-side benchmarks — the SURVEY.md §6 / BASELINE.json metrics.

Measures (one JSON line per metric, all emitted at the end):

1. ``predictor_req_per_s`` + ``predictor_p50_ms`` — ViT-B/16 replicas
   served through the real scatter/gather path (Predictor → QueueHub →
   InferenceWorker.model.predict → ensemble), closed-loop clients.
2. ``advisor_trials_per_hour`` — the in-process tune loop (MLP template,
   config #1) measured for N trials and extrapolated.

Same parent/child deadline architecture as ``bench.py``: accelerator
work runs in a child streaming stage records to a file; the parent owns
the clock and always prints parseable lines, rc=0. Run directly:

    python bench_extra.py                 # accelerator (axon/TPU) or CPU
    RAFIKI_BENCH_DEADLINE=600 python bench_extra.py

The predictor leg uses the InProc hub by default (single-host fast
path); ``--kv`` routes it through the native kv server instead (one
``rafiki-kvd`` subprocess), which measures the cross-process transport.
"""

from __future__ import annotations

import json
import os
import sys
import time

from _bench_common import (collect_errors, record as _record,
                           run_with_cpu_fallback)

DEADLINE = float(os.environ.get("RAFIKI_BENCH_DEADLINE", "480"))

#: RAFIKI_BENCH_ONLY=kv_tier,disagg_prefill narrows a run to the named
#: stages — how a PR's committed BENCH_<stage>.json lines are produced
#: without paying for the whole suite. Empty (default) = run all.
_ONLY = frozenset(s.strip() for s in
                  os.environ.get("RAFIKI_BENCH_ONLY", "").split(",")
                  if s.strip())


def _want(stage: str) -> bool:
    return not _ONLY or stage in _ONLY


# ----------------------------------------------------------------- child

def _bench_predictor(out_path: str, use_kv: bool, duration: float) -> None:
    import threading

    import numpy as np

    from rafiki_tpu.models.vit import ViTBase16
    from rafiki_tpu.serving.predictor import Predictor
    from rafiki_tpu.serving.queues import InProcQueueHub
    from rafiki_tpu.store.param_store import ParamStore
    from rafiki_tpu.worker.inference import InferenceWorker

    import jax

    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)

    # ViT-B/16 on the accelerator; a small ViT on CPU so the run finishes
    knobs = {
        "max_epochs": 1, "patch_size": 16 if on_accel else 8,
        "hidden_dim": 768 if on_accel else 96,
        "depth": 12 if on_accel else 2,
        "n_heads": 12 if on_accel else 4,
        "learning_rate": 1e-3, "weight_decay": 1e-4, "warmup_frac": 0.1,
        # bf16 compute only where the MXU wants it: on CPU it would be
        # EMULATED bf16 and slow the serving numbers down
        "batch_size": 32, "bf16": on_accel,
        "quick_train": True, "share_params": False,
    }
    img = 224 if on_accel else 64

    # serving perf does not depend on trained weights: init-and-dump
    model = ViTBase16(**knobs)
    model._n_classes = 1000 if on_accel else 10
    model._image_shape = [img, img, 3]
    import jax.numpy as jnp

    module = model._module()
    model._params = module.init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, img, img, 3),
                  jnp.bfloat16 if knobs["bf16"] else jnp.float32))["params"]
    blob = model.dump_parameters()

    store = ParamStore.from_uri("mem://")
    store.save("trial-bench", blob)

    kvd = None
    worker = None
    try:
        if use_kv:
            from rafiki_tpu.native.client import KVServer
            from rafiki_tpu.serving.queues import KVQueueHub

            kvd = KVServer()
            hub = KVQueueHub(kvd.host, kvd.port)
        else:
            hub = InProcQueueHub()

        worker = InferenceWorker(ViTBase16, "trial-bench", knobs, store,
                                 hub, worker_id="w0")
        wt = threading.Thread(target=worker.run, daemon=True)
        wt.start()

        predictor = Predictor(hub, ["w0"], gather_timeout=30.0)

        rng = np.random.default_rng(0)
        query = rng.integers(0, 255, size=(img, img, 3), dtype=np.uint8)

        # warm the serving path (compile happens in-worker on first
        # predict)
        preds, info = predictor.predict([query] * 8)
        if not preds or preds[0] is None:
            raise RuntimeError(f"warmup failed: {info}")
        _record(out_path, {"stage": "predictor_warm", "backend": backend})

        # closed-loop clients, batch of 8 queries per request
        stop_at = time.monotonic() + duration
        counts = {"req": 0, "q": 0}
        lock = threading.Lock()

        def client() -> None:
            while time.monotonic() < stop_at:
                p, _ = predictor.predict([query] * 8)
                with lock:
                    counts["req"] += 1
                    counts["q"] += len(p)

        clients = [threading.Thread(target=client, daemon=True)
                   for _ in range(4)]
        t0 = time.monotonic()
        for c in clients:
            c.start()
        for c in clients:
            c.join(timeout=duration + 30.0)
        dt = time.monotonic() - t0
    finally:
        if worker is not None:
            worker.stop()
        if kvd is not None:
            kvd.stop()

    stats = predictor.stats()
    _record(out_path, {
        "stage": "predictor", "backend": backend,
        "req_per_s": counts["req"] / dt,
        "queries_per_s": counts["q"] / dt,
        "p50_ms": stats["latency_p50_s"] * 1e3,
        "p95_ms": stats["latency_p95_s"] * 1e3,
        "model": "vit_b16" if on_accel else "vit_s64",
    })


def _bench_generation(out_path: str, duration: float) -> None:
    """Continuous-batch LM serving (BASELINE config #5): decode-loop
    worker + predictor, overlapping clients, generation req/s and
    tokens/s."""
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from rafiki_tpu.models.llama_lora import LlamaLoRA
    from rafiki_tpu.serving.predictor import Predictor
    from rafiki_tpu.serving.queues import InProcQueueHub
    from rafiki_tpu.store.param_store import ParamStore
    from rafiki_tpu.worker.inference import InferenceWorker

    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)
    knobs = {
        "max_epochs": 1, "vocab_size": 1 << 14,
        "hidden_dim": 512 if on_accel else 64,
        "depth": 8 if on_accel else 2,
        "n_heads": 8 if on_accel else 4, "kv_ratio": 2,
        "lora_rank": 8, "max_len": 128 if on_accel else 32,
        "model_parallel": 1, "learning_rate": 1e-3, "batch_size": 8,
        "bf16": on_accel, "quick_train": True, "share_params": False,
    }
    model = LlamaLoRA(**knobs)
    module = model._module()
    model._params = module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    blob = model.dump_parameters()
    store = ParamStore.from_uri("mem://")
    store.save("trial-lm", blob)

    hub = InProcQueueHub()
    max_new = 16 if on_accel else 6
    worker = InferenceWorker(LlamaLoRA, "trial-lm", knobs, store, hub,
                             worker_id="w0", decode_loop=True,
                             max_slots=8, max_new_tokens=max_new)
    wt = threading.Thread(target=worker.run, daemon=True)
    wt.start()
    try:
        predictor = Predictor(hub, ["w0"], gather_timeout=120.0)
        preds, info = predictor.predict(["tok1 tok2 tok3"])  # warm/compile
        if not preds or not preds[0]:
            raise RuntimeError(f"generation warmup failed: {info}")
        _record(out_path, {"stage": "generation_warm",
                           "backend": backend})

        stop_at = time.monotonic() + duration
        counts = {"req": 0, "q": 0}
        lock = threading.Lock()

        def client(i: int) -> None:
            prompt = f"tok{i} tok{i + 1} tok{i + 2}"
            while time.monotonic() < stop_at:
                p, _ = predictor.predict([prompt, prompt + " tokx"])
                with lock:
                    counts["req"] += 1
                    counts["q"] += len(p)

        clients = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(4)]
        t0 = time.monotonic()
        for c in clients:
            c.start()
        for c in clients:
            c.join(timeout=duration + 60.0)
        dt = time.monotonic() - t0
    finally:
        worker.stop()

    stats = predictor.stats()
    eng = worker.engine.stats
    _record(out_path, {
        "stage": "generation", "backend": backend,
        "req_per_s": counts["req"] / dt,
        "queries_per_s": counts["q"] / dt,
        "tokens_per_s": eng["tokens_generated"] / dt,
        "max_concurrent_slots": eng["max_concurrent"],
        "prefill_calls": eng["prefill_calls"],
        "prefill_tokens": eng["prefill_tokens"],
        "p50_ms": stats["latency_p50_s"] * 1e3,
        "max_new": max_new,
        "model": "llama_512x8" if on_accel else "llama_64x2",
    })

    # prompt-ingestion speedup: time a long prompt through chunked
    # prefill (C-token compiled calls) vs the token-wise decode scan
    from rafiki_tpu.serving.decode_engine import DecodeEngine

    plen = 96 if on_accel else 24
    prompt = np.arange(1, plen + 1, dtype=np.int32) % knobs["vocab_size"]

    def ingest_time(chunk: int) -> float:
        eng2 = DecodeEngine(module, model._params, max_slots=8,
                            max_len=knobs["max_len"],
                            prefill_chunk=chunk)
        eng2.submit("warm", prompt[:4], 1)     # pay both compiles
        while eng2.busy:
            eng2.step()
        eng2.poll()
        t0 = time.perf_counter()
        eng2.submit("p", prompt, 1)            # 1 new token: time ≈ prefill
        while eng2.busy:
            eng2.step()
        eng2.poll()
        return time.perf_counter() - t0

    tokenwise_s = ingest_time(1)
    chunked_s = ingest_time(32)
    _record(out_path, {
        "stage": "prefill", "backend": backend, "prompt_tokens": plen,
        "tokenwise_ms": tokenwise_s * 1e3, "chunked_ms": chunked_s * 1e3,
        "prefill_speedup": tokenwise_s / max(chunked_s, 1e-9),
    })

    # speculative decoding: greedy tokens/s with prompt-lookup drafting
    # vs the plain fused scan, same model/prompts. Acceptance is
    # content-dependent (greedy decode of LMs tends to cycle, which the
    # n-gram drafter exploits); the record carries the measured rate so
    # the ratio can be interpreted.
    rep = np.asarray(([1, 7, 2, 9] * 4)[:12], np.int32)
    # prime the prompt with the model's OWN greedy continuation: the
    # n-gram drafter exploits the model's cycle, not the prompt's, so
    # this measures speculation in the predictable-content regime the
    # stage exists to characterize (an unprimed prompt can gate the
    # path off before generation becomes self-predictable)
    from rafiki_tpu.models.llama_lora import greedy_generate

    seed_gen = np.asarray(greedy_generate(
        module, model._params, rep[None, :],
        np.asarray([len(rep)], np.int32), 8))[0].astype(np.int32)
    rep = np.concatenate([rep, seed_gen])

    # windows divide max_new so the stop boundary doesn't dilute the
    # acceptance accounting AT THE EXTREMES this stage records (full
    # acceptance advances exactly k per window; zero acceptance never
    # reaches the boundary early). Mid-acceptance drafts can still see
    # clamped final windows counting unused drafts as rejected.
    spec_new = 8

    def gen_rate(spec_k: int, draft=None):
        eng3 = DecodeEngine(module, model._params, max_slots=4,
                            max_len=knobs["max_len"], speculate_k=spec_k,
                            draft=draft)
        eng3.submit("warm", rep, 2)            # pay the compiles
        while eng3.busy:
            eng3.step()
        eng3.poll()
        warm = dict(eng3.stats)                # exclude warm-up from stats
        t0 = time.perf_counter()
        for r in range(4):
            eng3.submit(("r", r), rep, spec_new)
        while eng3.busy:
            eng3.step()
        eng3.poll()
        dt = time.perf_counter() - t0
        timed = {k: eng3.stats[k] - warm.get(k, 0) for k in eng3.stats}
        return 4 * spec_new / dt, timed

    plain_tps, _ = gen_rate(0)
    spec_tps, st = gen_rate(4)
    # draft-MODEL speculation with the model as its OWN draft: 100%
    # acceptance by construction — the ACCEPTANCE-machinery record,
    # content-independent. NOT a speed claim: a same-size draft costs
    # what it saves (real wins need a much smaller draft on content it
    # can predict; the unit suite proves losslessness either way)
    draft_tps, dst = gen_rate(4, draft=(module, model._params))
    _record(out_path, {
        "stage": "speculative", "backend": backend,
        "plain_tokens_per_s": plain_tps, "spec_tokens_per_s": spec_tps,
        "spec_speedup": spec_tps / max(plain_tps, 1e-9),
        "spec_calls": st["spec_calls"], "spec_drafted": st["spec_drafted"],
        "spec_accept_rate": (st["spec_accepted"]
                             / max(1, st["spec_drafted"])),
        "draft_model_tokens_per_s": draft_tps,
        "draft_model_speedup": draft_tps / max(plain_tps, 1e-9),
        "draft_model_accept_rate": (dst["spec_accepted"]
                                    / max(1, dst["spec_drafted"])),
    })


def build_small_draft_setup(on_accel: bool):
    """Shared recipe for the distilled-small-draft speculation leg —
    the bench stage AND its contract test
    (``tests/test_draft_spec.py::test_distilled_small_draft_partial_
    acceptance``) both build from HERE, so the test pins the exact
    bench configuration (corpus seed, 220 distillation steps, the
    horizon+2 eval design) instead of a drift-prone copy.

    Returns ``(t_mod, t_params, d_mod, d_params, evs, max_new,
    distill_loss)``."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from rafiki_tpu.models.llama_lora import Llama, greedy_generate

    vocab, max_len = 1 << 14, 64
    if on_accel:  # the serving-bench scale target; draft 1/8 width
        t_dims = dict(hidden_dim=512, depth=8, n_heads=8, n_kv_heads=4,
                      mlp_dim=2048)
        d_dims = dict(hidden_dim=64, depth=1, n_heads=4, n_kv_heads=2,
                      mlp_dim=128)
    else:
        t_dims = dict(hidden_dim=128, depth=4, n_heads=4, n_kv_heads=2,
                      mlp_dim=512)
        d_dims = dict(hidden_dim=32, depth=1, n_heads=4, n_kv_heads=2,
                      mlp_dim=64)
    t_mod = Llama(vocab_size=vocab, max_len=max_len, lora_rank=0,
                  **t_dims)
    t_params = t_mod.init(jax.random.PRNGKey(0),
                          jnp.zeros((1, 8), jnp.int32))["params"]
    d_mod = Llama(vocab_size=vocab, max_len=max_len, lora_rank=0,
                  **d_dims)

    # corpus: the target's greedy continuations from a 12-prompt family
    rng = np.random.default_rng(7)
    plen, glen = 12, 20
    prompts = rng.integers(1, 10, size=(12, plen)).astype(np.int32)
    gens = np.asarray(greedy_generate(
        t_mod, t_params, prompts,
        np.full((12,), plen, np.int32), glen)).astype(np.int32)
    ids = np.concatenate([prompts, gens], axis=1)

    d_params = d_mod.init(jax.random.PRNGKey(1),
                          jnp.zeros((1, 8), jnp.int32))["params"]
    tx = optax.adam(3e-3)
    opt = tx.init(d_params)
    xb, yb = jnp.asarray(ids[:, :-1]), jnp.asarray(ids[:, 1:])

    @jax.jit
    def dstep(p, o):
        def loss_fn(p):
            logits = d_mod.apply({"params": p}, xb)
            return jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(
                    logits.astype(jnp.float32), yb))

        loss, g = jax.value_and_grad(loss_fn)(p)
        u, o = tx.update(g, o)
        return optax.apply_updates(p, u), o, loss

    for _ in range(220):
        d_params, opt, d_loss = dstep(d_params, opt)

    # eval: 4 corpus prompts primed 8 tokens deep; max_new runs 2 past
    # the distillation horizon (the (0,1)-acceptance design point).
    # Greedy decode is deterministic, so the 8-token priming is just
    # the corpus continuation's own prefix — no regeneration needed.
    max_new = (glen - 8) + 2
    evs = [np.concatenate([prompts[i], gens[i][:8]]) for i in
           (0, 3, 5, 8)]
    return (t_mod, t_params, d_mod, d_params, evs, max_new,
            float(d_loss))


def _bench_small_draft_spec(out_path: str) -> None:
    """Speculative decoding with a GENUINELY smaller draft, distilled
    on the bench corpus (VERDICT r4 item 5): a depth-1 draft at 1/4 the
    target's width trains for ~20s on the target's own greedy
    continuations, then serves as the draft model for requests whose
    generations run 2 tokens PAST the distillation horizon — so
    acceptance lands strictly inside (0, 1): near-perfect on the
    trajectory body, content-dependent at the tail.

    The speedup column is backend-physics honest: speculation pays off
    where decode is MEMORY-bound (a k+1-token verify streams the
    target's weights once instead of k+1 times — the TPU/accelerator
    regime). On 1-core CPU at bench scale the fused scan is DISPATCH-
    bound (K tokens per dispatch) and the draft path's extra dispatches
    (draft scan + verify mirror per window) eat the streaming win, so
    the CPU row documents the machinery + acceptance while the on-chip
    row is where the ratio is expected to clear 1."""
    import jax

    from rafiki_tpu.serving.decode_engine import DecodeEngine

    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)
    (t_mod, t_params, d_mod, d_params, evs, max_new,
     d_loss) = build_small_draft_setup(on_accel)

    def rate(spec_k, draft=None):
        eng = DecodeEngine(t_mod, t_params, max_slots=4,
                           max_len=t_mod.max_len, speculate_k=spec_k,
                           draft=draft)
        eng.submit("warm", evs[0], 2)
        while eng.busy:
            eng.step()
        eng.poll()
        warm = dict(eng.stats)
        t0 = time.perf_counter()
        for r, e in enumerate(evs):
            eng.submit(("r", r), e, max_new)
        while eng.busy:
            eng.step()
        eng.poll()
        dt = time.perf_counter() - t0
        stt = {k: eng.stats[k] - warm.get(k, 0) for k in eng.stats}
        return 4 * max_new / dt, stt

    plain_tps, _ = rate(0)
    small_tps, sst = rate(4, draft=(d_mod, d_params))
    _record(out_path, {
        "stage": "speculative_small_draft", "backend": backend,
        "target": f"llama_{t_mod.hidden_dim}x{t_mod.depth}",
        "draft": f"llama_{d_mod.hidden_dim}x{d_mod.depth}",
        "distill_loss": float(d_loss),
        "plain_tokens_per_s": plain_tps,
        "small_draft_tokens_per_s": small_tps,
        "small_draft_speedup": small_tps / max(plain_tps, 1e-9),
        "small_draft_accept_rate": (sst["spec_accepted"]
                                    / max(1, sst["spec_drafted"])),
        "spec_drafted": sst["spec_drafted"],
        "spec_accepted": sst["spec_accepted"],
    })


def _bench_kv_footprint(out_path: str) -> None:
    """Paged vs contiguous KV serving (ISSUE 5 tentpole evidence):
    measured decode-cache bytes AND req/s on the SAME mixed-length
    workload at EQUAL concurrency (same slot count, all slots busy).
    The paged pool is sized to the workload's worst case — prompt +
    max_new per request — so its bytes track live tokens while the
    contiguous engine pays max_slots × max_len regardless; the run
    proves the ≥2x footprint cut costs no throughput (both engines do
    the same attention math; the pool only changes the KV layout).
    CPU-fallback friendly: tiny model, deterministic workload."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rafiki_tpu.models.llama_lora import Llama
    from rafiki_tpu.serving.decode_engine import DecodeEngine

    backend = jax.default_backend()
    vocab, max_len, slots = 1 << 10, 64, 8
    # big enough that per-step matmul work dominates the (fixed) page
    # gather — at toy widths a dispatch-bound CPU run overstates the
    # gather's share; at real serving widths weights dwarf it entirely
    dims = dict(vocab_size=vocab, max_len=max_len, hidden_dim=256,
                depth=4, n_heads=4, n_kv_heads=2, mlp_dim=1024,
                lora_rank=0)
    params = Llama(**dims).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]

    # mixed-length traffic: prompts 4..16 tokens, 6 generated — the
    # regime where per-slot max_len preallocation wastes the most
    rng = np.random.default_rng(0)
    max_new, p_hi = 6, 16
    reqs = [(r, rng.integers(1, vocab, size=int(rng.integers(4, p_hi + 1))
                             ).astype(np.int32), max_new)
            for r in range(32)]
    page = 8
    # pool = worst case of `slots` concurrent requests, NOT slots*L:
    # pages covering (p_hi - 1 + max_new) positions each, + scratch
    pages = 1 + slots * ((p_hi - 1 + max_new - 1) // page + 1)
    paged_mod = Llama(**dims, kv_page_size=page, kv_pages=pages)

    def build(module):
        eng = DecodeEngine(module, params, max_slots=slots,
                           max_len=max_len, steps_per_sync=4,
                           prefill_chunk=8)
        kv_bytes = sum(
            int(np.prod(l.shape)) * l.dtype.itemsize
            for l in jax.tree_util.tree_leaves(eng._cache))
        return eng, kv_bytes

    def one_pass(eng) -> float:
        t0 = time.perf_counter()
        for r in reqs:
            eng.submit(*r)
        while eng.busy:
            eng.step()
        eng.poll()
        return time.perf_counter() - t0

    contig, c_bytes = build(Llama(**dims))
    paged, p_bytes = build(paged_mod)
    # interleaved best-of-3 (after a compile/first-touch pass each):
    # back-to-back same-engine passes would fold CPU scheduler drift
    # into the ratio this stage exists to report
    c_dt = p_dt = float("inf")
    for i in range(4):
        c, p = one_pass(contig), one_pass(paged)
        if i:
            c_dt, p_dt = min(c_dt, c), min(p_dt, p)
    c_rps, p_rps = len(reqs) / c_dt, len(reqs) / p_dt
    c_stats, p_stats = dict(contig.stats), dict(paged.stats)
    _record(out_path, {
        "stage": "kv_footprint", "backend": backend,
        "contiguous_kv_bytes": c_bytes, "paged_kv_bytes": p_bytes,
        "footprint_reduction": c_bytes / max(1, p_bytes),
        "contiguous_req_per_s": c_rps, "paged_req_per_s": p_rps,
        "req_per_s_ratio": p_rps / max(c_rps, 1e-9),
        "max_concurrent_contig": c_stats["max_concurrent"],
        "max_concurrent_paged": p_stats["max_concurrent"],
        "kv_pages_high_water": p_stats["kv_pages_high_water"],
        "kv_pages_total": p_stats["kv_pages_total"],
        "admission_stalls": p_stats["admission_stalls"],
        "page_size": page, "max_len": max_len, "max_slots": slots})


def _bench_paged_decode(out_path: str) -> None:
    """Paged decode, kernel vs gather (ISSUE 10 tentpole evidence):
    tokens/s at high concurrency (all slots busy, decode-heavy
    traffic) on the SAME paged pool, once through the page-gather
    fallback and once through the Pallas block-table kernel. On TPU
    the kernel is the point — per-step HBM traffic scales with live
    tokens instead of re-materializing the logical KV. Off-TPU the
    kernel leg runs the Pallas INTERPRETER (recorded as
    ``kernel_provenance``): the ratio is then a correctness-cost
    artifact, not a speed claim — the committed number's job on CPU is
    to prove the stage runs end-to-end and to anchor the token-exact
    equivalence the tests enforce. The gather leg is the shipping CPU
    configuration either way."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rafiki_tpu.models.llama_lora import Llama
    from rafiki_tpu.serving.decode_engine import DecodeEngine

    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)
    vocab, max_len, slots = 1 << 10, 64, 8
    # CPU sizes keep the interpreter leg inside the stage budget; on
    # chip the kernel compiles once and real widths apply
    dims = dict(vocab_size=vocab, max_len=max_len,
                hidden_dim=256 if on_accel else 64,
                depth=4 if on_accel else 2, n_heads=4, n_kv_heads=2,
                mlp_dim=1024 if on_accel else 256, lora_rank=0)
    params = Llama(**dims).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]

    # decode-heavy mixed traffic: short prompts, long generations —
    # the per-token step loop (where the kernel lives) dominates
    rng = np.random.default_rng(0)
    max_new = 24 if on_accel else 12
    reqs = [(r, rng.integers(1, vocab,
                             size=int(rng.integers(4, 9))
                             ).astype(np.int32), max_new)
            for r in range(16)]
    page = 8
    pages = 1 + slots * ((8 - 1 + max_new - 1) // page + 1)

    def run(paged_kernel: bool):
        eng = DecodeEngine(
            Llama(**dims, kv_page_size=page, kv_pages=pages,
                  paged_kernel=paged_kernel),
            params, max_slots=slots, max_len=max_len,
            steps_per_sync=4, prefill_chunk=8)

        def one_pass():
            t0 = time.perf_counter()
            for r in reqs:
                eng.submit(*r)
            while eng.busy:
                eng.step()
            eng.poll()
            dt = time.perf_counter() - t0
            stats = eng.stats_snapshot()
            eng.reset_stats()
            return dt, stats

        one_pass()  # compile/first-touch
        best = float("inf")
        stats = {}
        for _ in range(3):
            dt, stats = one_pass()
            best = min(best, dt)
        return int(stats["tokens_generated"]) / best, stats

    gather_tps, g_stats = run(False)
    kernel_tps, k_stats = run(True)
    assert g_stats["paged_kernel_mode"] == 0
    assert k_stats["paged_kernel_mode"] == 2
    assert k_stats["paged_kernel_step_tokens"] > 0
    _record(out_path, {
        "stage": "paged_decode", "backend": backend,
        "gather_tokens_per_s": gather_tps,
        "kernel_tokens_per_s": kernel_tps,
        "tokens_per_s_ratio": kernel_tps / max(gather_tps, 1e-9),
        "kernel_provenance": ("mosaic" if on_accel
                              else "cpu-fallback-interpret"),
        "max_concurrent": k_stats["max_concurrent"],
        "kv_pages_high_water": k_stats["kv_pages_high_water"],
        "kv_pages_total": k_stats["kv_pages_total"],
        "requests": len(reqs), "max_new": max_new,
        "page_size": page, "max_len": max_len, "max_slots": slots})


def _bench_paged_prefill(out_path: str) -> None:
    """Chunked prefill, window kernel vs gather (ISSUE 19 tentpole
    evidence): prompt tokens/s under prefill-heavy traffic (long
    prompts, short generations — the chunk loop dominates) on the SAME
    paged pool, once through the multi-token page-gather fallback and
    once through the Pallas window kernel. On TPU the kernel is the
    point — each chunk's HBM traffic walks the block table instead of
    re-materializing the logical KV per window row. Off-TPU the kernel
    leg runs the Pallas INTERPRETER (``kernel_provenance`` records
    which): the committed CPU number proves the windowed stage runs
    end-to-end and anchors the token-exact equivalence the tests
    enforce; the gather leg is the shipping CPU configuration."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rafiki_tpu.models.llama_lora import Llama
    from rafiki_tpu.serving.decode_engine import DecodeEngine

    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)
    vocab, max_len, slots = 1 << 10, 64, 8
    dims = dict(vocab_size=vocab, max_len=max_len,
                hidden_dim=256 if on_accel else 64,
                depth=4 if on_accel else 2, n_heads=4, n_kv_heads=2,
                mlp_dim=1024 if on_accel else 256, lora_rank=0)
    params = Llama(**dims).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]

    # prefill-heavy mixed traffic: long prompts, 2 generated tokens —
    # the chunked window calls (where the window kernel lives) dominate
    rng = np.random.default_rng(0)
    plen_hi = 49 if on_accel else 33
    reqs = [(r, rng.integers(1, vocab,
                             size=int(rng.integers(16, plen_hi))
                             ).astype(np.int32), 2)
            for r in range(16)]
    page, chunk = 8, 8
    pages = 1 + slots * ((plen_hi - 1 + 2 - 1) // page + 1)

    def run(paged_kernel: bool):
        eng = DecodeEngine(
            Llama(**dims, kv_page_size=page, kv_pages=pages,
                  paged_kernel=paged_kernel),
            params, max_slots=slots, max_len=max_len,
            steps_per_sync=2, prefill_chunk=chunk)

        def one_pass():
            t0 = time.perf_counter()
            for r in reqs:
                eng.submit(*r)
            while eng.busy:
                eng.step()
            eng.poll()
            dt = time.perf_counter() - t0
            stats = eng.stats_snapshot()
            eng.reset_stats()
            return dt, stats

        one_pass()  # compile/first-touch
        best = float("inf")
        stats = {}
        for _ in range(3):
            dt, stats = one_pass()
            best = min(best, dt)
        return int(stats["prefill_tokens"]) / best, stats

    gather_tps, g_stats = run(False)
    kernel_tps, k_stats = run(True)
    assert g_stats["paged_kernel_mode"] == 0
    assert g_stats["paged_kernel_window_tokens"] == 0
    assert k_stats["paged_kernel_mode"] == 2
    # every prompt token of the pass attended through a window call
    assert (k_stats["paged_kernel_window_tokens"]
            == k_stats["prefill_tokens"] > 0)
    _record(out_path, {
        "stage": "paged_prefill", "backend": backend,
        "gather_prefill_tokens_per_s": gather_tps,
        "kernel_prefill_tokens_per_s": kernel_tps,
        "prefill_tokens_per_s_ratio": kernel_tps / max(gather_tps,
                                                       1e-9),
        "kernel_provenance": ("mosaic" if on_accel
                              else "cpu-fallback-interpret"),
        "prefill_tokens_per_pass": int(k_stats["prefill_tokens"]),
        "window_tokens_per_pass": int(
            k_stats["paged_kernel_window_tokens"]),
        "prefill_calls_per_pass": int(k_stats["prefill_calls"]),
        "kv_pages_high_water": k_stats["kv_pages_high_water"],
        "kv_pages_total": k_stats["kv_pages_total"],
        "requests": len(reqs), "prefill_chunk": chunk,
        "page_size": page, "max_len": max_len, "max_slots": slots})


def _bench_kv_tier(out_path: str) -> None:
    """Two-tier KV capacity at a FIXED HBM page budget (ISSUE 13
    tentpole evidence): the same decode-heavy traffic through the same
    tiny HBM pool, once HBM-only (admission serializes once the pool's
    worst-case reservations are spoken for) and once with the
    pinned-host page tier behind it (cold slots park, their pages
    evict to host, the prefetcher stages them back) — max concurrent
    streams, admission stalls, and tokens/s, with every output checked
    token-exact against an untiered big-pool reference engine. Off-TPU
    the numbers measure the TIERING plane (park/evict/prefetch policy
    + the transfer thread) rather than HBM bandwidth — provenance says
    so; the ≥2× concurrency claim is a policy property that holds
    wherever the page budget, not compute, is the binding constraint."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rafiki_tpu.models.llama_lora import Llama
    from rafiki_tpu.serving.decode_engine import DecodeEngine

    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)
    vocab, max_len, slots, page = 1 << 10, 64, 8, 8
    dims = dict(vocab_size=vocab, max_len=max_len,
                hidden_dim=256 if on_accel else 64,
                depth=4 if on_accel else 2, n_heads=4, n_kv_heads=2,
                mlp_dim=1024 if on_accel else 256, lora_rank=0)
    params = Llama(**dims).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]

    # 16 requests at 2-3 pages worst-case each (~40 pages combined)
    # against 5 usable HBM pages: HBM-only MUST serialize; the host
    # tier must absorb the overflow and fill all 8 slots
    rng = np.random.default_rng(0)
    max_new = 12
    reqs = [(r, rng.integers(1, vocab,
                             size=int(rng.integers(4, 9))
                             ).astype(np.int32), max_new)
            for r in range(16)]
    HBM_PAGES, HOST_PAGES = 6, 64  # 6 pool pages = 5 usable (page 0
    #                                is scratch) + the host tier

    def run(kv_pages: int, host_pages: int):
        eng = DecodeEngine(
            Llama(**dims, kv_page_size=page, kv_pages=kv_pages),
            params, max_slots=slots, max_len=max_len,
            host_kv_pages=host_pages)
        eng.submit("warm", reqs[0][1][:4], 2)  # pay the compiles
        while eng.busy:
            eng.step()
        eng.poll()
        eng.reset_stats()
        t0 = time.perf_counter()
        for r in reqs:
            eng.submit(*r)
        done, steps = {}, 0
        while eng.busy and steps < 5000:
            eng.step()
            steps += 1
            done.update(dict(eng.poll()))
        dt = time.perf_counter() - t0
        return done, dt, eng.stats_snapshot(), steps < 5000

    ref, _dt, _s, ref_ok = run(33, 0)          # untiered big pool
    hbm, hbm_dt, hbm_s, hbm_ok = run(HBM_PAGES, 0)
    tier, tier_dt, tier_s, tier_ok = run(HBM_PAGES, HOST_PAGES)
    drained = ref_ok and hbm_ok and tier_ok \
        and len(hbm) == len(reqs) and len(tier) == len(reqs)
    _record(out_path, {
        "stage": "kv_tier", "backend": backend,
        "provenance": ("mosaic" if on_accel else "cpu-fallback") +
                      "; real DecodeEngine + HostPageTier, tiny model "
                      "— measures the park/evict/prefetch tiering "
                      "plane at a fixed page budget, not HBM bandwidth",
        "requests": len(reqs), "max_new": max_new,
        "max_slots": slots, "page_size": page,
        "hbm_pages_usable": HBM_PAGES - 1, "host_pages": HOST_PAGES,
        "hbm_only_max_concurrent": hbm_s["max_concurrent"],
        "tiered_max_concurrent": tier_s["max_concurrent"],
        "concurrency_ratio": (tier_s["max_concurrent"]
                              / max(hbm_s["max_concurrent"], 1)),
        "hbm_only_admission_stalls": hbm_s["admission_stalls"],
        "tiered_admission_stalls": tier_s["admission_stalls"],
        "hbm_only_tokens_per_s": hbm_s["tokens_generated"] / hbm_dt,
        "tiered_tokens_per_s": tier_s["tokens_generated"] / tier_dt,
        "token_exact_vs_untiered": bool(hbm == ref and tier == ref),
        "admission_deadlocks": 0 if drained else 1,
        "kv_evictions_total": tier_s["kv_evictions_total"],
        "kv_prefetch_hits": tier_s["kv_prefetch_hits"],
        "kv_prefetch_misses": tier_s["kv_prefetch_misses"],
        "kv_transfer_bytes_total": tier_s["kv_transfer_bytes_total"],
        "kv_unparks_total": tier_s["kv_unparks_total"]})


def _bench_disagg_prefill(out_path: str) -> None:
    """Inter-token latency of ACTIVE decode streams while long prompts
    keep arriving, unified vs disaggregated — real workers, real hub
    wire path, tiny LM. In the unified engine every long-prompt
    arrival interleaves its chunked prefill with the decode hot loop
    and the actives' token gaps spike; with the prefill/decode split
    the prefill worker chews the prompt and ships finished KV pages,
    so the decode worker's actives hold their no-arrival baseline.
    The kill leg stops the prefill worker mid-run and asserts every
    stream still completes token-exact (wait window expires → local
    re-prefill), zero dropped/duplicated deltas on the wire."""
    import threading

    import jax
    import jax.numpy as jnp

    from rafiki_tpu.models.llama_lora import LlamaLoRA
    from rafiki_tpu.serving.predictor import Predictor
    from rafiki_tpu.serving.queues import InProcQueueHub
    from rafiki_tpu.store.param_store import ParamStore
    from rafiki_tpu.worker.inference import InferenceWorker

    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)
    knobs = {
        "max_epochs": 1, "vocab_size": 1 << 14,
        "hidden_dim": 512 if on_accel else 64,
        "depth": 8 if on_accel else 2,
        "n_heads": 8 if on_accel else 4, "kv_ratio": 2,
        "lora_rank": 8, "max_len": 128 if on_accel else 32,
        "model_parallel": 1, "learning_rate": 1e-3, "batch_size": 8,
        "bf16": on_accel, "quick_train": True, "share_params": False,
    }
    model = LlamaLoRA(**knobs)
    model._params = model._module().init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    store = ParamStore.from_uri("mem://")
    store.save("bench-lm", model.dump_parameters())

    MAX_NEW = 12
    LONG_TOKS = 80 if on_accel else 18
    # cpu-fallback: the tiny bench model's prompt forward is ~free, so
    # the unified engine's prefill/decode interleave — the phenomenon
    # this stage measures — is invisible in wall time. Dilate prompt
    # compute to a modeled floor (seconds/token, engine knob) so the
    # prefill:decode cost ratio matches a real long-prompt workload;
    # every wire/install/scheduling cost stays real. Off on
    # accelerators (prompts there are genuinely long instead).
    PREFILL_COST_S = 0.0 if on_accel else 0.003
    # single-token prompts: the actives exist to measure DECODE
    # inter-token latency, so their own prompt walk must be empty —
    # a multi-token active prompt re-prefills every closed-loop
    # iteration and its (dilated) chunk cost pollutes the very tail
    # the stage compares across legs
    ACTIVE = ["tok1", "tok2"]
    LONG = [" ".join(f"tok{(i * 3 + j * 5) % 19 + 1}"
                     for i in range(LONG_TOKS)) for j in range(4)]
    DUR = 10.0

    def make_worker(hub, wid, **kw):
        return InferenceWorker(LlamaLoRA, "bench-lm", knobs, store,
                               hub, worker_id=wid, decode_loop=True,
                               max_slots=8, max_new_tokens=MAX_NEW,
                               steps_per_sync=6,
                               kv_page_size=8, kv_pages=33, **kw)

    def p95(xs):
        s = sorted(xs)
        return s[int(0.95 * (len(s) - 1))] if s else 0.0

    finals = {}      # prompt -> list of final texts, across ALL legs
    flock = threading.Lock()
    bad = []         # wire violations (dropped/dup deltas, no final)

    def leg(split: bool, arrivals: bool, kill: bool = False):
        hub = InProcQueueHub()
        dec = make_worker(hub, "w-dec",
                          **({"role": "decode",
                              "kv_wait_s": 0.4 if kill else 2.0}
                             if split else {}))
        workers = [dec]
        pre = None
        if split:
            pre = make_worker(hub, "w-pre", role="prefill")
            workers.append(pre)
        if PREFILL_COST_S:
            for w in workers:
                w.engine.engine.prefill_token_cost_s = PREFILL_COST_S
        threads = [threading.Thread(target=w.run, daemon=True)
                   for w in workers]
        for t in threads:
            t.start()
        try:
            pred = Predictor(hub, [w.worker_id for w in workers],
                             gather_timeout=120.0)
            for _ in range(400):
                if all(hub.get_worker_stats(w.worker_id)
                       for w in workers):
                    break
                time.sleep(0.05)
            pred._refresh_load_signals()

            def consume(p, record):
                acc, last, final = "", None, None
                for e in pred.predict_stream([p]):
                    d = e.get("delta")
                    if d and "0" in d:
                        t = time.monotonic()
                        if record and last is not None:
                            with flock:
                                gaps.append(t - last)
                        last = t
                        acc += d["0"]
                    if e.get("done"):
                        final = e
                if final is None or "predictions" not in final:
                    bad.append((p[:16], "no final"))
                    return
                txt = final["predictions"][0]
                if not txt.startswith(acc):
                    bad.append((p[:16], "delta/final mismatch"))
                with flock:
                    finals.setdefault(p, []).append(txt)

            # pay every compile before the clock starts (one short +
            # one long stream warms prefill, step, and — split — the
            # ship/install path on both workers)
            gaps = []
            consume(ACTIVE[0], False)
            consume(LONG[0], False)
            gaps = []
            stop_at = time.monotonic() + DUR

            def active_client(i):
                while time.monotonic() < stop_at:
                    consume(ACTIVE[i % len(ACTIVE)], True)

            def arrival_client():
                j = 0
                while time.monotonic() < stop_at:
                    if kill and j == 2 and pre is not None:
                        pre.stop()  # mid-run: later legs are never
                        #             served; wait window must expire
                    consume(LONG[j % len(LONG)], False)
                    j += 1
                    time.sleep(0.02)

            cts = [threading.Thread(target=active_client, args=(i,),
                                    daemon=True) for i in range(2)]
            if arrivals:
                cts.append(threading.Thread(target=arrival_client,
                                            daemon=True))
            for c in cts:
                c.start()
            for c in cts:
                c.join(timeout=DUR + 120.0)
            wstats = {w.worker_id: dict(w.stats) for w in workers}
            return p95(gaps), len(gaps), wstats
        finally:
            for w in workers:
                w.stop()
            for t in threads:
                t.join(timeout=15)

    # PAIRED rounds, median of per-round ratios: on a shared-core
    # host the absolute gap quantum wanders ±20% minute to minute —
    # far more than the split-vs-baseline delta this stage resolves.
    # Each round measures all three legs back to back under the same
    # drift, the RATIOS are formed within the round, and the median
    # across rounds drops outlier rounds. Accelerator hosts are
    # quiet; one round suffices there.
    REPS = 1 if on_accel else 5
    rounds = []
    base_n = uni_n = spl_n = 0
    spl_stats = None
    for _ in range(REPS):
        b, bn, _ = leg(split=False, arrivals=False)
        u, un, _ = leg(split=False, arrivals=True)
        s, sn, spl_stats = leg(split=True, arrivals=True)
        rounds.append({"baseline": b, "unified": u, "split": s})
        base_n += bn
        uni_n += un
        spl_n += sn

    def med(vals):
        vals = sorted(vals)
        return vals[len(vals) // 2]

    base_p95 = med([r["baseline"] for r in rounds])
    uni_p95 = med([r["unified"] for r in rounds])
    spl_p95 = med([r["split"] for r in rounds])
    split_ratio = med([r["split"] / max(r["baseline"], 1e-9)
                       for r in rounds])
    unified_ratio = med([r["unified"] / max(r["baseline"], 1e-9)
                         for r in rounds])
    _k_p95, _k_n, kill_stats = leg(split=True, arrivals=True,
                                   kill=True)

    # token-exactness across every topology (greedy → one text per
    # prompt, wherever and however its prefill ran)
    token_exact = bool(finals) and not bad and all(
        len(set(v)) == 1 for v in finals.values())
    _record(out_path, {
        "stage": "disagg_prefill", "backend": backend,
        "provenance": ("mosaic" if on_accel else "cpu-fallback") +
                      "; tiny LM through the REAL engine/hub/predictor "
                      "wire path — measures the phase-split scheduling "
                      "plane (prefill interleaving vs shipped pages), "
                      "not kernels" +
                      ("" if not PREFILL_COST_S else
                       f"; prompt compute dilated to "
                       f"{PREFILL_COST_S * 1e3:g} ms/token (engine "
                       "prefill_token_cost_s) so the tiny model's "
                       "prefill:decode cost ratio matches a real "
                       "long-prompt workload — wire/install/scheduling"
                       " costs are real, all legs equally dilated; "
                       f"p95s are per-leg medians over {REPS} "
                       "interleaved rounds (shared-core host drift)"),
        "prefill_token_cost_s": PREFILL_COST_S,
        "max_new": MAX_NEW, "long_prompt_tokens": LONG_TOKS,
        "leg_duration_s": DUR, "steps_per_sync": 6,
        "rounds": rounds,
        "itl_p95_baseline_s": base_p95,
        "itl_p95_unified_arrivals_s": uni_p95,
        "itl_p95_split_arrivals_s": spl_p95,
        "unified_stall_ratio": unified_ratio,
        "split_ratio": split_ratio,
        "gap_samples": {"baseline": base_n, "unified": uni_n,
                        "split": spl_n},
        "token_exact_across_legs": token_exact,
        "wire_violations": len(bad),
        "split_kv_ships_sent": spl_stats["w-pre"]["kv_ships_sent"],
        "split_kv_imports_installed":
            spl_stats["w-dec"]["kv_imports_installed"],
        "split_kv_import_fallbacks":
            spl_stats["w-dec"]["kv_import_fallbacks"],
        "kill_kv_wait_timeouts":
            kill_stats["w-dec"]["kv_wait_timeouts"],
        "kill_kv_imports_installed":
            kill_stats["w-dec"]["kv_imports_installed"]})


def _bench_metrics_overhead(out_path: str) -> None:
    """Obs-plane overhead on the decode loop (ISSUE 6 tentpole
    evidence): the SAME engine + workload driven once bare (no span
    sink — the pre-obs hot path, since StatsMap writes are always on)
    and once with the full worker-grade instrumentation wired — span
    sink feeding a TraceBuffer + TTFT/e2e/tokens-per-s histograms,
    per-step batch-occupancy observe, periodic registry snapshots (the
    publish cadence). The committed ratio proves the tracing plane
    costs < 2% req/s; the StatsMap's own cost is inside the bare
    number, i.e. the baseline is the shipping configuration."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rafiki_tpu.models.llama_lora import Llama
    from rafiki_tpu.obs import (MetricsRegistry, TraceBuffer,
                                mint_trace_id)
    from rafiki_tpu.serving.decode_engine import DecodeEngine

    backend = jax.default_backend()
    vocab, max_len, slots = 1 << 10, 64, 8
    dims = dict(vocab_size=vocab, max_len=max_len, hidden_dim=256,
                depth=4, n_heads=4, n_kv_heads=2, mlp_dim=1024,
                lora_rank=0)
    module = Llama(**dims)
    params = module.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, 8), jnp.int32))["params"]
    rng = np.random.default_rng(0)
    reqs = [(r, rng.integers(1, vocab,
                             size=int(rng.integers(4, 17))
                             ).astype(np.int32), 6)
            for r in range(32)]

    def build(instrumented: bool):
        eng = DecodeEngine(module, params, max_slots=slots,
                           max_len=max_len, steps_per_sync=4,
                           prefill_chunk=8)
        state = {"eng": eng, "steps": 0}
        if instrumented:
            registry = MetricsRegistry()
            registry.register_stats(eng.stats)
            traces = TraceBuffer(512)
            h_ttft = registry.histogram("ttft_seconds")
            h_e2e = registry.histogram("request_seconds")
            h_tps = registry.histogram(
                "decode_tokens_per_s",
                buckets=(1, 10, 100, 1000, 10000))
            h_occ = registry.histogram(
                "batch_occupancy", buckets=(0, 1, 2, 4, 8, 16))
            req_t0 = {}

            def sink(event, rid, attrs):
                entry = req_t0.get(rid)
                if entry is None:
                    return
                tid, t0 = entry
                now = time.monotonic()
                if event == "admitted":
                    traces.add_span(tid, "admitted", **attrs)
                elif event == "first_token":
                    h_ttft.observe(now - t0)
                    traces.add_span(tid, "first_token")
                elif event == "done":
                    dt = now - t0
                    h_e2e.observe(dt)
                    toks = attrs.get("tokens") or 0
                    if toks and dt > 0:
                        h_tps.observe(toks / dt)
                    traces.add_span(tid, "done", **attrs)
                    req_t0.pop(rid, None)
                else:
                    traces.add_span(tid, event, **attrs)

            eng.span_sink = sink
            state.update(registry=registry, traces=traces,
                         req_t0=req_t0, h_occ=h_occ)
        return state

    def one_pass(state) -> float:
        eng = state["eng"]
        instrumented = "traces" in state
        t0 = time.perf_counter()
        for r in reqs:
            if instrumented:
                tid = mint_trace_id()
                state["traces"].start(tid, request_id=str(r[0]))
                state["req_t0"][(r[0])] = (tid, time.monotonic())
            eng.submit(*r)
        while eng.busy:
            n = eng.step()
            if instrumented:
                state["h_occ"].observe(n)
                state["steps"] += 1
                if state["steps"] % 50 == 0:  # the publish cadence
                    state["registry"].snapshot()
        eng.poll()
        return time.perf_counter() - t0

    bare = build(False)
    inst = build(True)
    # interleaved best-of-3 after a compile/first-touch pass each (the
    # kv_footprint discipline: same-engine back-to-back passes fold
    # scheduler drift into the ratio)
    b_dt = i_dt = float("inf")
    for i in range(4):
        b, ins = one_pass(bare), one_pass(inst)
        if i:
            b_dt, i_dt = min(b_dt, b), min(i_dt, ins)
    b_rps, i_rps = len(reqs) / b_dt, len(reqs) / i_dt
    _record(out_path, {
        "stage": "metrics_overhead", "backend": backend,
        "bare_req_per_s": b_rps, "instrumented_req_per_s": i_rps,
        "req_per_s_ratio": i_rps / max(b_rps, 1e-9),
        "spans_recorded": len(inst["traces"]),
        "ttft_observations": inst["registry"].snapshot().get(
            "ttft_seconds_count", 0),
        "requests": len(reqs), "max_len": max_len,
        "max_slots": slots})


def _bench_advisor(out_path: str, n_trials: int) -> None:
    import tempfile

    import jax

    from rafiki_tpu.data import generate_image_classification_dataset
    from rafiki_tpu.model import tune_model
    from rafiki_tpu.models.mlp import JaxFeedForward

    with tempfile.TemporaryDirectory() as d:
        tr, va = f"{d}/tr.npz", f"{d}/va.npz"
        generate_image_classification_dataset(tr, 512, seed=0)
        generate_image_classification_dataset(va, 128, seed=1)
        # one throwaway trial pays the first-compile cost
        tune_model(JaxFeedForward, tr, va, total_trials=1,
                   advisor_type="random")
        t0 = time.monotonic()
        res = tune_model(JaxFeedForward, tr, va, total_trials=n_trials,
                         advisor_type="bayes_gp")
        dt = time.monotonic() - t0
    _record(out_path, {
        "stage": "advisor", "backend": jax.default_backend(),
        "trials_per_hour": n_trials / dt * 3600.0,
        "n_trials": n_trials, "best_score": res.best_score,
    })


#: trials/hour of the sequential advisor stage as committed by the
#: round that measured it (`advisor_trials_per_hour`, cpu fallback) —
#: the denominator ISSUE 8's ≥10× gang target is defined against
_SEQ_ADVISOR_BASELINE_TPH = 892.0


def _bench_advisor_gang(out_path: str) -> None:
    """Gang-compiled trials/hour on the MLP template vs the sequential
    892/h baseline. Apples-to-apples: the random advisor (every trial a
    full-budget train, same dataset sizes as the sequential stage) with
    the shape knobs pinned so all lanes share one static bucket; a
    fresh 4-trial sequential sample is timed alongside as an on-rig
    denominator next to the committed baseline."""
    import tempfile

    import jax

    from rafiki_tpu.advisor import make_advisor
    from rafiki_tpu.data import generate_image_classification_dataset
    from rafiki_tpu.model import tune_model
    from rafiki_tpu.models.mlp import JaxFeedForward
    from rafiki_tpu.tuning import GangEngine

    backend = jax.default_backend()
    gang_size = 16
    n_trials = 64
    pins = {"hidden_layer_count": 2, "hidden_layer_units": 64,
            "batch_size": 64}
    with tempfile.TemporaryDirectory() as d:
        tr, va = f"{d}/tr.npz", f"{d}/va.npz"
        generate_image_classification_dataset(tr, 512, seed=0)
        generate_image_classification_dataset(va, 128, seed=1)
        seq_n = 4
        t0 = time.monotonic()
        tune_model(JaxFeedForward, tr, va, total_trials=seq_n,
                   advisor_type="random", seed=1, knob_overrides=pins)
        seq_tph = seq_n / (time.monotonic() - t0) * 3600.0
        adv = make_advisor(JaxFeedForward.get_knob_config(), "random",
                           total_trials=n_trials, seed=0)
        eng = GangEngine(JaxFeedForward, adv, tr, va,
                         gang_size=gang_size, mode="gang",
                         knob_overrides=pins)
        t0 = time.monotonic()
        results = eng.run()
        dt = time.monotonic() - t0
    tph = len(results) / dt * 3600.0
    best = adv.best_effort
    _record(out_path, {
        "stage": "advisor_gang", "backend": backend,
        "gang_size": gang_size, "n_trials": len(results),
        "search_s": dt, "trials_per_hour": tph,
        "baseline_trials_per_hour": _SEQ_ADVISOR_BASELINE_TPH,
        "speedup_vs_baseline": tph / _SEQ_ADVISOR_BASELINE_TPH,
        "seq_sample_trials_per_hour": seq_tph,
        "speedup_vs_seq_sample": tph / max(seq_tph, 1e-9),
        "static_buckets": eng.n_buckets,
        "compiles": sum(eng.compile_counts().values()),
        "best_score": float(best.score) if best else -1.0})


def _bench_gang_lora(out_path: str) -> None:
    """Gang-compiled LoRA lanes on the Llama template: K adapter sets
    vmapped over ONE frozen broadcast base vs the timed sequential
    baseline (same knobs, same dataset, per-trial compile). Records
    trials/hour for both (target: >= 3x), the compile count (one per
    static bucket, not per trial), aggregate training tokens/s across
    lanes, and the overlap-knob provenance: on CPU
    ``overlap_compiler_options`` is {} by design, so a CPU-fallback run
    is compile-neutral and says so."""
    import tempfile

    import jax

    from rafiki_tpu.advisor import make_advisor
    from rafiki_tpu.data import generate_text_classification_dataset
    from rafiki_tpu.model import tune_model
    from rafiki_tpu.models.llama_lora import LlamaLoRA
    from rafiki_tpu.parallel.sharding import overlap_compiler_options
    from rafiki_tpu.tuning import GangEngine

    backend = jax.default_backend()
    gang_size = 4
    n_trials = 16
    pins = {"hidden_dim": 64, "depth": 2, "n_heads": 4, "kv_ratio": 2,
            "lora_rank": 4, "max_len": 32, "batch_size": 16,
            "model_parallel": 1, "sequence_parallel": 1,
            "pipeline_stages": 1, "grad_accum": 1, "loss_chunk": 0,
            "pretrained_path": "", "tokenizer_path": "",
            "rope_scaling": "", "rope_theta": 10000.0,
            "remat": False, "remat_policy": "none",
            "overlap_collectives": False, "bf16": False,
            "quantize_int8": False, "kv_cache_int8": False,
            "adapters_only": True, "quick_train": True}
    with tempfile.TemporaryDirectory() as d:
        tr, va = f"{d}/tr.jsonl", f"{d}/va.jsonl"
        # LoRA tuning's short-trial regime: with adapters_only +
        # quick_train a trial is a handful of steps, so per-trial
        # setup + compile dominates the sequential path — exactly the
        # overhead gang lanes amortize
        generate_text_classification_dataset(tr, 48, seed=0)
        generate_text_classification_dataset(va, 32, seed=1)
        seq_n = 2
        t0 = time.monotonic()
        tune_model(LlamaLoRA, tr, va, total_trials=seq_n,
                   advisor_type="random", seed=1, knob_overrides=pins)
        seq_tph = seq_n / (time.monotonic() - t0) * 3600.0
        adv = make_advisor(LlamaLoRA.get_knob_config(), "random",
                           total_trials=n_trials, seed=0)
        eng = GangEngine(LlamaLoRA, adv, tr, va, gang_size=gang_size,
                         mode="gang", knob_overrides=pins)
        t0 = time.monotonic()
        results = eng.run()
        dt = time.monotonic() - t0
    tph = len(results) / dt * 3600.0
    # engine samples are summed lane-samples per round; every sample
    # contributes max_len training tokens
    tokens = int(eng.stats["samples"]) * int(pins["max_len"])
    best = adv.best_effort
    _record(out_path, {
        "stage": "gang_lora", "backend": backend,
        "gang_size": gang_size, "n_trials": len(results),
        "search_s": dt, "trials_per_hour": tph,
        "seq_sample_trials_per_hour": seq_tph,
        "speedup_vs_seq_sample": tph / max(seq_tph, 1e-9),
        "static_buckets": eng.n_buckets,
        "compiles": sum(eng.compile_counts().values()),
        "aggregate_tokens_per_s": tokens / max(dt, 1e-9),
        # provenance: the overlap knob's XLA options are TPU-only; on
        # CPU the schedule is compile-neutral by construction
        "overlap_options_applied": bool(overlap_compiler_options(True)),
        "best_score": float(best.score) if best else -1.0})


def _bench_failover(out_path: str) -> None:
    """Kill one worker mid-stream under load and measure what the
    client experiences: the stream-gap (longest silence between
    delivered events, covering detection + re-scatter + prefix
    re-ingest) and zero-token-loss (streamed deltas + final text
    exactly equal a no-fault reference run)."""
    import threading

    import jax

    from rafiki_tpu.chaos import ChaosConfig, ChaosInjector
    from rafiki_tpu.models.llama_lora import LlamaLoRA
    from rafiki_tpu.serving.predictor import Predictor
    from rafiki_tpu.serving.queues import InProcQueueHub
    from rafiki_tpu.store.param_store import ParamStore
    from rafiki_tpu.worker.inference import InferenceWorker

    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)
    knobs = {
        "max_epochs": 1, "vocab_size": 1 << 14,
        "hidden_dim": 256 if on_accel else 64,
        "depth": 4 if on_accel else 2,
        "n_heads": 8 if on_accel else 4, "kv_ratio": 2,
        "lora_rank": 8, "max_len": 64 if on_accel else 32,
        "model_parallel": 1, "learning_rate": 1e-3, "batch_size": 8,
        "bf16": on_accel, "quick_train": True, "share_params": False,
    }
    # a REAL quick-trained trial, not an init-dump: prefix re-ingestion
    # round-trips through the tokenizer's learned id↔token table, which
    # an untrained dump does not populate (its <id> renderings are
    # one-way — no production trial serves untrained)
    import tempfile

    from rafiki_tpu.data import generate_text_classification_dataset

    model = LlamaLoRA(**knobs)
    with tempfile.TemporaryDirectory() as d:
        tr = f"{d}/train.jsonl"
        generate_text_classification_dataset(tr, 64, seed=0)
        model.train(tr)
    store = ParamStore.from_uri("mem://")
    store.save("trial-lm", model.dump_parameters())
    max_new = 24 if on_accel else 12
    kill_after = max_new // 2
    prompt = "tok1 tok2 tok3"

    def boot(hub, wid, **kw):
        w = InferenceWorker(LlamaLoRA, "trial-lm", knobs, store, hub,
                            worker_id=wid, decode_loop=True,
                            max_slots=8, max_new_tokens=max_new, **kw)
        th = threading.Thread(target=w.run, daemon=True)
        th.start()
        return w, th

    def run_stream(pred):
        events, times = [], []
        for ev in pred.predict_stream([prompt], timeout=120.0):
            events.append(ev)
            times.append(time.monotonic())
        acc = "".join(v for e in events[:-1]
                      for v in e.get("delta", {}).values())
        return events[-1], acc, times

    # no-fault reference
    hub = InProcQueueHub()
    ref, ref_t = boot(hub, "ref")
    final, ref_acc, _ = run_stream(
        Predictor(hub, ["ref"], gather_timeout=120.0))
    expected = final["predictions"][0]
    ref.stop()
    ref_t.join(timeout=30)

    # faulty fleet under background unary load
    hub = InProcQueueHub()
    chaos = ChaosInjector(ChaosConfig(kill_after_tokens=kill_after))
    w0, t0_ = boot(hub, "w0", steps_per_sync=1, chaos=chaos)
    w1, t1_ = boot(hub, "w1")
    pred = Predictor(hub, ["w0", "w1"], gather_timeout=120.0,
                     stream_silence_timeout_s=1.0,
                     breaker_fail_threshold=1)
    stop_load = threading.Event()

    def load_client():
        while not stop_load.is_set():
            try:
                pred.predict([prompt], timeout=5.0)
            except Exception:  # noqa: BLE001 — load gen best-effort
                pass

    loaders = [threading.Thread(target=load_client, daemon=True)
               for _ in range(2)]
    for th in loaders:
        th.start()
    try:
        t_start = time.monotonic()
        final, acc, times = run_stream(pred)
        dt = time.monotonic() - t_start
    finally:
        stop_load.set()
        for th in loaders:
            th.join(timeout=10)
        w1.stop()
        t1_.join(timeout=30)
        t0_.join(timeout=30)

    gaps = [b - a for a, b in zip(times, times[1:])]
    _record(out_path, {
        "stage": "failover", "backend": backend,
        "zero_token_loss": bool(
            final.get("predictions") == [expected]
            and acc == ref_acc == expected),
        "stream_gap_s": max(gaps) if gaps else dt,
        "stream_total_s": dt,
        "failovers": int(final.get("info", {}).get("failovers", -1)),
        "silence_timeout_s": 1.0, "kill_after_tokens": kill_after,
        "max_new": max_new,
        "breaker_trips": int(
            pred.breakers.counters["breaker_trips"]),
    })


def _bench_scaleout(out_path: str) -> None:
    """1 vs N=3 workers under shared-prefix + mixed stream traffic,
    then a full membership cycle (autoscale-up, drain-based
    scale-down, rolling restart) under load — on the deterministic
    capacity-model harness (``rafiki_tpu.chaos.scaleout``): per-step
    cost = base + per_req × live, so capacity genuinely scales with
    engines the way separate accelerators do. The numbers measure the
    ROUTING/SCALING plane (placement, affinity, zero-loss membership
    changes), never kernels — provenance says so explicitly."""
    import jax

    from rafiki_tpu.chaos.scaleout import (ScaleoutHarness,
                                           shared_prefix_prompts)

    MAX_NEW = 20
    KW = dict(max_slots=8, max_new=MAX_NEW, base_step_s=0.001,
              per_req_step_s=0.002, stream_silence_timeout_s=10.0)

    # leg 1: one worker, saturating shared-prefix load
    h1 = ScaleoutHarness(1, **KW)
    try:
        single = h1.run_load(shared_prefix_prompts(6, 3), n_clients=18,
                             streams_per_client=2, timeout=120.0)
    finally:
        h1.stop()

    # leg 2: three workers, shared-prefix families balanced by the
    # real HRW map (2 per worker) + per-family user-turn mix
    h3 = ScaleoutHarness(3, **KW)
    try:
        fams: dict = {w: [] for w in h3.workers}
        g = 0
        while any(len(v) < 2 for v in fams.values()) and g < 500:
            fam = f"fam{g:03d}-" * 12
            owner = h3.pred.router.owner(fam[:64])
            if len(fams[owner]) < 2:
                fams[owner].append(fam)
            g += 1
        prompts3 = [f"{p} user question {j}"
                    for v in fams.values() for p in v for j in range(3)]
        scaled = h3.run_load(prompts3, n_clients=18,
                             streams_per_client=2, timeout=120.0)
        snap = h3.pred.router.snapshot()
    finally:
        h3.stop()

    # leg 3: membership cycle under load — zero dropped/dup tokens
    hc = ScaleoutHarness(2, **KW)
    try:
        events = []

        def cycle():
            wid = hc.add_worker()
            events.append("up")
            time.sleep(0.3)
            victim = [w for w in hc.workers if w != wid][0]
            hc.drain_worker(victim)
            events.append("down")
            time.sleep(0.2)
            hc.rolling_restart()
            events.append("rolling_restart")

        cyc = hc.run_load(shared_prefix_prompts(4, 3), n_clients=8,
                          streams_per_client=6, timeout=120.0,
                          on_half_done=cycle)
    finally:
        hc.stop()

    _record(out_path, {
        "stage": "scaleout", "backend": jax.default_backend(),
        "provenance": "cpu-fallback; simulated decode capacity (stub "
                      "engine, base+per_req step-time model) — "
                      "measures the routing/scaling plane, not "
                      "kernels",
        "workers": 3, "max_slots": 8, "max_new": MAX_NEW,
        "single_tokens_per_s": single["tokens_per_s"],
        "scaled_tokens_per_s": scaled["tokens_per_s"],
        "throughput_ratio": (scaled["tokens_per_s"]
                             / max(single["tokens_per_s"], 1e-9)),
        "single_ttft_p95_s": single["ttft_p95_s"],
        "scaled_ttft_p95_s": scaled["ttft_p95_s"],
        "affinity_hit_rate": snap["affinity_hit_rate"],
        "single_zero_token_loss": single["ok"],
        "scaled_zero_token_loss": scaled["ok"],
        "cycle_zero_token_loss": cyc["ok"],
        "cycle_streams": cyc["streams"],
        "cycle_failovers": cyc["failovers"],
        "cycle_events": events})


def _bench_slo_overload(out_path: str) -> None:
    """Mixed-traffic overload on ONE replica (the deterministic
    capacity-model harness, ``rafiki_tpu.chaos.sloload``): interactive
    TTFT p95 unloaded vs under sustained interactive + batch +
    background pressure with class-aware admission, preemption, aging,
    and predictor-side shedding all live. The committed numbers prove
    the POLICY plane — the p95 hold ratio, zero-loss preempt-resume
    (hard string property of the stub token function), background shed
    with structured retry hints — never kernels; provenance says so."""
    import jax

    from rafiki_tpu.chaos.sloload import SloLoadHarness

    KW = dict(max_slots=4, max_new=12, base_step_s=0.002,
              per_req_step_s=0.005, stream_silence_timeout_s=10.0,
              pool_id="slobench")
    # interactive with think-time gaps between a client's streams: the
    # troughs are what best-effort legitimately fills (and what makes
    # the returning wave exercise preemption). 8 clients on 4 slots
    # put the unloaded baseline well above the fused-step quantum
    # (own-class queueing), so the ratio measures the policy rather
    # than step-boundary rounding.
    IA = {"clients": 8, "streams": 3, "max_new": 4, "think_s": 0.15}
    h = SloLoadHarness(1, shed_depths={"background": 2, "batch": 64},
                       **KW)
    try:
        base = h.run_mixed({"interactive": dict(IA)}, timeout=60.0)
        base.pop("_wall_s")
        mixed = h.run_mixed({
            "interactive": dict(IA),
            "batch": {"clients": 2, "streams": 2, "max_new": 12},
            "background": {"clients": 8, "streams": 3, "max_new": 12,
                           "think_s": 0.05}}, timeout=120.0)
        wall = mixed.pop("_wall_s")
        stats = list(h.engine_stats().values())[0]
        slo_health = h.pred.stats()["slo"]
    finally:
        h.stop()

    ia, bt, bg = (mixed["interactive"], mixed["batch"],
                  mixed["background"])
    unloaded = base["interactive"]["ttft_p95_s"]
    _record(out_path, {
        "stage": "slo_overload", "backend": jax.default_backend(),
        "provenance": "cpu-fallback; simulated decode capacity (stub "
                      "engine, base+per_req step-time model) — "
                      "measures the SLO admission/preemption/shed "
                      "plane, not kernels",
        "max_slots": 4, "max_new": 12,
        # TTFT here is quantized in fused-step units: ratios in
        # [1, 1.5] are within one quantum of parity — read the p95s
        # against this, not as a continuous measurement
        "step_quantum_s": (KW["base_step_s"]
                           + KW["per_req_step_s"] * KW["max_slots"]),
        "interactive_ttft_p95_unloaded_s": unloaded,
        "interactive_ttft_p95_loaded_s": ia["ttft_p95_s"],
        "interactive_p95_ratio": (ia["ttft_p95_s"]
                                  / max(unloaded, 1e-9)),
        "interactive_streams": ia["streams"],
        "interactive_shed": ia["shed"],
        "interactive_zero_token_loss": (ia["ok"]
                                        and base["interactive"]["ok"]),
        "batch_zero_token_loss": bt["ok"],
        "background_zero_token_loss": bg["ok"],
        "preemptions": stats["preemptions"],
        "aged_promotions": stats["slo_aged_promotions"],
        "batch_served": bt["served"],
        "background_served": bg["served"],
        "background_shed": bg["shed"],
        "background_shed_with_retry_hint": bg["shed_with_retry_hint"],
        "batch_tokens_per_s": bt["tokens_per_s"],
        "background_tokens_per_s": bg["tokens_per_s"],
        "brownout_stage_final": slo_health["brownout"]["stage"],
        "requests_shed_total": slo_health["requests_shed"],
        "wall_s": wall})


def _bench_admin_recovery(out_path: str) -> None:
    """kill -9 a REAL control-plane process under streaming load,
    restart it against the same workdir, and measure what matters:
    time-to-reconverge (second boot → full re-adoption, including the
    lease-TTL wait) and the load the DATA PLANE dropped during the
    control plane's death (target: zero — the kvd and every worker
    survive and are adopted, so streams never notice)."""
    import os
    import signal
    import subprocess
    import tempfile
    import threading

    from rafiki_tpu.native.client import KVClient

    workdir = tempfile.mkdtemp(prefix="bench_admin_recovery_")
    lease_ttl = 3.0
    n_services = 4

    def start_driver(mode: str, ready: str) -> subprocess.Popen:
        cfg = {"workdir": workdir, "db_path": f"{workdir}/meta.db",
               "n_services": n_services, "mode": mode,
               "ready_file": f"{workdir}/{ready}",
               "lease_ttl_s": lease_ttl}
        path = f"{workdir}/{ready}.cfg.json"
        with open(path, "w") as f:
            json.dump(cfg, f)
        return subprocess.Popen(
            [sys.executable, "-m", "rafiki_tpu.chaos.control_driver",
             "--config", path],
            env=dict(os.environ, JAX_PLATFORMS="cpu"))

    def wait_ready(name: str, proc: subprocess.Popen,
                   timeout: float = 120.0) -> dict:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if os.path.exists(f"{workdir}/{name}"):
                with open(f"{workdir}/{name}") as f:
                    return json.load(f)
            if proc.poll() is not None:
                raise RuntimeError(f"driver died rc={proc.returncode}")
            time.sleep(0.05)
        raise TimeoutError(name)

    p1 = start_driver("boot", "r1.json")
    r1 = wait_ready("r1.json", p1)

    # streaming load over the kvd queues: sequence-numbered round
    # trips; any missing seq = a dropped message, gaps in the round-
    # trip timeline = data-plane unavailability
    stop_load = threading.Event()
    sent, got, times = [], [], []

    def load() -> None:
        cli = KVClient("127.0.0.1", int(r1["kv_port"]))
        seq = 0
        while not stop_load.is_set():
            try:
                cli.rpush("bench:stream", str(seq).encode())
                sent.append(seq)
                out = cli.brpop("bench:stream", timeout=2.0)
                if out is not None:
                    got.append(int(out[1]))
                    times.append(time.monotonic())
                seq += 1
                time.sleep(0.005)
            except OSError:
                time.sleep(0.05)  # transport gap — shows up as a
                # round-trip gap in `times`, which is the measurement

    loader = threading.Thread(target=load, daemon=True)
    loader.start()
    time.sleep(1.0)  # steady-state load before the kill

    t_kill = time.monotonic()
    os.kill(p1.pid, signal.SIGKILL)
    p1.wait()
    p2 = start_driver("reconcile", "r2.json")
    try:
        r2 = wait_ready("r2.json", p2)
        reconverge_s = time.monotonic() - t_kill
        time.sleep(1.0)  # load continues after recovery
        stop_load.set()
        loader.join(timeout=10)
        gaps = [b - a for a, b in zip(times, times[1:])]
        _record(out_path, {
            "stage": "admin_recovery", "backend": "cpu",
            "reconverge_s": round(reconverge_s, 3),
            "lease_ttl_s": lease_ttl,
            "driver_boot_s": r2.get("boot_s"),
            "services_expected": n_services,
            "services_adopted": r2.get("services_adopted"),
            "kv_adopted": r2.get("kv_adopted"),
            "adopted_pids_match": sorted(r2.get("adopted_pids") or [])
            == sorted(r1.get("spawned_pids") or []),
            "lease_generation": r2.get("lease_generation"),
            "stream_msgs": len(sent),
            "dropped_stream_msgs": len(set(sent[:-1]) - set(got)),
            "stream_max_gap_s": round(max(gaps), 3) if gaps else None,
        })
    finally:
        p2.terminate()
        try:
            p2.wait(timeout=30)
        except subprocess.TimeoutExpired:
            p2.kill()
        # p1 was SIGKILLed by design, orphaning its kvd + dummies; p2
        # normally adopts-then-stops them, but if the reconcile leg
        # failed they would outlive the bench — sweep them from the
        # MetaStore rows (identity-gated) like `stack stop` does, then
        # drop the scratch workdir
        try:
            import shutil
            from pathlib import Path

            from rafiki_tpu.admin.stack import _reap_orphans

            _reap_orphans(Path(workdir))
            shutil.rmtree(workdir, ignore_errors=True)
        except Exception as e:  # noqa: BLE001 — cleanup best-effort
            print(f"admin_recovery cleanup failed: {e!r}",
                  file=sys.stderr)


def _bench_kvd_recovery(out_path: str) -> None:
    """kill -9 the kvd DATA PLANE under streaming + blob-write load,
    let the admin's supervisor respawn it on the same port with WAL
    replay, and measure what matters: time-to-reconverge (kill → first
    successful round-trip on the respawned server), message loss
    (target: zero — dedup-id pushes + WAL replay), double delivery
    (target: zero — the dedup recent-set survives the crash), and
    durable-blob integrity through the outage."""
    import os
    import shutil
    import signal
    import tempfile
    import threading

    from rafiki_tpu.admin.services_manager import ServicesManager
    from rafiki_tpu.native.client import KVClient
    from rafiki_tpu.parallel.mesh import DeviceSpec
    from rafiki_tpu.store.meta_store import MetaStore

    workdir = tempfile.mkdtemp(prefix="bench_kvd_recovery_")
    meta = MetaStore(f"{workdir}/meta.db")
    mgr = ServicesManager(meta, workdir, slot_size=1, platform="cpu",
                          devices=[DeviceSpec(id=0)])
    try:
        mgr.start_data_plane()
        host, port = mgr.kv_host, mgr.kv_port
        kv_pid = mgr._kv_proc.pid

        stop = threading.Event()
        sent, got, times = [], [], []
        blobs: dict = {}

        def stream_load() -> None:
            # sequence-numbered dedup-push → blocking-pop round trips:
            # a missing seq = a dropped message, a repeated seq = a
            # double delivery, gaps in `times` = plane unavailability
            cli = KVClient(host, port, retry_window_s=20.0)
            seq = 0
            while not stop.is_set():
                try:
                    cli.lpush_dedup("bench:stream", f"s{seq}",
                                    str(seq).encode())
                    sent.append(seq)
                    out = cli.brpop("bench:stream", timeout=5.0)
                    if out is not None:
                        got.append(int(out[1]))
                        times.append(time.monotonic())
                    seq += 1
                    time.sleep(0.004)
                except (ConnectionError, OSError):
                    time.sleep(0.05)  # window exhausted: retry; shows
                    # up as a round-trip gap, which is the measurement

        def blob_load() -> None:
            # the train-side pattern: durable param blobs written
            # straight through the outage (SET retries transparently)
            cli = KVClient(host, port, retry_window_s=20.0)
            i = 0
            while not stop.is_set():
                key = f"params:bench-{i % 32}"
                val = (b"%06d" % i) * 256
                try:
                    cli.set(key, val)
                    blobs[key] = val
                    i += 1
                except (ConnectionError, OSError):
                    pass  # unacked write: not in `blobs`, not owed
                time.sleep(0.01)

        loaders = [threading.Thread(target=stream_load, daemon=True),
                   threading.Thread(target=blob_load, daemon=True)]
        for th in loaders:
            th.start()
        time.sleep(1.0)  # steady-state load before the kill

        t_kill = time.monotonic()
        os.kill(kv_pid, signal.SIGKILL)
        # the supervisor: the admin monitor's poll tick. Deadline-
        # bounded: a respawn path that goes degraded (port grabbed,
        # poisoned data dir) must record a stage error, not hang the
        # whole bench run
        while mgr.recovery["kvd_respawns"] < 1:
            if time.monotonic() - t_kill > 30.0:
                raise RuntimeError(
                    "kvd never respawned within 30s "
                    f"(degraded={mgr.degraded_jobs()})")
            mgr.poll()
            time.sleep(0.02)
        respawn_s = time.monotonic() - t_kill
        assert mgr.kv_port == port  # same address, clients reconnect

        time.sleep(1.5)  # load continues against the respawned kvd
        stop.set()
        for th in loaders:
            th.join(timeout=30)
        after = [t for t in times if t > t_kill]
        reconverge_s = (after[0] - t_kill) if after else None
        gaps = [b - a for a, b in zip(times, times[1:])]

        blob_losses = 0
        check = KVClient(host, port)
        for key, val in blobs.items():
            if check.get(key) != val:
                blob_losses += 1
        stats = check.stats()
        _record(out_path, {
            "stage": "kvd_recovery", "backend": "cpu",
            "provenance": "cpu fallback — measures the supervision/"
                          "replay/reconnect plane, not kernels",
            "respawn_s": round(respawn_s, 3),
            "reconverge_s": (round(reconverge_s, 3)
                             if reconverge_s is not None else None),
            "replay_seconds": stats.get("replay_seconds"),
            "replayed_records": stats.get("replayed_records"),
            "wal_bytes": stats.get("wal_bytes"),
            "stream_msgs": len(sent),
            "dropped_stream_msgs": len(set(sent[:-1]) - set(got)),
            "double_delivered_msgs": len(got) - len(set(got)),
            "stream_max_gap_s": round(max(gaps), 3) if gaps else None,
            "blobs_written": len(blobs),
            "blob_losses": blob_losses,
        })
    finally:
        try:
            mgr.stop_all()
        except Exception as e:  # noqa: BLE001 — cleanup best-effort
            print(f"kvd_recovery cleanup failed: {e!r}",
                  file=sys.stderr)
        shutil.rmtree(workdir, ignore_errors=True)


def _child(out_path: str, budget: float, use_kv: bool) -> None:
    t_start = time.monotonic()

    from rafiki_tpu.utils.platform import apply_platform_env

    apply_platform_env()

    import jax

    jax.devices()  # force backend init inside the child's budget
    _record(out_path, {"stage": "probe", "backend": jax.default_backend()})

    if _want("predictor"):
        try:
            _bench_predictor(out_path, use_kv,
                             duration=min(20.0, budget / 8.0))
        except Exception as e:  # noqa: BLE001
            _record(out_path, {"stage": "predictor_error",
                               "error": repr(e)[:300]})

    if _want("generation") and \
            budget - (time.monotonic() - t_start) > 90:
        try:
            _bench_generation(out_path, duration=min(20.0, budget / 8.0))
        except Exception as e:  # noqa: BLE001
            _record(out_path, {"stage": "generation_error",
                               "error": repr(e)[:300]})

    if _want("small_draft") and \
            budget - (time.monotonic() - t_start) > 120:
        try:
            _bench_small_draft_spec(out_path)
        except Exception as e:  # noqa: BLE001
            _record(out_path, {"stage": "small_draft_error",
                               "error": repr(e)[:300]})

    if _want("kv_footprint") and \
            budget - (time.monotonic() - t_start) > 60:
        try:
            _bench_kv_footprint(out_path)
        except Exception as e:  # noqa: BLE001
            _record(out_path, {"stage": "kv_footprint_error",
                               "error": repr(e)[:300]})

    if _want("paged_decode") and \
            budget - (time.monotonic() - t_start) > 60:
        try:
            _bench_paged_decode(out_path)
        except Exception as e:  # noqa: BLE001
            _record(out_path, {"stage": "paged_decode_error",
                               "error": repr(e)[:300]})

    if _want("paged_prefill") and \
            budget - (time.monotonic() - t_start) > 60:
        try:
            _bench_paged_prefill(out_path)
        except Exception as e:  # noqa: BLE001
            _record(out_path, {"stage": "paged_prefill_error",
                               "error": repr(e)[:300]})

    if _want("kv_tier") and \
            budget - (time.monotonic() - t_start) > 60:
        try:
            _bench_kv_tier(out_path)
        except Exception as e:  # noqa: BLE001
            _record(out_path, {"stage": "kv_tier_error",
                               "error": repr(e)[:300]})

    if _want("disagg_prefill") and \
            budget - (time.monotonic() - t_start) > 90:
        try:
            _bench_disagg_prefill(out_path)
        except Exception as e:  # noqa: BLE001
            _record(out_path, {"stage": "disagg_prefill_error",
                               "error": repr(e)[:300]})

    if _want("metrics_overhead") and \
            budget - (time.monotonic() - t_start) > 60:
        try:
            _bench_metrics_overhead(out_path)
        except Exception as e:  # noqa: BLE001
            _record(out_path, {"stage": "metrics_overhead_error",
                               "error": repr(e)[:300]})

    if _want("advisor") and \
            budget - (time.monotonic() - t_start) > 60:
        try:
            _bench_advisor(out_path, n_trials=6)
        except Exception as e:  # noqa: BLE001
            _record(out_path, {"stage": "advisor_error",
                               "error": repr(e)[:300]})

    if _want("advisor_gang") and \
            budget - (time.monotonic() - t_start) > 60:
        try:
            _bench_advisor_gang(out_path)
        except Exception as e:  # noqa: BLE001
            _record(out_path, {"stage": "advisor_gang_error",
                               "error": repr(e)[:300]})

    if _want("gang_lora") and \
            budget - (time.monotonic() - t_start) > 60:
        try:
            _bench_gang_lora(out_path)
        except Exception as e:  # noqa: BLE001
            _record(out_path, {"stage": "gang_lora_error",
                               "error": repr(e)[:300]})

    if _want("failover") and \
            budget - (time.monotonic() - t_start) > 60:
        try:
            _bench_failover(out_path)
        except Exception as e:  # noqa: BLE001
            _record(out_path, {"stage": "failover_error",
                               "error": repr(e)[:300]})

    if _want("scaleout") and \
            budget - (time.monotonic() - t_start) > 45:
        try:
            _bench_scaleout(out_path)
        except Exception as e:  # noqa: BLE001
            _record(out_path, {"stage": "scaleout_error",
                               "error": repr(e)[:300]})

    if _want("slo_overload") and \
            budget - (time.monotonic() - t_start) > 40:
        try:
            _bench_slo_overload(out_path)
        except Exception as e:  # noqa: BLE001
            _record(out_path, {"stage": "slo_overload_error",
                               "error": repr(e)[:300]})

    if _want("admin_recovery") and \
            budget - (time.monotonic() - t_start) > 30:
        try:
            _bench_admin_recovery(out_path)
        except Exception as e:  # noqa: BLE001
            _record(out_path, {"stage": "admin_recovery_error",
                               "error": repr(e)[:300]})

    if _want("kvd_recovery") and \
            budget - (time.monotonic() - t_start) > 20:
        try:
            _bench_kvd_recovery(out_path)
        except Exception as e:  # noqa: BLE001
            _record(out_path, {"stage": "kvd_recovery_error",
                               "error": repr(e)[:300]})

    if _want("stream_search") and \
            budget - (time.monotonic() - t_start) > 120:
        try:
            _bench_stream_search(out_path)
        except Exception as e:  # noqa: BLE001
            _record(out_path, {"stage": "stream_error",
                               "error": repr(e)[:300]})
    _record(out_path, {"stage": "done"})


def _bench_stream_search(out_path: str) -> None:
    """BASELINE config #2 slice: BOHB search over ResNet shapes fed by
    the STREAMING loader (constant-memory zip reads + augmentation) —
    loader throughput and search outcome in one stage."""
    import os
    import tempfile

    import jax

    from rafiki_tpu.data.stream import (StreamingImageDataset,
                                        generate_streaming_image_zip)
    from rafiki_tpu.model import tune_model
    from rafiki_tpu.models.resnet import ResNetClassifier

    from rafiki_tpu.advisor import make_advisor
    from rafiki_tpu.worker.train import TrainWorker

    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)
    n_imgs = 4096 if on_accel else 768
    with tempfile.TemporaryDirectory() as d:
        tr = f"{d}/train.zip"
        va = f"{d}/val.zip"
        generate_streaming_image_zip(tr, n_imgs, image_shape=(32, 32, 3),
                                     n_classes=4, seed=0)
        generate_streaming_image_zip(va, 256, image_shape=(32, 32, 3),
                                     n_classes=4, seed=1)

        # raw loader throughput first (decode + augment, 4 workers)
        sds = StreamingImageDataset(tr)
        t0 = time.monotonic()
        n = sum(int(b["mask"].sum())
                for b in sds.iter_batches(128, augment=True))
        img_per_s = n / (time.monotonic() - t0)

        # BOHB over ResNet with the shape knobs pinned to the bench
        # budget (knob_overrides — the job-level pin mechanism); rung
        # scheduling and the streaming feed are what's measured
        n_trials = 3
        advisor = make_advisor(ResNetClassifier.get_knob_config(),
                               "bohb", total_trials=n_trials, seed=0)
        worker = TrainWorker(
            ResNetClassifier, advisor, tr, va,
            knob_overrides={
                "variant": "resnet18",
                "width_mult": 1.0 if on_accel else 0.25,
                "batch_size": 64 if on_accel else 32},
            checkpoint_interval_s=0)
        os.environ["RAFIKI_FORCE_STREAMING"] = "1"
        try:
            t0 = time.monotonic()
            done = worker.run(max_trials=n_trials)
            dt = time.monotonic() - t0
        finally:
            os.environ.pop("RAFIKI_FORCE_STREAMING", None)
        best = advisor.best_effort
        _record(out_path, {
            "stage": "stream_search", "backend": backend,
            "loader_img_per_s": img_per_s, "n_images": n_imgs,
            "n_trials": done, "search_s": dt,
            "trials_per_hour": done / dt * 3600.0,
            "best_score": float(best.score) if best else -1.0})


# ---------------------------------------------------------------- parent

def main() -> None:
    use_kv = "--kv" in sys.argv
    t0 = time.monotonic()
    out_path = os.path.abspath(f".benchx_stages_{os.getpid()}.jsonl")

    def _no_results(records: list) -> bool:
        if _ONLY:
            return not any(r.get("stage") in _ONLY for r in records)
        return not any(r.get("stage") in ("predictor", "generation",
                                          "advisor") for r in records)

    records, _fallback = run_with_cpu_fallback(
        __file__, out_path, DEADLINE, time.monotonic, t0,
        fallback_reserve=85.0, need_rerun=_no_results,
        extra_args=["--kv"] if use_kv else None)

    pred = next((r for r in records if r.get("stage") == "predictor"), None)
    gen = next((r for r in records if r.get("stage") == "generation"), None)
    adv = next((r for r in records if r.get("stage") == "advisor"), None)
    pre = next((r for r in records if r.get("stage") == "prefill"), None)
    ss = next((r for r in records if r.get("stage") == "stream_search"),
              None)
    if ss:
        print(json.dumps({
            "metric": "stream_bohb_trials_per_hour",
            "value": round(ss["trials_per_hour"], 1),
            "unit": "trials/hour", "backend": ss["backend"],
            "loader_img_per_s": round(ss["loader_img_per_s"], 0),
            "best_score": ss["best_score"]}))
    if pre:
        print(json.dumps({
            "metric": "prefill_speedup_chunked_vs_tokenwise",
            "value": round(pre["prefill_speedup"], 2), "unit": "x",
            "backend": pre["backend"],
            "prompt_tokens": pre["prompt_tokens"],
            "tokenwise_ms": round(pre["tokenwise_ms"], 1),
            "chunked_ms": round(pre["chunked_ms"], 1)}))
    spec = next((r for r in records if r.get("stage") == "speculative"),
                None)
    if spec:
        line = {
            "metric": "speculative_decode_speedup",
            "value": round(spec["spec_speedup"], 2), "unit": "x",
            "backend": spec["backend"],
            "plain_tokens_per_s": round(spec["plain_tokens_per_s"], 1),
            "spec_tokens_per_s": round(spec["spec_tokens_per_s"], 1),
            "spec_accept_rate": round(spec["spec_accept_rate"], 3)}
        if "draft_model_speedup" in spec:
            line["draft_model_speedup"] = round(
                spec["draft_model_speedup"], 2)
            line["draft_model_accept_rate"] = round(
                spec["draft_model_accept_rate"], 3)
        print(json.dumps(line))
    kvf = next((r for r in records if r.get("stage") == "kv_footprint"),
               None)
    if kvf:
        print(json.dumps({
            "metric": "kv_footprint_reduction_paged_vs_contiguous",
            "value": round(kvf["footprint_reduction"], 2), "unit": "x",
            "backend": kvf["backend"],
            "contiguous_kv_bytes": kvf["contiguous_kv_bytes"],
            "paged_kv_bytes": kvf["paged_kv_bytes"],
            "contiguous_req_per_s": round(
                kvf["contiguous_req_per_s"], 2),
            "paged_req_per_s": round(kvf["paged_req_per_s"], 2),
            "req_per_s_ratio": round(kvf["req_per_s_ratio"], 3),
            "max_concurrent_paged": kvf["max_concurrent_paged"],
            "kv_pages_high_water": kvf["kv_pages_high_water"],
            "kv_pages_total": kvf["kv_pages_total"],
            "admission_stalls": kvf["admission_stalls"]}))
    pd = next((r for r in records if r.get("stage") == "paged_decode"),
              None)
    if pd:
        print(json.dumps({
            "metric": "paged_decode_kernel_tokens_per_s_ratio",
            "value": round(pd["tokens_per_s_ratio"], 3), "unit": "x",
            "backend": pd["backend"],
            "kernel_provenance": pd["kernel_provenance"],
            "gather_tokens_per_s": round(pd["gather_tokens_per_s"], 1),
            "kernel_tokens_per_s": round(pd["kernel_tokens_per_s"], 1),
            "max_concurrent": pd["max_concurrent"],
            "kv_pages_high_water": pd["kv_pages_high_water"],
            "kv_pages_total": pd["kv_pages_total"],
            "requests": pd["requests"], "max_new": pd["max_new"]}))
    pp = next((r for r in records if r.get("stage") == "paged_prefill"),
              None)
    if pp:
        print(json.dumps({
            "metric": "paged_prefill_kernel_tokens_per_s_ratio",
            "value": round(pp["prefill_tokens_per_s_ratio"], 3),
            "unit": "x", "backend": pp["backend"],
            "kernel_provenance": pp["kernel_provenance"],
            "gather_prefill_tokens_per_s": round(
                pp["gather_prefill_tokens_per_s"], 1),
            "kernel_prefill_tokens_per_s": round(
                pp["kernel_prefill_tokens_per_s"], 1),
            "window_tokens_per_pass": pp["window_tokens_per_pass"],
            "prefill_calls_per_pass": pp["prefill_calls_per_pass"],
            "kv_pages_high_water": pp["kv_pages_high_water"],
            "kv_pages_total": pp["kv_pages_total"],
            "requests": pp["requests"],
            "prefill_chunk": pp["prefill_chunk"]}))
    fo = next((r for r in records if r.get("stage") == "failover"),
              None)
    if fo:
        print(json.dumps({
            "metric": "failover_stream_gap_s",
            "value": round(fo["stream_gap_s"], 3), "unit": "s",
            "backend": fo["backend"],
            "zero_token_loss": fo["zero_token_loss"],
            "failovers": fo["failovers"],
            "silence_timeout_s": fo["silence_timeout_s"],
            "kill_after_tokens": fo["kill_after_tokens"],
            "max_new": fo["max_new"],
            "breaker_trips": fo["breaker_trips"],
            "stream_total_s": round(fo["stream_total_s"], 3)}))
    so = next((r for r in records if r.get("stage") == "scaleout"),
              None)
    if so:
        print(json.dumps({
            "metric": "scaleout_throughput_ratio_3x_workers",
            "value": round(so["throughput_ratio"], 2), "unit": "x",
            "backend": so["backend"], "provenance": so["provenance"],
            "workers": so["workers"],
            "single_tokens_per_s": round(so["single_tokens_per_s"], 1),
            "scaled_tokens_per_s": round(so["scaled_tokens_per_s"], 1),
            "single_ttft_p95_s": round(so["single_ttft_p95_s"], 4),
            "scaled_ttft_p95_s": round(so["scaled_ttft_p95_s"], 4),
            "affinity_hit_rate": round(so["affinity_hit_rate"], 4),
            "cycle_zero_token_loss": so["cycle_zero_token_loss"],
            "cycle_streams": so["cycle_streams"],
            "cycle_failovers": so["cycle_failovers"],
            "cycle_events": so["cycle_events"],
            "max_slots": so["max_slots"], "max_new": so["max_new"]}))
    sl = next((r for r in records if r.get("stage") == "slo_overload"),
              None)
    if sl:
        print(json.dumps({
            "metric": "slo_overload_interactive_p95_ratio",
            "value": round(sl["interactive_p95_ratio"], 3), "unit": "x",
            "backend": sl["backend"], "provenance": sl["provenance"],
            "step_quantum_s": sl["step_quantum_s"],
            "interactive_ttft_p95_unloaded_s": round(
                sl["interactive_ttft_p95_unloaded_s"], 4),
            "interactive_ttft_p95_loaded_s": round(
                sl["interactive_ttft_p95_loaded_s"], 4),
            "zero_token_loss": bool(
                sl["interactive_zero_token_loss"]
                and sl["batch_zero_token_loss"]
                and sl["background_zero_token_loss"]),
            "preemptions": sl["preemptions"],
            "background_served": sl["background_served"],
            "background_shed": sl["background_shed"],
            "background_shed_with_retry_hint":
                sl["background_shed_with_retry_hint"],
            "batch_tokens_per_s": round(sl["batch_tokens_per_s"], 1),
            "background_tokens_per_s": round(
                sl["background_tokens_per_s"], 1)}))
    kt = next((r for r in records if r.get("stage") == "kv_tier"),
              None)
    if kt:
        print(json.dumps({
            "metric": "kv_tier_max_concurrency_ratio",
            "value": round(kt["concurrency_ratio"], 2), "unit": "x",
            "backend": kt["backend"], "provenance": kt["provenance"],
            "hbm_pages_usable": kt["hbm_pages_usable"],
            "host_pages": kt["host_pages"],
            "hbm_only_max_concurrent": kt["hbm_only_max_concurrent"],
            "tiered_max_concurrent": kt["tiered_max_concurrent"],
            "hbm_only_admission_stalls":
                kt["hbm_only_admission_stalls"],
            "tiered_admission_stalls": kt["tiered_admission_stalls"],
            "hbm_only_tokens_per_s": round(
                kt["hbm_only_tokens_per_s"], 1),
            "tiered_tokens_per_s": round(kt["tiered_tokens_per_s"], 1),
            "token_exact_vs_untiered": kt["token_exact_vs_untiered"],
            "admission_deadlocks": kt["admission_deadlocks"],
            "kv_evictions_total": kt["kv_evictions_total"],
            "kv_prefetch_hits": kt["kv_prefetch_hits"],
            "kv_prefetch_misses": kt["kv_prefetch_misses"],
            "kv_transfer_bytes_total": kt["kv_transfer_bytes_total"],
            "requests": kt["requests"], "max_slots": kt["max_slots"],
            "max_new": kt["max_new"]}))
    dp = next((r for r in records
               if r.get("stage") == "disagg_prefill"), None)
    if dp:
        print(json.dumps({
            "metric": "disagg_prefill_itl_p95_ratio",
            "value": round(dp["split_ratio"], 3), "unit": "x",
            "backend": dp["backend"], "provenance": dp["provenance"],
            "itl_p95_baseline_s": round(dp["itl_p95_baseline_s"], 4),
            "itl_p95_unified_arrivals_s": round(
                dp["itl_p95_unified_arrivals_s"], 4),
            "itl_p95_split_arrivals_s": round(
                dp["itl_p95_split_arrivals_s"], 4),
            "unified_stall_ratio": round(dp["unified_stall_ratio"], 3),
            "token_exact_across_legs": dp["token_exact_across_legs"],
            "wire_violations": dp["wire_violations"],
            "split_kv_ships_sent": dp["split_kv_ships_sent"],
            "split_kv_imports_installed":
                dp["split_kv_imports_installed"],
            "split_kv_import_fallbacks":
                dp["split_kv_import_fallbacks"],
            "kill_kv_wait_timeouts": dp["kill_kv_wait_timeouts"],
            "kill_kv_imports_installed":
                dp["kill_kv_imports_installed"],
            "gap_samples": dp["gap_samples"],
            "long_prompt_tokens": dp["long_prompt_tokens"],
            "max_new": dp["max_new"],
            "steps_per_sync": dp.get("steps_per_sync"),
            "rounds": dp.get("rounds"),
            "leg_duration_s": dp["leg_duration_s"]}))
    ar = next((r for r in records
               if r.get("stage") == "admin_recovery"), None)
    if ar:
        print(json.dumps({
            "metric": "admin_recovery_reconverge_s",
            "value": ar["reconverge_s"], "unit": "s",
            "backend": ar["backend"],
            "lease_ttl_s": ar["lease_ttl_s"],
            "services_adopted": ar["services_adopted"],
            "services_expected": ar["services_expected"],
            "kv_adopted": ar["kv_adopted"],
            "adopted_pids_match": ar["adopted_pids_match"],
            "lease_generation": ar["lease_generation"],
            "dropped_stream_msgs": ar["dropped_stream_msgs"],
            "stream_max_gap_s": ar["stream_max_gap_s"],
            "stream_msgs": ar["stream_msgs"]}))
    kr = next((r for r in records
               if r.get("stage") == "kvd_recovery"), None)
    if kr:
        print(json.dumps({
            "metric": "kvd_recovery_reconverge_s",
            "value": kr["reconverge_s"], "unit": "s",
            "backend": kr["backend"],
            "provenance": kr["provenance"],
            "respawn_s": kr["respawn_s"],
            "replay_seconds": kr["replay_seconds"],
            "replayed_records": kr["replayed_records"],
            "stream_msgs": kr["stream_msgs"],
            "dropped_stream_msgs": kr["dropped_stream_msgs"],
            "double_delivered_msgs": kr["double_delivered_msgs"],
            "stream_max_gap_s": kr["stream_max_gap_s"],
            "blobs_written": kr["blobs_written"],
            "blob_losses": kr["blob_losses"]}))
    mo = next((r for r in records
               if r.get("stage") == "metrics_overhead"), None)
    if mo:
        print(json.dumps({
            "metric": "metrics_overhead_req_per_s_ratio",
            "value": round(mo["req_per_s_ratio"], 3), "unit": "x",
            "backend": mo["backend"],
            "bare_req_per_s": round(mo["bare_req_per_s"], 2),
            "instrumented_req_per_s": round(
                mo["instrumented_req_per_s"], 2),
            "spans_recorded": mo["spans_recorded"],
            "ttft_observations": mo["ttft_observations"],
            "requests": mo["requests"]}))
    sd = next((r for r in records
               if r.get("stage") == "speculative_small_draft"), None)
    if sd:
        print(json.dumps({
            "metric": "small_draft_spec_speedup",
            "value": round(sd["small_draft_speedup"], 2), "unit": "x",
            "backend": sd["backend"], "target": sd["target"],
            "draft": sd["draft"],
            "plain_tokens_per_s": round(sd["plain_tokens_per_s"], 1),
            "small_draft_tokens_per_s": round(
                sd["small_draft_tokens_per_s"], 1),
            "accept_rate": round(sd["small_draft_accept_rate"], 3),
            "spec_drafted": sd["spec_drafted"],
            "distill_loss": round(sd["distill_loss"], 4)}))
    if gen:
        print(json.dumps({
            "metric": f"generation_req_per_s_{gen['model']}",
            "value": round(gen["req_per_s"], 2), "unit": "req/s",
            "backend": gen["backend"],
            "tokens_per_s": round(gen["tokens_per_s"], 1),
            "p50_ms": round(gen["p50_ms"], 2),
            "max_concurrent_slots": gen["max_concurrent_slots"],
            "max_new": gen["max_new"]}))
    if pred:
        print(json.dumps({
            "metric": f"predictor_req_per_s_{pred['model']}",
            "value": round(pred["req_per_s"], 2), "unit": "req/s",
            "backend": pred["backend"],
            "queries_per_s": round(pred["queries_per_s"], 2),
            "p50_ms": round(pred["p50_ms"], 2),
            "p95_ms": round(pred["p95_ms"], 2),
            "transport": "kv" if use_kv else "inproc"}))
    if adv:
        print(json.dumps({
            "metric": "advisor_trials_per_hour",
            "value": round(adv["trials_per_hour"], 1),
            "unit": "trials/hour", "backend": adv["backend"],
            "n_trials": adv["n_trials"],
            "best_score": adv["best_score"]}))
    ag = next((r for r in records if r.get("stage") == "advisor_gang"),
              None)
    if ag:
        print(json.dumps({
            "metric": "gang_trials_per_hour",
            "value": round(ag["trials_per_hour"], 1),
            "unit": "trials/hour", "backend": ag["backend"],
            "gang_size": ag["gang_size"], "n_trials": ag["n_trials"],
            "speedup_vs_baseline": round(ag["speedup_vs_baseline"], 2),
            "seq_sample_trials_per_hour": round(
                ag["seq_sample_trials_per_hour"], 1),
            "static_buckets": ag["static_buckets"],
            "compiles": ag["compiles"],
            "best_score": ag["best_score"]}))
    gl = next((r for r in records if r.get("stage") == "gang_lora"),
              None)
    if gl:
        print(json.dumps({
            "metric": "gang_lora_trials_per_hour",
            "value": round(gl["trials_per_hour"], 1),
            "unit": "trials/hour", "backend": gl["backend"],
            "gang_size": gl["gang_size"], "n_trials": gl["n_trials"],
            "seq_sample_trials_per_hour": round(
                gl["seq_sample_trials_per_hour"], 1),
            "speedup_vs_seq_sample": round(
                gl["speedup_vs_seq_sample"], 2),
            "static_buckets": gl["static_buckets"],
            "compiles": gl["compiles"],
            "aggregate_tokens_per_s": round(
                gl["aggregate_tokens_per_s"], 1),
            "overlap_options_applied": gl["overlap_options_applied"],
            "best_score": gl["best_score"]}))
    if not pred and not gen and not adv:
        print(json.dumps({"metric": "bench_extra_error", "value": 0.0,
                          "unit": "", "errors": collect_errors(records)}))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        try:
            _child(sys.argv[2], float(sys.argv[3]),
                   use_kv="--kv" in sys.argv)
        except Exception as e:  # noqa: BLE001
            _record(sys.argv[2], {"stage": "child_error",
                                  "error": repr(e)[:300]})
            sys.exit(1)
        sys.exit(0)
    try:
        main()
    except Exception as e:  # noqa: BLE001
        print(json.dumps({"metric": "bench_extra_error", "value": 0.0,
                          "unit": "", "error": repr(e)[:300]}))
        sys.exit(0)