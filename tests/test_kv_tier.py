"""Host-RAM KV page tier + disaggregated prefill/decode — ISSUE 13.

Two mechanisms, one oracle. (1) The host tier: a paged engine with a
TINY HBM pool plus ``host_kv_pages`` must serve more concurrent
streams than HBM alone could hold — parking cold slots, evicting
their pages to pinned host memory, prefetching them back — while
staying token-BIT-EXACT against an untiered engine with a huge pool,
in every decode mode (greedy/sampled/int8-KV/multi-adapter/
speculative). (2) The prefill/decode split: a prefill-role engine
chews a prompt and ships its KV pages as a wire blob; a decode-role
engine installs the blob and must produce the identical stream —
and every failure mode (late, lost, mismatched shipment) degrades to
a local re-prefill, never a hang or a wrong answer.
"""

import threading
import time

import numpy as np
import pytest

from rafiki_tpu.models.llama_lora import LlamaLoRA, stack_lora_adapters
from rafiki_tpu.serving.decode_engine import DecodeEngine
from rafiki_tpu.serving.kv_tier import HostPageTier
from rafiki_tpu.serving.kv_transfer import (check_kv_blob,
                                            make_kv_blob,
                                            normalize_role)

from test_decode_engine import KNOBS  # noqa: F401 — shared knobs
from test_multi_adapter import _lora_variant  # noqa: F401

L = int(KNOBS["max_len"])
PS = 8  # page size throughout (divides max_len=32)

#: tiered engine geometry used by the parity tests: 6 pool pages =
#: 5 usable HBM pages (page 0 is scratch) — far below the traffic's
#: combined reservation — plus a host tier that absorbs the rest
TIER_KW = {"kv_page_size": PS, "kv_pages": 6}
HOST_PAGES = 24


def _mixed_reqs(n=8, seed=0, max_new=6, vocab=64):
    rng = np.random.default_rng(seed)
    return [(r, rng.integers(1, vocab,
                             size=int(rng.integers(2, 15))
                             ).astype(np.int32), max_new)
            for r in range(n)]


def _drain(eng, reqs, submit_kw=None):
    for i, (rid, p, mn) in enumerate(reqs):
        eng.submit(rid, p, mn, **(submit_kw(i) if submit_kw else {}))
    done = {}
    for _ in range(600):
        eng.step()
        done.update(dict(eng.poll()))
        if len(done) == len(reqs):
            return done
    raise AssertionError(f"undrained: {sorted(done)} / {dict(eng.stats)}")


def _tier_pair(trained, reqs, engine_kw=None, submit_kw=None,
               module_kw=None, params=None):
    """(untiered reference outputs, tiered engine) on identical
    traffic: big-pool untiered vs tiny-HBM + host tier. Asserts
    token-exactness and full page recycling; returns the tiered
    engine for extra assertions."""
    engine_kw = engine_kw or {}
    module_kw = module_kw or {}
    params = trained._params if params is None else params
    ref_eng = DecodeEngine(
        trained._module(kv_page_size=PS, kv_pages=33, **module_kw),
        params, max_slots=4, max_len=L, **engine_kw)
    tiered = DecodeEngine(
        trained._module(**TIER_KW, **module_kw), params,
        max_slots=4, max_len=L, host_kv_pages=HOST_PAGES, **engine_kw)
    ref = _drain(ref_eng, reqs, submit_kw)
    got = _drain(tiered, reqs, submit_kw)
    assert got == ref, {k: (got.get(k), ref[k]) for k in ref
                        if got.get(k) != ref[k]}
    s = tiered.stats
    assert s["kv_pages_used"] == 0, dict(s)       # HBM fully recycled
    assert s["kv_host_pages_used"] == 0, dict(s)  # host fully recycled
    assert s["kv_parked_slots"] == 0
    assert len(tiered._free_pages) == TIER_KW["kv_pages"] - 1
    return ref, tiered


# ---- eviction -> prefetch round-trip parity, per decode mode ----

def test_tiered_matches_untiered_greedy(trained):
    """10 mixed greedy requests through 5 usable HBM pages: the tier
    MUST engage (evictions, parks, unparks all > 0) and every output
    is bit-exact vs the untiered big-pool engine."""
    _, eng = _tier_pair(trained, _mixed_reqs(10))
    s = eng.stats
    assert s["kv_evictions_total"] > 0, dict(s)
    assert s["kv_unparks_total"] > 0, dict(s)
    assert s["kv_prefetch_hits"] + s["kv_prefetch_misses"] > 0
    assert s["kv_transfer_bytes_total"] > 0


def test_tiered_sampled_parity(trained):
    """Seeded sampling is position-keyed, so park/unpark (which
    replays NOTHING — the restored pages are the KV) must reproduce
    sampled streams exactly, mixed with greedy in one batch."""

    def samp(i):
        if i % 2 == 0:
            return {}
        return {"temperature": 0.9, "top_k": 8, "top_p": 0.95,
                "seed": 100 + i}

    _tier_pair(trained, _mixed_reqs(8, seed=1), submit_kw=samp)


def test_tiered_int8_kv_parity(trained):
    """int8 KV tiers identically: the int8 pools AND their f32 scale
    rows evict/prefetch together (every cache leaf uniformly)."""
    m8 = LlamaLoRA(**{**KNOBS, "kv_cache_int8": True})
    m8._params = trained._params
    _tier_pair(m8, _mixed_reqs(8, seed=2))


def test_tiered_multi_adapter_parity(trained):
    """Mixed-adapter traffic over one tiered pool: parking a slot of
    one tenant must not perturb another's stream."""
    stacked = stack_lora_adapters(
        [trained._params, _lora_variant(trained._params)])
    _tier_pair(trained, _mixed_reqs(8, seed=4),
               module_kw={"n_adapters": 2}, params=stacked,
               submit_kw=lambda i: {"adapter_id": i % 2})


def test_tiered_speculative_parity(trained):
    """Speculative decoding over the tier: the verify window's pages
    ride the same reservations, and park/unpark stays lossless."""
    reqs = [(0, np.asarray([1, 7, 2, 7, 2, 7, 2], np.int32), 8),
            (1, np.asarray([1, 5, 9, 13], np.int32), 8),
            (2, np.asarray([1, 3], np.int32), 8),
            (3, np.asarray([2, 4, 6, 8, 10], np.int32), 8),
            (4, np.asarray([1, 5, 9, 13, 2, 4], np.int32), 8)]
    _, eng = _tier_pair(trained, reqs,
                        engine_kw={"speculate_k": 4})
    assert eng.stats["spec_calls"] > 0


# ---- two-tier admission ----

def test_two_tier_admission_admits_beyond_hbm(trained):
    """4 requests whose combined worst-case reservation exceeds the
    HBM pool alone (which would stall the queue and serialize) are
    ALL admitted concurrently against the combined HBM+host budget —
    zero admission stalls, zero deadlocks, token-exact outputs."""
    reqs = [(r, np.asarray([1 + r, 5, 9, 13, 2, 6], np.int32), 8)
            for r in range(4)]  # stop 13 -> 2 pages each, 8 total
    # HBM-only twin: 5 usable pages < 8 reserved -> must stall
    hbm_only = DecodeEngine(trained._module(**TIER_KW),
                            trained._params, max_slots=4, max_len=L)
    ref = _drain(hbm_only, reqs)
    assert hbm_only.stats["admission_stalls"] > 0
    assert hbm_only.stats["max_concurrent"] < 4
    tiered = DecodeEngine(trained._module(**TIER_KW), trained._params,
                          max_slots=4, max_len=L,
                          host_kv_pages=HOST_PAGES)
    got = _drain(tiered, reqs)
    assert got == ref
    assert tiered.stats["admission_stalls"] == 0, dict(tiered.stats)
    assert tiered.stats["max_concurrent"] == 4


def test_tier_requires_paged_engine(trained):
    with pytest.raises(ValueError, match="paged"):
        DecodeEngine(trained._module(), trained._params, max_slots=2,
                     max_len=L, host_kv_pages=8)
    with pytest.raises(ValueError, match="host_kv_pages"):
        trained.make_decode_engine(host_kv_pages=8)
    with pytest.raises(ValueError, match="host_kv_pages"):
        trained.estimate_serving_device_bytes(host_kv_pages=8)


def test_estimator_reports_host_tier_outside_hbm_total(trained):
    base = trained.estimate_serving_device_bytes(
        kv_page_size=PS, kv_pages=9)
    tiered = trained.estimate_serving_device_bytes(
        kv_page_size=PS, kv_pages=9, host_kv_pages=16)
    assert tiered["total"] == base["total"]  # host RAM, not HBM
    assert tiered["host_kv_cache"] > 0


# ---- HostPageTier mechanism (no model needed) ----

class _Stats(dict):
    def set(self, k, v):
        self[k] = v

    def inc(self, k, n=1):
        self[k] = self.get(k, 0) + n
        return self[k]


def _wait(pred, timeout=5.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.005)
    return False


def test_host_tier_evict_fetch_roundtrip():
    """Bytes written by an eviction come back exactly from fetch(),
    and fetch blocks on the pending write instead of reading stale
    pool contents."""
    tier = HostPageTier(4, _Stats())
    try:
        ids = tier.alloc(2)
        assert sorted(ids) == [0, 1]
        leaves = [np.arange(2 * 3 * 4, dtype=np.float32
                            ).reshape(2, 3, 4),
                  np.ones((2, 3), np.int8)]
        tier.evict_submit(ids, leaves)
        got = tier.fetch(ids)
        assert np.array_equal(got[0], leaves[0])
        assert np.array_equal(got[1], leaves[1])
        tier.free(ids)
        assert tier.free_pages() == 4
        assert tier.alloc(5) is None  # refuses, never corrupts
    finally:
        tier.close()


def test_host_tier_prefetch_staging():
    """A prefetch stages device arrays the consumer takes exactly
    once; stale stagings (different id set) read as misses."""
    stats = _Stats()
    tier = HostPageTier(4, stats)
    try:
        ids = tier.alloc(2)
        leaves = [np.full((2, 4), 7.5, np.float32)]
        tier.evict_submit(ids, leaves)
        tier.prefetch_submit("k1", ids)
        assert _wait(lambda: tier.take_staged("k1", ids) is not None
                     or stats.get("kv_transfer_bytes_total", 0) > 0)
        # the staging was either consumed above or still present:
        # re-stage deterministically and consume
        tier.prefetch_submit("k1", ids)
        _wait(lambda: tier._staged.get("k1") is not None
              and tier._staged["k1"][2].done.is_set())
        staged = tier.take_staged("k1", ids)
        if staged is not None:
            assert np.array_equal(np.asarray(staged[0]), leaves[0])
        assert tier.take_staged("k1", ids) is None  # consumed once
        tier.prefetch_submit("k2", ids)
        _wait(lambda: not tier._q)
        assert tier.take_staged("k2", [ids[0]]) is None  # wrong ids
    finally:
        tier.close()


class _FlakyLeaf:
    """Device-array stand-in whose d2h materialization fails the
    first ``fail_times`` attempts — the transient transfer error the
    tier must never convert into silently-zero KV."""

    def __init__(self, arr, fail_times=1):
        self._arr = arr
        self.fails = int(fail_times)
        self.dtype = arr.dtype
        self.shape = arr.shape
        self.nbytes = arr.nbytes

    def __array__(self, dtype=None, copy=None):
        if self.fails > 0:
            self.fails -= 1
            raise RuntimeError("transient d2h failure (injected)")
        return self._arr


def test_host_tier_failed_evict_recovers_on_fetch():
    """A failed eviction transfer must surface as a retried copy (or
    a loud error), NEVER as fetch() serving the never-written host
    pool bytes — that would be a correct-looking wrong answer."""
    tier = HostPageTier(4, _Stats())
    try:
        want = np.full((1, 3, 4), 5.0, np.float32)
        ids = tier.alloc(1)
        tier.evict_submit(ids, [_FlakyLeaf(want.copy(), fail_times=1)])
        got = tier.fetch(ids)  # recovers from the retained payload
        assert np.array_equal(got[0], want)
        # still-failing content is LOUD, then recoverable once the
        # transient clears
        ids2 = tier.alloc(1)
        tier.evict_submit(ids2,
                          [_FlakyLeaf(want.copy(), fail_times=2)])
        with pytest.raises(RuntimeError):
            tier.fetch(ids2)
        assert np.array_equal(tier.fetch(ids2)[0], want)
    finally:
        tier.close()


class _SlowLeaf:
    """Device-array stand-in whose materialization sleeps — holds the
    tier thread busy so later-queued tickets stay queued."""

    def __init__(self, arr, delay_s):
        self._arr = arr
        self._delay = float(delay_s)
        self.dtype = arr.dtype
        self.shape = arr.shape
        self.nbytes = arr.nbytes

    def __array__(self, dtype=None, copy=None):
        time.sleep(self._delay)
        return self._arr


def test_host_tier_stale_prefetch_never_stores():
    """A prefetch whose park key died before the tier thread ran it
    (slot seated/preempted, drop_staged called) must not store under
    the dead key — park keys are never reused, so that entry would
    pin its staged device arrays forever."""
    tier = HostPageTier(4, _Stats())
    try:
        ids = tier.alloc(1)
        arr = np.ones((1, 2), np.float32)
        tier.evict_submit(ids, [_SlowLeaf(arr, 0.25)])  # busy thread
        tier.prefetch_submit("k", ids)   # queued behind the evict
        tier.drop_staged("k")            # the park dies first
        assert _wait(lambda: not tier._q)
        got = tier.fetch(ids)            # drains/waits everything
        assert np.array_equal(got[0], arr)
        assert "k" not in tier._staged   # no orphan staging
    finally:
        tier.close()


def test_host_tier_submit_after_close_never_hangs():
    """An eviction submitted after close() (stop racing a still-
    stepping engine) has no consumer: it must resolve through the
    failed-ticket recovery path instead of stranding fetch() on a
    done event nobody will set."""
    tier = HostPageTier(4, _Stats())
    ids = tier.alloc(1)
    tier.close()
    want = np.full((1, 2), 3.0, np.float32)
    tier.evict_submit(ids, [want.copy()])
    got = tier.fetch(ids)  # synchronous recovery, no hang
    assert np.array_equal(got[0], want)


def test_host_tier_evict_releases_device_payload():
    """A completed eviction drops its gathered device arrays — the
    writers map keeps the ticket until the pages free, and a retained
    payload would pin every evicted page's bytes in HBM."""
    tier = HostPageTier(4, _Stats())
    try:
        ids = tier.alloc(1)
        tier.evict_submit(ids, [np.ones((1, 2), np.float32)])
        t = tier._writers[ids[0]]
        assert t.done.wait(5.0)
        assert t.payload is None and not t.failed
    finally:
        tier.close()


# ---- KV shipment blobs ----

def test_kv_blob_validation_rejects_mismatches():
    leaves = [np.zeros((2, PS, 2, 4), np.float32)]
    blob = make_kv_blob(10, "paged", PS, leaves, adapter_id=0)
    ok = dict(layout="paged", page_size=PS,
              expect_sig=[[[PS, 2, 4], "float32"]], prompt_len=12,
              expect_leading=2)
    assert check_kv_blob(dict(blob), **ok) is not None
    for mutate, match in [
            ({"v": 99}, "version"),
            ({"layout": "rows"}, "layout"),
            ({"page_size": 4}, "page_size"),
            ({"adapter_id": 1}, "adapter"),
            ({"covered": 12}, "covers"),
            ({"sig": [[[PS, 2, 8], "float32"]]}, "signature"),
            ({"leaves": []}, "truncated")]:
        bad = {**blob, **mutate}
        with pytest.raises(ValueError, match=match):
            check_kv_blob(bad, **ok)
    with pytest.raises(ValueError, match="pages/rows"):
        check_kv_blob(dict(blob), **{**ok, "expect_leading": 3})


def test_normalize_role():
    assert normalize_role(None) == "unified"
    assert normalize_role("") == "unified"
    assert normalize_role(" Decode ") == "decode"
    assert normalize_role("prefill") == "prefill"
    with pytest.raises(ValueError, match="unknown worker role"):
        normalize_role("prefil")


# ---- disaggregated prefill -> decode (engine level) ----

def _prefill_ship(pre, reqs, adapter_kw=None):
    for i, (rid, p, mn) in enumerate(reqs):
        kw = adapter_kw(i) if adapter_kw else {}
        pre.submit(rid, p, mn, prefill_only=True, **kw)
    blobs = {}
    for _ in range(300):
        pre.step()
        for rid, blob in pre.poll_kv():
            blobs[rid] = blob
        if len(blobs) == len(reqs):
            return blobs
    raise AssertionError(f"unshipped: {sorted(blobs)}")


def test_disagg_ship_install_token_exact(trained):
    """Prefill engine ships, decode engine installs: identical streams
    to a locally-prefilled engine, pages fully recycled on both, and
    the prefill engine emits NO generated tokens."""
    reqs = _mixed_reqs(6, seed=5)
    ref = _drain(DecodeEngine(trained._module(kv_page_size=PS,
                                              kv_pages=33),
                              trained._params, max_slots=4, max_len=L),
                 reqs)
    pre = DecodeEngine(trained._module(kv_page_size=PS, kv_pages=33),
                       trained._params, max_slots=4, max_len=L)
    dec = DecodeEngine(trained._module(kv_page_size=PS, kv_pages=33),
                       trained._params, max_slots=4, max_len=L)
    blobs = _prefill_ship(pre, reqs)
    assert not dict(pre.poll()), "prefill role must not generate"
    assert pre.stats["kv_exports"] == len(reqs)
    assert pre.stats["kv_pages_used"] == 0  # shipped slots freed
    got = _drain(dec, reqs,
                 submit_kw=lambda i: {"kv_import": blobs[i]})
    assert got == ref
    assert dec.stats["kv_imports"] == len(reqs)
    # the shipment actually skipped prefill compute on the decode leg:
    # only the last prompt token runs through the chunked-prefill path
    assert dec.stats["prefill_tokens"] < sum(
        len(p) - 1 for _r, p, _m in reqs)


def test_disagg_rows_layout_contiguous_engines(trained):
    """The same split works for contiguous (non-paged) engines via the
    rows layout."""
    reqs = _mixed_reqs(4, seed=6)
    ref = _drain(DecodeEngine(trained._module(), trained._params,
                              max_slots=4, max_len=L), reqs)
    pre = DecodeEngine(trained._module(), trained._params,
                       max_slots=4, max_len=L)
    dec = DecodeEngine(trained._module(), trained._params,
                       max_slots=4, max_len=L)
    blobs = _prefill_ship(pre, reqs)
    got = _drain(dec, reqs,
                 submit_kw=lambda i: {"kv_import": blobs[i]})
    assert got == ref


def test_disagg_rejects_wrong_adapter_blob(trained):
    """A blob computed under adapter 0 must not install into an
    adapter-1 request (wrong-tenant KV = correct-looking wrong
    answer): submit raises, the caller degrades."""
    stacked = stack_lora_adapters(
        [trained._params, _lora_variant(trained._params)])
    module_kw = {"n_adapters": 2}
    pre = DecodeEngine(trained._module(kv_page_size=PS, kv_pages=33,
                                       **module_kw),
                       stacked, max_slots=4, max_len=L)
    dec = DecodeEngine(trained._module(kv_page_size=PS, kv_pages=33,
                                       **module_kw),
                       stacked, max_slots=4, max_len=L)
    reqs = _mixed_reqs(1, seed=7)
    blobs = _prefill_ship(pre, reqs)  # computed under adapter 0
    rid, prompt, mn = reqs[0]
    with pytest.raises(ValueError, match="adapter"):
        dec.submit(rid, prompt, mn, adapter_id=1,
                   kv_import=blobs[rid])


def test_disagg_import_on_tiered_engine(trained):
    """The decode leg composes with the host tier: shipped KV installs
    into a tiered engine under HBM pressure, still token-exact."""
    reqs = _mixed_reqs(8, seed=8)
    ref = _drain(DecodeEngine(trained._module(kv_page_size=PS,
                                              kv_pages=33),
                              trained._params, max_slots=4, max_len=L),
                 reqs)
    pre = DecodeEngine(trained._module(kv_page_size=PS, kv_pages=33),
                       trained._params, max_slots=4, max_len=L)
    dec = DecodeEngine(trained._module(**TIER_KW), trained._params,
                       max_slots=4, max_len=L,
                       host_kv_pages=HOST_PAGES)
    blobs = _prefill_ship(pre, reqs)
    got = _drain(dec, reqs,
                 submit_kw=lambda i: {"kv_import": blobs[i]})
    assert got == ref
    assert dec.stats["kv_pages_used"] == 0


# ---- prefix snapshot export/import ----

def test_prefix_export_import_cross_engine(trained):
    """A prefix prefilled ONCE exports as a blob a peer imports
    without recomputing: identical outputs, and the importer records
    prefix hits without ever calling register_prefix."""
    prefix = np.asarray([1, 5, 9, 13, 2], np.int32)
    prompts = [("hit", np.concatenate([prefix, [7, 4]]
                                      ).astype(np.int32), 6),
               ("miss", np.asarray([2, 5, 9, 3], np.int32), 6)]
    module = trained._module(kv_page_size=PS, kv_pages=9)
    a = DecodeEngine(module, trained._params, max_slots=2, max_len=L)
    a.register_prefix(prefix)
    ref = _drain(a, prompts)
    blob = a.export_prefix()
    assert blob is not None and blob["len"] == len(prefix)
    b = DecodeEngine(module, trained._params, max_slots=2, max_len=L)
    assert b.import_prefix(blob) == len(prefix)
    got = _drain(b, prompts)
    assert got == ref
    assert b.stats["prefix_hits"] == 1
    with pytest.raises(ValueError, match="prefix"):
        b.import_prefix({"v": 1, "ids": prefix, "len": 99,
                         "leaves": []})


# ---- worker-level disaggregation + chaos degradation ----

def _lm_worker(trained, hub, wid, **kw):
    from rafiki_tpu.store.param_store import ParamStore
    from rafiki_tpu.worker.inference import InferenceWorker

    store = ParamStore.from_uri("mem://")
    store.save("lm0", trained.dump_parameters())
    return InferenceWorker(LlamaLoRA, "lm0", KNOBS, store, hub, wid,
                           decode_loop=True, max_slots=4,
                           max_new_tokens=6, **kw)


PROMPTS = ["tok1 tok2 tok3 tok4 tok5 tok6 tok7 tok8",
           "tok9 tok8 tok7 tok6 tok5 tok4",
           "tok2 tok4 tok6 tok8 tok1 tok3 tok5"]


def _stream_all(pred, prompts):
    outs = []
    for p in prompts:
        evs = list(pred.predict_stream([p]))
        final = [e for e in evs if e.get("done")][-1]
        assert "predictions" in final, final
        # delta concatenation must equal the final text (no dropped or
        # duplicated tokens on the wire)
        acc = "".join(e["delta"]["0"] for e in evs if e.get("delta"))
        assert final["predictions"][0].startswith(acc), (
            acc, final["predictions"])
        outs.append(final["predictions"][0])
    return outs


@pytest.fixture()
def unified_reference(trained):
    """Streamed outputs of a single unified worker on PROMPTS — the
    oracle every disaggregated/chaos topology must reproduce."""
    from rafiki_tpu.serving.predictor import Predictor
    from rafiki_tpu.serving.queues import InProcQueueHub

    hub = InProcQueueHub()
    w = _lm_worker(trained, hub, "w-uni")
    t = threading.Thread(target=w.run, daemon=True)
    t.start()
    try:
        yield _stream_all(Predictor(hub, ["w-uni"],
                                    gather_timeout=120.0), PROMPTS)
    finally:
        w.stop()
        t.join(timeout=10)


def _run_disagg(trained, reference, chaos_cfg=None, kv_wait_s=3.0,
                kill_prefill_after=None):
    """Drive PROMPTS through a prefill+decode worker pair (optionally
    chaos-wrapped / killed mid-run) and assert token-exactness vs the
    unified reference. Returns (decode worker, prefill worker)."""
    from rafiki_tpu.chaos import ChaosInjector
    from rafiki_tpu.serving.predictor import Predictor
    from rafiki_tpu.serving.queues import InProcQueueHub

    hub = InProcQueueHub()
    dec = _lm_worker(trained, hub, "w-dec", role="decode",
                     kv_page_size=PS, kv_pages=33, kv_wait_s=kv_wait_s)
    pre = _lm_worker(trained, hub, "w-pre", role="prefill",
                     kv_page_size=PS, kv_pages=33,
                     chaos=(ChaosInjector(chaos_cfg)
                            if chaos_cfg else None))
    threads = [threading.Thread(target=w.run, daemon=True)
               for w in (dec, pre)]
    for t in threads:
        t.start()
    try:
        pred = Predictor(hub, ["w-dec", "w-pre"], gather_timeout=120.0)
        for _ in range(200):
            if hub.get_worker_stats("w-dec") and \
                    hub.get_worker_stats("w-pre"):
                break
            time.sleep(0.05)
        pred._refresh_load_signals()
        assert pred.router.select_prefill() == "w-pre"
        outs = []
        for i, p in enumerate(PROMPTS):
            if kill_prefill_after is not None \
                    and i == kill_prefill_after:
                # the mid-shipment kill: the prefill worker vanishes;
                # in-flight + later streams must degrade to local
                # re-prefill with zero dropped/duplicated tokens
                pre.stop()
            outs.extend(_stream_all(pred, [p]))
        assert outs == reference, (outs, reference)
        return dec, pre
    finally:
        for w in (dec, pre):
            w.stop()
        for t in threads:
            t.join(timeout=10)


def test_worker_disagg_token_exact(trained, unified_reference):
    """The full wire path — predictor places the prefill leg, prefill
    worker ships pages over the hub, decode worker installs — streams
    the exact unified outputs, with zero fallbacks."""
    dec, pre = _run_disagg(trained, unified_reference)
    assert pre.stats["kv_ships_sent"] == len(PROMPTS)
    assert dec.stats["kv_imports_installed"] == len(PROMPTS)
    assert dec.stats["kv_wait_timeouts"] == 0
    assert dec.stats["kv_import_fallbacks"] == 0


def test_worker_disagg_dropped_shipment_degrades(trained,
                                                 unified_reference):
    """chaos drop_kv_page_p=1: every shipment is lost. The decode
    worker's wait window expires and each stream re-prefills locally
    — token-exact, no hang."""
    from rafiki_tpu.chaos import ChaosConfig

    dec, pre = _run_disagg(
        trained, unified_reference,
        chaos_cfg=ChaosConfig(drop_kv_page_p=1.0, seed=3),
        kv_wait_s=0.3)
    assert dec.stats["kv_wait_timeouts"] == len(PROMPTS)
    assert dec.stats["kv_imports_installed"] == 0


def test_worker_disagg_slow_shipment_degrades(trained,
                                              unified_reference):
    """chaos delay_kv_transfer_s beyond the wait window: same
    degradation contract as a loss — the stream never blocks on the
    transfer."""
    from rafiki_tpu.chaos import ChaosConfig

    dec, _pre = _run_disagg(
        trained, unified_reference,
        chaos_cfg=ChaosConfig(delay_kv_transfer_s=0.8, seed=3),
        kv_wait_s=0.15)
    assert dec.stats["kv_wait_timeouts"] > 0


def test_worker_disagg_prefill_kill_mid_run(trained,
                                            unified_reference):
    """The prefill worker dies after the first stream: later streams
    (whose prefill legs are never served) re-prefill locally after
    the wait window — zero dropped/duplicated tokens end to end."""
    dec, _pre = _run_disagg(trained, unified_reference,
                            kv_wait_s=0.4, kill_prefill_after=1)
    assert dec.stats["kv_wait_timeouts"] >= 1


def test_worker_role_validation(trained):
    from rafiki_tpu.serving.queues import InProcQueueHub

    with pytest.raises(ValueError, match="role"):
        _lm_worker(trained, InProcQueueHub(), "w-x", role="prefil")
    with pytest.raises(ValueError, match="host_kv_pages"):
        _lm_worker(trained, InProcQueueHub(), "w-x", host_kv_pages=4)


def test_worker_prefix_snapshot_shared_across_pool(trained):
    """Two replicas of one pool with the same system prefix: the
    second boot imports the first's published snapshot blob instead
    of re-running the prefix prefill."""
    from rafiki_tpu.serving.queues import InProcQueueHub

    hub = InProcQueueHub()
    w1 = _lm_worker(trained, hub, "w-a", kv_page_size=PS, kv_pages=33,
                    system_prefix="tok1 tok2", pool_id="job1")
    assert hub.get_blob("prefix:job1:0") is not None
    w2 = _lm_worker(trained, hub, "w-b", kv_page_size=PS, kv_pages=33,
                    system_prefix="tok1 tok2", pool_id="job1")
    assert w2.stats["kv_imports_installed"] == 1
    t1 = threading.Thread(target=w1.run, daemon=True)
    t2 = threading.Thread(target=w2.run, daemon=True)
    t1.start()
    t2.start()
    try:
        from rafiki_tpu.serving.predictor import Predictor

        p1 = Predictor(hub, ["w-a"], gather_timeout=120.0)
        p2 = Predictor(hub, ["w-b"], gather_timeout=120.0)
        q = "tok1 tok2 tok5 tok6"
        a, _ = p1.predict([q])
        b, _ = p2.predict([q])
        assert a == b
    finally:
        w1.stop()
        w2.stop()
        t1.join(timeout=10)
        t2.join(timeout=10)


# ---- router placement ----

def test_router_prefill_placement():
    from rafiki_tpu.serving.breaker import BreakerBoard
    from rafiki_tpu.serving.router import Router

    board = BreakerBoard(["d0", "d1", "p0"])  # fresh = CLOSED
    r = Router(["d0", "d1", "p0"], board)
    r.observe("p0", {"role": "prefill"})
    r.observe("d0", {"role": "decode"})
    r.observe("d1", {"role": "decode", "queue_p95_s": 0.5})
    # decode placement never lands on the prefill worker
    for key in ("a", "b", "c", "zebra", "quux"):
        assert r.select(key) in ("d0", "d1")
    assert r.select_prefill() == "p0"
    assert r.select_prefill(exclude=("p0",)) is None
    assert r.role_of("p0") == "prefill"
    # an all-prefill pool still serves (degraded beats unservable)
    r2 = Router(["p0"], BreakerBoard(["p0"]))
    r2.observe("p0", {"role": "prefill"})
    assert r2.select("k") == "p0"
