"""Gang-compiled LoRA lanes over the Llama template (ISSUE 20).

The load-bearing claims:
- a 1-lane gang run of ``tune_model`` scores EXACTLY equal to the
  sequential path (the functional train loop IS the lane function);
- compile count equals the number of static buckets under a
  remat_policy x gang_size sweep, never the trial count;
- the gang winner's exported blob loads into the multi-adapter engine
  and serves token-identically to a sequentially trained same adapter;
- ``propose_batch`` over the Llama knob space is seed-deterministic;
- the worker's gang admission uses the remat_policy-aware estimator:
  ``remat_policy="full"`` admits a gang the same HBM budget refuses at
  ``"none"``, and the estimator's resident pool agrees with the bytes
  the gang actually allocates.
"""

import numpy as np
import pytest

from rafiki_tpu.advisor import make_advisor
from rafiki_tpu.model import tune_model
from rafiki_tpu.models.llama_lora import LlamaLoRA
from rafiki_tpu.tuning import GangEngine, supports_gang

#: pins putting every proposal in ONE gangable static bucket — the
#: advisor still searches the traceable knobs (learning_rate,
#: lora_scale), which ride as per-lane traced operands
LLAMA_PINS = {"hidden_dim": 64, "depth": 2, "n_heads": 4, "kv_ratio": 2,
              "lora_rank": 4, "max_len": 32, "batch_size": 16,
              "model_parallel": 1, "sequence_parallel": 1,
              "pipeline_stages": 1, "grad_accum": 1, "loss_chunk": 0,
              "pretrained_path": "", "tokenizer_path": "",
              "rope_scaling": "", "rope_theta": 10000.0,
              "remat": False, "remat_policy": "none",
              "overlap_collectives": False, "bf16": False,
              "quantize_int8": False, "kv_cache_int8": False,
              "adapters_only": False, "quick_train": True}


@pytest.fixture(scope="module")
def text_data(tmp_path_factory):
    from rafiki_tpu.data import generate_text_classification_dataset

    d = tmp_path_factory.mktemp("gang_llama")
    tr, va = str(d / "tr.jsonl"), str(d / "va.jsonl")
    generate_text_classification_dataset(tr, 48, seed=0)
    generate_text_classification_dataset(va, 16, seed=1)
    return tr, va


def test_llama_supports_gang_and_names_blockers():
    assert supports_gang(LlamaLoRA)
    blockers = LlamaLoRA.gang_blockers({**LLAMA_PINS, "grad_accum": 2,
                                        "model_parallel": 2})
    joined = "; ".join(blockers)
    assert "grad_accum" in joined and "model_parallel" in joined
    assert LlamaLoRA.gang_blockers(dict(LLAMA_PINS)) == []


def test_one_lane_gang_scores_equal_sequential_tune_model(text_data):
    """Acceptance: gang_size=1 ``tune_model`` produces scores EXACTLY
    equal to the sequential path — same proposals, bit-equal training
    and eval (the 1-lane executor compiles the spec's functions
    unvmapped, so the HLO is the sequential trial's)."""
    tr, va = text_data
    seq = tune_model(LlamaLoRA, tr, va, advisor_type="random",
                     total_trials=1, seed=7, knob_overrides=LLAMA_PINS)
    gang = tune_model(LlamaLoRA, tr, va, advisor_type="random",
                      total_trials=1, seed=7, knob_overrides=LLAMA_PINS,
                      gang_size=1)
    assert sorted(t.score for t in seq.trials) \
        == sorted(t.score for t in gang.trials)
    assert gang.best_score == seq.best_score


def test_compile_count_equals_buckets_remat_by_gang_sweep(text_data):
    """Acceptance: sweeping remat_policy (a static schedule knob) at
    K=2, the jitted step compiles once per static bucket — the policy
    forks buckets, gang_size and the traceable knobs never do (the
    1-lane executor's compile discipline rides the equivalence test
    above; the vmap path is identical at any K>1)."""
    tr, va = text_data
    pins = {k: v for k, v in LLAMA_PINS.items() if k != "remat_policy"}
    adv = make_advisor(LlamaLoRA.get_knob_config(), "random",
                       total_trials=4, seed=4)
    eng = GangEngine(LlamaLoRA, adv, tr, va, gang_size=2,
                     mode="gang", knob_overrides=pins)
    results = eng.run()
    assert len(results) == 4
    policies = {r.knobs["remat_policy"] for r in results}
    assert len(policies) >= 2, "seed must spread over policies"
    assert eng.n_buckets == len(policies)
    assert len(results) > eng.n_buckets
    # one executable per bucket: no per-trial or per-lane recompiles
    assert list(eng.compile_counts().values()) == [1] * len(policies)


def test_gang_winner_blob_serves_in_multi_adapter_engine(
        text_data, monkeypatch):
    """Acceptance: the winner lane's exported blob (rank-scale already
    folded into lora_b) loads into ``make_multi_adapter_engine`` next
    to a SEQUENTIALLY trained adapter of the same knobs, and both slots
    serve token-identically — gang training is invisible downstream.

    The same engine run also proves the observability satellite:
    gang_lanes_active / gang_samples_per_s cover Llama gangs, and the
    per-lane lane_tokens_per_s / lane_est_mfu gauges ride the
    Prometheus exposition with one ``lane=<i>`` series per lane."""
    from rafiki_tpu.model import TrainContext
    from rafiki_tpu.model.log import ModelLogger
    from rafiki_tpu.obs import MetricsRegistry

    monkeypatch.setenv("RAFIKI_DEVICE_PEAK_FLOPS", "1e12")
    tr, va = text_data
    pins = {**LLAMA_PINS, "adapters_only": True}
    reg = MetricsRegistry()
    adv = make_advisor(LlamaLoRA.get_knob_config(), "random",
                       total_trials=2, seed=4)
    eng = GangEngine(LlamaLoRA, adv, tr, va, gang_size=2, mode="gang",
                     knob_overrides=pins, metrics=reg)
    results = eng.run()

    snap = reg.snapshot()
    assert snap["gang_lanes_active"] == 0  # drained at exit
    assert snap["trials_per_hour"] > 0
    assert "gang_samples_per_s" in snap
    prom = reg.render_prometheus()
    for lane in (0, 1):
        assert f'lane_tokens_per_s{{lane="{lane}"}}' in prom
        assert f'lane_est_mfu{{lane="{lane}"}}' in prom

    best = max(results, key=lambda r: r.score)
    blob = eng._blobs[f"gang-{best.trial_no}"]

    # the sequential twin: same knobs, the template's own train()
    twin = LlamaLoRA(**best.knobs)
    twin.train(tr, TrainContext(logger=ModelLogger()))

    served = LlamaLoRA(**best.knobs)
    served.load_parameters(blob)
    multi = served.make_multi_adapter_engine(
        [served._params, twin._params], max_slots=2, max_new_tokens=6)
    prompt = "tok1 tok2 tok3"
    multi.submit("gang", prompt, adapter_id=0)
    multi.submit("seq", prompt, adapter_id=1)
    got = {}
    for _ in range(400):
        if not multi.busy:
            break
        multi.step()
        for rid, text in multi.poll():
            got[rid] = text
    assert set(got) == {"gang", "seq"}
    assert got["gang"] == got["seq"], \
        "gang-trained adapter diverged from its sequential twin"


def test_propose_batch_seed_determinism_llama_knob_space():
    """Acceptance: batched proposals over the (large) Llama knob space
    are a pure function of the advisor seed — gang runs are replayable
    across processes."""
    kc = LlamaLoRA.get_knob_config()
    for advisor_type in ("random", "bohb"):
        a = make_advisor(kc, advisor_type, total_trials=8, seed=11)
        b = make_advisor(kc, advisor_type, total_trials=8, seed=11)
        pa = a.propose_batch(4) + a.propose_batch(4)
        pb = b.propose_batch(4) + b.propose_batch(4)
        assert [p.knobs for p in pa] == [p.knobs for p in pb]
        assert [p.trial_no for p in pa] == [p.trial_no for p in pb]


def test_llama_gang_override_typo_rejected(text_data):
    """A typo'd pin fails fast through the SAME validator as the admin
    API — on the gang path too, before any compile."""
    tr, va = text_data
    with pytest.raises(ValueError, match="knob_overrides.*lora_rnk"):
        tune_model(LlamaLoRA, tr, va, total_trials=1, gang_size=2,
                   knob_overrides={"lora_rnk": 4})


def test_tune_model_warning_names_blocking_knob(text_data, monkeypatch):
    """Satellite: the fallback warning says WHICH pinned knob blocked
    ganging, not just that it fell back. The warning fires BEFORE any
    training, so the trial itself is stubbed — the mesh-path mp=2
    compile is covered by the llama model tests, not here."""
    tr, va = text_data
    monkeypatch.setattr(LlamaLoRA, "train", lambda self, *a, **k: None)
    monkeypatch.setattr(LlamaLoRA, "evaluate", lambda self, *a, **k: 0.5)
    monkeypatch.setattr(LlamaLoRA, "dump_parameters",
                        lambda self: None)
    with pytest.warns(UserWarning, match="model_parallel"):
        res = tune_model(LlamaLoRA, tr, va, advisor_type="random",
                         total_trials=1, seed=0, gang_size=2,
                         knob_overrides={**LLAMA_PINS,
                                         "model_parallel": 2})
    assert len(res.trials) == 1  # sequential fallback still tunes


def test_remat_policy_is_an_admission_lever(text_data, monkeypatch):
    """Acceptance: at a fixed HBM budget, a gang refused at
    remat_policy="none" is admitted at "full" — the estimator prices
    recompute-for-HBM, so admission can trade them. The worker's gang
    admission callback carries the verdict, and the refused bucket
    falls back to sequential trials instead of OOMing."""
    from rafiki_tpu.worker.train import TrainWorker

    tr, va = text_data
    none_total = LlamaLoRA(**LLAMA_PINS).estimate_device_budget(
        1, gang_size=2)["total"]
    full_total = LlamaLoRA(
        **{**LLAMA_PINS, "remat_policy": "full"}).estimate_device_budget(
        1, gang_size=2)["total"]
    assert full_total < none_total, \
        "full remat must shrink the estimated gang footprint"
    limit = (none_total + full_total) // 2
    monkeypatch.setenv("RAFIKI_DEVICE_HBM_BYTES", str(limit))

    def run_worker(policy, n_trials):
        adv = make_advisor(LlamaLoRA.get_knob_config(), "random",
                           total_trials=n_trials, seed=6)
        worker = TrainWorker(
            LlamaLoRA, adv, tr, va, checkpoint_interval_s=0,
            knob_overrides={**LLAMA_PINS, "remat_policy": policy})
        n = worker.run_gang(gang_size=2, max_trials=n_trials)
        return n, worker.gang_engine

    n_full, eng_full = run_worker("full", 2)
    assert n_full == 2
    assert not eng_full._blocked_buckets, "full remat must be admitted"
    assert eng_full.n_buckets == 1  # ran as a real gang

    n_none, eng_none = run_worker("none", 1)
    assert n_none == 1  # refusal falls back, it does not strand trials
    reasons = list(eng_none._blocked_buckets.values())
    assert reasons and "remat_policy" in reasons[0]
    assert eng_none.n_buckets == 0  # nothing compiled as a gang


def test_gang_estimator_matches_measured_resident_pool(text_data):
    """Estimator-vs-measured: the params+opt components of
    ``estimate_gang_device_bytes`` must agree with the bytes a live
    4-lane executor actually keeps resident (broadcast base + stacked
    lane states) — the admission verdict is grounded, not folklore."""
    import jax

    from rafiki_tpu.models.llama_lora import estimate_gang_device_bytes
    from rafiki_tpu.tuning.gang import _VmapExec

    import random

    from rafiki_tpu.model.knob import sample_knobs

    tr, va = text_data
    knobs = {**sample_knobs(LlamaLoRA.get_knob_config(),
                            random.Random(0)), **LLAMA_PINS}
    est = estimate_gang_device_bytes(
        LlamaLoRA(**knobs)._module(),
        batch_size=int(knobs["batch_size"]), gang_size=4)
    spec = LlamaLoRA.make_gang_spec(knobs, tr, va)
    exec_ = _VmapExec(spec, 4)
    for i in range(4):
        exec_.fill_lane(i, knobs, None)
    measured = sum(
        leaf.nbytes for leaf in jax.tree_util.tree_leaves(exec_.state))
    # stacked lane states = K x (trainable + 2 x adam moments); the
    # estimator's params component additionally carries the broadcast
    # base, so compare against (params - base) + opt where base is the
    # K-independent remainder
    predicted = est["params"] + est["opt"] - est["base"]
    assert abs(measured - predicted) / predicted < 0.05, \
        (measured, predicted)
