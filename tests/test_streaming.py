"""Streaming generation end to end (engine → worker → predictor → SSE).

The reference predictor is strictly request/response (SURVEY.md §3.3);
token streaming is a beyond-reference serving capability: the
continuous-batching engine's ``poll_partial`` deltas ride the ordinary
reply queue ahead of the final predictions message, the predictor
re-exposes them as ``predict_stream`` events, and ``PredictorService``
serves them as server-sent events consumed by ``Client.predict_stream``.
"""

import threading

import numpy as np
import pytest

from rafiki_tpu.models.llama_lora import LlamaLoRA
from rafiki_tpu.serving.predictor import Predictor, PredictorService
from rafiki_tpu.serving.queues import InProcQueueHub
from rafiki_tpu.store.param_store import ParamStore
from rafiki_tpu.worker.inference import InferenceWorker

from test_decode_engine import KNOBS  # noqa: F401 — shared knobs


def test_engine_poll_partial_streams_exact_prefixes(trained):  # noqa: F811
    """Deltas collected while a request is live concatenate to a prefix
    of the final text, and the final text extends it exactly (the tail
    may finish inside the completing fused step, which never produces a
    partial event). steps_per_sync=1 guarantees at least one partial
    for a multi-token generation."""
    eng = trained.make_decode_engine(max_slots=2, max_new_tokens=6,
                                    steps_per_sync=1, prefill_chunk=1)
    eng.submit("a", "tok1 tok2 tok3")
    eng.submit("b", "tok4 tok5")
    deltas = {"a": [], "b": []}
    finals = {}
    for _ in range(200):
        if not eng.busy:
            break
        eng.step()
        for rid, d in eng.poll_partial():
            assert d, "empty deltas must be dropped"
            deltas[rid].append(d)
        for rid, text in eng.poll():
            finals[rid] = text
    assert set(finals) == {"a", "b"}
    for rid in ("a", "b"):
        streamed = "".join(deltas[rid])
        assert finals[rid].startswith(streamed)
        assert deltas[rid], "no partial events for a 6-token generation"
    # streaming state is cleaned up with the finished requests
    assert eng._stream_sent == {}


def test_text_stream_withholds_incomplete_utf8():
    """A token boundary that splits a multi-byte character must not
    leak U+FFFD into the stream: the trailing replacement char is
    withheld until a later decode completes the byte sequence, keeping
    the delivered stream append-only (deltas concatenate exactly)."""
    from rafiki_tpu.serving.decode_engine import TextDecodeEngine

    eur = "€".encode("utf-8")  # 3 bytes

    class StubEngine:
        def __init__(self):
            self.partials = []

        def poll_partial(self):
            p, self.partials = self.partials, []
            return p

        def poll(self):
            return []

    def decode(ids):  # ids are raw utf-8 byte values here
        return bytes(ids).decode("utf-8", errors="replace")

    stub = StubEngine()
    eng = TextDecodeEngine(stub, lambda t: np.zeros(1, np.int32), decode)

    # "a" + first 2 bytes of € → trailing U+FFFD withheld
    stub.partials = [("r", [ord("a"), eur[0], eur[1]])]
    out = eng.poll_partial()
    assert out == [("r", "a")]
    # € completes, plus 'b': the delta starts where delivery stopped
    stub.partials = [("r", [ord("a"), eur[0], eur[1], eur[2], ord("b")])]
    out = eng.poll_partial()
    assert out == [("r", "a€b"[1:])]  # "€b"
    # nothing new → no event
    stub.partials = [("r", [ord("a"), eur[0], eur[1], eur[2], ord("b")])]
    assert eng.poll_partial() == []


def _stream_through_stack(trained, hub):
    """Shared body: predict_stream over a real worker decode loop on
    the given hub — deltas accumulate to exactly the final predictions,
    which equal the non-streaming answer for the same greedy request."""
    store = ParamStore.from_uri("mem://")
    store.save("t0", trained.dump_parameters())
    worker = InferenceWorker(LlamaLoRA, "t0", KNOBS, store, hub, "w0",
                             decode_loop=True, max_slots=4,
                             max_new_tokens=6)
    wt = threading.Thread(target=worker.run, daemon=True)
    wt.start()
    try:
        pred = Predictor(hub, ["w0"], gather_timeout=120.0)
        events = list(pred.predict_stream(["tok1 tok2 tok3", "tok4"]))
        assert events and events[-1].get("done") is True
        final = events[-1]
        assert "error" not in final
        preds = final["predictions"]
        assert len(preds) == 2 and all(isinstance(p, str) for p in preds)
        acc = {0: "", 1: ""}
        n_delta = 0
        for ev in events[:-1]:
            assert set(ev) == {"delta"}
            for k, v in ev["delta"].items():
                acc[int(k)] += v
                n_delta += 1
        assert n_delta >= 1, "stream produced no delta events"
        assert [acc[0], acc[1]] == preds
        # greedy: the streamed text equals the request/response answer
        plain, info = pred.predict(["tok1 tok2 tok3", "tok4"])
        assert info["workers_answered"] == 1
        assert plain == preds
    finally:
        worker.stop()
        wt.join(timeout=10)


@pytest.mark.slow
def test_predict_stream_through_stack(trained):  # noqa: F811
    _stream_through_stack(trained, InProcQueueHub())


@pytest.mark.slow
def test_predict_stream_sse_http_and_client(trained):  # noqa: F811
    """The SSE endpoint over a real socket, consumed by the client SDK
    generator: same delta-accumulation invariant, served as
    text/event-stream with connection-close framing."""
    from rafiki_tpu.client.client import Client

    store = ParamStore.from_uri("mem://")
    store.save("t0", trained.dump_parameters())
    hub = InProcQueueHub()
    worker = InferenceWorker(LlamaLoRA, "t0", KNOBS, store, hub, "w0",
                             decode_loop=True, max_slots=4,
                             max_new_tokens=6)
    wt = threading.Thread(target=worker.run, daemon=True)
    wt.start()
    svc = PredictorService(Predictor(hub, ["w0"], gather_timeout=120.0))
    host, port = svc.start()
    try:
        client = Client.__new__(Client)  # predictor-only use: no admin
        client.timeout = 120.0
        events = list(client.predict_stream(
            f"http://{host}:{port}", ["tok1 tok2 tok3"], timeout=120.0))
        assert events and events[-1].get("done") is True
        preds = events[-1]["predictions"]
        acc = ""
        for ev in events[:-1]:
            acc += "".join(ev["delta"].values())
        assert acc == preds[0]
        assert isinstance(preds[0], str) and preds[0]
    finally:
        svc.stop()
        worker.stop()
        wt.join(timeout=10)


@pytest.mark.slow
def test_predict_stream_over_native_kv_transport(trained):  # noqa: F811
    """Same contract over the native rafiki-kvd RESP transport:
    per-query FIFO holds and the armed TTL tolerates the extra partial
    messages (one shared body with the in-proc leg)."""
    from rafiki_tpu.native import KVServer
    from rafiki_tpu.serving.queues import KVQueueHub

    with KVServer() as server:
        _stream_through_stack(trained, KVQueueHub(server.host,
                                                  server.port))


class _ScriptedHub:
    """Minimal hub double: returns a scripted sequence of reply
    payloads for pop_prediction; records pushes/discards."""

    def __init__(self, replies):
        from rafiki_tpu.serving.queues import pack_message

        self._replies = [pack_message(r) for r in replies]
        self.pushed = []
        self.discarded = []

    def arm_reply_ttl(self, qid, ttl):
        pass

    def push_query(self, wid, msg):
        self.pushed.append((wid, msg))

    def pop_prediction(self, qid, timeout):
        if self._replies:
            return self._replies.pop(0)
        import time as _time

        _time.sleep(min(timeout, 0.005))  # mirror real blocking pops
        return None

    def discard_prediction_queue(self, qid):
        self.discarded.append(qid)


def test_predict_stream_terminal_contract_replace_error_timeout():
    """The documented event contract, exercised branch by branch:
    a diverging final text arrives as a REPLACE event (never a delta a
    concatenating client would double-count); worker errors and
    timeouts both end in done events carrying the accumulated partial
    text; the reply queue is discarded in every outcome."""
    # replace: final text does NOT extend the streamed prefix
    hub = _ScriptedHub([
        {"id": "x", "worker_id": "w0", "delta": {"0": "abc"}},
        {"id": "x", "worker_id": "w0", "predictions": ["zzz"]}])
    pred = Predictor(hub, ["w0"], gather_timeout=5.0)
    events = list(pred.predict_stream(["q"]))
    kinds = [next(iter(e)) for e in events]
    assert kinds == ["delta", "replace", "done"]
    assert events[1]["replace"] == {"0": "zzz"}
    assert events[-1]["predictions"] == ["zzz"]
    assert hub.discarded, "reply queue must be discarded"

    # worker error: done carries the error AND the partial text
    hub = _ScriptedHub([
        {"id": "x", "worker_id": "w0", "delta": {"0": "par"}},
        {"id": "x", "worker_id": "w0", "predictions": [],
         "error": "boom"}])
    events = list(Predictor(hub, ["w0"],
                            gather_timeout=5.0).predict_stream(["q"]))
    final = events[-1]
    assert final["done"] and final["error"] == "boom"
    assert final["partial"] == ["par"]
    assert hub.discarded

    # timeout: same terminal shape. Streams default to STREAM_TIMEOUT
    # (minutes — gather_timeout is a unary bound), so pass an explicit
    # per-request deadline
    hub = _ScriptedHub([
        {"id": "x", "worker_id": "w0", "delta": {"0": "pa"}}])
    events = list(Predictor(hub, ["w0"], gather_timeout=5.0)
                  .predict_stream(["q"], timeout=0.05))
    final = events[-1]
    assert final["done"] and "timed out" in final["error"]
    assert final["partial"] == ["pa"]
    assert hub.discarded
