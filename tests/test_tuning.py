"""Gang-compiled tuning engine: equivalence vs the sequential/process
paths, static-bucket compile accounting, and the worker/dev plumbing.

The load-bearing claims (ISSUE 8 acceptance):
- a 1-lane gang run scores IDENTICALLY to the sequential ``tune_model``
  path on the MLP template (vmapped lane == sequential trial);
- ASHA/BOHB culls the same trial set in gang mode as in process mode
  for a fixed seed (same proposals, same scores, same promotions);
- compile count equals the number of static knob buckets, not the
  number of trials (asserted via the jitted step's compilation cache).
"""

import numpy as np
import pytest

from rafiki_tpu.advisor import make_advisor
from rafiki_tpu.model import tune_model
from rafiki_tpu.models.mlp import JaxFeedForward
from rafiki_tpu.models.tabular import JaxTabularMLP
from rafiki_tpu.tuning import GangEngine, supports_gang

#: shape pins so every proposal lands in ONE static bucket (the knobs
#: the advisor still searches — learning_rate (+ dropout for tabular) —
#: are traceable, i.e. per-lane traced operands)
MLP_PINS = {"hidden_layer_count": 1, "hidden_layer_units": 24,
            "batch_size": 32}
TAB_PINS = {"hidden_layer_count": 2, "hidden_layer_units": 32,
            "batch_size": 128}


@pytest.fixture(scope="module")
def image_data(tmp_path_factory):
    from rafiki_tpu.data import generate_image_classification_dataset

    d = tmp_path_factory.mktemp("gang_img")
    tr, va = str(d / "tr.npz"), str(d / "va.npz")
    generate_image_classification_dataset(tr, 256, seed=0)
    generate_image_classification_dataset(va, 96, seed=1)
    return tr, va


@pytest.fixture(scope="module")
def table_data(tmp_path_factory):
    from rafiki_tpu.data import generate_tabular_dataset

    d = tmp_path_factory.mktemp("gang_tab")
    tr, va = str(d / "tr.npz"), str(d / "va.npz")
    generate_tabular_dataset(tr, 384, seed=0)
    generate_tabular_dataset(va, 128, seed=1)
    return tr, va


def result_tuples(results):
    return [(r.trial_no, r.score, r.budget_scale, r.meta.get("rung"),
             r.meta.get("parent_trial_no")) for r in results]


def test_supports_gang_detection():
    from rafiki_tpu.models.resnet import ResNetClassifier

    assert supports_gang(JaxFeedForward)
    assert supports_gang(JaxTabularMLP)
    # gang_epochs without make_gang_spec is not enough
    assert not supports_gang(ResNetClassifier)
    with pytest.raises(ValueError, match="make_gang_spec"):
        GangEngine(ResNetClassifier, object(), "tr", "va", mode="gang")


def test_one_lane_gang_scores_equal_sequential_tune_model(image_data):
    """ISSUE criterion (a): a 1-lane gang IS a sequential trial — same
    proposals, bit-equal scores (vmap over one lane changes nothing)."""
    tr, va = image_data
    seq = tune_model(JaxFeedForward, tr, va, total_trials=3,
                     advisor_type="random", seed=7)
    adv = make_advisor(JaxFeedForward.get_knob_config(), "random",
                       total_trials=3, seed=7)
    eng = GangEngine(JaxFeedForward, adv, tr, va, gang_size=1,
                     mode="gang")
    results = eng.run()
    assert [r.knobs for r in results] == [t.knobs for t in seq.trials]
    assert [r.score for r in results] == [t.score for t in seq.trials]
    assert adv.best_effort.score == seq.best_score


def test_gang_asha_culls_match_process_mode(table_data):
    """ISSUE criterion (b): same seed → gang mode and process mode feed
    the advisor identical scores in identical order, so BOHB promotes
    (and therefore culls) the same trial set. Covers two traceable
    knobs (lr + dropout) and in-lane warm-started promotions."""
    tr, va = table_data
    kc = JaxTabularMLP.get_knob_config()
    a_gang = make_advisor(kc, "bohb", total_trials=8, seed=5)
    e_gang = GangEngine(JaxTabularMLP, a_gang, tr, va, gang_size=4,
                        mode="gang", knob_overrides=TAB_PINS)
    r_gang = e_gang.run()
    a_proc = make_advisor(kc, "bohb", total_trials=8, seed=5)
    e_proc = GangEngine(JaxTabularMLP, a_proc, tr, va, gang_size=4,
                        mode="sequential", knob_overrides=TAB_PINS)
    r_proc = e_proc.run()
    assert result_tuples(r_gang) == result_tuples(r_proc)
    promoted_gang = {r.meta.get("parent_trial_no") for r in r_gang
                     if r.meta.get("parent_trial_no") is not None}
    promoted_proc = {r.meta.get("parent_trial_no") for r in r_proc
                     if r.meta.get("parent_trial_no") is not None}
    assert promoted_gang == promoted_proc
    culled_gang = {r.trial_no for r in r_gang} - promoted_gang
    culled_proc = {r.trial_no for r in r_proc} - promoted_proc
    assert culled_gang == culled_proc
    assert promoted_gang, "fixture must exercise at least one promotion"
    # every proposal shared the pinned bucket: exactly one compile total
    assert e_gang.n_buckets == 1
    assert list(e_gang.compile_counts().values()) == [1]
    assert a_gang.best_effort.score == a_proc.best_effort.score


def test_compile_count_equals_static_buckets_not_trials(image_data):
    """ISSUE criterion (c): with batch_size free (a shape knob) trials
    spread over up to 3 buckets; the jitted step count — via JAX's own
    compilation cache — must equal the bucket count, NOT the trial
    count."""
    tr, va = image_data
    pins = {"hidden_layer_count": 1, "hidden_layer_units": 24}
    adv = make_advisor(JaxFeedForward.get_knob_config(), "random",
                       total_trials=6, seed=2)
    eng = GangEngine(JaxFeedForward, adv, tr, va, gang_size=2,
                     mode="gang", knob_overrides=pins)
    results = eng.run()
    assert len(results) == 6
    batch_sizes = {r.knobs["batch_size"] for r in results}
    assert len(batch_sizes) >= 2, "seed must spread over buckets"
    assert eng.n_buckets == len(batch_sizes)
    assert len(results) > eng.n_buckets
    counts = eng.compile_counts()
    # one executable per bucket: no silent per-trial recompiles
    assert list(counts.values()) == [1] * len(batch_sizes)


def test_gang_max_trials_cap_enforced_mid_session(image_data):
    """Regression: the cap bounds trials STARTED on every lane refill,
    not just between bucket sessions — and proposals pulled but never
    laned are released back to the advisor (no stranded outstanding
    slots)."""
    tr, va = image_data
    adv = make_advisor(JaxFeedForward.get_knob_config(), "random",
                       total_trials=64, seed=0)
    eng = GangEngine(JaxFeedForward, adv, tr, va, gang_size=2,
                     mode="gang", knob_overrides=MLP_PINS)
    results = eng.run(max_trials=4)
    assert len(results) == 4
    assert eng.stats["trials_started"] == 4
    assert not adv._outstanding


def test_tune_model_gang_path_and_override_validation(image_data):
    tr, va = image_data
    res = tune_model(JaxFeedForward, tr, va, total_trials=4,
                     advisor_type="random", seed=3, gang_size=2,
                     knob_overrides=MLP_PINS)
    assert len(res.trials) == 4
    assert res.best_score == max(t.score for t in res.trials)
    assert res.best_params and "params" in res.best_params
    # the dev loop now fails fast on typo'd override keys, exactly like
    # the admin API's job-level validation (shared validator)
    with pytest.raises(ValueError, match="knob_overrides.*learnin_rate"):
        tune_model(JaxFeedForward, tr, va, total_trials=1,
                   knob_overrides={"learnin_rate": 1e-3})
    with pytest.raises(ValueError, match="knob_overrides.*learnin_rate"):
        tune_model(JaxFeedForward, tr, va, total_trials=1, gang_size=2,
                   knob_overrides={"learnin_rate": 1e-3})


def test_tune_model_gang_falls_back_without_spec(tmp_path):
    """A template without a gang spec warns and runs the sequential
    loop — gang_size is a hint, not a hard requirement."""
    from rafiki_tpu.model import BaseModel, FixedKnob

    calls = []

    class _Toy(BaseModel):
        @staticmethod
        def get_knob_config():
            return {"c": FixedKnob(1)}

        def train(self, dataset_path, ctx=None):
            calls.append("train")

        def evaluate(self, dataset_path):
            return 0.5

        def predict(self, queries):
            return [0.0 for _ in queries]

        def dump_parameters(self):
            return {"w": np.zeros(1)}

        def load_parameters(self, params):
            pass

    with pytest.warns(UserWarning, match="no gang spec"):
        res = tune_model(_Toy, "tr", "va", total_trials=2,
                         advisor_type="random", gang_size=4)
    assert calls == ["train", "train"]
    assert res.best_score == 0.5


def test_gang_obs_gauges_ride_metrics_registry(image_data):
    from rafiki_tpu.obs import MetricsRegistry

    tr, va = image_data
    reg = MetricsRegistry()
    adv = make_advisor(JaxFeedForward.get_knob_config(), "bohb",
                       total_trials=6, seed=1)
    eng = GangEngine(JaxFeedForward, adv, tr, va, gang_size=3,
                     mode="gang", knob_overrides=MLP_PINS, metrics=reg)
    results = eng.run()
    snap = reg.snapshot()
    assert snap["gang_lanes_active"] == 0  # drained at exit
    assert snap["trials_per_hour"] > 0
    assert snap["gang_lanes_culled_total"] == sum(
        1 for r in results if r.budget_scale < 1.0 - 1e-9)
    assert eng.stats["trials_completed"] == len(results)


def test_train_worker_gang_mode(image_data):
    """Worker plumbing: run_gang reports one completed trial per lane
    through the worker's stores/counters (dashboard parity with process
    trials)."""
    from rafiki_tpu.worker.train import TrainWorker

    tr, va = image_data
    adv = make_advisor(JaxFeedForward.get_knob_config(), "random",
                       total_trials=4, seed=9)
    worker = TrainWorker(JaxFeedForward, adv, tr, va,
                         knob_overrides=MLP_PINS,
                         checkpoint_interval_s=0)
    n = worker.run_gang(gang_size=2)
    assert n == 4
    assert worker.trials_run == 4
    snap = worker.metrics.snapshot()
    assert snap["trials_completed"] == 4
    assert snap["gang_lanes_active"] == 0
    assert snap["trials_per_hour"] > 0
    # params of every lane-trial landed in the worker's ParamStore
    for r in adv.results:
        assert worker.param_store.load(r.trial_id) is not None
