"""Unit tests for the whole-program thread model (analysis/threads.py).

Each test builds a tiny on-disk project (the model resolves targets
through ProjectContext, so sources must live in files) and asserts on
root discovery, context reachability, witness traces, and the
happens-before exemptions the race rules lean on.

No jax import, no device work — runs in milliseconds.
"""

import textwrap

from rafiki_tpu.analysis.project import ProjectContext
from rafiki_tpu.analysis.threads import MAIN, ThreadModel


def _model(tmp_path, **modules):
    # module names include the root dir basename: pin it to ``proj``
    # so qualnames are stable (``proj.svc:Svc._run``)
    root = tmp_path / "proj"
    root.mkdir()
    for name, src in modules.items():
        (root / f"{name}.py").write_text(textwrap.dedent(src))
    return ThreadModel(ProjectContext([str(root)]))


def _root(model, kind=None):
    roots = [r for r in model.roots if kind is None or r.kind == kind]
    assert len(roots) == 1, [r.label for r in model.roots]
    return roots[0]


# ---- root discovery ----

def test_discovers_thread_target_method(tmp_path):
    model = _model(tmp_path, svc="""\
        import threading

        class Svc:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                pass
        """)
    root = _root(model, "thread")
    assert root.target == "proj.svc:Svc._run"
    assert root.spawner == "proj.svc:Svc.start"
    assert root.daemon
    assert not root.multi


def test_discovers_nested_def_loop_target(tmp_path):
    model = _model(tmp_path, svc="""\
        import threading

        class Svc:
            def start(self):
                def loop():
                    self.tick()
                threading.Thread(target=loop).start()

            def tick(self):
                pass
        """)
    root = _root(model, "thread")
    assert root.target == "proj.svc:Svc.start.<locals>.loop"
    assert not root.daemon
    # the synthetic nested-def entry reaches through to tick()
    assert root.label in model.contexts_of("proj.svc:Svc.tick")


def test_discovers_timer_and_executor_and_handler_roots(tmp_path):
    model = _model(tmp_path, svc="""\
        import threading

        class Svc:
            def __init__(self, http, pool):
                http.route("GET", "/stats", self._stats)
                pool.submit(self._warm)

            def kick(self):
                threading.Timer(5.0, self._expire).start()

            def _stats(self, request):
                pass

            def _warm(self):
                pass

            def _expire(self):
                pass
        """)
    kinds = {r.kind: r for r in model.roots}
    assert set(kinds) == {"timer", "executor", "handler"}
    assert kinds["handler"].target == "proj.svc:Svc._stats"
    assert kinds["executor"].target == "proj.svc:Svc._warm"
    assert kinds["timer"].target == "proj.svc:Svc._expire"
    # handlers and executor tasks run arbitrarily many instances
    assert kinds["handler"].multi
    assert kinds["executor"].multi
    assert not kinds["timer"].multi


def test_spawn_inside_loop_is_multi_instance(tmp_path):
    model = _model(tmp_path, svc="""\
        import threading

        class Pool:
            def start(self, n):
                for _ in range(n):
                    threading.Thread(target=self._worker).start()

            def _worker(self):
                pass
        """)
    root = _root(model, "thread")
    assert root.multi
    assert model.is_multi(root.label)


# ---- reachability + traces ----

def test_reachability_propagates_through_calls(tmp_path):
    model = _model(tmp_path, svc="""\
        import threading

        class Svc:
            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                self._step()

            def _step(self):
                pass

            def api(self):
                self._step()
        """)
    label = _root(model, "thread").label
    # _step runs under BOTH the thread (via _run) and main (via api)
    assert model.contexts_of("proj.svc:Svc._step") == {label, MAIN}
    assert model.contexts_of("proj.svc:Svc._run") == {label}
    # api has no resolved caller: main-seeded
    assert model.contexts_of("proj.svc:Svc.api") == {MAIN}


def test_trace_walks_spawn_site_to_access_function(tmp_path):
    model = _model(tmp_path, svc="""\
        import threading

        class Svc:
            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                self._step()

            def _step(self):
                pass
        """)
    label = _root(model, "thread").label
    steps = model.trace(label, "proj.svc:Svc._step")
    assert len(steps) == 2
    assert "spawned" in steps[0].note and "_run" in steps[0].note
    assert "_run" in steps[1].note and "_step" in steps[1].note
    assert all(s.path.endswith("svc.py") for s in steps)
    # not reachable under a context -> empty witness
    assert model.trace(label, "proj.svc:Svc.start") == ()


# ---- happens-before exemptions ----

def test_writes_before_start_happen_before_the_thread(tmp_path):
    model = _model(tmp_path, svc="""\
        class Svc:
            def start(self, threading):
                self.n = 0
                t = threading.Thread(target=self._run)
                t.start()
                self.n = 1

            def _run(self):
                pass
        """)
    root = _root(model, "thread")
    before, after = 3, 6
    assert model.happens_before("proj.svc:Svc.start", before, root.label)
    assert not model.happens_before("proj.svc:Svc.start", after, root.label)


def test_setup_closure_writes_happen_before_foreign_roots(tmp_path):
    model = _model(tmp_path, svc="""\
        import threading

        class Sink:
            def __init__(self):
                self.n = 0
                self._configure()

            def _configure(self):
                self.n = 1

        class Driver:
            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                pass
        """)
    root = _root(model, "thread")
    # __init__ and its private helper finish before the object can be
    # handed to any thread
    assert "_configure" in model.setup_closure("proj.svc:Sink")
    assert model.happens_before("proj.svc:Sink.__init__", 5, root.label)
    assert model.happens_before("proj.svc:Sink._configure", 8, root.label)


def test_self_escape_during_construction_is_not_exempt(tmp_path):
    model = _model(tmp_path, svc="""\
        import threading

        class Svc:
            def __init__(self):
                threading.Thread(target=self._run).start()
                self.n = 0

            def _run(self):
                pass
        """)
    root = _root(model, "thread")
    # the same __init__ spawned the thread before the write: no edge
    assert not model.happens_before("proj.svc:Svc.__init__", 6, root.label)
