"""Sharded checkpointing (SURVEY §5.4): per-shard files, per-process
write bounds, async donation-safe saves, cross-topology restore, and
resume parity with the whole-blob path."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from rafiki_tpu.store.param_store import ParamStore
from rafiki_tpu.store.sharded_ckpt import (ShardedCheckpointer,
                                           ShardedCheckpointRef)


def _mesh(shape=(4, 2)):
    devs = np.array(jax.devices()[:shape[0] * shape[1]],
                    dtype=object).reshape(shape)
    return Mesh(devs, ("data", "model"))


def _tree(mesh):
    """A mixed tree: 2-D sharded, 1-D sharded, replicated, plain numpy."""
    w = jax.device_put(
        jnp.arange(64 * 32, dtype=jnp.float32).reshape(64, 32),
        NamedSharding(mesh, P("data", "model")))
    e = jax.device_put(jnp.arange(128, dtype=jnp.float32).reshape(16, 8),
                       NamedSharding(mesh, P("data")))
    r = jax.device_put(jnp.ones((8,), jnp.float32),
                       NamedSharding(mesh, P()))
    return {"a": {"w": w, "e": e}, "r": r,
            "host": np.arange(6, dtype=np.int32)}


def test_roundtrip_same_topology(tmp_path):
    mesh = _mesh()
    tree = _tree(mesh)
    ck = ShardedCheckpointer(str(tmp_path))
    ck.save("t0", tree)
    out = ck.restore("t0", tree)
    for (kp, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(tree)[0],
            jax.tree_util.tree_flatten_with_path(out)[0]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=str(kp))
    # sharded leaves restore INTO their shardings
    assert out["a"]["w"].sharding == tree["a"]["w"].sharding
    # the big leaf is stored as multiple per-shard files, not one blob
    d = ck._dir("t0")
    import os
    w_files = [f for f in os.listdir(d) if f.startswith("L")]
    assert len(w_files) > 4


def test_per_process_write_bound(tmp_path):
    """The disjoint-writer rule: simulate 4 processes each owning 2 of
    the 8 devices — every process writes < full-tree/4 bytes, the union
    reassembles exactly (the VERDICT r3 acceptance criterion)."""
    mesh = _mesh()
    tree = _tree(mesh)
    full_bytes = sum(np.asarray(x).nbytes
                     for x in jax.tree_util.tree_leaves(tree))
    ck = ShardedCheckpointer(str(tmp_path))
    devs = jax.devices()[:8]
    written = []
    for proc in range(4):
        mine = set(devs[2 * proc: 2 * proc + 2])

        def owns(shard, mine=mine):
            return shard.replica_id == 0 and shard.device in mine

        # all processes plan identical manifests; files accumulate
        written.append(ck.save("t0", tree, owns=owns,
                               process_index=proc))
    # each simulated process stayed under a quarter of the tree
    for w in written[1:]:  # process 0 also writes the replicated+host
        assert 0 < w < full_bytes / 4, (w, full_bytes)
    out = ck.restore("t0", tree)
    for a, b in zip(jax.tree_util.tree_leaves(tree),
                    jax.tree_util.tree_leaves(out)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_cross_topology_restore(tmp_path):
    """Save on a (4,2) mesh, restore onto (2,4) and onto plain host
    arrays — shard files are assembled by overlap, not by matching."""
    mesh_a = _mesh((4, 2))
    tree = _tree(mesh_a)
    ck = ShardedCheckpointer(str(tmp_path))
    ck.save("t0", tree)

    mesh_b = _mesh((2, 4))
    tmpl_b = _tree(mesh_b)
    out_b = ck.restore("t0", tmpl_b)
    for a, b in zip(jax.tree_util.tree_leaves(tree),
                    jax.tree_util.tree_leaves(out_b)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert out_b["a"]["w"].sharding == tmpl_b["a"]["w"].sharding

    host_tmpl = jax.tree_util.tree_map(np.asarray, tree)
    out_h = ck.restore("t0", host_tmpl)
    np.testing.assert_array_equal(np.asarray(out_h["a"]["w"]),
                                  np.asarray(tree["a"]["w"]))


def test_async_save_and_error_surfacing(tmp_path):
    mesh = _mesh()
    tree = _tree(mesh)
    ck = ShardedCheckpointer(str(tmp_path))
    ck.save_async("t0", tree)
    ck.wait()
    out = ck.restore("t0", tree)
    np.testing.assert_array_equal(np.asarray(out["a"]["w"]),
                                  np.asarray(tree["a"]["w"]))
    # ref handle waits for in-flight saves via ParamStore.sharded_ref
    ck.save_async("t1", tree)
    ref = ShardedCheckpointRef(ck, "t1")
    ck.wait()
    assert ref.exists()


def test_param_store_integration(tmp_path):
    store = ParamStore.from_uri(f"file://{tmp_path}/params")
    mesh = _mesh()
    tree = _tree(mesh)
    assert store.save_sharded_async("ckpt-x", tree) is True
    ref = store.sharded_ref("ckpt-x")
    assert ref is not None and store.exists_sharded("ckpt-x")
    out = ref.restore(tree)
    np.testing.assert_array_equal(np.asarray(out["a"]["w"]),
                                  np.asarray(tree["a"]["w"]))
    # copy (the resume pre-seed) and unified delete
    assert store.copy_sharded("ckpt-x", "ckpt-y")
    assert store.exists_sharded("ckpt-y")
    store.delete("ckpt-x")
    assert not store.exists_sharded("ckpt-x")
    # mem backend: cleanly reports no sharded support
    mem = ParamStore.from_uri("mem://")
    assert mem.save_sharded_async("k", tree) is False
    assert mem.sharded_ref("k") is None


def test_partial_checkpoint_is_loud(tmp_path):
    import os

    mesh = _mesh()
    tree = _tree(mesh)
    ck = ShardedCheckpointer(str(tmp_path))
    ck.save("t0", tree)
    d = ck._dir("t0")
    victim = next(f for f in sorted(os.listdir(d))
                  if f.startswith("L0000"))
    os.unlink(os.path.join(d, victim))
    with pytest.raises((ValueError, FileNotFoundError)):
        ck.restore("t0", tree)




def test_trial_resume_sharded_matches_blob(tmp_path):
    """A preempted trial that checkpoints SHARDED resumes to the exact
    same result as the whole-blob path (VERDICT r3 item 3)."""
    from typing import Optional

    from rafiki_tpu.advisor.base import make_advisor
    from rafiki_tpu.model.base import BaseModel, TrainContext
    from rafiki_tpu.model.knob import FixedKnob, PolicyKnob
    from rafiki_tpu.store.meta_store import MetaStore
    from rafiki_tpu.worker.train import TrainWorker

    mesh = _mesh((8, 1))

    class ShardedToy(BaseModel):
        """w += 1 per epoch over a SHARDED device array; checkpoints
        pass the live tree so sharded-capable stores use it."""

        TASKS = ("IMAGE_CLASSIFICATION",)
        FAIL_AT: Optional[int] = None

        @staticmethod
        def get_knob_config():
            return {"max_epochs": FixedKnob(5),
                    "share_params": PolicyKnob("SHARE_PARAMS")}

        def __init__(self, **knobs):
            super().__init__(**knobs)
            self._w = None

        def train(self, dataset_path, ctx=None):
            ctx = ctx or TrainContext()
            w = jax.device_put(jnp.zeros((16, 8), jnp.float32),
                               NamedSharding(mesh, P("data")))
            if ctx.shared_params is not None and \
                    self.knobs.get("share_params"):
                if hasattr(ctx.shared_params, "restore"):
                    w = ctx.shared_params.restore({"w": w})["w"]
                else:
                    w = jnp.asarray(ctx.shared_params["w"])
            epochs = max(1, round(5 * float(ctx.budget_scale)))
            for epoch in range(epochs):
                w = w + 1.0
                self._w = w  # blob fallback calls dump_parameters()
                if ctx.checkpoint is not None:
                    ctx.checkpoint(self.dump_parameters,
                                   frac_done=(epoch + 1) / epochs,
                                   tree={"w": w})
                if self.FAIL_AT is not None and epoch >= self.FAIL_AT:
                    raise OSError("simulated preemption")

        def evaluate(self, dataset_path):
            return float(np.asarray(self._w).mean())

        def predict(self, queries):
            return [0 for _ in queries]

        def dump_parameters(self):
            return {"w": np.asarray(self._w)}

        def load_parameters(self, params):
            self._w = jnp.asarray(params["w"])

    class Flaky(ShardedToy):
        FAIL_AT = 2

    def run_scenario(store):
        meta = MetaStore(":memory:")
        user = meta.create_user("u@x", "pw", "ADMIN")
        model = meta.create_model(user["id"], "toy",
                                  "IMAGE_CLASSIFICATION", "T", b"")
        job = meta.create_train_job(user["id"], "app", 1,
                                    "IMAGE_CLASSIFICATION",
                                    {"TRIAL_COUNT": 1}, "tr", "va")
        sub = meta.create_sub_train_job(job["id"], model["id"])

        def worker(model_class, wid, trials):
            return TrainWorker(
                model_class=model_class,
                advisor=make_advisor(model_class.get_knob_config(),
                                     "random", total_trials=trials),
                train_dataset_path="u", val_dataset_path="u",
                param_store=store, meta_store=meta,
                sub_train_job_id=sub["id"], model_id=model["id"],
                worker_id=wid, checkpoint_interval_s=1e-9)

        worker(Flaky, "w0", 1).run(max_trials=1)
        w2 = worker(ShardedToy, "w1", 0)
        assert w2.resume_orphaned_trials() == 1
        done = [t for t in meta.get_trials_of_sub_train_job(sub["id"])
                if t["status"] == "COMPLETED"]
        assert len(done) == 1
        return done[0]["score"]

    blob_score = run_scenario(ParamStore.from_uri("mem://"))
    sharded_store = ParamStore.from_uri(f"file://{tmp_path}/ps")
    sharded_score = run_scenario(sharded_store)
    assert sharded_score == blob_score == 5.0
    # and the sharded path actually used the sharded store
    root = sharded_store.sharded_checkpointer().root
    import os
    assert os.path.isdir(root)

def test_manifests_identical_across_processes(tmp_path):
    """File names come from the GLOBAL sharding, so every process plans
    the identical manifest — no cross-host name collisions or
    under-described shards (the multi-host disjoint-writer rule)."""
    mesh = _mesh()
    tree = _tree(mesh)
    ck = ShardedCheckpointer(str(tmp_path))
    plans = [ck._plan(tree) for _ in range(3)]
    assert plans[0] == plans[1] == plans[2]
    # the 2-D-sharded leaf enumerates all 8 global shards
    w_entry = next(e for e in plans[0]["leaves"]
                   if e["path"] == ["a", "w"])
    assert len(w_entry["shards"]) == 8
    files = [s["file"] for s in w_entry["shards"]]
    assert len(set(files)) == 8  # unique, content-addressed names


def test_ref_matches_probe(tmp_path):
    mesh = _mesh()
    tree = _tree(mesh)
    ck = ShardedCheckpointer(str(tmp_path))
    ck.save("t0", tree)
    ref = ShardedCheckpointRef(ck, "t0")
    assert ref.matches(tree)
    wrong = dict(tree)
    wrong["a"] = {"w": np.zeros((4, 4), np.float32), "e": tree["a"]["e"]}
    assert not ref.matches(wrong)
    assert not ShardedCheckpointRef(ck, "absent").matches(tree)


def test_stale_async_error_does_not_escape_probes(tmp_path):
    """A failed async save surfaces in wait() but NOT in presence
    probes/cleanup (trial fault isolation: an earlier trial's disk
    error must not kill an unrelated resume scan)."""
    mesh = _mesh()
    tree = _tree(mesh)
    ck = ShardedCheckpointer(str(tmp_path))
    ck._pending_error = OSError("disk full (parked)")
    # quiet paths: no raise
    assert ck.exists("whatever") is False
    ck.delete("whatever")
    assert ck.copy("a", "b") is False
    store = ParamStore.from_uri(f"file://{tmp_path}/ps")
    store.sharded_checkpointer()._pending_error = OSError("parked")
    assert store.exists_sharded("x") is False
    assert store.sharded_ref("x") is None
    store.delete("x")  # must not raise
    # the loud path still reports (fresh error)
    ck._pending_error = OSError("disk full again")
    with pytest.raises(OSError):
        ck.wait()
