import numpy as np

from rafiki_tpu.data import (CorpusDataset, ImageClassificationDataset,
                             batch_iterator, bucket_pad,
                             generate_corpus_dataset,
                             generate_image_classification_dataset,
                             load_image_classification_dataset,
                             prefetch_to_device)


def test_image_dataset_round_trip(tmp_path):
    p = str(tmp_path / "ds.npz")
    ds = generate_image_classification_dataset(p, n_examples=64, seed=0)
    loaded = load_image_classification_dataset(p)
    assert loaded.images.shape == (64, 28, 28, 1)
    assert loaded.images.dtype == np.uint8
    assert loaded.n_classes == 10
    np.testing.assert_array_equal(loaded.labels, ds.labels)


def test_synthetic_dataset_is_learnable():
    ds = generate_image_classification_dataset("", n_examples=512, seed=0)
    # nearest-template classification should beat chance by a wide margin
    x = ds.images.astype(np.float32).reshape(len(ds), -1)
    means = np.stack([x[ds.labels == c].mean(0) for c in range(10)])
    pred = np.argmin(
        ((x[:, None, :] - means[None]) ** 2).sum(-1), axis=1)
    assert (pred == ds.labels).mean() > 0.6


def test_batch_iterator_static_shapes():
    x = np.arange(10, dtype=np.float32)[:, None]
    y = np.arange(10)
    batches = list(batch_iterator({"x": x, "y": y}, batch_size=4,
                                  shuffle=False))
    assert len(batches) == 3
    for b in batches:
        assert b["x"].shape == (4, 1)
        assert b["mask"].shape == (4,)
    assert batches[-1]["mask"].sum() == 2  # 10 = 4+4+2
    # all real rows seen exactly once
    seen = np.concatenate([b["y"][b["mask"]] for b in batches])
    assert sorted(seen.tolist()) == list(range(10))


def test_batch_iterator_drop_remainder():
    x = np.arange(10)[:, None]
    batches = list(batch_iterator({"x": x}, 4, shuffle=False,
                                  drop_remainder=True))
    assert len(batches) == 2


def test_prefetch_to_device():
    x = np.arange(12, dtype=np.float32)[:, None]
    it = batch_iterator({"x": x}, 4, shuffle=False)
    out = list(prefetch_to_device(it, size=2))
    assert len(out) == 3
    assert float(out[0]["x"][0, 0]) == 0.0


def test_bucket_pad():
    assert bucket_pad(3, [4, 8, 16]) == 4
    assert bucket_pad(9, [4, 8, 16]) == 16
    assert bucket_pad(100, [4, 8, 16]) == 16


def test_corpus_round_trip(tmp_path):
    p = str(tmp_path / "corpus.jsonl")
    ds = generate_corpus_dataset(p, n_sentences=50, seed=0)
    loaded = CorpusDataset.load(p)
    assert len(loaded) == 50
    assert loaded.tag_names == ds.tag_names
    toks, tags = loaded.sentences[0]
    assert len(toks) == len(tags)


def test_zip_and_dir_datasets(tmp_path):
    import zipfile

    from PIL import Image

    ds = generate_image_classification_dataset("", n_examples=6, seed=0)
    # dir layout
    d = tmp_path / "imgdir"
    d.mkdir()
    rows = []
    for i in range(6):
        name = f"im{i}.png"
        Image.fromarray(ds.images[i, :, :, 0]).save(d / name)
        rows.append(f"{name},class_{ds.labels[i]}")
    (d / "labels.csv").write_text("path,class\n" + "\n".join(rows) + "\n")
    loaded = load_image_classification_dataset(str(d))
    assert len(loaded) == 6
    # zip layout
    zp = tmp_path / "img.zip"
    with zipfile.ZipFile(zp, "w") as z:
        for f in d.iterdir():
            z.write(f, f.name)
    loaded2 = load_image_classification_dataset(str(zp))
    assert len(loaded2) == 6
    np.testing.assert_array_equal(
        np.sort(loaded.labels), np.sort(loaded2.labels))


def test_fashion_archive_round_trip(tmp_path):
    """The FashionMNIST-layout fixture (VERDICT r4 item 7): real PNG
    bytes in a zip + labels.csv with the published class names, read
    back bit-exact through the archive loader."""
    import zipfile

    from rafiki_tpu.data import (FASHION_CLASSES,
                                 generate_fashion_archive,
                                 load_image_classification_dataset)

    zp = str(tmp_path / "fashion.zip")
    oracle = generate_fashion_archive(zp, n_examples=40, seed=3)

    with zipfile.ZipFile(zp) as z:
        names = z.namelist()
        assert "labels.csv" in names
        pngs = [n for n in names if n.endswith(".png")]
        assert len(pngs) == 40
        # REAL PNG byte format, not renamed arrays
        assert z.read(pngs[0])[:8] == b"\x89PNG\r\n\x1a\n"

    loaded = load_image_classification_dataset(zp)
    assert loaded.images.shape == (40, 28, 28, 1)
    assert loaded.class_names == sorted(FASHION_CLASSES)
    # PNG is lossless: pixel content survives exactly, labels align
    np.testing.assert_array_equal(loaded.images, oracle.images)
    np.testing.assert_array_equal(loaded.labels, oracle.labels)
