"""Test harness config: run JAX on a virtual 8-device CPU mesh.

This is the TPU-world analog of "multi-node on one box" (SURVEY.md §4):
sharding/collective code paths are exercised for real, just on host CPU.
Must run before jax initializes its backends, hence env vars at import time.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import pytest  # noqa: E402


@pytest.fixture()
def tmp_workdir(tmp_path):
    return tmp_path
