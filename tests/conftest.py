"""Test harness config: run JAX on a virtual 8-device CPU mesh.

This is the TPU-world analog of "multi-node on one box" (SURVEY.md §4):
sharding/collective code paths are exercised for real, just on host CPU.
Must run before jax initializes its backends, hence env vars at import time.
"""

import os

# Force-override: the image pre-sets JAX_PLATFORMS=axon (the TPU tunnel)
# and its sitecustomize imports jax at interpreter start, so the env var
# default is already baked — use jax.config instead, before any backend
# initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA-executable cache: OFF by default. It used to shave
# minutes off reruns, but on this jaxlib deserializing a cached CPU
# executable mid-suite SEGFAULTS the whole pytest process (reproduced
# deterministically: suite dies at the first test that gets a cache hit
# after enough prior compile state accumulates; passes start-to-finish
# with the cache disabled). Opt back in with RAFIKI_TEST_COMPILE_CACHE=1
# on a jax build where the cache is sound; the dir is keyed by jaxlib
# version so executables never cross versions.
if os.environ.get("RAFIKI_TEST_COMPILE_CACHE", "") == "1":
    import jaxlib

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(os.path.dirname(os.path.dirname(
                          os.path.abspath(__file__))), ".jax_cache",
                          getattr(jaxlib, "__version__", "unknown")))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
else:
    jax.config.update("jax_enable_compilation_cache", False)

import pytest  # noqa: E402


@pytest.fixture()
def tmp_workdir(tmp_path):
    return tmp_path


@pytest.fixture(scope="session")
def trained_lm(tmp_path_factory):
    """ONE tiny trained LM shared by every serving-side test file
    (decode engine, draft speculation, kv-int8, multi-adapter, paged
    KV, streaming) — previously each file's module-scoped copy re-ran
    the same training, ~5s a pop on the default leg. Tests treat it as
    read-only: engines and dumps never mutate ``_params``."""
    from test_decode_engine import KNOBS

    from rafiki_tpu.data import generate_text_classification_dataset
    from rafiki_tpu.models.llama_lora import LlamaLoRA

    d = tmp_path_factory.mktemp("lm_shared")
    tr = str(d / "train.jsonl")
    generate_text_classification_dataset(tr, 64, seed=0)
    m = LlamaLoRA(**KNOBS)
    m.train(tr)
    return m


@pytest.fixture(scope="session")
def trained(trained_lm):
    """Short name most serving tests use; ``trained_lm`` exists for
    files whose own module-level ``trained`` fixture shadows this one
    (e.g. test_worker_serving's sub-train-job fixture)."""
    return trained_lm
