"""Control-plane crash recovery: durable spawn state, service
re-adoption, admin fencing.

The admin is the last single point of failure after PR 7 made the data
plane survive worker death: these tests kill the control plane (by
abandoning its ServicesManager mid-flight, and — slow tier — by
``kill -9`` on a real driver process) and prove a restarted admin

- re-ADOPTS the previous admin's surviving children (identical pids,
  hardened ``(cmdline, start_time)`` identity, slots re-reserved, kvd
  data plane included, so in-flight streams never notice),
- flows dead rows (CRASHED) into the existing respawn path under the
  respawn budget PERSISTED in the MetaStore,
- reaps orphans whose job was stopped while no admin was alive,
- and is fenced by the single-writer lease: a duplicate admin on the
  same store refuses to boot, a stale one loses every mutating op.

Satellites covered here: the pid-recycle start-time guard, the
``claim_trial_for_resume`` two-claimant race, MetaStore online backup,
and the ``rafiki-tpu doctor --workdir`` drift audit.
"""

import json
import subprocess
import threading
import time
from pathlib import Path

import pytest

from rafiki_tpu.admin.proc import (AdoptedProcess, identity_matches,
                                   proc_start_time)
from rafiki_tpu.admin.services_manager import (AdminFencedError,
                                               LeaseHeldError,
                                               ServicesManager)
from rafiki_tpu.constants import ServiceStatus, ServiceType
from rafiki_tpu.parallel.mesh import DeviceSpec
from rafiki_tpu.store.meta_store import MetaStore


def _mgr(meta, path, n_devices=2):
    return ServicesManager(
        meta, str(path), slot_size=1, platform="cpu",
        devices=[DeviceSpec(id=i) for i in range(n_devices)])


def _running_inference_job(meta):
    user = meta.create_user(f"op{time.time_ns()}@x", "pw", "ADMIN")
    tj = meta.create_train_job(user["id"], f"app{time.time_ns()}", 1,
                               "LANGUAGE_MODELING", {"TRIAL_COUNT": 1},
                               "d1", "d2")
    ij = meta.create_inference_job(user["id"], tj["id"])
    meta.update_inference_job(ij["id"], status="RUNNING")
    return ij


def _spawn_dummy(mgr, wd, ij_id, wid, slot=True):
    return mgr._spawn(
        "rafiki_tpu.chaos.dummy_service",
        {"worker_id": wid, "drain_linger_s": 0.2,
         "obs_port_file": str(Path(wd) / f"{wid}.obs_port")},
        ServiceType.INFERENCE_WORKER,
        slot=mgr.allocator.acquire(timeout=5.0) if slot else None,
        inference_job_id=ij_id)


def _wait_ports(wd, wids, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all((Path(wd) / f"{w}.obs_port").exists() for w in wids):
            return
        time.sleep(0.05)
    raise TimeoutError(f"obs ports never appeared for {wids}")


# ------------------------------------------------- durable spawn state

def test_spawn_records_durable_state(tmp_path):
    """The service row carries the FULL spawn recipe plus the pid's
    kernel start time — everything a restarted admin needs to re-adopt
    or respawn without any in-memory state."""
    meta = MetaStore(str(tmp_path / "meta.db"))
    ij = _running_inference_job(meta)
    mgr = _mgr(meta, tmp_path / "wd")
    try:
        svc = _spawn_dummy(mgr, tmp_path / "wd", ij["id"], "dw-0")
        row = meta.get_service(svc.service_id)
        spec = row["spawn_spec"]
        assert spec["module"] == "rafiki_tpu.chaos.dummy_service"
        assert spec["service_type"] == ServiceType.INFERENCE_WORKER
        assert spec["needs_slot"] is True
        assert spec["config"]["worker_id"] == "dw-0"
        assert spec["meta_kwargs"]["inference_job_id"] == ij["id"]
        assert row["start_time"] == proc_start_time(svc.proc.pid) > 0
        assert identity_matches(svc.proc.pid, row["start_time"])
        # the data plane row is durable the same way
        mgr.start_data_plane()
        kv_row = meta.get_service(mgr._kv_service_id)
        assert kv_row["start_time"] == proc_start_time(
            mgr._kv_proc.pid) > 0
        assert kv_row["spawn_spec"]["service_type"] == \
            ServiceType.DATA_PLANE
    finally:
        mgr.stop_all()


def test_reconcile_adopts_live_services_and_kv(tmp_path):
    """Admin dies (manager abandoned, children keep running) → a fresh
    manager on the same store re-adopts every survivor: identical pids,
    slots re-reserved, kvd adopted, respawn specs re-armed."""
    meta = MetaStore(str(tmp_path / "meta.db"))
    ij = _running_inference_job(meta)
    mgr1 = _mgr(meta, tmp_path / "wd")
    mgr1.start_data_plane()
    kv_pid = mgr1._kv_proc.pid
    old = [_spawn_dummy(mgr1, tmp_path / "wd", ij["id"], f"dw-{i}")
           for i in range(2)]
    _wait_ports(tmp_path / "wd", ["dw-0", "dw-1"])
    old_pids = sorted(s.proc.pid for s in old)

    # "SIGKILL the admin": mgr1 is abandoned without stop_all — its
    # children and MetaStore rows survive it
    mgr2 = _mgr(MetaStore(str(tmp_path / "meta.db")), tmp_path / "wd")
    try:
        rec = mgr2.reconcile()
        assert rec["services_adopted"] == 2
        assert rec["kv_adopted"] == 1
        assert rec["services_crashed"] == 0
        adopted = sorted(s.proc.pid for s in mgr2.services.values())
        assert adopted == old_pids  # identical pids — nothing restarted
        assert all(s.adopted and s.alive()
                   for s in mgr2.services.values())
        assert mgr2.allocator.free_count() == 0  # slots re-reserved
        assert mgr2.kv_port and mgr2._kv_proc.pid == kv_pid
        # healing is re-armed from the durable spawn specs
        assert set(mgr2._respawn_specs) == \
            {s.service_id for s in old}
        # rolling restart still works over ADOPTED handles (drain →
        # exit 0 → replace): proof the rebuilt processes are managed,
        # not just listed
        out = mgr2.rolling_restart(ij["id"], drain_timeout=30.0)
        assert len(out["restarted"]) == 2
        assert all(s.alive() for s in mgr2.services.values())
    finally:
        mgr2.stop_all()


def test_reconcile_respawns_crashed_under_persisted_budget(tmp_path):
    """Rows whose process died with the admin go CRASHED and re-enter
    the respawn path — but under the budget PERSISTED in the store: an
    admin restart cannot hand a crash-looping config a fresh budget."""
    meta = MetaStore(str(tmp_path / "meta.db"))
    ij = _running_inference_job(meta)
    wd = tmp_path / "wd"
    wd.mkdir()

    def dead_worker_row(job_id, wid):
        proc = subprocess.Popen(["/bin/true"])
        proc.wait()
        spec = {"module": "rafiki_tpu.chaos.dummy_service",
                "config": {"worker_id": wid, "drain_linger_s": 0.1,
                           "obs_port_file": str(wd / f"{wid}.obs_port")},
                "service_type": ServiceType.INFERENCE_WORKER,
                "needs_slot": False,
                "meta_kwargs": {"inference_job_id": job_id}}
        row = meta.create_service(
            ServiceType.INFERENCE_WORKER, inference_job_id=job_id,
            pid=proc.pid, spawn_spec=spec, start_time=123.0)
        meta.update_service(row["id"], status=ServiceStatus.RUNNING)
        return row

    # budget has one respawn left (1 spent of max 2)
    meta.incr_respawn_count(ServiceType.INFERENCE_WORKER, ij["id"])
    row = dead_worker_row(ij["id"], "dw-r")
    mgr = _mgr(meta, wd)
    mgr.max_respawns = 2
    try:
        rec = mgr.reconcile()
        assert rec["services_crashed"] == 1
        assert meta.get_service(row["id"])["status"] == \
            ServiceStatus.CRASHED
        live = [s for s in mgr.services.values() if s.alive()]
        assert len(live) == 1  # replacement spawned
        # the increment WROTE THROUGH: a third admin would see 2 spent
        lineage = f"{ServiceType.INFERENCE_WORKER}:{ij['id']}"
        assert meta.get_respawn_counts()[lineage] == 2

        # next admin restart: budget now exhausted → no new respawn,
        # the job surfaces as degraded instead of crash-looping
        for s in live:
            s.proc.terminate()
            s.proc.wait(timeout=10)
        meta2 = MetaStore(str(tmp_path / "meta.db"))
        row2 = dead_worker_row(ij["id"], "dw-r2")
        mgr2 = _mgr(meta2, wd)
        mgr2.max_respawns = 2
        try:
            rec2 = mgr2.reconcile()
            assert rec2["services_crashed"] >= 1
            assert not [s for s in mgr2.services.values()
                        if s.service_type ==
                        ServiceType.INFERENCE_WORKER and s.alive()]
            assert ij["id"] in mgr2.degraded_jobs()
            assert meta2.get_service(row2["id"])["status"] == \
                ServiceStatus.CRASHED
        finally:
            mgr2.stop_all()
    finally:
        mgr.stop_all()


def test_reconcile_reaps_orphans_of_stopped_jobs(tmp_path):
    """A survivor whose job was stopped while no admin was alive is an
    orphan burning a slot: killed (identity-gated) and marked STOPPED."""
    meta = MetaStore(str(tmp_path / "meta.db"))
    ij = _running_inference_job(meta)
    mgr1 = _mgr(meta, tmp_path / "wd")
    svc = _spawn_dummy(mgr1, tmp_path / "wd", ij["id"], "dw-orph")
    _wait_ports(tmp_path / "wd", ["dw-orph"])
    # the job is stopped AFTER the admin "died"
    meta.update_inference_job(ij["id"], status="STOPPED")

    mgr2 = _mgr(MetaStore(str(tmp_path / "meta.db")), tmp_path / "wd")
    try:
        rec = mgr2.reconcile()
        assert rec["orphans_reaped"] == 1
        assert rec["services_adopted"] == 0
        assert meta.get_service(svc.service_id)["status"] == \
            ServiceStatus.STOPPED
        svc.proc.wait(timeout=10)  # reap our child: actually dead
        assert not mgr2.services
        assert mgr2.allocator.free_count() == 2  # slot NOT reserved
    finally:
        mgr2.stop_all()


def test_pid_recycle_guard_start_time(tmp_path):
    """A row whose pid is alive but whose recorded start time does not
    match points at a RECYCLED pid: the reconciler must neither adopt
    nor kill that process — the row is simply dead (CRASHED)."""
    meta = MetaStore(str(tmp_path / "meta.db"))
    ij = _running_inference_job(meta)
    mgr1 = _mgr(meta, tmp_path / "wd")
    svc = _spawn_dummy(mgr1, tmp_path / "wd", ij["id"], "dw-rec",
                       slot=False)
    _wait_ports(tmp_path / "wd", ["dw-rec"])
    # forge a wrong start time (as if the real worker died and the
    # kernel handed its pid to this unrelated-but-rafiki process);
    # drop the spawn_spec so the crash path cannot respawn a twin that
    # would muddy the aliveness assertion below
    meta.update_service(svc.service_id, start_time=1.0, spawn_spec=None)
    try:
        mgr2 = _mgr(MetaStore(str(tmp_path / "meta.db")),
                    tmp_path / "wd")
        rec = mgr2.reconcile()
        assert rec["services_adopted"] == 0
        assert rec["services_crashed"] >= 1
        assert meta.get_service(svc.service_id)["status"] == \
            ServiceStatus.CRASHED
        assert svc.alive()  # the recycled pid was NOT killed
        # AdoptedProcess judges the same identity: wrong start time =
        # dead, and signalling through it is a no-op
        ap = AdoptedProcess(svc.proc.pid, start_time=1.0)
        assert ap.poll() == AdoptedProcess.ADOPTED_EXIT
        ap.kill()
        assert svc.alive()
        mgr2.stop_all()
    finally:
        mgr1.stop_all()


def test_cold_start_reaps_instead_of_adopting(tmp_path, capsys):
    """`stack start --cold` path: reap_stale_services kills every
    recorded survivor (identity-gated) instead of adopting — the
    operator opt-out for untrusted state."""
    meta = MetaStore(str(tmp_path / "meta.db"))
    ij = _running_inference_job(meta)
    mgr1 = _mgr(meta, tmp_path / "wd")
    svc = _spawn_dummy(mgr1, tmp_path / "wd", ij["id"], "dw-cold")
    _wait_ports(tmp_path / "wd", ["dw-cold"])

    mgr2 = _mgr(MetaStore(str(tmp_path / "meta.db")), tmp_path / "wd")
    try:
        assert mgr2.reap_stale_services() >= 1
        svc.proc.wait(timeout=10)
        assert meta.get_service(svc.service_id)["status"] == \
            ServiceStatus.STOPPED
        assert not mgr2.services  # nothing adopted
        # the CLI exposes the flag (stack forwards it as cold_start)
        from rafiki_tpu.cli import main as cli_main

        with pytest.raises(SystemExit) as ei:
            cli_main(["stack", "--help"])
        assert ei.value.code == 0
        assert "--cold" in capsys.readouterr().out
    finally:
        mgr2.stop_all()
        mgr1.stop_all()


# ------------------------------------------------------- admin fencing

def test_admin_lease_acquire_takeover_and_fencing(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.db"))
    mgr1 = _mgr(meta, tmp_path / "wd")
    mgr2 = _mgr(MetaStore(str(tmp_path / "meta.db")), tmp_path / "w2")
    got = mgr1.acquire_lease(ttl_s=30.0)
    assert got["generation"] == 1 and not got["took_over"]
    # a second live admin is fenced OUT at boot
    with pytest.raises(LeaseHeldError) as ei:
        mgr2.acquire_lease(ttl_s=30.0)
    assert ei.value.lease["generation"] == 1
    # re-acquire by the holder is a renew, not a takeover
    assert mgr1.acquire_lease()["generation"] == 1

    # the holder dies (heartbeat goes stale) → takeover bumps the
    # fencing generation
    meta.release_admin_lease(mgr1.lease_holder)
    got2 = mgr2.acquire_lease(ttl_s=30.0)
    assert got2["took_over"] and got2["generation"] == 2
    assert mgr2.recovery["lease_takeovers"] == 1

    # the stale admin's next renew FAILS and fences it: every mutating
    # op now raises, and stop_all releases handles without killing
    assert mgr1.renew_lease() is False
    assert mgr1.fenced
    with pytest.raises(AdminFencedError):
        mgr1._spawn("rafiki_tpu.chaos.dummy_service", {},
                    ServiceType.INFERENCE_WORKER)
    with pytest.raises(AdminFencedError):
        mgr1.stop_service("any")
    with pytest.raises(AdminFencedError):
        mgr1.rolling_restart("any")
    with pytest.raises(AdminFencedError):
        mgr1.start_data_plane()
    mgr1.stop_all()  # must be a no-op cleanup, not a raise
    # an unleased manager (unit-test/embedded use) is never fenced
    mgr3 = _mgr(MetaStore(str(tmp_path / "meta.db")), tmp_path / "w3")
    assert mgr3.renew_lease() is True and not mgr3.fenced
    mgr2.stop_all()


def test_fenced_stop_all_spares_adopted_children(tmp_path):
    """The acceptance detail that makes fencing worth having: a STALE
    admin shutting down must not kill the children the NEW admin just
    adopted."""
    meta = MetaStore(str(tmp_path / "meta.db"))
    ij = _running_inference_job(meta)
    mgr1 = _mgr(meta, tmp_path / "wd")
    mgr1.acquire_lease(ttl_s=30.0)
    svc = _spawn_dummy(mgr1, tmp_path / "wd", ij["id"], "dw-f")
    _wait_ports(tmp_path / "wd", ["dw-f"])

    mgr2 = _mgr(MetaStore(str(tmp_path / "meta.db")), tmp_path / "wd")
    meta.release_admin_lease(mgr1.lease_holder)  # mgr1 "died"
    mgr2.acquire_lease(ttl_s=30.0)
    try:
        assert mgr2.reconcile()["services_adopted"] == 1
        assert mgr1.renew_lease() is False  # fenced
        mgr1.stop_all()
        time.sleep(0.2)
        assert svc.alive(), "fenced admin killed an adopted child"
        assert mgr2.services and all(
            s.alive() for s in mgr2.services.values())
    finally:
        mgr2.stop_all()
    svc.proc.wait(timeout=10)
    assert not identity_matches(svc.proc.pid, 0)


# ------------------------------- the acceptance chaos test (tier-1)

def test_admin_kill_mid_stream_zero_drop(trained, tmp_path):
    """THE acceptance drill: the control plane dies with an inference
    stream in flight and is restarted against the same workdir +
    MetaStore. The stream rides the kvd data plane, which the new
    admin ADOPTS (same pid) instead of restarting — so the stream
    completes token-exact vs a no-fault run: zero dropped, zero
    duplicated. A concurrently booted second admin is fenced out by
    the lease the whole time."""
    from test_decode_engine import KNOBS

    from rafiki_tpu.chaos import ChaosConfig, ChaosHub, ChaosInjector
    from rafiki_tpu.models.llama_lora import LlamaLoRA
    from rafiki_tpu.serving.predictor import Predictor
    from rafiki_tpu.serving.queues import KVQueueHub
    from rafiki_tpu.store.param_store import ParamStore
    from rafiki_tpu.worker.inference import InferenceWorker

    store = ParamStore.from_uri("mem://")
    store.save("t0", trained.dump_parameters())
    prompt = "tok1 tok2 tok3"
    max_new = 16

    def boot_worker(hub, delay_s=0.0):
        if delay_s:
            # pace the reply pushes so the 16-token stream SPANS the
            # admin's death + lease takeover + reconcile (~1.5s) —
            # delays change timing only, never content
            hub = ChaosHub(hub, ChaosInjector(
                ChaosConfig(delay_queue_s=delay_s)))
        w = InferenceWorker(LlamaLoRA, "t0", KNOBS, store, hub, "w0",
                            decode_loop=True, max_slots=4,
                            max_new_tokens=max_new, steps_per_sync=1)
        th = threading.Thread(target=w.run, daemon=True)
        th.start()
        return w, th

    def collect(pred, out):
        for ev in pred.predict_stream([prompt], timeout=120.0):
            out.append((time.monotonic(), ev))

    meta = MetaStore(str(tmp_path / "meta.db"))
    mgr1 = _mgr(meta, tmp_path / "wd")
    mgr1.acquire_lease(ttl_s=1.0)
    mgr1.start_data_plane()
    kv_pid = mgr1._kv_proc.pid

    # no-fault reference over the SAME kvd (deterministic greedy)
    hub = KVQueueHub(mgr1.kv_host, mgr1.kv_port)
    w, th = boot_worker(hub)
    ref: list = []
    collect(Predictor(hub, ["w0"], gather_timeout=120.0), ref)
    expected = ref[-1][1]["predictions"]
    assert expected and expected[0]
    w.stop()
    th.join(timeout=30)

    # live run: stream in flight while the admin dies + restarts
    hub = KVQueueHub(mgr1.kv_host, mgr1.kv_port)
    w, th = boot_worker(hub, delay_s=0.25)
    events: list = []
    t = threading.Thread(
        target=collect,
        args=(Predictor(hub, ["w0"], gather_timeout=120.0), events),
        daemon=True)
    t.start()
    # wait until deltas are flowing — the stream IS in flight
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and len(events) < 2:
        time.sleep(0.01)
    assert len(events) >= 2, "stream never started"

    # admin dies (no graceful shutdown ran); lease ttl 1s expires
    t_kill = time.monotonic()
    mgr2 = _mgr(MetaStore(str(tmp_path / "meta.db")), tmp_path / "wd")
    while True:  # supervisor-style retry until the stale lease expires
        try:
            lease = mgr2.acquire_lease(ttl_s=30.0)
            break
        except LeaseHeldError:
            assert time.monotonic() - t_kill < 30
            time.sleep(0.05)
    assert lease["took_over"] and lease["generation"] == 2
    rec = mgr2.reconcile()
    assert rec["kv_adopted"] == 1
    assert mgr2._kv_proc.pid == kv_pid  # SAME kvd: queues intact
    n_at_recovery = len(events)

    # a duplicate third admin is fenced out while mgr2 is live
    mgr3 = _mgr(MetaStore(str(tmp_path / "meta.db")), tmp_path / "w3")
    with pytest.raises(LeaseHeldError):
        mgr3.acquire_lease(ttl_s=30.0)

    t.join(timeout=120)
    assert not t.is_alive(), "stream never finished"
    final = events[-1][1]
    assert final.get("done") and "error" not in final, final
    # token-exact vs the no-fault reference: zero dropped, zero
    # duplicated tokens across the admin's death and rebirth
    acc = "".join(v for _, e in events[:-1]
                  for v in e.get("delta", {}).values())
    assert final["predictions"] == expected
    assert acc == expected[0]
    # the stream was genuinely mid-flight when the control plane died
    assert 0 < n_at_recovery < len(events)

    w.stop()
    th.join(timeout=30)
    mgr2.stop_all()


# ------------------------------------------ claim-race satellite

def test_claim_trial_for_resume_two_concurrent_claimants(tmp_path):
    """Exactly one of two concurrent claimants wins the conditional
    UPDATE; the loser's (i.e. the presumed-dead owner's) late
    mark_trial_completed is rejected by the fenced terminal update."""
    meta = MetaStore(str(tmp_path / "meta.db"))
    user = meta.create_user("op@x", "pw", "ADMIN")
    tj = meta.create_train_job(user["id"], "app", 1,
                               "IMAGE_CLASSIFICATION",
                               {"TRIAL_COUNT": 1}, "d1", "d2")
    sub = meta.create_sub_train_job(tj["id"], "m1")
    trial = meta.create_trial(sub["id"], 0, "m1", {"lr": 0.1},
                              worker_id="dead-worker")
    # stale heartbeat: the owner is presumed dead
    meta.update_trial(trial["id"], heartbeat_at=time.time() - 3600,
                      started_at=time.time() - 3600)

    barrier = threading.Barrier(2)
    results = {}

    def claim(wid):
        # each claimant gets its own connection — two real worker
        # processes would
        m = MetaStore(str(tmp_path / "meta.db"))
        barrier.wait()
        results[wid] = m.claim_trial_for_resume(trial["id"], wid,
                                                stale_after_s=60.0)

    ts = [threading.Thread(target=claim, args=(f"w{i}",))
          for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert sorted(results.values()) == [False, True], results
    assert meta.get_trial(trial["id"])["status"] == "TERMINATED"
    # the presumed-dead owner un-stalls and reports success: fenced out
    assert meta.mark_trial_completed(trial["id"], 0.9, True) is False
    assert meta.get_trial(trial["id"])["status"] == "TERMINATED"
    # and an errored report is fenced identically
    assert meta.mark_trial_errored(trial["id"], "boom") is False


# ------------------------------------------------ backup satellite

def test_metastore_backup_online_and_admin_route(tmp_path):
    """Online snapshot while the store is live; the copy opens as a
    full MetaStore. The admin exposes it as POST /system/backup and
    the client SDK wraps that."""
    from rafiki_tpu.admin.admin import Admin
    from rafiki_tpu.admin.app import AdminApp
    from rafiki_tpu.client import Client

    meta = MetaStore(str(tmp_path / "meta.db"))
    user = meta.create_user("u@x", "pw", "ADMIN")
    tj = meta.create_train_job(user["id"], "app", 1,
                               "IMAGE_CLASSIFICATION",
                               {"TRIAL_COUNT": 1}, "d1", "d2")
    out = meta.backup(str(tmp_path / "snap.db"))
    assert out["bytes"] > 0
    copy = MetaStore(str(tmp_path / "snap.db"))
    assert copy.get_train_job(tj["id"])["app"] == "app"
    assert copy.get_user_by_email("u@x") is not None

    manager = _mgr(meta, tmp_path / "wd", n_devices=1)
    admin = Admin(meta, manager)
    app = AdminApp(admin)
    host, port = app.start()
    try:
        c = Client(f"http://{host}:{port}")
        c.login("superadmin@rafiki", "rafiki")
        got = c.backup(str(tmp_path / "snap2.db"))
        assert got["ok"] and got["bytes"] > 0
        assert MetaStore(str(tmp_path / "snap2.db")).get_train_job(
            tj["id"]) is not None
        # non-admin users may not write server-side files
        c.create_user("dev@x", "pw", "APP_DEVELOPER")
        c2 = Client(f"http://{host}:{port}")
        c2.login("dev@x", "pw")
        from rafiki_tpu.client.client import HttpStatusError

        with pytest.raises(HttpStatusError) as ei:
            c2.backup(str(tmp_path / "nope.db"))
        assert ei.value.status == 403
    finally:
        app.stop()


def test_backup_cli(tmp_path, capsys):
    from rafiki_tpu.cli import main as cli_main

    wd = tmp_path / "stack"
    wd.mkdir()
    meta = MetaStore(str(wd / "meta.db"))
    meta.create_user("u@x", "pw", "ADMIN")
    rc = cli_main(["backup", str(tmp_path / "out.db"),
                   "--workdir", str(wd)])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out.strip())
    assert rep["ok"] and rep["bytes"] > 0
    assert MetaStore(str(tmp_path / "out.db")).get_user_by_email(
        "u@x") is not None
    # missing store: structured failure, not a traceback
    assert cli_main(["backup", str(tmp_path / "o2.db"),
                     "--workdir", str(tmp_path / "nowhere")]) == 1


# ------------------------------------------------ doctor satellite

def test_doctor_workdir_audit_reports_drift(tmp_path, capsys):
    from rafiki_tpu.admin.doctor import audit_workdir, render_text
    from rafiki_tpu.cli import main as cli_main

    meta = MetaStore(str(tmp_path / "meta.db"))
    ij = _running_inference_job(meta)
    mgr = _mgr(meta, tmp_path)
    try:
        svc = _spawn_dummy(mgr, tmp_path, ij["id"], "dw-a")
        _wait_ports(tmp_path, ["dw-a"])
        rep = audit_workdir(str(tmp_path))
        assert rep["ok"] and rep["drift"] == []
        entry = next(s for s in rep["services"]
                     if s["id"] == svc.service_id)
        assert entry["pid_alive"] and entry["identity_ok"]
        assert "no drift" in render_text(rep)

        # drift 1: RUNNING row whose pid is dead
        dead = subprocess.Popen(["/bin/true"])
        dead.wait()
        r1 = meta.create_service(ServiceType.INFERENCE_WORKER,
                                 inference_job_id=ij["id"],
                                 pid=dead.pid, start_time=5.0)
        meta.update_service(r1["id"], status=ServiceStatus.RUNNING)
        # drift 2: STOPPED row whose process is still alive (orphan)
        row = meta.get_service(svc.service_id)
        meta.update_service(svc.service_id,
                            status=ServiceStatus.STOPPED)
        # drift 3: stale obs_port file nothing listens on
        (tmp_path / "ghost.obs_port").write_text("1")

        rep2 = audit_workdir(str(tmp_path))
        assert not rep2["ok"]
        text = "\n".join(rep2["drift"])
        assert "dead" in text
        assert "still alive (orphaned process)" in text
        assert "ghost.obs_port" in text

        # the CLI renders both forms and exits 1 on drift
        assert cli_main(["doctor", "--workdir", str(tmp_path),
                         "--json"]) == 1
        parsed = json.loads(capsys.readouterr().out)
        assert parsed["drift"] == rep2["drift"]
        assert cli_main(["doctor", "--workdir", str(tmp_path)]) == 1
        assert "DRIFT" in capsys.readouterr().out
        # restore the row so stop_all finalizes cleanly
        meta.update_service(svc.service_id, status=row["status"])
    finally:
        mgr.stop_all()


# ------------------------------------------- slow-tier real kill -9

@pytest.mark.slow
def test_control_driver_kill9_e2e(tmp_path):
    """Out-of-process acceptance: a REAL control-plane process is
    kill -9'd and a second one reconverges against the same workdir —
    adopted pids identical, lease generation bumped, zero drift in the
    doctor audit afterwards."""
    import os
    import signal
    import sys

    from rafiki_tpu.admin.doctor import audit_workdir

    def start(mode, ready):
        cfg = {"workdir": str(tmp_path),
               "db_path": str(tmp_path / "meta.db"), "n_services": 2,
               "ready_file": str(tmp_path / ready), "mode": mode,
               "lease_ttl_s": 3.0}
        cfg_path = tmp_path / f"{ready}.cfg.json"
        cfg_path.write_text(json.dumps(cfg))
        return subprocess.Popen(
            [sys.executable, "-m", "rafiki_tpu.chaos.control_driver",
             "--config", str(cfg_path)],
            env=dict(os.environ, JAX_PLATFORMS="cpu"))

    def wait_ready(name, proc, timeout=90.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if (tmp_path / name).exists():
                return json.loads((tmp_path / name).read_text())
            assert proc.poll() is None, "driver died"
            time.sleep(0.1)
        raise TimeoutError(name)

    p1 = start("boot", "r1.json")
    r1 = wait_ready("r1.json", p1)
    os.kill(p1.pid, signal.SIGKILL)
    p1.wait()
    p2 = start("reconcile", "r2.json")
    try:
        r2 = wait_ready("r2.json", p2)
        assert r2["adopted_pids"] == r1["spawned_pids"]
        assert r2["kv_port"] == r1["kv_port"]
        assert r2["took_over"] and r2["lease_generation"] == 2
        assert r2["services_adopted"] == 2 and r2["kv_adopted"] == 1
        rep = audit_workdir(str(tmp_path))
        assert rep["ok"], rep["drift"]
    finally:
        p2.terminate()
        assert p2.wait(timeout=60) == 0
