"""Config #1 end-to-end on CPU: contract conformance + local tuning."""

import numpy as np
import pytest

from rafiki_tpu.constants import TaskType
from rafiki_tpu.data import generate_image_classification_dataset
from rafiki_tpu.model import test_model_class, tune_model
from rafiki_tpu.models.mlp import JaxFeedForward


@pytest.fixture(scope="module")
def datasets(tmp_path_factory):
    d = tmp_path_factory.mktemp("ds")
    train_p = str(d / "train.npz")
    val_p = str(d / "val.npz")
    generate_image_classification_dataset(train_p, n_examples=512, seed=0)
    val = generate_image_classification_dataset(val_p, n_examples=128, seed=1)
    return train_p, val_p, val


def test_mlp_contract(datasets):
    train_p, val_p, val = datasets
    knobs = {"max_epochs": 5, "hidden_layer_count": 1,
             "hidden_layer_units": 64, "learning_rate": 1e-3,
             "batch_size": 64, "quick_train": False, "share_params": False}
    preds = test_model_class(
        JaxFeedForward, TaskType.IMAGE_CLASSIFICATION, train_p, val_p,
        queries=[val.images[0], val.images[1]], knobs=knobs)
    assert len(preds) == 2
    assert len(preds[0]) == 10
    assert abs(sum(preds[0]) - 1.0) < 1e-3  # probabilities


def test_mlp_learns(datasets):
    train_p, val_p, _ = datasets
    m = JaxFeedForward(max_epochs=3, hidden_layer_count=1,
                       hidden_layer_units=64, learning_rate=1e-3,
                       batch_size=64, quick_train=False, share_params=False)
    m.train(train_p)
    assert m.evaluate(val_p) > 0.5  # 10-class chance is 0.1


def test_tune_model_random(datasets):
    train_p, val_p, _ = datasets
    result = tune_model(JaxFeedForward, train_p, val_p, total_trials=3,
                        advisor_type="random", seed=0)
    assert len(result.trials) == 3
    assert result.best_score >= max(t.score for t in result.trials) - 1e-9
    assert result.best_params  # params captured for deployment


def test_bucketed_forward_empty_input():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rafiki_tpu.model import bucketed_forward

    @jax.jit
    def fwd(params, xb):
        return jnp.dot(xb, params)

    params = jnp.ones((4, 3))
    out = bucketed_forward(fwd, params, np.zeros((0, 4), np.float32),
                          bucket=8)
    assert out.shape == (0, 3)
    assert out.dtype == np.float32


def test_profiler_trace_per_trial(tmp_path, datasets):
    from rafiki_tpu.model import tune_model
    from rafiki_tpu.models.mlp import JaxFeedForward

    train_p, val_p, _ = datasets
    prof = tmp_path / "profiles"
    # pin the shape knobs tiny: the oracle is "a trace lands per
    # trial", not the sampled model's size — an unlucky random draw
    # (3x256 hidden) made this the slowest default test
    tune_model(JaxFeedForward, train_p, val_p,
               total_trials=1, advisor_type="random",
               profile_dir=str(prof),
               knob_overrides={"max_epochs": 1, "hidden_layer_count": 1,
                               "hidden_layer_units": 16,
                               "batch_size": 64})
    trial_dirs = list(prof.iterdir())
    assert len(trial_dirs) == 1 and trial_dirs[0].name == "local-0"
    # jax.profiler writes plugins/profile/<ts>/*.trace.json.gz (and more)
    traces = list(trial_dirs[0].rglob("*"))
    assert any(f.is_file() for f in traces), "no trace artifacts written"


def test_retrain_after_load_is_donation_safe(tmp_path):
    """train() donates its param buffers; a model warm-started via
    load_parameters must survive a second train() + dump/predict cycle
    (the donated buffers must never alias self._params)."""
    from rafiki_tpu.data import generate_image_classification_dataset
    from rafiki_tpu.models.mlp import JaxFeedForward

    tr = str(tmp_path / "tr.npz")
    generate_image_classification_dataset(tr, 128, seed=0)
    knobs = {"max_epochs": 1, "hidden_layer_count": 1,
             "hidden_layer_units": 16, "learning_rate": 1e-3,
             "batch_size": 64, "quick_train": True, "share_params": False}
    m = JaxFeedForward(**knobs)
    m.train(tr)
    blob = m.dump_parameters()

    m2 = JaxFeedForward(**knobs)
    m2.load_parameters(blob)
    m2.train(tr)  # donates buffers that must not alias the loaded tree
    out = m2.dump_parameters()
    assert out["params"] is not None
    preds = m2.predict([__import__("numpy").zeros((28, 28, 1))])
    assert len(preds) == 1
