import threading

import numpy as np
import pytest

from rafiki_tpu.store import (FileBackend, InMemoryBackend, MetaStore,
                              ParamStore, params_from_bytes, params_to_bytes)


def sample_params():
    return {"params": {"dense": {"kernel": np.arange(6, dtype=np.float32)
                                 .reshape(2, 3),
                                 "bias": np.zeros(3, np.float32)}},
            "meta": {"n_classes": 3}}


def assert_params_equal(a, b):
    np.testing.assert_array_equal(a["params"]["dense"]["kernel"],
                                  b["params"]["dense"]["kernel"])
    assert int(b["meta"]["n_classes"]) == 3


def test_params_bytes_round_trip():
    blob = params_to_bytes(sample_params())
    assert isinstance(blob, bytes)
    assert_params_equal(sample_params(), params_from_bytes(blob))


@pytest.mark.parametrize("backend_kind", ["mem", "file"])
def test_param_store_backends(backend_kind, tmp_path):
    backend = (InMemoryBackend() if backend_kind == "mem"
               else FileBackend(str(tmp_path / "params")))
    store = ParamStore(backend, cache_size=2)
    store.save("trial-1", sample_params())
    store.save("trial/../2", sample_params())  # hostile key is sanitized
    assert_params_equal(sample_params(), store.load("trial-1"))
    assert store.load("nope") is None
    assert set(store.keys()) == {"trial-1", "trial/../2"}
    store.delete("trial-1")
    assert store.load("trial-1") is None


def test_param_store_file_persistence(tmp_path):
    root = str(tmp_path / "params")
    ParamStore(FileBackend(root)).save("t1", sample_params())
    # fresh store over the same dir sees the blob (index reload)
    store2 = ParamStore.from_uri(f"file://{root}")
    assert_params_equal(sample_params(), store2.load("t1"))
    assert store2.keys() == ["t1"]


def test_meta_store_users_and_auth():
    ms = MetaStore()
    u = ms.create_user("dev@x.com", "secret", "MODEL_DEVELOPER")
    assert ms.authenticate_user("dev@x.com", "secret")["id"] == u["id"]
    assert ms.authenticate_user("dev@x.com", "wrong") is None
    assert ms.authenticate_user("ghost@x.com", "secret") is None
    ms.ban_user(u["id"])
    assert ms.authenticate_user("dev@x.com", "secret") is None


def test_meta_store_models_visibility():
    ms = MetaStore()
    a = ms.create_user("a@x.com", "p", "MODEL_DEVELOPER")
    b = ms.create_user("b@x.com", "p", "MODEL_DEVELOPER")
    ms.create_model(a["id"], "priv", "IMAGE_CLASSIFICATION", "M", b"src")
    ms.create_model(a["id"], "pub", "IMAGE_CLASSIFICATION", "M", b"src",
                    access_right="PUBLIC")
    ms.create_model(b["id"], "other", "POS_TAGGING", "M", b"src")
    vis = ms.get_available_models(task="IMAGE_CLASSIFICATION",
                                  user_id=b["id"])
    assert [m["name"] for m in vis] == ["pub"]
    vis_a = ms.get_available_models(user_id=a["id"])
    assert {m["name"] for m in vis_a} == {"priv", "pub"}


def test_meta_store_train_job_lifecycle():
    ms = MetaStore()
    u = ms.create_user("u@x.com", "p", "APP_DEVELOPER")
    m = ms.create_model(u["id"], "mlp", "IMAGE_CLASSIFICATION",
                        "JaxFeedForward", b"src")
    d1 = ms.create_dataset(u["id"], "train", "IMAGE_CLASSIFICATION",
                           "file:///train.npz")
    d2 = ms.create_dataset(u["id"], "val", "IMAGE_CLASSIFICATION",
                           "file:///val.npz")
    job = ms.create_train_job(u["id"], "app", 1, "IMAGE_CLASSIFICATION",
                              {"TRIAL_COUNT": 4}, d1["id"], d2["id"])
    sub = ms.create_sub_train_job(job["id"], m["id"])

    t1 = ms.create_trial(sub["id"], 0, m["id"], {"lr": 0.1})
    t2 = ms.create_trial(sub["id"], 1, m["id"], {"lr": 0.01})
    t3 = ms.create_trial(sub["id"], 2, m["id"], {"lr": 1.0},
                         budget_scale=0.3)
    ms.mark_trial_completed(t1["id"], 0.7, params_saved=True)
    ms.mark_trial_completed(t2["id"], 0.9, params_saved=True)
    ms.mark_trial_completed(t3["id"], 0.95, params_saved=True)  # low budget
    t4 = ms.create_trial(sub["id"], 3, m["id"], {"lr": 9.0})
    ms.mark_trial_errored(t4["id"], "NaN loss")

    best = ms.get_best_trials_of_train_job(job["id"], max_count=2)
    # low-budget and errored trials are excluded
    assert [b["score"] for b in best] == [0.9, 0.7]

    trials = ms.get_trials_of_train_job(job["id"])
    assert len(trials) == 4
    assert ms.get_latest_train_job_of_app(u["id"], "app")["id"] == job["id"]

    ms.update_train_job(job["id"], status="STOPPED")
    assert ms.get_train_job(job["id"])["status"] == "STOPPED"
    with pytest.raises(KeyError):
        ms.update_train_job("missing", status="STOPPED")


def test_meta_store_trial_logs():
    ms = MetaStore()
    ms.add_trial_log("t1", "values", {"epoch": 0, "loss": 1.5})
    ms.add_trial_log("t1", "values", {"epoch": 1, "loss": 0.5})
    logs = ms.get_trial_logs("t1")
    assert [r["data"]["loss"] for r in logs] == [1.5, 0.5]


def test_meta_store_concurrent_writes(tmp_path):
    ms = MetaStore(str(tmp_path / "meta.db"))
    u = ms.create_user("u@x.com", "p", "APP_DEVELOPER")
    m = ms.create_model(u["id"], "m", "T", "C", b"s")
    d = ms.create_dataset(u["id"], "d", "T", "uri")
    job = ms.create_train_job(u["id"], "app", 1, "T", {}, d["id"], d["id"])
    sub = ms.create_sub_train_job(job["id"], m["id"])

    errors = []

    def writer(k):
        try:
            for i in range(20):
                t = ms.create_trial(sub["id"], k * 100 + i, m["id"], {})
                ms.mark_trial_completed(t["id"], 0.5, params_saved=True)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(k,)) for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert len(ms.get_trials_of_sub_train_job(sub["id"])) == 80


def test_param_store_lru_cache_eviction():
    store = ParamStore(InMemoryBackend(), cache_size=2)
    for i in range(4):
        store.save(f"t{i}", sample_params())
    assert len(store._cache) == 2
    # evicted entries still load through the backend
    assert_params_equal(sample_params(), store.load("t0"))


# ---- database adapter seam (SURVEY §7 "swap to PostgreSQL") ----

def test_adapter_url_dispatch():
    from rafiki_tpu.store.db import SqliteAdapter, adapter_for

    assert isinstance(adapter_for(":memory:"), SqliteAdapter)
    assert isinstance(adapter_for("/tmp/x.db"), SqliteAdapter)
    a = adapter_for("sqlite:///tmp/y.db")
    assert isinstance(a, SqliteAdapter) and a.path == "tmp/y.db"
    # postgres urls route to the postgres adapter, which on this
    # psycopg2-less image must fail LOUDLY with install guidance
    import pytest as _pytest

    with _pytest.raises(ImportError, match="psycopg2"):
        adapter_for("postgresql://u:p@host/db")


def test_postgres_sql_translation():
    from rafiki_tpu.store.db import qmark_to_format, sqlite_ddl_to_postgres
    from rafiki_tpu.store.meta_store import _SCHEMA

    assert qmark_to_format("UPDATE t SET a=? WHERE id=?") == \
        "UPDATE t SET a=%s WHERE id=%s"
    # quoted literals keep their question marks
    assert qmark_to_format("SELECT '?' , a FROM t WHERE b=?") == \
        "SELECT '?' , a FROM t WHERE b=%s"
    ddl = sqlite_ddl_to_postgres(_SCHEMA)
    assert "AUTOINCREMENT" not in ddl
    assert "BIGSERIAL PRIMARY KEY" in ddl
    assert "BLOB" not in ddl and "BYTEA" in ddl
    assert " REAL" not in ddl


def test_postgres_metastore_live_roundtrip():
    """VERDICT r4 item 8: the PostgresAdapter against a REAL wire —
    placeholder translation under write args, DDL creation, bytea
    blobs, fenced-UPDATE rowcount semantics (trial claim/completion),
    migration duplicate-column no-op, and statement-failure isolation
    under autocommit. Gated: runs wherever psycopg2 + a server are
    available (``RAFIKI_PG_URL``), skips cleanly otherwise — this
    image ships sqlite-only."""
    import os
    import uuid

    import pytest as _pytest

    url = os.environ.get("RAFIKI_PG_URL", "")
    if not url:
        _pytest.skip("RAFIKI_PG_URL not set (no postgres in this env)")
    psycopg2 = _pytest.importorskip("psycopg2")
    schema = f"rafiki_test_{uuid.uuid4().hex[:12]}"
    try:
        admin = psycopg2.connect(url, connect_timeout=5)
    except Exception as e:  # noqa: BLE001
        _pytest.skip(f"postgres unreachable: {e}")
    admin.autocommit = True
    sep = "&" if "?" in url else "?"
    scoped_url = (f"{url}{sep}options=-csearch_path%3D{schema}")
    try:
        with admin.cursor() as cur:
            cur.execute(f'CREATE SCHEMA "{schema}"')

        from rafiki_tpu.store.meta_store import MetaStore

        m = MetaStore(scoped_url)
        try:
            # users + auth (placeholder translation on INSERT/SELECT)
            u = m.create_user("pg@test", "pw", "ADMIN")
            assert m.authenticate_user("pg@test", "pw")["id"] == u["id"]
            # model upload: bytea blob round-trip
            blob = bytes(range(256)) * 4
            mod = m.create_model(u["id"], "m1", "IMAGE_CLASSIFICATION",
                                 "Model", blob, {})
            assert bytes(m.get_model(mod["id"])["model_bytes"]) == blob
            # trial state machine: fenced completion via rowcount
            t = m.create_trial("sj1", 0, model_id=mod["id"],
                               knobs={"lr": 0.1}, worker_id="w0",
                               budget_scale=1.0, shape_sig="s")
            m.heartbeat_trial(t["id"])
            assert m.mark_trial_completed(t["id"], 0.9,
                                          params_saved=True) is True
            # second terminal mark must FENCE OUT (rowcount 0 on pg)
            assert m.mark_trial_completed(t["id"], 0.1,
                                          params_saved=True) is False
            row = m.get_trial(t["id"])
            assert row["status"] == "COMPLETED"
            assert abs(float(row["score"]) - 0.9) < 1e-9
            # migration re-run: DuplicateColumn maps to a clean no-op
            assert m._adapter.try_migration(
                m._conn, "ALTER TABLE trials ADD COLUMN error_class "
                "TEXT") is False
            # failed statement doesn't poison the connection
            with _pytest.raises(Exception):
                m._exec("SELECT * FROM does_not_exist")
            assert m.get_user(u["id"])["email"] == "pg@test"
        finally:
            m.close()
    finally:
        try:
            with admin.cursor() as cur:
                cur.execute(f'DROP SCHEMA "{schema}" CASCADE')
        finally:
            admin.close()


def test_meta_store_accepts_sqlite_url(tmp_path):
    from rafiki_tpu.store.meta_store import MetaStore

    m = MetaStore(f"sqlite:///{tmp_path}/via_url.db")
    u = m.create_user("a@b", "pw", "ADMIN")
    assert m.get_user(u["id"])["email"] == "a@b"
    m.close()
    # file landed where the url said
    import os

    assert os.path.exists(f"{tmp_path}/via_url.db")
