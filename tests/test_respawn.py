"""Worker self-healing: crashed train/inference workers are respawned
(capped per job) while their parent job is still RUNNING."""

import time

import pytest

from rafiki_tpu.admin.services_manager import ServicesManager
from rafiki_tpu.constants import ServiceType
from rafiki_tpu.parallel.mesh import DeviceSpec
from rafiki_tpu.store.meta_store import MetaStore


@pytest.fixture()
def mgr_and_job(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.db"))
    user = meta.create_user("op@x", "pw", "ADMIN")
    job = meta.create_train_job(user["id"], "app", 1,
                                "IMAGE_CLASSIFICATION", {"TRIAL_COUNT": 1},
                                "d1", "d2")
    meta.update_train_job(job["id"], status="RUNNING")
    mgr = ServicesManager(meta, str(tmp_path / "wd"), slot_size=1,
                          platform="cpu",
                          devices=[DeviceSpec(id=0), DeviceSpec(id=1)])
    try:
        yield mgr, meta, job
    finally:
        mgr.stop_all()


def _wait_dead_then_poll(mgr, svc, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if not svc.alive():
            mgr.poll()
            return
        time.sleep(0.2)
    raise TimeoutError("service did not exit")


@pytest.mark.slow
def test_crashed_train_worker_respawned_until_cap(mgr_and_job):
    mgr, meta, job = mgr_and_job
    # a worker whose config is unreadable crashes on startup (rc != 0)
    svc = mgr._spawn("rafiki_tpu.worker.train",
                     {"model_file": "/nonexistent", "model_class": "X",
                      "train_dataset": "d", "val_dataset": "d"},
                     ServiceType.TRAIN_WORKER,
                     slot=mgr.allocator.acquire(),
                     train_job_id=job["id"])
    mgr.max_respawns = 2
    seen = {svc.service_id}
    for _ in range(2):  # each crash yields one replacement, twice
        cur = next(iter(
            s for s in mgr.services.values()
            if s.service_type == ServiceType.TRAIN_WORKER))
        _wait_dead_then_poll(mgr, cur)
        live = [s for s in mgr.services.values()
                if s.service_type == ServiceType.TRAIN_WORKER]
        assert len(live) == 1, "crashed worker was not replaced"
        assert live[0].service_id not in seen
        seen.add(live[0].service_id)
    # budget exhausted: the next crash is terminal
    cur = next(iter(
        s for s in mgr.services.values()
        if s.service_type == ServiceType.TRAIN_WORKER))
    _wait_dead_then_poll(mgr, cur)
    assert not [s for s in mgr.services.values()
                if s.service_type == ServiceType.TRAIN_WORKER]
    assert mgr._respawn_counts[(ServiceType.TRAIN_WORKER, job["id"])] == 2
    # every slot made it back to the allocator
    assert mgr.allocator.free_count() == 2


def test_no_respawn_after_job_stops(mgr_and_job):
    mgr, meta, job = mgr_and_job
    spec = {"module": "rafiki_tpu.worker.train",
            "config": {}, "service_type": ServiceType.TRAIN_WORKER,
            "needs_slot": False, "meta_kwargs": {"train_job_id": job["id"]}}
    meta.update_train_job(job["id"], status="STOPPED")
    mgr._respawn("dead-svc", spec)
    assert not mgr.services  # finished job: nothing respawned


def test_normal_exit_is_not_respawned(mgr_and_job):
    import subprocess

    from rafiki_tpu.admin.services_manager import ManagedService

    mgr, meta, job = mgr_and_job
    # rc == 0 (e.g. advisor budget exhausted → worker done) must NOT
    # trigger healing; register a finished rc=0 process directly
    proc = subprocess.Popen(["/bin/true"])
    proc.wait()
    row = meta.create_service(ServiceType.TRAIN_WORKER, host="", port=0,
                              pid=proc.pid, train_job_id=job["id"])
    mgr.services[row["id"]] = ManagedService(
        row["id"], ServiceType.TRAIN_WORKER, proc)
    mgr._respawn_specs[row["id"]] = {
        "module": "rafiki_tpu.worker.train", "config": {},
        "service_type": ServiceType.TRAIN_WORKER, "needs_slot": False,
        "meta_kwargs": {"train_job_id": job["id"]}}
    mgr.poll()
    assert not mgr.services
    assert (ServiceType.TRAIN_WORKER, job["id"]) not in mgr._respawn_counts


def test_respawn_budget_exhaustion_surfaces_degraded(mgr_and_job):
    """The pending-respawn drop / budget-exhaustion path must not be
    just a log line: the job shows up in respawn_stats/degraded_jobs
    (what the admin /health exposes) and — with no workers left — its
    store row flips to ERRORED (what the dashboard's status column
    renders)."""
    import subprocess

    from rafiki_tpu.admin.services_manager import ManagedService

    mgr, meta, job = mgr_and_job
    mgr.max_respawns = 0  # healing budget already spent
    proc = subprocess.Popen(["/bin/false"])
    proc.wait()
    row = meta.create_service(ServiceType.TRAIN_WORKER, host="", port=0,
                              pid=proc.pid, train_job_id=job["id"])
    mgr.services[row["id"]] = ManagedService(
        row["id"], ServiceType.TRAIN_WORKER, proc)
    mgr._respawn_specs[row["id"]] = {
        "module": "rafiki_tpu.worker.train", "config": {},
        "service_type": ServiceType.TRAIN_WORKER, "needs_slot": False,
        "meta_kwargs": {"train_job_id": job["id"]}}
    mgr.poll()
    stats = mgr.respawn_stats()
    assert stats["degraded_jobs"] == 1
    assert "respawn budget exhausted" in \
        mgr.degraded_jobs()[job["id"]]
    # last worker gone + healing gone = the job is dead, not degraded
    assert meta.get_train_job(job["id"])["status"] == "ERRORED"


def test_slotless_respawn_queued_and_retried(mgr_and_job):
    import subprocess

    from rafiki_tpu.admin.services_manager import ManagedService

    mgr, meta, job = mgr_and_job
    # both slots taken by someone else: the crashed worker can't respawn
    held = [mgr.allocator.acquire(), mgr.allocator.acquire()]
    proc = subprocess.Popen(["/bin/false"])
    proc.wait()
    row = meta.create_service(ServiceType.TRAIN_WORKER, host="", port=0,
                              pid=proc.pid, train_job_id=job["id"])
    mgr.services[row["id"]] = ManagedService(
        row["id"], ServiceType.TRAIN_WORKER, proc)
    mgr._respawn_specs[row["id"]] = {
        "module": "rafiki_tpu.worker.train",
        "config": {"model_file": "/nonexistent", "model_class": "X",
                   "train_dataset": "d", "val_dataset": "d"},
        "service_type": ServiceType.TRAIN_WORKER, "needs_slot": True,
        "meta_kwargs": {"train_job_id": job["id"]}}
    mgr.poll()
    assert len(mgr._pending_respawns) == 1  # queued, not lost
    mgr.poll()
    assert len(mgr._pending_respawns) == 1  # still no slot: still queued
    mgr.allocator.release(held.pop())
    mgr.poll()  # slot free now → replacement spawns
    assert not mgr._pending_respawns
    live = [s for s in mgr.services.values()
            if s.service_type == ServiceType.TRAIN_WORKER]
    assert len(live) == 1
