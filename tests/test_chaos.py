"""Request-path fault tolerance, driven by deterministic fault injection.

Covers the four tentpole layers of the fault-tolerance substrate:
per-worker circuit breakers (closed/open/half-open, scatter-time
skipping, all-open fast-fail 503), streaming failover (chaos-killed
worker mid-stream → resumed on a healthy replica with token-exact
output), graceful drain (in-flight streams finish, new work is
rejected structured, the loop exits 0) with ``rolling_restart``
orchestration, and the ``rafiki_tpu.chaos`` injectors themselves
(seeded determinism). Plus the deadline-skew satellite
(``ttl_s``/``sent_ts`` judged through the worker's skew estimator) and
the client SDK satellite (503 retry honoring ``retry_after_s``, typed
``StreamInterrupted`` + auto-resume).
"""

import threading
import time

import pytest

from rafiki_tpu.chaos import ChaosConfig, ChaosHub, ChaosInjector
from rafiki_tpu.models.llama_lora import LlamaLoRA
from rafiki_tpu.serving.breaker import (CLOSED, HALF_OPEN, OPEN,
                                        BreakerBoard)
from rafiki_tpu.serving.predictor import Predictor, PredictorService
from rafiki_tpu.serving.queues import (InProcQueueHub, pack_message,
                                       unpack_message)
from rafiki_tpu.store.param_store import ParamStore
from rafiki_tpu.worker.inference import (ClockSkewEstimator,
                                         InferenceWorker, _expired)

from test_decode_engine import KNOBS  # noqa: F401 — shared knobs


# ---------------------------------------------------------------- breakers

class _Clock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_breaker_state_machine():
    """closed → (threshold misses) → open → (cooldown) → half-open
    probe → success closes / failure re-opens with doubled cooldown."""
    clk = _Clock()
    b = BreakerBoard(["w0", "w1"], fail_threshold=2, cooldown_s=1.0,
                     max_cooldown_s=8.0, now=clk)
    assert b.targets() == ["w0", "w1"]
    b.record_failure("w0")
    assert b.state("w0") == CLOSED  # one miss < threshold
    b.record_failure("w0")
    assert b.state("w0") == OPEN
    assert b.targets() == ["w1"]    # open worker skipped at scatter
    assert int(b.counters["breaker_trips"]) == 1
    # a success resets the OTHER worker's streak independently
    b.record_failure("w1")
    b.record_success("w1")
    b.record_failure("w1")
    assert b.state("w1") == CLOSED
    # cooldown elapses: exactly one probe is admitted
    clk.t += 1.01
    assert sorted(b.targets()) == ["w0", "w1"]  # probe issued here
    assert b.state("w0") == HALF_OPEN
    assert "w0" not in b.targets()  # probe outstanding: no second one
    # failed probe re-opens with doubled cooldown
    b.record_failure("w0")
    assert b.state("w0") == OPEN
    clk.t += 1.5
    assert "w0" not in b.targets()  # 2.0s cooldown now
    clk.t += 0.6
    assert "w0" in b.targets()
    b.record_success("w0")          # probe answered: recovered
    assert b.state("w0") == CLOSED
    assert int(b.counters["breaker_recoveries"]) == 1


def test_breaker_retry_after_and_stale_and_drain():
    clk = _Clock()
    b = BreakerBoard(["w0", "w1"], fail_threshold=1, cooldown_s=2.0,
                     now=clk)
    b.record_failure("w0")
    b.record_stale("w1")  # monotonic-staleness feed force-opens
    assert int(b.counters["breaker_stale_trips"]) == 1
    assert b.targets() == []
    # retry_after = time to the earliest probe
    assert abs(b.retry_after_s() - 2.0) < 1e-6
    clk.t += 1.5
    assert abs(b.retry_after_s() - 0.5) < 1e-6
    # draining workers are excluded without being failures
    b2 = BreakerBoard(["a", "b"], now=clk)
    b2.set_draining("a", True)
    assert b2.targets() == ["b"]
    assert b2.state("a") == CLOSED
    b2.set_draining("a", False)
    assert b2.targets() == ["a", "b"]


# ------------------------------------------------------------- chaos core

def test_chaos_config_parse_and_env():
    cfg = ChaosConfig.parse("kill_after_tokens=8, drop_reply_p=0.25; "
                            "seed=7")
    assert cfg.kill_after_tokens == 8 and cfg.drop_reply_p == 0.25
    assert cfg.seed == 7 and cfg.armed
    with pytest.raises(ValueError):
        ChaosConfig.parse("drop_replyp=0.5")  # typo'd knob fails loudly
    assert ChaosConfig.from_env({"RAFIKI_CHAOS": ""}) is None
    assert ChaosConfig.from_env({}) is None
    got = ChaosConfig.from_env({"RAFIKI_CHAOS": "delay_queue_s=0.01"})
    assert got is not None and got.delay_queue_s == 0.01


def test_chaos_injector_deterministic_and_hub_faults():
    """Same seed + same traffic order = same faults; drops/corruption
    are counted; pops pass through untouched."""
    def run(seed):
        inj = ChaosInjector(ChaosConfig(drop_reply_p=0.5, seed=seed))
        hub = ChaosHub(InProcQueueHub(), inj)
        outcomes = []
        for i in range(32):
            hub.push_prediction("q", b"x%d" % i)
            outcomes.append(hub.pop_prediction("q", 0.0) is not None)
        return outcomes, int(inj.counters["replies_dropped"])

    a, dropped_a = run(3)
    b, dropped_b = run(3)
    c, _ = run(4)
    assert a == b                      # seeded: replayable
    assert a != c                      # different seed: different run
    assert 0 < dropped_a < 32 and dropped_a == dropped_b

    # corruption flips a byte but still delivers
    inj = ChaosInjector(ChaosConfig(corrupt_payload_p=1.0, seed=1))
    hub = ChaosHub(InProcQueueHub(), inj)
    hub.push_prediction("q", b"\x00\x00")
    got = hub.pop_prediction("q", 0.0)
    assert got is not None and got != b"\x00\x00"
    assert int(inj.counters["payloads_corrupted"]) == 1
    # kill trigger latches at the threshold
    inj = ChaosInjector(ChaosConfig(kill_after_tokens=3))
    assert not inj.should_kill(2)
    assert inj.should_kill(3) and inj.should_kill(99)


def test_corrupted_reply_skipped_in_unary_gather():
    """A corrupted reply payload is one replica's bad answer: the
    gather records the error and keeps the other replica's vote."""
    hub = InProcQueueHub()
    pred = Predictor(hub, ["w0", "w1"], gather_timeout=5.0)

    def worker(wid, corrupt):
        raw = hub.pop_query(wid, 5.0)
        msg = unpack_message(raw)
        data = pack_message({"id": msg["id"], "worker_id": wid,
                             "predictions": [[1.0]]})
        if corrupt:
            data = b"\xc1" + data  # 0xc1: never-used msgpack byte
        hub.push_prediction(msg["id"], data)

    ts = [threading.Thread(target=worker, args=("w0", True), daemon=True),
          threading.Thread(target=worker, args=("w1", False),
                           daemon=True)]
    for t in ts:
        t.start()
    preds, info = pred.predict([[0.0]], timeout=5.0)
    assert info["workers_answered"] == 1
    assert preds == [[1.0]]
    assert any("undecodable" in e for e in info["errors"])


# ----------------------------------------------------- fast-fail (503)

def test_all_breakers_open_fast_fails_structured_503():
    """With every worker dead: the first gather burns its (real)
    timeout and trips the breakers; the next request fast-fails in
    ~zero time with a structured 503 + retry_after_s; after the
    cooldown a probe is re-admitted."""
    hub = InProcQueueHub()
    # long cooldown: the breakers must stay open through the whole
    # test's HTTP leg (probe re-admission is unit-tested with the
    # injectable clock above)
    pred = Predictor(hub, ["w0", "w1"], gather_timeout=30.0,
                     breaker_fail_threshold=1, breaker_cooldown_s=60.0)
    _, info = pred.predict([[1.0]], timeout=1.1)
    assert info["workers_answered"] == 0 and not info.get("fast_fail")
    t0 = time.monotonic()
    preds, info = pred.predict([[1.0]], timeout=20.0)
    dt = time.monotonic() - t0
    assert dt < 0.5, f"fast-fail burned {dt:.2f}s of a 20s budget"
    assert preds == [] and info["fast_fail"]
    assert info["retry_after_s"] > 0
    assert info["workers_asked"] == 0
    assert info["workers_skipped"] == 2
    assert int(pred._c_fast_fail.value) == 1
    # the HTTP front maps it to a structured 503
    from rafiki_tpu.utils.http import HttpStatusError, json_request

    svc = PredictorService(pred)
    host, port = svc.start()
    try:
        with pytest.raises(HttpStatusError) as ei:
            json_request("POST", f"http://{host}:{port}/predict",
                         {"queries": [[1.0]], "timeout": 20.0})
        assert ei.value.status == 503
        assert ei.value.payload["retry_after_s"] > 0
        # breaker/fast-fail counters are visible on /metrics
        import urllib.request

        text = urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=5).read().decode()
        assert "breaker_trips 2" in text
        # two fast-fails by now: the direct predict() + the HTTP one
        assert "requests_fast_failed 2" in text
        assert "breaker_open_workers 2" in text
    finally:
        svc.stop()


def test_adaptive_budget_misses_do_not_trip_breakers():
    """Misses under a collapsed ADAPTIVE budget (or a tiny explicit
    timeout) are the latency controller shedding stragglers, not death:
    they must not feed the breakers (BREAKER_MIN_TIMEOUT_S gate)."""
    hub = InProcQueueHub()
    pred = Predictor(hub, ["w0"], gather_timeout=30.0,
                     breaker_fail_threshold=1)
    for _ in range(3):
        _, info = pred.predict([[1.0]], timeout=0.05)
        assert info["workers_answered"] == 0
    assert pred.breakers.state("w0") == CLOSED
    assert int(pred.breakers.counters["breaker_trips"]) == 0


def test_drained_workers_readmitted_without_health_polls():
    """The draining exclusion must self-clear from the respawned
    worker's published stats on the REQUEST path: a predictor used
    purely via predict() (no /health consumer) must not fast-fail
    forever after a rolling restart."""
    hub = InProcQueueHub()
    pred = Predictor(hub, ["w0"], gather_timeout=5.0)
    pred.breakers.set_draining("w0", True)
    # the respawned worker published fresh stats (draining=False)
    hub.put_worker_stats("w0", {"draining": False, "uptime_s": 1.0,
                                "stale_after_s": 60.0})

    def worker():
        raw = hub.pop_query("w0", 5.0)
        msg = unpack_message(raw)
        hub.push_prediction(msg["id"], pack_message(
            {"id": msg["id"], "worker_id": "w0",
             "predictions": [[1.0]]}))

    threading.Thread(target=worker, daemon=True).start()
    preds, info = pred.predict([[0.0]], timeout=5.0)
    assert not info.get("fast_fail")
    assert info["workers_answered"] == 1 and preds == [[1.0]]


# ------------------------------------------------ streaming failover

def _boot_lm_worker(trained, store, hub, wid, **kw):
    worker = InferenceWorker(LlamaLoRA, "t0", KNOBS, store, hub, wid,
                             decode_loop=True, max_slots=4,
                             max_new_tokens=6, **kw)
    th = threading.Thread(target=worker.run, daemon=True)
    th.start()
    return worker, th


@pytest.fixture()
def lm_store(trained):
    store = ParamStore.from_uri("mem://")
    store.save("t0", trained.dump_parameters())
    return store


def _collect_stream(events_iter):
    events = list(events_iter)
    acc = ""
    for ev in events[:-1]:
        assert set(ev) == {"delta"}, ev
        acc += "".join(ev["delta"].values())
    return events, acc


def test_stream_failover_token_exact_on_worker_kill(trained, lm_store):
    """THE acceptance chaos test: a worker chaos-killed mid-stream
    (deltas already delivered) fails over to a healthy replica which
    re-ingests the delivered text as a forced prefix — the stream
    completes with output exactly equal to a no-fault run: nothing
    duplicated, nothing lost."""
    # no-fault reference
    hub = InProcQueueHub()
    ref, ref_t = _boot_lm_worker(trained, lm_store, hub, "ref")
    try:
        events, acc = _collect_stream(Predictor(
            hub, ["ref"], gather_timeout=120.0).predict_stream(
                ["tok1 tok2 tok3"], timeout=60.0))
        expected = events[-1]["predictions"]
        assert acc == expected[0]
    finally:
        ref.stop()
        ref_t.join(timeout=10)

    # faulty fleet: w0 dies after 3 generated tokens (steps_per_sync=1
    # so deltas stream out BEFORE the death — the resume path, not a
    # clean retry), w1 healthy
    hub = InProcQueueHub()
    chaos = ChaosInjector(ChaosConfig(kill_after_tokens=3))
    w0, t0_ = _boot_lm_worker(trained, lm_store, hub, "w0",
                              steps_per_sync=1, chaos=chaos)
    w1, t1_ = _boot_lm_worker(trained, lm_store, hub, "w1")
    pred = Predictor(hub, ["w0", "w1"], gather_timeout=120.0,
                     stream_silence_timeout_s=1.0,
                     breaker_fail_threshold=1)
    try:
        events, acc = _collect_stream(
            pred.predict_stream(["tok1 tok2 tok3"], timeout=60.0))
        final = events[-1]
        assert final.get("done") and "error" not in final, final
        assert final["predictions"] == expected
        assert acc == expected[0], (acc, expected)
        assert final["info"]["failovers"] == 1
        assert w0.chaos_killed
        assert int(pred._c_failover.value) == 1
        assert pred.breakers.state("w0") == OPEN
        # the chaos injection is visible on the worker's metrics
        assert int(chaos.counters["kills"]) == 1
    finally:
        w1.stop()
        t1_.join(timeout=10)
        t0_.join(timeout=10)


def test_stream_resumable_error_and_client_side_resume(trained,
                                                       lm_store):
    """With NO healthy worker left after the kill, the stream ends in a
    structured resumable event (qid + partial + retry_after_s); feeding
    the partial back as ``resume_partial`` against a healthy fleet
    completes the generation without re-delivering the partial text."""
    hub = InProcQueueHub()
    ref, ref_t = _boot_lm_worker(trained, lm_store, hub, "ref")
    try:
        events, _ = _collect_stream(Predictor(
            hub, ["ref"], gather_timeout=120.0).predict_stream(
                ["tok1 tok2 tok3"], timeout=60.0))
        expected = events[-1]["predictions"]
    finally:
        ref.stop()
        ref_t.join(timeout=10)

    hub = InProcQueueHub()
    chaos = ChaosInjector(ChaosConfig(kill_after_tokens=3))
    w0, t0_ = _boot_lm_worker(trained, lm_store, hub, "w0",
                              steps_per_sync=1, chaos=chaos)
    pred = Predictor(hub, ["w0"], gather_timeout=120.0,
                     stream_silence_timeout_s=1.0,
                     breaker_fail_threshold=1)
    events, acc = _collect_stream(
        pred.predict_stream(["tok1 tok2 tok3"], timeout=60.0))
    t0_.join(timeout=10)
    final = events[-1]
    assert final["done"] and final.get("resumable"), final
    assert final["retry_after_s"] > 0 and final.get("qid")
    assert final["partial"][0] == acc and acc, final
    assert expected[0].startswith(acc) and acc != expected[0]
    assert int(pred._c_resumable.value) == 1

    # client-driven resume against a healthy fleet: the stream picks
    # up where it stopped — deltas continue PAST the partial and the
    # final text is exactly the no-fault answer
    hub2 = InProcQueueHub()
    w1, t1_ = _boot_lm_worker(trained, lm_store, hub2, "w1")
    try:
        pred2 = Predictor(hub2, ["w1"], gather_timeout=120.0)
        events2, acc2 = _collect_stream(pred2.predict_stream(
            ["tok1 tok2 tok3"], timeout=60.0,
            resume_partial=final["partial"]))
        final2 = events2[-1]
        assert "error" not in final2
        assert final2["predictions"] == expected
        assert acc + acc2 == expected[0], (acc, acc2, expected)
    finally:
        w1.stop()
        t1_.join(timeout=10)


# ------------------------------------------------------ graceful drain

def test_drain_finishes_inflight_stream_and_exits(trained, lm_store):
    """Drain mid-stream: the in-flight stream completes (zero dropped
    streams), new messages get structured draining rejections the
    predictor fails over on, the loop exits cleanly, and the published
    stats carry the draining flag into the breaker board."""
    hub = InProcQueueHub()
    w0, t0_ = _boot_lm_worker(trained, lm_store, hub, "w0",
                              steps_per_sync=1)
    w1, t1_ = _boot_lm_worker(trained, lm_store, hub, "w1")
    pred = Predictor(hub, ["w0", "w1"], gather_timeout=120.0)
    try:
        events = []
        got_first = threading.Event()

        def consume():
            for ev in pred.predict_stream(["tok1 tok2 tok3"],
                                          timeout=60.0):
                events.append(ev)
                got_first.set()

        th = threading.Thread(target=consume, daemon=True)
        th.start()
        assert got_first.wait(timeout=30), "no first delta"
        w0.drain()  # mid-stream: round-robin pinned this stream to w0
        th.join(timeout=60)
        final = events[-1]
        assert final.get("done") and "error" not in final, final
        assert final["predictions"][0]
        t0_.join(timeout=30)
        assert not t0_.is_alive(), "drained worker loop must exit"
        assert not w0.chaos_killed

        # the predictor learns the drain from published stats and
        # excludes the worker from scatter
        s = pred.stats()
        assert s["workers"]["w0"]["draining"] is True
        assert s["breakers"]["w0"]["draining"] is True
        assert pred.breakers.targets() == ["w1"]

        # new streams route around the drained id and still answer
        events2, acc2 = _collect_stream(
            pred.predict_stream(["tok4"], timeout=60.0))
        assert events2[-1].get("predictions")
    finally:
        w1.stop()
        t1_.join(timeout=10)


def test_drain_via_queue_control_message(trained, lm_store):
    """The {"control": "drain"} queue message drains a worker with no
    HTTP reachability; queued requests behind it get structured
    rejections (counted), and the loop exits. Messages are queued
    BEFORE the loop runs so the pop order is deterministic."""
    hub = InProcQueueHub()
    worker = InferenceWorker(LlamaLoRA, "t0", KNOBS, lm_store, hub,
                             "w0", decode_loop=True, max_slots=4,
                             max_new_tokens=6)
    hub.push_query("w0", pack_message({"control": "drain"}))
    # a request queued BEHIND the drain control: rejected, not starved
    hub.push_query("w0", pack_message(
        {"id": "q1", "queries": ["tok1"],
         "deadline_ts": time.time() + 60.0}))
    worker.run(poll_timeout=0.1)  # returns: drain-complete breaks it
    assert worker.draining
    reply = unpack_message(hub.pop_prediction("q1", 5.0))
    assert reply["draining"] and "draining" in reply["error"]
    assert int(worker.stats["drain_rejected"]) == 1


def test_drain_endpoint_on_obs_sidecar(trained, lm_store):
    """POST /drain on the obs sidecar (what rolling_restart uses)."""
    from rafiki_tpu.utils.http import json_request

    hub = InProcQueueHub()
    w0, t0_ = _boot_lm_worker(trained, lm_store, hub, "w0")
    host, port = w0.serve_obs()
    try:
        out = json_request("POST", f"http://{host}:{port}/drain", {},
                           timeout=5.0)
        assert out == {"ok": True, "draining": True}
        t0_.join(timeout=30)
        assert not t0_.is_alive() and w0.draining
    finally:
        w0.stop()


# ------------------------------------------------- rolling restart

def test_rolling_restart_drains_and_replaces_workers(tmp_path):
    """ServicesManager.rolling_restart over drainable child processes:
    each worker is drained (obs /drain), exits 0, and is replaced one
    at a time; slots are conserved and the counter advances."""
    from rafiki_tpu.admin.services_manager import ServicesManager
    from rafiki_tpu.constants import ServiceType
    from rafiki_tpu.parallel.mesh import DeviceSpec
    from rafiki_tpu.store.meta_store import MetaStore

    meta = MetaStore(str(tmp_path / "meta.db"))
    user = meta.create_user("op@x", "pw", "ADMIN")
    tj = meta.create_train_job(user["id"], "app", 1,
                               "LANGUAGE_MODELING", {"TRIAL_COUNT": 1},
                               "d1", "d2")
    ij = meta.create_inference_job(user["id"], tj["id"])
    meta.update_inference_job(ij["id"], status="RUNNING")
    mgr = ServicesManager(meta, str(tmp_path / "wd"), slot_size=1,
                          platform="cpu",
                          devices=[DeviceSpec(id=0), DeviceSpec(id=1)])
    try:
        old = []
        for i in range(2):
            wid = f"dw-{i}"
            old.append(mgr._spawn(
                "rafiki_tpu.chaos.dummy_service",
                {"worker_id": wid, "drain_linger_s": 0.2,
                 "obs_port_file": str(tmp_path / "wd"
                                      / f"{wid}.obs_port")},
                ServiceType.INFERENCE_WORKER,
                slot=mgr.allocator.acquire(),
                inference_job_id=ij["id"]))
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and not all(
                (tmp_path / "wd" / f"dw-{i}.obs_port").exists()
                for i in range(2)):
            time.sleep(0.05)
        out = mgr.rolling_restart(ij["id"], drain_timeout=30.0)
        assert len(out["restarted"]) == 2
        live = [s for s in mgr.services.values()
                if s.service_type == ServiceType.INFERENCE_WORKER]
        assert len(live) == 2 and all(s.alive() for s in live)
        assert not ({s.service_id for s in old}
                    & {s.service_id for s in live})
        # the drained originals exited CLEANLY (rc 0: drain, not crash)
        assert all(s.proc.returncode == 0 for s in old)
        assert mgr.respawn_stats()["rolling_restarts_done"] == 2
        assert mgr.allocator.free_count() == 0  # slots conserved
        with pytest.raises(KeyError):
            mgr.rolling_restart("no-such-job")
    finally:
        mgr.stop_all()


# -------------------------------------------- deadline skew (ttl_s)

def test_ttl_expiry_survives_worker_clock_skew():
    """A worker clock running AHEAD used to silently drop every fresh
    query once skew beat the wall pad; the relative ttl_s judged
    through the skew estimator serves them, while genuinely expired
    messages still drop with a far smaller pad."""
    est = ClockSkewEstimator()
    now = time.time()
    skew = 10.0  # predictor's clock is 10s behind this worker's
    fresh = {"deadline_ts": now - skew + 2.0, "ttl_s": 2.0,
             "sent_ts": now - skew}
    # wall fallback (old behavior): drops the FRESH query
    assert _expired(fresh) is True
    # ttl path: skew cancels, the query serves
    assert _expired(fresh, skew_est=est) is False
    # with the baseline established, true expiry still drops: sent 4s
    # of real elapsed ago against a 2s ttl
    stale = {"deadline_ts": now - skew + 2.0, "ttl_s": 2.0,
             "sent_ts": now - skew - 4.0}
    assert _expired(stale, skew_est=est) is True
    # payloads without the relative pair keep the wall behavior
    assert _expired({"deadline_ts": now + 60.0}, skew_est=est) is False
    assert _expired({"deadline_ts": now - 60.0}, skew_est=est) is True
    assert _expired({}, skew_est=est) is False


def test_clock_skew_estimator_converges_on_minimum():
    est = ClockSkewEstimator()
    base = time.time()
    # observations = skew(5s) + queueing noise; min converges on 5
    for delay in (3.0, 0.5, 1.5, 0.0, 2.0):
        est.elapsed_since(base - 5.0 - delay + (time.time() - base))
    # a fresh message now reads ~its true queueing delay
    got = est.elapsed_since(time.time() - 5.0 - 1.0)
    assert 0.5 < got < 1.6, got


# ------------------------------------------------- client SDK satellite

def test_client_predict_retries_structured_503():
    """One retry, honoring retry_after_s — then success."""
    from rafiki_tpu.client.client import Client
    from rafiki_tpu.utils.http import JsonHttpService

    calls = []

    def handler(_m, _b, _h):
        calls.append(time.monotonic())
        if len(calls) == 1:
            return 503, {"error": "all breakers open",
                         "retry_after_s": 0.3}
        return 200, {"predictions": [[1.0]], "info": {}}

    http = JsonHttpService()
    http.route("POST", "/predict", handler)
    host, port = http.start()
    try:
        client = Client.__new__(Client)
        client.timeout = 10.0
        out = client.predict(f"http://{host}:{port}", [[0.0]])
        assert out == [[1.0]]
        assert len(calls) == 2
        assert calls[1] - calls[0] >= 0.28  # honored retry_after_s
    finally:
        http.stop()


def test_client_stream_auto_resume_and_typed_event():
    """First stream ends resumable → the SDK re-requests with the
    partial as ``resume`` and splices the continuation; with resumes
    exhausted the terminal event is a typed StreamInterrupted."""
    import json as _json

    from rafiki_tpu.client.client import Client, StreamInterrupted
    from rafiki_tpu.utils.http import JsonHttpService, StreamResponse

    bodies = []

    def handler(_m, body, _h):
        bodies.append(body)

        def sse(events):
            for ev in events:
                yield b"data: " + _json.dumps(ev).encode() + b"\n\n"

        if len(bodies) == 1:
            return 200, StreamResponse(sse([
                {"delta": {"0": "par"}},
                {"done": True, "error": "no healthy worker",
                 "resumable": True, "qid": "q1", "partial": ["par"],
                 "retry_after_s": 0.05}]))
        return 200, StreamResponse(sse([
            {"delta": {"0": "tial"}},
            {"done": True, "predictions": ["partial"],
             "info": {}}]))

    http = JsonHttpService()
    http.route("POST", "/predict_stream", handler)
    host, port = http.start()
    try:
        client = Client.__new__(Client)
        client.timeout = 10.0
        events = list(client.predict_stream(
            f"http://{host}:{port}", ["q"], auto_resume=1))
        # the resumable event is swallowed; deltas splice seamlessly
        assert [e for e in events if isinstance(e, dict)
                and "delta" in e] == [{"delta": {"0": "par"}},
                                      {"delta": {"0": "tial"}}]
        assert events[-1]["predictions"] == ["partial"]
        assert bodies[1]["resume"] == ["par"]  # partial handed back

        # exhausted resumes: typed terminal event, duck-dict compatible
        bodies.clear()
        events = list(client.predict_stream(
            f"http://{host}:{port}", ["q"], auto_resume=0))
        term = events[-1]
        assert isinstance(term, StreamInterrupted)
        assert term.done and term.resumable
        assert term.partial == ["par"] and term.qid == "q1"
        assert term.get("done") is True  # dict-style access works
        assert term["partial"] == ["par"]
    finally:
        http.stop()


# --------------------------------- TextDecodeEngine forced prefix

def test_text_engine_forced_prefix_instant_done():
    """A resume whose prefix already covers the whole token budget
    completes without touching the engine (the lost-final-message
    case)."""
    from rafiki_tpu.serving.decode_engine import TextDecodeEngine

    class StubEngine:
        def __init__(self):
            self.submitted = []

        def submit(self, *a, **k):
            self.submitted.append((a, k))

        def poll(self):
            return []

        def poll_partial(self):
            return []

    import numpy as np

    stub = StubEngine()
    eng = TextDecodeEngine(
        stub, lambda t: np.zeros(len(t.split()), np.int32),
        lambda ids: "", max_new=2)
    assert eng.supports_resume
    # prefix of 2 words == the whole budget: instant done
    eng.submit("r", "p1 p2", forced_prefix="g1 g2")
    assert stub.submitted == []
    assert eng.poll() == [("r", "g1 g2")]
    assert eng.poll() == []
    # prefix of 1 word: budget shrinks to 1, prompt carries the prefix
    eng.submit("r2", "p1 p2", max_new=2, forced_prefix="g1")
    (args, kwargs) = stub.submitted[0]
    assert len(args[1]) == 3  # p1 p2 g1 re-ingested as prompt
    assert args[2] == 1       # one token left to generate
