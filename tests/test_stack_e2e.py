"""Full-stack end-to-end: Admin REST → train services → deploy → predict.

The scale-down analog of the reference's quickstart integration flow
(SURVEY.md §4): the whole multi-service topology on one machine, CPU JAX,
real subprocesses for advisor / train workers / data plane / inference
workers / predictor.
"""

import numpy as np
import pytest

from rafiki_tpu.admin.admin import Admin
from rafiki_tpu.admin.app import AdminApp
from rafiki_tpu.admin.services_manager import ServicesManager
from rafiki_tpu.client import Client
from rafiki_tpu.data import generate_image_classification_dataset
from rafiki_tpu.models.mlp import JaxFeedForward
from rafiki_tpu.parallel.mesh import DeviceSpec
from rafiki_tpu.store.meta_store import MetaStore


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    work = tmp_path_factory.mktemp("stack")
    meta = MetaStore(str(work / "meta.db"))
    manager = ServicesManager(
        meta, str(work), slot_size=1, platform="cpu",
        devices=[DeviceSpec(id=i) for i in range(4)])
    manager.start_data_plane()
    admin = Admin(meta, manager)
    admin.start_monitor(interval_s=0.3)
    app = AdminApp(admin)
    host, port = app.start()
    client = Client(f"http://{host}:{port}")
    try:
        yield client, work
    finally:
        app.stop()


@pytest.fixture(scope="module")
def datasets(tmp_path_factory):
    d = tmp_path_factory.mktemp("e2e_ds")
    tr, va = str(d / "train.npz"), str(d / "val.npz")
    generate_image_classification_dataset(tr, 256, seed=0)
    val = generate_image_classification_dataset(va, 64, seed=1)
    return tr, va, val


@pytest.mark.slow
def test_full_stack_train_deploy_predict(stack, datasets):
    client, _work = stack
    tr, va, val = datasets

    out = client.login("superadmin@rafiki", "rafiki")
    assert out["token"]

    model = client.create_model("mlp", "IMAGE_CLASSIFICATION",
                                JaxFeedForward)
    ds_tr = client.create_dataset("train", "IMAGE_CLASSIFICATION", tr)
    ds_va = client.create_dataset("val", "IMAGE_CLASSIFICATION", va)

    job = client.create_train_job(
        app="e2e-app", task="IMAGE_CLASSIFICATION",
        train_dataset_id=ds_tr["id"], val_dataset_id=ds_va["id"],
        budget={"TRIAL_COUNT": 2, "WORKER_COUNT": 2},
        model_ids=[model["id"]],
        train_args={"advisor": "random"})
    assert job["status"] == "RUNNING"
    assert len(job["sub_train_jobs"]) == 1

    job = client.wait_until_train_job_finished(job["id"], timeout=600)
    assert job["status"] == "STOPPED"

    trials = client.get_trials_of_train_job(job["id"])
    assert len(trials) == 2
    completed = [t for t in trials if t["status"] == "COMPLETED"]
    assert completed, f"no completed trials: {trials}"

    best = client.get_best_trials_of_train_job(job["id"])
    assert best[0]["score"] > 0.3
    logs = client.get_trial_logs(best[0]["id"])
    assert any(r["kind"] == "values" for r in logs)

    ijob = client.create_inference_job(job["id"], max_workers=2)
    assert ijob["predictor_url"]

    preds = client.predict(ijob["predictor_url"],
                           [val.images[i] for i in range(4)], timeout=120)
    assert len(preds) == 4
    acc = np.mean([int(np.argmax(p)) == val.labels[i]
                   for i, p in enumerate(preds)])
    assert acc >= 0.5

    client.stop_inference_job(ijob["id"])
    final = client.get_inference_job(ijob["id"])
    assert final["status"] == "STOPPED"


@pytest.mark.slow
def test_full_stack_lm_generation(stack):
    """Config #5 through the REST stack: LlamaLoRA train job -> deploy ->
    the inference worker serves generations via the continuous-batching
    decode loop (decode_loop auto-enabled for LANGUAGE_MODELING)."""
    from rafiki_tpu.data import generate_text_classification_dataset
    from rafiki_tpu.models.llama_lora import LlamaLoRA

    client, work = stack
    d = work / "lm_ds"
    d.mkdir(exist_ok=True)
    tr, va = str(d / "train.jsonl"), str(d / "val.jsonl")
    generate_text_classification_dataset(tr, 64, seed=0)
    generate_text_classification_dataset(va, 24, seed=1)

    client.login("superadmin@rafiki", "rafiki")
    model = client.create_model("llama", "LANGUAGE_MODELING", LlamaLoRA)
    job = client.create_train_job(
        app="lm-app", task="LANGUAGE_MODELING",
        train_dataset_id=tr, val_dataset_id=va,
        budget={"TRIAL_COUNT": 1, "WORKER_COUNT": 1},
        model_ids=[model["id"]],
        # knob_overrides pin the advisor's samples to a tiny in-domain
        # config (FixedKnobs like vocab_size can't be overridden)
        train_args={"advisor": "random", "knob_overrides": {
            "hidden_dim": 64, "depth": 2, "n_heads": 4, "kv_ratio": 2,
            "lora_rank": 4, "max_len": 32, "model_parallel": 1,
            "learning_rate": 1e-2, "batch_size": 8, "bf16": False,
            "quick_train": True, "share_params": False}})
    job = client.wait_until_train_job_finished(job["id"], timeout=600)
    assert job["status"] == "STOPPED"
    trials = client.get_trials_of_train_job(job["id"])
    assert any(t["status"] == "COMPLETED" for t in trials), trials

    ijob = client.create_inference_job(job["id"], max_workers=1)
    assert ijob["predictor_url"]
    prompts = ["tok1 tok2 tok3", "tok4 tok5"]
    preds = client.predict(ijob["predictor_url"], prompts, timeout=180)
    assert len(preds) == 2
    assert all(isinstance(p, str) and p for p in preds), preds
    client.stop_inference_job(ijob["id"])

    # paged-KV deployment surface: misconfigurations fail the API call
    # (not a crash-looping worker), a sized-down pool serves the SAME
    # text as the contiguous engine above, and the pool gauges ride
    # /health (KV_PAGE_SIZE/KV_PAGES — docs/operations.md)
    with pytest.raises(RuntimeError, match="KV_PAGES requires"):
        client.create_inference_job(job["id"], max_workers=1,
                                    budget={"KV_PAGES": 9})
    with pytest.raises(RuntimeError, match="KV_PAGE_SIZE"):
        client.create_inference_job(
            job["id"], max_workers=1,
            budget={"KV_PAGE_SIZE": 5})  # doesn't divide max_len=32
    with pytest.raises(RuntimeError, match="KV_PAGES"):
        client.create_inference_job(
            job["id"], max_workers=1,
            budget={"KV_PAGE_SIZE": 8, "KV_PAGES": 1})
    with pytest.raises(RuntimeError, match="PAGED_KERNEL requires"):
        client.create_inference_job(job["id"], max_workers=1,
                                    budget={"PAGED_KERNEL": True})
    # PAGED_KERNEL rides the same surface (forced gather here — the
    # auto rule resolves to gather on CPU anyway; the gauge proves the
    # dispatch is visible end-to-end)
    ijob = client.create_inference_job(
        job["id"], max_workers=1,
        budget={"KV_PAGE_SIZE": 8, "KV_PAGES": 9,
                "PAGED_KERNEL": "false"})
    paged = client.predict(ijob["predictor_url"], prompts, timeout=180)
    assert paged == preds, (paged, preds)
    health = client.get_inference_job_health(ijob["id"])
    workers = (health.get("workers") or {}).values()
    assert any(s.get("engine_kv_pages_total") == 8 for s in workers), \
        health
    assert any(s.get("engine_paged_kernel_mode") == 0
               for s in workers), health
    client.stop_inference_job(ijob["id"])


@pytest.mark.slow
def test_typod_knob_override_rejected_at_api(stack, datasets):
    """A knob_overrides key matching no model's knob config must 400 at
    job creation (not silently run the search unpinned), and must not
    leave a zombie RUNNING job behind."""
    client, _work = stack
    tr, va, _val = datasets

    client.login("superadmin@rafiki", "rafiki")
    model = client.create_model("mlp-typo", "IMAGE_CLASSIFICATION",
                                JaxFeedForward)
    with pytest.raises(RuntimeError, match="knob_overrides.*learnin_rate"):
        client.create_train_job(
            app="typo-app", task="IMAGE_CLASSIFICATION",
            train_dataset_id=tr, val_dataset_id=va,
            budget={"TRIAL_COUNT": 1, "WORKER_COUNT": 1},
            model_ids=[model["id"]],
            train_args={"knob_overrides": {"learnin_rate": 1e-4}})
    job = client.get_train_job_of_app("typo-app")
    assert job["status"] == "ERRORED", job


@pytest.mark.slow
def test_full_stack_multi_adapter_deploy(stack):
    """MULTI_ADAPTER budget flag through the REST stack: two
    adapters_only LoRA trials deploy as ONE stacked-adapter worker
    (one device slot) and requests route by sampling adapter_id."""
    from rafiki_tpu.data import generate_text_classification_dataset
    from rafiki_tpu.models.llama_lora import LlamaLoRA

    client, work = stack
    d = work / "ma_ds"
    d.mkdir(exist_ok=True)
    tr, va = str(d / "train.jsonl"), str(d / "val.jsonl")
    generate_text_classification_dataset(tr, 64, seed=0)
    generate_text_classification_dataset(va, 24, seed=1)

    client.login("superadmin@rafiki", "rafiki")
    model = client.create_model("llama-ma", "LANGUAGE_MODELING",
                                LlamaLoRA)
    job = client.create_train_job(
        app="lm-ma-app", task="LANGUAGE_MODELING",
        train_dataset_id=tr, val_dataset_id=va,
        budget={"TRIAL_COUNT": 2, "WORKER_COUNT": 1},
        model_ids=[model["id"]],
        train_args={"advisor": "random", "knob_overrides": {
            "hidden_dim": 64, "depth": 2, "n_heads": 4, "kv_ratio": 2,
            "lora_rank": 4, "max_len": 32, "model_parallel": 1,
            "batch_size": 8, "bf16": False, "quick_train": True,
            "share_params": False, "adapters_only": True}})
    job = client.wait_until_train_job_finished(job["id"], timeout=600)
    assert job["status"] == "STOPPED"
    trials = client.get_trials_of_train_job(job["id"])
    assert sum(t["status"] == "COMPLETED" for t in trials) >= 2, trials

    ijob = client.create_inference_job(
        job["id"], max_workers=2,
        budget={"MULTI_ADAPTER": 1, "ADAPTIVE_GATHER": 1})
    assert ijob["predictor_url"]
    p0 = client.predict(ijob["predictor_url"], ["tok1 tok2 tok3"],
                        timeout=180, sampling={"adapter_id": 0})
    p1 = client.predict(ijob["predictor_url"], ["tok1 tok2 tok3"],
                        timeout=180, sampling={"adapter_id": 1})
    assert all(isinstance(p[0], str) and p[0] for p in (p0, p1))
    # ONE stacked worker served both trials (stats publish on the
    # worker's loop, so check AFTER traffic has flowed)
    import time as _time
    for _ in range(40):
        health = client._call(
            "GET", f"/inference_jobs/{ijob['id']}/health")
        if len(health.get("workers") or {}) == 1:
            break
        _time.sleep(0.5)
    assert len(health.get("workers") or {}) == 1, health
    # the ADAPTIVE_GATHER budget flag reached the spawned predictor
    assert health.get("adaptive_gather") is True, health
    assert "gather_deadline_s" in health
    # out-of-range tenant ids are rejected, not silently misrouted
    import pytest as _pytest
    with _pytest.raises(RuntimeError):
        client.predict(ijob["predictor_url"], ["tok1"], timeout=60,
                       sampling={"adapter_id": 5})
    client.stop_inference_job(ijob["id"])


@pytest.mark.slow
def test_quickstart_fashion_archive_end_to_end(stack, tmp_path_factory):
    """SURVEY §4's quickstart-as-integration-test with the REAL archive
    byte format (VERDICT r4 item 7): a FashionMNIST-layout zip (28x28
    grayscale PNGs + labels.csv with the published class names) flows
    client -> train -> deploy -> predict through the full service
    stack — config #1 on the actual bytes the reference's quickstart
    downloads, generated offline."""
    from rafiki_tpu.data import generate_fashion_archive

    client, _work = stack
    d = tmp_path_factory.mktemp("fashion")
    tr, va = str(d / "fashion_train.zip"), str(d / "fashion_val.zip")
    generate_fashion_archive(tr, n_examples=256, seed=0)
    val = generate_fashion_archive(va, n_examples=64, seed=1)

    client.login("superadmin@rafiki", "rafiki")
    model = client.create_model("mlp-fashion", "IMAGE_CLASSIFICATION",
                                JaxFeedForward)
    ds_tr = client.create_dataset("fashion-train", "IMAGE_CLASSIFICATION",
                                  tr)
    ds_va = client.create_dataset("fashion-val", "IMAGE_CLASSIFICATION",
                                  va)

    job = client.create_train_job(
        app="fashion-app", task="IMAGE_CLASSIFICATION",
        train_dataset_id=ds_tr["id"], val_dataset_id=ds_va["id"],
        budget={"TRIAL_COUNT": 2, "WORKER_COUNT": 2},
        model_ids=[model["id"]],
        train_args={"advisor": "random"})
    job = client.wait_until_train_job_finished(job["id"], timeout=600)
    assert job["status"] == "STOPPED"
    best = client.get_best_trials_of_train_job(job["id"])
    assert best and best[0]["status"] == "COMPLETED"
    assert best[0]["score"] > 0.3, best[0]

    ijob = client.create_inference_job(job["id"], max_workers=1)
    preds = client.predict(ijob["predictor_url"],
                           [val.images[i] for i in range(8)],
                           timeout=120)
    assert len(preds) == 8
    acc = np.mean([int(np.argmax(p)) == val.labels[i]
                   for i, p in enumerate(preds)])
    assert acc >= 0.5, acc
    client.stop_inference_job(ijob["id"])


@pytest.mark.slow
def test_full_stack_speculative_deploy(stack):
    """SPECULATE_K + DRAFT_TRIAL_ID through the REST stack: an LM job
    trains two trials; the best deploys with the other completed trial
    as its draft MODEL. The engine must actually run the speculative
    path (stats counter) and still serve text. Misconfigurations
    (DRAFT_TRIAL_ID without SPECULATE_K, SPECULATE_K < 2) must fail
    the API call loudly, not crash-loop a worker."""
    from rafiki_tpu.data import generate_text_classification_dataset
    from rafiki_tpu.models.llama_lora import LlamaLoRA

    client, work = stack
    d = work / "spec_ds"
    d.mkdir(exist_ok=True)
    tr, va = str(d / "train.jsonl"), str(d / "val.jsonl")
    generate_text_classification_dataset(tr, 64, seed=0)
    generate_text_classification_dataset(va, 24, seed=1)

    client.login("superadmin@rafiki", "rafiki")
    model = client.create_model("llama-spec", "LANGUAGE_MODELING",
                                LlamaLoRA)
    job = client.create_train_job(
        app="spec-app", task="LANGUAGE_MODELING",
        train_dataset_id=tr, val_dataset_id=va,
        budget={"TRIAL_COUNT": 2, "WORKER_COUNT": 1},
        model_ids=[model["id"]],
        train_args={"advisor": "random", "knob_overrides": {
            "hidden_dim": 64, "depth": 2, "n_heads": 4, "kv_ratio": 2,
            "lora_rank": 4, "max_len": 32, "model_parallel": 1,
            "learning_rate": 1e-2, "batch_size": 8, "bf16": False,
            "quick_train": True, "share_params": False}})
    job = client.wait_until_train_job_finished(job["id"], timeout=600)
    trials = [t for t in client.get_trials_of_train_job(job["id"])
              if t["status"] == "COMPLETED"]
    assert len(trials) >= 2, trials
    best = client.get_best_trials_of_train_job(job["id"])
    draft_id = next(t["id"] for t in trials if t["id"] != best[0]["id"])

    # misconfigurations fail the API call, not a crash-looping worker
    with pytest.raises(RuntimeError, match="SPECULATE_K"):
        client.create_inference_job(
            job["id"], max_workers=1,
            budget={"DRAFT_TRIAL_ID": draft_id})
    with pytest.raises(RuntimeError, match="SPECULATE_K"):
        client.create_inference_job(
            job["id"], max_workers=1,
            budget={"SPECULATE_K": 1, "DRAFT_TRIAL_ID": draft_id})

    ijob = client.create_inference_job(
        job["id"], max_workers=1,
        budget={"SPECULATE_K": 4, "DRAFT_TRIAL_ID": draft_id,
                "MAX_NEW_TOKENS": 6})
    preds = client.predict(ijob["predictor_url"],
                           ["tok1 tok2 tok3"], timeout=180)
    assert len(preds) == 1 and isinstance(preds[0], str) and preds[0]
    # engine counters publish as engine_* keys every STATS_EVERY loop
    # iterations — keep traffic flowing so the loop iterates (and the
    # speculative path keeps running) until a snapshot lands
    eng = {}
    for i in range(30):
        client.predict(ijob["predictor_url"],
                       [f"tok{i % 5 + 1} tok2 tok3"], timeout=60)
        health = client.get_inference_job_health(ijob["id"])
        eng = next(iter(health.get("workers", {}).values()), {})
        if eng.get("engine_spec_draft_model_calls", 0) or \
                eng.get("engine_spec_calls", 0):
            break
    assert eng.get("engine_spec_draft_model_calls", 0) > 0 or \
        eng.get("engine_spec_calls", 0) > 0, eng
    client.stop_inference_job(ijob["id"])
