"""Gradient accumulation (grad_accum knob): summed micro-batch
gradients are EXACTLY the big-batch step (the global valid-token count
is model-independent, so each micro-batch's objective divides by it)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rafiki_tpu.data import generate_text_classification_dataset
from rafiki_tpu.model import TrainContext
from rafiki_tpu.models.llama_lora import LlamaLoRA

from test_models_llama import TINY  # noqa: F401


def _train(tmp_path, **extra):
    tr = str(tmp_path / "t.jsonl")
    if not (tmp_path / "t.jsonl").exists():
        generate_text_classification_dataset(tr, 96, seed=0)
    # batch 32: divisible by the 8-device data axis AND by
    # grad_accum*data (4*8), so both runs see the SAME batches
    knobs = {**TINY, "model_parallel": 1, "max_epochs": 2,
             "batch_size": 32, **extra}
    m = LlamaLoRA(**knobs)
    ctx = TrainContext(devices=list(jax.devices()))
    m.train(tr, ctx)
    return m, ctx.logger.get_values("loss")


@pytest.mark.slow
def test_grad_accum_matches_big_batch_exactly(tmp_path):
    """Same data order, same init: grad_accum=4 must reproduce the
    big-batch parameters numerically (identical math, different
    activation-memory profile)."""
    m1, l1 = _train(tmp_path)
    m4, l4 = _train(tmp_path, grad_accum=4)
    np.testing.assert_allclose(np.asarray(l4), np.asarray(l1),
                               rtol=2e-5, atol=2e-5)
    a = jax.tree_util.tree_leaves(m1._params)
    b = jax.tree_util.tree_leaves(m4._params)
    for x, y in zip(a, b):
        # reduction ORDER differs (sequential scan vs fused batch), so
        # f32 noise compounds through two epochs of adam — 1e-3 still
        # cleanly separates equivalent math from a wrong objective
        # (which differs at 1e-1 scale)
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-3, atol=1e-3)


@pytest.mark.slow
def test_grad_accum_composes_with_chunked_loss(tmp_path):
    # slow leg: the composition smoke compiles a grad-accum scan AROUND
    # the checkpointed chunked-loss scan (~20s of XLA for a loss-goes-
    # down assertion); the component oracles already ride the slow twins
    # (test_grad_accum_matches_big_batch_exactly, test_llama_trains_
    # with_chunked_loss), so the default leg keeps neither duplicated
    m, losses = _train(tmp_path, grad_accum=2, loss_chunk=8)
    assert losses and np.isfinite(losses[-1])
    assert losses[-1] < losses[0]


def test_grad_accum_rejects_pipeline(tmp_path):
    tr = str(tmp_path / "t.jsonl")
    generate_text_classification_dataset(tr, 16, seed=0)
    import pytest

    knobs = {**TINY, "model_parallel": 1, "depth": 4,
             "pipeline_stages": 2, "grad_accum": 2}
    with pytest.raises(ValueError, match="redundant"):
        LlamaLoRA(**knobs).train(
            tr, TrainContext(devices=list(jax.devices())))
