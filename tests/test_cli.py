"""CLI surface: the operator doctor report."""


def test_cli_doctor(capsys):
    from rafiki_tpu.cli import main

    rc = main(["doctor"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "jax backend" in out and "bpe round-trip" in out
    assert "all checks passed" in out
