"""Dashboard page + jobs-listing REST (SURVEY.md §1 layer 1)."""

import urllib.request

import pytest

from rafiki_tpu.admin.admin import Admin
from rafiki_tpu.admin.app import AdminApp
from rafiki_tpu.admin.services_manager import ServicesManager
from rafiki_tpu.parallel.mesh import DeviceSpec
from rafiki_tpu.store.meta_store import MetaStore
from rafiki_tpu.utils.http import json_request


def test_dashboard_and_job_listing(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.db"))
    manager = ServicesManager(meta, str(tmp_path), slot_size=1,
                              platform="cpu",
                              devices=[DeviceSpec(id=0)])
    admin = Admin(meta, manager)
    app = AdminApp(admin)
    host, port = app.start()
    base = f"http://{host}:{port}"
    try:
        with urllib.request.urlopen(base + "/", timeout=10) as resp:
            assert resp.headers["Content-Type"].startswith("text/html")
            html = resp.read().decode()
        assert "rafiki-tpu dashboard" in html
        assert "/trials/" in html  # wired to the loss-curve endpoint
        assert "Search convergence" in html  # best-score-vs-trials plot
        assert "trialDetail" in html  # preemption/error forensics pane

        token = json_request("POST", base + "/tokens",
                             {"email": "superadmin@rafiki",
                              "password": "rafiki"})["token"]
        hdrs = {"Authorization": f"Bearer {token}"}
        jobs = json_request("GET", base + "/train_jobs", headers=hdrs)
        assert jobs == []
        health = json_request("GET", base + "/health")
        assert health["ok"] and health["respawns_done"] == 0
        assert health["pending_respawns"] == 0
    finally:
        app.stop()


def test_dashboard_panels_and_endpoints(tmp_path):
    """The round-3 panels (models/datasets/inference jobs + predictor
    health) render and their REST endpoints answer live."""
    meta = MetaStore(str(tmp_path / "meta.db"))
    manager = ServicesManager(meta, str(tmp_path), slot_size=1,
                              platform="cpu",
                              devices=[DeviceSpec(id=0)])
    admin = Admin(meta, manager)
    app = AdminApp(admin)
    host, port = app.start()
    base = f"http://{host}:{port}"
    try:
        with urllib.request.urlopen(base + "/", timeout=10) as resp:
            html = resp.read().decode()
        # panels present and wired to their endpoints
        for section in ("Models", "Datasets", "Inference jobs"):
            assert section in html, section
        for endpoint in ('"/models"', '"/datasets"', '"/inference_jobs"',
                         "/health"):
            assert endpoint in html, endpoint

        token = json_request("POST", base + "/tokens",
                             {"email": "superadmin@rafiki",
                              "password": "rafiki"})["token"]
        hdrs = {"Authorization": f"Bearer {token}"}
        assert json_request("GET", base + "/models", headers=hdrs) == []
        assert json_request("GET", base + "/datasets", headers=hdrs) == []
        assert json_request("GET", base + "/inference_jobs",
                            headers=hdrs) == []

        # register a dataset + model; the listings pick them up
        ds = json_request("POST", base + "/datasets",
                          {"name": "d1", "task": "IMAGE_CLASSIFICATION",
                           "uri": str(tmp_path / "d.npz")}, headers=hdrs)
        assert ds["name"] == "d1"
        datasets = json_request("GET", base + "/datasets", headers=hdrs)
        assert [d["name"] for d in datasets] == ["d1"]
    finally:
        app.stop()


@pytest.mark.slow
def test_dashboard_write_paths(tmp_path):
    """VERDICT r3 item 9: model upload, dataset registration, train-job
    create/stop, inference deploy/stop — the page's forms/buttons exist
    AND the exact endpoints they call work end-to-end over HTTP."""
    import base64

    from rafiki_tpu.data import generate_image_classification_dataset

    meta = MetaStore(str(tmp_path / "meta.db"))
    manager = ServicesManager(meta, str(tmp_path), slot_size=1,
                              platform="cpu",
                              devices=[DeviceSpec(id=0)])
    admin = Admin(meta, manager)
    app = AdminApp(admin)
    host, port = app.start()
    base = f"http://{host}:{port}"
    try:
        with urllib.request.urlopen(base + "/", timeout=10) as resp:
            html = resp.read().decode()
        # the write-path UI is wired: forms + the endpoints they POST to
        for control in ("nmUpload", "ndRegister", "njCreate", "niDeploy",
                        "niMulti", "niAdaptive",  # budget-flag options
                        "MULTI_ADAPTER", "ADAPTIVE_GATHER",
                        "gather_deadline_s",  # controller in health
                        "+ upload model", "+ register dataset",
                        "+ new train job", "+ deploy inference job"):
            assert control in html, control
        for call in ('api("POST", "/models"', 'api("POST", "/datasets"',
                     'api("POST", "/train_jobs"',
                     'api("POST", "/inference_jobs"',
                     "/stop`"):
            assert call in html, call

        token = json_request("POST", base + "/tokens",
                             {"email": "superadmin@rafiki",
                              "password": "rafiki"})["token"]
        hdrs = {"Authorization": f"Bearer {token}"}

        # 1) model upload — exactly the page's payload shape (b64 source)
        src = (
            "from rafiki_tpu.models.mlp import JaxFeedForward\n"
            "class MyMLP(JaxFeedForward):\n"
            "    pass\n")
        model = json_request("POST", base + "/models", {
            "name": "my-mlp", "task": "IMAGE_CLASSIFICATION",
            "model_class": "MyMLP",
            "model_bytes": base64.b64encode(src.encode()).decode()},
            headers=hdrs)
        assert model["name"] == "my-mlp"
        assert [m["name"] for m in json_request(
            "GET", base + "/models", headers=hdrs)] == ["my-mlp"]

        # 2) dataset registration (train + val)
        tr = str(tmp_path / "tr.npz")
        va = str(tmp_path / "va.npz")
        generate_image_classification_dataset(tr, 96, seed=0)
        generate_image_classification_dataset(va, 32, seed=1)
        ds_tr = json_request("POST", base + "/datasets",
                             {"name": "tr", "task": "IMAGE_CLASSIFICATION",
                              "uri": tr}, headers=hdrs)
        ds_va = json_request("POST", base + "/datasets",
                             {"name": "va", "task": "IMAGE_CLASSIFICATION",
                              "uri": va}, headers=hdrs)

        # 3) train job create (page body shape) … then stop from the UI
        job = json_request("POST", base + "/train_jobs", {
            "app": "ui-app", "task": "IMAGE_CLASSIFICATION",
            "train_dataset_id": ds_tr["id"],
            "val_dataset_id": ds_va["id"],
            "budget": {"TRIAL_COUNT": 1},
            "model_ids": [model["id"]]}, headers=hdrs)
        assert job["status"] in ("RUNNING", "STARTED")
        assert json_request("POST",
                            base + f"/train_jobs/{job['id']}/stop",
                            {}, headers=hdrs)["ok"]
        stopped = json_request("GET", base + f"/train_jobs/{job['id']}",
                               headers=hdrs)
        assert stopped["status"] == "STOPPED"

        # 4) inference deploy against a job with no completed trials
        # answers with a structured error, not a 500 (the UI shows it)
        try:
            json_request("POST", base + "/inference_jobs",
                         {"train_job_id": job["id"]}, headers=hdrs)
            deployed = True
        except RuntimeError as e:  # json_request wraps HTTP errors
            deployed = False
            assert "409" in str(e) or "400" in str(e), e
        if deployed:  # (a trial may have completed before the stop)
            ij = json_request("GET", base + "/inference_jobs",
                              headers=hdrs)[0]
            json_request("POST",
                         base + f"/inference_jobs/{ij['id']}/stop",
                         {}, headers=hdrs)
    finally:
        app.stop()
