"""Dashboard page + jobs-listing REST (SURVEY.md §1 layer 1)."""

import urllib.request

from rafiki_tpu.admin.admin import Admin
from rafiki_tpu.admin.app import AdminApp
from rafiki_tpu.admin.services_manager import ServicesManager
from rafiki_tpu.parallel.mesh import DeviceSpec
from rafiki_tpu.store.meta_store import MetaStore
from rafiki_tpu.utils.http import json_request


def test_dashboard_and_job_listing(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.db"))
    manager = ServicesManager(meta, str(tmp_path), slot_size=1,
                              platform="cpu",
                              devices=[DeviceSpec(id=0)])
    admin = Admin(meta, manager)
    app = AdminApp(admin)
    host, port = app.start()
    base = f"http://{host}:{port}"
    try:
        with urllib.request.urlopen(base + "/", timeout=10) as resp:
            assert resp.headers["Content-Type"].startswith("text/html")
            html = resp.read().decode()
        assert "rafiki-tpu dashboard" in html
        assert "/trials/" in html  # wired to the loss-curve endpoint
        assert "Search convergence" in html  # best-score-vs-trials plot
        assert "trialDetail" in html  # preemption/error forensics pane

        token = json_request("POST", base + "/tokens",
                             {"email": "superadmin@rafiki",
                              "password": "rafiki"})["token"]
        hdrs = {"Authorization": f"Bearer {token}"}
        jobs = json_request("GET", base + "/train_jobs", headers=hdrs)
        assert jobs == []
        health = json_request("GET", base + "/health")
        assert health["ok"] and health["respawns_done"] == 0
        assert health["pending_respawns"] == 0
    finally:
        app.stop()


def test_dashboard_panels_and_endpoints(tmp_path):
    """The round-3 panels (models/datasets/inference jobs + predictor
    health) render and their REST endpoints answer live."""
    meta = MetaStore(str(tmp_path / "meta.db"))
    manager = ServicesManager(meta, str(tmp_path), slot_size=1,
                              platform="cpu",
                              devices=[DeviceSpec(id=0)])
    admin = Admin(meta, manager)
    app = AdminApp(admin)
    host, port = app.start()
    base = f"http://{host}:{port}"
    try:
        with urllib.request.urlopen(base + "/", timeout=10) as resp:
            html = resp.read().decode()
        # panels present and wired to their endpoints
        for section in ("Models", "Datasets", "Inference jobs"):
            assert section in html, section
        for endpoint in ('"/models"', '"/datasets"', '"/inference_jobs"',
                         "/health"):
            assert endpoint in html, endpoint

        token = json_request("POST", base + "/tokens",
                             {"email": "superadmin@rafiki",
                              "password": "rafiki"})["token"]
        hdrs = {"Authorization": f"Bearer {token}"}
        assert json_request("GET", base + "/models", headers=hdrs) == []
        assert json_request("GET", base + "/datasets", headers=hdrs) == []
        assert json_request("GET", base + "/inference_jobs",
                            headers=hdrs) == []

        # register a dataset + model; the listings pick them up
        ds = json_request("POST", base + "/datasets",
                          {"name": "d1", "task": "IMAGE_CLASSIFICATION",
                           "uri": str(tmp_path / "d.npz")}, headers=hdrs)
        assert ds["name"] == "d1"
        datasets = json_request("GET", base + "/datasets", headers=hdrs)
        assert [d["name"] for d in datasets] == ["d1"]
    finally:
        app.stop()
