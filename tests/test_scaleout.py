"""Horizontal scale-out: affinity router, live membership, autoscaler.

Covers the data-plane router (rendezvous-hash affinity with minimal
remap, load-aware fallback, breaker gating under seeded
membership/flap chaos), the predictor's live pool membership
(add/remove_worker, hub-published diffs, the in-flight-stream removal
regression), the control-plane autoscaler (policy decisions, budget
validation, process-level grow/shrink over real child processes), and
the acceptance drill: N=3 workers ≥ 2.5× single-worker streamed
tokens/s at no-worse p95 TTFT, affinity hit rate > 0.9 under
shared-prefix traffic, and zero dropped/duplicated stream tokens
across an autoscale-up, a drain-based scale-down, and a rolling
restart — on the deterministic capacity-model harness
(``rafiki_tpu.chaos.scaleout``)."""

import random
import threading
import time

import pytest

from rafiki_tpu.chaos.scaleout import (ScaleoutHarness,
                                       shared_prefix_prompts)
from rafiki_tpu.serving.breaker import CLOSED, OPEN, BreakerBoard
from rafiki_tpu.serving.predictor import Predictor
from rafiki_tpu.serving.queues import (InProcQueueHub, pack_message,
                                       unpack_message)
from rafiki_tpu.serving.router import Router


# ------------------------------------------------------------- router

def _router(wids, **kw):
    board = BreakerBoard(wids, fail_threshold=1, cooldown_s=60.0)
    return Router(wids, board, **kw), board


def test_hrw_owner_is_stable_and_remaps_minimally():
    """THE rendezvous property: removing a worker remaps only the keys
    it owned; adding one only claims keys whose new top score it is —
    every other key keeps its (warm) worker."""
    r, _ = _router(["w0", "w1", "w2"])
    keys = [f"prefix-{i}" for i in range(300)]
    before = {k: r.owner(k) for k in keys}
    assert all(v in ("w0", "w1", "w2") for v in before.values())
    # every worker owns a nontrivial share (blake2b spreads)
    for w in ("w0", "w1", "w2"):
        assert sum(1 for v in before.values() if v == w) > 30

    r.remove_worker("w1")
    after_rm = {k: r.owner(k) for k in keys}
    for k in keys:
        if before[k] != "w1":
            assert after_rm[k] == before[k], k  # survivors keep keys

    r.add_worker("w1")
    after_add = {k: r.owner(k) for k in keys}
    assert after_add == before  # re-join restores the exact map

    r.add_worker("w3")
    after_w3 = {k: r.owner(k) for k in keys}
    for k in keys:
        assert after_w3[k] in (before[k], "w3"), k  # only w3 claims


def test_select_affinity_hit_and_exclude_successor():
    r, _ = _router(["w0", "w1", "w2"])
    key = "shared-system-prefix"
    owner = r.owner(key)
    assert r.select(key) == owner
    assert int(r.counters["router_affinity_hits"]) == 1
    # same key, many selects: always the same worker
    assert {r.select(key) for _ in range(10)} == {owner}
    # a failover retry (owner excluded) goes to the HRW successor —
    # still counted as affinity (minimal remap), still deterministic
    successor = r.owner(key, exclude=(owner,))
    assert r.select(key, exclude=(owner,)) == successor != owner


def test_select_load_redirect_on_saturation_and_least_loaded():
    r, _ = _router(["w0", "w1", "w2"])
    key = "shared-prefix"
    owner = r.owner(key)
    others = [w for w in ("w0", "w1", "w2") if w != owner]
    # saturate the owner: page pool ~full
    r.observe(owner, {"engine_kv_pages_used": 97,
                      "engine_kv_pages_total": 100})
    assert r.saturated(owner)
    # load-rank the others: w_busy has backlog, w_idle is empty
    w_busy, w_idle = others
    r.observe_queue_depth(w_busy, 5)
    r.observe(w_busy, {"engine_kv_pages_used": 50,
                       "engine_kv_pages_total": 100})
    pick = r.select(key)
    assert pick == w_idle
    assert int(r.counters["router_affinity_redirects"]) == 1
    assert int(r.counters["router_least_loaded_picks"]) == 1
    assert 0.0 <= r.affinity_hit_rate() < 1.0
    # a stall-counter INCREASE marks saturated; the hold then expires
    clk = [100.0]
    r2, _ = _router(["a", "b"])
    r2._now = lambda: clk[0]
    r2.observe("a", {"engine_admission_stalls": 3})
    assert not r2.saturated("a")  # first sight: baseline, no delta
    r2.observe("a", {"engine_admission_stalls": 5})
    assert r2.saturated("a")
    clk[0] += Router.STALL_HOLD_S + 0.1
    assert not r2.saturated("a")


def test_select_gates_on_breakers_and_probes_one():
    r, board = _router(["w0", "w1"])
    board.record_failure("w0")  # threshold=1: open
    assert board.state("w0") == OPEN
    for _ in range(8):
        assert r.select("any-key") == "w1"
    board.set_draining("w1", True)
    # no closed candidate, w0's cooldown (60s) not due: nothing
    assert r.select("any-key") is None
    assert int(r.counters["router_no_candidate"]) >= 1
    # draining clears: w1 serves again without a breaker penalty
    board.set_draining("w1", False)
    assert r.select("any-key") == "w1"
    # all open with a due cooldown: exactly one probe per due breaker
    clk = _Clock()
    board2 = BreakerBoard(["a", "b"], fail_threshold=1, cooldown_s=1.0,
                          now=clk)
    r2 = Router(["a", "b"], board2)
    board2.record_failure("a")
    board2.record_failure("b")
    assert r2.select("k") is None
    clk.t += 1.01
    probe = r2.select("k")
    assert probe in ("a", "b")
    assert int(r2.counters["router_probe_picks"]) == 1
    # the probe is outstanding: the OTHER due breaker gets the next one
    second = r2.select("k")
    assert second in ("a", "b") and second != probe


class _Clock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_router_chaos_membership_and_breaker_flaps_seeded():
    """Seeded chaos over joins/leaves/trips/recoveries/drains: the
    router never hands out a worker that is excluded, non-member,
    draining, or open-without-a-due-probe; and every leave remaps only
    the departed worker's keys."""
    rng = random.Random(7)
    clk = _Clock()
    board = BreakerBoard([], fail_threshold=1, cooldown_s=5.0, now=clk)
    r = Router([], board, now=clk)
    keys = [f"k{i}" for i in range(60)]
    pool = []
    next_id = 0
    for step in range(300):
        ev = rng.choice(["join", "leave", "trip", "recover", "drain",
                         "undrain", "tick"])
        if ev == "join" or not pool:
            wid = f"w{next_id}"
            next_id += 1
            board.add_worker(wid)
            r.add_worker(wid)
            pool.append(wid)
        elif ev == "leave" and len(pool) > 1:
            wid = rng.choice(pool)
            owned = {k for k in keys if r.owner(k) == wid}
            before = {k: r.owner(k) for k in keys}
            board.remove_worker(wid)
            r.remove_worker(wid)
            pool.remove(wid)
            for k in keys:  # minimal remap holds under churn
                if k not in owned:
                    assert r.owner(k) == before[k]
            # straggling outcome feeds must not resurrect the id
            board.record_failure(wid)
            board.record_success(wid)
            assert wid not in board.snapshot()
        elif ev == "trip":
            board.record_failure(rng.choice(pool))
        elif ev == "recover":
            board.record_success(rng.choice(pool))
        elif ev == "drain":
            board.set_draining(rng.choice(pool), True)
        elif ev == "undrain":
            board.set_draining(rng.choice(pool), False)
        else:
            clk.t += rng.random() * 3.0

        for k in rng.sample(keys, 10):
            exclude = set(rng.sample(pool, min(len(pool) - 1,
                                               rng.randrange(2))))
            snap = board.snapshot()
            pick = r.select(k, exclude=exclude)
            if pick is None:
                continue
            assert pick in pool and pick not in exclude
            st = snap.get(pick)
            assert st is not None and not st["draining"]
            # CLOSED, or the single admitted half-open probe
            assert st["state"] == CLOSED or \
                board.state(pick) == "half_open"


def test_breaker_board_membership():
    b = BreakerBoard(["w0"], fail_threshold=1)
    b.add_worker("w1")
    assert b.targets() == ["w0", "w1"]
    b.remove_worker("w0")
    assert b.targets() == ["w1"]
    assert b.state("w0") == CLOSED  # unknown reads as closed...
    assert not b.allow("w0")        # ...but is never admittable
    b.record_failure("w0")          # no resurrection
    b.set_draining("w0", True)
    b.record_stale("w0")
    assert "w0" not in b.snapshot()
    assert b.retry_after_s() == 0.0  # w1 is admittable
    b.add_worker("w0")               # re-join starts CLOSED
    assert b.state("w0") == CLOSED and b.allow("w0")


# --------------------------------------- predictor live membership

def _unary_worker(hub, wid, stop):
    """Answer unary scatters until stopped."""
    def loop():
        while not stop.is_set():
            raw = hub.pop_query(wid, 0.1)
            if raw is None:
                continue
            m = unpack_message(raw)
            if "id" not in m:
                continue
            hub.push_prediction(m["id"], pack_message(
                {"id": m["id"], "worker_id": wid,
                 "predictions": [[1.0]] * len(m["queries"])}))

    th = threading.Thread(target=loop, daemon=True)
    th.start()
    return th


def test_predictor_add_remove_worker_unary():
    hub = InProcQueueHub()
    stop = threading.Event()
    ths = [_unary_worker(hub, w, stop) for w in ("w0", "w1")]
    pred = Predictor(hub, ["w0"], gather_timeout=10.0)
    try:
        _, info = pred.predict([[0.0]], timeout=10.0)
        assert info["workers_asked"] == 1
        pred.add_worker("w1")
        _, info = pred.predict([[0.0]], timeout=10.0)
        assert info["workers_asked"] == 2 and \
            info["workers_answered"] == 2
        pred.remove_worker("w0")
        preds, info = pred.predict([[0.0]], timeout=10.0)
        assert info["workers_asked"] == 1 and preds == [[1.0]]
        assert sorted(pred.breakers.snapshot()) == ["w1"]
        assert pred.router.members() == ["w1"]
        assert "w0" not in pred._worker_seen
    finally:
        stop.set()
        for th in ths:
            th.join(timeout=5)


def test_predictor_membership_follows_hub_publish():
    """The router/breaker tables follow the control plane's published
    membership without a rebuild; stale versions and empty lists are
    ignored."""
    hub = InProcQueueHub()
    pred = Predictor(hub, ["w0"], gather_timeout=5.0, pool_id="job1")
    hub.put_pool_members("job1", {"workers": ["w0", "w1"],
                                  "version": 100.0})
    pred._refresh_membership(force=True)
    assert pred.router.members() == ["w0", "w1"]
    # an OLDER version must not roll the pool back
    hub.put_pool_members("job1", {"workers": ["w0"], "version": 50.0})
    pred._refresh_membership(force=True)
    assert pred.router.members() == ["w0", "w1"]
    # an empty worker list is a publisher bug, not an instruction
    hub.put_pool_members("job1", {"workers": [], "version": 200.0})
    pred._refresh_membership(force=True)
    assert pred.router.members() == ["w0", "w1"]
    # a newer list applies both the add and the remove
    hub.put_pool_members("job1", {"workers": ["w1", "w2"],
                                  "version": 300.0})
    pred._refresh_membership(force=True)
    assert pred.router.members() == ["w1", "w2"]
    assert sorted(pred.breakers.snapshot()) == ["w1", "w2"]


def test_remove_worker_with_inflight_stream_fails_over():
    """THE satellite regression: removing a worker that has an
    in-flight stream must fail the stream over (token-exact via the
    forced prefix), not KeyError."""
    h = ScaleoutHarness(2, max_slots=4, max_new=40,
                        base_step_s=0.005, per_req_step_s=0.005,
                        stream_silence_timeout_s=10.0)
    try:
        prompt = shared_prefix_prompts(1, 1)[0]
        # route deterministically: the stream lands on the key's owner
        victim = h.pred.router.owner(h.pred.router.affinity_key([prompt]))
        got_first = threading.Event()
        out = {}

        def consume():
            out.update(h.run_stream(prompt, timeout=60.0))

        # run_stream sets no event; watch the victim's engine instead
        th = threading.Thread(target=consume, daemon=True)
        th.start()
        deadline = time.monotonic() + 10
        w, _ = h.workers[victim]
        while time.monotonic() < deadline and not int(
                w.engine.stats.get("tokens_generated", 0) or 0):
            time.sleep(0.01)
        assert int(w.engine.stats.get("tokens_generated", 0) or 0), \
            "stream never started on the affinity owner"
        got_first.set()
        h.pred.remove_worker(victim)
        th.join(timeout=60)
        assert not th.is_alive()
        assert out["ok"], out  # token-exact despite mid-stream removal
        assert out["failovers"] >= 1
        assert victim not in h.pred.router.members()
    finally:
        h.stop()


# ----------------------------------------------- autoscaler policy

def test_autoscale_config_from_budget_validation():
    from rafiki_tpu.admin.autoscaler import AutoscaleConfig

    assert AutoscaleConfig.from_budget({}, 2) is None
    cfg = AutoscaleConfig.from_budget(
        {"AUTOSCALE": 1, "MIN_WORKERS": 1, "MAX_WORKERS": 4,
         "AUTOSCALE_COOLDOWN_S": 5}, 2)
    assert (cfg.min_workers, cfg.max_workers, cfg.cooldown_s) == (1, 4,
                                                                  5.0)
    with pytest.raises(ValueError):  # bounds without the switch
        AutoscaleConfig.from_budget({"MAX_WORKERS": 3}, 1)
    with pytest.raises(ValueError):  # AUTOSCALE without a ceiling
        # would default MAX to the initial count — a policy that can
        # never scale up, silently
        AutoscaleConfig.from_budget({"AUTOSCALE": 1}, 2)
    with pytest.raises(ValueError):  # initial outside bounds
        AutoscaleConfig.from_budget(
            {"AUTOSCALE": 1, "MIN_WORKERS": 2, "MAX_WORKERS": 3}, 1)
    with pytest.raises(ValueError):
        AutoscaleConfig.from_budget(
            {"AUTOSCALE": 1, "MIN_WORKERS": 0}, 1)
    with pytest.raises(ValueError):
        AutoscaleConfig.from_budget(
            {"AUTOSCALE": 1, "MAX_WORKERS": 2,
             "AUTOSCALE_COOLDOWN_S": 0}, 1)


def test_autoscale_policy_grow_shrink_cooldown():
    from rafiki_tpu.admin.autoscaler import (AutoscaleConfig,
                                             AutoscalePolicy)

    clk = _Clock()
    cfg = AutoscaleConfig(min_workers=1, max_workers=3, cooldown_s=10.0,
                          grow_stall_ticks=2, shrink_idle_ticks=3,
                          shrink_pages_ratio=0.5)
    p = AutoscalePolicy(cfg, now=clk)

    def stats(stalls, used=1, total=32):
        return {"w0": {"engine_admission_stalls": stalls,
                       "engine_kv_pages_used": used,
                       "engine_kv_pages_total": total}}

    assert p.observe(stats(0)) is None      # baseline
    assert p.observe(stats(2)) is None      # 1st stalling tick
    assert p.observe(stats(5)) == "up"      # 2nd consecutive: grow
    clk.t += 1.0
    assert p.observe(stats(9)) is None      # cooldown blocks
    clk.t += 10.0
    # idle: stalls flat + pages low → shrink after 3 ticks (and the
    # pool must exceed min_workers, which one worker does not)
    for _ in range(5):
        assert p.observe(stats(9)) is None
    two = {"w0": stats(9)["w0"], "w1": {"engine_admission_stalls": 0,
                                        "engine_kv_pages_used": 1,
                                        "engine_kv_pages_total": 32}}
    clk.t += 20.0
    assert p.observe(two) is None
    assert p.observe(two) is None
    assert p.observe(two) == "down"
    # a missing worker's stats block shrink, not grow
    clk.t += 20.0
    gone = {"w0": two["w0"], "w1": None}
    for _ in range(6):
        assert p.observe(gone) is None
    # high pages block shrink too
    clk.t += 20.0
    hot = {"w0": {"engine_admission_stalls": 9,
                  "engine_kv_pages_used": 30,
                  "engine_kv_pages_total": 32}, "w1": two["w1"]}
    for _ in range(6):
        assert p.observe(hot) is None


# ------------------------------- autoscaler over real processes

@pytest.fixture()
def inference_job_manager(tmp_path):
    """MetaStore + ServicesManager + kvd data plane + a RUNNING
    inference job whose 'workers' are drainable dummy services."""
    from rafiki_tpu.admin.services_manager import ServicesManager
    from rafiki_tpu.constants import ServiceType
    from rafiki_tpu.parallel.mesh import DeviceSpec
    from rafiki_tpu.store.meta_store import MetaStore

    meta = MetaStore(str(tmp_path / "meta.db"))
    user = meta.create_user("op@x", "pw", "ADMIN")
    tj = meta.create_train_job(user["id"], "app", 1,
                               "LANGUAGE_MODELING", {"TRIAL_COUNT": 1},
                               "d1", "d2")
    ij = meta.create_inference_job(
        user["id"], tj["id"],
        budget={"AUTOSCALE": 1, "MIN_WORKERS": 1, "MAX_WORKERS": 3,
                "AUTOSCALE_COOLDOWN_S": 0.05})
    meta.update_inference_job(ij["id"], status="RUNNING")
    mgr = ServicesManager(meta, str(tmp_path / "wd"), slot_size=1,
                          platform="cpu",
                          devices=[DeviceSpec(id=i) for i in range(3)])
    mgr.start_data_plane()
    wid = f"iw-{ij['id'][:8]}-0"
    mgr._spawn(
        "rafiki_tpu.chaos.dummy_service",
        {"worker_id": wid, "drain_linger_s": 0.1,
         "obs_port_file": str(tmp_path / "wd" / f"{wid}.obs_port")},
        ServiceType.INFERENCE_WORKER,
        slot=mgr.allocator.acquire(),
        inference_job_id=ij["id"])
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and not (
            tmp_path / "wd" / f"{wid}.obs_port").exists():
        time.sleep(0.05)
    try:
        yield mgr, ij["id"], wid
    finally:
        mgr.stop_all()


def _publish_worker_stats(mgr, wid, stalls, used=1, total=32):
    from rafiki_tpu.serving.queues import KVQueueHub

    KVQueueHub(mgr.kv_host, mgr.kv_port).put_worker_stats(
        wid, {"engine_admission_stalls": stalls,
              "engine_kv_pages_used": used,
              "engine_kv_pages_total": total, "uptime_s": 1.0})


def test_autoscaler_grows_and_shrinks_over_processes(
        inference_job_manager):
    """End-to-end control plane: sustained stalls spawn a REAL extra
    worker process from the job's template (joining the published
    routing pool only once its obs port reports), idle signals drain it
    back out through the graceful-drain path, membership is published
    to the kv hub at every step, and slots are conserved."""
    from rafiki_tpu.serving.queues import KVQueueHub

    mgr, job_id, w0 = inference_job_manager
    hub = KVQueueHub(mgr.kv_host, mgr.kv_port)
    st = mgr._ensure_scaleout(job_id)  # the rebuild path (adoption)
    assert st is not None and st["policy"] is not None
    assert st["pool"] == [w0]
    mgr._publish_pool(job_id)
    assert hub.get_pool_members(job_id)["workers"] == [w0]

    # sustained stalls → scale-up (policy needs a baseline + 2 ticks)
    _publish_worker_stats(mgr, w0, stalls=0)
    assert mgr.autoscale_tick(force=True) == []
    _publish_worker_stats(mgr, w0, stalls=4)
    mgr.autoscale_tick(force=True)
    _publish_worker_stats(mgr, w0, stalls=9)
    actions = mgr.autoscale_tick(force=True)
    assert [a["action"] for a in actions] == ["up"], actions
    new_wid = actions[0]["worker"]
    assert new_wid != w0
    assert int(mgr.scaling["autoscale_ups"]) == 1
    # warming: not yet in the published pool until the obs port lands
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        mgr.autoscale_tick(force=True)
        if hub.get_pool_members(job_id)["workers"] == [w0, new_wid]:
            break
        time.sleep(0.05)
    assert hub.get_pool_members(job_id)["workers"] == [w0, new_wid]
    assert mgr.scaleout_status(job_id)["pool"] == [w0, new_wid]

    # idle signals → drain-based scale-down of the emptier worker
    for i in range(8):
        _publish_worker_stats(mgr, w0, stalls=9, used=2)
        _publish_worker_stats(mgr, new_wid, stalls=0, used=1)
        actions = mgr.autoscale_tick(force=True)
        if actions:
            break
        time.sleep(0.02)
    assert [a["action"] for a in actions] == ["down"], actions
    assert actions[0]["worker"] == new_wid
    # membership shrank IMMEDIATELY (before the victim finished)
    assert hub.get_pool_members(job_id)["workers"] == [w0]
    # the victim drains (dummy exits 0) and is reaped; slot conserved
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        mgr.poll()
        mgr.autoscale_tick(force=True)
        if mgr.scaleout_status(job_id)["victim"] is None:
            break
        time.sleep(0.05)
    assert mgr.scaleout_status(job_id)["victim"] is None
    assert mgr.scaleout_status(job_id)["pool"] == [w0]
    assert int(mgr.scaling["autoscale_downs"]) == 1
    assert mgr.allocator.free_count() == 2  # 3 slots, 1 worker + kvd=0


def test_manual_scale_inference_job(inference_job_manager):
    """The operator override: scale to an exact count through the same
    warm-then-publish / drain-then-reap machinery, synchronously."""
    from rafiki_tpu.serving.queues import KVQueueHub

    mgr, job_id, w0 = inference_job_manager
    hub = KVQueueHub(mgr.kv_host, mgr.kv_port)
    out = mgr.scale_inference_job(job_id, 3, drain_timeout=30.0)
    assert len(out["scaled_up"]) == 2 and out["scaled_down"] == []
    assert len(out["pool"]) == 3
    assert hub.get_pool_members(job_id)["workers"] == out["pool"]
    assert mgr.allocator.free_count() == 0
    out = mgr.scale_inference_job(job_id, 1, drain_timeout=30.0)
    assert len(out["scaled_down"]) == 2
    assert out["pool"] == [w0]
    assert hub.get_pool_members(job_id)["workers"] == [w0]
    assert mgr.allocator.free_count() == 2
    with pytest.raises(ValueError):
        mgr.scale_inference_job(job_id, 0)
    with pytest.raises(KeyError):
        mgr.scale_inference_job("no-such-job", 2)


def test_ensemble_pool_refuses_scaling(tmp_path):
    """A pool whose replicas serve DISTINCT trials is an ensemble:
    the rebuilt autoscaler disables itself (clones would skew the
    gather) and manual scale refuses with a clear error."""
    from rafiki_tpu.admin.services_manager import ServicesManager
    from rafiki_tpu.constants import ServiceType
    from rafiki_tpu.parallel.mesh import DeviceSpec
    from rafiki_tpu.store.meta_store import MetaStore

    meta = MetaStore(str(tmp_path / "meta.db"))
    user = meta.create_user("op@x", "pw", "ADMIN")
    tj = meta.create_train_job(user["id"], "app", 1,
                               "LANGUAGE_MODELING", {"TRIAL_COUNT": 1},
                               "d1", "d2")
    ij = meta.create_inference_job(
        user["id"], tj["id"],
        budget={"AUTOSCALE": 1, "MAX_WORKERS": 4})
    meta.update_inference_job(ij["id"], status="RUNNING")
    mgr = ServicesManager(meta, str(tmp_path / "wd"), slot_size=1,
                          platform="cpu",
                          devices=[DeviceSpec(id=0), DeviceSpec(id=1)])
    try:
        for i, trial in enumerate(("trial-A", "trial-B")):
            mgr._respawn_specs[f"sid{i}"] = {
                "module": "rafiki_tpu.chaos.dummy_service",
                "config": {"worker_id": f"iw-{ij['id'][:8]}-{i}",
                           "trial_id": trial},
                "service_type": ServiceType.INFERENCE_WORKER,
                "needs_slot": True,
                "meta_kwargs": {"inference_job_id": ij["id"]}}
        st = mgr._ensure_scaleout(ij["id"])
        assert st is not None and st["policy"] is None  # disabled
        with pytest.raises(RuntimeError, match="DISTINCT trials"):
            mgr.scale_inference_job(ij["id"], 3)
    finally:
        mgr.stop_all()


def test_sdk_scale_and_autoscaler_endpoints():
    """Client SDK ↔ admin-route contract for the new endpoints."""
    from rafiki_tpu.client.client import Client
    from rafiki_tpu.utils.http import JsonHttpService

    calls = []

    def scale(m, body, _h):
        calls.append(("scale", m["id"], body))
        return 200, {"job_id": m["id"], "pool": ["a", "b"],
                     "scaled_up": ["b"], "scaled_down": []}

    def autoscaler(m, _b, _h):
        calls.append(("get", m["id"], None))
        return 200, {"enabled": True, "pool": ["a", "b"],
                     "min_workers": 1, "max_workers": 4}

    http = JsonHttpService()
    http.route("POST", "/inference_jobs/<id>/scale", scale)
    http.route("GET", "/inference_jobs/<id>/autoscaler", autoscaler)
    host, port = http.start()
    try:
        client = Client(admin_url=f"http://{host}:{port}", timeout=10.0)
        out = client.scale_inference_job("j1", 2, drain_timeout=5.0)
        assert out["pool"] == ["a", "b"]
        assert calls[0] == ("scale", "j1",
                            {"workers": 2, "drain_timeout": 5.0})
        out = client.get_inference_job_autoscaler("j1")
        assert out["enabled"] and out["max_workers"] == 4
    finally:
        http.stop()


# ----------------------------------------------- acceptance drill

def test_scaleout_acceptance_throughput_affinity_and_zero_loss():
    """THE acceptance chaos+load proof, on the deterministic
    capacity-model harness: (a) 3 workers sustain ≥ 2.5× the
    single-worker aggregate streamed tokens/s at a p95 TTFT no worse
    than the single worker's; (b) prefix-affinity hit rate > 0.9 under
    shared-prefix traffic; (c) zero dropped/duplicated stream tokens —
    every stream token-exact vs its deterministic expected completion —
    across one autoscale-up, one drain-based scale-down, and one
    rolling restart performed mid-load."""
    MAX_NEW = 20
    KW = dict(max_slots=8, max_new=MAX_NEW, base_step_s=0.001,
              per_req_step_s=0.002, stream_silence_timeout_s=10.0)

    # --- phase 1: one worker, saturating shared-prefix load
    h1 = ScaleoutHarness(1, **KW)
    try:
        prompts = shared_prefix_prompts(6, 3)
        single = h1.run_load(prompts, n_clients=18,
                             streams_per_client=2, timeout=120.0)
    finally:
        h1.stop()
    assert single["ok"], single["failures"][:2]

    # --- phase 2: three workers, same load, balanced prefix groups
    # (prefix families assigned by the real HRW map: 2 per worker, so
    # the measurement isolates scaling from hash-imbalance luck)
    h3 = ScaleoutHarness(3, **KW)
    try:
        groups_per_worker = {w: [] for w in h3.workers}
        g = 0
        while any(len(v) < 2 for v in groups_per_worker.values()) \
                and g < 500:
            fam = f"fam{g:03d}-" * 12  # > 64 chars: one affinity key
            owner = h3.pred.router.owner(fam[:64])
            if len(groups_per_worker[owner]) < 2:
                groups_per_worker[owner].append(fam)
            g += 1
        assert all(len(v) == 2 for v in groups_per_worker.values())
        prompts3 = [f"{p} user question {j}"
                    for fam in groups_per_worker.values()
                    for p in fam for j in range(3)]
        scaled = h3.run_load(prompts3, n_clients=18,
                             streams_per_client=2, timeout=120.0)
        snap = h3.pred.router.snapshot()
    finally:
        h3.stop()
    assert scaled["ok"], scaled["failures"][:2]
    ratio = scaled["tokens_per_s"] / max(single["tokens_per_s"], 1e-9)
    assert ratio >= 2.5, (ratio, single["tokens_per_s"],
                          scaled["tokens_per_s"])
    assert scaled["ttft_p95_s"] <= single["ttft_p95_s"], (
        scaled["ttft_p95_s"], single["ttft_p95_s"])
    hit_rate = snap["affinity_hit_rate"]
    assert hit_rate > 0.9, snap

    # --- phase 3: membership cycle under load, zero token loss
    hc = ScaleoutHarness(2, **KW)
    try:
        prompts = shared_prefix_prompts(4, 3)
        events = []

        def cycle():
            wid = hc.add_worker()          # autoscale-up
            events.append(("up", wid))
            time.sleep(0.3)
            victim = [w for w in hc.workers if w != wid][0]
            hc.drain_worker(victim)        # drain-based scale-down
            events.append(("down", victim))
            time.sleep(0.2)
            hc.rolling_restart()           # zero-downtime deploy
            events.append(("rolling_restart", tuple(hc.workers)))

        cyc = hc.run_load(prompts, n_clients=8, streams_per_client=6,
                          timeout=120.0, on_half_done=cycle)
        assert len(events) == 3, events
        assert cyc["ok"], cyc["failures"][:2]  # zero dropped/dup tokens
        assert cyc["streams"] == 48
    finally:
        hc.stop()
