"""ViT template: contract conformance + DP sharding on the virtual mesh."""

import functools

import pytest

import jax
import numpy as np

from rafiki_tpu.constants import TaskType
from rafiki_tpu.data import generate_image_classification_dataset
from rafiki_tpu.model import TrainContext, test_model_class
from rafiki_tpu.models.vit import ViT, ViTBase16


TINY = {"patch_size": 4, "hidden_dim": 96, "depth": 2, "n_heads": 4,
        "batch_size": 32, "max_epochs": 5, "learning_rate": 1e-3,
        "weight_decay": 1e-4, "warmup_frac": 0.1, "bf16": False,
        "remat": False,
        "quick_train": False, "share_params": False}


@pytest.mark.slow
def test_vit_module_shapes():
    m = ViT(patch_size=4, hidden_dim=64, depth=2, n_heads=4, mlp_dim=128,
            n_classes=7)
    x = np.zeros((2, 32, 32, 3), np.float32)
    params = m.init(jax.random.PRNGKey(0), x)["params"]
    out = m.apply({"params": params}, x)
    assert out.shape == (2, 7)


@pytest.mark.slow
def test_vit_template_contract(tmp_path):
    tr, va = str(tmp_path / "t.npz"), str(tmp_path / "v.npz")
    generate_image_classification_dataset(tr, 192, seed=0)
    ds = generate_image_classification_dataset(va, 48, seed=1)
    preds = test_model_class(ViTBase16, TaskType.IMAGE_CLASSIFICATION,
                             tr, va, queries=[ds.images[0]], knobs=TINY)
    assert len(preds) == 1 and len(preds[0]) == ds.n_classes


@pytest.mark.slow
def test_vit_trains_data_parallel(tmp_path):
    """Train over 8 virtual devices; loss must decrease."""
    tr = str(tmp_path / "t.npz")
    generate_image_classification_dataset(tr, 192, seed=0)
    model = ViTBase16(**TINY)
    ctx = TrainContext(devices=list(jax.devices()))
    model.train(tr, ctx)
    losses = ctx.logger.get_values("loss")
    assert len(losses) >= 2 and losses[-1] < losses[0]


@pytest.mark.slow
def test_vit_bf16_compute_keeps_f32_params():
    """The bf16 knob must give bf16 ACTIVATIONS with f32 params — a
    promotion regression would silently triple MXU cost on TPU."""
    import jax.numpy as jnp

    m = ViT(patch_size=4, hidden_dim=64, depth=1, n_heads=4, mlp_dim=128,
            n_classes=5, dtype=jnp.bfloat16)
    x = jnp.zeros((2, 16, 16, 3), jnp.bfloat16)
    params = m.init(jax.random.PRNGKey(0), x)["params"]
    # master params stay f32 (optimizer numerics + checkpoints)
    assert all(p.dtype == jnp.float32
               for p in jax.tree_util.tree_leaves(params))
    # the transformer blocks compute in bf16: check a block's dense
    # output dtype via a captured intermediate
    out, state = m.apply({"params": params}, x, capture_intermediates=True)
    block_out = state["intermediates"]["block_0"]["__call__"][0]
    assert block_out.dtype == jnp.bfloat16, block_out.dtype
    # logits head stays f32 for a stable softmax/loss
    assert out.dtype == jnp.float32


def test_vit_v1_checkpoint_prep_compat():
    """A v1 checkpoint (trained on [0,1] inputs) must keep seeing [0,1]
    inputs at serving time — the version follows the weights, not the
    build (ADVICE r3)."""
    m = ViTBase16(**TINY)
    assert m._prep_version == 2  # fresh models train under v2
    m._n_classes = 3
    m._image_shape = [8, 8, 3]
    white = np.full((1, 8, 8, 3), 255, np.uint8)
    assert np.isclose(m._prep(white).max(), 1.0)
    assert np.isclose(m._prep(np.zeros((1, 8, 8, 3), np.uint8)).min(), -1.0)

    # v1 load: normalization switches to [0, 1] and survives a re-dump
    m2 = ViTBase16(**TINY)
    m2.load_parameters({
        "params": {"w": np.zeros((1,), np.float32)},
        "meta": {"n_classes": 3, "image_shape": [8, 8, 3]},  # no version
    })
    assert m2._prep_version == 1
    assert np.isclose(m2._prep(white).max(), 1.0)
    assert np.isclose(m2._prep(np.zeros((1, 8, 8, 3), np.uint8)).min(), 0.0)
    assert m2.dump_parameters()["meta"]["prep_version"] == 1


@pytest.mark.slow
def test_remat_identical_math_smaller_residuals():
    """remat=True must change NOTHING numerically (same outputs, same
    grads from the same params) while rematerializing block activations
    in the backward instead of saving them."""
    import jax.numpy as jnp

    kw = dict(patch_size=4, hidden_dim=64, depth=3, n_heads=4,
              mlp_dim=128, n_classes=5)
    plain = ViT(**kw)
    remat = ViT(**kw, remat=True)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 16, 16, 3))
    params = plain.init(jax.random.PRNGKey(1), x)["params"]

    def loss(m):
        return lambda p: jnp.sum(
            m.apply({"params": p}, x).astype(jnp.float32) ** 2)

    np.testing.assert_allclose(
        np.asarray(plain.apply({"params": params}, x)),
        np.asarray(remat.apply({"params": params}, x)),
        atol=1e-6, rtol=1e-6)
    g_plain = jax.grad(loss(plain))(params)
    g_remat = jax.grad(loss(remat))(params)
    for a, b in zip(jax.tree_util.tree_leaves(g_plain),
                    jax.tree_util.tree_leaves(g_remat)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)
    # the rematerialized backward actually carries checkpoint markers
    jaxpr = str(jax.make_jaxpr(jax.grad(loss(remat)))(params))
    assert "remat" in jaxpr or "checkpoint" in jaxpr
    assert "remat" not in str(
        jax.make_jaxpr(jax.grad(loss(plain)))(params))


def test_vit_train_step_matmuls_are_bf16():
    """VERDICT r4 item 2 (confirm bf16 end-to-end): every LARGE
    dot_general in the full train step's jaxpr — forward, backward,
    and optimizer — must take bf16 operands. An f32 matmul lowers to
    ~3x-cost multi-pass bf16 on the MXU, and one silent promotion
    anywhere in the backward erases the sweep's bf16 win; the
    shape-level activation checks above can't see the BACKWARD's
    dtypes, this jaxpr walk can."""
    import jax.numpy as jnp
    import optax

    m = ViT(patch_size=4, hidden_dim=64, depth=1, n_heads=4, mlp_dim=128,
            n_classes=5, dtype=jnp.bfloat16)
    x = jnp.zeros((2, 16, 16, 3), jnp.bfloat16)
    y = jnp.zeros((2,), jnp.int32)
    params = m.init(jax.random.PRNGKey(0), x)["params"]
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    def step(params, opt_state, xb, yb):
        def loss_fn(p):
            logits = m.apply({"params": p}, xb)
            return jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(
                    logits.astype(jnp.float32), yb))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    jaxpr = jax.make_jaxpr(step)(params, opt_state, x, y)
    big_f32 = []

    def walk(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name in ("dot_general",
                                      "conv_general_dilated"):
                avals = [v.aval for v in eqn.invars]
                # "large" = MXU-relevant: skip the tiny logits/loss
                # projections whose f32 math is deliberate
                if max(int(np.prod(a.shape)) for a in avals) >= 1 << 14 \
                        and any(a.dtype == jnp.float32 for a in avals):
                    big_f32.append([(a.dtype, a.shape) for a in avals])
            for v in eqn.params.values():
                if hasattr(v, "jaxpr"):  # ClosedJaxpr
                    walk(v.jaxpr)
                elif hasattr(v, "eqns"):
                    walk(v)
    walk(jaxpr.jaxpr)
    assert not big_f32, f"f32 matmuls in the bf16 train step: {big_f32}"


def test_vit_bf16_dtype_invariants_shape_level():
    """Fast-leg twin of test_vit_bf16_compute_keeps_f32_params (slow):
    the same bf16-activations / f32-params / f32-logits invariant via
    jax.eval_shape — no compute, so a dtype-promotion regression is
    still caught by the default test run."""
    import jax.numpy as jnp

    m = ViT(patch_size=4, hidden_dim=64, depth=1, n_heads=4, mlp_dim=128,
            n_classes=5, dtype=jnp.bfloat16)
    x = jax.ShapeDtypeStruct((2, 16, 16, 3), jnp.bfloat16)
    variables = jax.eval_shape(m.init, jax.random.PRNGKey(0), x)
    assert all(p.dtype == jnp.float32
               for p in jax.tree_util.tree_leaves(variables["params"]))
    out, state = jax.eval_shape(
        functools.partial(m.apply, capture_intermediates=True),
        {"params": variables["params"]}, x)
    block_out = state["intermediates"]["block_0"]["__call__"][0]
    assert block_out.dtype == jnp.bfloat16, block_out.dtype
    assert out.dtype == jnp.float32
