"""Continuous-batching decode engine (BASELINE.md config #5 serving).

Covers: engine output == lockstep greedy_generate on the same weights;
mid-flight admission (two requests at different depths share one
compiled step); the inference worker's decode-loop mode serving two
overlapping messages through the queue hub; and the compile-once
property of the cached greedy generate.
"""

import threading
import time

import numpy as np
import pytest

from rafiki_tpu.models.llama_lora import (Llama, LlamaLoRA,
                                          greedy_generate)
from rafiki_tpu.serving.decode_engine import DecodeEngine
from rafiki_tpu.serving.predictor import Predictor
from rafiki_tpu.serving.queues import InProcQueueHub
from rafiki_tpu.store.param_store import ParamStore
from rafiki_tpu.worker.inference import InferenceWorker

KNOBS = {"max_epochs": 1, "vocab_size": 1 << 10, "hidden_dim": 32,
         "depth": 2, "n_heads": 4, "kv_ratio": 2, "lora_rank": 4,
         "max_len": 32, "model_parallel": 1, "learning_rate": 1e-2,
         "batch_size": 8, "bf16": False, "quick_train": True,
         "share_params": False}


# the `trained` fixture lives in conftest.py (session scope): one tiny
# trained LM shared across every serving-side test file


def _module_and_params(model):
    return model._module(), model._params


def test_engine_matches_lockstep_generate(trained):
    module, params = _module_and_params(trained)
    prompts = [np.asarray([1, 5, 9, 13], np.int32),
               np.asarray([1, 7], np.int32)]
    max_new = 6

    # lockstep reference: left-aligned rows, per-example lens
    width = max(len(p) for p in prompts)
    ids = np.zeros((2, width), np.int32)
    lens = np.zeros((2,), np.int32)
    for i, p in enumerate(prompts):
        ids[i, :len(p)] = p
        lens[i] = len(p)
    ref = np.asarray(greedy_generate(module, params, ids, lens, max_new))

    eng = DecodeEngine(module, params, max_slots=4, max_len=32)
    eng.submit("a", prompts[0], max_new)
    eng.submit("b", prompts[1], max_new)
    done = {}
    for _ in range(64):
        eng.step()
        done.update(dict(eng.poll()))
        if len(done) == 2:
            break
    assert set(done) == {"a", "b"}
    np.testing.assert_array_equal(np.asarray(done["a"]), ref[0])
    np.testing.assert_array_equal(np.asarray(done["b"]), ref[1])


def test_engine_mid_flight_admission(trained):
    """A request admitted while another is mid-generation must not
    perturb it, and both must finish in one shared engine."""
    module, params = _module_and_params(trained)
    p1 = np.asarray([1, 5, 9, 13], np.int32)
    p2 = np.asarray([1, 7, 11], np.int32)
    max_new = 6

    # solo references
    def solo(p):
        e = DecodeEngine(module, params, max_slots=4, max_len=32)
        e.submit("x", p, max_new)
        while e.busy:
            e.step()
        return dict(e.poll())["x"]

    ref1, ref2 = solo(p1), solo(p2)

    # K=1 AND C=1: the test reasons about exact single-token step
    # boundaries, so chunked prefill (which ingests the whole prompt at
    # admission) must be off
    eng = DecodeEngine(module, params, max_slots=4, max_len=32,
                       steps_per_sync=1, prefill_chunk=1)
    eng.submit("r1", p1, max_new)
    # run r1 past its prefill and into generation
    for _ in range(len(p1) + 2):
        eng.step()
    assert eng.busy
    eng.submit("r2", p2, max_new)  # admitted mid-flight
    done = {}
    for _ in range(64):
        if not eng.busy:
            break
        eng.step()
        done.update(dict(eng.poll()))
    assert set(done) == {"r1", "r2"}
    assert done["r1"] == list(ref1)
    assert done["r2"] == list(ref2)
    assert eng.stats["max_concurrent"] >= 2


def test_engine_slot_reuse_no_leak(trained):
    """A slot freed by one request serves the next with identical output
    (stale cache from the previous occupant must be unreachable)."""
    module, params = _module_and_params(trained)
    p = np.asarray([1, 6, 2], np.int32)
    eng = DecodeEngine(module, params, max_slots=1, max_len=32)
    outs = []
    for rid in ("first", "second"):
        eng.submit(rid, p, 5)
        while eng.busy:
            eng.step()
        outs.append(dict(eng.poll())[rid])
    assert outs[0] == outs[1]


def test_worker_decode_loop_overlapping_messages(trained):
    """Two messages pushed back-to-back share one decode loop; each gets
    its own reply with per-query generations, and the engine saw them
    concurrently."""
    store = ParamStore.from_uri("mem://")
    store.save("t0", trained.dump_parameters())
    hub = InProcQueueHub()
    worker = InferenceWorker(LlamaLoRA, "t0", KNOBS, store, hub, "w0",
                             decode_loop=True, max_slots=4,
                             max_new_tokens=5)
    wt = threading.Thread(target=worker.run, daemon=True)
    wt.start()
    try:
        pred = Predictor(hub, ["w0"], gather_timeout=120.0)
        results = {}

        def call(name, queries):
            preds, info = pred.predict(queries)
            results[name] = (preds, info)

        t1 = threading.Thread(
            target=call, args=("m1", ["tok1 tok2 tok3", "tok4 tok5"]))
        t2 = threading.Thread(target=call, args=("m2", ["tok6 tok7"]))
        t1.start()
        t2.start()
        t1.join(timeout=180)
        t2.join(timeout=180)
        assert set(results) == {"m1", "m2"}
        m1_preds, m1_info = results["m1"]
        m2_preds, m2_info = results["m2"]
        assert m1_info["workers_answered"] == 1
        assert m2_info["workers_answered"] == 1
        assert len(m1_preds) == 2 and len(m2_preds) == 1
        assert all(isinstance(p, str) and p for p in m1_preds + m2_preds)
        # both messages' queries really were in flight together
        assert worker.engine.stats["max_concurrent"] >= 2
        assert worker.engine.stats["requests_done"] == 3
    finally:
        worker.stop()
        wt.join(timeout=10)


def test_greedy_generate_compiles_once(trained):
    """Serving-shaped repeat calls must hit the jit executable cache
    (the round-2 compile-per-request finding)."""
    module, params = _module_and_params(trained)
    ids = np.asarray([[1, 4, 7, 2]], np.int32)
    lens = np.asarray([4], np.int32)
    greedy_generate(module, params, ids, lens, 4)  # compile
    t0 = time.perf_counter()
    for _ in range(3):
        np.asarray(greedy_generate(module, params, ids, lens, 4))
    warm = (time.perf_counter() - t0) / 3
    t0 = time.perf_counter()
    np.asarray(greedy_generate(module, params, ids, lens, 4))
    single = time.perf_counter() - t0
    # a retrace of the whole scan would be >100x a cached dispatch; allow
    # wide margin for timer noise
    assert single < max(0.25, warm * 10), (single, warm)


def test_predict_batch_bucketing(trained):
    """predict() pads the batch to a power-of-two bucket and discards
    pad rows, so 3 queries return exactly 3 strings."""
    out = trained.predict(["tok1 tok2", "tok3", "tok4 tok5 tok6"])
    assert len(out) == 3
    assert all(isinstance(t, str) and t for t in out)


def test_fused_steps_match_lockstep(trained):
    """steps_per_sync=K fuses K decode steps into one device program;
    outputs must be IDENTICAL to K=1 lockstep for any K, including
    mid-scan prefill→generate transitions and mid-scan completions."""
    module, params = _module_and_params(trained)
    prompts = {"a": np.asarray([1, 5, 9, 13], np.int32),
               "b": np.asarray([1, 7], np.int32),
               "c": np.asarray([1, 2, 3, 4, 5, 6, 7], np.int32)}
    max_new = {"a": 6, "b": 3, "c": 5}

    def run(k):
        e = DecodeEngine(module, params, max_slots=4, max_len=32,
                         steps_per_sync=k)
        for rid, p in prompts.items():
            e.submit(rid, p, max_new[rid])
        out = {}
        for _ in range(200):
            if not e.busy:
                break
            e.step()
            out.update(dict(e.poll()))
        assert not e.busy
        return out

    ref = run(1)
    for k in (2, 4, 7):
        got = run(k)
        assert got == ref, (k, got, ref)
    for rid in prompts:
        assert len(ref[rid]) == max_new[rid]


def test_fused_mid_flight_admission_and_slot_reuse(trained):
    """K>1: requests admitted at fused-step boundaries into REUSED slots
    must match their solo outputs (exercises the host-side input
    reconstruction and stale-prompt-row clearing under K>1)."""
    module, params = _module_and_params(trained)
    prompts = [np.asarray([1, 5, 9, 13], np.int32),
               np.asarray([1, 7], np.int32),
               np.asarray([1, 2, 3], np.int32)]

    def solo(p):
        e = DecodeEngine(module, params, max_slots=1, max_len=32,
                         steps_per_sync=1)
        e.submit("x", p, 6)
        while e.busy:
            e.step()
        return dict(e.poll())["x"]

    refs = [solo(p) for p in prompts]

    # ONE slot, K=3: every request flows through the same reused slot,
    # later ones admitted mid-run at fused boundaries
    eng = DecodeEngine(module, params, max_slots=1, max_len=32,
                       steps_per_sync=3)
    eng.submit(0, prompts[0], 6)
    eng.step()  # first request mid-flight...
    eng.submit(1, prompts[1], 6)  # ...queued behind it
    eng.submit(2, prompts[2], 6)
    done = {}
    for _ in range(100):
        if not eng.busy:
            break
        eng.step()
        done.update(dict(eng.poll()))
    assert not eng.busy
    for i, ref in enumerate(refs):
        assert done[i] == list(ref), (i, done[i], ref)


def _run_engine(eng, reqs):
    for r in reqs:
        eng.submit(*r[:2], **r[2] if len(r) > 2 else {})
    done = {}
    for _ in range(128):
        eng.step()
        done.update(dict(eng.poll()))
        if len(done) == len(reqs):
            return done
    raise AssertionError(f"engine did not finish: {done.keys()}")


def test_chunked_prefill_matches_tokenwise(trained):
    """prefill_chunk > 1 must produce byte-identical generations to the
    token-by-token path (VERDICT r4 item: chunked-vs-tokenwise
    equivalence) — the chunk is pure KV population, same math."""
    module, params = _module_and_params(trained)
    prompts = [np.arange(1, 20, dtype=np.int32),      # 19-token prompt
               np.asarray([3, 1, 4, 1, 5], np.int32),
               np.asarray([7], np.int32)]             # no prefill at all
    reqs = [(f"r{i}", p, {"max_new": 5}) for i, p in enumerate(prompts)]

    tokenwise = _run_engine(DecodeEngine(module, params, max_slots=4,
                                         max_len=32, prefill_chunk=1),
                            reqs)
    chunked = DecodeEngine(module, params, max_slots=4, max_len=32,
                           prefill_chunk=8)
    got = _run_engine(chunked, reqs)
    for rid in tokenwise:
        np.testing.assert_array_equal(np.asarray(got[rid]),
                                      np.asarray(tokenwise[rid]))
    # the chunked engine actually took the prefill path, and paid far
    # fewer program dispatches for the 19-token prompt
    assert chunked.stats["prefill_calls"] >= 1
    assert chunked.stats["prefill_tokens"] >= 18


@pytest.mark.slow
def test_sampling_determinism_and_knobs(trained):
    """Seeded sampling is a pure function of (seed, position): identical
    across runs, across steps_per_sync, and across batch composition;
    temp<=0 is greedy; top_k=1 collapses to greedy even at high temp."""
    module, params = _module_and_params(trained)
    p = np.asarray([1, 5, 9], np.int32)
    samp = {"max_new": 6, "temperature": 0.9, "top_k": 50,
            "top_p": 0.95, "seed": 1234}

    def run(steps_per_sync, extra_reqs=()):
        eng = DecodeEngine(module, params, max_slots=4, max_len=32,
                           steps_per_sync=steps_per_sync)
        done = _run_engine(eng, [("x", p, samp), *extra_reqs])
        return np.asarray(done["x"])

    a = run(4)
    b = run(4)
    np.testing.assert_array_equal(a, b)          # same run twice
    c = run(1)
    np.testing.assert_array_equal(a, c)          # K-fusion invariant
    d = run(4, extra_reqs=[("y", np.asarray([2, 8], np.int32),
                            {"max_new": 4, "temperature": 0.7,
                             "seed": 7})])
    np.testing.assert_array_equal(a, d)          # batch-mix invariant

    # the seed must actually steer the draws: an implementation that
    # drops it would return `a` for every seed. Three other seeds, all
    # colliding with `a` over 6 sampled tokens, is vanishingly unlikely
    # at temperature 0.9 / top_k 50.
    others = []
    for seed in (4321, 77, 31337):
        done = _run_engine(
            DecodeEngine(module, params, max_slots=4, max_len=32),
            [("x", p, {**samp, "seed": seed})])
        assert len(done["x"]) == 6
        others.append(list(done["x"]))
    assert any(o != list(a) for o in others), \
        "sampling ignores the seed"

    # greedy flag and degenerate filters reduce to argmax
    greedy = _run_engine(DecodeEngine(module, params, max_slots=4,
                                      max_len=32),
                         [("x", p, {"max_new": 6})])["x"]
    k1 = _run_engine(DecodeEngine(module, params, max_slots=4,
                                  max_len=32),
                     [("x", p, {"max_new": 6, "temperature": 2.0,
                                "top_k": 1})])["x"]
    np.testing.assert_array_equal(np.asarray(k1), np.asarray(greedy))
    tiny_p = _run_engine(DecodeEngine(module, params, max_slots=4,
                                      max_len=32),
                         [("x", p, {"max_new": 6, "temperature": 2.0,
                                    "top_p": 1e-6})])["x"]
    np.testing.assert_array_equal(np.asarray(tiny_p), np.asarray(greedy))


@pytest.mark.slow
def test_sampled_tokens_respect_top_k(trained):
    """With top_k=2 every sampled token must be one of the two highest-
    probability tokens at its step (checked by replaying the model)."""
    import jax
    import jax.numpy as jnp

    module, params = _module_and_params(trained)
    p = np.asarray([1, 5, 9], np.int32)
    done = _run_engine(DecodeEngine(module, params, max_slots=2,
                                    max_len=32),
                       [("x", p, {"max_new": 5, "temperature": 1.5,
                                  "top_k": 2, "seed": 99})])
    gen = list(done["x"])
    # replay: teacher-force prompt+generated, check each sampled token
    # is in that step's top-2 logits
    ids = np.concatenate([p, np.asarray(gen[:-1], np.int32)])[None, :]
    logits = module.apply({"params": params}, jnp.asarray(ids))
    logits = np.asarray(logits[0], np.float32)  # (T, V)
    for j, tok in enumerate(gen):
        step_logits = logits[len(p) - 1 + j]
        top2 = np.argsort(step_logits)[-2:]
        assert tok in top2, (j, tok, top2)


@pytest.mark.slow
def test_sampling_flows_through_serving_stack(trained):
    """sampling={} rides the message from Predictor.predict to the
    decode loop: same seed → identical generations through the whole
    scatter/gather path, different seed → (with high probability)
    different ones."""
    store = ParamStore.from_uri("mem://")
    store.save("t0", trained.dump_parameters())
    hub = InProcQueueHub()
    worker = InferenceWorker(LlamaLoRA, "t0", KNOBS, store, hub, "w0",
                             decode_loop=True, max_slots=4,
                             max_new_tokens=8)
    wt = threading.Thread(target=worker.run, daemon=True)
    wt.start()
    try:
        pred = Predictor(hub, ["w0"], gather_timeout=120.0)
        samp = {"temperature": 0.9, "top_k": 50, "seed": 1234}
        a, info = pred.predict(["tok1 tok2 tok3"], sampling=samp)
        assert info["workers_answered"] == 1
        b, _ = pred.predict(["tok1 tok2 tok3"], sampling=samp)
        assert a == b  # seeded: reproducible across requests
        outs = {tuple(a)}
        for seed in (7, 99, 31337):
            o, _ = pred.predict(["tok1 tok2 tok3"],
                                sampling={**samp, "seed": seed})
            outs.add(tuple(o))
        assert len(outs) > 1, "seed ignored through the stack"
        # malformed sampling degrades, never kills the loop
        c, info_c = pred.predict(["tok1 tok2 tok3"],
                                 sampling={"temperature": "hot"})
        assert info_c["workers_answered"] == 1 and c
    finally:
        worker.stop()
        wt.join(timeout=10)


def test_ngram_draft():
    from rafiki_tpu.serving.decode_engine import _ngram_draft

    # suffix [7, 8] occurred earlier, followed by 9, 3 — draft those
    ctx = np.asarray([5, 7, 8, 9, 3, 1, 7, 8], np.int32)
    np.testing.assert_array_equal(_ngram_draft(ctx, 2), [9, 3])
    # continuation shorter than k pads with the last context token
    np.testing.assert_array_equal(_ngram_draft(ctx, 5), [9, 3, 1, 7, 8])
    # no n-gram recurrence -> repeat-last fallback
    np.testing.assert_array_equal(
        _ngram_draft(np.asarray([4, 6, 2], np.int32), 3), [2, 2, 2])
    # degenerate single-token context
    np.testing.assert_array_equal(
        _ngram_draft(np.asarray([9], np.int32), 2), [9, 9])


def test_speculative_engine_matches_plain_greedy(trained):
    """Speculation must be lossless: identical tokens to the plain
    engine whether drafts hit (repetitive prompts) or miss (arbitrary
    prompts), across mid-flight admission and slot reuse."""
    module, params = _module_and_params(trained)
    prompts = [
        np.asarray([1, 5, 9, 13], np.int32),              # arbitrary
        np.asarray([1, 7, 2, 7, 2, 7, 2], np.int32),      # repetitive
        np.asarray([1, 3], np.int32),
    ]
    max_new = 10

    def run(spec_k):
        eng = DecodeEngine(module, params, max_slots=2, max_len=32,
                           speculate_k=spec_k)
        for i, p in enumerate(prompts):   # 3 requests, 2 slots: reuse
            eng.submit(i, p, max_new)
        done = {}
        for _ in range(200):
            eng.step()
            done.update(dict(eng.poll()))
            if len(done) == len(prompts):
                return done, eng.stats
        raise AssertionError(f"undrained: {sorted(done)}")

    plain, _ = run(0)
    spec, stats = run(4)
    assert stats["spec_calls"] > 0
    assert stats["spec_drafted"] > 0
    for i in range(len(prompts)):
        np.testing.assert_array_equal(np.asarray(spec[i]),
                                      np.asarray(plain[i]))
    # the model was trained on repetitive synthetic text: at least one
    # draft must have been accepted across these runs (the speedup
    # exists), and acceptances never exceed drafts
    assert 0 <= stats["spec_accepted"] <= stats["spec_drafted"]


def test_speculative_engine_sampling_falls_back(trained):
    """A sampling request in the batch must force the exact sampler
    path — outputs identical to a non-speculative engine under the same
    seeds."""
    module, params = _module_and_params(trained)
    p = np.asarray([1, 5, 9], np.int32)

    def run(spec_k):
        eng = DecodeEngine(module, params, max_slots=2, max_len=32,
                           speculate_k=spec_k)
        eng.submit("g", p, 6)  # greedy
        eng.submit("s", p, 6, temperature=0.8, top_k=5, seed=7)
        done = {}
        for _ in range(100):
            eng.step()
            done.update(dict(eng.poll()))
            if len(done) == 2:
                return done, eng.stats
        raise AssertionError("undrained")

    plain, _ = run(0)
    spec, stats = run(4)
    np.testing.assert_array_equal(np.asarray(spec["g"]),
                                  np.asarray(plain["g"]))
    np.testing.assert_array_equal(np.asarray(spec["s"]),
                                  np.asarray(plain["s"]))
    assert stats["spec_calls"] == 0  # sampling present -> scan path


def test_speculation_gates_off_at_low_acceptance(trained):
    """When drafts rarely hit, the EMA gate must return traffic to the
    amortized scan (and re-probe later) rather than paying one dispatch
    per token forever."""
    from rafiki_tpu.serving import decode_engine as de

    module, params = _module_and_params(trained)
    eng = DecodeEngine(module, params, max_slots=2, max_len=32,
                       speculate_k=4)
    # force the worst case: pretend every verify call emitted 1 token
    eng._spec_ema = 1.0
    eng.submit("x", np.asarray([1, 5, 9], np.int32), 8)
    eng.step()
    calls_before = eng.stats["spec_calls"]
    for _ in range(4):
        eng.step()
    # gated: the scan path served these calls
    assert eng.stats["spec_calls"] == calls_before
    assert eng._spec_idle > 0
    # re-probe fires once the idle budget is spent
    eng._spec_idle = de.SPEC_REPROBE_CALLS
    eng.submit("y", np.asarray([1, 7, 2, 7, 2], np.int32), 8)
    drained = 0
    for _ in range(50):
        eng.step()
        drained += len(eng.poll())
        if drained >= 2 and eng.stats["spec_calls"] > calls_before:
            break
    assert eng.stats["spec_calls"] > calls_before


def test_prefix_cache_matches_plain(trained):
    """A registered shared prefix must not change a single output token
    — for prompts that extend it (KV-copy path), equal it, miss it, or
    are shorter than it — while skipping the prefix's prefill."""
    module, params = _module_and_params(trained)
    prefix = np.asarray([1, 5, 9, 13, 2], np.int32)
    prompts = {
        "hit": np.concatenate([prefix, [7, 4]]).astype(np.int32),
        "hit2": np.concatenate([prefix, [3]]).astype(np.int32),
        "exact": prefix.copy(),                  # not strictly longer
        "miss": np.asarray([2, 5, 9, 13, 2, 7], np.int32),
        "short": np.asarray([1, 5], np.int32),
    }

    def run(register):
        eng = DecodeEngine(module, params, max_slots=3, max_len=32)
        if register:
            assert eng.register_prefix(prefix) == len(prefix)
        for name, p in prompts.items():
            eng.submit(name, p, 6)
        done = {}
        for _ in range(200):
            eng.step()
            done.update(dict(eng.poll()))
            if len(done) == len(prompts):
                return done, eng.stats
        raise AssertionError(f"undrained: {sorted(done)}")

    plain, _ = run(False)
    cached, stats = run(True)
    for name in prompts:
        np.testing.assert_array_equal(np.asarray(cached[name]),
                                      np.asarray(plain[name]), name)
    assert stats["prefix_hits"] == 2          # hit + hit2 only
    assert stats["prefix_tokens"] == 2 * len(prefix)


def test_prefix_cache_with_tokenwise_prefill(trained):
    """Prefix install must compose with prefill_chunk=1 (the remaining
    prompt streams through the decode scan from the prefix boundary)."""
    module, params = _module_and_params(trained)
    prefix = np.asarray([1, 7, 2, 9], np.int32)
    prompt = np.concatenate([prefix, [5, 3]]).astype(np.int32)

    def run(register):
        eng = DecodeEngine(module, params, max_slots=2, max_len=32,
                           prefill_chunk=1)
        if register:
            eng.register_prefix(prefix)
        eng.submit("x", prompt, 5)
        done = {}
        for _ in range(100):
            eng.step()
            done.update(dict(eng.poll()))
            if done:
                return done["x"]
        raise AssertionError("undrained")

    np.testing.assert_array_equal(run(True), run(False))


def test_system_prefix_through_template(trained):
    """make_decode_engine(system_prefix=...) registers the prefix and
    serving text that starts with it produces identical completions."""
    plain = trained.make_decode_engine(max_slots=2, max_new_tokens=6)
    sys_text = "tok1 tok5"
    pref = trained.make_decode_engine(max_slots=2, max_new_tokens=6,
                                      system_prefix=sys_text)
    query = sys_text + " tok9 tok13"
    outs = {}
    for name, eng in (("plain", plain), ("pref", pref)):
        eng.submit("q", query)
        done = {}
        for _ in range(100):
            eng.step()
            done.update(dict(eng.poll()))
            if done:
                break
        outs[name] = done["q"]
    assert outs["plain"] == outs["pref"]
    assert pref.engine.stats["prefix_hits"] == 1


def test_eos_early_stop(trained):
    """An emitted eos_id must end the request early — EOS dropped from
    the reply, later fused-call tokens discarded — on both the scan and
    speculative paths, and the freed slot must serve a new request."""
    module, params = _module_and_params(trained)
    p = np.asarray([1, 5, 9, 13], np.int32)

    # discover the plain greedy stream, pick its 3rd token as "EOS"
    ref_eng = DecodeEngine(module, params, max_slots=2, max_len=32)
    ref_eng.submit("ref", p, 10)
    done = {}
    while not done:
        ref_eng.step()
        done.update(dict(ref_eng.poll()))
    ref = done["ref"]
    assert len(ref) == 10
    eos = ref[2]

    for spec_k in (0, 4):
        eng = DecodeEngine(module, params, max_slots=2, max_len=32,
                           speculate_k=spec_k)
        eng.submit("a", p, 10, eos_id=eos)
        done = {}
        for _ in range(60):
            eng.step()
            done.update(dict(eng.poll()))
            if done:
                break
        got = done["a"]
        # everything before the first EOS occurrence, EOS excluded
        assert got == ref[:ref.index(eos)], (spec_k, got, ref)
        # the freed slot still serves: a follow-up without eos matches
        eng.submit("b", p, 10)
        done = {}
        for _ in range(60):
            eng.step()
            done.update(dict(eng.poll()))
            if done:
                break
        assert done["b"] == ref, spec_k
