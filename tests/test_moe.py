"""Mixture-of-Experts (ops/moe.py): routing correctness vs a per-token
oracle, capacity drops, aux loss, expert-parallel sharding on the
8-device mesh, and the Llama integration."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from rafiki_tpu.ops.moe import (MoEFeedForward, moe_aux_loss,
                                router_dispatch)


def test_router_dispatch_oracle():
    """With capacity ≥ tokens-per-expert, every token lands in its
    argmax expert's next free slot with its router prob as weight."""
    logits = jnp.asarray([[2.0, 0.0, 0.0],
                          [0.0, 3.0, 0.0],
                          [1.5, 0.0, 0.0],
                          [0.0, 0.0, 4.0]], jnp.float32)
    dispatch, combine, aux = router_dispatch(logits, capacity=2)
    probs = np.asarray(jax.nn.softmax(logits, -1))
    d = np.asarray(dispatch)
    c = np.asarray(combine)
    # token 0 → expert 0 slot 0; token 2 → expert 0 slot 1
    assert d[0, 0, 0] == 1 and d[2, 0, 1] == 1
    assert d[1, 1, 0] == 1 and d[3, 2, 0] == 1
    assert d.sum() == 4  # every token placed exactly once
    np.testing.assert_allclose(c[0, 0, 0], probs[0, 0], rtol=1e-6)
    np.testing.assert_allclose(c[3, 2, 0], probs[3, 2], rtol=1e-6)
    assert float(aux) > 0


def test_capacity_overflow_drops_later_tokens():
    # 3 tokens all pick expert 0; capacity 2 → third token dropped
    logits = jnp.asarray([[5.0, 0.0]] * 3, jnp.float32)
    dispatch, combine, _ = router_dispatch(logits, capacity=2)
    d = np.asarray(dispatch)
    assert d[0].sum() == 1 and d[1].sum() == 1
    assert d[2].sum() == 0  # overflow: dropped (passes via residual)
    assert np.asarray(combine)[2].sum() == 0


def test_aux_loss_minimal_at_uniform_routing():
    t, e = 64, 4
    uniform = jnp.zeros((t, e), jnp.float32)
    skewed = jnp.concatenate(
        [jnp.full((t, 1), 4.0), jnp.zeros((t, e - 1))], axis=-1)
    _, _, aux_u = router_dispatch(uniform, capacity=t)
    _, _, aux_s = router_dispatch(skewed, capacity=t)
    np.testing.assert_allclose(float(aux_u), 1.0, rtol=1e-5)
    assert float(aux_s) > 2.0  # concentration is penalized


def test_moe_matches_manual_expert_compute():
    """Full-capacity MoE output == manually routing each token through
    its argmax expert's SwiGLU, scaled by its router prob."""
    m = MoEFeedForward(n_experts=3, mlp_dim=16, capacity_factor=3.0)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 8))
    variables = m.init(jax.random.PRNGKey(1), x)
    params = variables["params"]
    y, muts = m.apply({"params": params}, x, mutable=["losses"])
    assert y.shape == x.shape
    assert float(moe_aux_loss(muts)) > 0

    xf = np.asarray(x, np.float32).reshape(-1, 8)
    logits = xf @ np.asarray(params["router"], np.float32)
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), -1))
    want = np.zeros_like(xf)
    for t in range(xf.shape[0]):
        e_idx = int(np.argmax(logits[t]))
        g = xf[t] @ np.asarray(params["experts_gate"][e_idx])
        u = xf[t] @ np.asarray(params["experts_up"][e_idx])
        silu = g / (1 + np.exp(-g)) * u
        want[t] = probs[t, e_idx] * (
            silu @ np.asarray(params["experts_down"][e_idx]))
    np.testing.assert_allclose(np.asarray(y).reshape(-1, 8), want,
                               atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_moe_grads_flow_to_router_and_experts():
    m = MoEFeedForward(n_experts=2, mlp_dim=8, capacity_factor=2.0)
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 8, 4))
    params = m.init(jax.random.PRNGKey(3), x)["params"]

    def loss(p):
        y, muts = m.apply({"params": p}, x, mutable=["losses"])
        return (jnp.sum(y.astype(jnp.float32) ** 2)
                + 0.01 * moe_aux_loss(muts))

    g = jax.grad(loss)(params)
    for name in ("router", "experts_gate", "experts_up", "experts_down"):
        total = float(np.abs(np.asarray(g[name])).sum())
        assert np.isfinite(total) and total > 0, name


@pytest.mark.slow
def test_expert_parallel_sharding_matches_single_device():
    """Experts sharded over the model axis (TP_RULES 'experts' rule):
    same outputs as replicated execution, expert dim actually split."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from rafiki_tpu.models.llama_lora import TP_RULES
    from rafiki_tpu.parallel.sharding import param_shardings

    m = MoEFeedForward(n_experts=4, mlp_dim=16, capacity_factor=2.0)
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 8, 8))
    params = m.init(jax.random.PRNGKey(5), x)["params"]
    ref = m.apply({"params": params}, x)

    devs = np.array(jax.devices()[:8], dtype=object).reshape(2, 4)
    mesh = Mesh(devs, ("data", "model"))
    shardings = param_shardings(params, mesh, tp_rules=TP_RULES,
                                fsdp=False)
    flat = jax.tree_util.tree_flatten_with_path(shardings)[0]
    by = {"/".join(str(getattr(k, "key", k)) for k in kp): v
          for kp, v in flat}
    spec = tuple(by["experts_gate"].spec)
    assert spec and spec[0] == "model" and \
        all(s is None for s in spec[1:]), spec  # EXPERT dim sharded
    sharded = jax.tree_util.tree_map(jax.device_put, params, shardings)
    xb = jax.device_put(x, NamedSharding(mesh, P("data")))
    with mesh:
        out = jax.jit(lambda p, x: m.apply({"params": p}, x))(sharded, xb)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_moe_llama_trains_and_generates(tmp_path):
    """Config-#5 MoE variant: the template trains with the aux loss in
    the objective (loss decreases) and serves through the same decode
    path (sow is a no-op outside mutable losses)."""
    from rafiki_tpu.data import generate_text_classification_dataset
    from rafiki_tpu.model import TrainContext
    from rafiki_tpu.models.llama_lora import LlamaLoRA

    tr = str(tmp_path / "t.jsonl")
    generate_text_classification_dataset(tr, 96, seed=0)
    knobs = {"max_epochs": 3, "vocab_size": 1 << 10, "hidden_dim": 32,
             "depth": 2, "n_heads": 4, "kv_ratio": 2, "lora_rank": 4,
             "max_len": 32, "model_parallel": 2, "learning_rate": 1e-2,
             "batch_size": 8, "bf16": False, "remat": False,
             "moe_experts": 4, "quick_train": False,
             "share_params": False, "tokenizer_path": "",
             "pretrained_path": ""}
    model = LlamaLoRA(**knobs)
    ctx = TrainContext(devices=list(jax.devices()))
    model.train(tr, ctx)
    losses = ctx.logger.get_values("loss")
    assert len(losses) >= 2 and losses[-1] < losses[0]
    out = model.predict(["tok1 tok2"])
    assert isinstance(out[0], str) and out[0]


def test_moe_params_are_trainable_and_import_safe():
    """The LoRA freeze mask must NOT freeze MoE routers/experts (no
    pretrained base exists for them), and a dense HF checkpoint import
    leaves them at init instead of erroring."""
    from rafiki_tpu.models.convert import hf_name_for
    from rafiki_tpu.models.llama_lora import Llama, lora_trainable_mask

    m = Llama(vocab_size=128, max_len=16, hidden_dim=32, depth=1,
              n_heads=4, n_kv_heads=2, mlp_dim=64, lora_rank=2,
              n_experts=2)
    params = m.init(jax.random.PRNGKey(0),
                    np.ones((1, 8), np.int32))["params"]
    mask = lora_trainable_mask(params)
    flat = jax.tree_util.tree_flatten_with_path(mask)[0]
    moe_flags = {"/".join(str(getattr(k, "key", k)) for k in kp): v
                 for kp, v in flat if "moe" in str(kp)}
    assert moe_flags and all(moe_flags.values()), moe_flags
    # importer: MoE paths have no HF counterpart → keep-init, not raise
    assert hf_name_for(("block_0", "moe", "router")) is None
    assert hf_name_for(("block_0", "moe", "experts_gate")) is None


def test_moe_expert_count_must_divide_model_axis(tmp_path):
    from rafiki_tpu.data import generate_text_classification_dataset
    from rafiki_tpu.model import TrainContext
    from rafiki_tpu.models.llama_lora import LlamaLoRA

    tr = str(tmp_path / "t.jsonl")
    generate_text_classification_dataset(tr, 16, seed=0)
    knobs = {"max_epochs": 1, "vocab_size": 1 << 9, "hidden_dim": 32,
             "depth": 1, "n_heads": 4, "kv_ratio": 2, "lora_rank": 2,
             "max_len": 32, "model_parallel": 2, "learning_rate": 1e-2,
             "batch_size": 8, "bf16": False, "remat": False,
             "moe_experts": 3, "quick_train": True,
             "share_params": False, "tokenizer_path": "",
             "pretrained_path": ""}
    with pytest.raises(ValueError, match="divisible"):
        LlamaLoRA(**knobs).train(
            tr, TrainContext(devices=list(jax.devices())))


def test_top2_routing_matches_manual():
    """top_k=2: each token's output is the gate-weighted sum of its two
    best experts' SwiGLU outputs (gates renormalized over the pair)."""
    m = MoEFeedForward(n_experts=4, mlp_dim=8, capacity_factor=4.0,
                       router_top_k=2)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 8))
    params = m.init(jax.random.PRNGKey(1), x)["params"]
    y, _ = m.apply({"params": params}, x, mutable=["losses"])

    xf = np.asarray(x, np.float32).reshape(-1, 8)
    logits = xf @ np.asarray(params["router"], np.float32)
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), -1))
    want = np.zeros_like(xf)
    for t in range(xf.shape[0]):
        top2 = np.argsort(probs[t])[-2:][::-1]
        gsum = probs[t, top2].sum()
        for e_idx in top2:
            g = xf[t] @ np.asarray(params["experts_gate"][e_idx])
            u = xf[t] @ np.asarray(params["experts_up"][e_idx])
            silu = g / (1 + np.exp(-g)) * u
            want[t] += (probs[t, e_idx] / gsum) * (
                silu @ np.asarray(params["experts_down"][e_idx]))
    np.testing.assert_allclose(np.asarray(y).reshape(-1, 8), want,
                               atol=1e-4, rtol=1e-4)


def test_top2_overflow_drops_second_choice_first():
    """First choices fill capacity before any second choice gets a
    slot (priority order), and gates still sum ≤ 1 per token."""
    # 3 tokens, 2 experts: everyone's 1st choice = expert 0, 2nd = e1
    logits = jnp.asarray([[5.0, 2.0]] * 3, jnp.float32)
    dispatch, combine, _ = router_dispatch(logits, capacity=2, top_k=2)
    d = np.asarray(dispatch)
    # expert 0: tokens 0,1 keep their FIRST choice; token 2's dropped
    assert d[0, 0].sum() == 1 and d[1, 0].sum() == 1
    assert d[2, 0].sum() == 0
    # expert 1 (capacity 2 as well): first two second-choices land,
    # token 2 is dropped from BOTH experts
    assert d[0, 1].sum() == 1 and d[1, 1].sum() == 1
    assert d[2].sum() == 0
    c = np.asarray(combine)
    token_gates = c.sum(axis=(1, 2))
    assert (token_gates <= 1.0 + 1e-6).all()


@pytest.mark.slow
def test_llama_moe_top_k_plumbed():
    """The moe_top_k field reaches MoEFeedForward (top-2 capacity is
    larger, param shapes identical, forward runs)."""
    from rafiki_tpu.models.llama_lora import Llama

    m = Llama(vocab_size=64, max_len=16, hidden_dim=32, depth=1,
              n_heads=4, n_kv_heads=2, mlp_dim=64, lora_rank=0,
              n_experts=2, moe_top_k=2)
    ids = jnp.ones((2, 8), jnp.int32)
    params = m.init(jax.random.PRNGKey(0), ids)["params"]
    out, muts = m.apply({"params": params}, ids, mutable=["losses"])
    assert out.shape == (2, 8, 64)
    assert float(moe_aux_loss(muts)) > 0


def test_moe_layer_grads_flow_fast():
    """Fast-leg twin of test_moe_grads_flow_to_router_and_experts
    (slow): nonzero router + expert gradients at LAYER level — cheap
    enough for the default run."""
    moe = MoEFeedForward(n_experts=2, mlp_dim=16, capacity_factor=2.0)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 8))
    variables = moe.init(jax.random.PRNGKey(1), x)

    def loss(params):
        out, muts = moe.apply({"params": params}, x, mutable=["losses"])
        return jnp.sum(out ** 2) + moe_aux_loss(muts)

    g = jax.grad(loss)(variables["params"])
    leaves = {"/".join(str(getattr(k, "key", k)) for k in kp):
              np.abs(np.asarray(v)).max()
              for kp, v in jax.tree_util.tree_flatten_with_path(g)[0]}
    router = [v for n, v in leaves.items() if "router" in n]
    experts = [v for n, v in leaves.items() if "experts" in n]
    assert router and max(router) > 0
    assert experts and max(experts) > 0


@pytest.mark.slow
def test_moe_sp_tp_forward_parity():
    """MoE under the 3-axis dp x sp x tp mesh (experts over `model`,
    activation L over `sp`, ulysses attention in the head group):
    logits equal the plain single-device module — the exactness basis
    for relaxing the MoE x sequence_parallel exclusion."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from rafiki_tpu.models.llama_lora import TP_RULES, Llama
    from rafiki_tpu.parallel.sharding import param_shardings

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                ("data", "sp", "model"))
    kw = dict(vocab_size=256, max_len=32, hidden_dim=32, depth=2,
              n_heads=4, n_kv_heads=2, mlp_dim=64, lora_rank=4,
              n_experts=2)
    m_sp = Llama(**kw, seq_mesh=mesh, seq_axis="sp", head_axis="model")
    m_plain = Llama(**kw)
    ids = np.random.RandomState(0).randint(
        1, 200, size=(4, 32)).astype(np.int32)
    params = m_plain.init(jax.random.PRNGKey(0),
                          jnp.asarray(ids))["params"]
    shardings = param_shardings(params, mesh, tp_rules=TP_RULES,
                                fsdp=True, min_size=0)
    params_s = jax.tree_util.tree_map(jax.device_put, params, shardings)
    ids_s = jax.device_put(jnp.asarray(ids),
                           NamedSharding(mesh, P("data", "sp")))
    with mesh:
        ref, _ = m_plain.apply({"params": params}, jnp.asarray(ids),
                               mutable=["losses"])
        got, _ = m_sp.apply({"params": params_s}, ids_s,
                            mutable=["losses"])
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(ref, np.float32),
                               atol=3e-4, rtol=3e-4)
