"""Reconnecting hub clients + supervised kvd respawn: the integration
half of the crash-survivable data plane.

Covers the client reconnect layer (transparent idempotent retry, BRPOP
resumption, non-retryable verbs), the seeded per-RPC connection-drop
storm over every hub verb (no double-delivery — dedup ids — and no
lost durable blob), the predictor's structured data-plane-down 503,
the worker's serve-loop pause, and THE acceptance drill: kill -9 the
kvd mid-stream under mixed serve+train load, watch the admin respawn
it with WAL replay, and prove the stream completes token-exact with
zero lost durable state (docs/operations.md "Data-plane death &
recovery").
"""

import os
import signal
import socket
import threading
import time

import pytest

from rafiki_tpu.chaos import (ChaosConfig, ChaosHub, ChaosInjector,
                              arm_kvd_kill)
from rafiki_tpu.native.client import (CLIENT_STATS, KVClient, KVServer,
                                      ensure_built)
from rafiki_tpu.serving.queues import KVQueueHub, pack_message, \
    unpack_message


@pytest.fixture(scope="module", autouse=True)
def _built():
    ensure_built()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _kill9(server):
    os.kill(server._proc.pid, signal.SIGKILL)
    server._proc.wait()


# ----------------------------------------------- client reconnect layer

def test_retryable_verbs_survive_server_restart(tmp_path):
    s = KVServer(data_dir=str(tmp_path / "dd"))
    port = s.port
    c = KVClient(s.host, port, retry_window_s=10.0)
    c.set("k", b"v")
    before = CLIENT_STATS.snapshot()
    _kill9(s)
    s2 = KVServer(port=port, data_dir=str(tmp_path / "dd"))
    # transparent retry across the respawn: reads AND idempotent writes
    assert c.get("k") == b"v"
    c.set("k2", b"v2")
    assert c.exists("k2")
    assert c.lpush_dedup("q", "i1", b"m") == 1
    after = CLIENT_STATS.snapshot()
    assert after["hub_reconnects_total"] > before["hub_reconnects_total"]
    assert after["hub_rpc_retries_total"] > before["hub_rpc_retries_total"]
    s2.stop()


def test_brpop_resumes_on_new_socket(tmp_path):
    s = KVServer(data_dir=str(tmp_path / "dd"))
    port = s.port
    popper = KVClient(s.host, port, retry_window_s=10.0)
    got = {}

    def blocked_pop():
        got["v"] = popper.brpop("bq", 20.0)

    t = threading.Thread(target=blocked_pop, daemon=True)
    t.start()
    time.sleep(0.3)  # the BRPOP is in flight
    _kill9(s)
    time.sleep(0.3)
    s2 = KVServer(port=port, data_dir=str(tmp_path / "dd"))
    KVClient(s2.host, port).lpush("bq", b"resumed")
    t.join(timeout=15)
    assert got["v"] == ("bq", b"resumed")
    s2.stop()


def test_nonidempotent_verbs_do_not_retry(tmp_path):
    """INCR and plain LPUSH/RPUSH have no idempotent replay story —
    a dropped-ack retry could double them — so the reconnect layer
    refuses and surfaces ConnectionError (callers must use the dedup
    pushes)."""
    s = KVServer(data_dir=str(tmp_path / "dd"))
    c = KVClient(s.host, s.port, retry_window_s=5.0)
    c.incr("ctr")
    _kill9(s)
    with pytest.raises(ConnectionError):
        c.incr("ctr")
    with pytest.raises(ConnectionError):
        c.lpush("q", b"m")


def test_no_retry_window_keeps_old_contract(tmp_path):
    s = KVServer(data_dir=str(tmp_path / "dd"))
    c = KVClient(s.host, s.port)  # retry_window_s=0: legacy behavior
    _kill9(s)
    with pytest.raises(ConnectionError):
        c.get("k")


# ------------------------------------- seeded connection-drop storm

def test_conn_drop_storm_every_verb_no_double_delivery(tmp_path):
    """drop_hub_conn_p=0.3 force-closes the hub's socket before ~30%
    of RPCs: every verb must come back through reconnect + idempotent
    replay with NOTHING lost and NOTHING double-delivered (queue
    pushes are dedup-id'd; blobs/stats/pools overwrite)."""
    s = KVServer(data_dir=str(tmp_path / "dd"))
    hub = KVQueueHub(s.host, s.port, retry_window_s=10.0)
    injector = ChaosInjector(ChaosConfig(drop_hub_conn_p=0.3, seed=7))
    chub = ChaosHub(hub, injector)

    n = 120
    for i in range(n):
        chub.push_query("w0", b"q%d" % i)
    assert chub.query_depth("w0") == n
    popped = [chub.pop_query("w0", 1.0) for _ in range(n)]
    assert popped == [b"q%d" % i for i in range(n)]  # exactly once,
    #                                                   in order
    assert chub.pop_query("w0", 0.0) is None

    for i in range(40):
        chub.push_prediction("qid1", b"p%d" % i)
        chub.push_kv("w0", b"kv%d" % i)
    preds = [chub.pop_prediction("qid1", 1.0) for _ in range(40)]
    ships = [chub.pop_kv("w0", 1.0) for _ in range(40)]
    assert preds == [b"p%d" % i for i in range(40)]
    assert ships == [b"kv%d" % i for i in range(40)]

    blob = bytes(range(256)) * 64
    chub.put_blob("prefix:pool:0", blob)
    assert chub.get_blob("prefix:pool:0") == blob  # no lost/torn blob
    chub.put_worker_stats("w0", {"uptime_s": 1.5, "queued": 3})
    st = chub.get_worker_stats("w0")
    assert st and st["queued"] == 3
    chub.put_pool_members("pool", {"workers": ["w0"], "version": 2})
    assert chub.get_pool_members("pool")["workers"] == ["w0"]
    for i in range(20):  # the depth/discard/TTL verbs ride the storm
        assert chub.kv_depth("w0") == 0  # too (LLEN/DEL/EXPIRE)
        assert chub.query_depth("w0") == 0
        chub.arm_reply_ttl(f"qid-{i}", 30.0)
        chub.discard_prediction_queue(f"qid-{i}")

    assert injector.counters["hub_conn_drops"] > 10  # the storm fired
    s.stop()


def test_chaos_config_parses_new_knobs():
    cfg = ChaosConfig.parse("kill_kvd_after_s=1.5,drop_hub_conn_p=0.2,"
                            "seed=3")
    assert cfg.kill_kvd_after_s == 1.5
    assert cfg.drop_hub_conn_p == 0.2
    assert cfg.armed
    assert arm_kvd_kill(ChaosConfig(), lambda: 0) is None  # off = None


# ------------------------------------------- predictor fast-fail 503

def test_predictor_data_plane_down_structured_503():
    from rafiki_tpu.serving.predictor import Predictor, PredictorService

    hub = KVQueueHub("127.0.0.1", _free_port(), retry_window_s=0.3)
    p = Predictor(hub, ["w0"], gather_timeout=5.0)
    preds, info = p.predict(["hello"])
    assert preds == []
    assert info["data_plane_down"] and info["fast_fail"]
    assert info["retry_after_s"] > 0
    assert p.data_plane_health()["down"]

    svc = PredictorService(p, "127.0.0.1", 0)
    code, body = svc._predict({}, {"queries": ["hi"]}, {})
    assert code == 503
    assert body["data_plane_down"] and body["retry_after_s"] > 0

    # streams end with a RESUMABLE terminal event (client auto-resume)
    evs = list(p.predict_stream(["hello"], timeout=5.0))
    last = evs[-1]
    assert last["done"] and last["resumable"] and \
        last["data_plane_down"]
    assert "partial" in last and last["retry_after_s"] > 0


def test_down_gate_fast_fails_without_reconnect_stall(tmp_path):
    """Once the plane is KNOWN down, subsequent requests must 503
    instantly via the liveness-probe gate instead of each re-stalling
    in the client's reconnect window."""
    from rafiki_tpu.serving.predictor import Predictor

    s = KVServer(data_dir=str(tmp_path / "dd"))
    hub = KVQueueHub(s.host, s.port, retry_window_s=5.0)
    p = Predictor(hub, ["w0"], gather_timeout=5.0,
                  adaptive_gather=False)
    p.predict(["x"], timeout=0.2)  # establish the thread-local client
    _kill9(s)
    _, info = p.predict(["x"], timeout=0.2)  # pays the bounded window
    assert info["data_plane_down"]
    t0 = time.monotonic()
    _, info2 = p.predict(["x"], timeout=0.2)
    dt = time.monotonic() - t0
    assert info2["data_plane_down"]
    assert dt < 1.0, f"gated request stalled {dt:.2f}s"


def test_predictor_clears_down_flag_when_plane_returns(tmp_path):
    from rafiki_tpu.serving.predictor import Predictor

    port = _free_port()
    hub = KVQueueHub("127.0.0.1", port, retry_window_s=0.3)
    p = Predictor(hub, ["w0"], gather_timeout=0.5,
                  adaptive_gather=False)
    _, info = p.predict(["x"], timeout=0.3)
    assert info["data_plane_down"]
    s = KVServer(port=port, data_dir=str(tmp_path / "dd"))
    _, info = p.predict(["x"], timeout=0.3)
    # no worker answers, but the gather REACHED the kvd: a plain
    # timeout, not a data-plane verdict — and the flag clears
    assert "data_plane_down" not in info
    assert not p.data_plane_health()["down"]
    s.stop()


# --------------------------------- THE acceptance drill (kill -9 kvd)

def test_kvd_kill9_mid_stream_token_exact_zero_loss(trained, tmp_path):
    """Kill -9 the kvd mid-stream under mixed serve+train load. The
    admin's monitor respawns it ON THE SAME PORT with WAL replay; the
    worker's and predictor's reconnecting clients ride it out (dedup
    ids keep retried deltas single-delivery); the stream completes
    token-exact vs a no-fault reference; every durable blob written
    before and during the outage survives; the doctor's audit comes
    back clean."""
    from test_decode_engine import KNOBS

    from rafiki_tpu.admin.doctor import audit_workdir
    from rafiki_tpu.admin.services_manager import ServicesManager
    from rafiki_tpu.models.llama_lora import LlamaLoRA
    from rafiki_tpu.parallel.mesh import DeviceSpec
    from rafiki_tpu.serving.predictor import Predictor
    from rafiki_tpu.store.meta_store import MetaStore
    from rafiki_tpu.store.param_store import ParamStore
    from rafiki_tpu.worker.inference import InferenceWorker

    store = ParamStore.from_uri("mem://")
    store.save("t0", trained.dump_parameters())
    prompt = "tok1 tok2 tok3"
    max_new = 16

    def boot_worker(hub, delay_s=0.0):
        if delay_s:
            # pace reply pushes so the 16-token stream SPANS the kvd's
            # death + respawn + replay (~0.5s) — timing only, never
            # content
            hub = ChaosHub(hub, ChaosInjector(
                ChaosConfig(delay_queue_s=delay_s)))
        w = InferenceWorker(LlamaLoRA, "t0", KNOBS, store, hub, "w0",
                            decode_loop=True, max_slots=4,
                            max_new_tokens=max_new, steps_per_sync=1)
        th = threading.Thread(target=w.run, daemon=True)
        th.start()
        return w, th

    def collect(pred, out):
        for ev in pred.predict_stream([prompt], timeout=120.0):
            out.append((time.monotonic(), ev))

    meta = MetaStore(str(tmp_path / "meta.db"))
    mgr = ServicesManager(meta, str(tmp_path / "wd"), slot_size=1,
                          platform="cpu", devices=[DeviceSpec(id=0)])
    mgr.start_data_plane()
    port = mgr.kv_port
    kv_pid = mgr._kv_proc.pid

    # no-fault reference over the SAME kvd (deterministic greedy)
    hub = KVQueueHub(mgr.kv_host, port)
    w, th = boot_worker(hub)
    ref: list = []
    collect(Predictor(hub, ["w0"], gather_timeout=120.0), ref)
    expected = ref[-1][1]["predictions"]
    assert expected and expected[0]
    w.stop()
    th.join(timeout=30)

    # train-side load: durable blobs written continuously through the
    # outage via the ParamStore's kv backend (its own reconnect window)
    blob_store = ParamStore.from_uri(f"kv://{mgr.kv_host}:{port}")
    blobs_written: dict = {}
    stop_blobs = threading.Event()

    def blob_load():
        i = 0
        while not stop_blobs.is_set():
            key = f"drill-{i}"
            val = {"w": float(i), "tag": "x" * 64}
            blob_store.save(key, val)
            blobs_written[key] = val
            i += 1
            time.sleep(0.05)

    blobber = threading.Thread(target=blob_load, daemon=True)
    blobber.start()

    # live run: stream in flight when the data plane dies
    hub = KVQueueHub(mgr.kv_host, port)
    w, th = boot_worker(hub, delay_s=0.25)
    events: list = []
    t = threading.Thread(
        target=collect,
        args=(Predictor(hub, ["w0"], gather_timeout=120.0), events),
        daemon=True)
    t.start()
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and len(events) < 2:
        time.sleep(0.01)
    assert len(events) >= 2, "stream never started"

    # the chaos kill timer is the trigger (counts chaos_kvd_kills)
    injector = ChaosInjector(ChaosConfig(kill_kvd_after_s=0.05))
    arm_kvd_kill(ChaosConfig(kill_kvd_after_s=0.05),
                 lambda: mgr._kv_proc.pid, injector=injector)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and \
            mgr._kv_proc.poll() is None:
        time.sleep(0.01)
    assert mgr._kv_proc.poll() is not None, "chaos kill never fired"
    assert injector.counters["kvd_kills"] == 1
    n_at_kill = len(events)

    # the admin's monitor tick is the supervisor: respawn-with-replay
    mgr.poll()
    assert mgr.kv_port == port  # SAME address: clients reconnect
    assert mgr._kv_proc.pid != kv_pid
    assert mgr.recovery["kvd_respawns"] == 1
    assert mgr.recovery["kvd_replay_seconds"] >= 0.0

    t.join(timeout=120)
    assert not t.is_alive(), "stream never finished"
    stop_blobs.set()
    blobber.join(timeout=30)
    final = events[-1][1]
    assert final.get("done") and "error" not in final, final
    # token-exact vs the no-fault reference: zero dropped, zero
    # duplicated tokens across the data plane's death and rebirth
    acc = "".join(v for _, e in events[:-1]
                  for v in e.get("delta", {}).values())
    assert final["predictions"] == expected
    assert acc == expected[0]
    # the stream was genuinely mid-flight when the kvd died
    assert 0 < n_at_kill < len(events)

    # zero lost durable state: every blob acknowledged (pre- and
    # post-kill) reads back intact from the respawned kvd
    assert len(blobs_written) > 2
    check = ParamStore.from_uri(f"kv://{mgr.kv_host}:{port}")
    for key, val in blobs_written.items():
        got = check.load(key)
        assert got is not None, f"durable blob {key} lost"
        assert got["w"] == val["w"] and got["tag"] == val["tag"]

    # worker rode the outage without dying (pause path, not a crash)
    assert w.stats.snapshot()["data_plane_down"] == 0
    w.stop()
    th.join(timeout=30)

    # the doctor's data-plane audit blesses the recovered workdir
    report = audit_workdir(str(tmp_path / "wd"),
                           db_path=str(tmp_path / "meta.db"))
    dp = report["data_plane"]
    assert dp["reachable"] and dp["replay"]["ok"], report["drift"]
    mgr.stop_all()


def test_worker_pauses_and_resumes_on_hub_outage(trained, tmp_path):
    """The serve loop PAUSES on a dead data plane (no crash, obs state
    intact, `data_plane_down` flips to 1) and resumes serving when a
    kvd comes back on the same port."""
    from test_decode_engine import KNOBS

    from rafiki_tpu.models.llama_lora import LlamaLoRA
    from rafiki_tpu.serving.predictor import Predictor
    from rafiki_tpu.store.param_store import ParamStore
    from rafiki_tpu.worker.inference import InferenceWorker

    store = ParamStore.from_uri("mem://")
    store.save("t0", trained.dump_parameters())
    s = KVServer(data_dir=str(tmp_path / "dd"))
    port = s.port
    hub = KVQueueHub(s.host, port, retry_window_s=0.5)
    w = InferenceWorker(LlamaLoRA, "t0", KNOBS, store, hub, "w0",
                        decode_loop=True, max_slots=2,
                        max_new_tokens=4, steps_per_sync=1)
    th = threading.Thread(target=w.run, daemon=True)
    th.start()
    _kill9(s)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and \
            w.stats.snapshot()["data_plane_down"] != 1:
        time.sleep(0.05)
    snap = w.stats.snapshot()
    assert snap["data_plane_down"] == 1, snap
    assert snap["hub_outages"] == 1
    assert th.is_alive()  # paused, not crashed

    s2 = KVServer(port=port, data_dir=str(tmp_path / "dd"))
    pred = Predictor(KVQueueHub(s2.host, port), ["w0"],
                     gather_timeout=60.0)
    preds, info = pred.predict(["tok1 tok2"])
    assert info["workers_answered"] == 1, info
    assert preds and preds[0]
    assert w.stats.snapshot()["data_plane_down"] == 0  # resumed
    w.stop()
    th.join(timeout=30)
    s2.stop()
