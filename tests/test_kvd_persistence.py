"""kvd WAL + snapshot persistence: replay, torn tails, CRC refusal,
dedup pushes, compaction, the STATS verb, and the Python dry-run
scanner.

These are the unit-level halves of the crash-survivable data plane
(docs/operations.md "Data-plane death & recovery"); the integration
halves — supervised respawn, reconnecting hub clients, the kill -9
acceptance drill — live in tests/test_hub_reconnect.py.
"""

import os
import random
import signal
import struct
import subprocess
import time
import zlib
from pathlib import Path

import pytest

from rafiki_tpu.native import wal as kvwal
from rafiki_tpu.native.client import KVClient, KVServer, ensure_built


@pytest.fixture(scope="module", autouse=True)
def _built():
    ensure_built()


def _boot(data_dir, **kw):
    return KVServer(data_dir=str(data_dir), **kw)


def _kill9(server):
    os.kill(server._proc.pid, signal.SIGKILL)
    server._proc.wait()


# ------------------------------------------------------ basic replay

def test_graceful_restart_restores_state(tmp_path):
    """SHUTDOWN fsyncs; a reboot on the same data dir restores blobs,
    list content AND order, and the effect of pops/deletes."""
    s = _boot(tmp_path / "dd")
    c = KVClient(s.host, s.port)
    c.set("params:t1", b"\x00blob\xff")
    c.set("doomed", b"x")
    c.delete("doomed")
    for v in (b"a", b"b", b"c", b"d"):
        c.rpush("q", v)
    assert c.brpop("q", 1.0) == ("q", b"d")  # tail pop logged
    assert c.lpop("q") == b"a"               # head pop logged
    c.incr("ctr")
    c.incr("ctr")
    c.shutdown()
    s._proc.wait(timeout=5)

    s2 = _boot(tmp_path / "dd")
    c2 = KVClient(s2.host, s2.port)
    assert c2.get("params:t1") == b"\x00blob\xff"
    assert c2.get("doomed") is None
    # surviving list content in original order: b then c
    assert c2.lpop("q") == b"b"
    assert c2.lpop("q") == b"c"
    assert c2.llen("q") == 0
    # INCR is WAL-logged as its resulting SET — replay can't double it
    assert c2.incr("ctr") == 3
    s2.stop()


def test_kill9_restart_restores_state_without_fsync(tmp_path):
    """A PROCESS crash loses nothing even under --fsync no: records
    are written to the fd per command, and kill -9 only discards
    user-space state. (The fsync policy guards against host crashes.)"""
    s = _boot(tmp_path / "dd", fsync="no")
    c = KVClient(s.host, s.port)
    c.set("k", b"v")
    c.lpush("q", b"m")
    _kill9(s)
    s2 = _boot(tmp_path / "dd", fsync="no")
    c2 = KVClient(s2.host, s2.port)
    assert c2.get("k") == b"v"
    assert c2.llen("q") == 1
    s2.stop()


def test_fsync_policies_accepted(tmp_path):
    for i, policy in enumerate(("always", "everysec", "no")):
        s = _boot(tmp_path / f"dd{i}", fsync=policy)
        c = KVClient(s.host, s.port)
        c.set("k", b"v")
        assert c.stats()["fsync_policy"] == policy
        s.stop()
    with pytest.raises(ValueError):
        KVServer(data_dir=str(tmp_path / "bad"), fsync="sometimes")


def test_expiry_rearmed_after_replay(tmp_path):
    """EXPIRE records replay by re-arming from boot time: a condemned
    key is still collected after a crash (late, never early)."""
    s = _boot(tmp_path / "dd")
    c = KVClient(s.host, s.port)
    c.set("mortal", b"v")
    c.expire("mortal", 0.5)
    _kill9(s)
    s2 = _boot(tmp_path / "dd")
    c2 = KVClient(s2.host, s2.port)
    assert c2.get("mortal") == b"v"  # TTL re-armed, not pre-fired
    time.sleep(0.8)
    c2.ping()  # trigger the purge scan
    assert c2.get("mortal") is None
    s2.stop()


# ------------------------------------------------- torn tail / corrupt

def test_torn_tail_truncated_loudly_and_served(tmp_path):
    s = _boot(tmp_path / "dd")
    c = KVClient(s.host, s.port)
    c.set("k", b"v")
    c.shutdown()
    s._proc.wait(timeout=5)
    wal_path = tmp_path / "dd" / "wal"
    good = wal_path.read_bytes()
    # a half-written record: plausible header promising more bytes
    # than exist (exactly what kill -9 mid-append leaves behind)
    wal_path.write_bytes(good + struct.pack("<II", 64, 0) + b"GARBAGE")
    s2 = _boot(tmp_path / "dd")
    c2 = KVClient(s2.host, s2.port)
    assert c2.get("k") == b"v"
    st = c2.stats()
    assert st["wal_truncated_bytes"] == 8 + len(b"GARBAGE")
    # the torn bytes were truncated IN the file, not just skipped
    assert wal_path.read_bytes() == good
    s2.stop()


def test_crc_corrupt_record_refuses_boot(tmp_path):
    """A full-length record whose CRC mismatches is disk/operator
    damage: the boot must FAIL with a structured JSON error, not serve
    silently-wrong state."""
    s = _boot(tmp_path / "dd")
    c = KVClient(s.host, s.port)
    c.set("k", b"A" * 64)
    c.set("k2", b"B" * 64)
    c.shutdown()
    s._proc.wait(timeout=5)
    wal_path = tmp_path / "dd" / "wal"
    data = bytearray(wal_path.read_bytes())
    data[20] ^= 0xFF  # flip a byte inside the first record's payload
    wal_path.write_bytes(bytes(data))
    with pytest.raises(RuntimeError) as ei:
        _boot(tmp_path / "dd")
    assert "kvd_wal_corrupt" in str(ei.value)
    assert "rc=4" in str(ei.value)


# -------------------------------------------------------- dedup pushes

def test_dedup_push_within_and_across_restart(tmp_path):
    s = _boot(tmp_path / "dd")
    c = KVClient(s.host, s.port)
    assert c.lpush_dedup("q", "id-1", b"m1") == 1
    assert c.lpush_dedup("q", "id-1", b"m1") == 1  # retry: no-op
    assert c.lpush_dedup("q", "id-2", b"m2") == 2
    _kill9(s)
    s2 = _boot(tmp_path / "dd")
    c2 = KVClient(s2.host, s2.port)
    assert c2.llen("q") == 2
    # the recent-set survived the crash via the WAL: a client retrying
    # its unacked push against the RESPAWNED server still can't
    # double-deliver
    assert c2.lpush_dedup("q", "id-2", b"m2") == 2
    assert c2.llen("q") == 2
    s2.stop()


# --------------------------------------------------------- compaction

def test_compact_shrinks_wal_and_preserves_state(tmp_path):
    s = _boot(tmp_path / "dd")
    c = KVClient(s.host, s.port)
    for i in range(50):
        c.set("hot", b"v%d" % i)  # 50 overwrites -> 1 snapshot record
    c.rpush("q", b"a", b"b")
    c.lpush_dedup("q", "idX", b"c")
    wal_before = c.stats()["wal_bytes"]
    assert wal_before > 0
    c.compact()
    st = c.stats()
    # the reset WAL holds only the snapshot-pairing WALHDR record
    assert 0 < st["wal_bytes"] < 64, st["wal_bytes"]
    assert st["snapshot_bytes"] > 0
    assert st["compactions"] == 1
    assert st["snapshot_age_s"] >= 0
    _kill9(s)
    s2 = _boot(tmp_path / "dd")
    c2 = KVClient(s2.host, s2.port)
    assert c2.get("hot") == b"v49"
    assert c2.lpop("q") == b"c"  # LPUSHD pushed front
    assert c2.lpop("q") == b"a"
    assert c2.lpop("q") == b"b"
    # dedup ids ride the snapshot as DEDUP records
    assert c2.lpush_dedup("q2", "idX", b"zzz") == 0
    s2.stop()


def test_stale_wal_after_snapshot_rename_not_double_applied(tmp_path):
    """The compaction crash window: a kill between the snapshot rename
    and the WAL truncate leaves the NEW snapshot next to the FULL
    pre-compaction WAL. Replaying both would double-deliver every
    queued message since the previous compaction — the epoch pairing
    (snapshot `EPOCH` ↔ WAL `WALHDR`) must make the boot DISCARD the
    stale WAL instead."""
    s = _boot(tmp_path / "dd")
    c = KVClient(s.host, s.port)
    c.rpush("q", b"m1", b"m2")
    c.set("k", b"v")
    c.shutdown()
    s._proc.wait(timeout=5)
    dd = tmp_path / "dd"
    stale_wal = (dd / "wal").read_bytes()
    assert stale_wal  # the pre-compaction records

    # run the compaction on a live server, then SIMULATE the crash
    # window by restoring the pre-compaction WAL next to the new
    # snapshot (exactly what dying before the truncate leaves behind)
    s = _boot(tmp_path / "dd")
    KVClient(s.host, s.port).compact()
    _kill9(s)
    (dd / "wal").write_bytes(stale_wal)

    s2 = _boot(tmp_path / "dd")
    c2 = KVClient(s2.host, s2.port)
    assert c2.get("k") == b"v"
    assert c2.llen("q") == 2  # NOT 4: the stale WAL was discarded
    assert c2.lpop("q") == b"m1"
    # the Python dry-run scanner agrees with the boot's verdict
    c2.shutdown()
    s2._proc.wait(timeout=5)
    state = kvwal.replay_state(str(dd))
    assert len(state["lists"]["q"]) == 1  # m2 (m1 popped, logged)


def test_auto_compaction_on_rotate_threshold(tmp_path):
    s = _boot(tmp_path / "dd", wal_rotate_bytes=2048)
    c = KVClient(s.host, s.port)
    for i in range(100):
        # distinct keys so the write that CROSSES the rotate threshold
        # is distinguishable — rotation must run after the mutation
        # lands, or the boundary write would be snapshot-less AND
        # truncated out of the WAL (durably lost)
        c.set("k%d" % i, b"x" * 64)
    st = c.stats()
    assert st["compactions"] >= 1
    assert st["wal_bytes"] <= 2048
    _kill9(s)
    s2 = _boot(tmp_path / "dd", wal_rotate_bytes=2048)
    c2 = KVClient(s2.host, s2.port)
    for i in range(100):  # every acknowledged write survived, incl.
        # the ones that triggered a rotation
        assert c2.get("k%d" % i) == b"x" * 64, i
    s2.stop()


def test_stats_verb_fields(tmp_path):
    s = _boot(tmp_path / "dd")
    c = KVClient(s.host, s.port)
    st = c.stats()
    for key in ("persist_enabled", "fsync_policy", "wal_bytes",
                "snapshot_bytes", "snapshot_age_s", "last_fsync_age_s",
                "replay_seconds", "replayed_records",
                "wal_truncated_bytes", "compactions", "dedup_ids",
                "keys", "lists"):
        assert key in st, key
    assert st["persist_enabled"] == 1
    s.stop()


def test_no_data_dir_is_pure_memory():
    with KVServer() as s:
        c = KVClient(s.host, s.port)
        st = c.stats()
        assert st["persist_enabled"] == 0
        with pytest.raises(RuntimeError):
            c.compact()  # structured error, not a crash


# ------------------------------------------- the Python dry-run scanner

def test_wal_scanner_matches_server_verdicts(tmp_path):
    s = _boot(tmp_path / "dd")
    c = KVClient(s.host, s.port)
    c.set("params:t1", b"blob")
    c.rpush("q", b"a", b"b")
    assert c.brpop("q", 1.0) == ("q", b"b")  # BRPOP pops the tail
    c.shutdown()
    s._proc.wait(timeout=5)

    rep = kvwal.dry_run_replay(str(tmp_path / "dd"))
    assert rep["ok"], rep["findings"]
    assert rep["replayable_records"] == 3  # SET, RPUSH, logged RPOP
    state = kvwal.replay_state(str(tmp_path / "dd"))
    assert state["kv"] == {"params:t1": b"blob"}
    assert state["lists"]["q"] == [b"a"]

    # torn tail: reported, still ok (a real boot truncates and serves)
    wal_path = tmp_path / "dd" / "wal"
    wal_path.write_bytes(wal_path.read_bytes() + b"\x01\x02\x03")
    rep = kvwal.dry_run_replay(str(tmp_path / "dd"))
    assert rep["ok"]
    assert rep["wal"]["torn_tail_bytes"] == 3

    # corruption: not ok, with the offset in the finding
    data = bytearray(wal_path.read_bytes()[:-3])
    data[9] ^= 0xFF  # inside the first record's crc/payload area
    wal_path.write_bytes(bytes(data))
    rep = kvwal.dry_run_replay(str(tmp_path / "dd"))
    assert not rep["ok"]
    assert any("corrupt" in f for f in rep["findings"])


def test_wal_scanner_crc_parity_with_server(tmp_path):
    """The Python scanner and the C++ loader must agree on framing and
    CRC — a record the scanner blesses replays on a real boot."""
    s = _boot(tmp_path / "dd")
    c = KVClient(s.host, s.port)
    payload = bytes(range(256)) * 3 + b"\r\n$*"
    c.set("bin", payload)
    c.shutdown()
    s._proc.wait(timeout=5)
    recs = kvwal.iter_records(tmp_path / "dd" / "wal")
    assert recs == [[b"SET", b"bin", payload]]
    # independent CRC check over the raw record bytes
    raw = (tmp_path / "dd" / "wal").read_bytes()
    length, crc = struct.unpack_from("<II", raw, 0)
    assert (zlib.crc32(raw[8:8 + length]) & 0xFFFFFFFF) == crc
    s2 = _boot(tmp_path / "dd")
    c2 = KVClient(s2.host, s2.port)
    assert c2.get("bin") == payload
    s2.stop()


def test_scanner_empty_dir_not_ok(tmp_path):
    rep = kvwal.dry_run_replay(str(tmp_path / "empty"))
    assert not rep["ok"]
    assert any("cold-start" in f for f in rep["findings"])
    assert Path(rep["data_dir"]).name == "empty"


# ---------------------------------------- seeded corruption fuzzing

def _healthy_wal(tmp_path) -> bytes:
    """A WAL exercising every record shape (SET, pushes, a logged
    pop, a dedup push, INCR-as-SET)."""
    s = _boot(tmp_path / "seed")
    c = KVClient(s.host, s.port)
    c.set("k1", b"A" * 40)
    c.rpush("q", b"m1", b"m2", b"m3")
    assert c.lpop("q") == b"m1"
    c.lpush_dedup("q", "id-1", b"m0")
    c.incr("ctr")
    c.shutdown()
    s._proc.wait(timeout=5)
    return (tmp_path / "seed" / "wal").read_bytes()


def test_wal_corruption_fuzz_scanner_agrees_with_server(tmp_path):
    """Seeded fuzz: random truncations and single-bit flips over a
    healthy WAL. On EVERY mutant the Python dry-run scanner and the
    C++ boot must reach the same verdict — a scanner-ok mutant boots
    and serves, a scanner-corrupt mutant refuses with the structured
    rc=4 error. Divergence means an operator preflight blesses a WAL
    the server then rejects (or, worse, the reverse)."""
    good = _healthy_wal(tmp_path)
    rng = random.Random(0xC0FFEE)
    mutants = [("trunc", good[:rng.randrange(len(good))])
               for _ in range(10)]
    for _ in range(14):
        data = bytearray(good)
        data[rng.randrange(len(data))] ^= 1 << rng.randrange(8)
        mutants.append(("flip", bytes(data)))
    for i, (kind, data) in enumerate(mutants):
        dd = tmp_path / f"m{i}"
        dd.mkdir()
        (dd / "wal").write_bytes(data)
        rep = kvwal.dry_run_replay(str(dd))
        try:
            s = KVServer(data_dir=str(dd))
        except RuntimeError as e:
            # refusals must be the structured exit-4 path, never a
            # crash or a hang
            assert "rc=4" in str(e), (i, kind, str(e))
            server_ok = False
        else:
            server_ok = True
            s.stop()
        assert rep["ok"] == server_ok, (
            i, kind, rep["findings"], server_ok)


# ----------------------------------- sanitizer builds (slow tier)

@pytest.fixture(scope="module")
def asan_kvd():
    """Build + boot-check an address-sanitized kvd, or skip cleanly
    where the toolchain/runtime can't produce or run one."""
    try:
        ensure_built(sanitize="address")
    except (RuntimeError, OSError,
            subprocess.CalledProcessError) as e:
        pytest.skip(f"ASan build unavailable: {e}")
    try:
        s = KVServer(sanitize="address")
    except (RuntimeError, OSError) as e:
        pytest.skip(f"ASan kvd cannot run here: {e}")
    s.stop()
    return "address"


@pytest.mark.slow
@pytest.mark.parametrize("case", [
    test_graceful_restart_restores_state,
    test_kill9_restart_restores_state_without_fsync,
    test_torn_tail_truncated_loudly_and_served,
    test_crc_corrupt_record_refuses_boot,
    test_dedup_push_within_and_across_restart,
], ids=lambda case: case.__name__)
def test_asan_rerun_core_cases(tmp_path, asan_kvd, case, monkeypatch):
    """The WAL-persistence core cases again, against an
    AddressSanitizer-instrumented kvd: replay, torn-tail truncation,
    and CRC refusal are exactly the buffer-math paths ASan watches.
    Runs through the RAFIKI_KVD_SANITIZE env hook so every KVServer
    the case spawns is instrumented."""
    monkeypatch.setenv("RAFIKI_KVD_SANITIZE", "address")
    case(tmp_path)


@pytest.fixture(scope="module")
def tsan_kvd():
    """Build + boot-check a thread-sanitized kvd, or skip cleanly
    where the toolchain/runtime can't produce or run one."""
    try:
        ensure_built(sanitize="thread")
    except (RuntimeError, OSError,
            subprocess.CalledProcessError) as e:
        pytest.skip(f"TSan build unavailable: {e}")
    try:
        s = KVServer(sanitize="thread")
    except (RuntimeError, OSError) as e:
        pytest.skip(f"TSan kvd cannot run here: {e}")
    s.stop()
    return "thread"


@pytest.mark.slow
@pytest.mark.parametrize("case", [
    test_fsync_policies_accepted,
    test_graceful_restart_restores_state,
    test_dedup_push_within_and_across_restart,
], ids=lambda case: case.__name__)
def test_tsan_rerun_core_cases(tmp_path, tsan_kvd, case, monkeypatch):
    """The dynamic counterpart of the static race layer: the same
    kvd, instrumented by ThreadSanitizer, driven through the cases
    that exercise its fsync thread and concurrent connection handling.
    TSan aborts the server on any data race, which the cases surface
    as protocol/boot failures."""
    monkeypatch.setenv("RAFIKI_KVD_SANITIZE", "thread")
    case(tmp_path)
