"""Llama LoRA family: module, LoRA freezing, 2-D sharding, generation."""

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from rafiki_tpu.constants import TaskType
from rafiki_tpu.data import generate_text_classification_dataset
from rafiki_tpu.model import TrainContext, test_model_class
from rafiki_tpu.models.llama_lora import (Llama, LlamaLoRA, greedy_generate,
                                          lora_trainable_mask)


TINY = {"max_epochs": 6, "vocab_size": 1 << 14, "hidden_dim": 64,
        "depth": 2, "n_heads": 4, "kv_ratio": 2, "lora_rank": 4,
        "max_len": 32, "model_parallel": 2, "learning_rate": 1e-2,
        "batch_size": 16, "bf16": False, "remat": False,
        "moe_experts": 0, "moe_top_k": 1, "pipeline_stages": 1,
        "pipeline_microbatches": 0, "loss_chunk": 0,
        "quantize_int8": False, "sequence_parallel": 1,
        "adapters_only": False, "rope_theta": 10000.0,
        "rope_scaling": "", "grad_accum": 1, "kv_cache_int8": False,
        "quick_train": False, "lora_scale": 1.0, "remat_policy": "none",
        "overlap_collectives": False,
        "share_params": False, "tokenizer_path": "", "pretrained_path": ""}


def _tiny_module(vocab=256, max_len=16, rank=2):
    return Llama(vocab_size=vocab, max_len=max_len, hidden_dim=32, depth=2,
                 n_heads=4, n_kv_heads=2, mlp_dim=64, lora_rank=rank)


def test_tiny_covers_every_knob():
    """TINY must be a FULL knob assignment: the slow template-contract
    test validates completeness, and a knob added without updating
    TINY fails only there — this default-leg guard surfaces the gap
    immediately instead."""
    missing = set(LlamaLoRA.get_knob_config()) - set(TINY)
    assert not missing, sorted(missing)


def test_llama_module_shapes():
    m = _tiny_module()
    ids = np.ones((2, 16), np.int32)
    params = m.init(jax.random.PRNGKey(0), ids)["params"]
    out = m.apply({"params": params}, ids)
    assert out.shape == (2, 16, 256)


def test_lora_mask_freezes_base():
    m = _tiny_module()
    ids = np.ones((2, 16), np.int32)
    params = m.init(jax.random.PRNGKey(0), ids)["params"]
    mask = lora_trainable_mask(params)
    flat = jax.tree_util.tree_flatten_with_path(mask)[0]
    by_path = {"/".join(str(getattr(k, "key", k)) for k in kp): v
               for kp, v in flat}
    assert by_path["block_0/attn/wq/lora_a"] is True
    assert by_path["block_0/attn/wq/kernel"] is False
    assert by_path["tok_embed/embedding"] is False
    assert by_path["lm_head/kernel"] is True
    assert any("final_norm" in p and v for p, v in by_path.items())
    # flax auto-names block RMSNorms "RMSNorm_0"/"RMSNorm_1" — they must
    # train too (the LoRA recipe tunes norms)
    assert any("RMSNorm" in p and v for p, v in by_path.items())


@pytest.mark.slow
def test_greedy_generate_matches_full_forward():
    """Cache decode must reproduce the full-forward next-token argmax."""
    m = _tiny_module(max_len=24)
    rng = np.random.default_rng(0)
    prompt = rng.integers(2, 256, size=(2, 6)).astype(np.int32)
    lens = np.asarray([6, 4], np.int32)
    params = m.init(jax.random.PRNGKey(1), prompt)["params"]

    out = np.asarray(greedy_generate(m, params, prompt, lens, max_new=3))
    assert out.shape == (2, 3)

    # oracle for example 0 (full-length prompt): step the full forward
    ids = list(prompt[0])
    for step in range(3):
        seq = np.asarray(ids, np.int32)[None, :]
        logits = m.apply({"params": params}, seq,
                         lens=jnp.asarray([len(ids)], jnp.int32))
        nxt = int(np.argmax(np.asarray(logits[0, len(ids) - 1],
                                       np.float32)))
        assert nxt == int(out[0, step]), f"mismatch at step {step}"
        ids.append(nxt)


@pytest.mark.slow
def test_llama_trains_2d_sharded(tmp_path):
    """fsdp × tensor (4×2) over 8 virtual devices; loss decreases and the
    frozen base stays bit-identical."""
    tr = str(tmp_path / "t.jsonl")
    generate_text_classification_dataset(tr, 128, seed=0)
    model = LlamaLoRA(**TINY)
    ctx = TrainContext(devices=list(jax.devices()))

    # snapshot a base kernel before training to prove freezing
    model.train(tr, ctx)
    losses = ctx.logger.get_values("loss")
    assert len(losses) >= 2 and losses[-1] < losses[0]

    params = model.dump_parameters()["params"]
    m2 = LlamaLoRA(**TINY)
    fresh = m2._module().init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, TINY["max_len"]), jnp.int32))["params"]
    np.testing.assert_array_equal(
        np.asarray(params["block_0"]["attn"]["wq"]["kernel"]),
        np.asarray(fresh["block_0"]["attn"]["wq"]["kernel"]))
    # ...while the LoRA adapters actually moved
    assert float(np.abs(np.asarray(
        params["block_0"]["attn"]["wq"]["lora_b"])).sum()) > 0


@pytest.mark.slow
def test_llama_template_contract(tmp_path):
    tr, va = str(tmp_path / "t.jsonl"), str(tmp_path / "v.jsonl")
    generate_text_classification_dataset(tr, 128, seed=0)
    generate_text_classification_dataset(va, 32, seed=1)
    preds = test_model_class(LlamaLoRA, TaskType.LANGUAGE_MODELING,
                             tr, va, queries=["tok1 tok2 tok3"], knobs=TINY)
    assert len(preds) == 1 and isinstance(preds[0], str)


def test_llama_bf16_compute_keeps_f32_params():
    m = Llama(vocab_size=128, max_len=16, hidden_dim=32, depth=1,
              n_heads=4, n_kv_heads=2, mlp_dim=64, lora_rank=2,
              dtype=jnp.bfloat16)
    ids = jnp.ones((2, 8), jnp.int32)
    params = m.init(jax.random.PRNGKey(0), ids)["params"]
    assert all(p.dtype == jnp.float32
               for p in jax.tree_util.tree_leaves(params))
    _, state = m.apply({"params": params}, ids,
                       capture_intermediates=True)
    block_out = state["intermediates"]["block_0"]["__call__"][0]
    assert block_out.dtype == jnp.bfloat16, block_out.dtype


# ---- fsdp at scale (VERDICT r3 weak #3) ----

def _abstract_params(module):
    return jax.eval_shape(lambda: module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"])


def _tree_bytes(tree):
    return sum(int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
               for l in jax.tree_util.tree_leaves(tree))


def test_8b_parameterization_specs_divide_abstract():
    """Shapes-only Llama-3-8B build (hidden 4096, depth 32, GQA 32/8,
    mlp 14336, vocab 128256): every TP/fsdp spec must divide its dim on
    each supported mesh factorization, and the per-device byte count
    computed from the shardings must be ~total/8 — no allocation, so
    this validates the REAL 8B spec table in seconds."""
    from rafiki_tpu.models.llama_lora import TP_RULES
    from rafiki_tpu.parallel.sharding import make_mesh, param_shardings

    module = Llama(vocab_size=128256, max_len=256, hidden_dim=4096,
                   depth=32, n_heads=32, n_kv_heads=8, mlp_dim=14336,
                   lora_rank=16)
    abstract = _abstract_params(module)
    total = _tree_bytes(abstract)
    assert total >= 8e9 * 4  # ≥ 8B f32 params

    for model_par in (1, 2, 4):
        mesh = make_mesh(jax.devices()[:8], model=model_par)
        shardings = param_shardings(abstract, mesh, tp_rules=TP_RULES,
                                    fsdp=True, min_size=2 ** 16)
        per_dev = 0
        n_sharded = 0
        for leaf, sh in zip(jax.tree_util.tree_leaves(abstract),
                            jax.tree_util.tree_leaves(shardings)):
            spec = sh.spec
            for dim, axis in enumerate(spec):
                if axis is not None:
                    assert leaf.shape[dim] % mesh.shape[axis] == 0, \
                        (leaf.shape, spec, axis)
            shard_shape = sh.shard_shape(leaf.shape)
            per_dev += int(np.prod(shard_shape)) * \
                np.dtype(leaf.dtype).itemsize
            if any(s is not None for s in spec):
                n_sharded += 1
            elif int(np.prod(leaf.shape)) >= 2 ** 16:
                raise AssertionError(
                    f"large leaf {leaf.shape} left replicated on "
                    f"mesh model={model_par}")
        # every big tensor sharded → per-device ≈ total/8 (+ tiny norms)
        assert per_dev <= total / 8 * 1.05, (per_dev, total)
        assert n_sharded >= 32 * 7  # all projections, every layer


def test_8b_lora_byte_budget_fits_v5e16():
    """VERDICT r4 item 6: per-device memory accounting for the Llama-3
    8B LoRA config (#5) on a 16-chip v5e mesh (dp=2 x tp=8, remat,
    loss_chunk, grad_accum, bf16 compute) from REAL shape math —
    abstract init + the template's actual sharding rules over an
    AbstractMesh, so no 16-device host (or allocation) is needed. The
    total must clear a v5e chip's 16GB HBM with headroom; dropping the
    memory knobs (no remat, dense loss) must blow the budget — proving
    the formula actually discriminates."""
    from rafiki_tpu.models.llama_lora import estimate_train_device_bytes

    spec = dict(vocab_size=128256, max_len=4096, hidden_dim=4096,
                depth=32, n_heads=32, n_kv_heads=8, mlp_dim=14336,
                lora_rank=16, dtype=jnp.bfloat16)
    budget = estimate_train_device_bytes(
        Llama(**spec, remat=True), batch_size=16,
        data_parallel=2, model_parallel=8, grad_accum=4,
        loss_chunk=512, remat=True)
    gib = 1 << 30
    # params (f32, fully tp+fsdp sharded) ~ 32GB/16 ~ 2GiB/chip
    assert 1.5 * gib < budget["params"] < 2.6 * gib, budget
    # trainables are LoRA + norms + lm_head (the recipe tunes the
    # head): adamw mu+nu for the 128k x 4096 head dominates, ~0.26GiB
    # per chip once tp+fsdp sharded
    assert budget["opt"] < 0.5 * gib, budget
    assert budget["total"] < 12 * gib, budget  # fits 16GB w/ headroom

    # the SAME job without the memory knobs must NOT fit — a formula
    # that passes everything is not admission control
    naive = estimate_train_device_bytes(
        Llama(**spec, remat=False), batch_size=16,
        data_parallel=2, model_parallel=8, grad_accum=1,
        loss_chunk=0, remat=False)
    assert naive["total"] > 16 * gib, naive


def test_byte_budget_matches_measured_small_build():
    """Grounding: the formula's EXACT terms (params, opt) must equal
    the bytes actually resident per device on a real sharded build —
    same rules, same mesh — so the 8B numbers are shape math over a
    verified base, not a parallel implementation that can drift."""
    import optax

    from rafiki_tpu.models.llama_lora import (
        TP_RULES, estimate_train_device_bytes, lora_trainable_mask)
    from rafiki_tpu.parallel.sharding import make_mesh, param_shardings

    module = Llama(vocab_size=2048, max_len=32, hidden_dim=128,
                   depth=2, n_heads=4, n_kv_heads=2, mlp_dim=256,
                   lora_rank=4)

    def init_fn():
        return module.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))["params"]

    mesh = make_mesh(jax.devices()[:8], model=2)
    shardings = param_shardings(jax.eval_shape(init_fn), mesh,
                                tp_rules=TP_RULES, fsdp=True,
                                min_size=2 ** 12)
    params = jax.jit(init_fn, out_shardings=shardings)()
    tx = optax.multi_transform(
        {"train": optax.adamw(1e-3), "freeze": optax.set_to_zero()},
        lambda p: jax.tree_util.tree_map(
            lambda t: "train" if t else "freeze",
            lora_trainable_mask(p)))
    opt_state = tx.init(params)

    def measured_dev0(tree):
        dev = jax.devices()[0]
        n = 0
        for leaf in jax.tree_util.tree_leaves(tree):
            for sh in getattr(leaf, "addressable_shards", []):
                if sh.device == dev:
                    n += sh.data.nbytes
        return n

    budget = estimate_train_device_bytes(
        module, batch_size=8, data_parallel=4, model_parallel=2,
        fsdp_min_size=2 ** 12)
    assert budget["params"] == measured_dev0(params), budget
    # opt: mu+nu for trainable leaves (count scalars et al. are noise)
    meas_opt = measured_dev0(opt_state)
    assert budget["opt"] <= meas_opt <= budget["opt"] + 4096, \
        (budget["opt"], meas_opt)


def test_byte_budget_sp_axes_shrink_activations():
    """The estimator's sequence-parallel branches: sharding L over sp
    divides the activation term (tokens_dev drops), and the sp×tp
    3-axis mesh additionally shards the params — the budget math the
    admission check relies on for long-context jobs."""
    from rafiki_tpu.models.llama_lora import estimate_train_device_bytes

    module = Llama(vocab_size=2048, max_len=128, hidden_dim=128,
                   depth=2, n_heads=4, n_kv_heads=2, mlp_dim=256,
                   lora_rank=4)
    # sp's value: when batch can't shard further (dp fixed), adding sp
    # devices divides each device's token count — the long-context
    # regime. (At a FIXED total device count per-device tokens are
    # invariant to the dp/sp split; that's not what sp is for.)
    base = estimate_train_device_bytes(module, batch_size=8,
                                       data_parallel=2)
    sp = estimate_train_device_bytes(module, batch_size=8,
                                     data_parallel=2,
                                     sequence_parallel=4)
    assert sp["activations"] < base["activations"], (sp, base)
    sptp = estimate_train_device_bytes(module, batch_size=8,
                                       data_parallel=2,
                                       sequence_parallel=2,
                                       model_parallel=2)
    # tp shards the big leaves the dp-only fsdp couldn't split further
    assert sptp["params"] < sp["params"], (sptp, sp)


def test_byte_budget_pipeline_mode_counts_replicated_params():
    """Pipeline mode replicates the param tree per device (train()'s
    rep_pp layout) — the estimator must charge the FULL tree, not the
    tp+fsdp shards pp mode doesn't use, or admission control would
    green-light trials that OOM at replication."""
    from rafiki_tpu.models.llama_lora import estimate_train_device_bytes

    module = Llama(vocab_size=2048, max_len=32, hidden_dim=128,
                   depth=4, n_heads=4, n_kv_heads=2, mlp_dim=256,
                   lora_rank=4)
    abstract = _abstract_params(module)
    total = _tree_bytes(abstract)
    pp = estimate_train_device_bytes(module, batch_size=8,
                                     data_parallel=4,
                                     pipeline_stages=2)
    sharded = estimate_train_device_bytes(module, batch_size=8,
                                          data_parallel=4,
                                          model_parallel=2)
    assert pp["params"] == total, (pp["params"], total)
    assert pp["params"] > sharded["params"]
    # the knob-level front routes pipeline_stages the same way
    from rafiki_tpu.models.llama_lora import LlamaLoRA
    model = LlamaLoRA(**{**TINY, "model_parallel": 1,
                         "pipeline_stages": 2,
                         "pipeline_microbatches": 4})
    via_knobs = model.estimate_device_budget(8)
    assert via_knobs["params"] == _tree_bytes(
        _abstract_params(model._module()))


@pytest.mark.slow
def test_fsdp_bounds_per_device_memory_at_1b():
    """REAL ~1.3B-param build on the 8-device mesh, initialized straight
    into its 2-D shardings (jit out_shardings — no full-tree host
    staging): the bytes actually resident per device must be ~total/8."""
    from rafiki_tpu.models.llama_lora import TP_RULES
    from rafiki_tpu.parallel.sharding import make_mesh, param_shardings

    module = Llama(vocab_size=32000, max_len=128, hidden_dim=2048,
                   depth=18, n_heads=16, n_kv_heads=8, mlp_dim=8192,
                   lora_rank=0)

    def init_fn():
        return module.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))["params"]

    abstract = jax.eval_shape(init_fn)
    total = _tree_bytes(abstract)
    assert total >= 1e9 * 4  # ≥ 1B f32 params

    mesh = make_mesh(jax.devices()[:8], model=2)
    shardings = param_shardings(abstract, mesh, tp_rules=TP_RULES,
                                fsdp=True, min_size=2 ** 12)
    params = jax.jit(init_fn, out_shardings=shardings)()

    by_dev = {}
    for leaf in jax.tree_util.tree_leaves(params):
        for sh in leaf.addressable_shards:
            by_dev[sh.device] = by_dev.get(sh.device, 0) + \
                sh.data.nbytes
    assert len(by_dev) == 8
    worst = max(by_dev.values())
    # each device holds its 1/8 slice plus replicated norm scales
    assert worst <= total / 8 * 1.1, (worst, total)
    assert worst >= total / 8 * 0.9
    del params


@pytest.mark.slow
def test_remat_identical_math_and_decode_unaffected():
    """Llama remat: identical train-path outputs/grads; the decode path
    (mutable cache) never rematerializes and still generates the same
    tokens."""
    kw = dict(vocab_size=128, max_len=16, hidden_dim=32, depth=2,
              n_heads=4, n_kv_heads=2, mlp_dim=64, lora_rank=2)
    plain = Llama(**kw)
    remat = Llama(**kw, remat=True)
    ids = np.ones((2, 8), np.int32)
    params = plain.init(jax.random.PRNGKey(0), ids)["params"]

    np.testing.assert_allclose(
        np.asarray(plain.apply({"params": params}, ids)),
        np.asarray(remat.apply({"params": params}, ids)),
        atol=1e-6, rtol=1e-6)

    def loss(m):
        return lambda p: jnp.sum(
            m.apply({"params": p}, ids).astype(jnp.float32) ** 2)

    for a, b in zip(
            jax.tree_util.tree_leaves(jax.grad(loss(plain))(params)),
            jax.tree_util.tree_leaves(jax.grad(loss(remat))(params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)

    prompts = np.asarray([[1, 5, 9], [1, 7, 0]], np.int32)
    lens = np.asarray([3, 2], np.int32)
    np.testing.assert_array_equal(
        np.asarray(greedy_generate(plain, params, prompts, lens, 4)),
        np.asarray(greedy_generate(remat, params, prompts, lens, 4)))


@pytest.mark.slow
def test_llama_trains_pipeline_parallel(tmp_path):
    """pipeline_stages=4: decoder blocks pipelined over 4 devices during
    training; loss decreases, the frozen base stays frozen, and the
    result serves through the UNCHANGED canonical decode path."""
    tr = str(tmp_path / "t.jsonl")
    generate_text_classification_dataset(tr, 128, seed=0)
    knobs = {**TINY, "depth": 4, "model_parallel": 1,
             "pipeline_stages": 4, "pipeline_microbatches": 8,
             "max_epochs": 4}
    model = LlamaLoRA(**knobs)
    ctx = TrainContext(devices=list(jax.devices()))
    model.train(tr, ctx)
    losses = ctx.logger.get_values("loss")
    assert len(losses) >= 2 and losses[-1] < losses[0]
    # LoRA freeze still holds under the pipelined step
    fresh = LlamaLoRA(**knobs)._module().init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, TINY["max_len"]), jnp.int32))["params"]
    np.testing.assert_array_equal(
        np.asarray(model._params["block_0"]["attn"]["wq"]["kernel"]),
        np.asarray(fresh["block_0"]["attn"]["wq"]["kernel"]))
    out = model.predict(["tok1 tok2 tok3"])
    assert isinstance(out[0], str) and out[0]


def test_llama_pipeline_knob_validation(tmp_path):
    tr = str(tmp_path / "t.jsonl")
    generate_text_classification_dataset(tr, 16, seed=0)
    bad_depth = {**TINY, "depth": 3, "pipeline_stages": 2,
                 "model_parallel": 1}
    with pytest.raises(ValueError, match="divide"):
        LlamaLoRA(**bad_depth).train(
            tr, TrainContext(devices=list(jax.devices())))
    moe_pp = {**TINY, "depth": 4, "pipeline_stages": 2,
              "model_parallel": 1, "moe_experts": 2}
    with pytest.raises(ValueError, match="MoE"):
        LlamaLoRA(**moe_pp).train(
            tr, TrainContext(devices=list(jax.devices())))


def _max_intermediate_elems(closed_jaxpr) -> int:
    """Largest array (by element count) any equation produces, walking
    nested jaxprs (scan/checkpoint/custom-vjp bodies included)."""
    best = 0
    seen = set()

    def walk(jaxpr):
        if id(jaxpr) in seen:
            return
        seen.add(id(jaxpr))
        for eqn in jaxpr.eqns:
            for v in eqn.outvars:
                shape = getattr(v.aval, "shape", None)
                if shape is not None:
                    best_ref[0] = max(best_ref[0],
                                      int(np.prod(shape)) if shape else 1)
            for val in eqn.params.values():
                for sub in _jaxprs_in(val):
                    walk(sub)

    def _jaxprs_in(val):
        import jax.extend.core as jex_core
        if isinstance(val, jex_core.ClosedJaxpr):
            yield val.jaxpr
        elif isinstance(val, jex_core.Jaxpr):
            yield val
        elif isinstance(val, (tuple, list)):
            for item in val:
                yield from _jaxprs_in(item)

    best_ref = [best]
    walk(closed_jaxpr.jaxpr)
    return best_ref[0]


@pytest.mark.slow
def test_chunked_lm_loss_matches_dense():
    """Streamed lm_head+CE: identical value/count/grads to the dense
    loss, with no (B, L, vocab)-sized intermediate anywhere in the
    backward jaxpr (the whole point of the chunking). Slow leg: ~16s
    of CPU compile; the default leg keeps chunked-loss coverage via
    test_grad_accum_composes_with_chunked_loss."""
    from rafiki_tpu.models.llama_lora import (chunked_lm_loss_terms,
                                              lm_loss_terms)

    # smallest config that still has multi-chunk + pad + GQA structure
    m = Llama(vocab_size=128, max_len=16, hidden_dim=16, depth=1,
              n_heads=2, n_kv_heads=1, mlp_dim=32, lora_rank=2)
    rng = np.random.RandomState(0)
    ids = rng.randint(1, 128, (3, 16)).astype(np.int32)
    lens = np.asarray([16, 9, 5], np.int32)
    mask = np.asarray([1.0, 1.0, 0.0], np.float32)
    params = m.init(jax.random.PRNGKey(0), ids)["params"]

    def dense_loss(p):
        logits = m.apply({"params": p}, ids, lens=lens)
        t, c = lm_loss_terms(logits, ids, lens, mask)
        return t / jnp.maximum(c, 1.0)

    def chunked_loss(p):
        h = m.apply({"params": p}, ids, lens=lens, return_hidden=True)
        t, c = chunked_lm_loss_terms(h, p["lm_head"]["kernel"], ids,
                                     lens, mask, chunk=5)  # 16 % 5 != 0
        return t / jnp.maximum(c, 1.0)

    np.testing.assert_allclose(dense_loss(params), chunked_loss(params),
                               rtol=1e-5)
    # counts agree even with a masked-out example and pad-to-chunk
    h = m.apply({"params": params}, ids, lens=lens, return_hidden=True)
    logits = m.apply({"params": params}, ids, lens=lens)
    _, c0 = lm_loss_terms(logits, ids, lens, mask)
    _, c1 = chunked_lm_loss_terms(h, params["lm_head"]["kernel"], ids,
                                  lens, mask, chunk=5)
    assert int(c0) == int(c1) == (16 - 1) + (9 - 1)

    g0 = jax.grad(dense_loss)(params)
    g1 = jax.grad(chunked_loss)(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4,
                                                atol=1e-7), g0, g1)

    # memory claim: the dense backward holds full (3, 16, 128) logits;
    # the chunked one never builds anything that big
    full = 3 * 16 * 128
    assert _max_intermediate_elems(
        jax.make_jaxpr(jax.grad(dense_loss))(params)) >= full
    assert _max_intermediate_elems(
        jax.make_jaxpr(jax.grad(chunked_loss))(params)) < full


@pytest.mark.slow
def test_llama_trains_with_chunked_loss(tmp_path):
    """loss_chunk knob: end-to-end train parity with the dense loss."""
    tr = str(tmp_path / "t.jsonl")
    generate_text_classification_dataset(tr, 24, seed=0)
    losses = {}
    for name, chunk in (("dense", 0), ("chunked", 8)):
        model = LlamaLoRA(**{**TINY, "max_epochs": 2, "model_parallel": 1,
                             "loss_chunk": chunk})
        logged = []
        ctx = TrainContext(devices=list(jax.devices()))
        orig_log = ctx.logger.log
        ctx.logger.log = lambda **kw: (logged.append(kw.get("loss")),
                                       orig_log(**kw))[-1]
        model.train(tr, ctx)
        losses[name] = logged
    assert len(losses["dense"]) == len(losses["chunked"]) == 2
    np.testing.assert_allclose(losses["dense"], losses["chunked"],
                               rtol=1e-3)


def test_llama_chunked_loss_rejects_pipeline(tmp_path):
    tr = str(tmp_path / "t.jsonl")
    generate_text_classification_dataset(tr, 16, seed=0)
    bad = {**TINY, "depth": 4, "model_parallel": 1, "pipeline_stages": 2,
           "loss_chunk": 8}
    with pytest.raises(ValueError, match="loss_chunk"):
        LlamaLoRA(**bad).train(
            tr, TrainContext(devices=list(jax.devices())))


def test_quantize_llama_params_reconstruction_and_size():
    from rafiki_tpu.models.llama_lora import quantize_llama_params

    m = _tiny_module()
    ids = np.ones((2, 16), np.int32)
    params = m.init(jax.random.PRNGKey(0), ids)["params"]
    qparams = quantize_llama_params(params)

    # every 2-D LoRADense kernel became int8 + per-channel scale with
    # bounded reconstruction error; everything else passed through
    flat_q = {"/".join(str(getattr(k, "key", k)) for k in kp): v
              for kp, v in jax.tree_util.tree_flatten_with_path(qparams)[0]}
    flat_f = {"/".join(str(getattr(k, "key", k)) for k in kp): v
              for kp, v in jax.tree_util.tree_flatten_with_path(params)[0]}
    assert "lm_head/qkernel" in flat_q and "lm_head/kernel" not in flat_q
    assert flat_q["block_0/attn/wq/qkernel"].dtype == jnp.int8
    np.testing.assert_array_equal(flat_q["tok_embed/embedding"],
                                  flat_f["tok_embed/embedding"])
    np.testing.assert_array_equal(flat_q["block_0/attn/wq/lora_a"],
                                  flat_f["block_0/attn/wq/lora_a"])
    for name in ("block_0/attn/wq", "block_1/down", "lm_head"):
        k = np.asarray(flat_f[f"{name}/kernel"])
        rec = (np.asarray(flat_q[f"{name}/qkernel"], np.float32)
               * np.asarray(flat_q[f"{name}/qscale"])[None, :])
        err = np.abs(rec - k)
        bound = np.abs(k).max(0) / 127.0 / 2 + 1e-7  # scale/2 per channel
        assert (err <= bound[None, :] + 1e-6).all()

    def nbytes(t):
        return sum(np.asarray(x).nbytes
                   for x in jax.tree_util.tree_leaves(t))

    # the quantized kernels themselves shrink 4x (+ tiny scale vectors);
    # embeddings/norms/adapters pass through, so compare kernel bytes
    k_orig = sum(np.asarray(v).nbytes for n, v in flat_f.items()
                 if n.endswith("/kernel"))
    k_quant = sum(np.asarray(v).nbytes for n, v in flat_q.items()
                  if n.endswith("/qkernel") or n.endswith("/qscale"))
    assert k_quant < 0.30 * k_orig, (k_quant, k_orig)
    assert nbytes(qparams) < nbytes(params)


def test_quantized_module_logits_close():
    from rafiki_tpu.models.llama_lora import quantize_llama_params

    m = _tiny_module()
    mq = Llama(vocab_size=256, max_len=16, hidden_dim=32, depth=2,
               n_heads=4, n_kv_heads=2, mlp_dim=64, lora_rank=2,
               quantized=True)
    ids = np.asarray([[1, 5, 9, 13, 2, 7, 4, 3, 1, 5, 9, 13, 2, 7, 4, 3]],
                     np.int32)
    params = m.init(jax.random.PRNGKey(0), ids)["params"]
    lg = np.asarray(m.apply({"params": params}, ids), np.float32)
    lgq = np.asarray(mq.apply({"params": quantize_llama_params(params)},
                              ids), np.float32)
    cos = (lg * lgq).sum() / (np.linalg.norm(lg) * np.linalg.norm(lgq))
    assert cos > 0.999, cos
    assert np.abs(lg - lgq).max() < 0.05 * max(1.0, np.abs(lg).max())


@pytest.mark.slow
def test_llama_serves_quantized(tmp_path):
    """quantize_int8 knob: predict() and the decode engine run on the
    int8 tree; evaluate() stays full precision. Slow leg: trains then
    serves twice (~14s); the int8 kernel math keeps default-leg
    coverage in test_kv_int8 / the LoRADense quantization tests."""
    tr = str(tmp_path / "t.jsonl")
    generate_text_classification_dataset(tr, 24, seed=0)
    model = LlamaLoRA(**{**TINY, "max_epochs": 1, "model_parallel": 1,
                         "quantize_int8": True})
    model.train(tr, TrainContext(devices=list(jax.devices())))
    out = model.predict(["tok1 tok2 tok3"])
    assert isinstance(out[0], str) and out[0]
    eng = model.make_decode_engine(max_slots=2, max_new_tokens=4)
    eng.submit("r", "tok1 tok2", max_new=4)
    done = {}
    for _ in range(40):
        eng.step()
        done.update(dict(eng.poll()))
        if done:
            break
    assert "r" in done and isinstance(done["r"], str)
    # the engine's params really are the int8 tree
    leaves = jax.tree_util.tree_leaves(eng.engine.params)
    assert any(x.dtype == jnp.int8 for x in leaves)
    assert float(model.evaluate(tr)) > 0  # f32 eval path still works


def _assert_sp_forward_matches_plain(model, mesh_shape, batch, seed):
    """The sp forward IS the plain forward: same params, same logits
    (shared parity protocol for the ulysses and ring dispatch paths).
    A 3-element mesh_shape builds the sp×tp (data, sp, model) mesh
    with the head dim tensor-parallel sharded."""
    from jax.sharding import Mesh

    devs = list(jax.devices())
    if len(mesh_shape) == 3:
        mesh = Mesh(np.array(devs, dtype=object).reshape(*mesh_shape),
                    ("data", "sp", "model"))
        sp_mod = model._module(seq_mesh=mesh, seq_axis="sp",
                               head_axis="model")
    else:
        mesh = Mesh(np.array(devs, dtype=object).reshape(*mesh_shape),
                    ("data", "sp"))
        sp_mod = model._module(seq_mesh=mesh, seq_axis="sp")
    plain = model._module()
    params = jax.tree_util.tree_map(np.asarray, model._params)
    ids = np.random.RandomState(seed).randint(
        1, 200, size=(batch, TINY["max_len"])).astype(np.int32)
    lens = np.full((batch,), TINY["max_len"], np.int32)
    ref = np.asarray(plain.apply({"params": params}, ids, lens=lens),
                     np.float32)
    got = np.asarray(sp_mod.apply({"params": params}, ids, lens=lens),
                     np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_sp_forward_parity_untrained():
    """Default-leg sp correctness without a train loop: on random-init
    params, the sp forward equals the plain forward through the
    ulysses dispatch path ((2, 4) mesh, heads divide). The ring path's
    module-level parity rides the slow ring-fallback train test plus
    the default ops-level GQA oracle (test_ring_gqa_matches_dense)."""
    model = LlamaLoRA(**{**TINY, "model_parallel": 1})
    model._params = model._module().init(
        jax.random.PRNGKey(3),
        jnp.zeros((1, TINY["max_len"]), jnp.int32))["params"]
    _assert_sp_forward_matches_plain(model, (2, 4), batch=4, seed=0)


def test_sp_tp_forward_parity_untrained():
    """sp×tp composition (VERDICT r4 item 4): on a (data=2, sp=2,
    model=2) 3-axis mesh the head dim is tensor-parallel sharded and
    the ulysses swap runs within each TP head group (per-shard heads
    4/2=2, sp=2 divides) — logits equal the plain forward."""
    model = LlamaLoRA(**{**TINY, "model_parallel": 2,
                         "sequence_parallel": 2})
    model._params = model._module().init(
        jax.random.PRNGKey(3),
        jnp.zeros((1, TINY["max_len"]), jnp.int32))["params"]
    _assert_sp_forward_matches_plain(model, (2, 2, 2), batch=4, seed=2)


@pytest.mark.slow
def test_sp_tp_forward_parity_ring_dispatch():
    """sp×tp with per-shard heads NOT divisible by sp: (data=1, sp=4,
    model=2) leaves 2 heads per TP shard against sp=4, forcing the
    ring dispatch under a tensor-parallel head sharding."""
    model = LlamaLoRA(**{**TINY, "model_parallel": 2,
                         "sequence_parallel": 4})
    model._params = model._module().init(
        jax.random.PRNGKey(3),
        jnp.zeros((1, TINY["max_len"]), jnp.int32))["params"]
    _assert_sp_forward_matches_plain(model, (1, 4, 2), batch=2, seed=3)


@pytest.mark.slow
def test_sp_forward_parity_ring_dispatch():
    """The (1, 8) mesh forces the ring dispatch (heads=4 don't divide
    8): module-level parity for that path."""
    model = LlamaLoRA(**{**TINY, "model_parallel": 1})
    model._params = model._module().init(
        jax.random.PRNGKey(3),
        jnp.zeros((1, TINY["max_len"]), jnp.int32))["params"]
    _assert_sp_forward_matches_plain(model, (1, 8), batch=2, seed=1)


@pytest.mark.slow
def test_llama_trains_sequence_parallel(tmp_path):
    """sequence_parallel=4 over a (data=2, sp=4) mesh: every (B, L)
    train activation's sequence dim is sharded and attention runs via
    ulysses all-to-alls. Loss decreases, the frozen base stays frozen,
    the sp forward is numerically the plain forward, and the result
    serves through the unchanged decode path."""
    tr = str(tmp_path / "t.jsonl")
    generate_text_classification_dataset(tr, 128, seed=0)
    knobs = {**TINY, "model_parallel": 1, "sequence_parallel": 4,
             "max_epochs": 4}
    model = LlamaLoRA(**knobs)
    ctx = TrainContext(devices=list(jax.devices()))
    model.train(tr, ctx)
    losses = ctx.logger.get_values("loss")
    assert len(losses) >= 2 and losses[-1] < losses[0]
    fresh = LlamaLoRA(**knobs)._module().init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, TINY["max_len"]), jnp.int32))["params"]
    np.testing.assert_array_equal(
        np.asarray(model._params["block_0"]["attn"]["wq"]["kernel"]),
        np.asarray(fresh["block_0"]["attn"]["wq"]["kernel"]))
    assert float(np.abs(np.asarray(
        model._params["block_0"]["attn"]["wq"]["lora_b"])).sum()) > 0

    _assert_sp_forward_matches_plain(model, (2, 4), batch=4, seed=0)

    out = model.predict(["tok1 tok2 tok3"])
    assert isinstance(out[0], str) and out[0]


def test_llama_sequence_parallel_knob_validation(tmp_path):
    tr = str(tmp_path / "t.jsonl")
    generate_text_classification_dataset(tr, 16, seed=0)
    ctx = lambda: TrainContext(devices=list(jax.devices()))  # noqa: E731
    with pytest.raises(ValueError, match="mutually exclusive"):
        LlamaLoRA(**{**TINY, "sequence_parallel": 2, "model_parallel": 1,
                     "pipeline_stages": 2}).train(tr, ctx())
    with pytest.raises(ValueError, match="divisible"):
        # kv heads (2) don't divide model_parallel=4: TP shards whole
        # heads, so the sp×tp composition must refuse
        LlamaLoRA(**{**TINY, "sequence_parallel": 2,
                     "model_parallel": 4}).train(tr, ctx())
    with pytest.raises(ValueError, match="devices"):
        LlamaLoRA(**{**TINY, "model_parallel": 1,
                     "sequence_parallel": 3}).train(tr, ctx())
    with pytest.raises(ValueError, match="model_parallel"):
        # MoE composes with sp only on the 3-axis mesh (experts need
        # the model axis); the dp x sp mesh refuses
        LlamaLoRA(**{**TINY, "model_parallel": 1, "moe_experts": 2,
                     "sequence_parallel": 2}).train(tr, ctx())
    with pytest.raises(ValueError, match="loss_chunk"):
        # loss_chunk composes with sp (chunked_lm_loss_terms_sp) but
        # NOT with sp×tp: the sharded loss keeps the head replicated
        LlamaLoRA(**{**TINY, "model_parallel": 2, "loss_chunk": 8,
                     "sequence_parallel": 2}).train(tr, ctx())


@pytest.mark.slow
def test_llama_trains_sequence_parallel_with_tp(tmp_path):
    """sp×tp 3-axis training (VERDICT r4 item 4): sequence_parallel=2
    composed with model_parallel=2 over a (data=2, sp=2, model=2)
    mesh — TP_RULES shard the params over `model`, activations shard
    L over `sp`, and the loss still decreases. The trained result
    matches the plain forward and serves through the unchanged
    decode path."""
    tr = str(tmp_path / "t.jsonl")
    generate_text_classification_dataset(tr, 128, seed=0)
    knobs = {**TINY, "model_parallel": 2, "sequence_parallel": 2,
             "max_epochs": 4}
    model = LlamaLoRA(**knobs)
    ctx = TrainContext(devices=list(jax.devices()))
    model.train(tr, ctx)
    losses = ctx.logger.get_values("loss")
    assert len(losses) >= 2 and losses[-1] < losses[0]
    fresh = LlamaLoRA(**knobs)._module().init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, TINY["max_len"]), jnp.int32))["params"]
    np.testing.assert_array_equal(
        np.asarray(model._params["block_0"]["attn"]["wq"]["kernel"]),
        np.asarray(fresh["block_0"]["attn"]["wq"]["kernel"]))
    assert float(np.abs(np.asarray(
        model._params["block_0"]["attn"]["wq"]["lora_b"])).sum()) > 0

    _assert_sp_forward_matches_plain(model, (2, 2, 2), batch=4, seed=0)

    out = model.predict(["tok1 tok2 tok3"])
    assert isinstance(out[0], str) and out[0]


def test_chunked_lm_loss_sp_matches_dense():
    """The sequence-parallel chunked loss (VERDICT r4 weak #5's last
    exclusivity): value/count/grads equal the dense lm_loss_terms with
    L sharded over an (data=2, sp=4) mesh — targets shift globally
    before partitioning, each shard streams its own chunks, one scalar
    psum combines."""
    from jax.sharding import Mesh

    from rafiki_tpu.models.llama_lora import (chunked_lm_loss_terms_sp,
                                              lm_loss_terms)

    b, L, d, vocab = 4, 32, 16, 64
    rng = np.random.default_rng(0)
    hidden = jnp.asarray(rng.normal(size=(b, L, d)), jnp.float32)
    kernel = jnp.asarray(rng.normal(size=(d, vocab)), jnp.float32)
    ids = jnp.asarray(rng.integers(1, vocab, size=(b, L)), jnp.int32)
    lens = jnp.asarray([L, 20, 7, L], jnp.int32)
    mask = jnp.asarray([1, 1, 0, 1], jnp.int32)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                ("data", "sp"))

    def dense(h, k):
        logits = h @ k
        return lm_loss_terms(logits, ids, lens, mask)

    def sharded(h, k):
        return chunked_lm_loss_terms_sp(h, k, ids, lens, mask, 4,
                                        mesh, "data", "sp")

    t_d, c_d = dense(hidden, kernel)
    t_s, c_s = sharded(hidden, kernel)
    np.testing.assert_allclose(float(t_s), float(t_d), rtol=1e-5)
    assert int(c_s) == int(c_d)

    g_d = jax.grad(lambda h, k: dense(h, k)[0], argnums=(0, 1))(
        hidden, kernel)
    g_s = jax.grad(lambda h, k: sharded(h, k)[0], argnums=(0, 1))(
        hidden, kernel)
    for a, b_ in zip(g_s, g_d):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=1e-5, rtol=1e-5)


@pytest.mark.slow
def test_llama_trains_moe_with_sp_tp(tmp_path):
    """MoE x sp x tp: experts shard over `model`, activations shard L
    over `sp` on the 3-axis mesh (the dp x sp mesh lacks the expert
    axis and still refuses). Loss finite and decreasing
    (quick_train caps epochs at 2, enough for the tiny set); the
    forward is parity-exact vs the plain module
    (test_moe_sp_tp_forward_parity)."""
    tr = str(tmp_path / "t.jsonl")
    generate_text_classification_dataset(tr, 64, seed=0)
    knobs = {**TINY, "model_parallel": 2, "sequence_parallel": 2,
             "moe_experts": 2, "max_epochs": 2, "quick_train": True}
    model = LlamaLoRA(**knobs)
    ctx = TrainContext(devices=list(jax.devices()))
    model.train(tr, ctx)
    losses = ctx.logger.get_values("loss")
    assert len(losses) >= 2 and np.isfinite(losses[-1])
    assert losses[-1] < losses[0], losses
    out = model.predict(["tok1 tok2 tok3"])
    assert isinstance(out[0], str) and out[0]


@pytest.mark.slow
def test_llama_trains_sequence_parallel_with_chunked_loss(tmp_path):
    """sp=2 + loss_chunk through the template: the train step streams
    each shard's own loss chunks (no per-chunk re-gather); loss
    decreases and the result serves."""
    tr = str(tmp_path / "t.jsonl")
    generate_text_classification_dataset(tr, 64, seed=0)
    knobs = {**TINY, "model_parallel": 1, "sequence_parallel": 2,
             "loss_chunk": 8, "max_epochs": 2, "quick_train": True}
    model = LlamaLoRA(**knobs)
    ctx = TrainContext(devices=list(jax.devices()))
    model.train(tr, ctx)
    losses = ctx.logger.get_values("loss")
    assert losses and np.isfinite(losses[-1])
    out = model.predict(["tok1 tok2 tok3"])
    assert isinstance(out[0], str) and out[0]


@pytest.mark.slow
def test_llama_sequence_parallel_ring_fallback(tmp_path):
    """sp=8 with n_heads=4: heads don't split over the axis, so the
    decoder's attention auto-falls-back from ulysses to ring K/V
    rotation — training still works and the sp forward still equals
    the plain forward."""
    tr = str(tmp_path / "t.jsonl")
    generate_text_classification_dataset(tr, 64, seed=0)
    knobs = {**TINY, "model_parallel": 1, "sequence_parallel": 8,
             "max_epochs": 2, "quick_train": True}
    model = LlamaLoRA(**knobs)
    ctx = TrainContext(devices=list(jax.devices()))
    model.train(tr, ctx)
    losses = ctx.logger.get_values("loss")
    assert losses and np.isfinite(losses[-1])

    _assert_sp_forward_matches_plain(model, (1, 8), batch=2, seed=1)


def test_rope_theta_knob_changes_positions_not_params():
    """rope_theta must alter long-range position handling (different
    logits at distant positions) without touching the param tree —
    Llama-3 checkpoints (theta=500000) load into the same structure as
    Llama-2 (10000), and a mismatched theta is a silent quality bug
    the knob exists to prevent."""
    m1 = Llama(vocab_size=128, max_len=32, hidden_dim=32, depth=1,
               n_heads=4, n_kv_heads=2, mlp_dim=64, lora_rank=2)
    m3 = Llama(vocab_size=128, max_len=32, hidden_dim=32, depth=1,
               n_heads=4, n_kv_heads=2, mlp_dim=64, lora_rank=2,
               rope_theta=500000.0)
    ids = np.arange(1, 25, dtype=np.int32)[None, :]
    params = m1.init(jax.random.PRNGKey(0), ids)["params"]
    # identical tree: theta is positional math, not a parameter
    jax.tree_util.tree_all(jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b), params,
        m3.init(jax.random.PRNGKey(0), ids)["params"]))
    o1 = np.asarray(m1.apply({"params": params}, ids), np.float32)
    o3 = np.asarray(m3.apply({"params": params}, ids), np.float32)
    assert not np.allclose(o1, o3), "theta had no effect"
    # the template threads the knob through
    model = LlamaLoRA(**{**TINY, "rope_theta": 500000.0})
    assert model._module().rope_theta == 500000.0


def test_rope_scaling_llama31_formula():
    """rope() with Llama-3.1 scaling matches the published recipe:
    high-frequency components unchanged, very low frequencies divided
    by factor, smooth interpolation between — verified against a
    direct numpy implementation, plus knob-string parsing."""
    from rafiki_tpu.models.llama_lora import _parse_rope_scaling, rope

    scaling = (8.0, 1.0, 4.0, 8192.0)
    theta = 500000.0
    d = 64
    x = np.random.RandomState(0).randn(1, 4, 2, d).astype(np.float32)
    pos = np.asarray([[0, 1000, 4000, 7000]], np.int32)

    got = np.asarray(rope(jnp.asarray(x), jnp.asarray(pos),
                          theta=theta, scaling=scaling))

    # direct reference implementation of the published recipe
    half = d // 2
    freqs = theta ** (-np.arange(0, half, dtype=np.float64) / half)
    factor, lo, hi, orig = scaling
    wavelen = 2 * np.pi / freqs
    ratio = orig / wavelen
    smooth = np.clip((ratio - lo) / (hi - lo), 0.0, 1.0)
    new = np.where(ratio < lo, freqs / factor,
                   np.where(ratio > hi, freqs,
                            (1 - smooth) * freqs / factor
                            + smooth * freqs))
    ang = pos[..., None].astype(np.float64) * new
    cos, sin = np.cos(ang)[:, :, None, :], np.sin(ang)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    ref = np.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                         axis=-1)
    # rope computes angles in f32, the reference in f64: at position
    # 7000 the rounding shows up at ~3e-4 after the trig
    np.testing.assert_allclose(got, ref, atol=1e-3, rtol=1e-3)
    # the highest-frequency component is untouched; the lowest scaled
    assert new[0] == freqs[0] and abs(new[-1] - freqs[-1] / 8) < 1e-12

    # knob plumbing: JSON string and dict both parse; template threads
    assert _parse_rope_scaling(
        '{"factor": 8, "original_max_position_embeddings": 8192}'
    ) == (8.0, 1.0, 4.0, 8192.0)
    model = LlamaLoRA(**{**TINY, "rope_theta": 500000.0,
                         "rope_scaling": '{"factor": 8}'})
    assert model._module().rope_scaling == (8.0, 1.0, 4.0, 8192.0)


def test_rope_scaling_rejects_unsupported_types():
    from rafiki_tpu.models.llama_lora import _parse_rope_scaling

    with pytest.raises(ValueError, match="unsupported"):
        _parse_rope_scaling('{"type": "linear", "factor": 4}')
    with pytest.raises(ValueError, match="unsupported"):
        _parse_rope_scaling({"rope_type": "yarn", "factor": 8})
    with pytest.raises(ValueError, match="factor"):
        _parse_rope_scaling({"rope_type": "llama3"})
    # llama3 passes; explicit 'default' means UNSCALED (HF semantics)
    assert _parse_rope_scaling(
        {"rope_type": "llama3", "factor": 8}) is not None
    assert _parse_rope_scaling(
        {"rope_type": "default", "factor": 8}) is None
