"""Llama LoRA family: module, LoRA freezing, 2-D sharding, generation."""

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from rafiki_tpu.constants import TaskType
from rafiki_tpu.data import generate_text_classification_dataset
from rafiki_tpu.model import TrainContext, test_model_class
from rafiki_tpu.models.llama_lora import (Llama, LlamaLoRA, greedy_generate,
                                          lora_trainable_mask)


TINY = {"max_epochs": 6, "vocab_size": 1 << 14, "hidden_dim": 64,
        "depth": 2, "n_heads": 4, "kv_ratio": 2, "lora_rank": 4,
        "max_len": 32, "model_parallel": 2, "learning_rate": 1e-2,
        "batch_size": 16, "bf16": False, "quick_train": False,
        "share_params": False, "tokenizer_path": "", "pretrained_path": ""}


def _tiny_module(vocab=256, max_len=16, rank=2):
    return Llama(vocab_size=vocab, max_len=max_len, hidden_dim=32, depth=2,
                 n_heads=4, n_kv_heads=2, mlp_dim=64, lora_rank=rank)


def test_llama_module_shapes():
    m = _tiny_module()
    ids = np.ones((2, 16), np.int32)
    params = m.init(jax.random.PRNGKey(0), ids)["params"]
    out = m.apply({"params": params}, ids)
    assert out.shape == (2, 16, 256)


def test_lora_mask_freezes_base():
    m = _tiny_module()
    ids = np.ones((2, 16), np.int32)
    params = m.init(jax.random.PRNGKey(0), ids)["params"]
    mask = lora_trainable_mask(params)
    flat = jax.tree_util.tree_flatten_with_path(mask)[0]
    by_path = {"/".join(str(getattr(k, "key", k)) for k in kp): v
               for kp, v in flat}
    assert by_path["block_0/attn/wq/lora_a"] is True
    assert by_path["block_0/attn/wq/kernel"] is False
    assert by_path["tok_embed/embedding"] is False
    assert by_path["lm_head/kernel"] is True
    assert any("final_norm" in p and v for p, v in by_path.items())
    # flax auto-names block RMSNorms "RMSNorm_0"/"RMSNorm_1" — they must
    # train too (the LoRA recipe tunes norms)
    assert any("RMSNorm" in p and v for p, v in by_path.items())


@pytest.mark.slow
def test_greedy_generate_matches_full_forward():
    """Cache decode must reproduce the full-forward next-token argmax."""
    m = _tiny_module(max_len=24)
    rng = np.random.default_rng(0)
    prompt = rng.integers(2, 256, size=(2, 6)).astype(np.int32)
    lens = np.asarray([6, 4], np.int32)
    params = m.init(jax.random.PRNGKey(1), prompt)["params"]

    out = np.asarray(greedy_generate(m, params, prompt, lens, max_new=3))
    assert out.shape == (2, 3)

    # oracle for example 0 (full-length prompt): step the full forward
    ids = list(prompt[0])
    for step in range(3):
        seq = np.asarray(ids, np.int32)[None, :]
        logits = m.apply({"params": params}, seq,
                         lens=jnp.asarray([len(ids)], jnp.int32))
        nxt = int(np.argmax(np.asarray(logits[0, len(ids) - 1],
                                       np.float32)))
        assert nxt == int(out[0, step]), f"mismatch at step {step}"
        ids.append(nxt)


@pytest.mark.slow
def test_llama_trains_2d_sharded(tmp_path):
    """fsdp × tensor (4×2) over 8 virtual devices; loss decreases and the
    frozen base stays bit-identical."""
    tr = str(tmp_path / "t.jsonl")
    generate_text_classification_dataset(tr, 128, seed=0)
    model = LlamaLoRA(**TINY)
    ctx = TrainContext(devices=list(jax.devices()))

    # snapshot a base kernel before training to prove freezing
    model.train(tr, ctx)
    losses = ctx.logger.get_values("loss")
    assert len(losses) >= 2 and losses[-1] < losses[0]

    params = model.dump_parameters()["params"]
    m2 = LlamaLoRA(**TINY)
    fresh = m2._module().init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, TINY["max_len"]), jnp.int32))["params"]
    np.testing.assert_array_equal(
        np.asarray(params["block_0"]["attn"]["wq"]["kernel"]),
        np.asarray(fresh["block_0"]["attn"]["wq"]["kernel"]))
    # ...while the LoRA adapters actually moved
    assert float(np.abs(np.asarray(
        params["block_0"]["attn"]["wq"]["lora_b"])).sum()) > 0


@pytest.mark.slow
def test_llama_template_contract(tmp_path):
    tr, va = str(tmp_path / "t.jsonl"), str(tmp_path / "v.jsonl")
    generate_text_classification_dataset(tr, 128, seed=0)
    generate_text_classification_dataset(va, 32, seed=1)
    preds = test_model_class(LlamaLoRA, TaskType.LANGUAGE_MODELING,
                             tr, va, queries=["tok1 tok2 tok3"], knobs=TINY)
    assert len(preds) == 1 and isinstance(preds[0], str)


def test_llama_bf16_compute_keeps_f32_params():
    m = Llama(vocab_size=128, max_len=16, hidden_dim=32, depth=1,
              n_heads=4, n_kv_heads=2, mlp_dim=64, lora_rank=2,
              dtype=jnp.bfloat16)
    ids = jnp.ones((2, 8), jnp.int32)
    params = m.init(jax.random.PRNGKey(0), ids)["params"]
    assert all(p.dtype == jnp.float32
               for p in jax.tree_util.tree_leaves(params))
    _, state = m.apply({"params": params}, ids,
                       capture_intermediates=True)
    block_out = state["intermediates"]["block_0"]["__call__"][0]
    assert block_out.dtype == jnp.bfloat16, block_out.dtype
