"""Paged-native flash decode kernel — LSE partial-softmax equivalence.

Property tests for ``ops/paged_attention.py``: the Pallas kernel (run
through the interpreter so CPU tier-1 exercises the REAL kernel math,
not a fallback) must match the pure-XLA page-gather oracle across page
counts, partial last pages, scratch-page garbage, GQA ratios, head
tiles, int8 scale rows, and bf16 pools. The oracle is the same math
``_DecoderAttention``'s gather path computes, which is what makes the
engine-level kernel-vs-gather bit-exactness in ``test_paged_kv.py``
plausible rather than lucky.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from rafiki_tpu.ops.paged_attention import (_paged_attention_reference,
                                            _paged_window_reference,
                                            paged_decode_attention,
                                            paged_window_attention,
                                            resolve_paged_kernel,
                                            resolve_paged_window_kernel)


def _setup(positions, n_kv=2, rep=2, dh=8, ps=8, n_tables=4,
           n_pages=12, seed=0, int8=False, scale=1.0, dtype=np.float32):
    """Random pools + a permuted block table per slot: live pages drawn
    from a shuffled free list (page 0 never live — the engine's scratch
    invariant), dead entries left at 0. Scratch page filled with large
    garbage so any leak past the position mask is loud."""
    rng = np.random.default_rng(seed)
    b = len(positions)
    heads = n_kv * rep
    q = (rng.normal(size=(b, heads, dh)) * scale).astype(dtype)
    if int8:
        kp = rng.integers(-127, 128,
                          size=(n_pages, ps, n_kv, dh)).astype(np.int8)
        vp = rng.integers(-127, 128,
                          size=(n_pages, ps, n_kv, dh)).astype(np.int8)
        ks = rng.uniform(1e-3, 0.1,
                         size=(n_pages, ps, n_kv)).astype(np.float32)
        vs = rng.uniform(1e-3, 0.1,
                         size=(n_pages, ps, n_kv)).astype(np.float32)
        scales = (ks, vs)
    else:
        kp = (rng.normal(size=(n_pages, ps, n_kv, dh))
              * scale).astype(dtype)
        vp = (rng.normal(size=(n_pages, ps, n_kv, dh))
              * scale).astype(dtype)
        kp[0], vp[0] = 1e3, -1e3  # scratch garbage: leaks are loud
        scales = None
    t = np.asarray(positions, np.int32)
    tabs = np.zeros((b, n_tables), np.int32)
    free = list(rng.permutation(np.arange(1, n_pages)))
    for i in range(b):
        for pg in range(int(t[i]) // ps + 1):
            tabs[i, pg] = free.pop()
    return q, kp, vp, tabs, t, scales


def _both(q, kp, vp, tabs, t, scales=None, **kw):
    sm = 1.0 / np.sqrt(q.shape[-1])
    sk, sv = scales if scales else (None, None)
    out = paged_decode_attention(q, kp, vp, tabs, t, sm_scale=sm,
                                 k_scale=sk, v_scale=sv,
                                 interpret=True, **kw)
    ref = _paged_attention_reference(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(tabs), t, sm,
        None if sk is None else jnp.asarray(sk),
        None if sv is None else jnp.asarray(sv))
    return np.asarray(out, np.float32), np.asarray(ref, np.float32)


@pytest.mark.parametrize("positions", [
    [0, 0, 0, 0],          # single live key, page count 1
    [3, 5, 1, 6],          # partial first page everywhere
    [7, 8, 15, 16],        # exact page boundaries and first-past-it
    [0, 7, 12, 31],        # mixed: 1..4 live pages, full last table
])
def test_kernel_matches_reference_across_page_counts(positions):
    q, kp, vp, tabs, t, _ = _setup(positions)
    out, ref = _both(q, kp, vp, tabs, t)
    np.testing.assert_allclose(out, ref, atol=2e-6, rtol=1e-5)


def test_scratch_page_garbage_never_leaks():
    """Dead table entries point at pool page 0 (the engine's scratch
    page). Its 1e3-magnitude garbage must not move the output: the
    kernel skips dead pages entirely and masks the live tail, so the
    answer equals an oracle run over a pool whose scratch page is
    ZEROED (not merely the garbage oracle agreeing with itself)."""
    q, kp, vp, tabs, t, _ = _setup([2, 9, 17, 30])
    out, _ = _both(q, kp, vp, tabs, t)
    kz, vz = kp.copy(), vp.copy()
    kz[0], vz[0] = 0.0, 0.0
    ref0 = np.asarray(_paged_attention_reference(
        jnp.asarray(q), jnp.asarray(kz), jnp.asarray(vz),
        jnp.asarray(tabs), t, 1.0 / np.sqrt(q.shape[-1])), np.float32)
    np.testing.assert_allclose(out, ref0, atol=2e-6, rtol=1e-5)


def test_live_width_table_slice_matches_full_width():
    """The engine passes its live-width table slice; the kernel's
    answer must not depend on how many dead columns ride along."""
    q, kp, vp, tabs, t, _ = _setup([5, 9, 2, 0], n_tables=8)
    full, _ = _both(q, kp, vp, tabs, t)
    narrow, _ = _both(q, kp, vp, tabs[:, :2], t)
    np.testing.assert_allclose(full, narrow, atol=2e-6, rtol=1e-5)


def test_lse_merge_across_magnitude_spread():
    """Pages with wildly different score magnitudes: the cross-page
    LSE merge must stay stable where a naive sum-of-exps would
    overflow/underflow."""
    q, kp, vp, tabs, t, _ = _setup([31, 31, 31, 31], n_pages=20,
                                   scale=1.0)
    # scale each LIVE page's keys by 10^page so the running max moves
    # on every merge step
    for i in range(tabs.shape[0]):
        for pg in range(4):
            kp[tabs[i, pg]] *= 10.0 ** pg
    out, ref = _both(q, kp, vp, tabs, t)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-4)


def test_gqa_ratios_and_block_h():
    """rep in {1, 2, 4} (MHA through 4:1 GQA) and the block_h kv-head
    tile both reproduce the oracle; an indivisible block_h fails
    loudly like flash_attention's."""
    for n_kv, rep in ((4, 1), (2, 2), (1, 4)):
        q, kp, vp, tabs, t, _ = _setup([4, 11, 19, 26], n_kv=n_kv,
                                       rep=rep, seed=n_kv)
        out, ref = _both(q, kp, vp, tabs, t)
        np.testing.assert_allclose(out, ref, atol=2e-6, rtol=1e-5)
    q, kp, vp, tabs, t, _ = _setup([4, 11, 19, 26], n_kv=4, rep=2)
    out, ref = _both(q, kp, vp, tabs, t, block_h=2)
    np.testing.assert_allclose(out, ref, atol=2e-6, rtol=1e-5)
    with pytest.raises(ValueError, match="block_h"):
        paged_decode_attention(q, kp, vp, tabs, t, sm_scale=0.3,
                               block_h=3, interpret=True)


def test_int8_scale_rows_dequant_in_kernel():
    """int8 pools + per-(page, pos, head) f32 absmax scale rows: the
    fused in-kernel dequant matches the dequantize-then-attend oracle
    (both accumulate in f32)."""
    q, kp, vp, tabs, t, scales = _setup([3, 8, 16, 30], int8=True)
    out, ref = _both(q, kp, vp, tabs, t, scales=scales)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-4)


def test_bf16_pools_and_output_dtype():
    q, kp, vp, tabs, t, _ = _setup([6, 13, 22, 31], dtype=np.float32)
    qb = jnp.asarray(q, jnp.bfloat16)
    kb, vb = jnp.asarray(kp, jnp.bfloat16), jnp.asarray(vp, jnp.bfloat16)
    sm = 1.0 / np.sqrt(q.shape[-1])
    out = paged_decode_attention(qb, kb, vb, tabs, t, sm_scale=sm,
                                 interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = _paged_attention_reference(qb, kb, vb, jnp.asarray(tabs), t, sm)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=2e-2, rtol=2e-2)


def test_kernel_composes_with_jit():
    """The serving engine calls the kernel from inside jitted step
    programs — the pallas_call must trace cleanly under jit with the
    positions/table as traced operands."""
    q, kp, vp, tabs, t, _ = _setup([2, 9, 17, 30])
    sm = 1.0 / np.sqrt(q.shape[-1])

    @jax.jit
    def step(q, kp, vp, tabs, t):
        return paged_decode_attention(q, kp, vp, tabs, t, sm_scale=sm,
                                      interpret=True)

    out = np.asarray(step(q, kp, vp, tabs, t), np.float32)
    _, ref = _both(q, kp, vp, tabs, t)
    np.testing.assert_allclose(out, ref, atol=2e-6, rtol=1e-5)


def test_resolve_paged_kernel_dispatch_rule():
    """None = auto (kernel only on TPU — CPU tier-1 must resolve to
    the gather fallback); explicit booleans always win."""
    auto = resolve_paged_kernel(None)
    assert auto == (jax.default_backend() == "tpu")
    assert resolve_paged_kernel(True) is True
    assert resolve_paged_kernel(False) is False


# ---------------------------------------------------------------------
# multi-token WINDOW kernel (ISSUE 19): chunked prefill and speculative
# verify attend (s >= 1) query windows straight off the pool, causal
# INSIDE the window
# ---------------------------------------------------------------------


def _wsetup(positions, n_kv=2, rep=2, dh=8, ps=8, n_tables=4,
            n_pages=12, seed=0, int8=False, scale=1.0,
            dtype=np.float32):
    """Window twin of ``_setup``: ``positions`` is (b, s) with
    NONDECREASING rows (the engine's window invariant). Live pages
    cover each row's maximum position; scratch page 0 carries loud
    garbage."""
    t = np.asarray(positions, np.int32)
    b, s = t.shape
    rng = np.random.default_rng(seed)
    heads = n_kv * rep
    q = (rng.normal(size=(b, s, heads, dh)) * scale).astype(dtype)
    if int8:
        kp = rng.integers(-127, 128,
                          size=(n_pages, ps, n_kv, dh)).astype(np.int8)
        vp = rng.integers(-127, 128,
                          size=(n_pages, ps, n_kv, dh)).astype(np.int8)
        ks = rng.uniform(1e-3, 0.1,
                         size=(n_pages, ps, n_kv)).astype(np.float32)
        vs = rng.uniform(1e-3, 0.1,
                         size=(n_pages, ps, n_kv)).astype(np.float32)
        scales = (ks, vs)
    else:
        kp = (rng.normal(size=(n_pages, ps, n_kv, dh))
              * scale).astype(dtype)
        vp = (rng.normal(size=(n_pages, ps, n_kv, dh))
              * scale).astype(dtype)
        kp[0], vp[0] = 1e3, -1e3  # scratch garbage: leaks are loud
        scales = None
    tabs = np.zeros((b, n_tables), np.int32)
    free = list(rng.permutation(np.arange(1, n_pages)))
    for i in range(b):
        for pg in range(int(t[i].max()) // ps + 1):
            tabs[i, pg] = free.pop()
    return q, kp, vp, tabs, t, scales


def _wboth(q, kp, vp, tabs, t, scales=None, **kw):
    sm = 1.0 / np.sqrt(q.shape[-1])
    sk, sv = scales if scales else (None, None)
    out = paged_window_attention(q, kp, vp, tabs, t, sm_scale=sm,
                                 k_scale=sk, v_scale=sv,
                                 interpret=True, **kw)
    ref = _paged_window_reference(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(tabs), t, sm,
        None if sk is None else jnp.asarray(sk),
        None if sv is None else jnp.asarray(sv))
    return np.asarray(out, np.float32), np.asarray(ref, np.float32)


@pytest.mark.parametrize("positions", [
    [[0, 1, 2, 3], [0, 1, 2, 3]],          # fresh prompts from zero
    [[3, 4, 5, 6], [1, 2, 3, 4]],          # partial first page
    [[5, 6, 7, 8], [13, 14, 15, 16]],      # window STRADDLES a page
                                           # boundary (7→8, 15→16)
    [[20, 21, 22, 23], [9, 9, 9, 9]],      # deep window + frozen row
                                           # (an idle verify lane)
    [[0, 1, 1, 1], [26, 27, 28, 28]],      # overhang rows repeating
                                           # the last real entry
])
def test_window_causal_mask_matches_reference(positions):
    """Per-ROW causality: window token i sees keys only up to its OWN
    position — including rows mid-page, rows at page boundaries, and
    frozen/padded rows."""
    q, kp, vp, tabs, t, _ = _wsetup(positions)
    out, ref = _wboth(q, kp, vp, tabs, t)
    np.testing.assert_allclose(out, ref, atol=2e-6, rtol=1e-5)


def test_window_scratch_garbage_never_leaks():
    """The in-window causal mask must keep every row clear of the
    scratch page's 1e3 garbage AND of later tokens' freshly-written
    keys: the kernel answer equals an oracle over a pool whose scratch
    page is ZEROED."""
    q, kp, vp, tabs, t, _ = _wsetup([[2, 3, 4, 5], [14, 15, 16, 17],
                                     [25, 26, 27, 28]])
    sm = 1.0 / np.sqrt(q.shape[-1])
    out = np.asarray(paged_window_attention(
        q, kp, vp, tabs, t, sm_scale=sm, interpret=True), np.float32)
    kz, vz = kp.copy(), vp.copy()
    kz[0], vz[0] = 0.0, 0.0
    ref0 = np.asarray(_paged_window_reference(
        jnp.asarray(q), jnp.asarray(kz), jnp.asarray(vz),
        jnp.asarray(tabs), t, sm), np.float32)
    np.testing.assert_allclose(out, ref0, atol=2e-6, rtol=1e-5)


def test_window_partial_last_pages_and_live_width():
    """Rows whose last live page is partial, plus the live-width table
    slice: the answer must not depend on dead trailing columns."""
    pos = [[9, 10, 11, 12], [1, 2, 3, 4]]
    q, kp, vp, tabs, t, _ = _wsetup(pos, n_tables=8)
    full, ref = _wboth(q, kp, vp, tabs, t)
    np.testing.assert_allclose(full, ref, atol=2e-6, rtol=1e-5)
    narrow, _ = _wboth(q, kp, vp, tabs[:, :2], t)
    np.testing.assert_allclose(full, narrow, atol=2e-6, rtol=1e-5)


def test_window_lse_merge_across_magnitude_spread():
    """Cross-page LSE merge stability with a per-row mask in play:
    live pages scaled by 10^page move the running max on every merge
    step for every window row."""
    q, kp, vp, tabs, t, _ = _wsetup([[28, 29, 30, 31]] * 4, n_pages=20)
    for i in range(tabs.shape[0]):
        for pg in range(4):
            kp[tabs[i, pg]] *= 10.0 ** pg
    out, ref = _wboth(q, kp, vp, tabs, t)
    # keys span 3 decades; the merge reorders the reduction, so allow
    # a touch more roundoff than the unscaled cases
    np.testing.assert_allclose(out, ref, atol=5e-5, rtol=1e-3)


def test_window_gqa_ratios_block_h_and_block_q():
    """rep in {1, 2, 4} × head tiling × window tiling all reproduce
    the oracle; indivisible block_q fails as loudly as block_h."""
    pos = [[4, 5, 6, 7, 8, 9], [17, 18, 19, 20, 21, 22]]
    for n_kv, rep in ((4, 1), (2, 2), (1, 4)):
        q, kp, vp, tabs, t, _ = _wsetup(pos, n_kv=n_kv, rep=rep,
                                        seed=n_kv)
        out, ref = _wboth(q, kp, vp, tabs, t)
        np.testing.assert_allclose(out, ref, atol=2e-6, rtol=1e-5)
    q, kp, vp, tabs, t, _ = _wsetup(pos, n_kv=4, rep=2)
    for bq in (1, 2, 3, 6):
        out, ref = _wboth(q, kp, vp, tabs, t, block_h=2, block_q=bq)
        np.testing.assert_allclose(out, ref, atol=2e-6, rtol=1e-5)
    with pytest.raises(ValueError, match="block_q"):
        paged_window_attention(q, kp, vp, tabs, t, sm_scale=0.3,
                               block_q=4, interpret=True)
    with pytest.raises(ValueError, match="block_h"):
        paged_window_attention(q, kp, vp, tabs, t, sm_scale=0.3,
                               block_h=3, interpret=True)


def test_window_int8_scale_rows_dequant_in_kernel():
    """int8 pools + f32 absmax scale rows through the window kernel:
    fused dequant matches the dequantize-then-attend oracle."""
    q, kp, vp, tabs, t, scales = _wsetup([[3, 4, 5, 6], [13, 14, 15, 16],
                                          [27, 28, 29, 30]], int8=True)
    out, ref = _wboth(q, kp, vp, tabs, t, scales=scales)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-4)


def test_window_s1_degenerate_bit_identical_to_step_kernel():
    """s == 1 through the window kernel is the SAME computation as the
    step kernel — same op shapes, same order — so outputs must be
    bit-for-bit identical, f32 and int8 alike. This is what lets the
    engine keep its hot loop on the step kernel while the window
    kernel serves everything else."""
    for int8 in (False, True):
        q, kp, vp, tabs, t, scales = _setup([2, 9, 17, 30], int8=int8,
                                            seed=int(int8))
        sm = 1.0 / np.sqrt(q.shape[-1])
        sk, sv = scales if scales else (None, None)
        step = paged_decode_attention(q, kp, vp, tabs, t, sm_scale=sm,
                                      k_scale=sk, v_scale=sv,
                                      interpret=True)
        win = paged_window_attention(q[:, None], kp, vp, tabs, t[:, None],
                                     sm_scale=sm, k_scale=sk, v_scale=sv,
                                     interpret=True)
        assert np.array_equal(np.asarray(step), np.asarray(win[:, 0])), \
            f"int8={int8}: window(s=1) diverged from the step kernel"


def test_window_composes_with_jit():
    """Prefill/verify programs call the window kernel from inside jit
    with traced positions/tables — must trace cleanly."""
    q, kp, vp, tabs, t, _ = _wsetup([[2, 3, 4, 5], [14, 15, 16, 17]])
    sm = 1.0 / np.sqrt(q.shape[-1])

    @jax.jit
    def win(q, kp, vp, tabs, t):
        return paged_window_attention(q, kp, vp, tabs, t, sm_scale=sm,
                                      interpret=True)

    out = np.asarray(win(q, kp, vp, tabs, t), np.float32)
    _, ref = _wboth(q, kp, vp, tabs, t)
    np.testing.assert_allclose(out, ref, atol=2e-6, rtol=1e-5)


def test_resolve_paged_window_kernel_rule(monkeypatch):
    """Windows follow the same tri-state flag as the step kernel, with
    the RAFIKI_PAGED_KERNEL_WINDOWS escape hatch on top: unset/enabled
    means windows go wherever the step kernel goes; 0/false/off forces
    step-only mode."""
    monkeypatch.delenv("RAFIKI_PAGED_KERNEL_WINDOWS", raising=False)
    assert resolve_paged_window_kernel(True) is True
    assert resolve_paged_window_kernel(False) is False
    assert resolve_paged_window_kernel(None) == resolve_paged_kernel(None)
    for off in ("0", "false", "off"):
        monkeypatch.setenv("RAFIKI_PAGED_KERNEL_WINDOWS", off)
        assert resolve_paged_window_kernel(True) is False
    monkeypatch.setenv("RAFIKI_PAGED_KERNEL_WINDOWS", "1")
    assert resolve_paged_window_kernel(True) is True
