"""Pallas kernels vs pure-XLA oracles.

Every call passes ``interpret=True`` explicitly: off-TPU the default
dispatch in ``rafiki_tpu.ops`` routes to the XLA path (fast model tests),
so equivalence tests must force the kernels through the interpreter to
actually exercise them."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rafiki_tpu.ops.attention import (_attention_reference, flash_attention,
                                      mha)
from rafiki_tpu.ops.patch_embed import (extract_patches, matmul_bias,
                                        patch_embed)


def _rand(*shape, key=0, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype)


@pytest.mark.parametrize("s_q,s_kv", [(128, 128), (100, 100), (197, 197),
                                      (64, 256)])
def test_flash_attention_matches_reference(s_q, s_kv):
    q = _rand(2, 4, s_q, 64, key=0)
    k = _rand(2, 4, s_kv, 64, key=1)
    v = _rand(2, 4, s_kv, 64, key=2)
    out = flash_attention(q, k, v, interpret=True)
    ref = _attention_reference(q, k, v, 1.0 / np.sqrt(64), False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_attention_causal():
    q = _rand(1, 2, 130, 32, key=0)
    k = _rand(1, 2, 130, 32, key=1)
    v = _rand(1, 2, 130, 32, key=2)
    out = flash_attention(q, k, v, None, True, interpret=True)
    ref = _attention_reference(q, k, v, 1.0 / np.sqrt(32), True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_attention_kv_lens_matches_reference():
    q = _rand(3, 2, 96, 32, key=0)
    k = _rand(3, 2, 96, 32, key=1)
    v = _rand(3, 2, 96, 32, key=2)
    lens = jnp.asarray([96, 17, 50], jnp.int32)
    out = flash_attention(q, k, v, kv_lens=lens, interpret=True)
    ref = _attention_reference(q, k, v, 1.0 / np.sqrt(32), False, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_flash_attention_kv_lens_grads():
    # slow leg: default varlen-grad coverage rides
    # test_flash_attention_varlen_grads_multiblock_and_empty
    q = _rand(2, 2, 40, 16, key=3)
    k = _rand(2, 2, 40, 16, key=4)
    v = _rand(2, 2, 40, 16, key=5)
    lens = jnp.asarray([40, 9], jnp.int32)

    def f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, kv_lens=lens, interpret=True) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(
            _attention_reference(q, k, v, 1.0 / np.sqrt(16), False,
                                 lens) ** 2)

    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)
    # masked-out keys get zero gradient
    assert float(jnp.abs(g[1][1, :, 9:, :]).max()) < 1e-6


def test_flash_attention_kv_lens_under_jit_and_causal():
    q = _rand(2, 2, 64, 16, key=6)
    k = _rand(2, 2, 64, 16, key=7)
    v = _rand(2, 2, 64, 16, key=8)
    lens = jnp.asarray([30, 64], jnp.int32)

    @jax.jit
    def run(q, k, v, lens):
        return flash_attention(q, k, v, causal=True, kv_lens=lens, interpret=True)

    out = run(q, k, v, lens)
    ref = _attention_reference(q, k, v, 1.0 / np.sqrt(16), True, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_flash_attention_grads():
    # slow leg: default full-path grad coverage rides the causal
    # multiblock and multihead-block grad oracles
    q = _rand(1, 2, 64, 32, key=0)
    k = _rand(1, 2, 64, 32, key=1)
    v = _rand(1, 2, 64, 32, key=2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(
            _attention_reference(q, k, v, 1.0 / np.sqrt(32), False) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("block_h,causal", [(2, False), (4, True)])
def test_flash_attention_multihead_block_matches_reference(block_h,
                                                           causal):
    """block_h > 1 (multi-head-per-program forward — the short-seq
    grid-overhead lever, VERDICT r4 item 3): exact vs the reference,
    fwd AND grads (the backward reuses the per-head kernels on the
    mh-written LSE residual), through the Pallas interpreter."""
    q = _rand(2, 4, 100, 32, key=0)
    k = _rand(2, 4, 100, 32, key=1)
    v = _rand(2, 4, 100, 32, key=2)
    out = flash_attention(q, k, v, causal=causal, interpret=True,
                          block_h=block_h, block_q=64, block_k=64)
    ref = _attention_reference(q, k, v, 1.0 / np.sqrt(32), causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(
            q, k, v, causal=causal, interpret=True, block_h=block_h,
            block_q=64, block_k=64) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_attention_reference(
            q, k, v, 1.0 / np.sqrt(32), causal) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_flash_attention_multihead_block_varlen():
    """block_h with kv_lens: every head row in a tile shares its
    example's length, including an empty (len 0) example."""
    q = _rand(3, 4, 64, 32, key=3)
    k = _rand(3, 4, 64, 32, key=4)
    v = _rand(3, 4, 64, 32, key=5)
    lens = jnp.asarray([64, 17, 0], jnp.int32)
    out = flash_attention(q, k, v, kv_lens=lens, interpret=True,
                          block_h=2, block_q=64, block_k=64)
    ref = _attention_reference(q, k, v, 1.0 / np.sqrt(32), False,
                               kv_lens=lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_attention_block_h_must_divide_heads():
    q = _rand(1, 4, 64, 32, key=6)
    with pytest.raises(ValueError, match="block_h"):
        flash_attention(q, q, q, interpret=True, block_h=3)


def test_attn_block_h_env_default(monkeypatch):
    """RAFIKI_ATTN_BLOCK_H applies fleet-wide without code edits:
    callers that don't pass block_h pick up the env default, and the
    env-driven block_h>1 disables the short-seq XLA route exactly like
    an explicit one (so the tuned kernels actually run on TPU)."""
    import rafiki_tpu.ops.attention as attn_mod

    calls = []
    real = attn_mod._flash_attention_full
    monkeypatch.setattr(attn_mod, "ATTN_BLOCK_H", 2)
    monkeypatch.setattr(
        attn_mod, "_flash_attention_full",
        lambda *a, **kw: (calls.append(a[8]), real(*a[:7], True,
                                                   *a[8:]))[1])
    monkeypatch.setattr(attn_mod, "use_xla_fallback",
                        lambda interpret: False)
    q = _rand(1, 4, 32, 16, key=7)  # short seq: XLA route iff block_h=1
    out = attn_mod.flash_attention(q, q, q)
    assert calls == [2], calls  # kernel path, env block_h applied
    ref = _attention_reference(q, q, q, 1.0 / np.sqrt(16), False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_attn_block_h_env_default_falls_back_on_indivisible(caplog):
    """A fleet RAFIKI_ATTN_BLOCK_H that doesn't divide this call's
    LOCAL head count (ulysses/ring inner calls see heads/tp/sp) must
    degrade to block_h=1 with one warning — not hard-fail a template
    that never asked for head tiling. An EXPLICIT indivisible block_h
    keeps raising (covered above)."""
    import logging

    import rafiki_tpu.ops.attention as attn_mod

    q = _rand(1, 4, 32, 16, key=9)
    ref = _attention_reference(q, q, q, 1.0 / np.sqrt(16), False)
    orig = attn_mod.ATTN_BLOCK_H
    try:
        attn_mod.ATTN_BLOCK_H = 3  # does not divide h=4
        with caplog.at_level(logging.WARNING,
                             logger="rafiki_tpu.ops.attention"):
            out = attn_mod.flash_attention(q, q, q)
            out2 = attn_mod.flash_attention(q, q, q)
    finally:
        attn_mod.ATTN_BLOCK_H = orig
        attn_mod._ENV_BLOCK_H_WARNED.clear()
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    warned = [r for r in caplog.records
              if "RAFIKI_ATTN_BLOCK_H" in r.getMessage()]
    assert len(warned) == 1  # one-time per (block_h, heads) shape


def test_flash_attention_bf16():
    q = _rand(1, 2, 128, 64, key=0, dtype=jnp.bfloat16)
    k = _rand(1, 2, 128, 64, key=1, dtype=jnp.bfloat16)
    v = _rand(1, 2, 128, 64, key=2, dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = _attention_reference(q.astype(jnp.float32),
                               k.astype(jnp.float32),
                               v.astype(jnp.float32), 1.0 / np.sqrt(64),
                               False)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), atol=3e-2, rtol=3e-2)


def test_mha_layer_shapes():
    d_model, n_heads = 64, 4
    params = {
        "wq": _rand(d_model, d_model, key=0),
        "wk": _rand(d_model, d_model, key=1),
        "wv": _rand(d_model, d_model, key=2),
        "wo": _rand(d_model, d_model, key=3),
        "bq": jnp.zeros(d_model), "bk": jnp.zeros(d_model),
        "bv": jnp.zeros(d_model), "bo": jnp.zeros(d_model),
    }
    x = _rand(2, 50, d_model, key=4)
    out = mha(x, x, params, n_heads, interpret=True)
    assert out.shape == (2, 50, d_model)
    assert np.all(np.isfinite(np.asarray(out)))


def test_matmul_bias():
    x = _rand(300, 200, key=0)
    w = _rand(200, 130, key=1)
    b = _rand(130, key=2)
    out = matmul_bias(x, w, b, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x @ w + b),
                               atol=1e-4, rtol=1e-4)


def test_extract_patches_roundtrip():
    imgs = _rand(2, 8, 8, 3, key=0)
    patches = extract_patches(imgs, 4)
    assert patches.shape == (2, 4, 48)
    # first patch == top-left 4x4 block flattened
    np.testing.assert_allclose(np.asarray(patches[0, 0]),
                               np.asarray(imgs[0, :4, :4, :]).reshape(-1))


def test_patch_embed_matches_conv():
    imgs = _rand(2, 32, 32, 3, key=0)
    p, d = 8, 96
    w = _rand(p * p * 3, d, key=1) * 0.02
    b = _rand(d, key=2) * 0.01
    out = patch_embed(imgs, w, b, p, True)
    assert out.shape == (2, 16, d)
    # oracle: conv with stride=kernel=p
    wk = w.reshape(p, p, 3, d)
    ref = jax.lax.conv_general_dilated(
        imgs, wk, (p, p), "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC")).reshape(2, 16, d) + b
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_patch_embed_grads():
    imgs = _rand(1, 16, 16, 3, key=0)
    p, d = 8, 32
    w = _rand(p * p * 3, d, key=1) * 0.02
    b = jnp.zeros(d)

    def loss_pe(imgs, w, b):
        return jnp.sum(patch_embed(imgs, w, b, p, True) ** 2)

    def loss_ref(imgs, w, b):
        pt = extract_patches(imgs, p)
        return jnp.sum((jnp.einsum("bnk,kd->bnd", pt, w) + b) ** 2)

    g1 = jax.grad(loss_pe, argnums=(0, 1, 2))(imgs, w, b)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(imgs, w, b)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_flash_attention_grads_causal_multiblock():
    # >1 query and key block so the bwd kernels' causal start/stop logic
    # and cross-block accumulation are exercised
    q = _rand(2, 2, 320, 32, key=10)
    k = _rand(2, 2, 320, 32, key=11)
    v = _rand(2, 2, 320, 32, key=12)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(
            _attention_reference(q, k, v, 1.0 / np.sqrt(32), True) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


@pytest.mark.slow
def test_flash_attention_varlen_grads_multiblock_and_empty():
    # kv_lens spanning block boundaries plus a zero-length example: the
    # LSE_MASKED path must produce exactly-zero grads, never NaN
    q = _rand(3, 2, 200, 16, key=13)
    k = _rand(3, 2, 200, 16, key=14)
    v = _rand(3, 2, 200, 16, key=15)
    lens = jnp.asarray([200, 131, 0], jnp.int32)

    def f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, kv_lens=lens, interpret=True) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(
            _attention_reference(q, k, v, 1.0 / np.sqrt(16), False,
                                 lens) ** 2)

    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    for a in g:
        assert np.all(np.isfinite(np.asarray(a)))
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    # example 2 attends to nothing: all its key/value grads vanish
    assert float(jnp.abs(g[1][2]).max()) == 0.0
    assert float(jnp.abs(g[2][2]).max()) == 0.0
    for a, b in zip(g[:2], g_ref[:2]):
        np.testing.assert_allclose(np.asarray(a[:2]), np.asarray(b[:2]),
                                   atol=2e-4, rtol=2e-4)


def test_flash_attention_bf16_grads():
    q = _rand(1, 2, 128, 64, key=16, dtype=jnp.bfloat16)
    k = _rand(1, 2, 128, 64, key=17, dtype=jnp.bfloat16)
    v = _rand(1, 2, 128, 64, key=18, dtype=jnp.bfloat16)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, interpret=True).astype(jnp.float32) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(
        lambda q, k, v: jnp.sum(_attention_reference(
            q, k, v, 1.0 / np.sqrt(64), False).astype(jnp.float32) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        assert a.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   atol=0.15, rtol=0.15)


@pytest.mark.tpu
def test_flash_attention_compiles_on_tpu():
    """Mosaic smoke test: fwd+bwd (incl. varlen) with interpret=False.

    Skipped off-TPU; on a real chip it catches TPU-lowering regressions
    (1-D refs, scalar reads in control flow) that interpret mode hides.
    """
    if jax.default_backend() != "tpu":
        pytest.skip("needs a real TPU backend")
    q = _rand(2, 2, 200, 64, key=0, dtype=jnp.bfloat16)
    k = _rand(2, 2, 200, 64, key=1, dtype=jnp.bfloat16)
    v = _rand(2, 2, 200, 64, key=2, dtype=jnp.bfloat16)
    lens = jnp.asarray([200, 77], jnp.int32)

    def loss(q, k, v):
        a = flash_attention(q, k, v, causal=True, interpret=False)
        b = flash_attention(q, k, v, kv_lens=lens, interpret=False)
        return jnp.sum(a.astype(jnp.float32) ** 2) + \
            jnp.sum(b.astype(jnp.float32) ** 2)

    val, grads = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))(
        q, k, v)
    assert np.isfinite(float(val))
    for g_ in grads:
        assert np.all(np.isfinite(np.asarray(g_, np.float32)))


def test_xla_fallback_empty_row_matches_kernels():
    """Off-TPU default dispatch (pure-XLA path): kv_len==0 rows must output
    exact zeros with zero grads, matching the kernels' LSE_MASKED path."""
    q = _rand(2, 2, 32, 16, key=20)
    k = _rand(2, 2, 32, 16, key=21)
    v = _rand(2, 2, 32, 16, key=22)
    lens = jnp.asarray([32, 0], jnp.int32)

    def f(q, k, v):
        # interpret unset -> XLA path on CPU (the model-template default)
        return jnp.sum(flash_attention(q, k, v, kv_lens=lens) ** 2)

    out = flash_attention(q, k, v, kv_lens=lens)
    assert float(jnp.abs(out[1]).max()) == 0.0
    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    assert float(jnp.abs(g[1][1]).max()) == 0.0
    assert float(jnp.abs(g[2][1]).max()) == 0.0
    # and the nonempty example still matches the interpreter kernels
    ker = flash_attention(q, k, v, kv_lens=lens, interpret=True)
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(ker[0]),
                               atol=2e-5, rtol=2e-5)


def test_short_seq_dispatch_routes_to_xla(monkeypatch):
    """Auto dispatch (interpret=None) must use the XLA path at or below
    XLA_SHORT_SEQ even when the backend looks like a TPU (measured
    faster on silicon at short seq), and the kernels above it."""
    import rafiki_tpu.ops.attention as attn_mod
    from rafiki_tpu.ops.attention import XLA_SHORT_SEQ

    calls = []
    real_ref = attn_mod._attention_reference
    real_full = attn_mod._flash_attention_full

    monkeypatch.setattr(
        attn_mod, "_attention_reference",
        lambda *a, **kw: (calls.append("xla"), real_ref(*a, **kw))[1])
    monkeypatch.setattr(
        attn_mod, "_flash_attention_full",
        lambda *a, **kw: (calls.append("pallas"),
                          real_full(*a[:7], True, *a[8:]))[1])
    # pretend the backend is a TPU so use_xla_fallback(None) is False
    monkeypatch.setattr(attn_mod, "use_xla_fallback",
                        lambda interpret: False)

    q = jnp.ones((1, 2, 8, 16), jnp.float32)
    attn_mod.flash_attention(q, q, q)  # seq 8 <= threshold
    assert calls == ["xla"]

    calls.clear()
    long_len = XLA_SHORT_SEQ + 8
    ql = jnp.ones((1, 1, long_len, 16), jnp.float32)
    attn_mod.flash_attention(ql, ql, ql)  # above threshold -> kernels
    assert calls == ["pallas"]
