"""Ulysses all-to-all sequence parallelism on the 8-device virtual mesh.

The second long-context strategy (next to ring attention): exactness vs
the dense oracle (fwd + grads, causal and full), head-divisibility
refusal, and gradient flow through both all-to-alls."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from rafiki_tpu.ops.attention import _attention_reference
from rafiki_tpu.ops.ulysses import ulysses_attention


def _rand(*shape, key=0, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype)


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("sp",))


# the degenerate and 8-way-causal configs stay in the default leg; the
# intermediate mesh sizes ride the slow leg (same code path, ~30s saved)
@pytest.mark.parametrize("n_par,causal", [
    (1, False), (8, True),
    pytest.param(4, False, marks=pytest.mark.slow),
    pytest.param(4, True, marks=pytest.mark.slow)])
def test_ulysses_matches_dense(n_par, causal):
    s, h = 64, 8  # heads divisible by every mesh size used
    q = _rand(2, h, s, 16, key=0)
    k = _rand(2, h, s, 16, key=1)
    v = _rand(2, h, s, 16, key=2)
    mesh = _mesh(n_par)
    out = ulysses_attention(q, k, v, mesh, "sp", causal=causal)
    ref = _attention_reference(q, k, v, 1.0 / np.sqrt(16), causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # output stays sequence-sharded — no all-gather of the result
    spec = tuple(out.sharding.spec)  # older jax trims trailing None
    assert "sp" in spec  # a replicated (all-gathered) result fails
    assert spec == (None, None, "sp", None)[:len(spec)]


@pytest.mark.slow
def test_ulysses_grads_match_dense():
    s, h = 32, 8
    q = _rand(1, h, s, 16, key=3)
    k = _rand(1, h, s, 16, key=4)
    v = _rand(1, h, s, 16, key=5)
    mesh = _mesh(8)

    def f(impl):
        def loss(q, k, v):
            return jnp.sum(impl(q, k, v).astype(jnp.float32) ** 2)
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    g = f(lambda q, k, v: ulysses_attention(q, k, v, mesh, "sp",
                                            causal=True))
    gr = f(lambda q, k, v: _attention_reference(
        q, k, v, 1.0 / np.sqrt(16), True))
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5)


def test_ulysses_refuses_indivisible_heads():
    mesh = _mesh(8)
    q = _rand(1, 6, 32, 16)  # 6 heads over 8 devices
    with pytest.raises(ValueError, match="ring_attention instead"):
        ulysses_attention(q, q, q, mesh, "sp")


def test_ulysses_gqa_head_axis_fwd_matches_dense():
    """Default-leg sp×tp GQA exactness WITHOUT the grad compile (the
    all-to-all VJP costs ~14s of CPU compile; the full fwd+grad
    oracles for both per-shard pairings ride the slow leg below):
    the small-swap pairing must stay aligned per TP shard."""
    s, h, h_kv = 32, 8, 4
    rep = h // h_kv
    q = _rand(1, h, s, 8, key=30)
    k = _rand(1, h_kv, s, 8, key=31)
    v = _rand(1, h_kv, s, 8, key=32)
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("sp", "model"))
    out = ulysses_attention(q, k, v, mesh, "sp", causal=True,
                            head_axis="model")
    ref = _attention_reference(
        q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1),
        1.0 / np.sqrt(8), True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.slow
@pytest.mark.parametrize("h_kv", [
    4,   # small-swap×tp: per-shard kv heads 2 divide sp=2
    2,   # repeat-before-swap×tp: per-shard kv heads 1 don't divide
])
def test_ulysses_gqa_with_head_axis_matches_dense(h_kv):
    """sp×tp GQA oracle: heads additionally sharded over a `model`
    mesh axis (head_axis), the ulysses swap running within each TP
    head group. The GQA pairing must stay aligned PER SHARD — both
    the small-swap path (kv heads divide sp within the shard) and the
    repeat-before-swap fallback — fwd and grads vs the dense oracle."""
    s, h = 32, 8
    rep = h // h_kv
    q = _rand(1, h, s, 8, key=30)
    k = _rand(1, h_kv, s, 8, key=31)
    v = _rand(1, h_kv, s, 8, key=32)
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("sp", "model"))

    def uly(q, k, v):
        return ulysses_attention(q, k, v, mesh, "sp", causal=True,
                                 head_axis="model")

    ref = _attention_reference(
        q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1),
        1.0 / np.sqrt(8), True)
    np.testing.assert_allclose(np.asarray(uly(q, k, v)),
                               np.asarray(ref), atol=2e-5, rtol=2e-5)

    def f(q, k, v):
        return jnp.sum(uly(q, k, v).astype(jnp.float32) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_attention_reference(
            q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1),
            1.0 / np.sqrt(8), True).astype(jnp.float32) ** 2)

    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


@pytest.mark.slow
def test_ring_gqa_with_head_axis_matches_dense():
    """sp×tp ring oracle: head dim sharded over `model`, independent
    K/V rings per TP shard, GQA group-reduce on LOCAL shapes — fwd and
    grads vs the dense oracle (covers the reduce_groups local-shape
    change in ops/ring_attention.py)."""
    from rafiki_tpu.ops.ring_attention import ring_attention

    s, h, h_kv = 32, 4, 2
    rep = h // h_kv
    q = _rand(1, h, s, 8, key=40)
    k = _rand(1, h_kv, s, 8, key=41)
    v = _rand(1, h_kv, s, 8, key=42)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2),
                ("sp", "model"))  # per-shard heads 2 < sp 4 -> ring

    def ring(q, k, v):
        return ring_attention(q, k, v, mesh, "sp", causal=True,
                              head_axis="model")

    ref = _attention_reference(
        q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1),
        1.0 / np.sqrt(8), True)
    np.testing.assert_allclose(np.asarray(ring(q, k, v)),
                               np.asarray(ref), atol=2e-5, rtol=2e-5)

    def f(q, k, v):
        return jnp.sum(ring(q, k, v).astype(jnp.float32) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_attention_reference(
            q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1),
            1.0 / np.sqrt(8), True).astype(jnp.float32) ** 2)

    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("n_par,h_kv", [
    # small-swap rides the slow leg — the default leg covers it (and
    # its grads) under tensor parallelism via the head_axis test above
    pytest.param(2, 4, marks=pytest.mark.slow),
    (4, 2),   # repeat-before-swap path: kv heads don't divide (2 % 4)
])
def test_ulysses_gqa_matches_dense(n_par, h_kv):
    """GQA ulysses: K/V carry h_kv < h heads. When h_kv divides the
    axis the SMALL tensors ride the all-to-alls and devices repeat
    their landed chunk locally; otherwise K/V repeat before the swap.
    Both paths must equal the dense oracle over repeated K/V — fwd and
    grads (incl. dK/dV group-reduced by autodiff)."""
    s, h = 32, 8
    rep = h // h_kv
    q = _rand(1, h, s, 8, key=20)
    k = _rand(1, h_kv, s, 8, key=21)
    v = _rand(1, h_kv, s, 8, key=22)
    mesh = _mesh(n_par)

    def f(q, k, v):
        return jnp.sum(ulysses_attention(q, k, v, mesh, "sp",
                                         causal=True) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_attention_reference(
            q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1),
            1.0 / np.sqrt(8), True) ** 2)

    np.testing.assert_allclose(
        np.asarray(ulysses_attention(q, k, v, mesh, "sp", causal=True)),
        np.asarray(_attention_reference(
            q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1),
            1.0 / np.sqrt(8), True)), atol=2e-5, rtol=2e-5)
    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)
