"""Byte-level BPE tokenizer: losslessness, compression, artifact
round-trip, determinism, and the HashTokenizer-compatible surface."""

import numpy as np
import pytest

from rafiki_tpu.data.bpe import (BOS_ID, N_SPECIAL, PAD_ID,
                                 ByteBPETokenizer)

CORPUS = [
    "the quick brown fox jumps over the lazy dog\n",
    "the quicker the fox the lazier the dog\n",
    "pack my box with five dozen liquor jugs\n",
    "sphinx of black quartz judge my vow\n",
] * 8


@pytest.fixture(scope="module")
def tok():
    return ByteBPETokenizer.train(CORPUS, vocab_size=320)


def test_lossless_roundtrip(tok):
    for text in ["the quick brown fox", "Hello, WORLD!  spaces\tand\nnl",
                 "unicode: déjà vu — 東京 🙂", "", "   ", "a"]:
        assert tok.decode(tok.encode_ids(text)) == text


def test_merges_compress(tok):
    text = "the quick brown fox jumps over the lazy dog"
    ids = tok.encode_ids(text)
    assert len(ids) < len(text.encode("utf-8"))  # merges learned
    # frequent whole words became single tokens
    assert len(tok.encode_ids("the")) == 1


def test_training_is_deterministic():
    a = ByteBPETokenizer.train(CORPUS, vocab_size=300)
    b = ByteBPETokenizer.train(CORPUS, vocab_size=300)
    assert a.merges == b.merges


def test_artifact_roundtrip(tok, tmp_path):
    path = str(tmp_path / "bpe.json")
    tok.save(path)
    loaded = ByteBPETokenizer.load(path)
    assert loaded.merges == tok.merges
    assert loaded.vocab_size == tok.vocab_size
    text = "the quick brown fox"
    assert loaded.encode_ids(text) == tok.encode_ids(text)


def test_hash_tokenizer_compatible_surface(tok):
    row, n = tok.encode("the fox", max_len=16)
    assert row[0] == BOS_ID and n >= 2 and len(row) == 16
    assert all(t == PAD_ID for t in row[n:])
    ids, lens = tok.encode_batch(["the fox", "dog"], max_len=16)
    assert ids.shape == (2, 16) and ids.dtype == np.int32
    assert lens[0] >= 2
    # truncation respects max_len
    long_row, ln = tok.encode("x" * 500, max_len=8)
    assert len(long_row) == 8 and ln == 8


def test_unknown_bytes_never_fail(tok):
    # bytes never seen in training still encode (byte-level base vocab)
    text = "\x00\x01\xff weird"
    assert tok.decode(tok.encode_ids(text)) == text


def test_vocab_floor():
    with pytest.raises(ValueError):
        ByteBPETokenizer.train(CORPUS, vocab_size=N_SPECIAL + 255)


def test_cli_bpe_train(tmp_path):
    """`rafiki-tpu bpe-train` produces a loadable artifact from a plain
    corpus AND from .jsonl (text fields)."""
    from rafiki_tpu.cli import main

    plain = tmp_path / "c.txt"
    plain.write_text("\n".join(CORPUS))
    out = str(tmp_path / "bpe.json")
    assert main(["bpe-train", str(plain), out, "--vocab", "300"]) == 0
    tok = ByteBPETokenizer.load(out)
    assert tok.vocab_size <= 300 and len(tok.merges) > 0
    assert tok.decode(tok.encode_ids("the fox")) == "the fox"

    jl = tmp_path / "c.jsonl"
    jl.write_text('{"n_classes": 2}\n'
                  '{"text": "alpha beta gamma", "label": 0}\n'
                  '{"text": "beta gamma delta", "label": 1}\n')
    out2 = str(tmp_path / "bpe2.json")
    assert main(["bpe-train", str(jl), out2, "--vocab", "280"]) == 0
    tok2 = ByteBPETokenizer.load(out2)
    assert tok2.decode(tok2.encode_ids("alpha beta")) == "alpha beta"


def test_cli_bpe_train_jsonl_skips_metadata(tmp_path):
    """.jsonl training must not learn merges from metadata rows' JSON
    punctuation or from null text fields."""
    from rafiki_tpu.cli import main

    jl = tmp_path / "c.jsonl"
    jl.write_text('{"n_classes": 2}\n'
                  '{"text": null, "label": 0}\n'
                  + "".join('{"text": "aaaa bbbb cccc", "label": 1}\n'
                            for _ in range(8)))
    out = str(tmp_path / "bpe.json")
    assert main(["bpe-train", str(jl), out, "--vocab", "280"]) == 0
    tok = ByteBPETokenizer.load(out)
    joined = "|".join(tok.decode([i])
                      for i in range(259, tok.vocab_size))
    assert "{" not in joined and "None" not in joined
    assert "aaaa" in joined  # real text was learned


def test_native_encoder_matches_python_exactly():
    """The C++ chunk encoder (native/bpe_encoder.cc) must be id-for-id
    identical to the Python merge loop on every input — same merges,
    same lowest-rank-first policy — and measurably usable through the
    full tokenizer surface."""

    from rafiki_tpu.data.bpe import ByteBPETokenizer, _native_encoder

    corpus = ["the quick brown fox jumps over the lazy dog",
              "pack my box with five dozen liquor jugs",
              "unicode: déjà vu, 東京, emoji 🙂 end"] * 4
    tok = ByteBPETokenizer.train(corpus, vocab_size=400)
    native = _native_encoder(tok.merges)
    if native is None:
        import pytest as _pytest

        _pytest.skip("native bpe unavailable (no toolchain)")

    texts = corpus + ["", " ", "a", "  leading", "trailing  ",
                      "mixed 東京 ascii", "\n\t whitespace runs \n"]
    for t in texts:
        # chunk-level identity against the pure-Python loop
        from rafiki_tpu.data.bpe import _CHUNK_RE

        for chunk in _CHUNK_RE.findall(t):
            cb = chunk.encode("utf-8")
            assert native.encode_chunk(cb) == tok._bpe_chunk(cb), chunk
    # the tokenizer really auto-picked the native path (native import
    # succeeded above, so the constructor must have too) and round-
    # trips losslessly through it
    assert tok._native is not None
    for t in texts:
        assert tok.decode(tok.encode_ids(t)) == t
