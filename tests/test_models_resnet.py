"""ResNet family: module shapes, contract conformance, DP training."""

import pytest

import jax
import numpy as np

from rafiki_tpu.constants import TaskType
from rafiki_tpu.data import generate_image_classification_dataset
from rafiki_tpu.model import TrainContext, test_model_class
from rafiki_tpu.models.resnet import ResNet, ResNetClassifier


TINY = {"variant": "resnet18", "width_mult": 0.25, "batch_size": 32,
        "max_epochs": 5, "learning_rate": 0.1, "weight_decay": 1e-4,
        "bf16": False, "quick_train": False, "share_params": False}


@pytest.mark.slow
def test_resnet_module_shapes_bottleneck():
    m = ResNet(stage_sizes=(1, 1, 1, 1), bottleneck=True, width=8,
               n_classes=7, small_inputs=True)
    x = np.zeros((2, 28, 28, 1), np.float32)
    variables = m.init(jax.random.PRNGKey(0), x, train=False)
    assert "batch_stats" in variables
    out = m.apply(variables, x, train=False)
    assert out.shape == (2, 7)


def test_resnet_module_large_stem():
    m = ResNet(stage_sizes=(1, 1, 1, 1), bottleneck=False, width=8,
               n_classes=3, small_inputs=False)
    x = np.zeros((1, 64, 64, 3), np.float32)
    variables = m.init(jax.random.PRNGKey(0), x, train=False)
    out = m.apply(variables, x, train=False)
    assert out.shape == (1, 3)


@pytest.mark.slow
def test_resnet_template_contract(tmp_path):
    tr, va = str(tmp_path / "t.npz"), str(tmp_path / "v.npz")
    generate_image_classification_dataset(tr, 192, seed=0)
    ds = generate_image_classification_dataset(va, 48, seed=1)
    preds = test_model_class(ResNetClassifier, TaskType.IMAGE_CLASSIFICATION,
                             tr, va, queries=[ds.images[0]], knobs=TINY)
    assert len(preds) == 1 and len(preds[0]) == ds.n_classes


@pytest.mark.slow
def test_resnet_trains_data_parallel(tmp_path):
    """Train over 8 virtual devices; loss must decrease and BN stats must
    update away from init."""
    tr = str(tmp_path / "t.npz")
    generate_image_classification_dataset(tr, 192, seed=0)
    model = ResNetClassifier(**TINY)
    ctx = TrainContext(devices=list(jax.devices()))
    model.train(tr, ctx)
    losses = ctx.logger.get_values("loss")
    assert len(losses) >= 2 and losses[-1] < losses[0]
    stats = jax.tree_util.tree_leaves(model._vars["batch_stats"])
    assert any(float(np.abs(np.asarray(s)).sum()) > 0 for s in stats)
