"""Pipeline parallelism (parallel/pipeline.py): exactness vs the
sequential oracle, gradients through the reverse pipeline, transformer-
block stages, remat, and composition with a data axis."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from rafiki_tpu.parallel.pipeline import (pipeline_apply, pipeline_oracle,
                                          stack_stage_params)


def _mesh(n, name="pipe"):
    return Mesh(np.array(jax.devices()[:n]), (name,))


def _dense_stage(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _dense_stack(n_stages, d, key=0):
    ks = jax.random.split(jax.random.PRNGKey(key), n_stages)
    per_stage = [{"w": jax.random.normal(k, (d, d)) / np.sqrt(d),
                  "b": jnp.zeros((d,))} for k in ks]
    return per_stage, stack_stage_params(per_stage)


@pytest.mark.parametrize("n_stages,n_micro", [
    (2, 4),  # the quick default-leg exactness check
    pytest.param(4, 4, marks=pytest.mark.slow),
    pytest.param(4, 8, marks=pytest.mark.slow),
    pytest.param(8, 8, marks=pytest.mark.slow),
    pytest.param(4, 1, marks=pytest.mark.slow)])
def test_pipeline_matches_sequential(n_stages, n_micro):
    d = 16
    per_stage, stacked = _dense_stack(n_stages, d)
    x = jax.random.normal(jax.random.PRNGKey(9), (n_micro, 4, d))
    mesh = _mesh(n_stages)
    out = pipeline_apply(_dense_stage, stacked, x, mesh)
    ref = pipeline_oracle(_dense_stage, per_stage, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("remat", [False, True])
@pytest.mark.slow
def test_pipeline_grads_match_sequential(remat):
    n_stages, n_micro, d = 4, 4, 8
    per_stage, stacked = _dense_stack(n_stages, d, key=3)
    x = jax.random.normal(jax.random.PRNGKey(4), (n_micro, 2, d))
    mesh = _mesh(n_stages)

    def loss_pipe(stacked):
        y = pipeline_apply(_dense_stage, stacked, x, mesh, remat=remat)
        return jnp.sum(y.astype(jnp.float32) ** 2)

    def loss_ref(stacked):
        per = [jax.tree_util.tree_map(lambda a: a[i], stacked)
               for i in range(n_stages)]
        y = pipeline_oracle(_dense_stage, per, x)
        return jnp.sum(y.astype(jnp.float32) ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_ref = jax.grad(loss_ref)(stacked)
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_pipeline_transformer_stages():
    """Stages can be real transformer blocks: per-stage flax params,
    stacked, pipelined — output equals running the blocks in order."""
    from flax import linen as nn

    class Block(nn.Module):
        @nn.compact
        def __call__(self, x):
            y = nn.LayerNorm()(x)
            y = nn.Dense(x.shape[-1] * 2)(y)
            y = nn.gelu(y)
            return x + nn.Dense(x.shape[-1])(y)

    block = Block()
    d, n_stages, n_micro = 8, 4, 4
    x = jax.random.normal(jax.random.PRNGKey(0), (n_micro, 2, 6, d))
    per_stage = [block.init(jax.random.PRNGKey(i), x[0])["params"]
                 for i in range(n_stages)]
    stacked = stack_stage_params(per_stage)

    def stage_fn(p, h):
        return block.apply({"params": p}, h)

    mesh = _mesh(n_stages)
    out = pipeline_apply(stage_fn, stacked, x, mesh)
    ref = pipeline_oracle(stage_fn, per_stage, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.slow
def test_pipeline_composes_with_data_axis():
    """pipe × data 2-D mesh: each microbatch's BATCH dim sharded over
    `data` (batch_axis), stages over `pipe` — both shardings at once,
    same math, and the output keeps the data sharding."""
    devs = np.array(jax.devices()[:8], dtype=object).reshape(4, 2)
    mesh = Mesh(devs, ("pipe", "data"))
    d, n_micro = 8, 4
    per_stage, stacked = _dense_stack(4, d, key=7)
    x = jax.random.normal(jax.random.PRNGKey(8), (n_micro, 4, d))
    out = pipeline_apply(_dense_stage, stacked, x, mesh, axis="pipe",
                         batch_axis="data")
    ref = pipeline_oracle(_dense_stage, per_stage, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)
    assert tuple(out.sharding.spec)[:2] == (None, "data")


def test_pipeline_rejects_wrong_stage_count():
    per_stage, stacked = _dense_stack(8, 8)  # 8 stages, 4-device axis
    mesh = _mesh(4)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 2, 8))
    with pytest.raises(ValueError, match="leading dim"):
        pipeline_apply(_dense_stage, stacked, x, mesh)


def test_stage_params_actually_sharded():
    """Each pipe device holds only its stage's weights (dim-0 sharding),
    not a replica of the whole stack."""
    n_stages, d = 4, 16
    _, stacked = _dense_stack(n_stages, d)
    mesh = _mesh(n_stages)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 2, d))
    # pipeline_apply device_puts internally; replicate that placement
    from jax.sharding import NamedSharding, PartitionSpec as P

    w = jax.device_put(stacked["w"], NamedSharding(mesh, P("pipe")))
    shard_bytes = {sh.device: sh.data.nbytes
                   for sh in w.addressable_shards}
    total = np.asarray(w).nbytes
    assert all(b == total // n_stages for b in shard_bytes.values())
    # and the pipelined result is still correct under that placement
    out = pipeline_apply(_dense_stage, stacked, x, mesh)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.slow
def test_pipeline_pytree_activations_with_positions():
    """Real-model shape: the activation is a (hidden, positions) pytree
    — attention-style stages need positions/masks alongside hidden
    states; the pipe threads the whole structure stage to stage."""
    from flax import linen as nn

    class PosBlock(nn.Module):
        @nn.compact
        def __call__(self, h, positions):
            # position-dependent mixing so threading positions matters
            pe = jnp.sin(positions[..., None].astype(jnp.float32)
                         / 7.0)
            y = nn.Dense(h.shape[-1])(h + pe.astype(h.dtype))
            return h + jnp.tanh(y)

    block = PosBlock()
    d, n_stages, n_micro, b, s = 8, 4, 4, 2, 6
    h = jax.random.normal(jax.random.PRNGKey(0), (n_micro, b, s, d))
    pos = jnp.broadcast_to(jnp.arange(s), (n_micro, b, s))
    per_stage = [block.init(jax.random.PRNGKey(i), h[0], pos[0])["params"]
                 for i in range(n_stages)]
    stacked = stack_stage_params(per_stage)

    def stage_fn(p, act):
        hh, pp = act["h"], act["pos"]
        return {"h": block.apply({"params": p}, hh, pp), "pos": pp}

    mesh = _mesh(n_stages)
    out = pipeline_apply(stage_fn, stacked, {"h": h, "pos": pos}, mesh)
    # oracle: sequential stages, positions threaded identically
    ref = []
    for m in range(n_micro):
        cur = h[m]
        for p in per_stage:
            cur = block.apply({"params": p}, cur, pos[m])
        ref.append(cur)
    np.testing.assert_allclose(np.asarray(out["h"]),
                               np.asarray(jnp.stack(ref)),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(out["pos"]),
                                  np.asarray(pos))


def test_pipeline_rank1_activation_leaves():
    """Per-microbatch rank-1 leaves (scalars/ids) thread through the
    pipe without a batch dim to shard."""
    per_stage, stacked = _dense_stack(4, 8)
    h = jax.random.normal(jax.random.PRNGKey(0), (4, 2, 8))
    scale = jnp.arange(4, dtype=jnp.float32) + 1.0  # (M,)
    mesh = _mesh(4)

    def stage_fn(p, act):
        return {"h": _dense_stage(p, act["h"]) * act["scale"],
                "scale": act["scale"]}

    out = pipeline_apply(stage_fn, stacked,
                         {"h": h, "scale": scale}, mesh)
    ref = []
    for m in range(4):
        cur = h[m]
        for p in per_stage:
            cur = _dense_stage(p, cur) * scale[m]
        ref.append(cur)
    np.testing.assert_allclose(np.asarray(out["h"]),
                               np.asarray(jnp.stack(ref)),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.slow
def test_pipelined_llama_forward_matches_canonical():
    """pipelined_lm_forward == Llama.apply for identical params: logits
    AND gradients (the flagship-LM pipeline-parallel integration)."""
    from rafiki_tpu.models.llama_lora import (Llama, pipelined_lm_forward)

    module = Llama(vocab_size=128, max_len=16, hidden_dim=32, depth=4,
                   n_heads=4, n_kv_heads=2, mlp_dim=64, lora_rank=2)
    ids = np.ones((8, 12), np.int32)
    ids[:, 3:] = (np.arange(8 * 9).reshape(8, 9) % 120) + 2
    lens = np.asarray([12, 10, 12, 8, 12, 12, 9, 12], np.int32)
    params = module.init(jax.random.PRNGKey(0),
                         jnp.asarray(ids))["params"]
    ref = module.apply({"params": params}, jnp.asarray(ids),
                       lens=jnp.asarray(lens))

    for n_stages, n_micro in ((2, 4), (4, 2)):
        mesh = _mesh(n_stages)
        got = pipelined_lm_forward(module, params, jnp.asarray(ids),
                                   jnp.asarray(lens), mesh, n_micro)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

    mesh = _mesh(4)

    def loss_pipe(p):
        logits = pipelined_lm_forward(module, p, jnp.asarray(ids),
                                      jnp.asarray(lens), mesh, 2,
                                      remat=True)
        return jnp.mean(logits.astype(jnp.float32) ** 2)

    def loss_ref(p):
        logits = module.apply({"params": p}, jnp.asarray(ids),
                              lens=jnp.asarray(lens))
        return jnp.mean(logits.astype(jnp.float32) ** 2)

    g_pipe = jax.grad(loss_pipe)(params)
    g_ref = jax.grad(loss_ref)(params)
    for (kp, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(g_pipe)[0],
            jax.tree_util.tree_flatten_with_path(g_ref)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=str(kp))


def test_pipelined_llama_forward_rejects_moe():
    from rafiki_tpu.models.llama_lora import (Llama, pipelined_lm_forward)

    module = Llama(vocab_size=64, max_len=16, hidden_dim=32, depth=2,
                   n_heads=4, n_kv_heads=2, mlp_dim=64, lora_rank=0,
                   n_experts=2)
    ids = jnp.ones((4, 8), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), ids)["params"]
    with pytest.raises(ValueError, match="MoE"):
        pipelined_lm_forward(module, params, ids,
                             jnp.full((4,), 8, jnp.int32), _mesh(2), 2)
