"""Tier-1 gate for the static-analysis subsystem (docs/linting.md).

Two jobs:

1. **Self-clean**: running every rule over ``rafiki_tpu/`` itself must
   produce zero unsuppressed findings. This is the CI gate — any PR
   that introduces a traced host-sync, an unlocked write against a
   locked attr, or a silent broad except fails here with the finding
   text in the assertion message.
2. **Rule correctness**: every rule fires on its positive fixture and
   stays quiet on its negative fixture (``tests/fixtures/lint/``), the
   suppression dialect works, and the CLI exit codes hold.

No jax import, no device work — this file runs in milliseconds.
"""

import json
import os
import subprocess
import sys

import pytest

from rafiki_tpu.analysis import (all_rules, analyze_paths,
                                 analyze_source, get_rule)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO_ROOT, "rafiki_tpu")
FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "lint")

#: rule id -> fixture stem; every registered rule must appear here
#: (the completeness test below enforces it), so adding a rule without
#: fixtures fails CI.
RULE_FIXTURES = {
    "jax-host-sync": "jax_host_sync",
    "jax-tracer-branch": "jax_tracer_branch",
    "jax-missing-donation": "jax_missing_donation",
    "inconsistent-lock": "inconsistent_lock",
    "thread-unlocked-global": "thread_unlocked_global",
    "silent-except": "silent_except",
    "library-internals": "library_internals",
    "obs-unregistered-metric": "obs_unregistered_metric",
    "wall-clock-deadline": "wall_clock_deadline",
    "blocking-transfer-in-decode-loop": "blocking_transfer",
}


# ---- the gate ----

def test_repo_is_self_clean():
    findings = analyze_paths([PACKAGE])
    assert not findings, (
        "rafiki_tpu/ has unsuppressed lint findings — fix them or, for "
        "a documented intentional pattern, suppress the line with "
        "`# rafiki: noqa[rule-id]`:\n"
        + "\n".join(f.format() for f in findings))


def test_issue_catalog_covers_every_category():
    cats = {r.category for r in all_rules().values()}
    assert {"jax", "concurrency", "robustness"} <= cats
    assert len(all_rules()) >= 6


# ---- per-rule fixtures ----

@pytest.mark.parametrize("rule_id", sorted(RULE_FIXTURES))
def test_rule_fires_on_positive_fixture(rule_id):
    path = os.path.join(FIXTURES, RULE_FIXTURES[rule_id] + "_bad.py")
    findings = analyze_paths([path], select=[rule_id])
    assert findings, f"{rule_id} missed its positive fixture"
    assert all(f.rule == rule_id for f in findings)
    assert all(f.path == path and f.line > 0 for f in findings)


@pytest.mark.parametrize("rule_id", sorted(RULE_FIXTURES))
def test_rule_quiet_on_negative_fixture(rule_id):
    path = os.path.join(FIXTURES, RULE_FIXTURES[rule_id] + "_ok.py")
    findings = analyze_paths([path], select=[rule_id])
    assert not findings, (
        f"{rule_id} false-positives on its negative fixture:\n"
        + "\n".join(f.format() for f in findings))


def test_positive_fixtures_trigger_no_foreign_rules():
    """Each bad fixture demonstrates exactly one hazard class — a
    finding from another rule means the fixtures drifted."""
    for rule_id, stem in RULE_FIXTURES.items():
        path = os.path.join(FIXTURES, stem + "_bad.py")
        rules_hit = {f.rule for f in analyze_paths([path])}
        assert rules_hit == {rule_id}, (stem, rules_hit)


def test_every_registered_rule_has_fixtures():
    assert set(RULE_FIXTURES) == set(all_rules()), (
        "keep RULE_FIXTURES in sync with the registry (one positive + "
        "one negative fixture per rule)")
    for rule_id in RULE_FIXTURES:
        rule = get_rule(rule_id)
        assert rule.description and rule.category and rule.severity


# ---- suppressions ----

def test_noqa_suppression_dialect():
    path = os.path.join(FIXTURES, "suppressed.py")
    src = open(path).read()
    # targeted + blanket suppressions hold; a wrong rule id does not
    unsuppressed = analyze_source(src, path)
    assert [(f.line, f.rule) for f in unsuppressed] == \
        [(21, "silent-except")]
    # audit mode still surfaces all three
    everything = analyze_source(src, path, with_suppressed=True)
    assert len(everything) == 3


def test_noqa_inside_string_is_not_a_suppression():
    src = (
        "def f(source):\n"
        "    try:\n"
        "        return source()\n"
        "    except Exception:\n"
        "        s = '# rafiki: noqa[silent-except]'\n"
        "        return s\n"
    )
    assert [f.rule for f in analyze_source(src)] == ["silent-except"]


# ---- engine behavior ----

def test_parse_error_is_a_finding_not_a_crash():
    src = open(os.path.join(FIXTURES, "parse_error.py.txt")).read()
    findings = analyze_source(src)
    assert [f.rule for f in findings] == ["parse-error"]
    assert findings[0].severity == "error"


def test_unknown_rule_id_raises():
    with pytest.raises(KeyError, match="no-such-rule"):
        analyze_paths([PACKAGE], select=["no-such-rule"])


def test_missing_path_raises_even_when_mixed_with_good_paths():
    # a typo'd CI argument must not yield a "clean" verdict on a tree
    # that was never visited
    with pytest.raises(OSError, match="no/such/dir"):
        analyze_paths([PACKAGE, "no/such/dir"])


def test_findings_report_real_locations():
    path = os.path.join(FIXTURES, "silent_except_bad.py")
    f = analyze_paths([path], select=["silent-except"])[0]
    line_text = open(path).read().splitlines()[f.line - 1]
    assert "except" in line_text


# ---- CLI ----

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "rafiki_tpu.cli", "lint", *args],
        capture_output=True, text=True, cwd=REPO_ROOT)


def test_cli_exits_zero_on_clean_tree():
    proc = _run_cli("rafiki_tpu")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def test_cli_exits_nonzero_on_fixtures():
    proc = _run_cli(os.path.join("tests", "fixtures", "lint"))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "silent-except" in proc.stdout


def test_cli_json_output():
    proc = _run_cli(os.path.join("tests", "fixtures", "lint"),
                    "--format", "json")
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["counts"]["total"] == len(payload["findings"]) > 0
    sample = payload["findings"][0]
    assert {"rule", "severity", "path", "line", "col",
            "message"} <= set(sample)


def test_cli_list_rules():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    for rule_id in RULE_FIXTURES:
        assert rule_id in proc.stdout


def test_cli_bad_path_exits_two():
    proc = _run_cli("no/such/dir")
    assert proc.returncode == 2
    assert "lint" in proc.stderr


def test_scripts_lint_runner():
    proc = subprocess.run(
        [sys.executable, os.path.join("scripts", "lint.py")],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert proc.returncode == 0, proc.stdout + proc.stderr
