"""Tier-1 gate for the static-analysis subsystem (docs/linting.md).

Two jobs:

1. **Self-clean**: running every rule over ``rafiki_tpu/`` itself must
   produce zero unsuppressed findings. This is the CI gate — any PR
   that introduces a traced host-sync, an unlocked write against a
   locked attr, or a silent broad except fails here with the finding
   text in the assertion message.
2. **Rule correctness**: every rule fires on its positive fixture and
   stays quiet on its negative fixture (``tests/fixtures/lint/``), the
   suppression dialect works, and the CLI exit codes hold.

No jax import, no device work — this file runs in milliseconds.
"""

import json
import os
import shutil
import subprocess
import sys

import pytest

from rafiki_tpu.analysis import (all_project_rules, all_rules,
                                 analyze_paths, analyze_project,
                                 analyze_source, get_project_rule,
                                 get_rule)
from rafiki_tpu.analysis.dataflow import all_flow_rules, get_flow_rule

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO_ROOT, "rafiki_tpu")
FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "lint")
PROJECT_FIXTURES = os.path.join(FIXTURES, "project")
FLOW_FIXTURES = os.path.join(FIXTURES, "flow")

#: rule id -> fixture stem; every registered rule must appear here
#: (the completeness test below enforces it), so adding a rule without
#: fixtures fails CI.
RULE_FIXTURES = {
    "jax-host-sync": "jax_host_sync",
    "jax-tracer-branch": "jax_tracer_branch",
    "jax-missing-donation": "jax_missing_donation",
    "silent-except": "silent_except",
    "library-internals": "library_internals",
    "obs-unregistered-metric": "obs_unregistered_metric",
    "blocking-transfer-in-decode-loop": "blocking_transfer",
}

#: flow rule id -> fixture stem under tests/fixtures/lint/flow/;
#: completeness against the flow registry enforced below
FLOW_RULE_FIXTURES = {
    "lock-release-path": "lock_release_path",
    "use-after-donate": "use_after_donate",
    "jit-recompile-hazard": "jit_recompile_hazard",
    "taint-wall-clock-flow": "taint_wall_clock_flow",
    "unvalidated-wire-input": "unvalidated_wire_input",
}

#: project rule id -> fixture directory stem under
#: tests/fixtures/lint/project/ (``<stem>_bad/`` + ``<stem>_ok/``
#: multi-module trees); completeness enforced like RULE_FIXTURES
PROJECT_RULE_FIXTURES = {
    "lock-order-cycle": "lock_cycle",
    "hub-verb-parity": "hub_parity",
    "metric-catalog-drift": "metric_drift",
    "budget-key-parity": "budget",
    "span-lifecycle": "span_lifecycle",
    "shared-state-race": "shared_state_race",
    "atomic-rmw-race": "atomic_rmw_race",
    "thread-lifecycle": "thread_lifecycle",
}


# ---- the gate ----

@pytest.fixture(scope="module")
def package_file_pass():
    """One timed per-file pass (module + flow rules) over the full
    package, shared by the self-clean gate and the runtime-budget
    test — the pass is the expensive part, not the assertions."""
    import time
    t0 = time.monotonic()
    findings = analyze_paths([PACKAGE])
    return findings, time.monotonic() - t0


@pytest.fixture(scope="module")
def package_project_pass():
    import time
    t0 = time.monotonic()
    findings = analyze_project([PACKAGE])
    return findings, time.monotonic() - t0


def test_repo_is_self_clean(package_file_pass):
    findings, _ = package_file_pass
    assert not findings, (
        "rafiki_tpu/ has unsuppressed lint findings — fix them or, for "
        "a documented intentional pattern, suppress the line with "
        "`# rafiki: noqa[rule-id]`:\n"
        + "\n".join(f.format() for f in findings))


def test_issue_catalog_covers_every_category():
    cats = {r.category for r in all_rules().values()}
    assert {"jax", "robustness"} <= cats
    assert len(all_rules()) >= 6
    # concurrency moved wholesale to the thread-aware project layer
    # when the module-local lock rules were retired (see
    # rules/concurrency.py)
    project_cats = {r.category for r in all_project_rules().values()}
    assert "concurrency" in project_cats


# ---- per-rule fixtures ----

@pytest.mark.parametrize("rule_id", sorted(RULE_FIXTURES))
def test_rule_fires_on_positive_fixture(rule_id):
    path = os.path.join(FIXTURES, RULE_FIXTURES[rule_id] + "_bad.py")
    findings = analyze_paths([path], select=[rule_id])
    assert findings, f"{rule_id} missed its positive fixture"
    assert all(f.rule == rule_id for f in findings)
    assert all(f.path == path and f.line > 0 for f in findings)


@pytest.mark.parametrize("rule_id", sorted(RULE_FIXTURES))
def test_rule_quiet_on_negative_fixture(rule_id):
    path = os.path.join(FIXTURES, RULE_FIXTURES[rule_id] + "_ok.py")
    findings = analyze_paths([path], select=[rule_id])
    assert not findings, (
        f"{rule_id} false-positives on its negative fixture:\n"
        + "\n".join(f.format() for f in findings))


def test_positive_fixtures_trigger_no_foreign_rules():
    """Each bad fixture demonstrates exactly one hazard class — a
    finding from another rule means the fixtures drifted."""
    for rule_id, stem in RULE_FIXTURES.items():
        path = os.path.join(FIXTURES, stem + "_bad.py")
        rules_hit = {f.rule for f in analyze_paths([path])}
        assert rules_hit == {rule_id}, (stem, rules_hit)


def test_every_registered_rule_has_fixtures():
    assert set(RULE_FIXTURES) == set(all_rules()), (
        "keep RULE_FIXTURES in sync with the registry (one positive + "
        "one negative fixture per rule)")
    for rule_id in RULE_FIXTURES:
        rule = get_rule(rule_id)
        assert rule.description and rule.category and rule.severity


# ---- flow (path-sensitive) rules ----

@pytest.mark.parametrize("rule_id", sorted(FLOW_RULE_FIXTURES))
def test_flow_rule_fires_with_trace(rule_id):
    path = os.path.join(FLOW_FIXTURES,
                        FLOW_RULE_FIXTURES[rule_id] + "_bad.py")
    findings = analyze_paths([path], select=[rule_id])
    assert findings, f"{rule_id} missed its positive fixture"
    for f in findings:
        assert f.rule == rule_id
        assert f.path == path and f.line > 0
        # the defining feature of a flow finding: a source→sink
        # witness, every step pinned to a real line
        assert f.trace, f"{rule_id} finding carries no trace"
        assert all(s.line > 0 and s.note for s in f.trace)
        assert "\n    " in f.format(), (
            "trace steps must render as indented lines in text output")


@pytest.mark.parametrize("rule_id", sorted(FLOW_RULE_FIXTURES))
def test_flow_rule_quiet_on_negative_fixture(rule_id):
    path = os.path.join(FLOW_FIXTURES,
                        FLOW_RULE_FIXTURES[rule_id] + "_ok.py")
    findings = analyze_paths([path], select=[rule_id])
    assert not findings, (
        f"{rule_id} false-positives on its negative fixture:\n"
        + "\n".join(f.format() for f in findings))


def test_flow_positive_fixtures_trigger_no_foreign_rules():
    """Flow fixtures run under the FULL per-file pass (module rules
    included) — exactly one hazard class per fixture."""
    for rule_id, stem in FLOW_RULE_FIXTURES.items():
        path = os.path.join(FLOW_FIXTURES, stem + "_bad.py")
        rules_hit = {f.rule for f in analyze_paths([path])}
        assert rules_hit == {rule_id}, (stem, rules_hit)


def test_every_flow_rule_has_fixtures():
    assert set(FLOW_RULE_FIXTURES) == set(all_flow_rules()), (
        "keep FLOW_RULE_FIXTURES in sync with the flow registry (one "
        "positive + one negative fixture per rule)")
    for rule_id in FLOW_RULE_FIXTURES:
        rule = get_flow_rule(rule_id)
        assert rule.description and rule.category and rule.severity
        # --explain contract: documented dataflow surface + a live
        # example every flow rule actually fires on
        assert rule.sources and rule.sinks and rule.sanitizers
        assert rule.example
        fired = analyze_source(rule.example, path="<example>",
                               select=[rule_id])
        assert fired, f"{rule_id}.example does not fire the rule"
        assert fired[0].trace


def test_flow_rule_ids_do_not_collide_with_module_rules():
    overlap = set(all_flow_rules()) & set(all_rules())
    assert not overlap, (
        f"flow and module registries share ids {overlap} — "
        "--select routing would be ambiguous")


def test_file_pass_runtime_budget(package_file_pass):
    """Per-file pass (module + flow rules) over the full package must
    fit the pre-commit budget (< 30s on CPU) — the flow rules run a
    CFG fixpoint per function, so this guards their cost."""
    _, elapsed = package_file_pass
    assert elapsed < 30.0, (
        f"per-file lint pass took {elapsed:.1f}s — over the 30s "
        "pre-commit budget; profile the CFG/taint fixpoints")


# ---- project (whole-program) rules ----

def test_repo_is_self_clean_under_project_rules(package_project_pass):
    """The CI gate for the cross-layer contracts: lock ordering, hub
    verb parity, metric catalogs, budget keys, span lifecycles."""
    findings, _ = package_project_pass
    assert not findings, (
        "rafiki_tpu/ has unsuppressed project-lint findings — fix the "
        "contract drift or, for a documented intentional pattern, "
        "suppress the line with `# rafiki: noqa[rule-id]` (``//`` / "
        "``<!--`` markers work in non-Python files):\n"
        + "\n".join(f.format() for f in findings))


@pytest.mark.parametrize("rule_id", sorted(PROJECT_RULE_FIXTURES))
def test_project_rule_fires_on_positive_fixture(rule_id):
    root = os.path.join(PROJECT_FIXTURES,
                        PROJECT_RULE_FIXTURES[rule_id] + "_bad")
    findings = analyze_project([root], select=[rule_id])
    assert findings, f"{rule_id} missed its positive fixture project"
    assert all(f.rule == rule_id for f in findings)
    assert all(f.line > 0 for f in findings)


@pytest.mark.parametrize("rule_id", sorted(PROJECT_RULE_FIXTURES))
def test_project_rule_quiet_on_negative_fixture(rule_id):
    root = os.path.join(PROJECT_FIXTURES,
                        PROJECT_RULE_FIXTURES[rule_id] + "_ok")
    findings = analyze_project([root], select=[rule_id])
    assert not findings, (
        f"{rule_id} false-positives on its negative fixture project:\n"
        + "\n".join(f.format() for f in findings))


def test_project_positive_fixtures_trigger_no_foreign_rules():
    for rule_id, stem in PROJECT_RULE_FIXTURES.items():
        root = os.path.join(PROJECT_FIXTURES, stem + "_bad")
        rules_hit = {f.rule for f in analyze_project([root])}
        assert rules_hit == {rule_id}, (stem, rules_hit)


def test_every_project_rule_has_fixtures():
    assert set(PROJECT_RULE_FIXTURES) == set(all_project_rules()), (
        "keep PROJECT_RULE_FIXTURES in sync with the project registry "
        "(one positive + one negative fixture project per rule)")
    for rule_id in PROJECT_RULE_FIXTURES:
        rule = get_project_rule(rule_id)
        assert rule.description and rule.category and rule.severity


def test_hub_fixture_reproduces_the_chaoshub_bug():
    """The historical regression this rule exists for: a decorator
    that silently fails to wrap a default-body verb."""
    root = os.path.join(PROJECT_FIXTURES, "hub_parity_bad")
    findings = analyze_project([root], select=["hub-verb-parity"])
    wrapper = [f for f in findings if "does not override" in f.message]
    assert wrapper and "ping" in wrapper[0].message
    wire = [f for f in findings if "XSTATS" in f.message]
    assert wire and wire[0].path.endswith("client.py")


def test_lock_fixture_reports_the_two_lock_cycle():
    root = os.path.join(PROJECT_FIXTURES, "lock_cycle_bad")
    findings = analyze_project([root], select=["lock-order-cycle"])
    cycles = [f for f in findings if "lock-order cycle" in f.message]
    assert len(cycles) == 1
    assert "alloc_lock" in cycles[0].message
    assert "evict_lock" in cycles[0].message


def test_project_findings_anchor_in_non_python_resources():
    """Drift findings point at the md/html surface that drifted, not
    just at Python."""
    root = os.path.join(PROJECT_FIXTURES, "metric_drift_bad")
    findings = analyze_project([root], select=["metric-catalog-drift"])
    exts = {f.path.rsplit(".", 1)[-1] for f in findings}
    assert {"md", "html", "py"} <= exts


def test_resource_noqa_suppression(tmp_path):
    """``// rafiki: noqa[rule]`` on the finding line silences a
    dashboard finding; audit mode still surfaces it."""
    (tmp_path / "w.py").write_text(
        "class W:\n"
        "    def __init__(self, metrics):\n"
        "        self.c = metrics.counter(\"requests_total\")\n")
    (tmp_path / "dashboard.html").write_text(
        "<script>\n"
        "panel.textContent = s.requests_total +\n"
        "  s.ghost_key;  // rafiki: noqa[metric-catalog-drift]\n"
        "</script>\n")
    root = str(tmp_path)
    clean = analyze_project([root], select=["metric-catalog-drift"])
    assert not clean, "\n".join(f.format() for f in clean)
    audit = analyze_project([root], select=["metric-catalog-drift"],
                            with_suppressed=True)
    assert [f for f in audit if "ghost_key" in f.message]


def test_project_pass_runtime_budget(package_project_pass):
    """The whole-program pass over the full package must stay cheap
    enough for a pre-commit hook (tier-1 budget: < 30s on CPU)."""
    _, elapsed = package_project_pass
    assert elapsed < 30.0, (
        f"project lint pass took {elapsed:.1f}s — over the 30s "
        "pre-commit budget; profile ProjectContext indexing or the "
        "rule bodies")


# ---- suppressions ----

def test_noqa_suppression_dialect():
    path = os.path.join(FIXTURES, "suppressed.py")
    src = open(path).read()
    # targeted + blanket suppressions hold; a wrong rule id does not
    unsuppressed = analyze_source(src, path)
    assert [(f.line, f.rule) for f in unsuppressed] == \
        [(21, "silent-except")]
    # audit mode still surfaces all three
    everything = analyze_source(src, path, with_suppressed=True)
    assert len(everything) == 3


def test_noqa_inside_string_is_not_a_suppression():
    src = (
        "def f(source):\n"
        "    try:\n"
        "        return source()\n"
        "    except Exception:\n"
        "        s = '# rafiki: noqa[silent-except]'\n"
        "        return s\n"
    )
    assert [f.rule for f in analyze_source(src)] == ["silent-except"]


# ---- engine behavior ----

def test_parse_error_is_a_finding_not_a_crash():
    src = open(os.path.join(FIXTURES, "parse_error.py.txt")).read()
    findings = analyze_source(src)
    assert [f.rule for f in findings] == ["parse-error"]
    assert findings[0].severity == "error"


def test_unknown_rule_id_raises():
    with pytest.raises(KeyError, match="no-such-rule"):
        analyze_paths([PACKAGE], select=["no-such-rule"])


def test_missing_path_raises_even_when_mixed_with_good_paths():
    # a typo'd CI argument must not yield a "clean" verdict on a tree
    # that was never visited
    with pytest.raises(OSError, match="no/such/dir"):
        analyze_paths([PACKAGE, "no/such/dir"])


def test_findings_report_real_locations():
    path = os.path.join(FIXTURES, "silent_except_bad.py")
    f = analyze_paths([path], select=["silent-except"])[0]
    line_text = open(path).read().splitlines()[f.line - 1]
    assert "except" in line_text


# ---- CLI ----

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "rafiki_tpu.cli", "lint", *args],
        capture_output=True, text=True, cwd=REPO_ROOT)


def test_cli_exits_zero_on_clean_tree():
    proc = _run_cli("rafiki_tpu")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def test_cli_exits_nonzero_on_fixtures():
    proc = _run_cli(os.path.join("tests", "fixtures", "lint"))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "silent-except" in proc.stdout


def test_cli_json_output():
    proc = _run_cli(os.path.join("tests", "fixtures", "lint"),
                    "--format", "json")
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["counts"]["total"] == len(payload["findings"]) > 0
    sample = payload["findings"][0]
    assert {"rule", "severity", "path", "line", "col",
            "message"} <= set(sample)


def test_cli_list_rules():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    for rule_id in RULE_FIXTURES:
        assert rule_id in proc.stdout


def test_cli_bad_path_exits_two():
    proc = _run_cli("no/such/dir")
    assert proc.returncode == 2
    assert "lint" in proc.stderr


def test_scripts_lint_runner():
    proc = subprocess.run(
        [sys.executable, os.path.join("scripts", "lint.py")],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_project_flag_runs_whole_program_rules():
    bad = os.path.join("tests", "fixtures", "lint", "project",
                       "lock_cycle_bad")
    proc = _run_cli("--project", "--select", "lock-order-cycle", bad)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "lock-order cycle" in proc.stdout
    # without --project the project rules never run
    proc = _run_cli("--select", "lock-order-cycle", bad)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_list_rules_includes_project_rules():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    for rule_id in PROJECT_RULE_FIXTURES:
        assert rule_id in proc.stdout


def test_cli_sarif_output_schema_shape():
    proc = _run_cli("--project",
                    os.path.join("tests", "fixtures", "lint",
                                 "project", "budget_bad"),
                    "--format", "sarif")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["version"] == "2.1.0"
    assert "sarif-schema-2.1.0" in doc["$schema"]
    run = doc["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "rafiki-tpu-lint"
    rule_ids = {r["id"] for r in driver["rules"]}
    assert "budget-key-parity" in rule_ids
    assert run["results"], "findings must map to SARIF results"
    for res in run["results"]:
        assert res["ruleId"] in rule_ids
        assert res["level"] in ("error", "warning")
        assert res["message"]["text"]
        loc = res["locations"][0]["physicalLocation"]
        uri = loc["artifactLocation"]["uri"]
        assert "\\" not in uri, "SARIF URIs use forward slashes"
        assert loc["region"]["startLine"] >= 1
        assert loc["region"]["startColumn"] >= 1


def test_cli_sarif_flow_findings_carry_code_flows():
    """Flow findings render their witness path as SARIF codeFlows
    (for flow-aware viewers) AND relatedLocations (for the rest)."""
    proc = _run_cli(os.path.join("tests", "fixtures", "lint", "flow",
                                 "lock_release_path_bad.py"),
                    "--format", "sarif")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    results = doc["runs"][0]["results"]
    assert results
    for res in results:
        assert res["ruleId"] == "lock-release-path"
        flows = res["codeFlows"]
        locs = flows[0]["threadFlows"][0]["locations"]
        assert len(locs) >= 2, "a witness needs a source and a sink"
        for entry in locs:
            loc = entry["location"]
            assert loc["message"]["text"]
            phys = loc["physicalLocation"]
            assert phys["artifactLocation"]["uri"].endswith(
                "lock_release_path_bad.py")
            assert phys["region"]["startLine"] >= 1
            assert phys["region"]["startColumn"] >= 1
        related = res["relatedLocations"]
        assert [r["physicalLocation"] for r in related] == \
            [e["location"]["physicalLocation"] for e in locs]


def test_cli_explain_prints_dataflow_surface_and_example_trace():
    proc = _run_cli("--explain", "taint-wall-clock-flow")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = proc.stdout
    assert "taint-wall-clock-flow" in out
    assert "[flow:robustness/warning]" in out
    for section in ("sources", "sinks", "sanitizers", "example"):
        assert section in out, f"--explain missing {section!r} section"
    # the example is linted live: the rendered trace proves the rule
    # still fires on its own documentation
    assert "which the rule reports as:" in out
    assert "wall-clock" in out


def test_cli_explain_works_for_module_and_project_rules():
    for rule_id in ("silent-except", "lock-order-cycle"):
        proc = _run_cli("--explain", rule_id)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert rule_id in proc.stdout


def test_cli_explain_unknown_rule_exits_two():
    proc = _run_cli("--explain", "no-such-rule")
    assert proc.returncode == 2
    assert "no-such-rule" in proc.stderr


def test_cli_list_rules_tags_flow_rules():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    for rule_id, rule in all_flow_rules().items():
        assert rule_id in proc.stdout
        tag = f"[flow:{rule.category}/{rule.severity}]"
        assert tag in proc.stdout, f"missing {tag} for {rule_id}"


# ---- concurrency layer (thread model + race rules) ----

THREAD_RULES = ("shared-state-race", "atomic-rmw-race",
                "thread-lifecycle")


def test_cli_list_rules_tags_thread_rules():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    for rule_id in THREAD_RULES:
        rule = get_project_rule(rule_id)
        assert rule.layer == "threads"
        tag = f"[threads:{rule.category}/{rule.severity}]"
        line = next(ln for ln in proc.stdout.splitlines()
                    if ln.strip().startswith(rule_id))
        assert tag in line, f"missing {tag} for {rule_id}"


def test_cli_explain_thread_rule_prints_model_and_witness():
    proc = _run_cli("--explain", "shared-state-race")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = proc.stdout
    assert "[threads:concurrency/error]" in out
    # the example is analyzed live: its discovered roots and the
    # two-stack witness prove the model still works end to end
    assert "thread model:" in out
    assert "which the rule reports as:" in out
    assert out.count("thread [") >= 2


def test_race_finding_renders_both_thread_stacks():
    bad = os.path.join(PROJECT_FIXTURES, "shared_state_race_bad")
    findings = analyze_project([bad], select=["shared-state-race"])
    assert len(findings) == 1
    f = findings[0]
    assert len(f.threads) == 2
    labels = [label for label, steps in f.threads]
    assert len(set(labels)) == 2, "the two stacks are distinct contexts"
    assert all(steps for _, steps in f.threads)
    text = f.format()
    # two stack headers ("    thread [ctx]:"); the spawn-site note
    # inside a stack also says "thread [...]", so match the indent
    assert text.count("    thread [") == 2
    # the witness crosses modules, so steps carry their own files
    assert "reaper.py" in text and "slots.py" in text


def test_cli_sarif_race_findings_carry_two_thread_flows():
    proc = _run_cli("--project",
                    os.path.join("tests", "fixtures", "lint",
                                 "project", "shared_state_race_bad"),
                    "--format", "sarif")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    results = [r for r in doc["runs"][0]["results"]
               if r["ruleId"] == "shared-state-race"]
    assert results
    res = results[0]
    flows = res["codeFlows"]
    assert len(flows) == 1, "a race is ONE codeFlow with two stacks"
    tfs = flows[0]["threadFlows"]
    assert len(tfs) == 2
    assert len({tf["id"] for tf in tfs}) == 2
    for tf in tfs:
        assert tf["locations"]
        for entry in tf["locations"]:
            loc = entry["location"]
            assert loc["message"]["text"]
            phys = loc["physicalLocation"]
            assert phys["region"]["startLine"] >= 1
            assert phys["region"]["startColumn"] >= 1
    # relatedLocations = both stacks concatenated, for flat viewers
    related = res["relatedLocations"]
    assert len(related) == sum(len(tf["locations"]) for tf in tfs)


def test_retired_rule_noqa_ids_silence_successor_rules(tmp_path):
    """PR 3's per-module lock rules were folded into the thread-aware
    race rules; suppressions written against the old ids keep
    working (engine.RULE_ALIASES)."""
    proj = tmp_path / "proj"
    shutil.copytree(
        os.path.join(PROJECT_FIXTURES, "shared_state_race_bad"), proj)
    findings = analyze_project([str(proj)])
    assert [f.rule for f in findings] == ["shared-state-race"]
    # suppress at the finding's anchor line, old-id spelling
    anchored = os.path.join(str(proj), os.path.basename(findings[0].path))
    lines = open(anchored).read().splitlines()
    lines[findings[0].line - 1] += "  # rafiki: noqa[inconsistent-lock]"
    with open(anchored, "w") as f:
        f.write("\n".join(lines) + "\n")
    assert analyze_project([str(proj)]) == []
    # and the audit channel still surfaces it as suppressed
    audited = analyze_project([str(proj)], with_suppressed=True)
    assert [f.rule for f in audited] == ["shared-state-race"]


def _git(*args, cwd):
    return subprocess.run(["git", *args], capture_output=True,
                          text=True, cwd=cwd)


def test_cli_changed_only_scopes_to_changed_files(tmp_path):
    """Only files changed vs the base ref (plus untracked) are linted
    by the per-module pass."""
    repo = tmp_path / "repo"
    pkg = repo / "pkg"
    pkg.mkdir(parents=True)
    committed_bad = (
        "def f(job):\n"
        "    try:\n"
        "        return job()\n"
        "    except Exception:\n"
        "        return None\n")
    (pkg / "old.py").write_text(committed_bad)
    for cmd in (("init", "-q"),
                ("config", "user.email", "lint@test"),
                ("config", "user.name", "lint"),
                ("add", "."), ("commit", "-q", "-m", "seed")):
        proc = _git(*cmd, cwd=repo)
        assert proc.returncode == 0, proc.stderr
    # a NEW (untracked) file with the same hazard
    (pkg / "new.py").write_text(committed_bad.replace("f(", "g("))
    env = dict(os.environ, PYTHONPATH=REPO_ROOT)
    proc = subprocess.run(
        [sys.executable, "-m", "rafiki_tpu.cli", "lint",
         "--changed-only", "HEAD", "--format", "json", "pkg"],
        capture_output=True, text=True, cwd=repo, env=env)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    paths = {f["path"] for f in json.loads(proc.stdout)["findings"]}
    assert any(p.endswith("new.py") for p in paths)
    assert not any(p.endswith("old.py") for p in paths), (
        "committed-unchanged files must not be linted under "
        "--changed-only")


def test_cli_changed_only_bad_ref_exits_two(tmp_path):
    """A typo'd base ref must fail loudly, not lint nothing and
    report clean."""
    repo = tmp_path / "repo"
    repo.mkdir()
    (repo / "x.py").write_text("A = 1\n")
    assert _git("init", "-q", cwd=repo).returncode == 0
    env = dict(os.environ, PYTHONPATH=REPO_ROOT)
    proc = subprocess.run(
        [sys.executable, "-m", "rafiki_tpu.cli", "lint",
         "--changed-only", "no-such-ref", "."],
        capture_output=True, text=True, cwd=repo, env=env)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "lint" in proc.stderr


def test_scripts_precommit_hook():
    proc = subprocess.run(
        ["sh", os.path.join("scripts", "precommit.sh")],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert proc.returncode == 0, proc.stdout + proc.stderr
