"""Multi-adapter (S-LoRA-style) serving: N adapter-only fine-tunes of
one base share ONE continuous-batching engine, each request selecting
its fine-tune per slot (``LoRADense.n_adapters`` / ``stack_lora_adapters``
/ ``DecodeEngine adapter_id``).

The reference deploys best-N trials as N full model replicas
(SURVEY.md §3.3); this collapses LoRA trials onto one base's HBM and
one compiled step — exactness is proven against per-tree
``greedy_generate`` oracles, including mixed-adapter batches in flight
together.
"""

import itertools
import threading

import jax
import numpy as np
import pytest

from rafiki_tpu.models.llama_lora import (LlamaLoRA, greedy_generate,
                                          stack_lora_adapters)
from rafiki_tpu.serving.decode_engine import DecodeEngine

from test_decode_engine import KNOBS  # noqa: F401 — shared knobs


def _lora_variant(params, seed=7, scale=0.05):
    """A second 'fine-tune': same base, perturbed lora_a/lora_b only."""
    key = jax.random.PRNGKey(seed)
    counter = itertools.count()

    def leafmod(kp, x):
        path = "/".join(str(getattr(k, "key", k)) for k in kp).lower()
        if "lora_a" in path or "lora_b" in path:
            k2 = jax.random.fold_in(key, next(counter))
            return x + scale * jax.random.normal(k2, x.shape, x.dtype)
        return x

    return jax.tree_util.tree_map_with_path(leafmod, params)


def _oracle(module, tree, prompt, max_new):
    ids = np.asarray(prompt, np.int32)[None, :]
    lens = np.asarray([len(prompt)], np.int32)
    out = np.asarray(greedy_generate(module, tree, ids, lens, max_new))
    return [int(t) for t in out[0]]


def test_multi_adapter_engine_matches_solo_oracles(trained):  # noqa: F811
    """Requests against different adapters, in flight TOGETHER in one
    fused step, each reproduce exactly what their own param tree
    generates solo."""
    module0 = trained._module()
    tree_a = trained._params
    tree_b = _lora_variant(tree_a)
    stacked = stack_lora_adapters([tree_a, tree_b])
    module = trained._module(n_adapters=2)

    prompts = [np.asarray([1, 5, 9, 13], np.int32),
               np.asarray([1, 7], np.int32),
               np.asarray([2, 4, 6], np.int32)]
    max_new = 6
    eng = DecodeEngine(module, stacked, max_slots=4,
                       max_len=int(KNOBS["max_len"]), steps_per_sync=2,
                       prefill_chunk=4)
    # interleave adapters across concurrent slots
    for i, p in enumerate(prompts):
        eng.submit(("r", i), p, max_new, adapter_id=i % 2)
    got = {}
    for _ in range(300):
        if not eng.busy:
            break
        eng.step()
        for rid, ids in eng.poll():
            got[rid] = ids
    assert set(got) == {("r", i) for i in range(3)}
    assert eng.stats["max_concurrent"] == 3
    for i, p in enumerate(prompts):
        tree = tree_a if i % 2 == 0 else tree_b
        assert got[("r", i)] == _oracle(module0, tree, p, max_new), \
            f"adapter {i % 2} diverged from its solo oracle"
    # the two adapters really behave differently on the same prompt
    assert (_oracle(module0, tree_a, prompts[0], max_new)
            != _oracle(module0, tree_b, prompts[0], max_new))


def test_out_of_range_adapter_rejected(trained):  # noqa: F811
    """An unknown adapter_id must fail fast, not silently serve a
    different fine-tune (correct-looking wrong answer in multi-tenant
    serving); single-adapter engines ignore adapter_id entirely."""
    tree_a = trained._params
    stacked = stack_lora_adapters([tree_a, _lora_variant(tree_a)])
    module = trained._module(n_adapters=2)
    eng = DecodeEngine(module, stacked, max_slots=2,
                       max_len=int(KNOBS["max_len"]))
    with pytest.raises(ValueError, match="out of range"):
        eng.submit("r", np.asarray([1, 2], np.int32), 4, adapter_id=5)
    with pytest.raises(ValueError, match="out of range"):
        eng.register_prefix(np.asarray([1, 2], np.int32), adapter_id=-1)
    # single-adapter engines ignore the field (back-compat)
    solo = trained.make_decode_engine(max_slots=1, max_new_tokens=2)
    solo.submit("s", "tok1", adapter_id=99)  # no raise


def test_stack_validates_shared_base(trained):  # noqa: F811
    tree_a = trained._params
    tree_b = _lora_variant(tree_a)

    def bump_norm(kp, x):
        path = "/".join(str(getattr(k, "key", k)) for k in kp).lower()
        return x + 1e-3 if "final_norm" in path else x

    tree_bad = jax.tree_util.tree_map_with_path(bump_norm, tree_b)
    with pytest.raises(ValueError, match="adapters_only"):
        stack_lora_adapters([tree_a, tree_bad])
    # validate=False trusts the caller (provenance already known)
    stack_lora_adapters([tree_a, tree_bad], validate=False)


def test_adapters_only_training_freezes_everything_else(tmp_path):
    """Two adapters_only trainings (different data) share every
    non-adapter leaf bit-for-bit — the provenance contract
    stack_lora_adapters validates, produced by the real train path."""
    from rafiki_tpu.data import generate_text_classification_dataset

    knobs = {**KNOBS, "adapters_only": True}
    trees = []
    for seed in (0, 1):
        tr = str(tmp_path / f"train{seed}.jsonl")
        generate_text_classification_dataset(tr, 48, seed=seed)
        m = LlamaLoRA(**knobs)
        m.train(tr)
        trees.append(m._params)
    stacked = stack_lora_adapters(trees)  # must not raise
    flat = jax.tree_util.tree_leaves_with_path(stacked)
    lora = [p for p, _ in flat
            if "lora" in "/".join(str(getattr(k, "key", k))
                                  for k in p).lower()]
    assert lora, "no stacked adapter leaves found"
    # and the adapters themselves differ (training happened)
    a_leaves = {"/".join(str(getattr(k, "key", k)) for k in p): v
                for p, v in jax.tree_util.tree_leaves_with_path(trees[0])}
    diff = False
    for p, v in jax.tree_util.tree_leaves_with_path(trees[1]):
        path = "/".join(str(getattr(k, "key", k)) for k in p)
        if "lora" in path.lower() and not np.array_equal(
                np.asarray(a_leaves[path]), np.asarray(v)):
            diff = True
    assert diff, "adapters_only training left the adapters untouched"


def test_prefix_cache_gated_by_adapter(trained):  # noqa: F811
    """A registered prefix only fast-forwards requests whose adapter
    matches the one that computed its KV; other adapters prefill
    normally — and both produce exact solo-oracle outputs."""
    module0 = trained._module()
    tree_a = trained._params
    tree_b = _lora_variant(tree_a)
    stacked = stack_lora_adapters([tree_a, tree_b])
    module = trained._module(n_adapters=2)

    prefix = np.asarray([3, 1, 4, 1, 5], np.int32)
    tail = np.asarray([9, 2, 6], np.int32)
    prompt = np.concatenate([prefix, tail])
    max_new = 5
    eng = DecodeEngine(module, stacked, max_slots=2,
                       max_len=int(KNOBS["max_len"]), steps_per_sync=1,
                       prefill_chunk=2)
    assert eng.register_prefix(prefix, adapter_id=1) == len(prefix)
    eng.submit("hit", prompt, max_new, adapter_id=1)
    eng.submit("miss", prompt, max_new, adapter_id=0)
    got = {}
    for _ in range(300):
        if not eng.busy:
            break
        eng.step()
        for rid, ids in eng.poll():
            got[rid] = ids
    assert eng.stats["prefix_hits"] == 1  # only the adapter-1 request
    assert got["hit"] == _oracle(module0, tree_b, prompt, max_new)
    assert got["miss"] == _oracle(module0, tree_a, prompt, max_new)

    # PER-ADAPTER registry: give adapter 0 its own prefix too — both
    # tenants now hit, each against its own snapshot, both exact
    assert eng.register_prefix(prefix, adapter_id=0) == len(prefix)
    eng.submit("hit0", prompt, max_new, adapter_id=0)
    eng.submit("hit1", prompt, max_new, adapter_id=1)
    got2 = {}
    for _ in range(300):
        if not eng.busy:
            break
        eng.step()
        for rid, ids in eng.poll():
            got2[rid] = ids
    assert eng.stats["prefix_hits"] == 3
    assert got2["hit0"] == _oracle(module0, tree_a, prompt, max_new)
    assert got2["hit1"] == _oracle(module0, tree_b, prompt, max_new)
    # empty ids clear ONE adapter's prefix, not the other's
    eng.register_prefix(np.zeros((0,), np.int32), adapter_id=1)
    eng.submit("cleared", prompt, max_new, adapter_id=1)
    eng.submit("kept", prompt, max_new, adapter_id=0)
    for _ in range(300):
        if not eng.busy:
            break
        eng.step()
        eng.poll()
    assert not eng.busy, "engine failed to drain"
    assert eng.stats["prefix_hits"] == 4  # only the adapter-0 request


@pytest.mark.slow
def test_multi_adapter_composes_with_int8(trained):  # noqa: F811
    """quantize_int8 + multi-adapter: the shared base serves int8
    (quantized ONCE for all tenants) while the stacked f32 adapters
    still route per request — each adapter's generations equal its own
    solo QUANTIZED oracle."""
    from rafiki_tpu.models.llama_lora import quantize_llama_params

    tree_a = trained._params
    tree_b = _lora_variant(tree_a)
    mq = LlamaLoRA(**{**KNOBS, "quantize_int8": True})
    mq.load_parameters(trained.dump_parameters())
    eng = mq.make_multi_adapter_engine([tree_a, tree_b], max_slots=2,
                                       max_new_tokens=5)
    assert eng.engine.module.quantized and eng.engine.module.n_adapters == 2

    prompt = np.asarray([1, 5, 9], np.int32)
    eng.engine.submit("a", prompt, 5, adapter_id=0)
    eng.engine.submit("b", prompt, 5, adapter_id=1)
    got = {}
    for _ in range(200):
        if not eng.busy:
            break
        eng.step()
        for rid, ids in eng.engine.poll():
            got[rid] = ids
    module_q = trained._module(quantized=True)
    for rid, tree in (("a", tree_a), ("b", tree_b)):
        assert got[rid] == _oracle(module_q, quantize_llama_params(tree),
                                   prompt, 5), rid


@pytest.mark.slow
def test_worker_boots_multi_adapter_from_store(trained):  # noqa: F811
    """The deployment path: a worker handed extra_adapter_trials loads
    each trial's dump from the ParamStore and boots ONE stacked engine —
    adapter 0 the primary trial, adapter 1 the extra — exactly what the
    services manager's MULTI_ADAPTER budget flag spawns."""
    from rafiki_tpu.serving.predictor import Predictor
    from rafiki_tpu.serving.queues import InProcQueueHub
    from rafiki_tpu.store.param_store import ParamStore
    from rafiki_tpu.worker.inference import InferenceWorker

    store = ParamStore.from_uri("mem://")
    dump_a = trained.dump_parameters()
    store.save("t-best", dump_a)
    dump_b = dict(dump_a)
    dump_b["params"] = jax.tree_util.tree_map(
        np.asarray, _lora_variant(trained._params))
    store.save("t-second", dump_b)

    worker = InferenceWorker(LlamaLoRA, "t-best", KNOBS, store,
                             InProcQueueHub(), "w0", decode_loop=True,
                             max_slots=4, max_new_tokens=6,
                             extra_adapter_trials=["t-second"])
    assert worker.engine is not None
    assert worker.engine.engine.n_adapters == 2
    hub = worker.hub
    wt = threading.Thread(target=worker.run, daemon=True)
    wt.start()
    try:
        pred = Predictor(hub, ["w0"], gather_timeout=120.0)
        out0, _ = pred.predict(["tok1 tok2 tok3"],
                               sampling={"adapter_id": 0})
        out1, _ = pred.predict(["tok1 tok2 tok3"],
                               sampling={"adapter_id": 1})
        assert out0 != out1
    finally:
        worker.stop()
        wt.join(timeout=10)

    # a mismatched base fails the boot LOUDLY, naming the remedy
    def bump(kp, x):
        path = "/".join(str(getattr(k, "key", k)) for k in kp).lower()
        return x + 1e-3 if "final_norm" in path else x

    dump_bad = dict(dump_a)
    dump_bad["params"] = jax.tree_util.tree_map(
        np.asarray,
        jax.tree_util.tree_map_with_path(bump, trained._params))
    store.save("t-bad", dump_bad)
    with pytest.raises(RuntimeError, match="adapters_only"):
        InferenceWorker(LlamaLoRA, "t-best", KNOBS, store,
                        InProcQueueHub(), "w1", decode_loop=True,
                        extra_adapter_trials=["t-bad"])


@pytest.mark.slow
def test_multi_adapter_through_serving_stack(trained):  # noqa: F811
    """adapter_id rides the sampling dict through Predictor → worker →
    engine: the same prompt served under adapter 0 vs 1 gives the two
    solo-engine answers."""
    from rafiki_tpu.serving.predictor import Predictor
    from rafiki_tpu.serving.queues import InProcQueueHub
    from rafiki_tpu.store.param_store import ParamStore
    from rafiki_tpu.worker.inference import InferenceWorker

    tree_a = trained._params
    tree_b = _lora_variant(tree_a)
    multi = trained.make_multi_adapter_engine([tree_a, tree_b],
                                              max_slots=4,
                                              max_new_tokens=6)

    store = ParamStore.from_uri("mem://")
    store.save("t0", trained.dump_parameters())
    hub = InProcQueueHub()
    worker = InferenceWorker(LlamaLoRA, "t0", KNOBS, store, hub, "w0",
                             decode_loop=True, max_slots=4,
                             max_new_tokens=6)
    worker.engine = multi  # serve the stacked engine
    wt = threading.Thread(target=worker.run, daemon=True)
    wt.start()
    try:
        pred = Predictor(hub, ["w0"], gather_timeout=120.0)
        out0, _ = pred.predict(["tok1 tok2 tok3"],
                               sampling={"adapter_id": 0})
        out1, _ = pred.predict(["tok1 tok2 tok3"],
                               sampling={"adapter_id": 1})
        # negative ids must be REJECTED (error reply), not silently
        # served by adapter 0 — wrong-tenant answers are the failure
        # mode the validation exists for
        _, info_neg = pred.predict(["tok1 tok2 tok3"],
                                   sampling={"adapter_id": -1})
        assert info_neg["errors"] and \
            "out of range" in info_neg["errors"][0]
        # solo engines as oracles, through the same tokenizer
        solo0 = trained.make_decode_engine(max_slots=1, max_new_tokens=6)
        solo0.submit("s", "tok1 tok2 tok3")
        while solo0.busy:
            solo0.step()
        ref0 = solo0.poll()[0][1]
        assert out0 == [ref0]
        assert out1 != out0, "adapter_id ignored through the stack"
    finally:
        worker.stop()
        wt.join(timeout=10)
