"""Native kv/queue server: build, wire protocol, queues, param backend."""

import threading
import time

import numpy as np
import pytest

from rafiki_tpu.native import KVClient, KVServer, ensure_built
from rafiki_tpu.store.param_store import ParamStore


@pytest.fixture(scope="module")
def server():
    ensure_built()
    with KVServer() as s:
        yield s


@pytest.fixture()
def client(server):
    c = KVClient(server.host, server.port)
    c.flushall()
    yield c
    c.close()


def test_kv_roundtrip(client):
    assert client.ping()
    client.set("a", b"hello")
    assert client.get("a") == b"hello"
    assert client.get("missing") is None
    assert client.exists("a") and not client.exists("missing")
    assert client.delete("a") == 1
    assert client.get("a") is None


def test_binary_safety(client):
    blob = bytes(range(256)) * 1000 + b"\r\n$*"
    client.set("bin", blob)
    assert client.get("bin") == blob


def test_keys_glob(client):
    for k in ["params:t1", "params:t2", "queue:q1"]:
        client.set(k, b"x")
    assert client.keys("params:*") == ["params:t1", "params:t2"]
    assert client.keys("*") == ["params:t1", "params:t2", "queue:q1"]


def test_incr(client):
    assert client.incr("ctr") == 1
    assert client.incr("ctr") == 2


def test_queue_fifo(client):
    client.lpush("q", b"first")
    client.lpush("q", b"second")
    assert client.llen("q") == 2
    # BRPOP pops the tail → FIFO relative to LPUSH
    assert client.brpop("q", 1.0) == ("q", b"first")
    assert client.brpop("q", 1.0) == ("q", b"second")


def test_brpop_timeout(client):
    t0 = time.monotonic()
    assert client.brpop("empty", 0.2) is None
    assert 0.15 <= time.monotonic() - t0 < 2.0


def test_brpop_blocks_until_push(server, client):
    got = {}

    def consumer():
        c2 = KVClient(server.host, server.port)
        got["v"] = c2.brpop("bq", 5.0)
        c2.close()

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.1)
    client.lpush("bq", b"payload")
    t.join(timeout=5)
    assert got["v"] == ("bq", b"payload")


def test_brpop_multi_key(client):
    client.lpush("q2", b"v2")
    assert client.brpop(["q1", "q2"], 1.0) == ("q2", b"v2")


def test_param_store_kv_backend(server):
    store = ParamStore.from_uri(f"kv://{server.host}:{server.port}")
    params = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
              "meta": {"n": 3}}
    store.save("trial-1", params)
    # fresh store (cold cache) → exercises the backend path
    store2 = ParamStore.from_uri(f"kv://{server.host}:{server.port}")
    loaded = store2.load("trial-1")
    np.testing.assert_array_equal(loaded["w"], params["w"])
    assert "trial-1" in store2.keys()
    store2.delete("trial-1")
    assert store2.load("trial-1") is None


def test_expire_collects_key(client):
    client.set("mortal", b"v")
    client.expire("mortal", 0.15)
    assert client.get("mortal") == b"v"  # not yet
    time.sleep(0.35)  # past TTL + the 50ms purge throttle
    client.ping()  # any command triggers the purge scan
    assert client.get("mortal") is None


def test_expire_survives_del_and_recreate(client):
    """kvd delta vs Redis (deliberate): a reply queue's TTL outlives
    discard, so a worker's LATE push after the predictor's DEL is still
    collected instead of leaking forever (ADVICE r3)."""
    client.expire("q:preds:q1", 0.15)  # armed before the key exists
    client.lpush("q:preds:q1", b"late reply")  # straggler recreates it
    assert client.llen("q:preds:q1") == 1
    time.sleep(0.35)
    client.ping()
    assert client.llen("q:preds:q1") == 0
    assert not client.exists("q:preds:q1")


def test_ttl_introspection(client):
    assert client.ttl("nope") == -2          # missing key
    client.set("immortal", b"v")
    assert client.ttl("immortal") == -1      # no expiry
    client.expire("immortal", 30)
    assert client.ttl("immortal") == 30      # rounds UP, like redis
    # a DEL'd key reports missing even while its TTL survives
    # internally (the reply-queue condemnation deviation)
    client.set("gone", b"v")
    client.expire("gone", 100)
    client.delete("gone")
    assert client.ttl("gone") == -2
