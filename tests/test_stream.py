"""Streaming image loader (BASELINE config #2): index-only startup,
bounded decode window, deterministic augmentation, throughput, and the
CNN-template integration."""

import os
import time

import numpy as np
import pytest

from rafiki_tpu.data.stream import (StreamingImageDataset,
                                    generate_streaming_image_zip,
                                    should_stream)


@pytest.fixture(scope="module")
def png_zip(tmp_path_factory):
    p = str(tmp_path_factory.mktemp("sz") / "ds.zip")
    generate_streaming_image_zip(p, 300, image_shape=(32, 32, 3),
                                 n_classes=4, seed=0, fmt="png")
    return p


def test_index_and_shapes(png_zip):
    ds = StreamingImageDataset(png_zip)
    assert ds.n == 300 and ds.n_classes == 4
    assert ds.image_shape == (32, 32, 3)
    assert ds.classes == ["c0", "c1", "c2", "c3"]


def test_batches_cover_every_sample_once(png_zip):
    ds = StreamingImageDataset(png_zip)
    seen = []
    for b in ds.iter_batches(64, epoch=0, shuffle=True, seed=1):
        assert b["x"].shape == (64, 32, 32, 3) and b["x"].dtype == np.uint8
        assert b["y"].shape == (64,)
        seen.extend(b["y"][b["mask"]].tolist())
    assert len(seen) == 300
    # label histogram matches the index exactly (each sample once)
    np.testing.assert_array_equal(np.bincount(seen, minlength=4),
                                  np.bincount(ds.labels, minlength=4))


def test_augmentation_deterministic_per_identity(png_zip):
    ds = StreamingImageDataset(png_zip)

    def first_batch(epoch, seed, augment):
        return next(iter(ds.iter_batches(32, epoch=epoch, shuffle=True,
                                         seed=seed, augment=augment)))

    a = first_batch(0, 7, True)
    b = first_batch(0, 7, True)
    np.testing.assert_array_equal(a["x"], b["x"])  # replayable epoch
    c = first_batch(1, 7, True)
    assert not np.array_equal(a["x"], c["x"])  # epochs differ
    raw = first_batch(0, 7, False)
    assert raw["x"].shape == a["x"].shape
    assert not np.array_equal(a["x"], raw["x"])  # augment does something


def test_decode_window_is_bounded(png_zip):
    """Consuming one batch of a 300-sample set must not decode the whole
    archive — the sliding window caps outstanding decodes."""
    ds = StreamingImageDataset(png_zip, prefetch_batches=2)
    calls = [0]
    orig = ds._decode

    def counting(name):
        calls[0] += 1
        return orig(name)

    ds._decode = counting
    it = ds.iter_batches(32, epoch=0)
    next(it)
    # window = prefetch_batches (2) × batch_size (32) + consumed batch
    assert calls[0] <= 2 * 32 + 32 + ds.n_workers, calls[0]
    it.close()  # unwind the generator's executor


def test_throughput_over_1k_images_per_s(tmp_path):
    p = str(tmp_path / "fast.zip")
    generate_streaming_image_zip(p, 4000, image_shape=(32, 32, 3),
                                 n_classes=4, seed=0, fmt="npy")
    ds = StreamingImageDataset(p, n_workers=4)
    n = 0
    t0 = time.perf_counter()
    for b in ds.iter_batches(128, epoch=0, augment=True):
        n += int(b["mask"].sum())
    rate = n / (time.perf_counter() - t0)
    assert n == 4000
    if (os.cpu_count() or 1) < 4:
        # the 1k img/s bar is calibrated for the 4 decode workers
        # actually running in parallel; on a 1-2 core CI box the
        # CORRECTNESS half above still runs, only the rate bar skips
        pytest.skip(f"{rate:.0f} img/s on {os.cpu_count()} cores "
                    "(rate bar needs >= 4)")
    assert rate > 1000, f"{rate:.0f} img/s"


def test_should_stream_policy(png_zip, monkeypatch):
    assert StreamingImageDataset.is_streamable(png_zip)
    assert not should_stream(png_zip)  # tiny file stays in-memory
    monkeypatch.setenv("RAFIKI_FORCE_STREAMING", "1")
    assert should_stream(png_zip)


@pytest.mark.slow
def test_resnet_trains_from_stream(png_zip, tmp_path, monkeypatch):
    """End-to-end config #2 slice: ResNet template trains from the
    streaming path (forced), loss decreases, eval works on the same
    archive through the in-memory eval path."""
    from rafiki_tpu.model import TrainContext
    from rafiki_tpu.models.resnet import ResNetClassifier

    monkeypatch.setenv("RAFIKI_FORCE_STREAMING", "1")
    knobs = {"variant": "resnet18", "width_mult": 0.25, "batch_size": 32,
             "max_epochs": 4, "learning_rate": 0.1, "weight_decay": 1e-4,
             "bf16": False, "quick_train": False, "share_params": False}
    model = ResNetClassifier(**knobs)
    ctx = TrainContext()
    model.train(png_zip, ctx)
    losses = ctx.logger.get_values("loss")
    assert len(losses) >= 2 and losses[-1] < losses[0]
    acc = model.evaluate(png_zip)
    assert acc > 0.5, acc  # quadrant classes are easy
