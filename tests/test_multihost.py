"""Multi-host distributed backend, scaled down to one box (SURVEY.md §4):
two REAL OS processes rendezvous at a JAX coordination service and run one
SPMD program over their joint device set — the same code path a v5e
multi-host pod uses, with virtual CPU devices standing in for chips.

Runs as subprocesses (not in-proc fakes) because jax.distributed wires a
per-process global runtime; the parent asserts on both children's output.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")

    from rafiki_tpu.parallel.multihost import (
        global_batch, global_mesh, initialize_from_env, is_coordinator)

    assert initialize_from_env(timeout_s=300), "env did not request multi-process"
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())  # 2 hosts x 4

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = global_mesh(data=4, model=2)
    # `data` rows must span processes (DCN-outermost layout)
    row_procs = {d.process_index for d in mesh.devices[:, 0]}
    assert len(row_procs) == 2, row_procs

    # each "host" contributes ITS half of the global batch
    pid = jax.process_index()
    local = np.arange(8, dtype=np.float32).reshape(8, 1) + 8 * pid
    batch = global_batch({"x": local}, mesh)
    assert batch["x"].shape == (16, 1)

    @jax.jit
    def global_mean(b):
        return jnp.mean(b["x"])  # cross-process all-reduce under the hood

    out = float(global_mean(batch))
    assert abs(out - 7.5) < 1e-6, out  # mean(0..15): needs BOTH halves
    print(f"proc{pid} ok mean={out} coordinator={is_coordinator()}",
          flush=True)
""")




def _run_two_procs(child_src, tmp_extra_env=None, timeout=600):
    """Spawn two rendezvous processes running ``child_src``; returns
    [(returncode, combined output), ...] (kills both on timeout)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update({
            "RAFIKI_COORDINATOR": f"127.0.0.1:{port}",
            "RAFIKI_NUM_PROCESSES": "2",
            "RAFIKI_PROCESS_ID": str(pid),
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        })
        env.update(tmp_extra_env or {})
        env.pop("JAX_PLATFORMS", None)  # children pin cpu themselves
        procs.append(subprocess.Popen(
            [sys.executable, "-c", child_src], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    results = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        results.append((p.returncode, out))
    return results

@pytest.mark.slow
def test_two_process_global_mesh_allreduce(tmp_path):
    results = _run_two_procs(CHILD)
    for pid, (rc, out) in enumerate(results):
        assert rc == 0, f"proc{pid} failed:\n{out}"
        assert f"proc{pid} ok mean=7.5" in out, out
    assert "coordinator=True" in results[0][1]


class _FakeDev:
    def __init__(self, pid, did):
        self.process_index, self.id = pid, did

    def __repr__(self):
        return f"d{self.process_index}.{self.id}"


def test_global_mesh_refuses_model_axis_across_hosts():
    from rafiki_tpu.parallel.multihost import global_mesh

    # 4 hosts x 2 devices, model=4: a model group would span two hosts
    devs = [_FakeDev(p, d) for p in range(4) for d in range(2)]
    with pytest.raises(ValueError, match="ICI"):
        global_mesh(data=2, model=4, devices=devs)
    # model=2 fits within each host: accepted, data rows span hosts
    mesh = global_mesh(data=4, model=2, devices=devs)
    for row in mesh.devices:
        assert len({d.process_index for d in row}) == 1, row


def test_initialize_from_env_rejects_partial_env(monkeypatch):
    from rafiki_tpu.parallel import multihost

    monkeypatch.setenv(multihost.COORD_ENV, "127.0.0.1:1")
    monkeypatch.delenv(multihost.NUM_PROCS_ENV, raising=False)
    monkeypatch.delenv(multihost.PROC_ID_ENV, raising=False)
    with pytest.raises(ValueError, match="RAFIKI_NUM_PROCESSES"):
        multihost.initialize_from_env()


CKPT_CHILD = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")

    from rafiki_tpu.parallel.multihost import (global_batch, global_mesh,
                                               initialize_from_env)

    assert initialize_from_env(timeout_s=300)
    import numpy as np
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    from rafiki_tpu.store.sharded_ckpt import ShardedCheckpointer

    pid = jax.process_index()
    mesh = global_mesh(data=8, model=1)
    # each "host" contributes its half of a known global array
    local = (np.arange(32, dtype=np.float32).reshape(8, 4)
             + 100 * pid)
    batch = global_batch({"x": local}, mesh)   # (16, 4) over 8 devices

    ck = ShardedCheckpointer(os.environ["CKPT_DIR"])
    # no explicit sync_fn: multi-process saves self-fence by default
    written = ck.save("t0", {"x": batch["x"]})
    total = 16 * 4 * 4  # f32 bytes of the global array
    # the disjoint-writer rule, for real: each process wrote only the
    # shards of ITS devices — half the array each
    assert written == total // 2, (written, total)

    # both processes restore into the SAME global sharding and see the
    # full array (each reads the shard files its devices need)
    out = ck.restore("t0", {"x": batch["x"]})
    got = multihost_utils.process_allgather(out["x"], tiled=True)
    # expected: proc0 contributed rows 0..7, proc1 rows 8..15
    want = np.concatenate([
        np.arange(32, dtype=np.float32).reshape(8, 4),
        np.arange(32, dtype=np.float32).reshape(8, 4) + 100])
    np.testing.assert_array_equal(np.asarray(got).reshape(16, 4), want)
    multihost_utils.sync_global_devices("restored")
    print(f"proc{pid} ckpt ok written={written}", flush=True)
""")


@pytest.mark.slow
def test_two_process_sharded_checkpoint_disjoint_writers(tmp_path):
    """The sharded checkpointer's multi-host contract, with two REAL
    processes: identical manifests, each process writes only its own
    devices' shards (bytes == total/2 each), self-fencing barriers
    (prep / commit / return), and both restore the full global array."""
    results = _run_two_procs(
        CKPT_CHILD, tmp_extra_env={"CKPT_DIR": str(tmp_path / "ck")})
    for pid, (rc, out) in enumerate(results):
        assert rc == 0, f"proc{pid} failed:\n{out}"
        assert f"proc{pid} ckpt ok" in out, out
