"""VGG family: module shapes, template contract, DP training."""

import pytest

import jax
import numpy as np

from rafiki_tpu.constants import TaskType
from rafiki_tpu.data import generate_image_classification_dataset
from rafiki_tpu.model import TrainContext, test_model_class
from rafiki_tpu.models.vgg import VGG, VGGClassifier

TINY = {"variant": "vgg11", "width_mult": 0.25, "batch_size": 32,
        "max_epochs": 5, "learning_rate": 0.05, "weight_decay": 1e-4,
        "bf16": False, "quick_train": False, "share_params": False}


def test_vgg_module_shapes():
    m = VGG(stage_sizes=(1, 1), width=16, n_classes=7)
    x = np.zeros((2, 32, 32, 3), np.float32)
    variables = m.init(jax.random.PRNGKey(0), x, train=False)
    out = m.apply(variables, x, train=False)
    assert out.shape == (2, 7)
    # deep variant on small inputs must not pool below 1 px
    deep = VGG(stage_sizes=(1, 1, 1, 1, 1), width=8, n_classes=3)
    xs = np.zeros((1, 8, 8, 1), np.float32)
    v2 = deep.init(jax.random.PRNGKey(0), xs, train=False)
    assert deep.apply(v2, xs, train=False).shape == (1, 3)


@pytest.mark.slow
def test_vgg_template_contract(tmp_path):
    tr, va = str(tmp_path / "t.npz"), str(tmp_path / "v.npz")
    generate_image_classification_dataset(tr, 192, seed=0)
    ds = generate_image_classification_dataset(va, 48, seed=1)
    preds = test_model_class(VGGClassifier, TaskType.IMAGE_CLASSIFICATION,
                             tr, va, queries=[ds.images[0]], knobs=TINY)
    assert len(preds) == 1 and len(preds[0]) == ds.n_classes


@pytest.mark.slow
def test_vgg_trains_data_parallel(tmp_path):
    """Train over 8 virtual devices; loss must decrease."""
    tr = str(tmp_path / "t.npz")
    generate_image_classification_dataset(tr, 192, seed=0)
    model = VGGClassifier(**TINY)
    ctx = TrainContext(devices=list(jax.devices()))
    model.train(tr, ctx)
    losses = ctx.logger.get_values("loss")
    assert len(losses) >= 2 and losses[-1] < losses[0]
