"""POS-tagging + tabular templates: contract + learnability.

These bring TaskType.POS_TAGGING / TABULAR_CLASSIFICATION alive
(SURVEY.md §2 "Model zoo": bigram HMM, BiLSTM tagger, sklearn DT,
plus the TPU-native tabular MLP).
"""

import numpy as np
import pytest

from rafiki_tpu.constants import TaskType
from rafiki_tpu.data import (generate_corpus_dataset,
                             generate_tabular_dataset,
                             load_tabular_dataset)
from rafiki_tpu.model import test_model_class
from rafiki_tpu.models.pos_tagging import BigramHMM, BiLSTMTagger
from rafiki_tpu.models.sklearn_models import SklearnDecisionTree
from rafiki_tpu.models.tabular import JaxTabularMLP


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    d = tmp_path_factory.mktemp("corpus")
    tr, va = str(d / "train.jsonl"), str(d / "val.jsonl")
    generate_corpus_dataset(tr, 500, seed=0)
    ds = generate_corpus_dataset(va, 120, seed=1)
    return tr, va, ds


@pytest.fixture(scope="module")
def table(tmp_path_factory):
    d = tmp_path_factory.mktemp("table")
    tr, va = str(d / "train.npz"), str(d / "val.npz")
    generate_tabular_dataset(tr, 1024, seed=0)
    ds = generate_tabular_dataset(va, 256, seed=1)
    return tr, va, ds


def test_hmm_contract_and_learns(corpus):
    tr, va, ds = corpus
    preds = test_model_class(
        BigramHMM, TaskType.POS_TAGGING, tr, va,
        queries=[ds.sentences[0][0]],
        knobs={"emission_k": 0.01, "transition_k": 0.1,
               "min_word_count": 1})
    assert len(preds[0]) == len(ds.sentences[0][0])
    assert all(t in ds.tag_names for t in preds[0])
    m = BigramHMM(emission_k=0.01, transition_k=0.1, min_word_count=1)
    m.train(tr)
    # the synthetic corpus has a dominant word→tag lexicon: an HMM must
    # beat uniform guessing (1/8) by a wide margin
    assert m.evaluate(va) > 0.7


@pytest.mark.slow
def test_bilstm_contract_and_learns(corpus):
    tr, va, ds = corpus
    preds = test_model_class(
        BiLSTMTagger, TaskType.POS_TAGGING, tr, va,
        queries=[ds.sentences[0][0]],
        knobs={"max_epochs": 10, "vocab_size": 1024, "embed_dim": 32,
               "hidden_dim": 64, "learning_rate": 5e-3, "batch_size": 32,
               "max_len": 32, "quick_train": False, "share_params": False})
    assert len(preds[0]) == len(ds.sentences[0][0])
    m = BiLSTMTagger(max_epochs=10, vocab_size=1024, embed_dim=32,
                     hidden_dim=64, learning_rate=5e-3, batch_size=32,
                     max_len=32, quick_train=False, share_params=False)
    m.train(tr)
    assert m.evaluate(va) > 0.7


def test_decision_tree_contract_and_learns(table):
    tr, va, ds = table
    preds = test_model_class(
        SklearnDecisionTree, TaskType.TABULAR_CLASSIFICATION, tr, va,
        queries=[ds.features[0]],
        knobs={"max_depth": 8, "min_samples_split": 4,
               "min_impurity_decrease": 1e-6, "criterion": "gini"})
    assert len(preds[0]) == ds.n_classes
    m = SklearnDecisionTree(max_depth=8, min_samples_split=4,
                            min_impurity_decrease=1e-6, criterion="gini")
    m.train(tr)
    # teacher is a depth-3 axis-aligned tree with 10% label noise: a DT
    # should essentially recover it
    assert m.evaluate(va) > 0.8
    # loaded-from-arrays predictor matches the freshly fit one
    blob = m.dump_parameters()
    m2 = SklearnDecisionTree(max_depth=8, min_samples_split=4,
                             min_impurity_decrease=1e-6, criterion="gini")
    m2.load_parameters(blob)
    q = ds.features[:32]
    np.testing.assert_allclose(m.predict(list(q)), m2.predict(list(q)))


def test_tabular_mlp_contract_and_learns(table):
    tr, va, ds = table
    preds = test_model_class(
        JaxTabularMLP, TaskType.TABULAR_CLASSIFICATION, tr, va,
        queries=[ds.features[0]],
        knobs={"max_epochs": 10, "hidden_layer_count": 2,
               "hidden_layer_units": 64, "dropout": 0.1,
               "learning_rate": 1e-2, "batch_size": 128,
               "quick_train": False, "share_params": False})
    assert len(preds[0]) == ds.n_classes
    m = JaxTabularMLP(max_epochs=10, hidden_layer_count=2,
                      hidden_layer_units=64, dropout=0.1,
                      learning_rate=1e-2, batch_size=128,
                      quick_train=False, share_params=False)
    m.train(tr)
    # the synthetic teacher is an axis-aligned tree: trees reach ~0.87
    # here but an MLP on 1k rows plateaus high-0.7s; assert it learns
    # well above chance (1/3), same bar as the HMM/BiLSTM tests
    assert m.evaluate(va) > 0.7


def test_tabular_csv_roundtrip(tmp_path):
    ds = generate_tabular_dataset("", 64, n_features=4, seed=3)
    p = tmp_path / "t.csv"
    with open(p, "w") as f:
        f.write("f0,f1,f2,f3,label\n")
        for row, lab in zip(ds.features, ds.labels):
            f.write(",".join(f"{v:.6f}" for v in row) + f",{lab}\n")
    loaded = load_tabular_dataset(str(p))
    assert loaded.n_classes == ds.n_classes
    np.testing.assert_allclose(loaded.features, ds.features, atol=1e-5)
    np.testing.assert_array_equal(loaded.labels, ds.labels)


def test_gbdt_contract_and_learns(table):
    from rafiki_tpu.models.sklearn_models import SklearnGBDT

    tr, va, ds = table
    preds = test_model_class(
        SklearnGBDT, TaskType.TABULAR_CLASSIFICATION, tr, va,
        queries=[ds.features[0]],
        knobs={"n_estimators": 60, "learning_rate_gb": 0.1,
               "max_depth": 3, "subsample": 1.0})
    assert len(preds[0]) == ds.n_classes
    m = SklearnGBDT(n_estimators=60, learning_rate_gb=0.1, max_depth=3,
                    subsample=1.0)
    m.train(tr)
    # boosted trees should beat the single tree's ~0.87 bar comfortably
    assert m.evaluate(va) > 0.8


def test_gbdt_probs_match_sklearn(table):
    """The array-exported ensemble must reproduce sklearn's own
    predict_proba (raw-score accumulation + link reimplementation)."""
    from sklearn.ensemble import GradientBoostingClassifier

    from rafiki_tpu.data import load_tabular_dataset
    from rafiki_tpu.models.sklearn_models import SklearnGBDT

    tr, va, ds = table
    m = SklearnGBDT(n_estimators=25, learning_rate_gb=0.2, max_depth=3,
                    subsample=1.0)
    m.train(tr)
    tds = load_tabular_dataset(tr)
    ref = GradientBoostingClassifier(n_estimators=25, learning_rate=0.2,
                                     max_depth=3, subsample=1.0,
                                     random_state=0)
    ref.fit(tds.features, tds.labels)
    vds = load_tabular_dataset(va)
    ours = m._probs(vds.features)
    theirs = ref.predict_proba(vds.features)
    np.testing.assert_allclose(ours, theirs, atol=1e-5)


def test_svm_contract_and_matches_sklearn(table):
    """SVM contract round-trip + OVO decision parity with sklearn's own
    predictions on the val set."""
    from sklearn.svm import SVC

    from rafiki_tpu.data import load_tabular_dataset
    from rafiki_tpu.models.sklearn_models import SklearnSVM

    tr, va, ds = table
    preds = test_model_class(
        SklearnSVM, TaskType.TABULAR_CLASSIFICATION, tr, va,
        queries=[ds.features[0]],
        knobs={"C": 1.0, "kernel": "rbf", "gamma_scale": 1.0})
    assert len(preds[0]) == ds.n_classes
    m = SklearnSVM(C=1.0, kernel="rbf", gamma_scale=1.0)
    m.train(tr)
    assert m.evaluate(va) > 0.6

    tds = load_tabular_dataset(tr)
    mean = tds.features.mean(axis=0)
    std = tds.features.std(axis=0) + 1e-6
    x = (tds.features - mean) / std
    gamma = 1.0 / (x.shape[1] * x.var())
    ref = SVC(C=1.0, kernel="rbf", gamma=gamma, random_state=0)
    ref.fit(x, tds.labels)
    vds = load_tabular_dataset(va)
    ours = np.argmax(m._probs(np.asarray(vds.features, np.float64)), -1)
    theirs = ref.predict((vds.features - mean) / std)
    assert np.mean(ours == theirs) > 0.98, np.mean(ours == theirs)
