"""Pretrained-weight ingestion (models/convert.py): HF-name mapping,
sharded import on the 8-device mesh, and the VERDICT round-trip —
synthetic safetensors → 2-D sharded Llama → generation matches the
dense-load oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rafiki_tpu.models.convert import (export_llama_safetensors,
                                       hf_name_for,
                                       import_llama_safetensors)
from rafiki_tpu.models.llama_lora import TP_RULES, Llama, greedy_generate
from rafiki_tpu.parallel.sharding import make_mesh, param_shardings

CFG = dict(vocab_size=512, max_len=32, hidden_dim=64, depth=2,
           n_heads=4, n_kv_heads=2, mlp_dim=128, lora_rank=4)


@pytest.fixture(scope="module")
def module_params():
    module = Llama(**CFG)
    params = module.init(jax.random.PRNGKey(7),
                         jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def test_hf_name_mapping(module_params):
    _, params = module_params
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    names = set()
    for kp, _ in flat:
        path = tuple(str(getattr(k, "key", k)) for k in kp)
        mapped = hf_name_for(path)
        if path[-1] in ("lora_a", "lora_b"):
            assert mapped is None
        else:
            name, _t = mapped
            assert name not in names  # bijective
            names.add(name)
    assert "model.embed_tokens.weight" in names
    assert "model.layers.1.self_attn.q_proj.weight" in names
    assert "model.layers.0.mlp.down_proj.weight" in names
    assert "lm_head.weight" in names
    with pytest.raises(KeyError):
        hf_name_for(("block_0", "mystery", "kernel"))


def test_export_import_roundtrip_dense(module_params, tmp_path):
    module, params = module_params
    path = str(tmp_path / "ckpt.safetensors")
    export_llama_safetensors(params, path)
    loaded = import_llama_safetensors(path, params, mesh=None)
    for (kp, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(params)[0],
            jax.tree_util.tree_flatten_with_path(loaded)[0]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=str(kp))


def test_sharded_import_matches_and_places(module_params, tmp_path):
    """Import onto the 2-D (data=4, model=2) mesh: every leaf lands in
    its param_shardings placement AND is bitwise equal to the source."""
    module, params = module_params
    path = str(tmp_path / "ckpt.safetensors")
    export_llama_safetensors(params, path)
    mesh = make_mesh(jax.devices()[:8], model=2)
    loaded = import_llama_safetensors(path, params, mesh=mesh,
                                      tp_rules=TP_RULES, fsdp=True,
                                      min_size=2 ** 10)
    expected = param_shardings(params, mesh, tp_rules=TP_RULES,
                               fsdp=True, min_size=2 ** 10)
    flat_l = jax.tree_util.tree_flatten_with_path(loaded)[0]
    flat_p = {tuple(str(getattr(k, "key", k)) for k in kp): v
              for kp, v in jax.tree_util.tree_flatten_with_path(params)[0]}
    flat_e = {tuple(str(getattr(k, "key", k)) for k in kp): v
              for kp, v in
              jax.tree_util.tree_flatten_with_path(expected)[0]}
    n_sharded = 0
    for kp, arr in flat_l:
        path_t = tuple(str(getattr(k, "key", k)) for k in kp)
        np.testing.assert_array_equal(np.asarray(arr),
                                      np.asarray(flat_p[path_t]),
                                      err_msg=str(path_t))
        if path_t[-1] in ("lora_a", "lora_b"):
            continue  # kept from template, placed by the caller
        assert arr.sharding == flat_e[path_t], path_t
        if any(s is not None for s in arr.sharding.spec):
            n_sharded += 1
    assert n_sharded >= 5  # the big projections actually sharded


def test_roundtrip_generation_matches_dense_oracle(module_params,
                                                   tmp_path):
    """The VERDICT acceptance test: synthetic safetensors → 2-D sharded
    import → generation identical to the dense in-memory weights."""
    module, params = module_params
    path = str(tmp_path / "ckpt.safetensors")
    export_llama_safetensors(params, path)
    mesh = make_mesh(jax.devices()[:8], model=2)
    loaded = import_llama_safetensors(path, params, mesh=mesh,
                                      tp_rules=TP_RULES, fsdp=True,
                                      min_size=2 ** 10)
    prompts = np.asarray([[1, 5, 9, 13], [1, 7, 0, 0]], np.int32)
    lens = np.asarray([4, 2], np.int32)
    ref = np.asarray(greedy_generate(module, params, prompts, lens, 6))
    got = np.asarray(greedy_generate(module, loaded, prompts, lens, 6))
    np.testing.assert_array_equal(got, ref)


def test_sharded_multifile_checkpoint(module_params, tmp_path):
    """HF Llama-3 8B ships as model-0000X-of-0000Y.safetensors + an
    index.json — the import must resolve names across shard files, via
    the directory OR the index path."""
    import json

    from safetensors.numpy import save_file

    module, params = module_params
    tensors = {}
    for p, leaf in [
            (tuple(str(getattr(k, "key", k)) for k in kp), v)
            for kp, v in jax.tree_util.tree_flatten_with_path(params)[0]]:
        mapped = hf_name_for(p)
        if mapped:
            name, t = mapped
            arr = np.asarray(leaf)
            tensors[name] = np.ascontiguousarray(arr.T if t else arr)
    names = sorted(tensors)
    half = len(names) // 2
    d = tmp_path / "ckpt"
    d.mkdir()
    save_file({n: tensors[n] for n in names[:half]},
              str(d / "model-00001-of-00002.safetensors"))
    save_file({n: tensors[n] for n in names[half:]},
              str(d / "model-00002-of-00002.safetensors"))
    weight_map = {n: ("model-00001-of-00002.safetensors" if i < half
                      else "model-00002-of-00002.safetensors")
                  for i, n in enumerate(names)}
    with open(d / "model.safetensors.index.json", "w") as f:
        json.dump({"weight_map": weight_map}, f)

    for path in (str(d), str(d / "model.safetensors.index.json")):
        loaded = import_llama_safetensors(path, params)
        np.testing.assert_array_equal(
            np.asarray(loaded["tok_embed"]["embedding"]),
            np.asarray(params["tok_embed"]["embedding"]))
        np.testing.assert_array_equal(
            np.asarray(loaded["block_1"]["down"]["kernel"]),
            np.asarray(params["block_1"]["down"]["kernel"]))


def test_missing_tensor_is_loud(module_params, tmp_path):
    from safetensors.numpy import save_file

    module, params = module_params
    path = str(tmp_path / "partial.safetensors")
    save_file({"model.embed_tokens.weight":
               np.zeros((CFG["vocab_size"], CFG["hidden_dim"]),
                        np.float32)}, path)
    with pytest.raises(KeyError, match="missing"):
        import_llama_safetensors(path, params)


def test_shape_mismatch_is_loud(module_params, tmp_path):
    module, params = module_params
    path = str(tmp_path / "ckpt.safetensors")
    export_llama_safetensors(params, path)
    wrong = Llama(**{**CFG, "hidden_dim": 128})
    wrong_params = wrong.init(jax.random.PRNGKey(0),
                              jnp.zeros((1, 8), jnp.int32))["params"]
    with pytest.raises(ValueError, match="shape"):
        import_llama_safetensors(path, wrong_params)


def test_llama_template_trains_with_bpe_and_pretrained(tmp_path):
    """End-to-end config #5 slice: BPE artifact + pretrained base →
    LlamaLoRA.train fine-tunes LoRA on top and serves with exact
    detokenization (no id→token table)."""
    from rafiki_tpu.data import generate_text_classification_dataset
    from rafiki_tpu.data.bpe import ByteBPETokenizer
    from rafiki_tpu.models.llama_lora import LlamaLoRA

    ds_path = str(tmp_path / "corpus.jsonl")
    generate_text_classification_dataset(ds_path, 48, seed=0)

    # train the tokenizer on the same corpus file contents
    import json as _json
    texts = [rec["text"] for line in open(ds_path) if line.strip()
             for rec in [_json.loads(line)] if "text" in rec]
    tok = ByteBPETokenizer.train(texts, vocab_size=300)
    tok_path = str(tmp_path / "bpe.json")
    tok.save(tok_path)

    knobs = {"max_epochs": 1, "vocab_size": 0,  # follows the artifact
             "hidden_dim": 64, "depth": 2, "n_heads": 4, "kv_ratio": 2,
             "lora_rank": 4, "max_len": 32, "model_parallel": 1,
             "learning_rate": 1e-2, "batch_size": 8, "bf16": False,
             "quick_train": True, "share_params": False,
             "tokenizer_path": tok_path}

    # build the "pretrained" base from a throwaway instance's shapes
    base = LlamaLoRA(**knobs)
    module = base._module()
    assert module.vocab_size == tok.vocab_size
    params = module.init(jax.random.PRNGKey(3),
                         jnp.zeros((1, 8), jnp.int32))["params"]
    ckpt = str(tmp_path / "base.safetensors")
    export_llama_safetensors(params, ckpt)

    model = LlamaLoRA(**{**knobs, "pretrained_path": ckpt})
    model.train(ds_path)
    # base weights came from the checkpoint, not random reinit
    got_embed = np.asarray(model._params["tok_embed"]["embedding"])
    np.testing.assert_array_equal(
        got_embed, np.asarray(params["tok_embed"]["embedding"]))
    # serving round-trip with REAL detokenization via dump/load
    blob = model.dump_parameters()
    assert blob["meta"].get("bpe_merges")
    fresh = LlamaLoRA(**knobs)
    fresh.load_parameters(blob)
    out = fresh.predict(["the quick"])
    assert isinstance(out[0], str)
    assert "<" not in out[0]  # no unknown-id placeholders — exact decode


def test_read_hf_rope_theta(tmp_path):
    import json

    from rafiki_tpu.models.convert import read_hf_rope_theta

    # absent config → None (no crash)
    assert read_hf_rope_theta(str(tmp_path)) is None
    (tmp_path / "config.json").write_text(
        json.dumps({"rope_theta": 500000.0}))
    assert read_hf_rope_theta(str(tmp_path)) == 500000.0
    # a checkpoint FILE resolves its sibling config
    (tmp_path / "model.safetensors").write_bytes(b"")
    assert read_hf_rope_theta(
        str(tmp_path / "model.safetensors")) == 500000.0
    (tmp_path / "config.json").write_text("{not json")
    assert read_hf_rope_theta(str(tmp_path)) is None


def test_read_hf_rope_config_scaling(tmp_path):
    import json

    from rafiki_tpu.models.convert import read_hf_rope_config

    (tmp_path / "config.json").write_text(json.dumps(
        {"rope_theta": 500000.0,
         "rope_scaling": {"rope_type": "llama3", "factor": 8}}))
    theta, scaling = read_hf_rope_config(str(tmp_path))
    assert theta == 500000.0
    assert scaling == {"rope_type": "llama3", "factor": 8}
