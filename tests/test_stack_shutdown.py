"""Stack lifecycle: graceful shutdown, restart adoption, CLI wiring.

VERDICT round-2 items #7/#8: SIGTERM on the admin must stop every child
(kvd data plane included) and leave MetaStore consistent; a restarted
admin must reap stale RUNNING rows; `--slot-size`/`--workers` must reach
the ServicesManager.
"""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

from rafiki_tpu.admin.services_manager import ServicesManager
from rafiki_tpu.parallel.mesh import DeviceSpec
from rafiki_tpu.store.meta_store import MetaStore
from rafiki_tpu.utils.http import json_request


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except (ProcessLookupError, PermissionError):
        return False


def _start_admin(work: Path, extra_cfg: dict) -> subprocess.Popen:
    cfg = {"workdir": str(work), "db_path": str(work / "meta.db"),
           "host": "127.0.0.1", "port": 0,
           "port_file": str(work / "admin.port"), **extra_cfg}
    (work / "admin.json").write_text(json.dumps(cfg))
    env = dict(os.environ)
    env["RAFIKI_JAX_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    proc = subprocess.Popen(
        [sys.executable, "-m", "rafiki_tpu.admin.app", "--config",
         str(work / "admin.json")],
        stdout=open(work / "admin.log", "ab"), stderr=subprocess.STDOUT,
        env=env, start_new_session=True)
    deadline = time.monotonic() + 120
    port_file = work / "admin.port"
    while time.monotonic() < deadline:
        if port_file.exists() and port_file.read_text().strip():
            return proc
        assert proc.poll() is None, (work / "admin.log").read_text()[-2000:]
        time.sleep(0.1)
    proc.kill()
    raise AssertionError("admin did not come up")


@pytest.mark.slow
def test_sigterm_stops_children_and_metastore_consistent(tmp_path):
    proc = _start_admin(tmp_path, {"slot_size": 1})
    port = int((tmp_path / "admin.port").read_text())
    health = json_request("GET", f"http://127.0.0.1:{port}/health",
                          timeout=10)
    assert health["ok"]

    # the data plane (kvd) is a recorded child with a live pid
    meta = MetaStore(str(tmp_path / "meta.db"))
    rows = [r for r in meta.get_services()
            if r["status"] not in ("STOPPED", "ERRORED")]
    assert rows, "expected at least the data-plane service row"
    child_pids = [int(r["pid"]) for r in rows if int(r.get("pid") or 0)]
    assert child_pids and all(_pid_alive(p) for p in child_pids)

    os.kill(proc.pid, signal.SIGTERM)
    assert proc.wait(timeout=30) == 0

    for p in child_pids:
        for _ in range(50):
            if not _pid_alive(p):
                break
            time.sleep(0.1)
        assert not _pid_alive(p), f"orphaned child pid {p}"
    # every service row finalized
    meta2 = MetaStore(str(tmp_path / "meta.db"))
    for r in meta2.get_services():
        assert r["status"] in ("STOPPED", "ERRORED"), r


@pytest.mark.slow
def test_restart_reaps_stale_rows(tmp_path):
    proc = _start_admin(tmp_path, {"slot_size": 1})
    meta = MetaStore(str(tmp_path / "meta.db"))
    # SIGKILL: graceful shutdown never runs, rows stay RUNNING/STARTED
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait(timeout=10)
    stale = [r for r in meta.get_services()
             if r["status"] not in ("STOPPED", "ERRORED")]
    assert stale, "SIGKILL should have left stale rows"

    (tmp_path / "admin.port").unlink()
    proc2 = _start_admin(tmp_path, {"slot_size": 1})
    try:
        meta2 = MetaStore(str(tmp_path / "meta.db"))
        for r in meta2.get_services():
            # stale rows reaped; only the new admin's children are live
            if r["status"] not in ("STOPPED", "ERRORED"):
                assert _pid_alive(int(r["pid"])), r
    finally:
        os.kill(proc2.pid, signal.SIGTERM)
        proc2.wait(timeout=30)


def test_slot_size_reaches_allocator():
    """--slot-size wiring: slot_size=2 over 8 devices -> 4 slots."""
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        meta = MetaStore(str(Path(d) / "meta.db"))
        mgr = ServicesManager(
            meta, d, slot_size=2, platform="cpu",
            devices=[DeviceSpec(id=i) for i in range(8)],
            default_workers=3)
        assert mgr.allocator.free_count() == 4
        assert mgr.default_workers == 3


def test_cli_stack_parser_has_slot_size_and_workers():
    from rafiki_tpu.cli import main as cli_main  # noqa: F401 — import ok
    import argparse

    from rafiki_tpu import cli

    parser = argparse.ArgumentParser()
    sub = parser.add_subparsers(dest="cmd")
    cli._register_service_commands(sub)
    args = parser.parse_args(["stack", "status", "--slot-size", "2",
                              "--workers", "3"])
    assert args.slot_size == 2 and args.workers == 3


def test_unknown_platform_env_warns(caplog):
    import logging

    from rafiki_tpu.parallel.mesh import (SubMeshAllocator,
                                          submesh_env_vars)

    alloc = SubMeshAllocator([DeviceSpec(id=0), DeviceSpec(id=1)], 1)
    slot = alloc.acquire()
    with caplog.at_level(logging.WARNING):
        env = submesh_env_vars("axon", slot)
    assert env == {}
    assert any("confinement" in r.message for r in caplog.records)


def test_train_job_rejects_unknown_dataset(tmp_path):
    from rafiki_tpu.admin.admin import Admin

    meta = MetaStore(str(tmp_path / "meta.db"))
    mgr = ServicesManager(meta, str(tmp_path), slot_size=1, platform="cpu",
                          devices=[DeviceSpec(id=0)])
    admin = Admin(meta, mgr)
    user = meta.get_user_by_email("superadmin@rafiki")
    with pytest.raises(ValueError, match="neither a registered dataset"):
        admin.create_train_job(user["id"], "app", "IMAGE_CLASSIFICATION",
                               "nonexistent-id", "also-nonexistent",
                               {"TRIAL_COUNT": 1})
