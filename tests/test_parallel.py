"""Mesh partitioning + sharding on the virtual 8-device CPU slice."""

import math
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from rafiki_tpu.parallel import (SubMeshAllocator, batch_sharding, make_mesh,
                                 param_shardings, partition_devices,
                                 replicate_tree, shard_batch,
                                 submesh_env_vars)
from rafiki_tpu.parallel.mesh import SubMesh, _tile_shape


def test_partition_devices_sizes():
    devs = jax.devices()
    assert len(devs) == 8
    for size in (1, 2, 4, 8):
        slots = partition_devices(devs, size)
        assert len(slots) == 8 // size
        all_ids = sorted(d.id for slot in slots for d in slot)
        assert all_ids == sorted(d.id for d in devs)  # disjoint cover
    with pytest.raises(ValueError):
        partition_devices(devs, 3)


def test_tile_shape_rectangles():
    assert _tile_shape(4, 4, 4) in ((2, 2), (1, 4), (4, 1))
    r, c = _tile_shape(4, 4, 4)
    assert r * c == 4
    assert _tile_shape(2, 4, 2)[0] * _tile_shape(2, 4, 2)[1] == 2
    assert _tile_shape(1, 8, 8) == (1, 8)


class _FakeDev:
    """Device stub with TPU-style coords, for topology tests."""

    def __init__(self, id_, x, y):
        self.id = id_
        self.coords = (x, y, 0)


@pytest.mark.parametrize("gw,gh,size", [(4, 4, 4), (4, 2, 4), (2, 4, 2),
                                        (8, 2, 4), (4, 4, 8)])
def test_partition_is_ici_contiguous_on_grid(gw, gh, size):
    # v5e-style grids; every slot must be a contiguous rectangle
    devs = [_FakeDev(y * gw + x, x, y) for y in range(gh) for x in range(gw)]
    slots = partition_devices(devs, size)
    assert len(slots) == gw * gh // size
    for slot in slots:
        xs = sorted(d.coords[0] for d in slot)
        ys = sorted(d.coords[1] for d in slot)
        # contiguous rectangle: bounding box area == slot size
        area = (xs[-1] - xs[0] + 1) * (ys[-1] - ys[0] + 1)
        assert area == size, f"fragmented slot: {[d.coords for d in slot]}"


def test_submesh_allocator():
    alloc = SubMeshAllocator(jax.devices(), 2)
    assert alloc.n_slots == 4
    slots = [alloc.acquire() for _ in range(4)]
    assert alloc.free_count() == 0
    assert alloc.acquire(timeout=0.05) is None
    alloc.release(slots[1])
    got = alloc.acquire(timeout=1.0)
    assert got is not None and got.index == slots[1].index
    with pytest.raises(ValueError):
        alloc.release(slots[1]) or alloc.release(got) or alloc.release(got)


def test_submesh_allocator_blocking_handoff():
    alloc = SubMeshAllocator(jax.devices(), 4)
    a = alloc.acquire()
    b = alloc.acquire()
    results = []

    def waiter():
        results.append(alloc.acquire(timeout=5.0))

    t = threading.Thread(target=waiter)
    t.start()
    alloc.release(a)
    t.join()
    assert results[0] is not None and results[0].index == a.index


def test_submesh_mesh_axes():
    alloc = SubMeshAllocator(jax.devices(), 4)
    sm = alloc.acquire()
    mesh = sm.mesh({"data": 2, "model": 2})
    assert mesh.shape == {"data": 2, "model": 2}
    with pytest.raises(ValueError):
        sm.mesh({"data": 3})


def test_submesh_env_vars():
    sm = SubMesh(0, list(jax.devices())[:2])
    env = submesh_env_vars("cpu", sm)
    assert "device_count=2" in env["XLA_FLAGS"]
    tpu_env = submesh_env_vars("tpu", sm)
    assert tpu_env["TPU_VISIBLE_CHIPS"] == "0,1"


def test_data_parallel_train_step_on_mesh():
    """A real dp training step over the 8-device mesh: the loss/grad math
    must match the single-device result (XLA inserts the psum)."""
    mesh = make_mesh(data=8, model=1)
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(16, 4)).astype(np.float32))
    x = rng.normal(size=(32, 16)).astype(np.float32)
    y = rng.integers(0, 4, size=(32,))

    def loss_fn(w, xb, yb):
        logits = xb @ w
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, yb[:, None], 1))

    grad_fn = jax.jit(
        jax.grad(loss_fn),
        in_shardings=(NamedSharding(mesh, P()), batch_sharding(mesh),
                      NamedSharding(mesh, P("data"))),
        out_shardings=NamedSharding(mesh, P()))
    xs = shard_batch(x, mesh)
    ys = jax.device_put(y, NamedSharding(mesh, P("data")))
    ws = replicate_tree(w, mesh)
    g_sharded = grad_fn(ws, xs, ys)
    g_local = jax.grad(loss_fn)(w, jnp.asarray(x), jnp.asarray(y))
    np.testing.assert_allclose(np.asarray(g_sharded), np.asarray(g_local),
                               rtol=2e-5, atol=2e-6)


def test_param_shardings_tp_and_fsdp():
    mesh = make_mesh(data=4, model=2)
    params = {
        "attn": {"q_proj": jnp.zeros((256, 512)),
                 "o_proj": jnp.zeros((512, 256))},
        "mlp": {"up": jnp.zeros((256, 1024)), "down": jnp.zeros((1024, 256))},
        "norm": {"scale": jnp.zeros((256,))},
    }
    sh = param_shardings(
        params, mesh,
        tp_rules={"q_proj": -1, "up": -1, "o_proj": 0, "down": 0},
        fsdp=True, min_size=1024)
    assert sh["attn"]["q_proj"].spec[-1] == "model"
    assert sh["attn"]["o_proj"].spec[0] == "model"
    # fsdp fills the other dim with data
    assert "data" in tuple(sh["mlp"]["up"].spec)
    # small norm scale stays replicated
    assert tuple(sh["norm"]["scale"].spec) == ()
    # shardings must be placeable
    placed = jax.device_put(params["attn"]["q_proj"], sh["attn"]["q_proj"])
    assert placed.sharding.spec == sh["attn"]["q_proj"].spec


class _FakeDev3D:
    """Device stub with 3-D torus coords (v4/v5p-style)."""

    def __init__(self, id_, x, y, z):
        self.id = id_
        self.coords = (x, y, z)


@pytest.mark.parametrize("gx,gy,gz,size", [(2, 2, 4, 4), (2, 2, 2, 2),
                                           (4, 2, 2, 8), (2, 2, 4, 2)])
def test_partition_is_ici_contiguous_on_3d_torus(gx, gy, gz, size):
    """VERDICT r3 weak #6: coords[2] must be honored — every slot is a
    contiguous BOX on the 3-D torus, not an index-order stripe."""
    devs = [_FakeDev3D(z * gx * gy + y * gx + x, x, y, z)
            for z in range(gz) for y in range(gy) for x in range(gx)]
    slots = partition_devices(devs, size)
    assert len(slots) == gx * gy * gz // size
    seen = set()
    for slot in slots:
        assert len(slot) == size
        vol = 1
        for dim in range(3):
            vals = sorted(d.coords[dim] for d in slot)
            vol *= vals[-1] - vals[0] + 1
        assert vol == size, \
            f"fragmented 3-D slot: {[d.coords for d in slot]}"
        seen.update(d.id for d in slot)
    assert len(seen) == gx * gy * gz  # every device in exactly one slot


def test_submesh_env_bounds_include_z():
    from rafiki_tpu.parallel.mesh import SubMesh, submesh_env_vars

    # a slot spanning z: 1x1x4 column on a 3-D torus
    devs = [_FakeDev3D(i, 0, 0, i) for i in range(4)]
    env = submesh_env_vars("tpu", SubMesh(0, devs))
    assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,1,4"
    # and a 2x2x1 tile keeps the 2-D form
    devs2 = [_FakeDev3D(i, i % 2, i // 2, 0) for i in range(4)]
    env2 = submesh_env_vars("tpu", SubMesh(0, devs2))
    assert env2["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,2,1"


def test_tile_shape_nd_boxes():
    from rafiki_tpu.parallel.mesh import _tile_shape_nd

    assert math.prod(_tile_shape_nd((2, 2, 4), 4)) == 4
    assert math.prod(_tile_shape_nd((4, 4, 4), 8)) == 8
    assert _tile_shape_nd((1, 1, 8), 8) == (1, 1, 8)
    # halving prefers the longest axis → near-cubic tiles
    t = _tile_shape_nd((8, 2, 2), 8)
    assert max(t) <= 4
    with pytest.raises(ValueError):
        _tile_shape_nd((3, 5), 7)


def test_pad_batch_to_axis():
    """Leading-dim round-up to the mesh data axis: exact multiples pass
    through untouched; everything else tiles up to the next multiple
    with repeated rows."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from rafiki_tpu.parallel.sharding import pad_batch_to_axis

    import numpy as np

    mesh = Mesh(np.array(jax.devices()[:6]).reshape(3, 2),
                ("data", "model"))
    x = jnp.arange(8 * 2, dtype=jnp.float32).reshape(8, 2)
    out = pad_batch_to_axis(x, mesh)
    assert out.shape == (9, 2)  # next multiple of data=3
    np.testing.assert_array_equal(np.asarray(out[:8]), np.asarray(x))
    np.testing.assert_array_equal(np.asarray(out[8]), np.asarray(x[0]))
    # exact multiple: identity
    x6 = jnp.ones((6, 2))
    assert pad_batch_to_axis(x6, mesh) is x6
    # data axis larger than the batch: tile up to one full multiple
    mesh8 = Mesh(np.array(jax.devices()[:8]).reshape(8, 1),
                 ("data", "model"))
    out8 = pad_batch_to_axis(jnp.ones((3, 2)), mesh8)
    assert out8.shape == (8, 2)
