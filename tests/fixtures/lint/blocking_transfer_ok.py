"""Negative fixture: the sanctioned shapes — async tier hand-off in
the loop, blocking pulls only OUTSIDE the step-reachable set, host
casts with explicit dtypes, and a lone step() that is not an engine."""

import jax
import numpy as np


class Engine:
    def submit(self, rid, prompt):
        self.queue.append((rid, prompt))

    def step(self):
        # the sanctioned idiom: dispatch the gather, hand the sync to
        # the tier thread, pick up already-staged device arrays
        leaves = [c[self.idx] for c in self.cache]
        self.tier.evict_submit(self.host_ids, leaves)
        staged = self.tier.take_staged(self.key, self.host_ids)
        # host-side cast of host data: explicit dtype marks it
        ids = np.asarray(self.id_list, np.int64)
        return staged, ids

    def register_prefix(self, ids):
        # NOT step-reachable: a one-time registration may block
        snap = jax.device_get(self.snapshot)
        self.snapshot_host = np.asarray(snap)
        return jax.block_until_ready(snap)


class TierWorker:
    # no submit(): not a decode engine — its step may block (this IS
    # the transfer thread)
    def step(self):
        arr = np.asarray(self.dev)
        self.pool[self.idx] = arr
