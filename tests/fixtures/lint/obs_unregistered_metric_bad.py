"""Positive fixture: ad-hoc stats-dict counter writes the obs registry
never sees."""


class Engine:
    def __init__(self):
        self.stats = {"steps": 0, "tokens": 0}  # dict literal: invisible

    def step(self):
        self.stats["steps"] += 1  # augmented subscript write

    def finish(self, n):
        self.stats["tokens"] = self.stats["tokens"] + n  # plain write


def publish(worker):
    worker.engine.stats["published"] = 1  # deep chains count too
