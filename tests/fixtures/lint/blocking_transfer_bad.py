"""Positive fixture: synchronous device->host transfers inside a
decode engine's step loop (directly and via a step-reachable
helper)."""

import jax
import numpy as np


class Engine:
    def submit(self, rid, prompt):
        self.queue.append((rid, prompt))

    def step(self):
        # d2h sync in the hot loop: every live stream stalls per token
        logits = jax.device_get(self.dev_logits)
        self.dev_state.block_until_ready()
        self._harvest()
        return logits

    def _harvest(self):
        # bare np.asarray of a device array — the implicit d2h pull
        rows = np.asarray(self.dev_rows)
        return rows
