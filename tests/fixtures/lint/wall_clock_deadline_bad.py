"""Positive fixture: wall-clock (time.time) deadline arithmetic."""

import time


def expired(msg, skew_s=3.0):
    ts = msg.get("deadline_ts")
    # deadline test on the wall clock: cross-host skew rides straight in
    return ts is not None and time.time() > float(ts) + skew_s


def scatter_payload(timeout):
    # wall-clock deadline stamped into a payload another host will judge
    return {"deadline_ts": time.time() + timeout}


def arm(budget_s):
    deadline_ts = time.time() + budget_s
    return deadline_ts
