"""Negative fixture: registry-backed counters and plain reads are
fine; so are local scratch dicts that are not a ``.stats`` surface."""

from rafiki_tpu.obs import StatsMap


class Engine:
    def __init__(self):
        self.stats = StatsMap({"steps": 0, "tokens": 0})

    def step(self):
        self.stats.inc("steps")

    def finish(self, n):
        self.stats.inc("tokens", n)
        self.stats.max_set("max_tokens", n)


def read_side(engine):
    # reads keep dict ergonomics — only writes are policed
    snapshot = dict(engine.stats)
    total = engine.stats["tokens"]
    # a local scratch dict is not a metrics surface
    stats = {}
    stats["anything"] = total
    return snapshot, stats
