"""Negative fixture: private access that is ours to make."""


def module_helper():
    return _shared_state()


def _shared_state():
    return {}


def remember(fn):
    # function attribute on a module-local function: our own object
    if getattr(module_helper, "_done", False):
        return fn
    module_helper._done = True
    return fn


class Engine:
    def __init__(self, model):
        self._model = model  # own private attr

    def params(self):
        return self._model.params

    def peek(self):
        return self._model._params  # single hop: package-internal
