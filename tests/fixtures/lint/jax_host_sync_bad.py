"""Positive fixture: host-device syncs inside traced functions."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def train_step(params, x):
    loss = jnp.sum(x)
    print(loss.item())  # sync: concretizes on host every step
    return params


@jax.jit
def log_step(params, x):
    host = np.asarray(x)  # sync: device -> host numpy copy
    return params, host


@jax.jit
def scalarize(params, lr):
    return params, float(lr)  # sync: tracer -> Python scalar


def wrapped(x):
    x.block_until_ready()  # sync: pipeline stall inside the jit below
    return x


step = jax.jit(wrapped)


from functools import partial  # noqa: E402

from rafiki_tpu.ops.common import shard_map_kernels  # noqa: E402


@partial(shard_map_kernels, mesh=None, in_specs=(), out_specs=())
def sharded_body(x):
    return x.tolist()  # sync inside a shard_map body
