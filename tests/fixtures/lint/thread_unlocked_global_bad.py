"""Positive fixture: thread target mutating module state lock-free."""
import threading

STATS = {}
EVENTS = []


def _monitor_loop():
    STATS["ticks"] = STATS.get("ticks", 0) + 1  # racy dict write
    EVENTS.append("tick")  # racy list append


t = threading.Thread(target=_monitor_loop, daemon=True)
