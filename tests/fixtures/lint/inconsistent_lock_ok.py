"""Negative fixture: consistent locking, setup writes, owner-thread
mirrors, and the *_locked caller-holds-lock convention."""
import threading


class SafeSlotTable:
    def __init__(self):
        self._lock = threading.Lock()
        self._live = 0  # __init__ writes are pre-sharing

    def admit(self):
        with self._lock:
            self._live += 1

    def evict_all(self):
        with self._lock:
            self._live = 0

    def _rebuild_locked(self):
        self._live = 0  # caller holds the lock (naming convention)


class EngineMirrors:
    """Lock guards only the queue handoff; the numpy-mirror attrs are
    owned by the single step thread and written bare BY DESIGN — the
    lockset vote (bare majority) must keep this clean."""

    def __init__(self):
        self._lock = threading.Lock()
        self._queue = []
        self._pos = 0

    def submit(self, item):
        with self._lock:
            self._queue.append(item)

    def step(self):
        self._pos += 1  # owner-thread mirror, bare on purpose

    def prefill(self):
        self._pos = 0  # owner-thread mirror, bare on purpose

    def rewind(self):
        self._pos -= 1  # owner-thread mirror, bare on purpose
