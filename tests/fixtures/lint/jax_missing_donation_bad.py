"""Positive fixture: jit update function that rebinds its first arg
without donating it — both buffers live at step peak."""
import jax


@jax.jit
def train_step(params, grads):
    params = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g,
                                    params, grads)
    return params
