"""Positive fixture: jit update function that rebinds its first arg
without donating it — both buffers live at step peak."""
import jax


@jax.jit
def train_step(params, grads):
    params = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g,
                                    params, grads)
    return params


@jax.jit
def llama_gang_step(state, hp, batch):
    # the gang-lane variant: K stacked adapter sets + Adam moments is
    # the dominant resident pytree — rebinding it without donation
    # keeps both generations live at step peak, doubling lane HBM
    state = jax.tree_util.tree_map(lambda s: s * hp["learning_rate"],
                                   state)
    return state
