"""Positive fixture: wire fields reaching trusted sinks unwashed."""

import subprocess


def on_override(payload, dest):
    path = payload["snapshot_path"]  # wire field
    subprocess.run(["cp", path, dest])  # unwashed argv


class Applier:
    def apply(self, msg):
        self.config = msg.get("overrides")  # straight into config
