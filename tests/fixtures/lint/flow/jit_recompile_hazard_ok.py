"""Negative fixture: bucketed sizes and constants are static-safe."""

import jax


def decode(batch, max_len):
    return batch


step = jax.jit(decode, static_argnames=("max_len",))


def bucket_len(n):
    return max(8, n)


def serve(pending, batch):
    n = bucket_len(len(pending))  # few distinct values by design
    return step(batch, max_len=n)


def serve_fixed(batch):
    return step(batch, max_len=128)  # constant: one cache entry
