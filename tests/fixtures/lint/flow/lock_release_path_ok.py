"""Negative fixture: every path releases or hands the handle over."""


def work():
    pass


def balanced(lock, closed):
    lock.acquire()
    try:
        if closed:
            return None
        work()
    finally:
        lock.release()


def guarded_spawn(alloc, mgr, slots):
    slot = alloc.acquire(timeout=0.5)
    if slot is None:
        return None
    try:
        mgr.spawn(slot=slot)
    except Exception:
        alloc.release(slot)  # return the slot to the pool
        raise
    slots.append(slot)  # ownership transferred to the registry
    return slot


def structural(lock):
    with lock:  # release is structural — never tracked
        work()
