"""Positive fixture: acquire whose release is missing on SOME path."""


def work():
    pass


def leak_on_early_return(lock, closed):
    lock.acquire()
    if closed:
        return None  # exits with the lock held
    work()
    lock.release()
    return True


def leak_on_raising_spawn(alloc, mgr):
    slot = alloc.acquire(timeout=0.0)
    if slot is None:
        return None
    # if spawn raises before taking ownership, the slot handle is
    # gone until restart — no try/except returns it to the pool
    mgr.spawn(slot=slot)
    return True
