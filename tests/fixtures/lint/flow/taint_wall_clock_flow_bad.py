"""Positive fixture: time.time() flowing into deadline values/tests."""

import time


def arm(ttl_s):
    now = time.time()
    deadline_ts = now + ttl_s  # tainted through the intermediate
    return deadline_ts


def scatter(req, ttl_s):
    # wall-clock deadline stamped for another host to judge
    req["deadline_ts"] = time.time() + ttl_s
    return req


def _now():
    return time.time()


def expired(deadline_ts):
    # taint propagates through the module-local helper's return
    return _now() > deadline_ts
