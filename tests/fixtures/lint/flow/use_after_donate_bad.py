"""Positive fixture: donated buffer read again on a later path."""

import jax


def train_step(params, batch):
    return params


step = jax.jit(train_step, donate_argnums=(0,))


def loop(params, batches, log):
    for b in batches:
        # donates params but never rebinds it: iteration 2 passes a
        # freed buffer back into the compiled call
        loss = step(params, b)
        log(loss)
