"""Negative fixture: monotonic deadlines + plain wall-clock stamps."""

import time


def deadline(timeout_s):
    # monotonic clock: immune to wall steps and cross-host skew
    return time.monotonic() + timeout_s


def expired(now_mono, deadline_mono):
    return now_mono > deadline_mono


def stamp():
    # stamping when something happened is not deadline arithmetic
    sent = time.time()
    return {"sent_ts": sent, "published_at": sent}


def elapsed(skew_est, sent_ts):
    # the sanctioned cross-host path: skew-compensated elapsed time
    return skew_est.elapsed_since(sent_ts)
