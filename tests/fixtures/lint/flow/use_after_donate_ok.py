"""Negative fixture: rebinding in the donating statement is safe."""

import jax


def train_step(params, batch):
    return params


step = jax.jit(train_step, donate_argnums=(0,))


def loop(params, batches):
    for b in batches:
        params = step(params, b)  # rebinds: the safe donation idiom
    return params
