"""Negative fixture: validated, cast, or local-config flows."""

import json
import subprocess


def on_override(payload, dest):
    path = validate_snapshot_path(payload["snapshot_path"])
    subprocess.run(["cp", path, dest])


class Applier:
    def apply(self, msg):
        self.config = normalize_slo(msg.get("overrides"))

    def set_shards(self, msg):
        self.shards = int(msg.get("shards", 1))  # numeric cast


def load_local_config(path):
    # json.load of a local config file is trusted operator input
    with open(path) as f:
        return json.load(f)
