"""Positive fixture: runtime-varying value in a static jit arg."""

import jax


def decode(batch, max_len):
    return batch


step = jax.jit(decode, static_argnames=("max_len",))


def serve(pending, batch):
    n = len(pending)  # varies every call...
    return step(batch, max_len=n)  # ...so every call recompiles
