"""Negative fixture: the same operations where they are legal."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def train_step(params, x):
    return params, jnp.sum(x)  # stays on device


def eager_eval(x):
    # not traced: pulling to host in eager metric code is fine
    arr = np.asarray(x)
    return float(arr.mean()), arr.item() if arr.size == 1 else None


def outside(step_fn, params, x):
    out = step_fn(params, x)
    return jax.block_until_ready(out)  # sync AFTER the traced call
