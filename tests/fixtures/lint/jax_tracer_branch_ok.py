"""Negative fixture: branches that are static (or not branches on
tracers) inside traced functions."""
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("mode",))
def static_by_name(x, mode):
    if mode:  # static argument: resolved at trace time
        return x
    return -x


@partial(jax.jit, static_argnums=(1,))
def static_by_num(x, flip):
    if flip:  # static argument: resolved at trace time
        return -x
    return x


@jax.jit
def optional_arg(x, y=None):
    if y is None:  # identity test on the Python value, not the tracer
        return x
    return x + y


@jax.jit
def annotated_config(x, causal: bool):
    if causal:  # bool-annotated params are compile-time config
        return jnp.tril(x)
    return x


@jax.jit
def on_device_branch(x, limit):
    return jnp.where(limit > 0, x, -x)  # the traced way to branch


@jax.jit
def gang_train_step(state, dropout, batch):
    # traceable knobs stay in jnp-land: masks/where instead of `if`
    keep = 1.0 - dropout
    return state * jnp.where(keep > 0.5, keep, 1.0)


@jax.jit
def llama_lane_merge(adapters, lora_scale):
    # the traced way: apply the rank-scale unconditionally — scale=1
    # is bitwise identity, no branch needed
    return jax.tree_util.tree_map(lambda b: lora_scale * b, adapters)
