"""Negative fixture: broad excepts that handle visibly, narrow
excepts, and exception-variable use."""
import logging

log = logging.getLogger(__name__)


def admission_check(estimate, limit):
    try:
        total = estimate()
    except Exception as e:
        log.warning("admission check skipped: estimator raised %r", e,
                    exc_info=True)
        return
    if total > limit:
        raise ValueError("footprint exceeds device limit")


def narrow(cfg):
    try:
        return cfg["key"]
    except KeyError:  # narrow type: normal control flow
        return None


def reraise(source):
    try:
        return source()
    except Exception:
        raise


def inspected(source):
    try:
        return source()
    except Exception as e:
        return {"error": str(e)}  # the exception is read, not dropped


def fallback_call(primary, secondary):
    try:
        return primary()
    except Exception:
        return secondary()  # visible handling: a fallback path runs
