"""Positive fixture: navigating a foreign object's private internals."""


def rebind_socket(resp, read_timeout):
    sock = resp.fp.raw._sock  # CPython HTTPResponse internals
    sock.settimeout(read_timeout)


def probe(resp):
    return getattr(resp.fp, "_sock", None)  # same probe, getattr form
