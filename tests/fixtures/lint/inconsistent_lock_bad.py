"""Positive fixture: one bare write against an otherwise-locked attr."""
import threading


class SlotTable:
    def __init__(self):
        self._lock = threading.Lock()
        self._live = 0

    def admit(self):
        with self._lock:
            self._live += 1

    def finish(self):
        with self._lock:
            self._live -= 1

    def evict_all(self):
        self._live = 0  # bare write: races admit()/finish()
