"""Negative fixture: locked mutation, non-thread mutation, and local
shadowing."""
import threading

STATS = {}
_STATS_LOCK = threading.Lock()


def _monitor_loop():
    with _STATS_LOCK:
        STATS["ticks"] = STATS.get("ticks", 0) + 1


def eager_helper():
    # mutates the module dict but never runs on a thread
    STATS["calls"] = STATS.get("calls", 0) + 1


def _shadowing_loop():
    STATS = {}  # local name shadows the module global
    STATS["ticks"] = 1


t1 = threading.Thread(target=_monitor_loop, daemon=True)
t2 = threading.Thread(target=_shadowing_loop, daemon=True)
