"""Negative fixture: donation declared, or no in-place-style rebind."""
from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0,))
def train_step(params, grads):
    params = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g,
                                    params, grads)
    return params


@jax.jit
def evaluate(params, batch):
    # reads params, never rebinds them: nothing to donate
    preds = jax.tree_util.tree_map(lambda p: p * 2, params)
    return preds, batch


@partial(jax.jit, donate_argnums=(0,))
def llama_gang_step(state, hp, batch):
    # gang lanes donate the stacked lane state: the update happens in
    # place, one generation of adapters + Adam moments resident
    state = jax.tree_util.tree_map(lambda s: s * hp["learning_rate"],
                                   state)
    return state
