"""The spawned service: reads its config at startup. lease_s is a
required read (plain subscript) that admin.py never produces."""


def start(cfg):
    pages = cfg["kv_pages"]
    replicas = cfg.get("max_replicas", 1)
    lease_s = cfg["lease_s"]
    return pages, replicas, lease_s
