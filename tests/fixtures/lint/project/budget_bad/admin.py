"""Admin side of the budget contract, three ways broken: the
MAX_REPLICAS budget key is documented nowhere (README.md only
mentions KV_PAGES), burst_window is produced but the worker never
reads it (dead knob), and the worker's required lease_s read has no
producer here."""


class Admin:
    def __init__(self, mgr):
        self.mgr = mgr

    def create(self, budget):
        if "KV_PAGES" not in budget:
            raise ValueError("KV_PAGES is required")
        cfg = {
            "kv_pages": budget["KV_PAGES"],
            "max_replicas": budget.get("MAX_REPLICAS"),
            "burst_window": 30,
        }
        return self.mgr._spawn("budget_bad.worker", cfg)
