"""The closed loop: every budget key is documented, every produced
config key is consumed, every required read has a producer."""


class Admin:
    def __init__(self, mgr):
        self.mgr = mgr

    def create(self, budget):
        if "KV_PAGES" not in budget:
            raise ValueError("KV_PAGES is required")
        cfg = {
            "kv_pages": budget["KV_PAGES"],
            "max_replicas": budget.get("MAX_REPLICAS"),
            "lease_s": 30,
        }
        return self.mgr._spawn("budget_ok.worker", cfg)
