"""The spawned service: consumes everything admin.py produces."""


def start(cfg):
    pages = cfg["kv_pages"]
    replicas = cfg.get("max_replicas", 1)
    lease_s = cfg["lease_s"]
    return pages, replicas, lease_s
