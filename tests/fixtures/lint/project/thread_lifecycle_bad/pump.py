"""Non-daemon worker thread that ``close`` signals but never joins."""

import queue
import threading


class Pump:
    def __init__(self):
        self._q = queue.Queue()
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def push(self, item):
        self._q.put(item)

    def _run(self):
        while True:
            if self._q.get() is None:
                return

    def close(self):
        self._q.put(None)
