"""Worker threads with sound lifecycles.

``Pump.close`` joins its non-daemon worker; ``Beacon`` never joins but
its thread is daemonic, so interpreter shutdown is not blocked.
"""

import queue
import threading


class Pump:
    def __init__(self):
        self._q = queue.Queue()
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def push(self, item):
        self._q.put(item)

    def _run(self):
        while True:
            if self._q.get() is None:
                return

    def close(self):
        self._q.put(None)
        self._t.join(timeout=5)


class Beacon:
    def __init__(self):
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._blink, daemon=True)
        self._t.start()

    def _blink(self):
        while not self._stop.wait(1.0):
            pass

    def close(self):
        self._stop.set()
