"""Drives the pump for a batch of items."""

from pump import Pump


def main(items):
    p = Pump()
    for item in items:
        p.push(item)
    p.close()
