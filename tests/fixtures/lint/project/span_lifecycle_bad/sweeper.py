"""A clean sibling component: the sweeper's span stream does emit a
terminal, so only tracker.py should be flagged."""


def sweep(span_sink, rid):
    span_sink("expired", rid)
