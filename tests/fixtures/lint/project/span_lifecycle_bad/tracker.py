"""Emits admission/progress spans but never a terminal event — every
trace from this component looks permanently in-flight."""


class RequestTracker:
    def __init__(self, span_sink):
        self.span_sink = span_sink

    def admit(self, rid):
        self.span_sink("admitted", rid)

    def first_token(self, rid):
        self.span_sink("first_token", rid)
