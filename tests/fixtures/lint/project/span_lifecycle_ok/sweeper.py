"""Sweeper twin: terminal-only emission is trivially fine."""


def sweep(span_sink, rid):
    span_sink("expired", rid)
