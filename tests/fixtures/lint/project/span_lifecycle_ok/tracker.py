"""Every exit path emits a terminal event; the progress events are
fine once a done/rejected can close the stream."""


class RequestTracker:
    def __init__(self, span_sink):
        self.span_sink = span_sink

    def admit(self, rid):
        self.span_sink("admitted", rid)

    def first_token(self, rid):
        self.span_sink("first_token", rid)

    def finish(self, rid):
        self.span_sink("done", rid)

    def shed(self, rid):
        self.span_sink("rejected", rid)
