"""Same instruments as the bad twin — here all three surfaces
agree."""


class Worker:
    def __init__(self, metrics):
        self.requests = metrics.counter("requests_total")
        self.latency = metrics.histogram("request_latency_s")
        self.depth = metrics.gauge("queue_depth")

    def handle(self, req):
        self.requests.inc()
        return req
