"""Slot table where every ``_live`` access holds ``_lock``.

``_evict_locked`` itself takes no lock: its only caller acquires it, so
the interprocedural held-in fixpoint credits the helper with the lock.
"""

import threading


class SlotTable:
    def __init__(self):
        self._lock = threading.Lock()
        self._live = {}

    def admit(self, rid, slot):
        with self._lock:
            self._live[rid] = slot

    def evict_all(self):
        with self._lock:
            self._evict_locked()

    def _evict_locked(self):
        self._live.clear()
