"""Background reaper; safe because the table locks both sides."""

import threading

from slots import SlotTable


class Reaper:
    def __init__(self):
        self.table = SlotTable()
        self._t = threading.Thread(target=self._sweep, daemon=True)
        self._t.start()

    def admit(self, rid, slot):
        self.table.admit(rid, slot)

    def _sweep(self):
        while True:
            self.table.evict_all()
