"""The fixed decorator: every public verb of the interface is
wrapped, ping() included."""

from .iface import VerbHub


class ChaosHub(VerbHub):
    def __init__(self, inner: VerbHub, fail_rate=0.0):
        self.inner = inner
        self.fail_rate = fail_rate

    def put(self, key, value):
        return self.inner.put(key, value)

    def get(self, key):
        return self.inner.get(key)

    def drop(self, key):
        return self.inner.drop(key)

    def ping(self):
        return self.inner.ping()
