"""Wire client sending only verbs the server dispatches."""


class WireClient:
    def _cmd(self, *parts):
        return parts

    def put(self, key, value):
        return self._cmd("PUT", key, value)

    def drop(self, key):
        return self._cmd("DROP", key)
