// Dispatch covers every client verb; WAL_REPLAY is server-only,
// which is allowed (internal replay path, no client sender).
static Reply dispatch(const std::string& cmd, const Args& args) {
  if (cmd == "PUT") { return do_put(args); }
  if (cmd == "GET") { return do_get(args); }
  if (cmd == "DROP") { return do_drop(args); }
  if (cmd == "WAL_REPLAY") { return do_replay(args); }
  return Reply::error("unknown command");
}
