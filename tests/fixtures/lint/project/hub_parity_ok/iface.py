"""Same verb interface as the bad twin."""


class VerbHub:
    def put(self, key, value):
        raise NotImplementedError

    def get(self, key):
        raise NotImplementedError

    def drop(self, key):
        raise NotImplementedError

    def ping(self):
        return True
