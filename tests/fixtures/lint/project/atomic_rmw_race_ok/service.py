"""Request counter guarded by a module lock: read-modify-write is safe."""

import threading

STATS = {"requests": 0}
_STATS_LOCK = threading.Lock()


class StatsService:
    def __init__(self, http):
        http.route("GET", "/work", self._work)

    def _work(self, request):
        with _STATS_LOCK:
            STATS["requests"] += 1
        return {"ok": True}
