"""Wires the stats service onto an HTTP server."""

from service import StatsService


def main(http):
    return StatsService(http)
