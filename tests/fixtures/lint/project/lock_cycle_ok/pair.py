"""Same two locks, one global order: everybody takes alloc_lock
before evict_lock, so no interleaving can deadlock."""

import threading


class PageTable:
    def __init__(self):
        self.alloc_lock = threading.Lock()
        self.evict_lock = threading.Lock()
        self.pages = {}

    def allocate(self, key):
        with self.alloc_lock:
            self._reclaim()
            return key

    def _reclaim(self):
        with self.evict_lock:
            return len(self.pages)

    def evict(self, key):
        with self.alloc_lock:
            with self.evict_lock:
                return self.pages.get(key)
