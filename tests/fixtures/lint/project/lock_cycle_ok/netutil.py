"""The backoff sleeps OUTSIDE the critical section; the lock only
guards the actual send."""

import threading
import time

SEND_GATE = threading.Lock()


def backoff_send(payload):
    time.sleep(0.2)
    with SEND_GATE:
        return payload
