"""Request counter bumped from concurrent handler threads with no lock."""

STATS = {"requests": 0}


class StatsService:
    def __init__(self, http):
        http.route("GET", "/work", self._work)

    def _work(self, request):
        STATS["requests"] += 1
        return {"ok": True}
