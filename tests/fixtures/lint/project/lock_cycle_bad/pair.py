"""Two locks, two public entry points, opposite orders — the classic
inversion lock-order-cycle exists to catch: allocate() takes
alloc_lock then evict_lock (through _reclaim), evict() takes them the
other way round (through _touch). Either order alone is fine; two
threads interleaving deadlock."""

import threading


class PageTable:
    def __init__(self):
        self.alloc_lock = threading.Lock()
        self.evict_lock = threading.Lock()
        self.pages = {}

    def allocate(self, key):
        with self.alloc_lock:
            self._reclaim()
            return key

    def _reclaim(self):
        with self.evict_lock:
            return len(self.pages)

    def evict(self, key):
        with self.evict_lock:
            self._touch(key)

    def _touch(self, key):
        with self.alloc_lock:
            return self.pages.get(key)
