"""A module-level lock held across a blocking sleep: every other
sender stalls behind the backoff."""

import threading
import time

SEND_GATE = threading.Lock()


def backoff_send(payload):
    with SEND_GATE:
        time.sleep(0.2)
        return payload
