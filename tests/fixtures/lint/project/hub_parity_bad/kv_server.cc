// Minimal dispatch mirror of the real server for fixture purposes.
// XSTATS is deliberately absent.
static Reply dispatch(const std::string& cmd, const Args& args) {
  if (cmd == "PUT") { return do_put(args); }
  if (cmd == "GET") { return do_get(args); }
  if (cmd == "DROP") { return do_drop(args); }
  return Reply::error("unknown command");
}
