"""The transport-neutral verb interface: three abstract verbs plus one
with a default body (exactly where a missed wrap hides)."""


class VerbHub:
    def put(self, key, value):
        raise NotImplementedError

    def get(self, key):
        raise NotImplementedError

    def drop(self, key):
        raise NotImplementedError

    def ping(self):
        """Default no-op health check — subclass wrappers must still
        override it or the wrapped hub never sees the call."""
        return True
