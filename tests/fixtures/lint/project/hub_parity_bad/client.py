"""Wire client: XSTATS has no cmd == "XSTATS" dispatch in
kv_server.cc, so the server rejects it at runtime."""


class WireClient:
    def _cmd(self, *parts):
        return parts

    def put(self, key, value):
        return self._cmd("PUT", key, value)

    def stats(self):
        return self._cmd("XSTATS")
