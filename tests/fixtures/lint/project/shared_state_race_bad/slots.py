"""Slot table whose writers disagree about holding the lock.

``admit`` mutates ``_live`` under ``_lock`` but ``evict_all`` clears it
bare, so a reaper thread calling ``evict_all`` races every admit.
"""

import threading


class SlotTable:
    def __init__(self):
        self._lock = threading.Lock()
        self._live = {}

    def admit(self, rid, slot):
        with self._lock:
            self._live[rid] = slot

    def evict_all(self):
        self._live.clear()
