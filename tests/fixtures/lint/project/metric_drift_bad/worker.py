"""Publishes two instruments; the catalog and the dashboard each
drifted a different way (see observability.md / dashboard.html in
this directory)."""


class Worker:
    def __init__(self, metrics):
        self.requests = metrics.counter("requests_total")
        self.latency = metrics.histogram("request_latency_s")

    def handle(self, req):
        self.requests.inc()
        return req
