"""Suppression fixture: each finding silenced by the noqa dialect."""


def documented_swallow(source):
    try:
        return source()
    except Exception:  # rafiki: noqa[silent-except] — probe only
        return None


def blanket(source):
    try:
        return source()
    except Exception:  # rafiki: noqa
        return None


def wrong_rule(source):
    try:
        return source()
    except Exception:  # rafiki: noqa[jax-host-sync] — wrong id: fires
        return None
