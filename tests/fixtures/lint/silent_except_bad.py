"""Positive fixture: the ADVICE.md admission-control shape — broad
except whose body leaves no trace of the failure."""


def admission_check(estimate, limit):
    try:
        total = estimate()
    except Exception:
        return  # the estimator bug silently disables the check
    if total > limit:
        raise ValueError("footprint exceeds device limit")


def poll(source):
    try:
        return source()
    except:  # noqa: E722
        pass
