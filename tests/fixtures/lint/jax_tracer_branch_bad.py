"""Positive fixture: Python branches on traced data arguments."""
import jax


@jax.jit
def clip_if(x, limit):
    if limit:  # branches on a tracer -> ConcretizationTypeError
        return x
    return -x


@jax.jit
def loop_while(x, n):
    total = x
    while n:  # tracer-valued loop condition
        total = total + 1
        n = n - 1
    return total


@jax.jit
def gang_train_step(state, dropout, batch):
    # the gang-engine failure mode: a traceable knob arrives as a traced
    # per-lane scalar — a Python `if` on it branches on the TRACE
    if dropout > 0:  # traced hyperparameter in a Python branch
        return state * (1.0 - dropout)
    return state
