"""Positive fixture: Python branches on traced data arguments."""
import jax


@jax.jit
def clip_if(x, limit):
    if limit:  # branches on a tracer -> ConcretizationTypeError
        return x
    return -x


@jax.jit
def loop_while(x, n):
    total = x
    while n:  # tracer-valued loop condition
        total = total + 1
        n = n - 1
    return total


@jax.jit
def gang_train_step(state, dropout, batch):
    # the gang-engine failure mode: a traceable knob arrives as a traced
    # per-lane scalar — a Python `if` on it branches on the TRACE
    if dropout > 0:  # traced hyperparameter in a Python branch
        return state * (1.0 - dropout)
    return state


@jax.jit
def llama_lane_merge(adapters, lora_scale):
    # the Llama LoRA gang variant: lora_scale rides as a traced
    # per-lane scalar, so "skip the multiply when it's 1" branches on
    # the trace — scale unconditionally (scale=1 is already identity)
    if lora_scale != 1.0:  # traced rank-scale in a Python branch
        return jax.tree_util.tree_map(lambda b: lora_scale * b,
                                      adapters)
    return adapters
