"""Positive fixture: Python branches on traced data arguments."""
import jax


@jax.jit
def clip_if(x, limit):
    if limit:  # branches on a tracer -> ConcretizationTypeError
        return x
    return -x


@jax.jit
def loop_while(x, n):
    total = x
    while n:  # tracer-valued loop condition
        total = total + 1
        n = n - 1
    return total
