"""Paged (block-table) KV serving — ISSUE 5 tentpole; ISSUE 10 adds
the kernel-vs-gather equivalence suite at the bottom.

Per layer, decode K/V live in a ``(kv_pages, page_size, heads, dh)``
pool; each slot maps logical pages → pool pages via a host page table
fed to the compiled step (static shapes, no recompiles). The oracle
throughout is the CONTIGUOUS engine on the same weights: the paged
engine must be token-BIT-EXACT on mixed-length traffic across greedy,
sampled, int8-KV, multi-adapter, and speculative decoding, while
allocating pages lazily (positions, not max_len), backpressuring
admission on the pool without deadlock, and freeing everything at
completion/reset.
"""

import threading

import numpy as np
import pytest

from rafiki_tpu.models.llama_lora import LlamaLoRA, stack_lora_adapters
from rafiki_tpu.serving.decode_engine import DecodeEngine

from test_decode_engine import KNOBS  # noqa: F401 — shared knobs
from test_multi_adapter import _lora_variant  # noqa: F401

L = int(KNOBS["max_len"])
PS = 8  # page size used throughout (divides max_len=32 into 4 tables)


def _mixed_reqs(n=8, seed=0, max_new=6, vocab=64):
    """Deterministic mixed-length traffic: prompts 2..14 tokens."""
    rng = np.random.default_rng(seed)
    return [(r, rng.integers(1, vocab,
                             size=int(rng.integers(2, 15))
                             ).astype(np.int32), max_new)
            for r in range(n)]


def _drain(eng, reqs, submit_kw=None):
    for i, (rid, p, mn) in enumerate(reqs):
        eng.submit(rid, p, mn, **(submit_kw(i) if submit_kw else {}))
    done = {}
    for _ in range(600):
        eng.step()
        done.update(dict(eng.poll()))
        if len(done) == len(reqs):
            return done
    raise AssertionError(f"undrained: {sorted(done)} / {eng.stats}")


def _pair(trained, reqs, pages, engine_kw=None, submit_kw=None,
          module_kw=None, params=None):
    """(contiguous outputs, paged outputs, paged engine) on identical
    traffic — the parity harness every test below goes through."""
    engine_kw = engine_kw or {}
    module_kw = module_kw or {}
    params = trained._params if params is None else params
    contig = DecodeEngine(trained._module(**module_kw), params,
                          max_slots=4, max_len=L, **engine_kw)
    paged = DecodeEngine(
        trained._module(kv_page_size=PS, kv_pages=pages, **module_kw),
        params, max_slots=4, max_len=L, **engine_kw)
    ref = _drain(contig, reqs, submit_kw)
    got = _drain(paged, reqs, submit_kw)
    assert got == ref, (got, ref)
    return ref, got, paged


def test_paged_matches_contiguous_mixed_greedy(trained):
    """8 mixed-length greedy requests through 4 slots and a TIGHT pool
    (stalls expected): token-bit-exact, pages recycle to zero."""
    _, _, eng = _pair(trained, _mixed_reqs(8), pages=9)
    s = eng.stats
    assert s["kv_pages_total"] == 8
    assert 0 < s["kv_pages_high_water"] <= 8
    assert s["kv_pages_used"] == 0          # drained → all pages freed
    assert len(eng._free_pages) == 8        # allocator agrees
    assert s["max_concurrent"] >= 2         # traffic really overlapped


def test_paged_matches_contiguous_fused_and_chunked(trained):
    """Parity holds across steps_per_sync/prefill_chunk combinations
    (the fused-scan and chunked-prefill write paths both page)."""
    reqs = _mixed_reqs(6, seed=3)
    for kw in ({"steps_per_sync": 1, "prefill_chunk": 1},
               {"steps_per_sync": 3, "prefill_chunk": 4}):
        _pair(trained, reqs, pages=9, engine_kw=kw)


def test_paged_sampled_parity(trained):
    """Seeded sampling draws are position-keyed, so the paged engine
    must reproduce the contiguous engine's sampled streams exactly —
    greedy and sampled slots mixed in one batch."""

    def samp(i):
        if i % 2 == 0:
            return {}
        return {"temperature": 0.9, "top_k": 8, "top_p": 0.95,
                "seed": 100 + i}

    _pair(trained, _mixed_reqs(6, seed=1), pages=9, submit_kw=samp)


def test_paged_int8_kv_parity_and_pool_bytes(trained):
    """int8 KV pages identically (int8 pools + f32 scale pools): exact
    parity within the quantized world, and the paged pool's measured
    bytes sit well under the contiguous int8 cache's."""
    import jax

    m8 = LlamaLoRA(**{**KNOBS, "kv_cache_int8": True})
    m8._params = trained._params
    reqs = _mixed_reqs(6, seed=2)
    contig = DecodeEngine(m8._module(), m8._params, max_slots=4,
                          max_len=L)
    paged = DecodeEngine(m8._module(kv_page_size=PS, kv_pages=9),
                         m8._params, max_slots=4, max_len=L)
    assert _drain(contig, reqs) == _drain(paged, reqs)

    def nbytes(c):
        return sum(int(np.prod(v.shape)) * v.dtype.itemsize
                   for v in jax.tree_util.tree_leaves(c))

    # 9 pages * 8 positions = 72 vs 4 slots * 32 = 128 positions
    assert nbytes(paged._cache) < 0.6 * nbytes(contig._cache)


def test_paged_multi_adapter_parity(trained):
    """Mixed-adapter batches on one paged pool: every request matches
    the contiguous stacked engine token-for-token."""
    stacked = stack_lora_adapters(
        [trained._params, _lora_variant(trained._params)])
    _pair(trained, _mixed_reqs(6, seed=4), pages=9,
          module_kw={"n_adapters": 2}, params=stacked,
          submit_kw=lambda i: {"adapter_id": i % 2})


def test_paged_speculative_parity(trained):
    """Greedy speculation (prompt-lookup drafting) over a paged cache:
    lossless vs the contiguous speculative engine AND vs plain paged
    decoding; the verify path's multi-token window writes page."""
    reqs = [(0, np.asarray([1, 7, 2, 7, 2, 7, 2], np.int32), 8),
            (1, np.asarray([1, 5, 9, 13], np.int32), 8),
            (2, np.asarray([1, 3], np.int32), 8)]
    ref, _, _ = _pair(trained, reqs, pages=13)  # plain paged == contig
    _, spec, eng = _pair(trained, reqs, pages=13,
                         engine_kw={"speculate_k": 4})
    assert spec == ref
    assert eng.stats["spec_calls"] > 0


def test_paged_prefix_cache_parity(trained):
    """register_prefix on a paged engine: the snapshot computes through
    a contiguous twin and scatters into the hit slots' pages — hits
    stay exact and still skip the prefix's prefill."""
    module = trained._module(kv_page_size=PS, kv_pages=9)
    prefix = np.asarray([1, 5, 9, 13, 2], np.int32)
    prompts = {"hit": np.concatenate([prefix, [7, 4]]).astype(np.int32),
               "miss": np.asarray([2, 5, 9, 3], np.int32)}

    def run(register):
        eng = DecodeEngine(module, trained._params, max_slots=2,
                           max_len=L)
        if register:
            assert eng.register_prefix(prefix) == len(prefix)
        return (_drain(eng, [(n, p, 6) for n, p in prompts.items()]),
                eng.stats)

    plain, _ = run(False)
    cached, stats = run(True)
    assert cached == plain
    assert stats["prefix_hits"] == 1


def test_page_backpressure_waits_without_deadlock(trained):
    """A pool that fits ONE request at a time serves a 3-deep queue
    sequentially: admissions wait (stall counter moves), nothing
    deadlocks, every completion frees its pages for the next."""
    module = trained._module(kv_page_size=PS, kv_pages=3)  # 2 usable
    eng = DecodeEngine(module, trained._params, max_slots=4, max_len=L)
    reqs = [(r, np.asarray([1, 5 + r, 9], np.int32), 8)
            for r in range(3)]  # stop=10 → 2 pages each, pool-filling
    done = _drain(eng, reqs)
    solo = DecodeEngine(trained._module(), trained._params,
                        max_slots=1, max_len=L)
    assert done == _drain(solo, reqs)
    s = eng.stats
    assert s["admission_stalls"] > 0
    assert s["max_concurrent"] == 1         # the pool, not slots, bound
    assert s["kv_pages_used"] == 0 and len(eng._free_pages) == 2


def test_submit_rejects_request_larger_than_pool(trained):
    """A request whose worst case exceeds the WHOLE pool would stall
    the FIFO queue forever — submit refuses it loudly instead."""
    module = trained._module(kv_page_size=PS, kv_pages=3)
    eng = DecodeEngine(module, trained._params, max_slots=2, max_len=L)
    with pytest.raises(ValueError, match="KV pages"):
        eng.submit("big", np.arange(1, 20, dtype=np.int32), 12)


def test_lazy_allocation_tracks_positions(trained):
    """Pages are allocated as positions cross boundaries — mid-flight a
    long-generation slot holds fewer pages than its reservation — and
    chunked prefill of a prompt longer than one page maps pages chunk
    by chunk, with output parity against the contiguous engine."""
    module = trained._module(kv_page_size=PS, kv_pages=9)
    # long prompt (19 tokens > 2 pages) through chunked prefill
    long_prompt = np.arange(1, 20, dtype=np.int32)
    reqs = [("lp", long_prompt, 5)]
    contig = DecodeEngine(trained._module(), trained._params,
                          max_slots=2, max_len=L, prefill_chunk=8)
    paged = DecodeEngine(module, trained._params, max_slots=2,
                         max_len=L, prefill_chunk=8)
    assert _drain(paged, reqs) == _drain(contig, reqs)
    assert paged.stats["prefill_calls"] >= 1  # took the chunked path
    assert paged.stats["kv_pages_high_water"] == 3  # 23 positions

    # long generation: after ONE fused call the slot holds pages for
    # where it IS (position ~K), not its full reservation
    eng = DecodeEngine(module, trained._params, max_slots=2, max_len=L,
                       steps_per_sync=4, prefill_chunk=1)
    eng.submit("g", np.asarray([1, 5], np.int32), 20)  # stop=21: 3 pages
    eng.step()
    assert int(eng._n_res[0]) == 3
    assert int(eng._n_alloc[0]) < 3         # lazy: only ~K positions in
    while eng.busy:
        eng.step()
    eng.poll()
    assert int(eng._n_alloc[0]) == 0 and eng.stats["kv_pages_used"] == 0


def test_paged_reset_frees_pool(trained):
    """reset() mid-flight returns every page and reservation, and the
    rebuilt engine serves fresh traffic correctly."""
    module = trained._module(kv_page_size=PS, kv_pages=9)
    eng = DecodeEngine(module, trained._params, max_slots=4, max_len=L)
    for r, p, mn in _mixed_reqs(4, seed=5):
        eng.submit(r, p, mn)
    eng.step()
    assert eng.stats["kv_pages_used"] > 0
    eng.reset()
    assert eng.stats["kv_pages_used"] == 0
    assert len(eng._free_pages) == 8 and eng._res_total == 0
    assert not eng._ptab.any()
    reqs = _mixed_reqs(3, seed=6)
    ref = _drain(DecodeEngine(trained._module(), trained._params,
                              max_slots=4, max_len=L), reqs)
    assert _drain(eng, reqs) == ref


def test_estimator_models_page_pool(trained):
    """estimate_serving_device_bytes(kv_page_size, kv_pages): the
    kv_cache term equals the PAGED ENGINE'S measured pool bytes (f32
    and int8 flavors), and the kv_pages=0 default mirrors the engine's
    full-coverage default."""
    import jax

    def cache_bytes(model, **mk):
        eng = DecodeEngine(model._module(**mk), model._params,
                           max_slots=4, max_len=L)
        return sum(int(np.prod(v.shape)) * v.dtype.itemsize
                   for v in jax.tree_util.tree_leaves(eng._cache))

    b = trained.estimate_serving_device_bytes(
        max_slots=4, kv_page_size=PS, kv_pages=9)
    assert b["kv_cache"] == cache_bytes(trained, kv_page_size=PS,
                                        kv_pages=9)
    m8 = LlamaLoRA(**{**KNOBS, "kv_cache_int8": True})
    m8._params = trained._params
    b8 = m8.estimate_serving_device_bytes(
        max_slots=4, kv_page_size=PS, kv_pages=9)
    assert b8["kv_cache"] == cache_bytes(m8, kv_page_size=PS,
                                         kv_pages=9)
    # default pool (kv_pages=0) = scratch + full coverage, exactly what
    # make_decode_engine builds
    bd = trained.estimate_serving_device_bytes(max_slots=4,
                                               kv_page_size=PS)
    full = 1 + 4 * (L // PS)
    assert bd["kv_cache"] == cache_bytes(trained, kv_page_size=PS,
                                         kv_pages=full)
    # and a sized-down pool really is the smaller admission number
    assert b["kv_cache"] < bd["kv_cache"] < \
        trained.estimate_serving_device_bytes(max_slots=4)["kv_cache"] \
        + b["kv_cache"]
    # the estimator enforces the ENGINE'S validity rules: admission
    # must never bless a pool geometry the engine build will refuse
    with pytest.raises(ValueError, match="divide max_len"):
        trained.estimate_serving_device_bytes(max_slots=4,
                                              kv_page_size=5)
    with pytest.raises(ValueError, match="kv_pages >= 2"):
        trained.estimate_serving_device_bytes(
            max_slots=4, kv_page_size=PS, kv_pages=1)


def test_worker_admission_consumes_paged_estimate(trained, monkeypatch):
    """Both inference-worker deployment paths (single-trial decode loop
    and multi-adapter) hand the page-pool geometry to the estimator: a
    device limit sized between the paged and contiguous footprints
    refuses the contiguous boot and admits the paged one."""
    from rafiki_tpu.serving.queues import InProcQueueHub
    from rafiki_tpu.store.param_store import ParamStore
    from rafiki_tpu.worker.inference import InferenceWorker

    store = ParamStore.from_uri("mem://")
    store.save("t0", trained.dump_parameters())
    variant = LlamaLoRA(**KNOBS)
    dump = dict(trained.dump_parameters())
    dump["params"] = _lora_variant(trained._params)
    variant.load_parameters(dump)
    store.save("t1", variant.dump_parameters())

    paged = trained.estimate_serving_device_bytes(
        max_slots=4, kv_page_size=PS, kv_pages=9)["total"]
    contig = trained.estimate_serving_device_bytes(max_slots=4)["total"]
    assert paged < contig
    limit = (paged + contig) // 2
    monkeypatch.setenv("RAFIKI_DEVICE_HBM_BYTES", str(limit))

    def boot(**kw):
        return InferenceWorker(LlamaLoRA, "t0", KNOBS, store,
                               InProcQueueHub(), "w0", decode_loop=True,
                               max_slots=4, max_new_tokens=4, **kw)

    with pytest.raises(ValueError, match="admission control"):
        boot()                                  # contiguous: too big
    w = boot(kv_page_size=PS, kv_pages=9)       # paged: fits
    assert w.engine.engine.paged
    # pool stats flow worker → hub (→ /health → dashboard)
    w._publish_stats()
    s = w.hub.get_worker_stats("w0")
    assert s["engine_kv_pages_total"] == 8
    assert "engine_admission_stalls" in s
    # kernel-vs-gather visibility rides the same plane: the dispatch
    # gauge publishes (gather on CPU tier-1) and the worker's /metrics
    # carries the decode_step_seconds histogram the kernel difference
    # shows up in
    assert s["engine_paged_kernel_mode"] == 0
    prom = w.metrics.render_prometheus()
    assert "decode_step_seconds" in prom
    assert "paged_kernel_mode" in prom
    assert "paged_kernel_window_tokens" in prom
    assert "paged_kernel_step_tokens" in prom
    # multi-adapter path: same limit arithmetic through its estimator
    # call (re-centred between ITS paged/contiguous totals — the
    # stacked adapters add a term of their own)
    paged_ma = trained.estimate_serving_device_bytes(
        max_slots=4, n_extra_adapters=1, kv_page_size=PS,
        kv_pages=9)["total"]
    contig_ma = trained.estimate_serving_device_bytes(
        max_slots=4, n_extra_adapters=1)["total"]
    monkeypatch.setenv("RAFIKI_DEVICE_HBM_BYTES",
                       str((paged_ma + contig_ma) // 2))
    with pytest.raises(ValueError, match="admission control"):
        boot(extra_adapter_trials=["t1"])
    w2 = boot(extra_adapter_trials=["t1"], kv_page_size=PS, kv_pages=9)
    assert w2.engine.engine.paged
    assert w2.engine.engine.n_adapters == 2


def _kernel_vs_gather(trained, reqs, engine_kw=None, submit_kw=None,
                      module_kw=None, params=None, pages=9):
    """Same paged traffic through the gather fallback and the Pallas
    block-table kernels (forced on — the interpreter on CPU): tokens
    must match exactly, and the obs mode gauge must tell the paths
    apart (0 = gather, 2 = step + window kernels; prefill and verify
    windows dispatch through the window kernel too). Returns the
    kernel run's outputs and its pre-scrub stats snapshot."""
    engine_kw = engine_kw or {}
    module_kw = module_kw or {}
    params = trained._params if params is None else params
    outs = {}
    kstats = None
    for flag in (False, True):
        eng = DecodeEngine(
            trained._module(kv_page_size=PS, kv_pages=pages,
                            paged_kernel=flag, **module_kw),
            params, max_slots=4, max_len=L, **engine_kw)
        outs[flag] = _drain(eng, reqs, submit_kw)
        assert eng.stats["paged_kernel_mode"] == (2 if flag else 0)
        if flag:
            kstats = eng.stats_snapshot()
        else:
            # the gather engine's kernel token counters must not move
            assert eng.stats["paged_kernel_step_tokens"] == 0
            assert eng.stats["paged_kernel_window_tokens"] == 0
        eng.reset_stats()  # the worker's warmup scrub keeps the gauge
        assert eng.stats["paged_kernel_mode"] == (2 if flag else 0)
        assert eng.stats["paged_kernel_step_tokens"] == 0
    assert outs[True] == outs[False], (outs[True], outs[False])
    return outs[True], kstats


def test_kernel_matches_gather_greedy_and_sampled(trained):
    """ISSUE 10 equivalence bar, greedy + seeded-sampled lanes: the
    kernel's single-token steps interleave with chunked prefill (which
    keeps the gather) and both engines emit identical tokens."""
    def samp(i):
        if i % 2 == 0:
            return {}
        return {"temperature": 0.9, "top_k": 8, "top_p": 0.95,
                "seed": 100 + i}

    _kernel_vs_gather(trained, _mixed_reqs(6, seed=7))
    _kernel_vs_gather(trained, _mixed_reqs(6, seed=8), submit_kw=samp,
                      engine_kw={"steps_per_sync": 3,
                                 "prefill_chunk": 4})


def test_kernel_matches_gather_int8_kv(trained):
    """int8-KV pools: the kernel dequantizes in-register off the SAME
    scale rows the gather path reads — tokens match the gather engine
    exactly (the logits-close bar collapses to token-equal here)."""
    m8 = LlamaLoRA(**{**KNOBS, "kv_cache_int8": True})
    m8._params = trained._params
    _kernel_vs_gather(m8, _mixed_reqs(6, seed=9))


def test_kernel_matches_gather_multi_adapter(trained):
    """Mixed-adapter batches: per-row adapters change q/k/v, not the
    page walk — kernel tokens match the gather engine per tenant."""
    stacked = stack_lora_adapters(
        [trained._params, _lora_variant(trained._params)])
    _kernel_vs_gather(trained, _mixed_reqs(6, seed=10),
                      module_kw={"n_adapters": 2}, params=stacked,
                      submit_kw=lambda i: {"adapter_id": i % 2})


def test_kernel_matches_gather_speculative(trained):
    """Prompt-lookup speculation: scan steps take the step kernel AND
    verify windows take the WINDOW kernel — the interleaving is still
    greedy-lossless and token-identical to the all-gather engine, and
    the window-token counter proves the verify windows actually rode
    the kernel."""
    reqs = [(0, np.asarray([1, 7, 2, 7, 2, 7, 2], np.int32), 8),
            (1, np.asarray([1, 5, 9, 13], np.int32), 8),
            (2, np.asarray([1, 3], np.int32), 8)]
    out, ks = _kernel_vs_gather(trained, reqs, pages=13,
                                engine_kw={"speculate_k": 4})
    assert out  # all three drained through the all-kernel path
    assert ks["spec_calls"] > 0
    # every verify call pushed a k-wide window per live lane through
    # the window kernel (k=4, >= 1 live lane per call)
    assert ks["paged_kernel_window_tokens"] >= 4 * ks["spec_calls"]


def test_kernel_matches_gather_draft_model_verify(trained):
    """Draft-MODEL speculation on a paged target: the draft's own
    contiguous mirror passes stay off the paged kernels, but the
    TARGET's verify window must dispatch through the window kernel —
    token-identical to the all-gather engine."""
    perfect = LlamaLoRA(**KNOBS)
    perfect.load_parameters(trained.dump_parameters())
    reqs = [(0, np.asarray([1, 7, 2, 7, 2, 7, 2], np.int32), 8),
            (1, np.asarray([1, 5, 9, 13], np.int32), 8)]
    outs = {}
    kstats = None
    for flag in (False, True):
        eng = trained.make_decode_engine(
            max_slots=4, max_new_tokens=8, speculate_k=4,
            draft_model=perfect, kv_page_size=PS, kv_pages=13,
            paged_kernel=flag).engine
        for rid, p, mn in reqs:
            eng.submit(rid, p, mn)
        done = {}
        for _ in range(600):
            eng.step()
            done.update(dict(eng.poll()))
            if len(done) == len(reqs):
                break
        assert len(done) == len(reqs), (flag, sorted(done))
        outs[flag] = done
        assert eng.stats["paged_kernel_mode"] == (2 if flag else 0)
        if flag:
            kstats = eng.stats_snapshot()
    assert outs[True] == outs[False], (outs[True], outs[False])
    assert kstats["spec_draft_model_calls"] > 0
    assert kstats["paged_kernel_window_tokens"] >= \
        4 * kstats["spec_draft_model_calls"]


def test_windowed_prefill_kernel_exact_and_counters(trained):
    """Chunked prefill dispatches through the window kernel: long
    prompts ingest token-exact vs the gather engine, and every prefill
    token is accounted to ``paged_kernel_window_tokens`` (no spec
    traffic here, so the two counters must agree exactly) while the
    fused scan keeps feeding ``paged_kernel_step_tokens``."""
    reqs = [("lp", np.arange(1, 20, dtype=np.int32), 5),
            ("sp", np.asarray([3, 1, 4, 1, 5], np.int32), 5)]
    _, ks = _kernel_vs_gather(trained, reqs,
                              engine_kw={"prefill_chunk": 8})
    assert ks["prefill_calls"] >= 1
    assert ks["paged_kernel_window_tokens"] == ks["prefill_tokens"] > 0
    assert ks["paged_kernel_step_tokens"] > 0


def test_window_escape_hatch_forces_step_only_mode(trained, monkeypatch):
    """RAFIKI_PAGED_KERNEL_WINDOWS=0: the engine reports step-only mode
    (gauge 1), window traffic goes back to the gather (window-token
    counter stays 0) while the s==1 hot loop keeps the step kernel —
    and tokens stay exact vs the all-gather engine. A fresh pool
    geometry (pages=11) keeps the cached compiled fns from other tests
    (traced with windows enabled) out of this engine."""
    monkeypatch.setenv("RAFIKI_PAGED_KERNEL_WINDOWS", "0")
    reqs = _mixed_reqs(5, seed=11)
    outs = {}
    for flag in (False, True):
        eng = DecodeEngine(
            trained._module(kv_page_size=PS, kv_pages=11,
                            paged_kernel=flag),
            trained._params, max_slots=4, max_len=L, prefill_chunk=8)
        outs[flag] = _drain(eng, reqs)
        assert eng.stats["paged_kernel_mode"] == (1 if flag else 0)
        assert eng.stats["paged_kernel_window_tokens"] == 0
        if flag:
            assert eng.stats["paged_kernel_step_tokens"] > 0
    assert outs[True] == outs[False]


def test_multi_token_gather_window_rides_live_width_slice(trained):
    """Satellite: the gather-fallback prefill window consumes the
    engine's LIVE-WIDTH page-table slice (and the width-following
    mask), not the full table — off-TPU prefill must not gather dead
    pages. Page size 4 gives an 8-wide table of which this traffic
    can only ever light up half."""
    module = trained._module(kv_page_size=4, kv_pages=17,
                             paged_kernel=False)
    eng = DecodeEngine(module, trained._params, max_slots=4, max_len=L,
                       prefill_chunk=8)
    widths = []
    orig = eng._ptab_arg

    def spy():
        out = orig()
        widths.append(int(out.shape[1]))
        return out

    eng._ptab_arg = spy
    eng.submit("lp", np.arange(1, 11, dtype=np.int32), 4)  # 14 positions
    while eng.busy:
        eng.step()
    eng.poll()
    assert widths, "no compiled call consumed the table"
    assert max(widths) <= 4 < eng._n_table  # live slice, never full width


def test_paged_worker_serves_end_to_end(trained):
    """A paged decode-loop worker serves overlapping messages through
    the queue hub identically to a contiguous worker."""
    from rafiki_tpu.serving.predictor import Predictor
    from rafiki_tpu.serving.queues import InProcQueueHub
    from rafiki_tpu.store.param_store import ParamStore
    from rafiki_tpu.worker.inference import InferenceWorker

    store = ParamStore.from_uri("mem://")
    store.save("t0", trained.dump_parameters())
    queries = ["tok1 tok2 tok3", "tok4 tok5"]

    def serve(**kw):
        hub = InProcQueueHub()
        worker = InferenceWorker(LlamaLoRA, "t0", KNOBS, store, hub,
                                 "w0", decode_loop=True, max_slots=4,
                                 max_new_tokens=5, **kw)
        wt = threading.Thread(target=worker.run, daemon=True)
        wt.start()
        try:
            preds, info = Predictor(hub, ["w0"],
                                    gather_timeout=120.0).predict(queries)
            assert info["workers_answered"] == 1
            return preds, worker
        finally:
            worker.stop()
            wt.join(timeout=10)

    ref, _ = serve()
    got, worker = serve(kv_page_size=PS, kv_pages=9)
    assert got == ref
    assert worker.engine.engine.stats["kv_pages_used"] == 0
