"""Orbax interop (SURVEY.md §5.4 'Orbax as the blob format'): rafiki
trees round-trip through standard Orbax checkpoints, including restore
directly into NamedShardings on the 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rafiki_tpu.store.orbax_bridge import load_orbax, save_orbax


def _tree(key=0):
    k = jax.random.PRNGKey(key)
    return {"block_0": {"w": jax.random.normal(k, (16, 32)),
                        "b": jnp.zeros((32,))},
            "head": {"w": jax.random.normal(
                jax.random.fold_in(k, 1), (32, 8))}}


def test_orbax_roundtrip_plain(tmp_path):
    tree = _tree()
    p = save_orbax(str(tmp_path / "ckpt"), tree)
    back = load_orbax(p)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        tree, back)
    # and it IS a plain Orbax checkpoint: raw orbax restores it too
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        raw = ckptr.restore(p)
    np.testing.assert_array_equal(np.asarray(raw["head"]["w"]),
                                  np.asarray(tree["head"]["w"]))


def test_orbax_restore_into_shardings(tmp_path):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    tree = _tree(1)
    p = save_orbax(str(tmp_path / "ckpt"), tree)
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("data", "model"))
    template = {
        "block_0": {"w": jax.ShapeDtypeStruct(
            (16, 32), jnp.float32,
            sharding=NamedSharding(mesh, P("data", "model"))),
            "b": jax.ShapeDtypeStruct(
                (32,), jnp.float32,
                sharding=NamedSharding(mesh, P()))},
        "head": {"w": jax.ShapeDtypeStruct(
            (32, 8), jnp.float32,
            sharding=NamedSharding(mesh, P("model", None)))}}
    back = load_orbax(p, template)
    assert back["block_0"]["w"].sharding.spec == P("data", "model")
    assert back["head"]["w"].sharding.spec == P("model", None)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        tree, back)


def test_orbax_roundtrips_trained_llama(tmp_path):
    """A real template's params through the bridge: what a user would
    export for the wider JAX ecosystem."""
    from test_decode_engine import KNOBS
    from rafiki_tpu.models.llama_lora import LlamaLoRA

    m = LlamaLoRA(**KNOBS)
    params = m._module().init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, int(KNOBS["max_len"])), jnp.int32))["params"]
    p = save_orbax(str(tmp_path / "llama"), params)
    back = load_orbax(p)
    flat_a = jax.tree_util.tree_leaves(params)
    flat_b = jax.tree_util.tree_leaves(back)
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
