"""Unit tests for the flow layer's machinery: per-function CFGs
(``analysis/cfg.py``) and the dataflow engines (``analysis/
dataflow.py``) — separate from the rule-level tests in test_lint.py
so a rule regression and an engine regression point at different
files.

The scenarios are the ones single-instance finally modeling and naive
taint lattices historically get wrong: a ``return`` routed through a
``finally``, the false path that enters a finally normally and leaves
on the exception continuation, nested try/finally unwinding, ``break``
jumping out of a ``with``, and loop-carried taint.
"""

import ast
import textwrap

from rafiki_tpu.analysis.cfg import EDGE_NOTES, build_cfg
from rafiki_tpu.analysis.dataflow import (TaintEngine, header_exprs,
                                          path_search,
                                          tainted_return_helpers)


def _cfg(src):
    tree = ast.parse(textwrap.dedent(src))
    fn = next(n for n in ast.walk(tree)
              if isinstance(n, ast.FunctionDef))
    return build_cfg(fn)


def _calls(stmt, method):
    """Does this statement's header call ``<anything>.<method>()``?"""
    for part in header_exprs(stmt):
        for node in ast.walk(part):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == method:
                return True
    return False


def _after(cfg, method):
    """The (block, index) just past the first ``.<method>()`` call."""
    for block, idx, stmt in cfg.statements():
        if _calls(stmt, method):
            return block, idx + 1
    raise AssertionError(f"no .{method}() call in fixture")


def _search_release(cfg, **kw):
    block, idx = _after(cfg, "acquire")
    return path_search(
        cfg, block, idx,
        kill=lambda s: "hard" if _calls(s, "release") else None,
        to_exit=True, **kw)


# ---- path_search: finally discipline ----

def test_finally_covers_return_path():
    cfg = _cfg("""
        def f(lock):
            lock.acquire()
            try:
                return work()
            finally:
                lock.release()
    """)
    assert _search_release(cfg) == [], (
        "a finally release covers both the return and the exception "
        "route out of the protected region")


def test_early_return_without_finally_is_a_leak():
    cfg = _cfg("""
        def f(lock, closed):
            lock.acquire()
            if closed:
                return None
            lock.release()
    """)
    hits = _search_release(cfg)
    assert len(hits) == 1
    notes = [note for _, note in hits[0].steps]
    assert EDGE_NOTES["true"] in notes, (
        "the witness must show the branch decision that reaches the "
        "leaking return")


def test_normal_entry_cannot_leave_finally_on_exception_continuation():
    """The classic false path of single-instance finally modeling: the
    body cannot raise, so the only route through the finally is the
    normal one — the exception continuation out of the SAME finally
    block must not be taken."""
    cfg = _cfg("""
        def f(lock, flag):
            lock.acquire()
            try:
                x = flag
            finally:
                note()
            lock.release()
    """)
    assert _search_release(cfg) == [], (
        "kind-matched fin: continuations must stop a normally-entered "
        "path from exiting on the raise continuation")


def test_nested_finally_unwinds_to_outer_release():
    cfg = _cfg("""
        def f(lock):
            lock.acquire()
            try:
                try:
                    step()
                finally:
                    inner_cleanup()
            finally:
                lock.release()
            tail()
    """)
    assert _search_release(cfg) == [], (
        "an exception from step() unwinds inner finally -> outer "
        "finally, where the release settles it")


def test_nested_finally_without_release_reports_the_exception_path():
    cfg = _cfg("""
        def f(lock):
            lock.acquire()
            try:
                try:
                    step()
                finally:
                    inner_cleanup()
            finally:
                log()
            lock.release()
    """)
    hits = _search_release(cfg)
    assert hits, "the unwinding exception skips the final release"
    notes = [note for _, note in hits[0].steps]
    assert EDGE_NOTES["exc"] in notes


def test_return_inside_finally_overrides_pending_continuation():
    """CPython semantics: a return in the finally wins over the try
    body's return — the function exits AT the finally's return."""
    cfg = _cfg("""
        def f():
            try:
                return 1
            finally:
                return 2
    """)
    hits = path_search(cfg, cfg.entry, 0, kill=lambda s: None,
                       to_exit=True)
    assert len(hits) == 1
    exit_stmt = hits[0].stmt
    assert isinstance(exit_stmt, ast.Return)
    assert exit_stmt.value.value == 2


def test_break_out_of_with_leaks_past_the_release():
    """break inside a with jumps straight past the release at the
    bottom of the loop body — a with block is NOT a finally."""
    cfg = _cfg("""
        def f(lock, jobs, guard):
            for j in jobs:
                lock.acquire()
                with guard:
                    if j:
                        break
                lock.release()
            done()
    """)
    hits = _search_release(cfg)
    assert len(hits) == 1
    notes = [note for _, note in hits[0].steps]
    assert EDGE_NOTES["break"] in notes


def test_exception_reaches_handler_where_release_settles():
    cfg = _cfg("""
        def f(lock):
            lock.acquire()
            try:
                step()
            except ValueError:
                lock.release()
                raise
            lock.release()
    """)
    assert _search_release(cfg) == []


# ---- path_search: soft kills ----

def test_soft_kill_reports_the_raise_inside_the_settling_call():
    cfg = _cfg("""
        def f(alloc, mgr):
            slot = alloc.acquire()
            mgr.spawn(slot)
            tail()
    """)
    block, idx = _after(cfg, "acquire")
    hits = path_search(
        cfg, block, idx,
        kill=lambda s: "soft" if _calls(s, "spawn") else None,
        to_exit=True, soft_exc_note="LEAK")
    assert [h.note for h in hits] == ["LEAK"]
    assert _calls(hits[0].stmt, "spawn")


def test_soft_kill_with_guarding_handler_is_settled():
    cfg = _cfg("""
        def f(alloc, mgr):
            slot = alloc.acquire()
            try:
                mgr.spawn(slot)
            except Exception:
                alloc.release(slot)
                raise
            tail()
    """)
    block, idx = _after(cfg, "acquire")
    hits = path_search(
        cfg, block, idx,
        kill=lambda s: ("hard" if _calls(s, "release")
                        else "soft" if _calls(s, "spawn") else None),
        to_exit=True, soft_exc_note="LEAK")
    assert hits == [], (
        "the except handler releases the handle before re-raising — "
        "the soft kill's exception path is covered")


# ---- TaintEngine ----

def _wall_source(node):
    if isinstance(node, ast.Call) and isinstance(node.func,
                                                 ast.Attribute):
        if node.func.attr == "time":
            return "wall-clock read"
    return None


def _taint_engine(src, sanitizer=None):
    tree = ast.parse(textwrap.dedent(src))
    fn = next(n for n in ast.walk(tree)
              if isinstance(n, ast.FunctionDef))
    cfg = build_cfg(fn)
    return TaintEngine(cfg, _wall_source, sanitizer).run(), cfg


def _sink_arg(cfg, name="sink"):
    """The (stmt, first-arg node) of the ``sink(...)`` call."""
    for block, idx, stmt in cfg.statements():
        for part in header_exprs(stmt):
            for node in ast.walk(part):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Name) and \
                        node.func.id == name:
                    return stmt, node.args[0]
    raise AssertionError(f"no {name}() call in fixture")


def test_loop_carried_taint_reaches_the_previous_iterations_read():
    eng, cfg = _taint_engine("""
        def f(jobs):
            prev = None
            for j in jobs:
                sink(prev)
                prev = time.time()
    """)
    stmt, arg = _sink_arg(cfg)
    taint = eng.taint_at(arg, stmt)
    assert taint is not None, (
        "iteration 2 reads the taint assigned in iteration 1 — the "
        "fixpoint must carry it around the back edge")
    assert taint.steps[0][2] == "wall-clock read"
    assert any("prev" in note for _, _, note in taint.steps)


def test_rebinding_to_a_clean_value_kills_taint():
    eng, cfg = _taint_engine("""
        def f():
            t = time.time()
            t = 0
            sink(t)
    """)
    stmt, arg = _sink_arg(cfg)
    assert eng.taint_at(arg, stmt) is None


def test_sanitizer_call_cuts_the_flow():
    def wash(call):
        return isinstance(call.func, ast.Name) and \
            call.func.id == "wash"

    eng, cfg = _taint_engine("""
        def f():
            t = time.time()
            d = wash(t)
            sink(d)
    """, sanitizer=wash)
    stmt, arg = _sink_arg(cfg)
    assert eng.taint_at(arg, stmt) is None


def test_taint_on_one_branch_survives_the_merge():
    """May-analysis: taint reaching the join point on EITHER branch
    taints the join — one hostile path is enough for a finding."""
    eng, cfg = _taint_engine("""
        def f(c):
            if c:
                t = time.time()
            else:
                t = 0
            sink(t)
    """)
    stmt, arg = _sink_arg(cfg)
    assert eng.taint_at(arg, stmt) is not None


def test_arbitrary_call_does_not_launder_nor_propagate_args():
    """A general call's RESULT does not carry its arguments' taint
    (it returns a cursor, not the timestamp) — but value-preserving
    casts do."""
    eng, cfg = _taint_engine("""
        def f(db):
            t = time.time()
            cur = db.execute(t)
            sink(cur)
    """)
    stmt, arg = _sink_arg(cfg)
    assert eng.taint_at(arg, stmt) is None

    eng, cfg = _taint_engine("""
        def f():
            t = time.time()
            v = float(t)
            sink(v)
    """)
    stmt, arg = _sink_arg(cfg)
    assert eng.taint_at(arg, stmt) is not None


def test_tainted_return_helpers_one_level_of_interprocedural_reach():
    tree = ast.parse(textwrap.dedent("""
        def _now():
            return time.time()

        def fixed():
            return 42
    """))
    helpers = tainted_return_helpers(tree, _wall_source)
    assert "_now" in helpers and "self._now" in helpers
    assert "fixed" not in helpers
    assert helpers["_now"].steps[0][2] == "wall-clock read"


# ---- header_exprs ----

def test_header_exprs_sees_headers_not_bodies():
    tree = ast.parse(textwrap.dedent("""
        if cond():
            body_call()
    """))
    if_stmt = tree.body[0]
    parts = header_exprs(if_stmt)
    names = {n.func.id for p in parts for n in ast.walk(p)
             if isinstance(n, ast.Call)
             and isinstance(n.func, ast.Name)}
    assert names == {"cond"}, (
        "a compound statement evaluates only its header at its CFG "
        "position — the body belongs to other blocks")
