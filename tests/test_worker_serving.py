"""Train worker loop, inference workers, predictor scatter/gather/ensemble."""

import threading

import numpy as np
import pytest

from rafiki_tpu.advisor import make_advisor
from rafiki_tpu.data import generate_image_classification_dataset
from rafiki_tpu.models.mlp import JaxFeedForward
from rafiki_tpu.serving import InProcQueueHub, KVQueueHub
from rafiki_tpu.serving.predictor import (Predictor, PredictorService,
                                          ensemble_predictions)
from rafiki_tpu.store.meta_store import MetaStore
from rafiki_tpu.store.param_store import ParamStore
from rafiki_tpu.utils.http import json_request
from rafiki_tpu.worker import InferenceWorker, TrainWorker


@pytest.fixture(scope="module")
def datasets(tmp_path_factory):
    d = tmp_path_factory.mktemp("ds")
    tr, va = str(d / "train.npz"), str(d / "val.npz")
    generate_image_classification_dataset(tr, 256, seed=0)
    val_ds = generate_image_classification_dataset(va, 64, seed=1)
    return tr, va, val_ds


@pytest.fixture()
def trained(datasets):
    """One completed sub-train-job: meta rows + params in the store."""
    tr, va, _ = datasets
    meta = MetaStore(":memory:")
    params = ParamStore()
    user = meta.create_user("u@x", "pw", "ADMIN")
    model = meta.create_model(user["id"], "mlp", "IMAGE_CLASSIFICATION",
                              model_bytes=b"", model_class="JaxFeedForward")
    job = meta.create_train_job(user["id"], app="app", app_version=1,
                                task="IMAGE_CLASSIFICATION",
                                budget={"TRIAL_COUNT": 3},
                                train_dataset_id=tr, val_dataset_id=va)
    sub = meta.create_sub_train_job(job["id"], model["id"])
    advisor = make_advisor(JaxFeedForward.get_knob_config(), "random",
                           total_trials=3, seed=0)
    worker = TrainWorker(JaxFeedForward, advisor, tr, va,
                         param_store=params, meta_store=meta,
                         sub_train_job_id=sub["id"], model_id=model["id"])
    n = worker.run()
    assert n == 3
    return meta, params, job, advisor


def test_train_worker_records_trials(trained):
    meta, params, job, advisor = trained
    trials = meta.get_trials_of_train_job(job["id"])
    assert len(trials) == 3
    completed = [t for t in trials if t["status"] == "COMPLETED"]
    assert completed, "at least one trial should complete"
    best = meta.get_best_trials_of_train_job(job["id"], max_count=2)
    assert best and best[0]["score"] >= best[-1]["score"]
    # params were saved for completed trials
    for t in completed:
        assert params.load(t["id"]) is not None
    # trial logs flowed through the sink
    logs = meta.get_trial_logs(completed[0]["id"])
    assert any(r["kind"] == "values" for r in logs)
    assert advisor.best is not None


def test_trial_error_isolated(datasets):
    tr, va, _ = datasets

    class Exploding(JaxFeedForward):
        def train(self, path, ctx=None):
            raise RuntimeError("boom")

    meta = MetaStore(":memory:")
    advisor = make_advisor(Exploding.get_knob_config(), "random",
                           total_trials=2, seed=0)
    user = meta.create_user("u@x", "pw", "ADMIN")
    model = meta.create_model(user["id"], "exploding",
                              "IMAGE_CLASSIFICATION",
                              model_class="Exploding", model_bytes=b"")
    job = meta.create_train_job(user["id"], "app", 1, "IMAGE_CLASSIFICATION",
                                {"TRIAL_COUNT": 2}, tr, va)
    sub = meta.create_sub_train_job(job["id"], model["id"])
    w = TrainWorker(Exploding, advisor, tr, va, meta_store=meta,
                    sub_train_job_id=sub["id"], model_id=model["id"])
    assert w.run() == 2  # loop survives both failures
    trials = meta.get_trials_of_sub_train_job(sub["id"])
    assert all(t["status"] == "ERRORED" for t in trials)
    assert "boom" in trials[0]["error"]


def _boot_workers(trained, hub, n=2):
    meta, params, job, _ = trained
    best = meta.get_best_trials_of_train_job(job["id"], max_count=n)
    workers, threads = [], []
    for i, t in enumerate(best):
        w = InferenceWorker(JaxFeedForward, t["id"], t["knobs"], params,
                            hub, worker_id=f"iw-{i}")
        th = threading.Thread(target=w.run, kwargs={"poll_timeout": 0.1})
        th.start()
        workers.append(w)
        threads.append(th)
    return workers, threads


def test_predict_end_to_end_inproc(trained, datasets):
    _, _, val_ds = datasets
    hub = InProcQueueHub()
    workers, threads = _boot_workers(trained, hub)
    try:
        pred = Predictor(hub, [w.worker_id for w in workers],
                         gather_timeout=30.0)
        queries = [val_ds.images[i] for i in range(8)]
        preds, info = pred.predict(queries)
        assert info["workers_answered"] == 2
        assert len(preds) == 8
        acc = np.mean([int(np.argmax(p)) == val_ds.labels[i]
                       for i, p in enumerate(preds)])
        assert acc >= 0.5  # trained ensemble beats chance easily
    finally:
        for w in workers:
            w.stop()
        for th in threads:
            th.join(timeout=5)


@pytest.mark.slow
def test_predict_end_to_end_kv(trained, datasets):
    from rafiki_tpu.native import KVServer

    _, _, val_ds = datasets
    with KVServer() as server:
        hub = KVQueueHub(server.host, server.port)
        workers, threads = _boot_workers(trained, hub)
        try:
            pred = Predictor(hub, [w.worker_id for w in workers],
                             gather_timeout=30.0)
            preds, info = pred.predict([val_ds.images[0]])
            assert info["workers_answered"] == 2
            assert len(preds) == 1 and len(preds[0]) == val_ds.n_classes
        finally:
            for w in workers:
                w.stop()
            for th in threads:
                th.join(timeout=5)


def test_predictor_http_service(trained, datasets):
    _, _, val_ds = datasets
    hub = InProcQueueHub()
    workers, threads = _boot_workers(trained, hub, n=1)
    svc = PredictorService(Predictor(hub, [workers[0].worker_id],
                                     gather_timeout=30.0))
    host, port = svc.start()
    try:
        out = json_request(
            "POST", f"http://{host}:{port}/predict",
            {"queries": [np.asarray(val_ds.images[0]).tolist()]},
            timeout=60.0)
        assert len(out["predictions"]) == 1
        health = json_request("GET", f"http://{host}:{port}/health",
                              timeout=5.0)
        assert health["ok"]
    finally:
        svc.stop()
        for w in workers:
            w.stop()
        for th in threads:
            th.join(timeout=5)


def test_predictor_timeout_no_workers():
    hub = InProcQueueHub()
    pred = Predictor(hub, ["ghost"], gather_timeout=0.2)
    preds, info = pred.predict([[1, 2, 3]])
    assert preds == [] and info["workers_answered"] == 0


def test_ensemble_prob_averaging():
    a = [[0.8, 0.2], [0.1, 0.9]]
    b = [[0.6, 0.4], [0.3, 0.7]]
    out = ensemble_predictions([a, b])
    np.testing.assert_allclose(out[0], [0.7, 0.3])
    np.testing.assert_allclose(out[1], [0.2, 0.8])


def test_ensemble_majority_vote():
    out = ensemble_predictions([["cat", "dog"], ["cat", "cow"],
                                ["dog", "cow"]])
    assert out == ["cat", "cow"]


def test_train_worker_knob_overrides(tmp_path):
    """Job-level knob pins merge over every advisor proposal."""
    from rafiki_tpu.advisor import make_advisor
    from rafiki_tpu.data import generate_image_classification_dataset
    from rafiki_tpu.models.mlp import JaxFeedForward
    from rafiki_tpu.worker.train import TrainWorker

    tr = str(tmp_path / "tr.npz")
    va = str(tmp_path / "va.npz")
    generate_image_classification_dataset(tr, 128, seed=0)
    generate_image_classification_dataset(va, 64, seed=1)
    advisor = make_advisor(JaxFeedForward.get_knob_config(), "random",
                           total_trials=2, seed=0)
    worker = TrainWorker(
        JaxFeedForward, advisor, tr, va,
        knob_overrides={"hidden_layer_count": 1,
                        "hidden_layer_units": 16, "quick_train": True})
    n = worker.run()
    assert n == 2
    for r in advisor.results:
        assert r.knobs["hidden_layer_count"] == 1
        assert r.knobs["hidden_layer_units"] == 16


def test_inproc_hub_sweep_never_orphans_waiters(monkeypatch):
    """The idle-entry sweep must skip keys with parked poppers: deleting
    one would orphan the waiter (a later push notifies a NEW object)."""
    import threading
    import time

    from rafiki_tpu.serving import queues as qmod

    monkeypatch.setattr(qmod, "_IDLE_TTL_S", 0.0)  # everything is stale
    monkeypatch.setattr(qmod, "_SWEEP_EVERY", 4)   # sweep constantly
    hub = qmod.InProcQueueHub()

    got = []
    waiter = threading.Thread(
        target=lambda: got.append(hub.pop_prediction("q1", timeout=10.0)))
    waiter.start()
    time.sleep(0.2)  # parked on the condvar, entry empty + "stale"
    for i in range(64):  # churn other keys → many sweeps run
        hub.push_query(f"w{i}", b"x")
    hub.push_prediction("q1", b"reply")
    waiter.join(timeout=5.0)
    assert got == [b"reply"]


def test_predictor_discards_reply_queue_after_gather():
    """Late replies must not accumulate forever: the predictor drops its
    per-query reply queue once the gather finishes (both hubs)."""
    from rafiki_tpu.serving.predictor import Predictor
    from rafiki_tpu.serving.queues import (InProcQueueHub, pack_message,
                                           unpack_message)

    hub = InProcQueueHub()
    pred = Predictor(hub, ["w0"], gather_timeout=5.0)

    import threading

    def worker():  # answer the single query promptly
        raw = hub.pop_query("w0", timeout=5.0)
        msg = unpack_message(raw)
        hub.push_prediction(msg["id"], pack_message(
            {"id": msg["id"], "predictions": [[1.0]]}))

    t = threading.Thread(target=worker)
    t.start()
    preds, info = pred.predict([[0.0]])
    t.join(timeout=5)
    assert info["workers_answered"] == 1
    # the reply queue is gone from the hub map
    reply_keys = [k for k in hub._queues if k.startswith("p:")]
    assert reply_keys == [], reply_keys


def test_worker_drops_expired_queries():
    """A query popped after its gather deadline skips the forward pass
    and answers with a structured ``expired`` rejection (ISSUE 12: the
    predictor records a skipped vote / fails a stream over immediately
    instead of reading the drop as silence)."""
    import time

    from rafiki_tpu.serving.queues import pack_message, unpack_message
    from rafiki_tpu.worker.inference import InferenceWorker

    hub = InProcQueueHub()
    from rafiki_tpu.worker.inference import EXPIRY_SKEW_TOLERANCE_S

    hub.push_query("w0", pack_message(
        {"id": "dead", "queries": [[0.0]],
         # expired beyond the clock-skew margin
         "deadline_ts": time.time() - EXPIRY_SKEW_TOLERANCE_S - 1.0}))
    hub.push_query("w0", pack_message(
        {"id": "live", "queries": [[0.0]],
         "deadline_ts": time.time() + 30.0}))

    from rafiki_tpu.model.base import BaseModel

    class OneShot(BaseModel):
        TASKS = ("IMAGE_CLASSIFICATION",)

        @staticmethod
        def get_knob_config():
            return {}

        def train(self, dataset_path, ctx=None):
            pass

        def evaluate(self, dataset_path):
            return 1.0

        def predict(self, queries):
            return [[1.0] for _ in queries]

        def dump_parameters(self):
            return {"ok": np.asarray(1)}

        def load_parameters(self, params):
            pass

    store = ParamStore.from_uri("mem://")
    store.save("t0", OneShot().dump_parameters())
    w = InferenceWorker(OneShot, "t0", {}, store, hub, "w0")
    w.run(poll_timeout=0.1, max_iterations=1)
    # the expired query got a structured rejection, not a prediction
    # (and not silence) — and the drop counter still tells the
    # clock-skew story
    dead = hub.pop_prediction("dead", timeout=1.0)
    assert dead is not None
    m = unpack_message(dead)
    assert m["expired"] is True and m["predictions"] == []
    assert w.stats["dropped_expired"] == 1
    live = hub.pop_prediction("live", timeout=1.0)
    assert live is not None and unpack_message(live)["id"] == "live"


def test_worker_warms_serving_path_at_boot(trained):
    """Boot must pre-compile the serving forward so the first request
    doesn't pay XLA compilation."""
    meta, params, job, _ = trained
    best = meta.get_best_trials_of_train_job(job["id"], max_count=1)[0]
    calls = []

    class Spy(JaxFeedForward):
        def warmup(self):
            calls.append(1)
            super().warmup()

    hub = InProcQueueHub()
    InferenceWorker(Spy, best["id"], best["knobs"], params, hub, "w-warm")
    assert calls == [1]


def test_expiry_skew_tolerance():
    """Workers tolerate a few seconds of predictor↔worker clock skew
    before dropping a query as expired (ADVICE r3)."""
    import time

    from rafiki_tpu.worker.inference import (EXPIRY_SKEW_TOLERANCE_S,
                                             _expired)

    now = time.time()
    assert not _expired({"deadline_ts": now - 1.0})  # inside the margin
    assert _expired({"deadline_ts": now - EXPIRY_SKEW_TOLERANCE_S - 1.0})
    assert not _expired({})  # unstamped queries never expire


def test_dropped_expired_counter():
    from rafiki_tpu.obs import StatsMap

    w = InferenceWorker.__new__(InferenceWorker)  # no model boot needed
    w.worker_id = "w0"
    w.stats = StatsMap({"dropped_expired": 0})
    w._count_dropped(3)
    w._count_dropped(0)
    assert w.stats["dropped_expired"] == 3


def test_malformed_sampling_degrades_not_crashes():
    """A bad sampling dict from a client must coerce to defaults, not
    raise inside the decode loop (which would kill the worker thread)."""
    from rafiki_tpu.worker.inference import _safe_sampling

    assert _safe_sampling(None) == {"temperature": 0.0, "top_k": 0,
                                    "top_p": 1.0, "seed": 0}
    assert _safe_sampling("garbage")["temperature"] == 0.0
    out = _safe_sampling({"temperature": "hot", "top_k": 5,
                          "top_p": None, "seed": 2.0})
    assert out == {"temperature": 0.0, "top_k": 5, "top_p": 1.0,
                   "seed": 2}


def test_worker_stats_surface_in_predictor_health(trained, datasets):
    """Worker drop/engine counters publish through the hub and appear
    in Predictor.stats()['workers'] (ADVICE r3: silent drops must be
    visible predictor-side, not mystery timeouts)."""
    import time

    from rafiki_tpu.serving.queues import (EXPIRY_SKEW_TOLERANCE_S,
                                           pack_message)

    _, _, val_ds = datasets
    hub = InProcQueueHub()
    workers, threads = _boot_workers(trained, hub, n=1)
    try:
        wid = workers[0].worker_id
        # one live query + one long-expired one
        pred = Predictor(hub, [wid], gather_timeout=30.0)
        hub.push_query(wid, pack_message(
            {"id": "dead", "queries": [val_ds.images[0]],
             "deadline_ts": time.time() - EXPIRY_SKEW_TOLERANCE_S - 5}))
        pred.predict([val_ds.images[0]])
        workers[0]._publish_stats()  # deterministic flush for the test
        stats = pred.stats()
        assert stats["workers"][wid]["dropped_expired"] >= 1
    finally:
        for w in workers:
            w.stop()
        for th in threads:
            th.join(timeout=5)


def test_adaptive_gather_sheds_straggler():
    """The latency/accuracy controller (paper's serving tradeoff): with
    adaptive gathering, the gather deadline tracks observed reply
    latencies, so a persistently slow replica stops taxing every
    request — later requests answer with the fast replica only, far
    under the static timeout."""
    import threading
    import time as _time

    from rafiki_tpu.serving.predictor import Predictor
    from rafiki_tpu.serving.queues import (InProcQueueHub, pack_message,
                                           unpack_message)

    hub = InProcQueueHub()
    # target_answer_frac picks the accuracy/latency point: with half
    # of all replies coming from the straggler, capturing >50% of
    # replies would NECESSARILY wait for it — target 45% to trade that
    # replica's votes away for its latency
    pred = Predictor(hub, ["fast", "slow"], gather_timeout=5.0,
                     adaptive_gather=True, target_answer_frac=0.45,
                     gather_margin=1.5, min_gather_timeout=0.02)
    stop = threading.Event()

    def worker(wid, delay):
        while not stop.is_set():
            raw = hub.pop_query(wid, timeout=0.2)
            if raw is None:
                continue
            msg = unpack_message(raw)
            _time.sleep(delay)
            hub.push_prediction(msg["id"], pack_message(
                {"id": msg["id"], "worker_id": wid,
                 "predictions": [[1.0]]}))

    threads = [threading.Thread(target=worker, args=("fast", 0.005),
                                daemon=True),
               threading.Thread(target=worker, args=("slow", 0.4),
                                daemon=True)]
    for t in threads:
        t.start()
    try:
        # warmup: seed the latency pool until the 45th percentile
        # settles onto the fast replica's latencies (thread-startup
        # noise in the first samples washes out as fast entries
        # accumulate below the straggler's). The controller may start
        # shedding MID-warmup — that's it working; the first requests
        # must still see both replicas (static-timeout warmup phase)
        answered = []
        for _ in range(12):
            _, info = pred.predict([[0.0]])
            answered.append(info["workers_answered"])
        assert answered[0] == 2, answered  # warmup phase waits for all
        # the 45th-percentile reply latency is the fast worker's, so
        # the budget collapses to ~fast*margin — far below the slow
        # worker's 0.4s
        budget = pred._gather_deadline_s()
        assert budget < 0.3, budget
        t0 = __import__("time").monotonic()
        preds, info = pred.predict([[0.0]])
        dt = __import__("time").monotonic() - t0
        assert info["workers_answered"] == 1  # straggler shed
        assert preds == [[1.0]]
        assert dt < 0.38, dt  # didn't wait for the slow replica
        # the controller is visible in /health (the window mutated
        # since `budget` was read, so assert the regime, not equality)
        s = pred.stats()
        assert s["adaptive_gather"] and s["gather_deadline_s"] < 0.3
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)


def test_adaptive_gather_recovers_from_fleet_slowdown():
    """Anti-death-spiral: after the budget has collapsed onto a fast
    fleet, the WHOLE fleet slowing past the budget yields zero-answer
    gathers — penalty samples must push the budget back up until
    answers flow again (instead of 504ing forever on a frozen low
    budget)."""
    import threading
    import time as _time

    from rafiki_tpu.serving.predictor import Predictor
    from rafiki_tpu.serving.queues import (InProcQueueHub, pack_message,
                                           unpack_message)

    hub = InProcQueueHub()
    pred = Predictor(hub, ["w0"], gather_timeout=2.0,
                     adaptive_gather=True, target_answer_frac=0.9,
                     gather_margin=1.2, min_gather_timeout=0.01)
    delay = [0.005]
    stop = threading.Event()

    def worker():
        while not stop.is_set():
            raw = hub.pop_query("w0", timeout=0.2)
            if raw is None:
                continue
            msg = unpack_message(raw)
            _time.sleep(delay[0])
            hub.push_prediction(msg["id"], pack_message(
                {"id": msg["id"], "worker_id": "w0",
                 "predictions": [[1.0]]}))

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        for _ in range(6):  # converge onto the fast latency
            pred.predict([[0.0]])
        assert pred._gather_deadline_s() < 0.2
        delay[0] = 0.25  # fleet slows past the learned budget
        answered = []
        for _ in range(10):
            _, info = pred.predict([[0.0]])
            answered.append(info["workers_answered"])
        # early requests miss, penalties raise the budget, answers
        # return before the loop ends
        assert answered[-1] == 1, answered
        assert 0 in answered, answered  # the slowdown really bit first
    finally:
        stop.set()
        t.join(timeout=5)


def test_adaptive_gather_recovers_fast_with_full_window():
    """ADVICE r4: with a FULL 2048-sample window of stale fast
    latencies, one penalty sample per zero-answer gather would take
    ~100 failed requests to move the p95 — the escalate-then-flush
    recovery must relearn within a handful instead."""
    import threading
    import time as _time

    from rafiki_tpu.serving.predictor import Predictor
    from rafiki_tpu.serving.queues import (InProcQueueHub, pack_message,
                                           unpack_message)

    hub = InProcQueueHub()
    pred = Predictor(hub, ["w0"], gather_timeout=2.0,
                     adaptive_gather=True, target_answer_frac=0.9,
                     gather_margin=1.2, min_gather_timeout=0.01)
    # a long steady-state: the reservoir is FULL of fast samples
    pred._reply_lat.extend([0.01] * pred.LATENCY_WINDOW)
    assert pred._gather_deadline_s() < 0.2
    delay = [0.3]  # fleet is ALREADY slower than the learned budget
    stop = threading.Event()

    def worker():
        while not stop.is_set():
            raw = hub.pop_query("w0", timeout=0.2)
            if raw is None:
                continue
            msg = unpack_message(raw)
            _time.sleep(delay[0])
            hub.push_prediction(msg["id"], pack_message(
                {"id": msg["id"], "worker_id": "w0",
                 "predictions": [[1.0]]}))

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        answered = []
        for _ in range(5):
            _, info = pred.predict([[0.0]])
            answered.append(info["workers_answered"])
        # 3 misses flush the stale window -> static budget -> answers
        assert answered[-1] == 1, answered
        assert 0 in answered, answered
    finally:
        stop.set()
        t.join(timeout=5)


def test_predict_rejects_nonpositive_timeout():
    """ADVICE r4: an explicit degenerate timeout (0, negative, NaN,
    non-numeric) must 400, not silently fall back to the default."""
    from rafiki_tpu.serving.predictor import Predictor, PredictorService
    from rafiki_tpu.serving.queues import InProcQueueHub

    svc = PredictorService(Predictor(InProcQueueHub(), ["w0"]))
    for handler in (svc._predict, svc._predict_stream):
        for bad in (0, -1, "nope", float("nan"), float("inf"),
                    True, 1e15):
            code, body = handler(None, {"queries": [[0.0]],
                                        "timeout": bad}, None)
            assert code == 400, (handler, bad, code)
            assert "timeout" in body["error"]


def test_admission_check_refuses_oversized_budget(monkeypatch):
    """VERDICT r4 item 6: the worker refuses a trial whose estimated
    per-device footprint exceeds the device limit BEFORE any compile —
    and skips the check cleanly when no limit is known (CPU, no env)."""
    import pytest as _pytest

    from rafiki_tpu.worker.train import TrainWorker

    class Stub:
        def estimate_device_budget(self, n):
            return {"params": 32 << 30, "total": 64 << 30}

    w = TrainWorker.__new__(TrainWorker)
    w.devices = None
    monkeypatch.setenv("RAFIKI_DEVICE_HBM_BYTES", str(16 << 30))
    with _pytest.raises(ValueError, match="admission control"):
        w._admission_check(Stub())
    monkeypatch.setenv("RAFIKI_DEVICE_HBM_BYTES", str(128 << 30))
    w._admission_check(Stub())  # fits: admitted
    monkeypatch.delenv("RAFIKI_DEVICE_HBM_BYTES")
    w._admission_check(Stub())  # CPU without a limit: check skipped
    w._admission_check(object())  # no estimator: admitted
    # a config typo must not fail every trial closed: warn + skip
    monkeypatch.setenv("RAFIKI_DEVICE_HBM_BYTES", "16GiB")
    w._admission_check(Stub())


def test_admission_check_with_real_llama_budget(monkeypatch):
    """The real Llama formula flows through the worker check: a 1KiB
    fake device limit refuses even the tiny test spec, with the
    breakdown in the message."""
    import pytest as _pytest

    from rafiki_tpu.models.llama_lora import LlamaLoRA
    from rafiki_tpu.worker.train import TrainWorker

    w = TrainWorker.__new__(TrainWorker)
    w.devices = None
    model = LlamaLoRA(max_epochs=1, vocab_size=1 << 10, hidden_dim=64,
                      depth=2, n_heads=4, kv_ratio=2, lora_rank=4,
                      max_len=32, model_parallel=2, batch_size=8,
                      learning_rate=1e-2)
    monkeypatch.setenv("RAFIKI_DEVICE_HBM_BYTES", "1024")
    with _pytest.raises(ValueError, match="admission control"):
        w._admission_check(model)


def test_serving_byte_budget_terms():
    """estimate_serving_device_bytes: params term equals the ACTUAL
    loaded tree's bytes; the int8 KV knob shrinks the cache term; the
    adapters term grows linearly in tenant count."""
    import jax
    import jax.numpy as jnp

    from rafiki_tpu.models.llama_lora import LlamaLoRA

    knobs = dict(max_epochs=1, vocab_size=1 << 10, hidden_dim=64,
                 depth=2, n_heads=4, kv_ratio=2, lora_rank=4,
                 max_len=32, batch_size=8, learning_rate=1e-2)
    m = LlamaLoRA(**knobs)
    m._params = m._module().init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    b = m.estimate_serving_device_bytes(max_slots=4)
    measured = sum(int(np.prod(l.shape)) * l.dtype.itemsize
                   for l in jax.tree_util.tree_leaves(m._params))
    assert b["params"] == measured, (b["params"], measured)
    # kv cache math: slots*L*depth*2*kv_heads*dh*4 (f32 compute)
    assert b["kv_cache"] == 4 * 32 * 2 * 2 * 2 * 16 * 4, b
    m8 = LlamaLoRA(**{**knobs, "kv_cache_int8": True})
    m8._params = m._params
    b8 = m8.estimate_serving_device_bytes(max_slots=4)
    assert b8["kv_cache"] < b["kv_cache"], (b8, b)
    b1 = m.estimate_serving_device_bytes(max_slots=4,
                                         n_extra_adapters=1)
    b3 = m.estimate_serving_device_bytes(max_slots=4,
                                         n_extra_adapters=3)
    assert b1["adapters"] > 0
    assert b3["adapters"] == 3 * b1["adapters"]
    # a draft model adds its params + cache
    bd = m.estimate_serving_device_bytes(max_slots=4, draft=m8)
    assert bd["draft"] >= b8["params"] + b8["kv_cache"]
    # micro-batch deployments (no decode engine) charge no cache: the
    # worker passes max_slots=0 when decode_loop is off
    b0 = m.estimate_serving_device_bytes(max_slots=0)
    assert b0["kv_cache"] == 0 and b0["working"] == 0
    assert b0["total"] == b0["params"]


def test_serving_admission_refuses_oversized_engine(monkeypatch):
    """The inference worker refuses a deployment whose serving
    footprint exceeds the device limit BEFORE building the engine —
    and admits it again under a sane limit."""
    import jax
    import jax.numpy as jnp
    import pytest as _pytest

    from rafiki_tpu.models.llama_lora import LlamaLoRA
    from rafiki_tpu.serving.queues import InProcQueueHub
    from rafiki_tpu.store.param_store import ParamStore
    from rafiki_tpu.worker.inference import InferenceWorker
    from test_decode_engine import KNOBS

    lm = LlamaLoRA(**KNOBS)
    lm._params = lm._module().init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    store = ParamStore()
    store.save("t-adm", lm.dump_parameters())

    monkeypatch.setenv("RAFIKI_DEVICE_HBM_BYTES", "4096")  # 4KiB
    with _pytest.raises(ValueError, match="serving admission"):
        InferenceWorker(LlamaLoRA, "t-adm", KNOBS, store,
                        InProcQueueHub(), worker_id="w-adm",
                        decode_loop=True, max_slots=4)
    monkeypatch.setenv("RAFIKI_DEVICE_HBM_BYTES", str(64 << 30))
    w = InferenceWorker(LlamaLoRA, "t-adm", KNOBS, store,
                        InProcQueueHub(), worker_id="w-adm",
                        decode_loop=True, max_slots=4)
    assert w.engine is not None
    w.stop()


def test_per_request_max_new_clamped(trained_lm):
    """Clients control generation length via sampling.max_new, clamped
    by the worker's configured cap (slot-occupancy protection).
    ``trained_lm``: the session LM fixture (this file's own ``trained``
    fixture is the MLP sub-train-job and shadows the short name)."""
    import threading

    from rafiki_tpu.models.llama_lora import LlamaLoRA
    from rafiki_tpu.serving.queues import InProcQueueHub
    from test_decode_engine import KNOBS as LM_KNOBS

    store = ParamStore.from_uri("mem://")
    store.save("lm0", trained_lm.dump_parameters())
    hub = InProcQueueHub()
    worker = InferenceWorker(LlamaLoRA, "lm0", LM_KNOBS, store, hub,
                             "w0", decode_loop=True, max_slots=4,
                             max_new_tokens=6)
    wt = threading.Thread(target=worker.run, daemon=True)
    wt.start()
    try:
        pred = Predictor(hub, ["w0"], gather_timeout=120.0)
        short, _ = pred.predict(["tok1 tok2 tok3"],
                                sampling={"max_new": 2})
        capped, _ = pred.predict(["tok1 tok2 tok3"],
                                 sampling={"max_new": 50})
        default, _ = pred.predict(["tok1 tok2 tok3"])
        assert len(short[0].split()) == 2, short
        assert len(capped[0].split()) == 6, capped  # clamped to cap
        assert len(default[0].split()) == 6, default
        # the short answer is a prefix of the greedy default
        assert default[0].startswith(short[0])
    finally:
        worker.stop()
        wt.join(timeout=10)
