"""Serving-only int8 KV cache (kv_cache_int8 knob): half the decode
cache's HBM at bf16, bounded quantization error; engine-vs-oracle
exactness holds WITHIN the quantized world (both run the same module)."""

import jax
import jax.numpy as jnp
import numpy as np

from rafiki_tpu.models.llama_lora import LlamaLoRA, greedy_generate

from test_decode_engine import KNOBS  # noqa: F401 — shared knobs


def test_kv_int8_cache_dtype_and_size(trained):  # noqa: F811
    m8 = LlamaLoRA(**{**KNOBS, "kv_cache_int8": True})
    m8.load_parameters(trained.dump_parameters())
    eng = m8.make_decode_engine(max_slots=4, max_new_tokens=4)
    cache = eng.engine._cache
    leaves = {"/".join(str(getattr(k, "key", k)) for k in kp): v
              for kp, v in
              jax.tree_util.tree_leaves_with_path(cache)}
    k_leaves = [v for p, v in leaves.items() if p.endswith("/k")]
    s_leaves = [v for p, v in leaves.items() if p.endswith("/k_scale")]
    assert k_leaves and all(v.dtype == jnp.int8 for v in k_leaves)
    assert s_leaves and all(v.dtype == jnp.float32 for v in s_leaves)
    # per-layer KV bytes: int8 + scales < half of the f32 cache
    f32 = trained.make_decode_engine(max_slots=4, max_new_tokens=4)
    def nbytes(c):
        return sum(int(np.prod(v.shape)) * v.dtype.itemsize
                   for v in jax.tree_util.tree_leaves(c))
    assert nbytes(cache) < 0.5 * nbytes(f32.engine._cache)


def test_kv_int8_engine_matches_its_own_oracle(trained):  # noqa: F811
    """The engine and greedy_generate run the SAME int8-cache module,
    so serving must be token-identical to the oracle — exactness within
    the quantized world."""
    m8 = LlamaLoRA(**{**KNOBS, "kv_cache_int8": True})
    m8.load_parameters(trained.dump_parameters())
    module = m8._module()
    assert module.kv_int8
    prompts = [np.asarray([1, 5, 9, 13], np.int32),
               np.asarray([2, 7], np.int32)]
    width = max(len(p) for p in prompts)
    ids = np.zeros((2, width), np.int32)
    lens = np.zeros((2,), np.int32)
    for i, p in enumerate(prompts):
        ids[i, :len(p)] = p
        lens[i] = len(p)
    ref = np.asarray(greedy_generate(module, m8._params, ids, lens, 6))

    eng = m8.make_decode_engine(max_slots=2, max_new_tokens=6,
                                steps_per_sync=2, prefill_chunk=4)
    for i, p in enumerate(prompts):
        eng.engine.submit(("r", i), p, 6)
    got = {}
    for _ in range(300):
        if not eng.busy:
            break
        eng.engine.step()
        for rid, toks in eng.engine.poll():
            got[rid] = toks
    for i in range(2):
        assert got[("r", i)] == [int(t) for t in ref[i]], i


def test_kv_int8_logits_close_to_f32_cache(trained):  # noqa: F811
    """Quantization error is bounded: next-token logits through the
    int8 decode cache stay close to the f32-cache decode path on the
    same weights (same inputs, short context)."""
    m8 = LlamaLoRA(**{**KNOBS, "kv_cache_int8": True})
    m8.load_parameters(trained.dump_parameters())
    mod8 = m8._module()
    mod32 = trained._module()
    params = trained._params

    ids = np.asarray([[3, 1, 4, 1, 5, 9, 2, 6]], np.int32)
    pos = np.arange(8, dtype=np.int32)[None, :]

    def decode_logits(module):
        cache = module.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 1), jnp.int32),
                            decode=True)["cache"]
        out, _ = module.apply({"params": params, "cache": cache},
                              jnp.asarray(ids),
                              positions=jnp.asarray(pos), decode=True,
                              mutable=["cache"])
        return np.asarray(out[:, -1], np.float32)

    l8, l32 = decode_logits(mod8), decode_logits(mod32)
    denom = max(1e-6, float(np.max(np.abs(l32))))
    assert float(np.max(np.abs(l8 - l32))) / denom < 0.05, \
        np.max(np.abs(l8 - l32))


def test_kv_int8_composes_with_prefix_cache(trained):  # noqa: F811
    """Prefix snapshots trim/install per-leaf: the int8 cache's extra
    scale leaves ride the same machinery, and hits stay exact vs the
    no-prefix int8 engine."""
    m8 = LlamaLoRA(**{**KNOBS, "kv_cache_int8": True})
    m8.load_parameters(trained.dump_parameters())
    prefix = np.asarray([3, 1, 4, 1], np.int32)
    prompt = np.concatenate([prefix, np.asarray([5, 9], np.int32)])

    def run(register):
        eng = m8.make_decode_engine(max_slots=2, max_new_tokens=5,
                                    prefill_chunk=2)
        if register:
            assert eng.engine.register_prefix(prefix) == len(prefix)
        eng.engine.submit("r", prompt, 5)
        for _ in range(300):
            if not eng.busy:
                break
            eng.engine.step()
            done = eng.engine.poll()
            if done:
                return done[0][1], eng.engine.stats
        raise AssertionError("no drain")

    plain, _ = run(False)
    hit, stats = run(True)
    assert stats["prefix_hits"] == 1
    assert hit == plain


def test_kv_int8_composes_with_weight_int8_and_speculation(trained):  # noqa: F811
    """The doc-claimed compositions: kv_cache_int8 + quantize_int8
    serve together (int8 weights AND int8 cache), and speculation on
    an int8-cache engine stays exact vs the same engine without it."""
    m = LlamaLoRA(**{**KNOBS, "kv_cache_int8": True,
                     "quantize_int8": True})
    m.load_parameters(trained.dump_parameters())
    module, _ = m._serving_module_params()
    assert module.quantized and module.kv_int8

    def run(spec_k):
        eng = m.make_decode_engine(max_slots=2, max_new_tokens=6,
                                   speculate_k=spec_k)
        eng.engine.submit("r", np.asarray([1, 5, 9, 1, 5], np.int32), 6)
        for _ in range(300):
            if not eng.busy:
                break
            eng.engine.step()
            done = eng.engine.poll()
            if done:
                return done[0][1], dict(eng.engine.stats)
        raise AssertionError("no drain")

    plain, _ = run(0)
    spec, stats = run(4)
    assert spec == plain  # speculation lossless on the int8 engine
    assert stats["spec_calls"] > 0
