import random

import pytest

from rafiki_tpu.model.knob import (BaseKnob, CategoricalKnob, FixedKnob,
                                   FloatKnob, IntegerKnob, PolicyKnob,
                                   knob_config_from_json, knob_config_to_json,
                                   knobs_from_unit_vector,
                                   knobs_to_unit_vector, sample_knobs,
                                   shape_signature, static_signature,
                                   traceable_knobs, tunable_knobs,
                                   validate_knobs, validate_override_keys)


def make_config():
    return {
        "lr": FloatKnob(1e-5, 1e-1, is_exp=True),
        "hidden": IntegerKnob(32, 512, is_exp=True, shape_relevant=True),
        "act": CategoricalKnob(["relu", "gelu", "tanh"]),
        "epochs": FixedKnob(3),
        "early_stop": PolicyKnob("EARLY_STOP"),
    }


def test_sample_and_validate():
    cfg = make_config()
    rng = random.Random(0)
    for _ in range(50):
        knobs = sample_knobs(cfg, rng)
        validate_knobs(cfg, knobs)
        assert 1e-5 <= knobs["lr"] <= 1e-1
        assert 32 <= knobs["hidden"] <= 512
        assert knobs["act"] in ("relu", "gelu", "tanh")
        assert knobs["epochs"] == 3
        assert knobs["early_stop"] is False


def test_validate_rejects():
    cfg = make_config()
    knobs = sample_knobs(cfg, random.Random(1))
    with pytest.raises(ValueError):
        validate_knobs(cfg, {**knobs, "lr": 5.0})
    with pytest.raises(ValueError):
        validate_knobs(cfg, {**knobs, "act": "swish"})
    with pytest.raises(ValueError):
        validate_knobs(cfg, {**knobs, "epochs": 4})
    bad = dict(knobs)
    del bad["lr"]
    with pytest.raises(ValueError):
        validate_knobs(cfg, bad)
    with pytest.raises(ValueError):
        validate_knobs(cfg, {**knobs, "bogus": 1})


def test_json_round_trip():
    cfg = make_config()
    cfg2 = knob_config_from_json(knob_config_to_json(cfg))
    assert cfg == cfg2
    # serialized form must be stable and dispatchable
    for knob in cfg.values():
        assert BaseKnob.from_json(knob.to_json()) == knob


def test_unit_vector_round_trip():
    cfg = make_config()
    names = tunable_knobs(cfg)
    assert names == sorted(["lr", "hidden", "act"])
    knobs = sample_knobs(cfg, random.Random(2))
    vec = knobs_to_unit_vector(cfg, knobs)
    assert len(vec) == 3 and all(0.0 <= u <= 1.0 for u in vec)
    back = knobs_from_unit_vector(cfg, vec)
    validate_knobs(cfg, back)
    assert back["act"] == knobs["act"]
    assert back["hidden"] == knobs["hidden"]
    assert abs(back["lr"] - knobs["lr"]) / knobs["lr"] < 1e-6


def test_log_scale_coverage():
    # log-scaled sampling should hit small values often enough
    knob = FloatKnob(1e-5, 1e-1, is_exp=True)
    rng = random.Random(3)
    vals = [knob.sample(rng) for _ in range(500)]
    assert sum(v < 1e-3 for v in vals) > 100


def test_shape_signature():
    cfg = make_config()
    a = sample_knobs(cfg, random.Random(4))
    b = dict(a, lr=a["lr"] * 0.5)  # same shapes, different lr
    c = dict(a, hidden=a["hidden"] + 1)
    assert shape_signature(cfg, a) == shape_signature(cfg, b)
    assert shape_signature(cfg, a) != shape_signature(cfg, c)


def test_invalid_domains():
    with pytest.raises(ValueError):
        IntegerKnob(10, 5)
    with pytest.raises(ValueError):
        FloatKnob(0.0, 1.0, is_exp=True)
    with pytest.raises(ValueError):
        CategoricalKnob([])


def traced_config():
    return {
        "lr": FloatKnob(1e-5, 1e-1, is_exp=True, traceable=True),
        "dropout": FloatKnob(0.0, 0.5, traceable=True),
        "hidden": IntegerKnob(32, 512, is_exp=True, shape_relevant=True),
        "opt": CategoricalKnob(["adam", "sgd"]),
        "epochs": FixedKnob(3),
        "quick": PolicyKnob("QUICK_TRAIN"),
    }


def test_traceable_trait_and_json_round_trip():
    cfg = traced_config()
    assert traceable_knobs(cfg) == ["dropout", "lr"]
    cfg2 = knob_config_from_json(knob_config_to_json(cfg))
    assert cfg == cfg2
    assert cfg2["lr"].traceable and not cfg2["hidden"].traceable
    # pre-trait wire forms (no "traceable" key) stay loadable
    legacy = {k: {kk: vv for kk, vv in d.items() if kk != "traceable"}
              for k, d in knob_config_to_json(cfg).items()}
    loaded = knob_config_from_json(legacy)
    assert all(not k.traceable for k in loaded.values())


def test_traceable_excludes_shape_relevant():
    with pytest.raises(ValueError, match="shape_relevant and traceable"):
        FloatKnob(0.0, 1.0, shape_relevant=True, traceable=True)


def test_static_signature_buckets():
    cfg = traced_config()
    a = sample_knobs(cfg, random.Random(0))
    # traceable knobs never fork the bucket
    b = dict(a, lr=a["lr"] * 0.1, dropout=0.4)
    # policy knobs are scheduling, not program — BOHB flips them per rung
    c = dict(a, quick=not a["quick"])
    # static knobs (shape or not) do fork it
    d = dict(a, opt="sgd" if a["opt"] == "adam" else "adam")
    e = dict(a, hidden=a["hidden"] + 1)
    assert static_signature(cfg, a) == static_signature(cfg, b)
    assert static_signature(cfg, a) == static_signature(cfg, c)
    assert static_signature(cfg, a) != static_signature(cfg, d)
    assert static_signature(cfg, a) != static_signature(cfg, e)


def test_validate_override_keys_shared_validator():
    cfg = traced_config()
    validate_override_keys(cfg, None)
    validate_override_keys(cfg, {})
    validate_override_keys(cfg, {"lr": 1e-3, "hidden": 64})
    with pytest.raises(ValueError, match="knob_overrides.*learnin_rate"):
        validate_override_keys(cfg, {"learnin_rate": 1e-3})
    with pytest.raises(ValueError, match="job pins.*bogus"):
        validate_override_keys(["lr"], {"bogus": 1}, context="job pins")
