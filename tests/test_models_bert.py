"""BERT family: tokenizer determinism, module shapes, contract, DP, and
padding-mask invariance."""

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from rafiki_tpu.constants import TaskType
from rafiki_tpu.data import generate_text_classification_dataset
from rafiki_tpu.model import TrainContext, test_model_class
from rafiki_tpu.models.bert import (Bert, BertClassifier, HashTokenizer,
                                    PAD_ID)


TINY = {"max_epochs": 8, "vocab_size": 1 << 15, "hidden_dim": 96,
        "depth": 2, "n_heads": 4, "max_len": 32, "learning_rate": 1e-3,
        "weight_decay": 1e-4, "warmup_frac": 0.1, "batch_size": 32,
        "bf16": False, "quick_train": False, "share_params": False}


def test_tokenizer_deterministic_and_padded():
    tok = HashTokenizer(1024)
    ids1, n1 = tok.encode("Hello, World! hello", max_len=8)
    ids2, n2 = tok.encode("hello world hello", max_len=8)
    assert ids1 == ids2 and n1 == n2 == 4  # CLS + 3 tokens
    assert ids1[4:] == [PAD_ID] * 4
    # same token → same id; different tokens overwhelmingly differ
    assert ids1[1] == ids1[3] and ids1[1] != ids1[2]


def test_bert_module_shapes():
    m = Bert(vocab_size=512, max_len=16, hidden_dim=32, depth=2, n_heads=4,
             mlp_dim=64, n_classes=5)
    ids = np.zeros((3, 16), np.int32)
    lens = np.asarray([16, 4, 1], np.int32)
    params = m.init(jax.random.PRNGKey(0), ids, lens)["params"]
    out = m.apply({"params": params}, ids, lens)
    assert out.shape == (3, 5)


def test_bert_padding_invariance():
    """Logits must not depend on what sits in the padded tail."""
    m = Bert(vocab_size=512, max_len=16, hidden_dim=32, depth=2, n_heads=4,
             mlp_dim=64, n_classes=3)
    rng = np.random.default_rng(0)
    ids_a = rng.integers(2, 512, size=(2, 16)).astype(np.int32)
    lens = np.asarray([5, 9], np.int32)
    ids_b = ids_a.copy()
    ids_b[0, 5:] = 7  # rewrite pad region with garbage
    ids_b[1, 9:] = 3
    params = m.init(jax.random.PRNGKey(0), ids_a, lens)["params"]
    out_a = m.apply({"params": params}, ids_a, lens)
    out_b = m.apply({"params": params}, ids_b, lens)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.slow
def test_bert_template_contract(tmp_path):
    tr, va = str(tmp_path / "t.jsonl"), str(tmp_path / "v.jsonl")
    generate_text_classification_dataset(tr, 256, seed=0)
    generate_text_classification_dataset(va, 64, seed=1)
    preds = test_model_class(BertClassifier, TaskType.TEXT_CLASSIFICATION,
                             tr, va, queries=["tok1 tok2 tok3"], knobs=TINY)
    assert len(preds) == 1 and len(preds[0]) == 4


@pytest.mark.slow
def test_bert_trains_data_parallel(tmp_path):
    tr = str(tmp_path / "t.jsonl")
    va = str(tmp_path / "v.jsonl")
    generate_text_classification_dataset(tr, 256, seed=0)
    generate_text_classification_dataset(va, 64, seed=1)
    model = BertClassifier(**TINY)
    ctx = TrainContext(devices=list(jax.devices()))
    model.train(tr, ctx)
    losses = ctx.logger.get_values("loss")
    assert len(losses) >= 2 and losses[-1] < losses[0]
    # synthetic unigram-mixture text is nearly separable: a trained
    # encoder must beat chance (0.25) clearly
    assert model.evaluate(va) > 0.5
