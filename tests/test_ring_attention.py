"""Ring attention (sequence parallelism) on the 8-device virtual mesh.

Exactness vs the dense oracle (fwd + grads, causal and full), ring-size
sweep, and dtype behavior. This is the long-context leg: the sequence
axis is sharded over the mesh and K/V blocks rotate via ppermute.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from rafiki_tpu.ops.attention import _attention_reference
from rafiki_tpu.ops.ring_attention import ring_attention


def _rand(*shape, key=0, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype)


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("sp",))


@pytest.mark.parametrize("n_ring,causal", [(1, False), (4, False),
                                           (4, True), (8, True)])
def test_ring_matches_dense(n_ring, causal):
    s = 64  # global sequence, divides every ring size
    q = _rand(2, 2, s, 16, key=0)
    k = _rand(2, 2, s, 16, key=1)
    v = _rand(2, 2, s, 16, key=2)
    mesh = _mesh(n_ring)
    out = ring_attention(q, k, v, mesh, "sp", causal=causal)
    ref = _attention_reference(q, k, v, 1.0 / np.sqrt(16), causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # output stays sequence-sharded — no all-gather of the result
    assert tuple(out.sharding.spec) == (None, None, "sp", None)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_grads_match_dense(causal):
    # ring size 2: the VJP's reverse ring is fully exercised at any ring
    # size, and the unrolled shard_map backward is expensive to compile
    # on CPU (~60s at ring 4); both mask branches get grad coverage
    s = 32
    q = _rand(1, 2, s, 8, key=3)
    k = _rand(1, 2, s, 8, key=4)
    v = _rand(1, 2, s, 8, key=5)
    mesh = _mesh(2)

    def f(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, "sp",
                                      causal=causal) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_attention_reference(
            q, k, v, 1.0 / np.sqrt(8), causal) ** 2)

    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


def test_ring_bf16_long_sequence_under_jit():
    """bf16 in/out, longer-than-one-shard sequence, jitted end-to-end."""
    s = 256
    q = _rand(1, 2, s, 32, key=6, dtype=jnp.bfloat16)
    k = _rand(1, 2, s, 32, key=7, dtype=jnp.bfloat16)
    v = _rand(1, 2, s, 32, key=8, dtype=jnp.bfloat16)
    mesh = _mesh(8)

    @jax.jit
    def run(q, k, v):
        return ring_attention(q, k, v, mesh, "sp", causal=True)

    out = run(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = _attention_reference(q.astype(jnp.float32),
                               k.astype(jnp.float32),
                               v.astype(jnp.float32),
                               1.0 / np.sqrt(32), True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), atol=3e-2, rtol=3e-2)


def test_ring_2d_mesh_dp_times_sp():
    """dp × sp: batch sharded over 'data', sequence over 'sp' — the
    2-D long-context layout. Output keeps both shardings."""
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("data", "sp"))
    q = _rand(4, 2, 64, 16, key=10)
    k = _rand(4, 2, 64, 16, key=11)
    v = _rand(4, 2, 64, 16, key=12)
    out = ring_attention(q, k, v, mesh, "sp", causal=True,
                         batch_axis="data")
    ref = _attention_reference(q, k, v, 1.0 / np.sqrt(16), True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    assert tuple(out.sharding.spec) == ("data", None, "sp", None)
