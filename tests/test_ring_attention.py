"""Ring attention (sequence parallelism) on the 8-device virtual mesh.

Exactness vs the dense oracle (fwd + grads, causal and full), ring-size
sweep, and dtype behavior. This is the long-context leg: the sequence
axis is sharded over the mesh and K/V blocks rotate via ppermute.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from rafiki_tpu.ops.attention import _attention_reference
from rafiki_tpu.ops.ring_attention import ring_attention


def _rand(*shape, key=0, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype)


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("sp",))


@pytest.mark.parametrize("n_ring,causal", [
    (1, False),  # the quick default-suite exactness check
    pytest.param(4, False, marks=pytest.mark.slow),
    pytest.param(4, True, marks=pytest.mark.slow),
    pytest.param(8, True, marks=pytest.mark.slow)])
def test_ring_matches_dense(n_ring, causal):
    s = 64  # global sequence, divides every ring size
    q = _rand(2, 2, s, 16, key=0)
    k = _rand(2, 2, s, 16, key=1)
    v = _rand(2, 2, s, 16, key=2)
    mesh = _mesh(n_ring)
    out = ring_attention(q, k, v, mesh, "sp", causal=causal)
    ref = _attention_reference(q, k, v, 1.0 / np.sqrt(16), causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # output stays sequence-sharded — no all-gather of the result
    spec = tuple(out.sharding.spec)  # older jax trims trailing None
    assert "sp" in spec  # a replicated (all-gathered) result fails
    assert spec == (None, None, "sp", None)[:len(spec)]


@pytest.mark.slow
@pytest.mark.parametrize("causal", [False, True])
def test_ring_grads_match_dense(causal):
    # ring size 2: the VJP's reverse ring is fully exercised at any ring
    # size, and the unrolled shard_map backward is expensive to compile
    # on CPU (~60s at ring 4); both mask branches get grad coverage
    s = 32
    q = _rand(1, 2, s, 8, key=3)
    k = _rand(1, 2, s, 8, key=4)
    v = _rand(1, 2, s, 8, key=5)
    mesh = _mesh(2)

    def f(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, "sp",
                                      causal=causal) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_attention_reference(
            q, k, v, 1.0 / np.sqrt(8), causal) ** 2)

    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


def test_ring_bf16_long_sequence_under_jit():
    """bf16 in/out, longer-than-one-shard sequence, jitted end-to-end."""
    s = 256
    q = _rand(1, 2, s, 32, key=6, dtype=jnp.bfloat16)
    k = _rand(1, 2, s, 32, key=7, dtype=jnp.bfloat16)
    v = _rand(1, 2, s, 32, key=8, dtype=jnp.bfloat16)
    mesh = _mesh(8)

    @jax.jit
    def run(q, k, v):
        return ring_attention(q, k, v, mesh, "sp", causal=True)

    out = run(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = _attention_reference(q.astype(jnp.float32),
                               k.astype(jnp.float32),
                               v.astype(jnp.float32),
                               1.0 / np.sqrt(32), True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), atol=3e-2, rtol=3e-2)


@pytest.mark.slow
def test_ring_2d_mesh_dp_times_sp():
    """dp × sp: batch sharded over 'data', sequence over 'sp' — the
    2-D long-context layout. Output keeps both shardings."""
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("data", "sp"))
    q = _rand(4, 2, 64, 16, key=10)
    k = _rand(4, 2, 64, 16, key=11)
    v = _rand(4, 2, 64, 16, key=12)
    out = ring_attention(q, k, v, mesh, "sp", causal=True,
                         batch_axis="data")
    ref = _attention_reference(q, k, v, 1.0 / np.sqrt(16), True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    spec = tuple(out.sharding.spec)  # older jax trims trailing None
    assert "sp" in spec and "data" in spec  # gathered result fails
    assert spec == ("data", None, "sp", None)[:len(spec)]


def test_ring_local_block_is_streamed_not_materialized():
    """VERDICT r3 weak #4: the per-step local block must run through the
    flash kernel — no (L/P, L/P) f32 score matrix may appear anywhere in
    the traced program (kernel-internal tiles are (block_q, block_k))."""
    P, sq_local = 4, 512  # global L = 2048; blocks are 128
    s = P * sq_local
    q = _rand(1, 1, s, 16, key=0)
    mesh = _mesh(P)

    def f(q):
        return ring_attention(q, q, q, mesh, "sp", causal=True,
                              interpret=True)  # force the Pallas path

    jaxpr = str(jax.make_jaxpr(f)(q))
    assert f"{sq_local},{sq_local}" not in jaxpr, \
        "ring step materializes an (L/P)^2 score block"
    # the kernel's streamed tiles ARE there (the Pallas path was taken)
    assert "pallas_call" in jaxpr
    # ... and so is the backward ring (custom VJP, reverse rotation)
    gjaxpr = str(jax.make_jaxpr(
        jax.grad(lambda q: jnp.sum(f(q).astype(jnp.float32) ** 2)))(q))
    assert f"{sq_local},{sq_local}" not in gjaxpr, \
        "ring backward materializes an (L/P)^2 score block"


@pytest.mark.slow
def test_ring_backward_residuals_are_o_seq_over_p():
    """The training backward must NOT retain the rotated K/V of every
    ring step (P copies = the whole global K/V per device — the naive
    autodiff of the unrolled forward). The custom VJP saves exactly
    q/k/v/out + one lse row array."""
    P = 4
    q = _rand(1, 2, 512, 16, key=0)
    k = _rand(1, 2, 512, 16, key=1)
    v = _rand(1, 2, 512, 16, key=2)
    mesh = _mesh(P)
    out, vjp_fn = jax.vjp(
        lambda q, k, v: ring_attention(q, k, v, mesh, "sp", causal=True),
        q, k, v)
    res_bytes = sum(x.nbytes for x in jax.tree_util.tree_leaves(vjp_fn))
    # residuals: q, k, v, out (4 × q.nbytes f32) + lse (s rows) + slack;
    # the naive unrolled-forward autodiff retains ≳ 2P extra shard sets
    assert res_bytes <= 6 * q.nbytes, \
        f"{res_bytes} residual bytes vs {q.nbytes} per tensor — " \
        "backward is retaining per-step K/V copies"


@pytest.mark.slow
def test_flash_attention_lse_matches_xla_twin():
    """flash_attention_lse through the Pallas interpreter == XLA twin,
    for out, lse, AND gradients through a loss that consumes BOTH (the
    lse cotangent exercises the delta' = delta - g_lse backward fold)."""
    from rafiki_tpu.ops.attention import (_attention_reference_lse,
                                          flash_attention_lse)

    q = _rand(2, 2, 96, 16, key=3)
    k = _rand(2, 2, 96, 16, key=4)
    v = _rand(2, 2, 96, 16, key=5)
    scale = 1.0 / np.sqrt(16)

    for causal in (False, True):
        out_k, lse_k = flash_attention_lse(q, k, v, scale, causal,
                                           interpret=True)
        out_r, lse_r = _attention_reference_lse(q, k, v, scale, causal)
        np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_r),
                                   atol=2e-5, rtol=2e-5)
        np.testing.assert_allclose(np.asarray(lse_k), np.asarray(lse_r),
                                   atol=2e-5, rtol=2e-5)

        def loss(fn, interpret):
            def go(q, k, v):
                o, lse = fn(q, k, v, scale, causal, 128, 128, interpret) \
                    if interpret is not None else fn(q, k, v, scale, causal)
                # weight the two outputs asymmetrically so a wrong
                # lse-grad cannot cancel against the out-grad
                return (jnp.sum(o.astype(jnp.float32) ** 2)
                        + 0.7 * jnp.sum(jnp.sin(lse)))
            return go

        gk = jax.grad(loss(flash_attention_lse, True), argnums=(0, 1, 2))(
            q, k, v)
        gr = jax.grad(loss(_attention_reference_lse, None),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gk, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=3e-5, rtol=3e-5)


def test_ring_gqa_fwd_matches_dense():
    """Default-leg GQA ring exactness WITHOUT the grad compile (the
    reverse-ring VJP costs ~25s of CPU compile; its oracle rides the
    slow leg below plus the sp×tp head_axis test): only the small K/V
    rotate, forward equals the dense oracle over repeated K/V."""
    s, h, h_kv = 32, 4, 2
    rep = h // h_kv
    q = _rand(1, h, s, 8, key=10)
    k = _rand(1, h_kv, s, 8, key=11)
    v = _rand(1, h_kv, s, 8, key=12)
    mesh = _mesh(2)
    np.testing.assert_allclose(
        np.asarray(ring_attention(q, k, v, mesh, "sp", causal=False)),
        np.asarray(_attention_reference(
            q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1),
            1.0 / np.sqrt(8), False)), atol=2e-5, rtol=2e-5)
    with pytest.raises(ValueError, match="multiple"):
        ring_attention(q, _rand(1, 3, s, 8, key=13), v, mesh, "sp")


@pytest.mark.slow
@pytest.mark.parametrize("causal", [False, True])
def test_ring_gqa_matches_dense(causal):
    """GQA ring (kv_heads < heads): only the small K/V rotate; forward
    AND grads must equal the dense oracle over jnp.repeat'ed K/V —
    including dK/dV group-reduced back to the kv heads."""
    s, h, h_kv = 32, 4, 2
    rep = h // h_kv
    q = _rand(1, h, s, 8, key=10)
    k = _rand(1, h_kv, s, 8, key=11)
    v = _rand(1, h_kv, s, 8, key=12)
    mesh = _mesh(2)

    def f(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, "sp",
                                      causal=causal) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_attention_reference(
            q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1),
            1.0 / np.sqrt(8), causal) ** 2)

    np.testing.assert_allclose(
        np.asarray(ring_attention(q, k, v, mesh, "sp", causal=causal)),
        np.asarray(_attention_reference(
            q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1),
            1.0 / np.sqrt(8), causal)), atol=2e-5, rtol=2e-5)
    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)
    with pytest.raises(ValueError, match="multiple"):
        ring_attention(q, _rand(1, 3, s, 8, key=13), v, mesh, "sp")
