"""SLO classes, preemption, shedding, brownout — ISSUE 12 tentpole.

Four layers, mirroring the plumbing: (1) the policy objects
(``ClassQueue`` aging, ``BrownoutController`` hysteresis) in pure
Python; (2) the REAL decode engine's class-aware admission +
page/slot-backed preemption, token-EXACT against an uncontended
reference per decode mode (greedy, sampled, int8-KV, multi-adapter,
speculative — sampling is a pure function of (seed, position), so a
preempted request that re-ingests its generated prefix and continues
at the same absolute positions must reproduce the uncontended output
bit for bit); (3) the worker's structured ``expired`` rejection and
the predictor's shed gate / brownout ladder / typed 503s; (4) the
mixed-traffic acceptance drill on the deterministic capacity-model
harness (``rafiki_tpu.chaos.sloload``).
"""

import threading
import time

import numpy as np
import pytest

from rafiki_tpu.chaos.scaleout import StubLM
from rafiki_tpu.chaos.sloload import SloLoadHarness
from rafiki_tpu.client.client import Client
from rafiki_tpu.models.llama_lora import LlamaLoRA, stack_lora_adapters
from rafiki_tpu.serving.decode_engine import DecodeEngine
from rafiki_tpu.serving.predictor import Predictor, PredictorService
from rafiki_tpu.serving.queues import InProcQueueHub, pack_message, \
    unpack_message
from rafiki_tpu.serving.slo import (BrownoutController, ClassQueue,
                                    normalize_slo)
from rafiki_tpu.store.param_store import ParamStore
from rafiki_tpu.utils.http import HttpStatusError
from rafiki_tpu.worker.inference import InferenceWorker

from test_decode_engine import KNOBS  # noqa: F401 — shared tiny LM
from test_multi_adapter import _lora_variant  # noqa: F401

L = int(KNOBS["max_len"])
PS = 8


# ---- policy objects (no jax) ----

def test_normalize_slo():
    assert normalize_slo(None) == "interactive"
    assert normalize_slo("") == "interactive"
    assert normalize_slo("  Batch ") == "batch"
    assert normalize_slo(None, default="background") == "background"
    with pytest.raises(ValueError, match="unknown SLO class"):
        normalize_slo("turbo")


def test_class_queue_priority_and_fifo():
    q = ClassQueue()
    q.push("background", "b0")
    q.push("interactive", "i0")
    q.push("batch", "t0")
    q.push("interactive", "i1")
    order = [q.pop()[1] for _ in range(4)]
    # interactive first (FIFO within class), then batch, then background
    assert order == ["i0", "i1", "t0", "b0"]
    assert q.pop() is None
    # front-requeue (a preempted victim) outranks its class peers
    q.push("batch", "t1")
    q.push("batch", "t2", front=True)
    assert q.pop() == ("batch", "t2")


def test_class_queue_aging_promotes_within_bound():
    q = ClassQueue(aging_skips=3)
    q.push("background", "bg")
    served = []
    for i in range(10):
        q.push("interactive", f"i{i}")
        served.append(q.pop()[1])
        if "bg" in served:
            break
    # background was skipped at most aging_skips times, then promoted
    assert "bg" in served
    assert served.index("bg") <= 3
    assert q.promotions == 1


def test_class_queue_promotion_flag_marks_the_promoting_pop():
    q = ClassQueue(aging_skips=2)
    q.push("background", "bg")
    flags = []
    for i in range(5):
        q.push("interactive", f"i{i}")
        item = q.pop()[1]
        flags.append((item, q.last_pop_promoted))
        if item == "bg":
            break
    assert ("bg", True) in flags  # the aged pop is flagged (shielding)
    assert all(not f for it, f in flags if it != "bg")


def test_brownout_ladder_hysteresis():
    b = BrownoutController(target_p95_s=1.0, enter_ratio=1.5,
                           exit_ratio=1.1, dwell=2)
    assert b.enabled and b.stage == 0
    # two consecutive hot observations per escalation
    for expect in (0, 1, 1, 2, 2, 3, 3, 3):
        assert b.observe(2.0) == expect
    assert b.stage == 3 and b.escalations == 3
    # the sticky band (between exit and enter ratios) resets streaks
    b.observe(1.3)   # in the band: neither hot nor cool advances
    b.observe(0.5)   # cooling streak restarts AFTER the band
    assert b.stage == 3
    for expect in (2, 1, 0, 0):
        b.observe(0.5)
        b.observe(0.5)
        assert b.stage == expect
    assert b.stage == 0 and b.deescalations == 3
    # stage semantics: caps halve at >=1, background pauses at 3
    b.stage = 1
    assert b.shed_cap("interactive", 100) == -1
    assert b.shed_cap("batch", 100) == 50
    # the ladder may only TIGHTEN: an operator cap of 0 (shed on any
    # backlog) or 1 must not be RAISED by the stage-1 halving floor
    assert b.shed_cap("background", 0) == 0
    assert b.shed_cap("batch", 1) == 1
    b.stage = 3
    assert b.shed_cap("background", 100) == 0
    # stage-2 clamp applies to background only
    b.stage = 2
    assert b.clamp_max_new("background", 64, 8) == 8
    assert b.clamp_max_new("background", None, 8) == 8
    assert b.clamp_max_new("batch", 64, 8) == 64
    # disabled ladder never moves
    off = BrownoutController(target_p95_s=0.0)
    for _ in range(10):
        off.observe(99.0)
    assert off.stage == 0 and not off.enabled


# ---- real-engine preemption: token-exact per decode mode ----

BG_PROMPT = np.asarray([1, 5, 9, 13, 6], np.int32)
IA_PROMPT = np.asarray([2, 4], np.int32)
BG_NEW, IA_NEW = 10, 4

MODES = ("greedy", "sampled", "int8", "multi_adapter", "speculative")


def _mode_setup(trained, mode):
    """(model-with-params, module_kw, engine_kw, submit_kw, params)."""
    module_kw, engine_kw, submit_kw = {}, {}, {}
    model, params = trained, trained._params
    if mode == "int8":
        model = LlamaLoRA(**{**KNOBS, "kv_cache_int8": True})
        model._params = params
    elif mode == "multi_adapter":
        params = stack_lora_adapters(
            [trained._params, _lora_variant(trained._params)])
        module_kw = {"n_adapters": 2}
        submit_kw = {"adapter_id": 1}
    elif mode == "speculative":
        engine_kw = {"speculate_k": 4}
    elif mode == "sampled":
        submit_kw = {"temperature": 0.8, "top_k": 8, "top_p": 0.9,
                     "seed": 13}
    return model, module_kw, engine_kw, submit_kw, params


def _drain(eng, want, budget=800):
    done = {}
    for _ in range(budget):
        eng.step()
        done.update(dict(eng.poll()))
        if len(done) == want:
            return done
    raise AssertionError(f"undrained: {sorted(done)} / {dict(eng.stats)}")


@pytest.mark.parametrize("mode", MODES)
def test_preempt_resume_token_exact(trained, mode):
    """Slot preemption: 1 slot, background mid-generation, interactive
    arrives → background evicted, interactive served, background
    resumes — BOTH outputs identical to an uncontended run."""
    model, module_kw, engine_kw, submit_kw, params = _mode_setup(
        trained, mode)
    # uncontended reference: 2 slots, nothing preempts
    ref_eng = DecodeEngine(model._module(**module_kw), params,
                           max_slots=2, max_len=L, **engine_kw)
    ref_eng.submit("bg", BG_PROMPT, BG_NEW, slo="background",
                   **submit_kw)
    ref_eng.submit("ia", IA_PROMPT, IA_NEW, slo="interactive",
                   **submit_kw)
    ref = _drain(ref_eng, 2)

    eng = DecodeEngine(model._module(**module_kw), params,
                       max_slots=1, max_len=L, **engine_kw)
    eng.submit("bg", BG_PROMPT, BG_NEW, slo="background", **submit_kw)
    eng.step()
    eng.step()  # background is mid-generation in the only slot
    streamed = {}
    eng.submit("ia", IA_PROMPT, IA_NEW, slo="interactive", **submit_kw)
    done = {}
    for _ in range(800):
        eng.step()
        for rid, toks in eng.poll_partial():
            prev = streamed.get(rid, [])
            # streaming is append-only across the preemption: each
            # cumulative snapshot extends the previous one
            assert toks[:len(prev)] == prev, (rid, prev, toks)
            streamed[rid] = toks
        done.update(dict(eng.poll()))
        if len(done) == 2:
            break
    assert eng.stats["preemptions"] >= 1
    assert done == ref, f"{mode}: preempt-resume diverged"
    for rid, toks in streamed.items():
        assert done[rid][:len(toks)] == toks  # no dup/loss on stream


def test_paged_page_preemption_token_exact(trained):
    """Page preemption: two slots but a pool too small for both — the
    interactive head reclaims the background's RESERVED pages (they
    free instantly under paged KV), background resumes token-exact,
    and the pool drains back to empty."""
    module = trained._module(kv_page_size=PS, kv_pages=5)  # 4 usable
    ref_mod = trained._module(kv_page_size=PS, kv_pages=13)
    ref_eng = DecodeEngine(ref_mod, trained._params, max_slots=2,
                           max_len=L)
    ref_eng.submit("bg", BG_PROMPT, 16, slo="background")
    ref_eng.submit("ia", IA_PROMPT, 8, slo="interactive")
    ref = _drain(ref_eng, 2)

    eng = DecodeEngine(module, trained._params, max_slots=2, max_len=L)
    eng.submit("bg", BG_PROMPT, 16, slo="background")  # reserves 3/4
    eng.step()
    eng.step()
    eng.submit("ia", IA_PROMPT, 8, slo="interactive")  # needs 2 more
    done = _drain(eng, 2)
    assert eng.stats["preemptions"] >= 1
    assert done == ref
    assert eng.stats["kv_pages_used"] == 0
    assert len(eng._free_pages) == 4


def test_infeasible_preemption_evicts_nothing(trained):
    """When even evicting EVERY lower-class occupant could not free
    enough pages for the head, the engine stalls WITHOUT evicting —
    destroying a victim's progress while the head still cannot admit
    would be pure loss. Here interactive A (3 pages) + background B
    (2 pages) fill a 5-page pool; interactive C needs 3: B's 2
    reclaimable pages are insufficient, so B keeps running and C
    waits for A's completion."""
    ref_eng = DecodeEngine(trained._module(kv_page_size=PS,
                                           kv_pages=13),
                           trained._params, max_slots=3, max_len=L)
    ref_eng.submit("a", BG_PROMPT, 16, slo="interactive")
    ref_eng.submit("b", IA_PROMPT, 12, slo="background")
    ref_eng.submit("c", BG_PROMPT, 16, slo="interactive")
    ref = _drain(ref_eng, 3)

    eng = DecodeEngine(trained._module(kv_page_size=PS, kv_pages=6),
                       trained._params, max_slots=3, max_len=L)
    eng.submit("a", BG_PROMPT, 16, slo="interactive")   # 3 pages
    eng.submit("b", IA_PROMPT, 12, slo="background")    # 2 pages
    eng.step()
    assert int(eng._n_res.sum()) == 5  # pool exactly full
    eng.submit("c", BG_PROMPT, 16, slo="interactive")   # needs 3
    eng.step()
    eng.step()
    # B was NOT sacrificed for an admission that couldn't happen
    assert eng.stats["preemptions"] == 0
    assert eng.stats["admission_stalls"] >= 1
    done = _drain(eng, 3)
    assert eng.stats["preemptions"] == 0
    assert done == ref


def test_engine_aging_promotes_and_shields(trained):
    """Sustained interactive pressure on one slot: background still
    completes (aging promotes it) and, once promoted, it is shielded
    from the next interactive arrival's preemption."""
    eng = DecodeEngine(trained._module(), trained._params,
                       max_slots=1, max_len=L)
    eng._cq = ClassQueue(aging_skips=2)  # drill-speed aging
    ref_eng = DecodeEngine(trained._module(), trained._params,
                           max_slots=2, max_len=L)
    ref_eng.submit("bg", BG_PROMPT, 6, slo="background")
    ref = _drain(ref_eng, 1)

    eng.submit("bg", BG_PROMPT, 6, slo="background")
    done = {}
    for i in range(40):
        if i < 8:  # a fresh interactive arrival every step
            eng.submit(f"i{i}", IA_PROMPT, 2, slo="interactive")
        eng.step()
        done.update(dict(eng.poll()))
        if "bg" in done and len(done) == 9:
            break
    for _ in range(200):
        if len(done) == 9:
            break
        eng.step()
        done.update(dict(eng.poll()))
    assert "bg" in done, f"background starved: {sorted(done)}"
    assert done["bg"] == ref["bg"]
    assert eng.stats["slo_aged_promotions"] >= 1
    assert len(done) == 9  # every interactive answered too


def test_engine_interactive_admits_first(trained):
    """Class order beats arrival order: background submitted first,
    interactive still takes the only slot."""
    eng = DecodeEngine(trained._module(), trained._params,
                       max_slots=1, max_len=L)
    eng.submit("bg", BG_PROMPT, 6, slo="background")
    eng.submit("ia", IA_PROMPT, 8, slo="interactive")
    eng.step()  # one fused step: interactive seated, still mid-flight
    assert eng._slots[0] is not None and eng._slots[0].slo == \
        "interactive"
    assert eng.stats["queued_background"] == 1
    _drain(eng, 2)


def test_engine_rejects_unknown_slo(trained):
    eng = DecodeEngine(trained._module(), trained._params,
                       max_slots=1, max_len=L)
    with pytest.raises(ValueError, match="unknown SLO class"):
        eng.submit("x", IA_PROMPT, 2, slo="turbo")


# ---- worker: structured expired rejection ----

def _stub_worker(hub, wid="wx"):
    store = ParamStore.from_uri("mem://")
    store.save("stub", {})
    return InferenceWorker(StubLM, "stub", {}, store, hub, wid,
                           decode_loop=True, max_slots=2,
                           max_new_tokens=4)


def test_worker_expired_structured_rejection():
    hub = InProcQueueHub()
    w = _stub_worker(hub)
    hub.push_query("wx", pack_message(
        {"id": "q1", "queries": ["hello"],
         "deadline_ts": time.time() - 30.0, "trace_id": ""}))
    w.run(poll_timeout=0.02, max_iterations=5)
    reply = hub.pop_prediction("q1", 2.0)
    assert reply is not None, "expired query was silently dropped"
    m = unpack_message(reply)
    assert m["expired"] is True and m["predictions"] == []
    assert "expired" in m["error"]
    assert w.stats["dropped_expired"] == 1


def test_worker_published_p95_is_windowed_not_lifetime():
    """The published per-class p95 gauges must RECOVER once an
    overload ends: a window of recent samples, not the cumulative
    histogram quantile (which an ended 10-minute overload would
    pollute for hours, pinning the brownout ladder high)."""
    hub = InProcQueueHub()
    w = _stub_worker(hub, "wp")
    now = time.monotonic()
    # simulate an overload: 300 slow interactive first-tokens ...
    for i in range(300):
        w._req_obs[("m", i)] = ("", now - 5.0, "interactive")
        w._engine_span("first_token", ("m", i), {})
    # ... then recovery: a full window of fast ones
    for i in range(300, 600):
        w._req_obs[("m", i)] = ("", now - 0.01, "interactive")
        w._engine_span("first_token", ("m", i), {})
    w._publish_stats()
    pub = hub.get_worker_stats("wp")
    assert pub["slo_interactive_ttft_p95_s"] < 1.0, (
        "published p95 still reads the ended overload")
    # the cumulative labeled histogram (the /metrics view) still
    # remembers the overload — only the published gauge is windowed
    assert w._h_ttft_slo["interactive"].quantile(0.95) > 1.0


def test_worker_published_p95_ages_out_when_idle():
    """With interactive traffic STOPPED, the window drains by TIME
    (not only by displacement): an idle fleet must read as recovered
    (p95 0.0 → ladder cooling), not as its last overload forever."""
    from rafiki_tpu.worker.inference import SLO_WINDOW_MAX_AGE_S

    hub = InProcQueueHub()
    w = _stub_worker(hub, "wi")
    old = time.monotonic() - SLO_WINDOW_MAX_AGE_S - 5.0
    for i in range(50):  # overload-era samples, then silence
        w._slo_ttft_win["interactive"].append((old, 5.0))
    w._publish_stats()
    pub = hub.get_worker_stats("wi")
    assert pub["slo_interactive_ttft_p95_s"] == 0.0


def test_brownout_ignores_stale_worker_p95():
    """A dead worker's last-published hot p95 must not pin the
    ladder: the staleness verdict the load refresh already computes
    gates the ladder feed."""
    hub = InProcQueueHub()
    pred = Predictor(hub, ["w0"], brownout_target_p95_s=0.1)
    pred.LOAD_REFRESH_EVERY_S = 0.0
    hub.put_worker_stats("w0", {
        "uptime_s": 1.0, "stale_after_s": 0.01,
        "slo_interactive_ttft_p95_s": 5.0})  # 50x over target
    pred._refresh_load_signals()  # first sight: baseline, not stale
    time.sleep(0.05)  # uptime never advances -> stale
    for _ in range(5):
        pred._refresh_load_signals()
    # stale feeds read as no-signal (cooling), so the ladder held
    assert pred.brownout.stage == 0


def test_predictor_gather_treats_expired_as_skipped_vote():
    """An expired rejection reaches the gather as a fast skipped vote:
    the request fails fast (the worker IS responsive), not after the
    whole gather budget."""
    hub = InProcQueueHub()
    pred = Predictor(hub, ["wa"], gather_timeout=8.0)

    def fake_worker():
        raw = hub.pop_query("wa", 5.0)
        m = unpack_message(raw)
        hub.push_prediction(m["id"], pack_message(
            {"id": m["id"], "worker_id": "wa", "predictions": [],
             "expired": True, "error": "query expired in transit"}))

    th = threading.Thread(target=fake_worker, daemon=True)
    th.start()
    t0 = time.monotonic()
    preds, info = pred.predict(["x"], timeout=8.0)
    assert preds == [] and info["workers_answered"] == 0
    assert any("expired" in e for e in info["errors"])
    assert time.monotonic() - t0 < 4.0  # far under the gather budget
    th.join(timeout=5)


def test_stream_fails_over_on_expired_rejection():
    """A stream whose worker expired-rejects fails over IMMEDIATELY to
    the next replica instead of waiting out the silence window."""
    hub = InProcQueueHub()
    pred = Predictor(hub, ["wa", "wb"], stream_silence_timeout_s=20.0)
    # whichever worker the router picks first expired-rejects; the
    # failover target then serves normally
    first = {"wid": None}

    def worker_role(wid):
        raw = hub.pop_query(wid, 10.0)
        if raw is None:
            return
        m = unpack_message(raw)
        with lock:
            am_first = first["wid"] is None
            if am_first:
                first["wid"] = wid
        if am_first:
            hub.push_prediction(m["id"], pack_message(
                {"id": m["id"], "worker_id": wid, "predictions": [],
                 "expired": True, "error": "query expired"}))
        else:
            hub.push_prediction(m["id"], pack_message(
                {"id": m["id"], "worker_id": wid,
                 "delta": {"0": "hello"}}))
            hub.push_prediction(m["id"], pack_message(
                {"id": m["id"], "worker_id": wid,
                 "predictions": ["hello"]}))

    lock = threading.Lock()
    threads = [threading.Thread(target=worker_role, args=(w,),
                                daemon=True) for w in ("wa", "wb")]
    for th in threads:
        th.start()
    t0 = time.monotonic()
    events = list(pred.predict_stream(["hi"], timeout=15.0))
    dt = time.monotonic() - t0
    final = events[-1]
    assert final.get("predictions") == ["hello"], events
    assert final["info"]["failovers"] == 1
    assert dt < 10.0  # did NOT wait the 20s silence window
    for th in threads:
        th.join(timeout=5)


# ---- predictor: shed gate, brownout, typed 503s ----

def _loaded_predictor(backlog_cls="background", backlog=50):
    hub = InProcQueueHub()
    pred = Predictor(hub, ["w0"])
    pred.LOAD_REFRESH_EVERY_S = 0.0  # no rate limit in tests
    hub.put_worker_stats("w0", {
        "uptime_s": 1.0, "stale_after_s": 60.0,
        f"queued_{backlog_cls}": backlog})
    return hub, pred


def test_shed_gate_per_class():
    hub, pred = _loaded_predictor("background", 50)
    v = pred.shed_verdict("background")
    assert v is not None and v["shed"] is True
    assert v["retry_after_s"] > 0 and "background" in v["error"]
    assert pred.shed_verdict("interactive") is None  # never depth-shed
    assert pred.shed_verdict("batch") is None  # under its own cap
    # counters + /health block
    s = pred.stats()
    assert s["slo"]["requests_shed_background"] == 1
    assert s["slo"]["brownout"]["stage"] == 0


def test_shed_gate_ignores_dead_workers_backlog():
    """A crashed worker's last-published backlog gauges must not pin
    the shed gate shut on an idle fleet: breaker-open members are
    excluded from the backlog sums (same corpse-pins-the-controller
    rule as the brownout p95 feed)."""
    hub, pred = _loaded_predictor("background", 50)
    assert pred.shed_verdict("background") is not None  # alive: sheds
    pred.breakers.record_stale("w0")  # the worker dies (force-open)
    assert pred.shed_verdict("background") is None


def test_interactive_traffic_ticks_the_ladder():
    """The ladder must de-escalate on interactive-only traffic: the
    shed gate's refresh runs BEFORE the interactive early-return."""
    hub = InProcQueueHub()
    pred = Predictor(hub, ["w0"], brownout_target_p95_s=0.1)
    pred.LOAD_REFRESH_EVERY_S = 0.0
    pred.brownout.stage = 2
    hub.put_worker_stats("w0", {
        "uptime_s": 1.0, "stale_after_s": 60.0,
        "slo_interactive_ttft_p95_s": 0.01})  # recovered
    for i in range(pred.brownout.dwell * 2):
        hub.put_worker_stats("w0", {  # uptime advances: stays fresh
            "uptime_s": 1.0 + i, "stale_after_s": 60.0,
            "slo_interactive_ttft_p95_s": 0.01})
        assert pred.shed_verdict("interactive") is None
    assert pred.brownout.stage == 0


def test_resumed_admission_is_not_a_queue_wait_sample(trained):
    """A preempt-resume re-admission carries ``resumed=True`` on its
    `admitted` span — observers must not read the victim's own
    pre-preemption service time as queue backlog (queue_p95_s is the
    router's least-loaded input)."""
    eng = DecodeEngine(trained._module(), trained._params,
                       max_slots=1, max_len=L)
    events = []
    eng.span_sink = lambda ev, rid, attrs: events.append(
        (ev, rid, dict(attrs)))
    eng.submit("bg", BG_PROMPT, BG_NEW, slo="background")
    eng.step()
    eng.step()
    eng.submit("ia", IA_PROMPT, IA_NEW, slo="interactive")
    _drain(eng, 2)
    admits = [(rid, a.get("resumed")) for ev, rid, a in events
              if ev == "admitted"]
    assert ("bg", False) in admits   # first admission: real queue wait
    assert ("ia", False) in admits
    assert ("bg", True) in admits    # the re-admission is flagged


def test_shed_gate_brownout_stage3_pauses_background():
    hub, pred = _loaded_predictor("background", 0)  # no backlog at all
    assert pred.shed_verdict("background") is None
    pred.brownout.stage = 3
    v = pred.shed_verdict("background")
    assert v is not None and "paused" in v["error"]
    assert v["brownout_stage"] == 3
    assert pred.shed_verdict("batch") is None  # batch keeps running


def test_brownout_ladder_steps_on_live_p95():
    hub = InProcQueueHub()
    pred = Predictor(hub, ["w0"], brownout_target_p95_s=0.1)
    pred.LOAD_REFRESH_EVERY_S = 0.0
    hub.put_worker_stats("w0", {
        "uptime_s": 1.0, "stale_after_s": 60.0,
        "slo_interactive_ttft_p95_s": 1.0})  # 10x over target
    for _ in range(BrownoutController(0.1).dwell):
        pred._refresh_load_signals()
    assert pred.brownout.stage == 1
    # recovery: p95 back under the exit ratio walks the ladder down
    hub.put_worker_stats("w0", {
        "uptime_s": 2.0, "stale_after_s": 60.0,
        "slo_interactive_ttft_p95_s": 0.01})
    for _ in range(BrownoutController(0.1).dwell):
        pred._refresh_load_signals()
    assert pred.brownout.stage == 0


def test_brownout_stage2_clamps_background_max_new():
    hub = InProcQueueHub()
    pred = Predictor(hub, ["w0"], brownout_target_p95_s=1.0,
                     brownout_clamp_max_new=4)
    pred.brownout.stage = 2
    assert pred._brownout_sampling("background",
                                   {"max_new": 64}) == {"max_new": 4}
    assert pred._brownout_sampling("background", None) == {"max_new": 4}
    assert pred._brownout_sampling("batch", {"max_new": 64}) == \
        {"max_new": 64}
    assert pred._brownout_sampling("interactive", None) is None


def test_service_shed_503_and_invalid_slo_400():
    hub, pred = _loaded_predictor("background", 50)
    svc = PredictorService(pred)
    code, payload = svc._predict(
        "POST", {"queries": ["x"], "slo": "background"}, {})
    assert code == 503 and payload["shed"] is True
    assert payload["retry_after_s"] > 0
    code, payload = svc._predict_stream(
        "POST", {"queries": ["x"], "slo": "background"}, {})
    assert code == 503 and payload["shed"] is True  # SSE pre-flight
    code, payload = svc._predict(
        "POST", {"queries": ["x"], "slo": "turbo"}, {})
    assert code == 400 and "unknown SLO class" in payload["error"]


def test_sdk_distinguishes_shed_from_fast_fail(monkeypatch):
    """Typed 503s end to end: a shed 503 surfaces with ``.shed`` True
    (after one honored retry_after_s sleep); a breaker fast-fail 503
    surfaces with ``.shed`` False."""
    hub, pred = _loaded_predictor("background", 50)
    svc = PredictorService(pred)
    host, port = svc.start()
    url = f"http://{host}:{port}"
    slept = []
    monkeypatch.setattr("rafiki_tpu.client.client.time.sleep",
                        lambda s: slept.append(s))
    cli = Client()
    try:
        with pytest.raises(HttpStatusError) as ei:
            cli.predict(url, ["x"], slo="background")
        assert ei.value.status == 503 and ei.value.shed is True
        assert ei.value.retry_after_s and ei.value.retry_after_s > 0
        assert len(slept) == 1  # the one honored shed retry
        assert slept[0] == pytest.approx(ei.value.retry_after_s,
                                         abs=1e-6)
        # streams: the shed pre-flight 503 reaches predict_stream too
        # (while the worker is still alive — a dead worker's backlog
        # no longer sheds, see the breaker-gated backlog sums)
        with pytest.raises(HttpStatusError) as ei:
            list(cli.predict_stream(url, ["x"], slo="background"))
        assert ei.value.shed is True
        # breaker fast-fail: every breaker open -> 503 WITHOUT shed
        # (and the dead worker's published backlog stops shedding)
        for _ in range(3):
            pred.breakers.record_failure("w0")
        with pytest.raises(HttpStatusError) as ei:
            cli.predict(url, ["x"], retry_on_503=False)
        assert ei.value.status == 503 and ei.value.shed is False
        assert ei.value.retry_after_s is not None
    finally:
        svc.stop()


# ---- the acceptance drill: mixed traffic on the capacity model ----

def test_slo_overload_acceptance_drill():
    """ISSUE 12 acceptance: under deterministic mixed traffic on one
    replica, interactive TTFT p95 holds within 1.5x its unloaded
    value, preempted streams resume with zero duplicated/lost tokens
    (hard string property of the stub token function), background is
    SHED (structured retry_after_s) rather than errored once its cap
    is hit, and best-effort work still completes (aging + troughs)."""
    KW = dict(max_slots=4, max_new=12, base_step_s=0.002,
              per_req_step_s=0.005, stream_silence_timeout_s=10.0,
              pool_id="slodrill")
    # interactive with real think-time gaps between a client's
    # streams: the troughs are where best-effort legitimately admits
    # (longer best-effort jobs outlive the trough), so the returning
    # interactive wave exercises PREEMPTION, not just priority order.
    # 8 clients on 4 slots put the unloaded baseline WELL above the
    # step quantum (own-class queueing), making the ratio meaningful.
    IA = {"clients": 8, "streams": 3, "max_new": 4, "think_s": 0.15}
    # one fused engine step at full occupancy: TTFT in this harness is
    # quantized in these units, and a preempt-admit costs at most ~one
    # extra step — the bound below allows 1.5x OR the quantum, so a
    # sub-quantum baseline can't make the ratio unmeasurable
    step_s = KW["base_step_s"] + KW["per_req_step_s"] * KW["max_slots"]

    # leg 1: unloaded — interactive only
    h = SloLoadHarness(1, shed_depths={"background": 2, "batch": 64},
                       **KW)
    try:
        base = h.run_mixed({"interactive": dict(IA)}, timeout=60.0)
        base.pop("_wall_s")
        p95_unloaded = base["interactive"]["ttft_p95_s"]
        assert base["interactive"]["ok"]

        # leg 2: overload — same interactive + batch hogs + background
        mixed = h.run_mixed({
            "interactive": dict(IA),
            "batch": {"clients": 2, "streams": 2, "max_new": 12},
            "background": {"clients": 8, "streams": 3, "max_new": 12,
                           "think_s": 0.05}},
            timeout=120.0)
        mixed.pop("_wall_s")
        stats = list(h.engine_stats().values())[0]
        # the REAL worker publish path carries the per-class p95
        # gauges the brownout ladder feeds on
        pub = h.hub.get_worker_stats(next(iter(h.workers)))
        assert pub["slo_interactive_ttft_p95_s"] > 0
        assert "slo_background_e2e_p95_s" in pub
    finally:
        h.stop()

    ia, bg = mixed["interactive"], mixed["background"]
    # every stream (incl. every preempted-resumed one) token-exact
    assert ia["ok"] and mixed["batch"]["ok"] and bg["ok"], (
        ia["failures"], mixed["batch"]["failures"], bg["failures"])
    assert ia["shed"] == 0  # interactive is never shed
    # the SLO property: interactive p95 holds under mixed overload —
    # within 1.5x unloaded, up to the step-quantum measurement floor
    bound = max(1.5 * p95_unloaded, p95_unloaded + 2 * step_s, 0.02)
    assert ia["ttft_p95_s"] <= bound, (
        f"interactive p95 {ia['ttft_p95_s']:.4f}s vs unloaded "
        f"{p95_unloaded:.4f}s (bound {bound:.4f}s)")
    # preemption actually fired, and best-effort filled the troughs
    assert stats["preemptions"] >= 1
    assert bg["served"] >= 1, "background fully starved"
    assert mixed["batch"]["served"] >= 1
    # background overflow was SHED with a structured retry hint
    assert bg["shed"] >= 1
    assert bg["shed_with_retry_hint"] == bg["shed"]
